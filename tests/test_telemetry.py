"""Unified telemetry subsystem (docs/observability.md).

Registry merge exactness, disabled-path no-ops, StepRecord
flush/rotation, the event journal round-trip, Prometheus rendering, the
serving /metrics endpoint, calibration fit (planted constants + real
recorded runs), the ``telemetry/model-drift`` lint, the session/fit
integration (phase timers, health annotations, heartbeat snapshots),
re-armable trace windows (AUTODIST_TRACE_AT), and the
``python -m autodist_tpu.telemetry`` CLI.
"""
import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.telemetry import calibration as cal
from autodist_tpu.telemetry import events as ev
from autodist_tpu.telemetry import registry as reg
from autodist_tpu.telemetry import timeline as tl

pytestmark = pytest.mark.telemetry


@pytest.fixture(autouse=True)
def _clean_telemetry(monkeypatch):
    monkeypatch.delenv("AUTODIST_TELEMETRY", raising=False)
    monkeypatch.delenv("AUTODIST_TELEMETRY_DIR", raising=False)
    ev.reset_for_testing()
    yield
    ev.reset_for_testing()


# -- registry ----------------------------------------------------------------

def test_counter_gauge_basics():
    r = reg.MetricsRegistry()
    c = r.counter("reqs_total", "requests")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = r.gauge("depth", "queue depth")
    g.set(7)
    g.dec(2)
    assert g.value == 5
    # get-or-create is idempotent; kind mismatch is loud
    assert r.counter("reqs_total") is c
    with pytest.raises(ValueError):
        r.gauge("reqs_total")


def test_histogram_merge_is_exact_across_hosts():
    """The cross-host merge contract: two per-host histograms with the
    same fixed bounds merge into EXACTLY what one global histogram
    observing the union would hold — counts, sum, and count."""
    bounds = (0.01, 0.1, 1.0)
    rng = np.random.RandomState(0)
    a_samples = list(rng.uniform(0, 2, 100))
    b_samples = list(rng.uniform(0, 2, 137))

    host_a = reg.Histogram("h", buckets=bounds)
    host_b = reg.Histogram("h", buckets=bounds)
    oracle = reg.Histogram("h", buckets=bounds)
    for v in a_samples:
        host_a.observe(v)
        oracle.observe(v)
    for v in b_samples:
        host_b.observe(v)
        oracle.observe(v)

    host_a.merge(host_b)
    assert host_a.counts == oracle.counts
    assert host_a.count == oracle.count
    assert host_a.sum == pytest.approx(oracle.sum)

    # JSON-transport merge (chief side) is the same operation.
    r = reg.MetricsRegistry()
    r.histogram("h", buckets=bounds)
    r.merge_dict([host_b.to_dict()])
    merged = r.histogram("h", buckets=bounds)
    for v in a_samples:
        merged.observe(v)
    assert merged.counts == oracle.counts

    # Mismatched bounds must refuse, not re-bin approximately.
    other = reg.Histogram("h", buckets=(0.5, 5.0))
    with pytest.raises(ValueError, match="bounds differ"):
        host_a.merge(other)


def test_histogram_percentile():
    h = reg.Histogram("h", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 1.5, 3.0):
        h.observe(v)
    p50 = h.percentile(0.5)
    assert 1.0 <= p50 <= 2.0
    assert h.percentile(1.0) == 4.0


def test_disabled_path_is_noop(monkeypatch):
    monkeypatch.setenv("AUTODIST_TELEMETRY", "0")
    c = reg.counter("x_total")
    assert c is reg.NULL_METRIC
    c.inc()                      # must not throw, must not allocate
    assert reg.histogram("h") is reg.NULL_METRIC
    assert tl.StepRecorder.create("run") is None
    assert ev.emit_event("anything", a=1) is None
    # and nothing landed on the default registry / journal
    assert all(m.name != "x_total"
               for m in reg.DEFAULT_REGISTRY.metrics())


def test_prometheus_rendering():
    r = reg.MetricsRegistry()
    r.counter("steps_total", "steps run").inc(3)
    h = r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(2.0)
    text = reg.render_prometheus(r)
    assert "# TYPE steps_total counter" in text
    assert "steps_total 3" in text
    assert "# TYPE lat_seconds histogram" in text
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="1"} 2' in text
    assert 'lat_seconds_bucket{le="+Inf"} 3' in text
    assert "lat_seconds_count 3" in text


# -- step records ------------------------------------------------------------

def test_step_record_flush_and_rotation(tmp_path):
    rec = tl.StepRecorder("r", directory=str(tmp_path), flush_every=2,
                          rotate_records=3)
    for i in range(8):
        rec.add_phase("data_load", 0.002)
        rec.record_step(i, items=4)
    rec.flush()
    files = sorted(p for p in os.listdir(tmp_path)
                   if p.startswith("steps-"))
    assert len(files) == 3           # 8 records at 3/segment
    loaded = tl.load_step_records(str(tmp_path))
    assert [r.step for r in loaded] == list(range(8))
    assert loaded[3].phases["data_load"] == pytest.approx(0.002)
    assert loaded[1].step_time_s is not None


def test_step_record_annotate_and_snapshot(tmp_path):
    rec = tl.StepRecorder("r", directory=str(tmp_path))
    rec.record_step(0)
    rec.record_step(1)
    rec.annotate(loss=0.5, all_finite=True, skipped_steps=2)
    rec.annotate(step=0, rolled_back=True)
    assert rec.records[-1].loss == 0.5
    assert rec.records[-1].skipped_steps == 2
    assert rec.records[0].rolled_back is True
    snap = rec.snapshot()
    assert snap["step"] == 1 and snap["loss"] == 0.5


# -- event journal -----------------------------------------------------------

def test_event_journal_roundtrip(tmp_path):
    j = ev.EventJournal(directory=str(tmp_path))
    j.emit("chaos/kill", step=6, proc=1)
    j.emit("checkpoint/save", step=6, duration_s=0.25, path="/x")
    j.close()
    loaded = ev.load_run_events(str(tmp_path))
    assert [r["kind"] for r in loaded] == ["chaos/kill", "checkpoint/save"]
    assert loaded[0]["step"] == 6 and loaded[0]["pid"] == os.getpid()
    assert loaded[1]["duration_s"] == 0.25
    # merge across writers: a second "host" journal interleaves by time
    j2 = ev.EventJournal(directory=str(tmp_path), host="other-host")
    j2.emit("supervisor/attempt_start", attempt=0)
    j2.close()
    merged = ev.load_run_events(str(tmp_path))
    assert len(merged) == 3
    assert merged[-1]["kind"] == "supervisor/attempt_start"
    assert merged == sorted(merged, key=lambda r: r["time"])


def test_emit_event_process_journal(tmp_path):
    ev.configure(str(tmp_path))
    out = ev.emit_event("numerics/skip", step=3, skipped_total=1)
    assert out is not None
    assert ev.load_run_events(str(tmp_path))[0]["kind"] == "numerics/skip"
    # journal never raises on a broken directory
    ev.configure("/dev/null/not-a-dir")
    assert ev.emit_event("x") is None


# -- calibration -------------------------------------------------------------

def test_fit_constants_recovers_planted():
    bw, alpha = 2e9, 2e-4
    rng = np.random.RandomState(1)
    records = []
    for _ in range(40):
        x = float(rng.uniform(1e5, 5e7))
        n = float(rng.randint(1, 12))
        records.append({"step_time_s": x / bw + alpha * n,
                        "exposed_bytes": x, "num_collectives": n})
    fc = cal.fit_constants(records)
    assert fc.ici_bandwidth == pytest.approx(bw, rel=1e-3)
    assert fc.alpha == pytest.approx(alpha, rel=1e-3)
    assert fc.improved
    assert fc.mean_abs_error_s < fc.baseline_mean_abs_error_s


def test_fit_constants_degenerate_inputs():
    # Compute-bound: time does not grow with bytes — must clamp, not blow
    # up, and still beat the default constants on ITS records.
    records = [{"step_time_s": 0.05, "exposed_bytes": 1e6,
                "num_collectives": 2}] * 5
    fc = cal.fit_constants(records)
    assert fc is not None and fc.ici_bandwidth > 0 and fc.alpha >= 0
    assert fc.mean_abs_error_s <= fc.baseline_mean_abs_error_s
    assert cal.fit_constants([]) is None


def test_fit_constants_trims_outlier_steps():
    """A compile/trace-window hiccup (one 4 s step among 2 ms steps)
    must not dominate the fit or the drift verdict."""
    bw, alpha = 2e9, 2e-4
    rng = np.random.RandomState(2)
    records = []
    for _ in range(30):
        x = float(rng.uniform(1e5, 5e7))
        n = float(rng.randint(1, 12))
        records.append({"step_time_s": x / bw + alpha * n,
                        "exposed_bytes": x, "num_collectives": n})
    records.append({"step_time_s": 4.5, "exposed_bytes": 1e6,
                    "num_collectives": 2})      # the trace-window stall
    fc = cal.fit_constants(records)
    assert fc.ici_bandwidth == pytest.approx(bw, rel=1e-3)
    assert fc.n_records == 30                   # outlier trimmed
    pm = cal.predicted_vs_measured(
        [dict(r, predicted_step_time_s=r["step_time_s"]) for r in records])
    assert pm["drift"] is None                  # median is outlier-robust


def test_model_drift_rule():
    assert cal.model_drift_reason(0.01, 0.011) is None
    why = cal.model_drift_reason(0.001, 0.05)
    assert why is not None and "recalibrate" in why
    why = cal.model_drift_reason(0.05, 0.001)
    assert why is not None and "overprices" in why
    assert cal.model_drift_reason(None, 0.05) is None
    assert cal.model_drift_reason(0.01, None) is None


def test_model_drift_lint_fires():
    """analysis pass `telemetry`: WARN on drifted measurement provenance,
    quiet within threshold, inert without provenance."""
    from autodist_tpu.analysis import analyze
    from autodist_tpu.graph_item import GraphItem
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.strategy import AllReduce

    params = {"w": jnp.zeros((64, 64), jnp.float32)}
    gi = GraphItem(params)
    spec = ResourceSpec(resource_info={"nodes": [
        {"address": "127.0.0.1", "chips": 8, "chief": True}]})
    strat = AllReduce().build(gi, spec)

    report = analyze(strat, gi, mesh={"data": 8},
                     telemetry={"measured_step_time_s": 0.5,
                                "predicted_step_time_s": 0.001})
    assert any(d.rule == "telemetry/model-drift" for d in report.warnings)

    report = analyze(strat, gi, mesh={"data": 8},
                     telemetry={"measured_step_time_s": 0.0011,
                                "predicted_step_time_s": 0.001})
    assert not any(d.rule.startswith("telemetry/")
                   for d in report.diagnostics)

    report = analyze(strat, gi, mesh={"data": 8})
    assert not any(d.rule.startswith("telemetry/")
                   for d in report.diagnostics)

    # missing measurement -> INFO, not WARN
    report = analyze(strat, gi, mesh={"data": 8},
                     telemetry={"measured_step_time_s": 0.5})
    assert any(d.rule == "telemetry/no-measurement"
               for d in report.diagnostics)
    assert not any(d.rule == "telemetry/model-drift"
                   for d in report.diagnostics)


# -- session / fit integration ----------------------------------------------

@pytest.fixture(scope="module")
def session():
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.strategy import Zero1

    _reset_default_autodist_for_testing()
    rng = np.random.RandomState(0)
    params = {"l1": {"w": jnp.asarray(rng.randn(64, 64) * 0.05,
                                      jnp.float32)},
              "out": {"w": jnp.asarray(rng.randn(64, 1) * 0.1,
                                       jnp.float32)}}
    batch = {"x": rng.randn(32, 64).astype(np.float32),
             "y": rng.randn(32).astype(np.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["l1"]["w"])
        return jnp.mean(((h @ p["out"]["w"])[:, 0] - b["y"]) ** 2)

    ad = AutoDist(strategy_builder=Zero1(bucket_bytes=256 << 10))
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-3),
                   loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    yield sess, batch
    _reset_default_autodist_for_testing()


def test_session_records_steps_with_prediction(session):
    sess, batch = session
    for _ in range(5):
        sess.run(batch, sync=False)
    rec = sess.telemetry
    assert rec is not None
    records = rec.records
    assert len(records) >= 5
    last = records[-1]
    assert last.step == sess.step_count - 1
    assert last.step_time_s is not None and last.step_time_s > 0
    assert last.phases.get("dispatch", 0) > 0
    assert last.items_per_s and last.items_per_s > 0
    # the calibration bridge: every record carries the cost model's
    # prediction for the active (ZeRO-1) strategy
    assert last.sync_bytes and last.exposed_bytes
    assert last.exposed_bytes < last.sync_bytes   # prefetch hides AG half
    assert last.num_collectives and last.predicted_step_time_s
    snap = rec.snapshot()
    assert snap["step"] == last.step and "step_time_ms" in snap


def test_calibration_improves_on_recorded_run(session):
    """Acceptance: fit_constants() on a recorded run reduces the cost
    model's step-time prediction error on that run versus the default
    (uncalibrated) constants."""
    sess, batch = session
    for _ in range(10):
        sess.run(batch, sync=False)
    records = sess.telemetry.records
    fc = cal.fit_constants(records)
    assert fc is not None and fc.n_records > 0
    assert fc.mean_abs_error_s <= fc.baseline_mean_abs_error_s
    err_default = cal.prediction_error(records)
    err_fitted = cal.prediction_error(records, **fc.as_cost_kwargs())
    assert err_fitted <= err_default


def test_fit_adds_phases_and_loss(session):
    sess, batch = session
    hist = sess.fit([batch] * 6, epochs=1, log_every=2)
    assert hist.steps_run == 6
    records = sess.telemetry.records
    assert any("data_load" in r.phases for r in records)
    # log_every fetches annotate the loss onto the fetched step's record
    assert any(r.loss is not None for r in records)


def test_heartbeat_carries_step_snapshot(tmp_path, session):
    from autodist_tpu.resilience.heartbeat import (
        HeartbeatCallback,
        HeartbeatMonitor,
        HeartbeatWriter,
        WEDGED,
    )

    sess, batch = session
    writer = HeartbeatWriter(str(tmp_path), "worker0", interval=60.0)
    cb = HeartbeatCallback(writer)
    sess.fit([batch] * 3, epochs=1, callbacks=[cb])

    monitor = HeartbeatMonitor(str(tmp_path), timeout=30.0)
    health = monitor.check("worker0")
    assert health.snapshot is not None
    assert health.snapshot["step"] == sess.step_count - 1
    assert "step_time_ms" in health.snapshot

    # a stale beacon (process alive) is WEDGED — and the verdict still
    # says what the worker was doing, plus journals the transition once
    ev.configure(None)
    stale = HeartbeatMonitor(str(tmp_path), timeout=0.0)
    time.sleep(0.05)
    bad = stale.failures()
    assert bad["worker0"].state == WEDGED
    # The flight-recorder cursor leads the doing() rendering when the
    # beacon carries one (PR 15); the snapshot string is the fallback
    # (tests/test_flightrec.py covers both).
    doing = bad["worker0"].doing()
    assert "in phase step" in doing or "last doing: step" in doing
    verdicts = [e for e in ev.get_journal().events
                if e["kind"] == "heartbeat/verdict"]
    assert len(verdicts) == 1 and verdicts[0]["state"] == WEDGED
    stale.failures()   # second poll: same state, no duplicate event
    verdicts = [e for e in ev.get_journal().events
                if e["kind"] == "heartbeat/verdict"]
    assert len(verdicts) == 1


def test_step_records_flush_to_env_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("AUTODIST_TELEMETRY_DIR", str(tmp_path))
    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing
    from autodist_tpu.strategy import AllReduce

    _reset_default_autodist_for_testing()
    params = {"w": jnp.zeros((32, 32), jnp.float32)}
    batch = {"x": np.ones((16, 32), np.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"]) ** 2)

    ad = AutoDist(strategy_builder=AllReduce(bucket_bytes=64 << 10))
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1),
                   loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    for _ in range(3):
        sess.run(batch, sync=False)
    sess.telemetry.flush()
    loaded = tl.load_step_records(str(tmp_path))
    assert len(loaded) == 3
    _reset_default_autodist_for_testing()


# -- re-armable trace windows (AUTODIST_TRACE_AT) ---------------------------

def test_trace_at_opens_midrun_windows(tmp_path, monkeypatch):
    """AUTODIST_TRACE_AT=<steps> opens capture windows MID-RUN (the old
    tracer could only capture steps 0..N-1), one subdirectory per
    window, never overlapping."""
    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    monkeypatch.setenv("AUTODIST_TRACE_STEPS", "1")
    monkeypatch.setenv("AUTODIST_TRACE_AT", "2,4")
    from autodist_tpu.utils import tracing as tr
    monkeypatch.setattr(tr, "DEFAULT_TRACE_DIR", str(tmp_path / "traces"))

    from autodist_tpu.autodist import AutoDist, \
        _reset_default_autodist_for_testing

    _reset_default_autodist_for_testing()
    params = {"w": jnp.zeros((16, 16), jnp.float32)}
    batch = {"x": np.ones((8, 16), np.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"]) ** 2)

    ad = AutoDist(mesh_axes={"data": 8})
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1),
                   loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    for _ in range(6):
        sess.run(batch)
    tr.flush_active_trace()
    run_dirs = list((tmp_path / "traces").iterdir())
    assert len(run_dirs) == 1
    windows = sorted(p.name for p in run_dirs[0].iterdir())
    assert windows == ["step2", "step4"]
    for w in run_dirs[0].iterdir():
        files = [f for f in w.rglob("*") if f.is_file()]
        assert files, f"window {w} wrote no trace"
    _reset_default_autodist_for_testing()


def test_trace_at_parse_errors():
    from autodist_tpu.utils.tracing import _parse_trace_at

    assert _parse_trace_at("") == ()
    assert _parse_trace_at("4, 2,4") == (2, 4)
    with pytest.raises(ValueError, match="AUTODIST_TRACE_AT"):
        _parse_trace_at("two")


# -- serving /metrics --------------------------------------------------------

@pytest.mark.slow
def test_metrics_endpoint_smoke():
    import http.client

    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm
    from autodist_tpu.serving import DecodeEngine, EngineServer

    spec = transformer_lm(vocab_size=61, num_layers=1, num_heads=2,
                          head_dim=8, d_ff=32, max_len=48, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    eng = DecodeEngine(spec, params, slots=2, window=24, chunk=4)
    srv = EngineServer(eng, port=0, request_timeout_s=120).start()
    try:
        conn = http.client.HTTPConnection(*srv.address, timeout=120)
        conn.request("POST", "/v1/completions",
                     json.dumps({"prompt_tokens": [1, 2, 3],
                                 "max_new_tokens": 4}),
                     {"Content-Type": "application/json"})
        assert conn.getresponse().status == 200
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type").startswith("text/plain")
        text = resp.read().decode()
        assert "# TYPE autodist_serving_request_latency_seconds " \
               "histogram" in text
        assert "autodist_serving_request_latency_seconds_count 1" in text
        assert "autodist_serving_requests_served_total 1" in text
        assert "# TYPE autodist_serving_queue_depth histogram" in text
        conn.request("GET", "/v1/stats")
        st = json.loads(conn.getresponse().read())
        assert st["requests_served"] == 1
        assert st["latency_p50_ms"] > 0
        conn.close()
    finally:
        srv.close()


# -- CLI ---------------------------------------------------------------------

def _make_run_dir(tmp_path) -> str:
    run = tmp_path / "run"
    rec = tl.StepRecorder(
        "r", directory=str(run), flush_every=1,
        predictor=lambda: {"time_s": 2e-3, "wire_bytes": 3e6,
                           "exposed_wire_bytes": 2e6,
                           "num_collectives": 4})
    for i in range(20):
        rec.add_phase("data_load", 0.001)
        rec.add_phase("dispatch", 0.002)
        rec.record_step(i, items=8)
        time.sleep(0.001)
    rec.annotate(loss=0.25, all_finite=True)
    rec.flush()
    j = ev.EventJournal(directory=str(run))
    j.emit("checkpoint/save", step=19, duration_s=0.1, path="/ckpt")
    j.emit("supervisor/attempt_start", attempt=0)
    j.close()
    return str(run)


def test_cli_summarizes_run_dir(tmp_path, capsys):
    from autodist_tpu.telemetry.__main__ import main

    run = _make_run_dir(tmp_path)
    assert main([run, "--fit"]) == 0
    out = capsys.readouterr().out
    assert "steps: 20" in out
    assert "phase data_load" in out
    assert "events (2 total" in out
    assert "checkpoint/save" in out
    assert "calibrated:" in out
    # machine mode round-trips as one JSON object
    assert main([run, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["steps"] == 20
    assert len(payload["events"]) == 2
    # empty dir exits 2
    assert main([str(tmp_path / "empty")]) == 2


def test_cli_subprocess_smoke(tmp_path):
    """CI smoke: the module entry point runs jax-free on a fixture run
    dir and exits 0."""
    run = _make_run_dir(tmp_path)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.telemetry", run],
        cwd="/root/repo", env=env, capture_output=True, timeout=120)
    assert proc.returncode == 0, proc.stderr.decode()
    assert b"telemetry summary" in proc.stdout
