"""1F1B hand-scheduled pipeline backward vs autodiff oracles."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.mesh import build_mesh
from autodist_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from autodist_tpu.parallel.pipeline_1f1b import one_f_one_b

S, B, D = 4, 16, 12


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _loss_fn(y_mb, t_mb):
    return jnp.mean((y_mb - t_mb) ** 2)


def _make(rng):
    stages = [{"w": jnp.asarray(rng.standard_normal((D, D)) * 0.4,
                                jnp.float32),
               "b": jnp.asarray(rng.standard_normal(D) * 0.1, jnp.float32)}
              for _ in range(S)]
    stacked = stack_stage_params(stages)
    x = jnp.asarray(rng.standard_normal((B, D)), jnp.float32)
    t = jnp.asarray(rng.standard_normal((B, D)), jnp.float32)
    return stacked, x, t


def _oracle(stacked, x, t, m):
    """Autodiff through the GPipe pipeline (already parity-tested against
    sequential execution in test_pipeline.py)."""
    mesh = build_mesh({"pipe": S, "data": 1})

    def loss(sp, x):
        y = pipeline_apply(_stage_fn, sp, x, mesh, num_microbatches=m)
        mb = y.reshape((m, B // m, D))
        tb = t.reshape((m, B // m, D))
        return jnp.mean(jax.vmap(_loss_fn)(mb, tb))

    val, (dsp, dx) = jax.value_and_grad(loss, argnums=(0, 1))(stacked, x)
    return val, dsp, dx


@pytest.mark.parametrize("m", [4, 8])
def test_1f1b_matches_autodiff(m):
    # num_microbatches is PER DATA SHARD; build_mesh fills the 8 CPU
    # devices as pipe=4 x data=2, so each shard holds B/2 = 8 rows.
    rng = np.random.default_rng(0)
    stacked, x, t = _make(rng)
    mesh = build_mesh({"pipe": S, "data": 1})
    loss, dsp, dx = one_f_one_b(_stage_fn, _loss_fn, stacked, x, t, mesh,
                                num_microbatches=m)
    ref_loss, ref_dsp, ref_dx = _oracle(stacked, x, t, m)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        dsp, ref_dsp)
    np.testing.assert_allclose(dx, ref_dx, rtol=1e-5, atol=1e-6)


def test_1f1b_no_pipe_axis_falls_back():
    rng = np.random.default_rng(1)
    stacked, x, t = _make(rng)
    # a mesh without a pipe axis takes the plain scan+autodiff fallback
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    loss, dsp, dx = one_f_one_b(_stage_fn, _loss_fn, stacked, x, t, mesh,
                                num_microbatches=4)
    ref_loss, ref_dsp, ref_dx = _oracle(stacked, x, t, 4)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        dsp, ref_dsp)
    np.testing.assert_allclose(dx, ref_dx, rtol=1e-5, atol=1e-6)


def test_1f1b_validates_inputs():
    rng = np.random.default_rng(2)
    stacked, x, t = _make(rng)
    mesh = build_mesh({"pipe": S, "data": 1})
    with pytest.raises(ValueError, match="not divisible"):
        one_f_one_b(_stage_fn, _loss_fn, stacked, x, t, mesh,
                    num_microbatches=5)
    with pytest.raises(ValueError, match=">= stages"):
        one_f_one_b(_stage_fn, _loss_fn, stacked, x, t, mesh,
                    num_microbatches=2)


def test_1f1b_training_converges():
    """Use the manual grads in an SGD loop: loss decreases."""
    rng = np.random.default_rng(3)
    stacked, x, t = _make(rng)
    mesh = build_mesh({"pipe": S, "data": 1})
    losses = []
    sp = stacked
    for _ in range(25):
        loss, dsp, _ = one_f_one_b(_stage_fn, _loss_fn, sp, x, t, mesh,
                                   num_microbatches=8)
        losses.append(float(loss))
        sp = jax.tree_util.tree_map(lambda p, g: p - 0.5 * g.astype(p.dtype),
                                    sp, dsp)
    assert losses[-1] < 0.6 * losses[0], losses


def test_1f1b_activation_stash_is_O_S_not_O_M():
    """The schedule's reason to exist: compiled temp memory must NOT grow
    linearly with the microbatch count the way differentiated-scan GPipe
    stashing does.  Compare M=8 vs M=32 at fixed microbatch SIZE (so per-
    tick tensors are identical): the 1F1B growth must stay far below the
    4x of an O(M) stash."""
    mesh = build_mesh({"pipe": S, "data": 1})
    rng = np.random.default_rng(4)
    stages = [{"w": jnp.asarray(rng.standard_normal((D, D)) * 0.4,
                                jnp.float32),
               "b": jnp.zeros((D,), jnp.float32)}
              for _ in range(S)]
    stacked = stack_stage_params(stages)

    def temp_bytes(m):
        bsz = 4 * m                                  # mb size fixed at 4
        x = jnp.asarray(rng.standard_normal((bsz, D)), jnp.float32)
        t = jnp.asarray(rng.standard_normal((bsz, D)), jnp.float32)
        fn = jax.jit(lambda sp, x, t: one_f_one_b(
            _stage_fn, _loss_fn, sp, x, t, mesh, num_microbatches=m))
        mem = fn.lower(stacked, x, t).compile().memory_analysis()
        return mem.temp_size_in_bytes

    small, big = temp_bytes(8), temp_bytes(32)
    # O(M) stash would give ~4x; O(S) stash leaves only the [M, mb, ...]
    # input/dx banks growing.  Generous bound: < 2.5x.
    assert big < 2.5 * small, (small, big)


def test_1f1b_grad_dtypes_match_primals():
    """bf16 params/inputs yield bf16 grads on the pipelined path, matching
    what autodiff (and the s==1 fallback) produce — optimizer tree_maps
    must not see mesh-dependent dtype mixes."""
    rng = np.random.default_rng(5)
    stages = [{"w": jnp.asarray(rng.standard_normal((D, D)) * 0.3,
                                jnp.bfloat16)} for _ in range(S)]
    stacked = stack_stage_params(stages)
    x = jnp.asarray(rng.standard_normal((B, D)), jnp.bfloat16)
    t = jnp.asarray(rng.standard_normal((B, D)), jnp.bfloat16)
    mesh = build_mesh({"pipe": S, "data": 1})
    loss, dsp, dx = one_f_one_b(
        lambda p, h: jnp.tanh(h @ p["w"]),
        lambda y, tt: jnp.mean((y.astype(jnp.float32)
                                - tt.astype(jnp.float32)) ** 2),
        stacked, x, t, mesh, num_microbatches=8)
    assert dx.dtype == jnp.bfloat16
    assert all(g.dtype == jnp.bfloat16
               for g in jax.tree_util.tree_leaves(dsp))
    assert jnp.isfinite(loss)


def test_1f1b_target_shape_validated():
    rng = np.random.default_rng(6)
    stacked, x, _ = _make(rng)
    mesh = build_mesh({"pipe": S, "data": 1})
    bad_t = jnp.zeros((B + 1, D))
    with pytest.raises(ValueError, match="targets leading dim"):
        one_f_one_b(_stage_fn, _loss_fn, stacked, x, bad_t, mesh,
                    num_microbatches=4)


def test_1f1b_loss_params_gradients():
    """A head that lives AFTER the pipeline (loss-side params): its
    gradients accumulate on the last stage and match autodiff."""
    rng = np.random.default_rng(7)
    stacked, x, t = _make(rng)
    head = {"w": jnp.asarray(rng.standard_normal((D, D)) * 0.3, jnp.float32)}

    def head_loss(lp, y_mb, t_mb):
        return jnp.mean((y_mb @ lp["w"] - t_mb) ** 2)

    mesh = build_mesh({"pipe": S, "data": 1})
    loss, dsp, dlp, dx = one_f_one_b(
        _stage_fn, head_loss, stacked, x, t, mesh, num_microbatches=8,
        loss_params=head)

    def ref(sp, lp, x):
        y = pipeline_apply(_stage_fn, sp, x, mesh, num_microbatches=8)
        mb = y.reshape((8, B // 8, D))
        tb = t.reshape((8, B // 8, D))
        return jnp.mean(jax.vmap(lambda ym, tm: head_loss(lp, ym, tm))(mb, tb))

    rl, (rdsp, rdlp, rdx) = jax.value_and_grad(ref, argnums=(0, 1, 2))(
        stacked, head, x)
    np.testing.assert_allclose(loss, rl, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        dsp, rdsp)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        dlp, rdlp)
    np.testing.assert_allclose(dx, rdx, rtol=1e-5, atol=1e-6)


def _interleaved_stack(rng, s, v):
    from autodist_tpu.parallel.pipeline import interleaved_stage_order
    stages_po = [{"w": jnp.asarray(rng.standard_normal((D, D)) * 0.3,
                                   jnp.float32),
                  "b": jnp.asarray(rng.standard_normal(D) * 0.1, jnp.float32)}
                 for _ in range(s * v)]
    order = interleaved_stage_order(s, v)
    return stack_stage_params([stages_po[g] for g in order])


@pytest.mark.parametrize("v,m,b", [(2, 8, 16), (4, 4, 16), (3, 8, 16),
                                   (2, 6, 12)])  # m=6: M not a multiple of S
def test_1f1b_interleaved_matches_autodiff(v, m, b):
    """V>1 circular 1F1B vs autodiff through the interleaved-GPipe
    pipeline (device-major stage layout shared between the two)."""
    rng = np.random.default_rng(10 + v)
    stacked = _interleaved_stack(rng, S, v)
    x = jnp.asarray(rng.standard_normal((b, D)), jnp.float32)
    t = jnp.asarray(rng.standard_normal((b, D)), jnp.float32)
    mesh = build_mesh({"pipe": S, "data": 1})

    def oracle(sp, x):
        y = pipeline_apply(_stage_fn, sp, x, mesh, num_microbatches=m,
                           num_virtual_stages=v)
        mb = y.reshape((m, b // m, D))
        tb = t.reshape((m, b // m, D))
        return jnp.mean(jax.vmap(_loss_fn)(mb, tb))

    rl, (rdsp, rdx) = jax.value_and_grad(oracle, argnums=(0, 1))(stacked, x)
    loss, dsp, dx = one_f_one_b(_stage_fn, _loss_fn, stacked, x, t, mesh,
                                num_microbatches=m, num_virtual_stages=v)
    np.testing.assert_allclose(loss, rl, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        dsp, rdsp)
    np.testing.assert_allclose(dx, rdx, rtol=1e-5, atol=1e-6)


def test_1f1b_interleaved_with_loss_params_and_data_axis():
    """V=2 composed with data parallelism AND loss-side head params."""
    rng = np.random.default_rng(20)
    v, m = 2, 4                       # m is PER data shard
    stacked = _interleaved_stack(rng, S, v)
    x = jnp.asarray(rng.standard_normal((B, D)), jnp.float32)
    t = jnp.asarray(rng.standard_normal((B, D)), jnp.float32)
    head = {"w": jnp.asarray(rng.standard_normal((D, D)) * 0.3, jnp.float32)}

    def head_loss(lp, y_mb, t_mb):
        return jnp.mean((y_mb @ lp["w"] - t_mb) ** 2)

    mesh = build_mesh({"pipe": S, "data": 2})
    loss, dsp, dlp, dx = one_f_one_b(
        _stage_fn, head_loss, stacked, x, t, mesh, num_microbatches=m,
        num_virtual_stages=v, loss_params=head)

    # Oracle: per-data-shard GPipe pipelines averaged (the dp semantics).
    mesh1 = build_mesh({"pipe": S, "data": 1})

    def ref(sp, lp, x):
        losses = []
        for sh in range(2):
            rows = slice(sh * B // 2, (sh + 1) * B // 2)
            y = pipeline_apply(_stage_fn, sp, x[rows], mesh1,
                               num_microbatches=m, num_virtual_stages=v)
            mb = y.reshape((m, B // 2 // m, D))
            tb = t[rows].reshape((m, B // 2 // m, D))
            losses.append(jnp.mean(
                jax.vmap(lambda ym, tm: head_loss(lp, ym, tm))(mb, tb)))
        return jnp.mean(jnp.stack(losses))

    rl, (rdsp, rdlp, rdx) = jax.value_and_grad(ref, argnums=(0, 1, 2))(
        stacked, head, x)
    np.testing.assert_allclose(loss, rl, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        dsp, rdsp)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        dlp, rdlp)
    np.testing.assert_allclose(dx, rdx, rtol=1e-5, atol=1e-6)


def test_1f1b_interleaved_tick_count_and_bubble():
    """Schedule accounting: the documented tick formula, and interleaving
    strictly shrinking the 1F1B bubble for the same microbatch count."""
    from autodist_tpu.parallel.pipeline_1f1b import (bubble_fraction_1f1b,
                                                     schedule_ticks_1f1b)
    assert schedule_ticks_1f1b(4, 8, 1) == 8 + 2 * 3          # M + 2(S-1)
    assert schedule_ticks_1f1b(4, 8, 2) == 8 + 3 + 2 * 7 + 1  # tj(M-1)+2(SV-1)+1
    for s, m in ((4, 8), (4, 16), (8, 16)):
        b1 = bubble_fraction_1f1b(s, m, 1)
        b2 = bubble_fraction_1f1b(s, m, 2)
        b4 = bubble_fraction_1f1b(s, m, 4)
        assert b2 < b1 and b4 < b2, (s, m, b1, b2, b4)
    # In stage-work units the V=2 warmup+drain is (3S-2)/2 vs 2(S-1):
    # e.g. S=4: 5 < 6 stage units.
    s = 4
    overhead_v1 = (schedule_ticks_1f1b(s, 64, 1) - 64) * 1.0
    overhead_v2 = (schedule_ticks_1f1b(s, 64, 2) - 128) / 2.0
    assert overhead_v2 < overhead_v1


def test_1f1b_interleaved_stash_is_O_SV_not_O_M():
    """Interleaved 1F1B keeps the M-independent activation stash."""
    mesh = build_mesh({"pipe": S, "data": 1})
    rng = np.random.default_rng(21)
    stacked = _interleaved_stack(rng, S, 2)

    def temp_bytes(m):
        bsz = 4 * m
        x = jnp.asarray(rng.standard_normal((bsz, D)), jnp.float32)
        t = jnp.asarray(rng.standard_normal((bsz, D)), jnp.float32)
        fn = jax.jit(lambda sp, x, t: one_f_one_b(
            _stage_fn, _loss_fn, sp, x, t, mesh, num_microbatches=m,
            num_virtual_stages=2))
        mem = fn.lower(stacked, x, t).compile().memory_analysis()
        return mem.temp_size_in_bytes

    small, big = temp_bytes(8), temp_bytes(32)
    assert big < 2.5 * small, (small, big)


def test_1f1b_large_vocab_head_grads_sharded():
    """The Megatron vocab-parallel answer to 1F1B head gradients: with a
    'model' mesh axis and a vocab-sharding strategy, the tied-embedding
    table, its per-tick vjp gradient, and the f32 accumulator all stay
    sharded through the partial-manual shard_map — no replicated
    [vocab, d_model] f32 buffer exists anywhere in the per-device HLO,
    and the loss matches the autodiff GPipe spec."""
    import re

    import optax

    from autodist_tpu.autodist import (AutoDist,
                                       _reset_default_autodist_for_testing)
    from autodist_tpu.models.pipelined_lm import pipelined_transformer_lm
    from autodist_tpu.strategy import PSLoadBalancing

    vocab, d_model = 32768, 16
    mesh = build_mesh({"pipe": 2, "model": 2, "data": 2})
    kw = dict(vocab_size=vocab, num_layers=4, num_heads=2, head_dim=8,
              d_ff=32, max_len=16, seq_len=16, num_microbatches=2)
    spec1 = pipelined_transformer_lm(mesh, schedule="1f1b", **kw)
    spec0 = pipelined_transformer_lm(mesh, schedule="gpipe", **kw)
    params = spec0.init(jax.random.PRNGKey(0))
    batch = spec0.sample_batch(8)

    def run(spec, use_gf):
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=PSLoadBalancing(),
                      mesh_axes={"pipe": 2, "model": 2, "data": 2})
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-2),
                       loss_fn=spec.loss_fn,
                       grad_fn=spec.grad_fn if use_gf else None,
                       sparse_vars=spec.sparse_vars,
                       pipeline_vars=spec.pipeline_vars)
        sess = ad.create_distributed_session(mesh=mesh)
        return [float(sess.run(batch)["loss"]) for _ in range(3)], sess

    l1, sess1 = run(spec1, True)
    l0, _ = run(spec0, False)
    np.testing.assert_allclose(l1, l0, rtol=3e-4)

    step = sess1._step
    txt = step.step_fn.lower(
        sess1.sharded_params, sess1._opt_state, sess1._sync_state,
        sess1.place_batch(batch)).compile().as_text()
    assert not re.search(rf"f32\[{vocab},{d_model}\]", txt), \
        "replicated full-vocab f32 head gradient found in per-device HLO"
    assert re.search(rf"f32\[{vocab // 2},{d_model}\]", txt), \
        "expected model-sharded f32 head-gradient buffers"


def test_pipelined_lm_1f1b_warns_without_model_axis():
    """ADVICE #3: a large tied vocab under schedule='1f1b' with no model
    axis warns (dense replicated f32 head gradient), and stays silent
    when a model axis is there to shard it."""
    import logging as stdlib_logging

    from autodist_tpu.models.pipelined_lm import pipelined_transformer_lm

    records = []

    class _Capture(stdlib_logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    handler = _Capture(level=stdlib_logging.WARNING)
    logger = stdlib_logging.getLogger("autodist_tpu")
    logger.addHandler(handler)
    try:
        big = dict(vocab_size=262144, num_layers=4, num_heads=2,
                   head_dim=64, d_ff=32, max_len=16,
                   seq_len=16)                 # 256k x 128 f32 = 128 MB
        mesh = build_mesh({"pipe": 4, "data": 2})
        pipelined_transformer_lm(mesh, schedule="1f1b", **big)
        assert any("model" in m for m in records), records
        records.clear()
        mesh_tp = build_mesh({"pipe": 2, "model": 2, "data": 2})
        pipelined_transformer_lm(mesh_tp, schedule="1f1b", **big)
        pipelined_transformer_lm(mesh, schedule="gpipe", **big)
        assert not [m for m in records if "head gradient" in m], records
    finally:
        logger.removeHandler(handler)


@pytest.mark.parametrize("num_virtual", [1, 2])
def test_pipelined_lm_1f1b_trains_through_session(num_virtual):
    """Full integration: pipelined LM with schedule='1f1b' (incl. the
    interleaved V=2 variant) trains through an AutoDist session via
    capture(grad_fn=spec.grad_fn) — multi-step loss parity with the
    autodiff (GPipe) spec on the same mesh and virtual-stage layout."""
    import optax

    from autodist_tpu.autodist import (AutoDist,
                                       _reset_default_autodist_for_testing)
    from autodist_tpu.models.pipelined_lm import pipelined_transformer_lm
    from autodist_tpu.strategy import PSLoadBalancing

    mesh = build_mesh({"pipe": 4, "data": 2})
    kw = dict(vocab_size=64, num_layers=8, num_heads=2, head_dim=8,
              d_ff=32, max_len=16, seq_len=16, num_microbatches=4,
              num_virtual_stages=num_virtual)
    spec_1f1b = pipelined_transformer_lm(mesh, schedule="1f1b", **kw)
    spec_ref = pipelined_transformer_lm(mesh, schedule="gpipe", **kw)
    assert spec_1f1b.grad_fn is not None and spec_ref.grad_fn is None
    params = spec_ref.init(jax.random.PRNGKey(0))
    batch = spec_ref.sample_batch(8)

    def run(spec, use_grad_fn):
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=PSLoadBalancing(),
                      mesh_axes={"pipe": 4, "data": 2})
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(1e-2),
                       loss_fn=spec.loss_fn,
                       grad_fn=spec.grad_fn if use_grad_fn else None,
                       sparse_vars=spec.sparse_vars,
                       pipeline_vars=spec.pipeline_vars)
        sess = ad.create_distributed_session(mesh=mesh)
        return [float(sess.run(batch)["loss"]) for _ in range(3)]

    losses_1f1b = run(spec_1f1b, True)
    losses_ref = run(spec_ref, False)
    np.testing.assert_allclose(losses_1f1b, losses_ref, rtol=2e-4)
    assert losses_1f1b[-1] < losses_1f1b[0]
