"""Test configuration.

All tests run on a virtual 8-device CPU backend so multi-chip sharding is
exercised without TPU hardware — the capability upgrade over the reference's
test suite, which needed a real 2-machine GPU cluster for its distributed
matrix (reference ``tests/integration/test_dist.py:1-43``, Jenkinsfile:92-131).

Mirrors the reference's ``--run-integration`` gate
(reference ``tests/conftest.py:1-17``).
"""
import os

# Force CPU even if the host environment preset JAX_PLATFORMS to a TPU
# platform or pre-imported jax (sitecustomize): the config can still be
# updated as long as no backend has been initialized yet.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # jax >= 0.5 spelling; older jaxlibs only honor the XLA_FLAGS form
    # set above, so a missing option is not an error.
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass

# Tests target the modern `jax.set_mesh` / `jax.shard_map` spellings; on
# 0.4.x jaxlibs alias them to the framework's compat shims (the shims
# detect and skip these aliases, so there is no recursion on any jax).
from autodist_tpu.utils import compat as _compat  # noqa: E402

if not hasattr(jax, "set_mesh"):
    jax.set_mesh = _compat.set_mesh
if not hasattr(jax, "shard_map"):
    jax.shard_map = _compat.shard_map

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--run-integration", action="store_true", default=False,
        help="run integration tests (strategy x case matrix)")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--run-integration"):
        return
    skip = pytest.mark.skip(reason="needs --run-integration option to run")
    for item in items:
        if "integration" in item.keywords:
            item.add_marker(skip)
