"""Test configuration.

All tests run on a virtual 8-device CPU backend so multi-chip sharding is
exercised without TPU hardware — the capability upgrade over the reference's
test suite, which needed a real 2-machine GPU cluster for its distributed
matrix (reference ``tests/integration/test_dist.py:1-43``, Jenkinsfile:92-131).

Mirrors the reference's ``--run-integration`` gate
(reference ``tests/conftest.py:1-17``).
"""
import os

# Force CPU even if the host environment preset JAX_PLATFORMS to a TPU
# platform or pre-imported jax (sitecustomize): the config can still be
# updated as long as no backend has been initialized yet.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--run-integration", action="store_true", default=False,
        help="run integration tests (strategy x case matrix)")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--run-integration"):
        return
    skip = pytest.mark.skip(reason="needs --run-integration option to run")
    for item in items:
        if "integration" in item.keywords:
            item.add_marker(skip)
