"""Expert-parallel MoE in the schedule IR (docs/schedule-ir.md "MoE").

Three layers, mirroring the acceptance criteria:

* **builder units** — dispatch/combine ``all_to_all`` pairs per expert
  stack (per microbatch slot under accumulation), honest capacity-
  buffer wire bytes (quantized wire included), ``act:``/``expert:``
  namespaces, JSON/fingerprint round-trip, and fingerprint neutrality
  for non-MoE programs;
* **mutation goldens** — swapped dispatch/combine signatures across
  stages, a missing combine leg, a dropped dispatch→combine ordering
  edge, mismatched per-stage a2a sequences, and an under-provisioned
  capacity config are each rejected/flagged with their distinct rule
  id;
* **wiring** — the analysis pass surfaces ``moe/capacity-overflow``
  with a fix string, the collectives pass re-surfaces cross-stage a2a
  mismatches, and ``estimate_ir_cost`` prices a2a legs per-kind.
"""
import dataclasses

import numpy as np
import pytest

from autodist_tpu.kernel.synchronization import quant_ring
from autodist_tpu.kernel.synchronization import schedule_ir as sir

pytestmark = [pytest.mark.schedule, pytest.mark.moe]


def _moe(key="layers_0/moe", *, stage="", seq=1024, e=8, cf=2.0,
         comp="NoneCompressor", groups=2, d_model=64):
    return sir.MoEFact(key=key, groups=groups, seq=seq, d_model=d_model,
                       num_experts=e, capacity_factor=cf, stage=stage,
                       compressor=comp)


def _fact(name="dense/w", stage=""):
    return sir.PlanFact(name=name, shape=(64, 64), dtype="float32",
                        sync_kind="AllReduce")


def _ir(moe, *, axes=None, accum=1, facts=None):
    return sir.ir_from_facts(
        facts if facts is not None else [_fact()],
        axes=axes or {"data": 2, "expert": 4}, accum_steps=accum,
        moe=moe)


def _with_legs(ir, legs):
    clone = sir.ScheduleIR.from_dict(ir.to_dict())
    clone.legs = list(legs)
    return clone


def _errors(ir):
    return [v for v in sir.verify(ir) if v.severity == sir.SEV_ERROR]


def _rules(violations):
    return {v.rule for v in violations}


def _a2a(ir):
    return [l for l in ir.legs if l.kind == sir.LEG_ALL_TO_ALL]


# -- builder ------------------------------------------------------------------

def test_builder_emits_dispatch_combine_pair_with_namespaces():
    ir = _ir([_moe()])
    legs = _a2a(ir)
    assert [l.id for l in legs] == ["moe/layers_0/moe/dispatch",
                                    "moe/layers_0/moe/combine"]
    disp, comb = legs
    assert disp.reads == ("act:layers_0/moe",)
    assert disp.writes == ("expert:layers_0/moe",)
    assert comb.reads == ("expert:layers_0/moe",)
    assert comb.writes == ("act:layers_0/moe",)
    assert disp.id in comb.deps              # combine waits for dispatch
    assert disp.axis == comb.axis == "expert"
    assert sir.MOE_ROLE_DISPATCH in disp.sig
    assert sir.MOE_ROLE_COMBINE in comb.sig
    assert not sir.verify(ir)


def test_builder_wire_bytes_are_capacity_buffer_shard():
    """Leg nbytes = the per-device [E, G, C, M] capacity buffer — the
    exact tensor the runtime's dispatch einsum materializes and GSPMD
    re-slices across the expert axis."""
    mf = _moe(seq=1024, e=8, cf=2.0, groups=2, d_model=64)
    assert mf.capacity() == 256              # max(1, int(2.0*1024/8))
    elems = 8 * 2 * 256 * 64 // 4            # [E,G,C,M] / axis size
    assert mf.payload_elems(4) == elems
    (disp, comb) = _a2a(_ir([mf]))
    assert disp.nbytes == comb.nbytes == elems * 4


def test_builder_quantized_wire_prices_payload_plus_scales():
    full, quant = _moe(), _moe(comp="Int8Compressor")
    fmt = quant_ring.wire_format_of("Int8Compressor")
    elems = full.payload_elems(4)
    assert quant.leg_nbytes(4) == quant_ring.wire_nbytes(elems, fmt)
    assert quant.leg_nbytes(4) < full.leg_nbytes(4) // 3
    ir = _ir([quant])
    assert all(l.compressor == "Int8Compressor" for l in _a2a(ir))
    assert not _errors(ir)                   # stateless wire: pair is legal


def test_builder_accum_emits_per_slot_pairs_and_chains():
    ir = _ir([_moe()], accum=3)
    legs = _a2a(ir)
    assert len(legs) == 6                    # 3 slots x (dispatch, combine)
    assert sorted({l.slot for l in legs}) == [0, 1, 2]
    assert not sir.verify(ir)                # chained slots: race-free


def test_builder_skips_degenerate_expert_axis():
    assert not _a2a(_ir([_moe()], axes={"data": 8}))
    assert not _a2a(_ir([_moe()], axes={"data": 4, "expert": 1}))


def test_json_roundtrip_and_fingerprint_neutrality():
    ir = _ir([_moe(), _moe("layers_1/moe", comp="Int8Compressor")])
    clone = sir.ScheduleIR.from_json(ir.to_json())
    assert clone.fingerprint() == ir.fingerprint()
    assert clone.moe == ir.moe
    # a program without MoE facts serializes without a moe key at all,
    # so every pre-MoE fingerprint in the wild is preserved
    plain = _ir([])
    assert "moe" not in plain.to_dict()
    assert plain.fingerprint() == _ir(()).fingerprint()
    # and the MoE facts are fingerprint-relevant
    assert _ir([_moe()]).fingerprint() != plain.fingerprint()
    assert _ir([_moe(cf=1.5)]).fingerprint() != \
        _ir([_moe(cf=2.0)]).fingerprint()


def test_capacity_rule_matches_runtime_formula():
    # mirrors parallel/moe.py: capacity = max(1, int(cf * s / e))
    assert sir.moe_capacity_drop_fraction(2.0, 1024, 8) == 0.0
    assert sir.moe_capacity_drop_fraction(1.0, 1024, 8) == 0.5
    assert abs(sir.moe_capacity_drop_fraction(0.5, 1024, 8) - 0.75) < 1e-9
    assert sir.moe_capacity_drop_fraction(2.0, 1, 64) == 0.0  # floor of 1


# -- mutation goldens: each with its distinct rule id -------------------------

def _two_stage_ir():
    """Two pipeline stages, one expert stack each — the cross-stage
    sequence checker compares their a2a issue streams."""
    facts = [_fact("stage0/w"), _fact("stage1/w")]
    moe = [_moe("stage0/moe", stage="stage0"),
           _moe("stage1/moe", stage="stage1")]
    ir = sir.ir_from_facts(facts, axes={"data": 2, "expert": 4}, moe=moe)
    assert len(_a2a(ir)) == 4
    assert not _errors(ir)
    return ir


def test_mutation_swapped_dispatch_combine_across_stages():
    """stage1 issues combine before dispatch while stage0 keeps the
    dispatch-first order: the stages' collective issue streams diverge
    and the a2a deadlocks — caught by the cross-stage sequence rule
    (the a2a deadlock lint), role carried in the leg sig."""
    ir = _two_stage_ir()
    legs = list(ir.legs)
    idx = {l.id: i for i, l in enumerate(legs)}
    a, b = idx["moe/stage1/moe/dispatch"], idx["moe/stage1/moe/combine"]
    legs[a], legs[b] = (
        dataclasses.replace(legs[a], sig=legs[b].sig),
        dataclasses.replace(legs[b], sig=legs[a].sig))
    bad = _with_legs(ir, legs)
    assert sir.RULE_COLLECTIVE_MISMATCH in _rules(_errors(bad))


def test_mutation_missing_combine_leaks_expert_buffer():
    """Dropping a combine leg leaves the capacity buffer written and
    never consumed: dead dispatch work, flagged as a buffer leak."""
    ir = _ir([_moe(stage="moe0")])
    legs = [l for l in ir.legs if l.id != "moe/layers_0/moe/combine"]
    bad = _with_legs(ir, legs)
    leaks = [v for v in sir.verify(bad)
             if v.rule == sir.RULE_BUFFER_LEAK]
    assert leaks and any(v.location == "expert:layers_0/moe"
                         for v in leaks)


def test_mutation_dropped_dispatch_combine_edge_races():
    """Severing the dispatch→combine ordering edge leaves the combine
    reading the capacity buffer the dispatch writes with no
    happens-before path: a read-write race."""
    ir = _ir([_moe(stage="moe0")])
    legs = [dataclasses.replace(l, deps=())
            if l.id == "moe/layers_0/moe/combine" else l
            for l in ir.legs]
    bad = _with_legs(ir, legs)
    errs = _errors(bad)
    assert sir.RULE_RACE_READ_WRITE in _rules(errs)
    assert any(v.location == "expert:layers_0/moe" for v in errs
               if v.rule == sir.RULE_RACE_READ_WRITE)


def test_mutation_mismatched_per_stage_a2a_sequences():
    """stage0 runs two expert layers, stage1 only one: the stages'
    collective counts diverge — ranks in stage1 never post the second
    pair and the all_to_all hangs the step."""
    facts = [_fact("stage0/w"), _fact("stage1/w")]
    moe = [_moe("stage0/moe_a", stage="stage0"),
           _moe("stage0/moe_b", stage="stage0"),
           _moe("stage1/moe_a", stage="stage1")]
    ir = sir.ir_from_facts(facts, axes={"data": 2, "expert": 4}, moe=moe)
    errs = _errors(ir)
    assert sir.RULE_COLLECTIVE_MISMATCH in _rules(errs)


def test_mutation_capacity_overflow_config_warns():
    """An under-provisioned capacity_factor is flagged from the IR
    facts alone — WARN severity (the schedule still executes; tokens
    drop to the residual path)."""
    ir = _ir([_moe(cf=1.0)])
    hits = [v for v in sir.verify(ir)
            if v.rule == sir.RULE_CAPACITY_OVERFLOW]
    assert len(hits) == 1
    assert hits[0].severity == sir.SEV_WARN
    assert "50" in hits[0].message           # drop fraction rendered
    assert not _errors(ir)                   # WARN, not ERROR
    assert not [v for v in sir.verify(_ir([_moe(cf=2.0)]))
                if v.rule == sir.RULE_CAPACITY_OVERFLOW]


def test_mutation_rule_ids_are_distinct():
    """The four golden mutations map to four distinct rule ids."""
    assert len({sir.RULE_COLLECTIVE_MISMATCH, sir.RULE_BUFFER_LEAK,
                sir.RULE_RACE_READ_WRITE,
                sir.RULE_CAPACITY_OVERFLOW}) == 4


# -- wiring -------------------------------------------------------------------

def test_analysis_pass_surfaces_capacity_overflow_with_fix():
    import jax.numpy as jnp

    from autodist_tpu.analysis.analyzer import analyze
    from autodist_tpu.graph_item import GraphItem
    from autodist_tpu.strategy import AllReduce
    from autodist_tpu.resource_spec import ResourceSpec

    gi = GraphItem(
        {"layers_0": {"moe": {"wi": jnp.zeros((8, 16, 32)),
                              "wo": jnp.zeros((8, 32, 16))}}},
        expert_vars=("*/moe/wi", "*/moe/wo"))
    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": 8, "chief": True}],
        "mesh": {"data": 2, "expert": 4}})
    strategy = AllReduce().build(gi, spec)
    import os
    old = os.environ.get("AUTODIST_MOE_CAPACITY_FACTOR")
    os.environ["AUTODIST_MOE_CAPACITY_FACTOR"] = "1.0"
    try:
        report = analyze(strategy, gi, resource_spec=spec)
    finally:
        if old is None:
            os.environ.pop("AUTODIST_MOE_CAPACITY_FACTOR", None)
        else:
            os.environ["AUTODIST_MOE_CAPACITY_FACTOR"] = old
    hits = [d for d in report.diagnostics
            if d.rule == sir.RULE_CAPACITY_OVERFLOW]
    assert hits and hits[0].fix_hint
    assert "capacity_factor" in hits[0].fix_hint


def test_estimate_ir_cost_prices_a2a_per_kind():
    from autodist_tpu.strategy.cost_model import estimate_ir_cost

    ir = _ir([_moe()])
    report = estimate_ir_cost(ir)
    assert "all_to_all" in report.per_kind
    assert report.per_kind["all_to_all"] > 0
    # wire bytes: each device ships (d-1)/d of its capacity shard, both
    # directions of the pair
    nb = _a2a(ir)[0].nbytes
    expected = 2 * nb * 3 / 4
    assert abs(report.exposed_wire_bytes
               - (expected + _wire_excluding_a2a(ir))) < 1e-6


def _wire_excluding_a2a(ir):
    from autodist_tpu.strategy import cost_model as cm

    return sum(
        cm._leg_wire_bytes(l, int(ir.axes.get(l.axis, 1)))
        for l in ir.legs if l.kind in sir.COLLECTIVE_KINDS
        and l.kind != sir.LEG_ALL_TO_ALL)


def test_unfitted_a2a_borrows_all_reduce_constants():
    """A calibration fitted before MoE existed prices a2a legs with the
    all_reduce constants (the ps_exchange borrowing rule) instead of
    silently free."""
    from autodist_tpu.strategy.cost_model import leg_cost_s
    from autodist_tpu.telemetry.calibration import LegCalibration

    cal = LegCalibration()
    cal.bandwidths["all_reduce"] = 1e9
    cal.alphas["all_reduce"] = 1e-5
    ir = _ir([_moe()])
    (disp, _) = _a2a(ir)
    got = leg_cost_s(disp, ir, constants=cal)
    assert got > 1e-5                        # alpha + bytes/bw, not zero
    np.testing.assert_allclose(
        got, 1e-5 + disp.nbytes * (3 / 4) / 1e9, rtol=1e-6)


# -- CLI end-to-end smoke ----------------------------------------------------

def test_cli_moe_dump_ir_renders_a2a_legs():
    """``python -m autodist_tpu.analysis moe ... --dump-ir json
    --watermark`` lowers the builtin MoE demo model to a schedule whose
    JSON dump carries the dispatch/combine a2a pairs and their
    ``act:``/``expert:`` namespaces."""
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.analysis", "moe",
         "AllReduce", "--mesh", "data=2,expert=4", "--dump-ir", "json",
         "--watermark"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout)
    legs = payload["schedule_ir"]["legs"]
    a2a = [l for l in legs if l["kind"] == sir.LEG_ALL_TO_ALL]
    assert len(a2a) >= 2 and len(a2a) % 2 == 0
    assert {l["axis"] for l in a2a} == {"expert"}
    reads = {r for l in a2a for r in l["reads"]}
    writes = {w for l in a2a for w in l["writes"]}
    assert any(r.startswith("act:") for r in reads)
    assert any(w.startswith("expert:") for w in writes)
    # the watermark simulation saw the capacity transients
    assert payload["watermark"]["peak_bytes"] > 0


def test_cli_moe_watermark_exits_1_on_planted_over_budget_capacity():
    """Planting a huge token count (``AUTODIST_MOE_TOKENS``) against a
    tiny ``--budget-gb`` makes the capacity transients blow the HBM
    budget: the CLI exits 1 and names an ``expert:`` buffer."""
    import os
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               AUTODIST_MOE_TOKENS=str(1 << 22))
    proc = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.analysis", "moe",
         "AllReduce", "--mesh", "data=2,expert=4", "--watermark",
         "--budget-gb", "0.001"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 1, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert "EXCEEDED" in proc.stdout
    assert "expert:" in proc.stdout
