"""Flash-attention kernel vs the dense reference implementation.

The dense softmax (``models/transformer.py:dense_attention``) is the oracle:
forward outputs and gradients must agree to fp32 tolerance for causal and
full attention, including under a sharded mesh (shard_map manual path).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.mesh import build_mesh
from autodist_tpu.models.transformer import dense_attention
from autodist_tpu.ops import flash_attention, make_flash_attention


def _qkv(rng, b=2, t=32, h=2, d=16):
    shape = (b, t, h, d)
    q = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    k = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    v = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_dense(causal):
    q, k, v = _qkv(np.random.default_rng(0))
    out = flash_attention(q, k, v, causal, block_q=8, block_k=8)
    ref = dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_dense(causal):
    q, k, v = _qkv(np.random.default_rng(1), t=16, d=8)
    w = jnp.asarray(np.random.default_rng(2).standard_normal(
        (2, 16, 2, 8)), jnp.float32)

    def loss(attn):
        def f(q, k, v):
            return jnp.sum(attn(q, k, v, causal) * w)
        return f

    flash = lambda q, k, v, c: flash_attention(  # noqa: E731
        q, k, v, c, block_q=8, block_k=8)
    g_flash = jax.grad(loss(flash), argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss(dense_attention), argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=1e-4, atol=1e-4, err_msg=f"d{name}")


def test_uneven_blocks_picks_divisor():
    # t=24 with requested block 128 → kernel must fall back to a divisor.
    q, k, v = _qkv(np.random.default_rng(3), t=24)
    out = flash_attention(q, k, v, True)
    ref = dense_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pad_len_policy():
    from autodist_tpu.ops.flash_attention import _pad_len
    assert _pad_len(23, True) == 23          # interpret: no constraint
    assert _pad_len(23, False) == 24         # small: next multiple of 8
    assert _pad_len(128, False) == 128
    assert _pad_len(130, False) == 256       # large: next multiple of 128
    assert _pad_len(1, False) == 8


@pytest.mark.parametrize("causal", [False, True])
def test_padded_kernel_path_matches_dense(causal):
    """Drive the kv_len<T masked branches of all three kernels (the
    compiled-TPU padding path) in interpret mode: manually pad the inputs
    and pass the true kv_len through the private op, forward and backward.
    On real TPU `flash_attention` takes this path automatically for
    non-tileable lengths."""
    import importlib
    fa = importlib.import_module("autodist_tpu.ops.flash_attention")
    t = 23
    q, k, v = _qkv(np.random.default_rng(4), t=t, d=8)
    ref = dense_attention(q, k, v, causal)
    pad = [(0, 0), (0, 0), (0, 24 - t), (0, 0)]
    qt, kt, vt = (jnp.pad(x.transpose(0, 2, 1, 3), pad) for x in (q, k, v))

    o, _ = fa._flash(qt, kt, vt, causal, 8, 8, True, t)
    np.testing.assert_allclose(
        np.asarray(o[:, :, :t, :].transpose(0, 2, 1, 3)), np.asarray(ref),
        rtol=2e-5, atol=2e-5)

    w = jnp.asarray(np.random.default_rng(5).standard_normal(
        ref.shape), jnp.float32).transpose(0, 2, 1, 3)

    def loss_flash(qt, kt, vt):
        return jnp.sum(
            fa._flash(qt, kt, vt, causal, 8, 8, True, t)[0][:, :, :t, :]
            * w[:, :, :t, :])

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal)
                       * w[:, :, :t, :].transpose(0, 2, 1, 3))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(qt, kt, vt)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        gf = np.asarray(gf[:, :, :t, :].transpose(0, 2, 1, 3))
        np.testing.assert_allclose(gf, np.asarray(gd),
                                   rtol=1e-4, atol=1e-4, err_msg=f"d{name}")
        # Padded rows must carry zero gradient.
    for gf in g_flash:
        np.testing.assert_allclose(np.asarray(gf[:, :, t:, :]), 0.0,
                                   atol=1e-6)


def test_sharded_matches_dense():
    mesh = build_mesh({"data": 2, "model": 2, "seq": 1})
    attn = make_flash_attention(mesh, block_q=8, block_k=8)
    q, k, v = _qkv(np.random.default_rng(4), b=4, h=4)

    @jax.jit
    def run(q, k, v):
        return attn(q, k, v, True)

    with jax.set_mesh(mesh):
        out = run(q, k, v)
    ref = dense_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_bf16_forward_close():
    q, k, v = _qkv(np.random.default_rng(5))
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(qb, kb, vb, False, block_q=8, block_k=8)
    ref = dense_attention(q, k, v, False)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=5e-2, atol=5e-2)


def test_pallas_call_present_in_tpu_lowering():
    """Dump-based proof the flagship attention IS the Pallas kernel: the
    TPU cross-platform lowering of a flash-attention program contains the
    Mosaic custom call (dense attention lowers to plain dot/softmax ops)."""
    attn = make_flash_attention(interpret=False)  # compiled-kernel path
    q = jnp.zeros((2, 256, 4, 64), jnp.float32)
    traced = jax.jit(lambda q, k, v: attn(q, k, v, True)).trace(q, q, q)
    txt = traced.lower(lowering_platforms=("tpu",)).as_text()
    assert "tpu_custom_call" in txt
    dense_txt = jax.jit(
        lambda q, k, v: dense_attention(q, k, v, True)).trace(
            q, q, q).lower(lowering_platforms=("tpu",)).as_text()
    assert "tpu_custom_call" not in dense_txt


def test_default_attention_resolves_by_backend():
    """Construction-time backend decision (not trace time): dense on the
    CPU test backend; the factory exists for TPU."""
    from autodist_tpu.models.transformer import default_attention

    assert default_attention() is dense_attention  # CPU test backend

    from autodist_tpu.models.transformer_lm import transformer_lm

    spec = transformer_lm(vocab_size=64, num_layers=1, num_heads=2,
                          head_dim=8, d_ff=32, max_len=16)
    assert spec.config["vocab_size"] == 64  # factory accepts attn_fn=None


def test_block_picker_prefers_tile_multiples():
    from autodist_tpu.ops.flash_attention import _pick_block

    assert _pick_block(4096, 512) == 512
    assert _pick_block(2176, 512) == 128   # 17*128: only 128-multiple divisor
    assert _pick_block(2048, 512) == 512
    assert _pick_block(24, 512) == 24      # tiny interpret-mode sequence
    assert _pick_block(8192, 512) == 512
