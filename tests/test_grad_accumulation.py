"""Gradient accumulation: capture(accum_steps=N).

One step splits the batch into N microbatches under a ``lax.scan``,
averaging losses and gradients before the single optimizer update —
the effective batch at a fraction of the live activation memory.  Exact
for row-mean losses, so the whole trajectory must match the
non-accumulated step bit-close in f32.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.strategy import AllReduce, Parallax, PSLoadBalancing


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


def _problem():
    rng = np.random.RandomState(0)
    params = {"w": jnp.zeros((6, 2)), "b": jnp.zeros((2,))}

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"x": rng.randn(32, 6).astype(np.float32),
             "y": rng.randn(32, 2).astype(np.float32)}
    return params, loss_fn, batch


def _train(builder, accum, steps=5, **capture_kw):
    params, loss_fn, batch = _problem()
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=builder)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(0.05),
                   loss_fn=loss_fn, accum_steps=accum, **capture_kw)
    sess = ad.create_distributed_session()
    losses = [float(sess.run(batch)["loss"]) for _ in range(steps)]
    return losses, sess.params


@pytest.mark.parametrize("accum", [2, 4, 8])
def test_accumulation_matches_full_batch(accum):
    l1, p1 = _train(AllReduce(), 1)
    la, pa = _train(AllReduce(), accum)
    np.testing.assert_allclose(la, l1, rtol=1e-5, err_msg=f"accum={accum}")
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
        pa, p1)


def test_accumulation_with_sparse_and_ps():
    """Composes with vocab-sharded sparse embeddings (scatter-add grads
    sum across microbatches) and PS weight-update sharding."""
    vocab, dim = 64, 8
    rng = np.random.RandomState(1)
    params = {"emb": jnp.asarray(rng.randn(vocab, dim) * 0.1, jnp.float32),
              "head": jnp.asarray(rng.randn(dim) * 0.1, jnp.float32)}

    def loss_fn(p, batch):
        rows = jnp.take(p["emb"], batch["ids"], axis=0)
        return jnp.mean((rows @ p["head"] - batch["y"]) ** 2)

    batch = {"ids": rng.randint(0, vocab, (32,)).astype(np.int32),
             "y": rng.randn(32).astype(np.float32)}

    def run(accum):
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=Parallax())
        with ad.scope():
            ad.capture(params=params, optimizer=optax.sgd(0.1),
                       loss_fn=loss_fn, sparse_vars=("emb",),
                       accum_steps=accum)
        sess = ad.create_distributed_session()
        losses = [float(sess.run(batch)["loss"]) for _ in range(4)]
        return losses, sess.params

    l1, p1 = run(1)
    l4, p4 = run(4)
    np.testing.assert_allclose(l4, l1, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
        p4, p1)


def test_accumulation_uneven_tail_matches_full_batch():
    """32 rows over accum_steps=5: the first 32 % 5 = 2 microbatches
    carry one extra row and every contribution is row-weighted, so the
    trajectory still equals the full-batch mean (what used to raise
    'not divisible')."""
    l1, p1 = _train(PSLoadBalancing(), 1)
    l5, p5 = _train(PSLoadBalancing(), 5)   # 32 % 5 != 0 -> uneven tail
    np.testing.assert_allclose(l5, l1, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
        p5, p1)


def test_accumulation_more_microbatches_than_rows_rejected():
    params, loss_fn, batch = _problem()
    ad = AutoDist(strategy_builder=PSLoadBalancing())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1),
                   loss_fn=loss_fn, accum_steps=33)   # > 32 rows
    sess = ad.create_distributed_session()
    with pytest.raises(ValueError, match="exceeds"):
        sess.run(batch)


@pytest.mark.parametrize("compressor,fused,rtol", [
    ("NoneCompressor", True, 1e-5),        # fused groups, exact math
    ("HorovodCompressorEF", False, 1e-5),  # bf16 wire + error feedback
    ("Int8Compressor", False, 5e-3),       # lossy int8 wire
])
def test_accumulation_composes_with_explicit_compressor_path(
        compressor, fused, rtol):
    """accum_steps on the EXPLICIT shard_map path: the f32 accumulator
    scan runs inside the mapped step over each device's local microbatch
    slices, so the compressor still sees ONE averaged gradient per step.
    Gradient accumulation is exactly when bandwidth-saving compression
    matters most — trajectories must match the unaccumulated run at the
    same effective batch (compression applied post-accumulation in both,
    so the wire format cancels out of the comparison)."""
    from autodist_tpu.kernel.synchronization import explicit_sync

    builder = AllReduce(compressor=compressor,
                        fused_groups=fused, chunk_size=2)

    def run(accum):
        params, loss_fn, batch = _problem()
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=builder)
        with ad.scope():
            ad.capture(params=params, optimizer=optax.adam(0.05),
                       loss_fn=loss_fn, accum_steps=accum)
        sess = ad.create_distributed_session()
        assert explicit_sync.uses_explicit_path(sess._step.compiled_strategy)
        losses = [float(sess.run(batch)["loss"]) for _ in range(5)]
        return losses, sess.params

    l1, p1 = run(1)
    la, pa = run(2)
    np.testing.assert_allclose(la, l1, rtol=rtol)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=rtol, atol=1e-6),
        pa, p1)


def test_accumulation_explicit_path_uneven_local_slice():
    """Inside shard_map the accumulator splits the LOCAL batch slice
    (global/8 on the test mesh): 32 rows / 8 devices = 4 local rows over
    accum_steps=3 run as uneven [2, 1, 1]-row microbatches, row-weighted
    — the trajectory still matches the unaccumulated run."""
    def run(accum):
        params, loss_fn, batch = _problem()
        _reset_default_autodist_for_testing()
        ad = AutoDist(
            strategy_builder=AllReduce(compressor="HorovodCompressor"))
        with ad.scope():
            ad.capture(params=params, optimizer=optax.sgd(0.1),
                       loss_fn=loss_fn, accum_steps=accum)
        sess = ad.create_distributed_session()
        losses = [float(sess.run(batch)["loss"]) for _ in range(4)]
        return losses, sess.params

    l1, p1 = run(1)
    l3, p3 = run(3)
    np.testing.assert_allclose(l3, l1, rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
        p3, p1)


def test_accum_steps_validation():
    params, loss_fn, _ = _problem()
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        with pytest.raises(ValueError, match="accum_steps"):
            ad.capture(params=params, optimizer=optax.sgd(0.1),
                       loss_fn=loss_fn, accum_steps=0)


def test_accumulation_cuts_live_activation_memory():
    """The reason the feature exists: at fixed effective batch, compiled
    temp memory shrinks with accum_steps (activations live per
    microbatch).  Uses a wide MLP so activations dominate."""
    if jax.default_backend() != "cpu":
        pytest.skip("memory_analysis comparison is for the CPU mesh")
    # Activation-dominated regime (the regime the feature exists for):
    # batch x width activations far exceed the parameter bytes, so the
    # f32 grad accumulator the scan carries stays negligible.
    rng = np.random.RandomState(2)
    d, width, batch = 64, 256, 8192
    params = {"w1": jnp.asarray(rng.randn(d, width) * 0.05, jnp.float32),
              "w2": jnp.asarray(rng.randn(width, width) * 0.05, jnp.float32),
              "w3": jnp.asarray(rng.randn(width, 1) * 0.05, jnp.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["w1"])
        h = jnp.tanh(h @ p["w2"])
        return jnp.mean((h @ p["w3"] - b["y"]) ** 2)

    x = rng.randn(batch, d).astype(np.float32)
    y = rng.randn(batch, 1).astype(np.float32)

    def temp_bytes(accum):
        from autodist_tpu.kernel.graph_transformer import _accumulate_grads

        vg = jax.value_and_grad(loss_fn)
        if accum > 1:
            vg = _accumulate_grads(vg, accum, has_aux=False)
        fn = jax.jit(vg)
        mem = fn.lower(params, {"x": x, "y": y}).compile().memory_analysis()
        return mem.temp_size_in_bytes

    full, accumulated = temp_bytes(1), temp_bytes(8)
    assert accumulated < 0.5 * full, (full, accumulated)
