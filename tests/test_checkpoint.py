"""Checkpoint interchangeability tests.

Parity target: the reference's checkpoint suite — save under PartitionedPS,
restore into a PLAIN single-device program
(tests/checkpoint/test_partitionedPS_saver.py), SavedModel round-trip
(test_saved_model.py:38-50), and full resume.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.checkpoint import SavedModelBuilder, Saver
from autodist_tpu.checkpoint.saved_model_builder import load_saved_model
from autodist_tpu.checkpoint.saver import save_params
from autodist_tpu.strategy import AllReduce, PartitionedPS


@pytest.fixture(autouse=True)
def _testing_env(monkeypatch):
    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    _reset_default_autodist_for_testing()


def _problem(seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(16, 8).astype(np.float32)
    w = rng.randn(8, 4).astype(np.float32)
    params = {"linear": {"w": jnp.zeros((8, 4), jnp.float32),
                         "b": jnp.zeros((4,), jnp.float32)}}

    def loss_fn(p, b):
        pred = b["x"] @ p["linear"]["w"] + p["linear"]["b"]
        return jnp.mean((pred - b["y"]) ** 2)

    return params, loss_fn, {"x": x, "y": (x @ w).astype(np.float32)}


def _session(builder, params, loss_fn, opt=None):
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=builder)
    with ad.scope():
        ad.capture(params=params, optimizer=opt or optax.adam(1e-2),
                   loss_fn=loss_fn)
    return ad.create_distributed_session()


def test_partitioned_save_restores_into_single_device(tmp_path):
    """The reference's flagship invariant: distributed+partitioned checkpoint
    restores into a plain single-device program with original names/shapes."""
    params, loss_fn, batch = _problem()
    sess = _session(PartitionedPS(), params, loss_fn)
    for _ in range(3):
        sess.run(batch)
    saver = Saver(sess)
    path = saver.save(str(tmp_path / "ckpt"))

    plain = Saver.restore_params(path)
    # values equal the session's view; layout is plain numpy single-device
    np.testing.assert_allclose(plain["linear"]["w"],
                               sess.params["linear"]["w"], rtol=1e-6)
    assert isinstance(plain["linear"]["w"], np.ndarray)
    # and they are usable in a plain jax program
    loss = loss_fn(plain, batch)
    assert np.isfinite(float(loss))


def test_single_device_ckpt_restores_into_distributed(tmp_path):
    """Reverse interchange: a bare single-device params tree loads into a
    sharded session."""
    params, loss_fn, batch = _problem()
    trained = {"linear": {"w": jnp.full((8, 4), 0.5), "b": jnp.ones((4,))}}
    path = save_params(str(tmp_path / "plain"), trained)

    sess = _session(PartitionedPS(), params, loss_fn)
    sess.set_params(Saver.restore_params(path))
    np.testing.assert_allclose(sess.params["linear"]["w"], 0.5)
    np.testing.assert_allclose(sess.params["linear"]["b"], 1.0)


def test_full_resume_matches_uninterrupted(tmp_path):
    """Save mid-training (incl. Adam state), restore, continue — must match
    an uninterrupted run exactly."""
    params, loss_fn, batch = _problem()

    sess_a = _session(AllReduce(), params, loss_fn)
    for _ in range(6):
        sess_a.run(batch)
    uninterrupted = sess_a.params

    sess_b = _session(AllReduce(), params, loss_fn)
    for _ in range(3):
        sess_b.run(batch)
    saver = Saver(sess_b)
    path = saver.save(str(tmp_path / "resume"))

    sess_c = _session(AllReduce(), params, loss_fn)
    step = Saver(sess_c).restore(path)
    assert step == 3
    assert sess_c.step_count == 3
    for _ in range(3):
        sess_c.run(batch)
    np.testing.assert_allclose(sess_c.params["linear"]["w"],
                               uninterrupted["linear"]["w"], rtol=1e-6)


def test_cross_strategy_restore(tmp_path):
    """Checkpoint written under PartitionedPS restores into an AllReduce
    session (different shardings)."""
    params, loss_fn, batch = _problem()
    sess_a = _session(PartitionedPS(), params, loss_fn, opt=optax.sgd(0.1))
    for _ in range(2):
        sess_a.run(batch)
    path = Saver(sess_a).save(str(tmp_path / "x"))

    sess_b = _session(AllReduce(), params, loss_fn, opt=optax.sgd(0.1))
    Saver(sess_b).restore(path)
    np.testing.assert_allclose(sess_b.params["linear"]["w"],
                               sess_a.params["linear"]["w"], rtol=1e-6)


def test_latest_checkpoint_discovery(tmp_path):
    params, loss_fn, batch = _problem()
    sess = _session(AllReduce(), params, loss_fn)
    d = str(tmp_path / "many")
    saver = Saver(sess)
    sess.run(batch)
    saver.save(d)
    sess.run(batch)
    saver.save(d)
    assert Saver.latest_step(d) == 2
    assert Saver.latest_checkpoint(d).endswith("step_2")
    assert Saver.latest_step(str(tmp_path / "none")) is None


def test_saved_model_roundtrip(tmp_path):
    """Export apply_fn + trained params as StableHLO; load and serve without
    the original Python model code (SavedModel parity)."""
    params, loss_fn, batch = _problem()
    sess = _session(AllReduce(), params, loss_fn)
    for _ in range(3):
        sess.run(batch)
    trained = sess.params

    def apply_fn(p, x):
        return x @ p["linear"]["w"] + p["linear"]["b"]

    builder = SavedModelBuilder(str(tmp_path / "export"))
    builder.add_graph_and_variables(apply_fn, trained, [batch["x"]])
    export_dir = builder.save()

    served = load_saved_model(export_dir)
    np.testing.assert_allclose(np.asarray(served(batch["x"])),
                               np.asarray(apply_fn(trained, batch["x"])),
                               rtol=1e-4, atol=1e-5)


def test_compressed_resume_exact(tmp_path):
    """Resume of an error-feedback compressed run restores residuals and
    matches the uninterrupted run."""
    params, loss_fn, batch = _problem()
    builder = lambda: AllReduce(compressor="HorovodCompressorEF")  # noqa: E731

    sess_a = _session(builder(), params, loss_fn, opt=optax.sgd(0.1))
    for _ in range(6):
        sess_a.run(batch)

    sess_b = _session(builder(), params, loss_fn, opt=optax.sgd(0.1))
    for _ in range(3):
        sess_b.run(batch)
    assert jax.tree_util.tree_leaves(sess_b.sync_state)  # residuals exist
    path = Saver(sess_b).save(str(tmp_path / "c"))

    sess_c = _session(builder(), params, loss_fn, opt=optax.sgd(0.1))
    Saver(sess_c).restore(path)
    for _ in range(3):
        sess_c.run(batch)
    np.testing.assert_allclose(sess_c.params["linear"]["w"],
                               sess_a.params["linear"]["w"], rtol=1e-6)


def test_structural_sharded_checkpoint_interchange(tmp_path):
    """Pipe/expert-sharded (PartitionSpec('pipe','expert',...)) parameters
    must checkpoint to the single-device layout and restore into both a
    plain program and a freshly built distributed session."""
    from autodist_tpu.mesh import build_mesh
    from autodist_tpu.models.pipelined_moe_lm import \
        pipelined_moe_transformer_lm
    from autodist_tpu.strategy import PSLoadBalancing

    axes = {"pipe": 2, "expert": 2, "data": 2}
    mesh = build_mesh(axes)
    spec = pipelined_moe_transformer_lm(
        mesh, vocab_size=64, num_layers=4, num_heads=2, head_dim=8,
        d_ff=32, num_experts=2, max_len=16, seq_len=16)

    def session():
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=PSLoadBalancing(), mesh_axes=axes)
        with ad.scope():
            ad.capture(params=spec.init(jax.random.PRNGKey(0)),
                       optimizer=optax.adam(1e-2), loss_fn=spec.loss_fn,
                       sparse_vars=spec.sparse_vars,
                       pipeline_vars=spec.pipeline_vars,
                       expert_vars=spec.expert_vars)
        return ad.create_distributed_session(mesh=mesh)

    sess = session()
    batch = spec.sample_batch(8)
    for _ in range(2):
        sess.run(batch)
    path = Saver(sess).save(str(tmp_path / "ckpt"))

    # Single-device restore: plain numpy, full (unsharded) shapes.
    plain = Saver.restore_params(path)
    wi = plain["stack"]["moe"]["wi"]
    assert isinstance(wi, np.ndarray) and wi.shape[:2] == (4, 2)
    assert np.isfinite(float(spec.loss_fn(plain, batch)))

    # Restore into a fresh distributed session: same losses afterwards.
    sess2 = session()
    sess2.set_params(plain)
    l1 = float(sess.run(batch)["loss"])
    l2 = float(sess2.run(batch)["loss"])
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_async_save_overlaps_training_snapshot_consistent(tmp_path):
    """async_save=True: the save call returns while files persist in the
    background, yet the checkpoint holds the values AT SAVE TIME — the
    device->host transfer is synchronous, so training steps dispatched
    immediately after (which donate/overwrite the live buffers) cannot
    corrupt the snapshot."""
    params, loss_fn, batch = _problem()
    sess = _session(PartitionedPS(), params, loss_fn)
    for _ in range(2):
        sess.run(batch)
    snap_w = np.asarray(sess.params["linear"]["w"]).copy()

    saver = Saver(sess, async_save=True)
    path = saver.save(str(tmp_path / "ckpt"))
    for _ in range(4):          # mutate state while the save is in flight
        sess.run(batch)
    saver.wait()

    plain = Saver.restore_params(path)
    np.testing.assert_array_equal(plain["linear"]["w"], snap_w)
    assert not np.allclose(np.asarray(sess.params["linear"]["w"]), snap_w)

    # and a full restore through a fresh session resumes at the snapshot
    sess2 = _session(PartitionedPS(), *_problem()[:2])
    step = Saver(sess2).restore(path)
    assert step == 2
    np.testing.assert_allclose(np.asarray(sess2.params["linear"]["w"]),
                               snap_w, rtol=1e-6)


def test_latest_step_skips_uncommitted_dirs(tmp_path):
    """Crash-consistency: a step dir without a committed params item (an
    interrupted async save) must not be picked for resume."""
    params, loss_fn, batch = _problem()
    sess = _session(AllReduce(), params, loss_fn)
    sess.run(batch)
    saver = Saver(sess)
    saver.save(str(tmp_path / "c"), step=1)
    # simulate an interrupted later save: dir + meta, no committed items
    import os
    os.makedirs(tmp_path / "c" / "step_9")
    (tmp_path / "c" / "step_9" / "autodist_meta.json").write_text(
        '{"step": 9}')
    assert Saver.latest_step(str(tmp_path / "c")) == 1
