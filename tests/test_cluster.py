"""Cluster / Coordinator / network-utils tests.

Parity with the reference's server-starter smoke test and the
``AUTODIST_DEBUG_REMOTE`` mock facility (reference ``cluster.py:340-341``):
remote launches are exercised with the debug flag so no ssh happens.
"""
import os

import pytest

from autodist_tpu.cluster import (DEFAULT_COORDINATOR_PORT, Cluster,
                                  SSHCluster, TPUPodCluster, make_cluster)
from autodist_tpu.coordinator import Coordinator
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import Strategy
from autodist_tpu.utils.network import is_local_address, local_addresses

TWO_NODE_YAML = """
nodes:
  - address: 10.0.0.1
    chips: 4
    chief: true
  - address: 10.0.0.2
    chips: 4
    ssh_config: conf1
ssh:
  conf1:
    username: ubuntu
    key_file: ~/.ssh/id_rsa
    port: 22
"""


@pytest.fixture
def two_node_spec(tmp_path):
    p = tmp_path / "r.yml"
    p.write_text(TWO_NODE_YAML)
    return ResourceSpec(str(p))


@pytest.fixture
def debug_remote(monkeypatch):
    monkeypatch.setenv("AUTODIST_DEBUG_REMOTE", "True")


def test_is_local_address():
    assert is_local_address("localhost")
    assert is_local_address("127.0.0.1")
    assert is_local_address("127.0.0.1:15000")
    assert not is_local_address("10.255.254.253")
    assert len(local_addresses()) >= 3


def test_cluster_identity(two_node_spec):
    c = SSHCluster(two_node_spec)
    assert c.chief_address == "10.0.0.1"
    assert c.num_processes == 2
    assert c.coordinator_address == f"10.0.0.1:{DEFAULT_COORDINATOR_PORT}"
    assert c.process_id_for("10.0.0.1") == 0
    assert c.process_id_for("10.0.0.2") == 1
    assert c.local_process_id == 0  # not a worker process
    assert c.is_chief()


def test_cluster_worker_identity(two_node_spec, monkeypatch):
    monkeypatch.setenv("AUTODIST_WORKER", "10.0.0.2")
    c = SSHCluster(two_node_spec)
    assert not c.is_chief()
    assert c.local_process_id == 1


def test_coordinator_env_override(two_node_spec, monkeypatch):
    monkeypatch.setenv("AUTODIST_COORDINATOR_ADDRESS", "10.0.0.9:999")
    c = SSHCluster(two_node_spec)
    assert c.coordinator_address == "10.0.0.9:999"


def test_single_node_start_is_noop():
    c = SSHCluster(ResourceSpec())  # auto-derived single node
    assert c.num_processes == 1
    c.start()  # must not try to init jax.distributed
    c.start()  # idempotent


def test_multi_node_start_debug(two_node_spec, debug_remote):
    c = SSHCluster(two_node_spec)
    c.start()  # DEBUG_REMOTE: logs instead of initializing


def test_remote_exec_debug(two_node_spec, debug_remote):
    c = SSHCluster(two_node_spec)
    assert c.remote_exec(["echo", "hi"], "10.0.0.2") is None
    c.remote_copy("/tmp/nonexistent", "/tmp/x", "10.0.0.2")
    c.remote_file_write("/tmp/x", "data", "10.0.0.2")


def test_remote_exec_local(two_node_spec, tmp_path):
    c = SSHCluster(two_node_spec)
    out = tmp_path / "probe"
    proc = c.remote_exec(["touch", str(out)], "localhost")
    proc.wait()
    assert out.exists()
    c.terminate()


def test_remote_file_write_local(two_node_spec, tmp_path):
    c = SSHCluster(two_node_spec)
    p = tmp_path / "sub" / "f.txt"
    c.remote_file_write(str(p), "hello", "127.0.0.1")
    assert p.read_text() == "hello"


def test_remote_copy_local(two_node_spec, tmp_path):
    c = SSHCluster(two_node_spec)
    src = tmp_path / "src.txt"
    src.write_text("payload")
    dst = tmp_path / "d" / "dst.txt"
    c.remote_copy(str(src), str(dst), "localhost")
    assert dst.read_text() == "payload"


def test_coordinator_launch_debug(two_node_spec, debug_remote):
    # Note AUTODIST_TPU_WORKDIR can't be overridden here: const.py binds the
    # strategy dir at import time, so the default /tmp workdir is in use.
    strategy = Strategy()
    c = SSHCluster(two_node_spec)
    coord = Coordinator(strategy, c)
    coord.launch_clients(argv=["train.py", "--flag"])  # no ssh under debug
    coord.join()
    coord.terminate()


def test_make_cluster_flavors(two_node_spec, monkeypatch):
    assert isinstance(make_cluster(two_node_spec), SSHCluster)
    monkeypatch.setenv("AUTODIST_TPU_POD", "1")
    assert isinstance(make_cluster(two_node_spec), TPUPodCluster)


def test_terminate_kills_children(two_node_spec):
    c = SSHCluster(two_node_spec)
    proc = c.remote_exec(["sleep", "60"], "localhost")
    assert proc.poll() is None
    c.terminate()
    proc.wait()
    assert proc.poll() is not None


def test_remote_exec_quotes_args(two_node_spec, tmp_path):
    c = SSHCluster(two_node_spec)
    out = tmp_path / "with space.txt"
    proc = c.remote_exec(["touch", str(out)], "localhost")
    proc.wait()
    assert out.exists()
    c.terminate()
