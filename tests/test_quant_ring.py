"""Quantized ring collectives (kernel/synchronization/quant_ring.py).

The contracts of the PR issue:

1. **One quantization rule, one accuracy story** — the quantized ring
   reduce-scatter/all-gather and the single-collective ``all_to_all``
   lowering agree with each other and with the true mean at 1e-6 on
   per-chunk-grid-exact fixtures, for int8 AND fp8-e4m3, in both bucket
   modes (all_reduce's double quantization and ZeRO-1's stage-1-only
   reduce-scatter).  The grid fixture is ``x_d = c_d · v`` (one integer
   "shape" vector times a per-device scalar): every partial sum scales
   ``v`` uniformly, so every per-hop requantize lands exactly on its
   block grid and the scheme's answer equals the f32 oracle.
2. **Quantized buckets pipeline** under explicit ``overlap="pipeline"``
   — one quantized collective per bucket per microbatch slot, error
   feedback threaded across slots — with no overlap-fallback WARN, and
   the trajectory tracks the sequential quantized loop.
3. **Error-feedback state survives checkpoint round-trips.**
4. **Saturation is observed inside the legs**: an injected Inf shows up
   as a non-zero post-quantization ``sat_count`` in GradHealth (or the
   finiteness bit) and the step skips.
5. **Schedule-IR mutation goldens for the RELAXED
   schedule/quantized-pipelined rule**: the per-slot shape verifies
   clean; every deviation (missing slot, duplicate, slot/end-of-step
   mix, a non-capable compressor in a slot) is rejected.
6. **Convergence**: quantized training's final loss tracks f32 on the
   mlp-style fixture.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.kernel.synchronization import bucketing, overlap as ov
from autodist_tpu.kernel.synchronization import quant_ring as qr
from autodist_tpu.kernel.synchronization import schedule_ir as sir
from autodist_tpu.kernel.synchronization.compressor import get_compressor
from autodist_tpu.strategy import AllReduce, Zero1
from autodist_tpu.utils import compat

pytestmark = [pytest.mark.sync, pytest.mark.quant]

FORMATS = {"Int8Compressor": qr.WIRE_INT8, "Fp8Compressor": qr.WIRE_FP8_E4M3}


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


def _mesh():
    n = jax.device_count()
    return Mesh(np.array(jax.devices()).reshape(n), ("data",)), n


def _grid_exact(n, length, fmt, seed=0):
    """``x_d = c_d · v``: per-device data whose every quantize event —
    at any hop, on any partial sum — is exact on the per-chunk grid.
    ``v`` is integer-valued (int8) or power-of-two-valued (fp8) with
    each RING-CHUNK-sized scale block's amax pinned, and ``c_d`` are
    power-of-two device scalars, so partials ``S·v`` quantize to the
    same grid points ``v`` maps to (``S`` cancels out of ``x/scale``)."""
    rng = np.random.RandomState(seed)
    chunk = length // n
    block = min(qr.QUANT_BLOCK_ELEMS, chunk)
    if fmt.name == "int8":
        v = rng.randint(-126, 127, length).astype(np.float32)
        v[::block] = 127.0
    else:
        v = (2.0 ** rng.randint(-3, 4, length)).astype(np.float32) \
            * rng.choice([-1.0, 1.0], length)
    c = (2.0 ** rng.randint(-2, 3, n)).astype(np.float32)
    return c[:, None] * v[None, :]


# -- unit: quantize/dequantize ------------------------------------------------

@pytest.mark.parametrize("fmt", [qr.WIRE_INT8, qr.WIRE_FP8_E4M3],
                         ids=["int8", "fp8"])
def test_quantize_blocks_roundtrip_bound_and_wire_dtype(fmt):
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(1000).astype(np.float32) * 5)   # pads to 4 blocks
    q, scales, sat = jax.jit(lambda v: qr.quantize_blocks(v, fmt))(x)
    assert q.shape == x.shape and str(q.dtype) == fmt.name
    assert scales.shape == (qr.scale_count(1000),)
    assert float(sat) == 0.0
    deq = qr.dequantize_blocks(q, scales)
    # per-block bound: |err| <= half a grid step of that block's scale
    err = np.abs(np.asarray(deq - x)).reshape(-1)
    per_elem_scale = np.repeat(np.asarray(scales), qr.QUANT_BLOCK_ELEMS)[:1000]
    if fmt.name == "int8":
        assert (err <= per_elem_scale / 2 + 1e-6).all()
    else:
        # fp8: relative step is ~2^-3 near the block amax
        assert (err <= np.abs(np.asarray(x)) * 0.13 + per_elem_scale).all()


@pytest.mark.parametrize("fmt", [qr.WIRE_INT8, qr.WIRE_FP8_E4M3],
                         ids=["int8", "fp8"])
def test_quantize_blocks_counts_nonfinite_as_saturation(fmt):
    x = jnp.asarray(np.array([1.0, np.inf, -np.nan, 2.0], np.float32))
    q, scales, sat = qr.quantize_blocks(x, fmt)
    assert float(sat) == 2.0
    # the finite neighbors keep a sane grid (the block's FINITE amax)
    deq = np.asarray(qr.dequantize_blocks(q, scales))
    np.testing.assert_allclose(deq[[0, 3]], [1.0, 2.0], atol=0.02)


def test_scale_byte_accounting_pure():
    assert qr.scale_count(0) == 0
    assert qr.scale_count(1) == 1
    assert qr.scale_count(256) == 1 and qr.scale_count(257) == 2
    assert qr.scale_nbytes(512) == 8
    assert qr.wire_nbytes(512, qr.WIRE_INT8) == 512 + 8
    assert qr.wire_nbytes(512, qr.WIRE_FP8_E4M3) == 512 + 8


# -- unit: ring vs single-collective vs f32 oracle, all four paths -----------

@pytest.mark.parametrize("comp_name", list(FORMATS))
def test_ring_and_one_shot_reduce_scatter_match_oracle(comp_name):
    """ZeRO-1 leg, both lowerings: the per-hop requantizing ring and the
    one-shot all_to_all agree with each other AND the f32 mean at 1e-6
    on the grid fixture — the acceptance criterion's oracle parity."""
    mesh, n = _mesh()
    fmt = FORMATS[comp_name]
    x = _grid_exact(n, n * 96, fmt)
    true_mean = x.mean(0)

    def f(xs):
        xs = xs.reshape(-1)
        ring, _, sat_r = qr.quantized_ring_reduce_scatter(xs, "data", n, fmt)
        shot, _, sat_s = qr.quantized_all_to_all_reduce_scatter(
            xs, "data", n, fmt)
        return ring / n, shot / n, sat_r + sat_s

    m = jax.jit(compat.shard_map(
        f, mesh=mesh, in_specs=P("data"),
        out_specs=(P("data"), P("data"), P()), check_vma=False))
    ring, shot, sat = m(x)
    np.testing.assert_allclose(np.asarray(ring).ravel(), true_mean,
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(shot).ravel(), true_mean,
                               rtol=1e-6, atol=1e-6)
    assert float(sat) == 0.0
    # the wire really is 1-byte: ppermute/all_to_all on i8 (int8) or f8E4M3
    txt = m.lower(x).as_text()
    wire = "i8" if fmt.name == "int8" else "f8E4M3"
    assert "collective_permute" in txt and wire in txt


@pytest.mark.parametrize("comp_name", list(FORMATS))
@pytest.mark.parametrize("alg", ["ring", "fused"])
def test_all_reduce_bucket_paths_match_compressor_oracle(comp_name, alg):
    """All-reduce mode (double quantization), ring and fused lowerings,
    vs the single-collective ``Compressor.reduce`` oracle at 1e-6."""
    mesh, n = _mesh()
    fmt = FORMATS[comp_name]
    comp = get_compressor(comp_name)
    x = _grid_exact(n, n * 96, fmt, seed=1)
    true_mean = x.mean(0)

    def f(xs):
        xs = xs.reshape(-1)
        red, _, sat = qr.quant_bucket_reduce(
            xs, jnp.zeros_like(xs), "data", n, fmt,
            mode="all_reduce", alg=alg)
        oracle, _ = comp.reduce(xs, jnp.zeros_like(xs), "data")
        return red, oracle, sat

    m = jax.jit(compat.shard_map(
        f, mesh=mesh, in_specs=P("data"),
        out_specs=(P(), P(), P()), check_vma=False))
    red, oracle, sat = m(x)
    np.testing.assert_allclose(np.asarray(red), true_mean,
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(red), np.asarray(oracle),
                               rtol=1e-6, atol=1e-6)
    assert float(sat) == 0.0


def test_quantized_ring_all_gather_is_replicated_identically():
    """Every device must materialize the SAME dequantized values —
    including its own shard — or replicated params drift."""
    mesh, n = _mesh()
    rng = np.random.RandomState(5)
    shard = rng.randn(n, 64).astype(np.float32)   # off-grid on purpose

    def f(s):
        out, _ = qr.quantized_ring_all_gather(s.reshape(-1), "data", n,
                                              qr.WIRE_INT8)
        return out

    m = jax.jit(compat.shard_map(f, mesh=mesh, in_specs=P("data"),
                                 out_specs=P(None), check_vma=False))
    # out_specs P(None): replicated output — shard_map would fail the
    # replication check if devices disagreed... but check explicitly:
    full = np.asarray(m(shard))
    per_dev = jax.jit(compat.shard_map(
        f, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
        check_vma=False))(shard)
    per_dev = np.asarray(per_dev).reshape(n, -1)
    for d in range(n):
        np.testing.assert_array_equal(per_dev[d], per_dev[0])
    np.testing.assert_allclose(full, per_dev[0], atol=1e-6)


def test_quant_ring_degenerate_single_device():
    x = jnp.arange(8.0)
    out, err, sat = qr.quantized_ring_reduce_scatter(x, "data", 1,
                                                     qr.WIRE_INT8)
    assert out is x and float(sat) == 0.0
    out2, sat2 = qr.quantized_ring_all_gather(x, "data", 1, qr.WIRE_INT8)
    assert out2 is x


def test_error_feedback_residual_semantics():
    """Off-grid data: the ring's stage-1 residual is non-zero, bounded
    by the grid step, and adding it back into the next round removes
    the bias (the EF contract)."""
    mesh, n = _mesh()
    x = np.full((n, n * 16), 0.3, np.float32)
    x[:, ::16] = 1.0

    def f(xs):
        xs = xs.reshape(-1)
        red, err, _ = qr.quantized_ring_reduce_scatter(xs, "data", n,
                                                       qr.WIRE_INT8)
        red2, err2, _ = qr.quantized_ring_reduce_scatter(xs + err, "data",
                                                         n, qr.WIRE_INT8)
        return red / n, err, red2 / n

    m = jax.jit(compat.shard_map(
        f, mesh=mesh, in_specs=P("data"),
        out_specs=(P("data"), P("data"), P("data")), check_vma=False))
    red, err, red2 = m(x)
    err = np.asarray(err)
    assert 1e-4 < np.abs(err).max() < 1.0 / 127 + 1e-6
    # round 2 with feedback is at least as close to the true mean
    true = x.mean(0)
    e1 = np.abs(np.asarray(red).ravel() - true).mean()
    e2 = np.abs(np.asarray(red2).ravel() - true).mean()
    assert e2 <= e1 + 1e-7


# -- sessions: pipeline, ZeRO-1, convergence, checkpoints --------------------

def _problem(rows=32, seed=0):
    rng = np.random.RandomState(seed)
    params = {
        "l1": {"w": jnp.asarray(rng.randn(24, 32) * 0.1, jnp.float32),
               "b": jnp.zeros(32, jnp.float32)},
        "l2": {"w": jnp.asarray(rng.randn(32, 4) * 0.1, jnp.float32)},
    }
    batch = {"x": rng.randn(rows, 24).astype(np.float32),
             "y": rng.randn(rows, 4).astype(np.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["l1"]["w"] + p["l1"]["b"])
        return jnp.mean((h @ p["l2"]["w"] - b["y"]) ** 2)

    return params, loss_fn, batch


def _session(builder, params, loss_fn, accum=1, numerics=None, opt=None):
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=builder)
    with ad.scope():
        ad.capture(params=params, optimizer=opt or optax.adam(1e-2),
                   loss_fn=loss_fn, accum_steps=accum, numerics=numerics)
    return ad.create_distributed_session()


@pytest.mark.parametrize("comp_name", list(FORMATS))
@pytest.mark.parametrize("mk", [
    lambda comp, o: AllReduce(compressor=comp, bucket_bytes=1 << 20,
                              overlap=o),
    lambda comp, o: Zero1(compressor=comp, overlap=o),
], ids=["all_reduce", "reduce_scatter"])
def test_quantized_pipeline_tracks_sequential(mk, comp_name, caplog):
    """Explicit overlap='pipeline' pipelines the quantized bucket (one
    quantized collective per slot) with NO overlap-fallback WARN; the
    trajectory tracks the sequential quantized loop at per-slot
    quantization tolerance and converges."""
    params, loss_fn, batch = _problem()
    import logging as pylog
    with caplog.at_level(pylog.WARNING, logger="autodist_tpu"):
        piped = _session(mk(comp_name, "pipeline"), params, loss_fn,
                         accum=4)
    assert not [r for r in caplog.records
                if "overlap scheduling skipped" in r.getMessage()]
    assert piped.schedule_ir.pipelined_keys()
    seq = _session(mk(comp_name, "none"), params, loss_fn, accum=4)
    for _ in range(12):
        lp = float(piped.run(batch)["loss"])
        ls = float(seq.run(batch)["loss"])
        np.testing.assert_allclose(lp, ls, rtol=0.05, atol=1e-3)
    assert lp < 1.07  # both heading downhill from ~1.07 start


@pytest.mark.parametrize("comp_name", list(FORMATS))
def test_quantized_convergence_tracks_f32(comp_name):
    """End-to-end acceptance: quantized-vs-f32 final loss within
    tolerance on the mlp fixture, pipelined under accumulation."""
    params, loss_fn, batch = _problem()
    f32 = _session(Zero1(overlap="none"), params, loss_fn, accum=4,
                   opt=optax.sgd(0.1))
    q = _session(Zero1(compressor=comp_name, overlap="pipeline"),
                 params, loss_fn, accum=4, opt=optax.sgd(0.1))
    ref = [float(f32.run(batch)["loss"]) for _ in range(60)][-1]
    start = float(_problem()[1](params, batch))
    got = [float(q.run(batch)["loss"]) for _ in range(60)][-1]
    assert got < ref * 1.5 + 1e-3, (got, ref)
    assert got < start * 0.5


def test_quantized_ring_session_lowers_to_int8_ppermute():
    """A >=256 KiB quantized bucket under overlap='full' lowers to
    collective_permute on an i8 payload (the quantized ring), and the
    IR records the per-hop requantize."""
    rng = np.random.RandomState(1)
    params = {"big": jnp.asarray(rng.randn(512, 256) * 0.02, jnp.float32)}
    batch = {"x": rng.randn(16, 512).astype(np.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["big"]) ** 2)

    sess = _session(Zero1(compressor="Int8Compressor", overlap="full",
                          bucket_bytes=1 << 20), params, loss_fn)
    ir = sess.schedule_ir
    (node,) = ir.buckets
    assert node["wire_dtype"] == "int8"
    assert node["alg"] == sir.ALG_RING and node["requantize_per_hop"]
    assert node["scale_nbytes"] == qr.scale_nbytes(node["padded_total"])
    b = sess.place_batch(batch)
    txt = sess._step.step_fn.lower(
        sess.sharded_params, sess.opt_state, sess.sync_state, b).as_text()
    assert "collective_permute" in txt and "i8" in txt
    # ...and it still trains
    losses = [float(sess.run(batch)["loss"]) for _ in range(4)]
    assert losses[-1] < losses[0]


def test_error_feedback_state_checkpoint_roundtrip(tmp_path):
    """EF residuals ride sync_state through save/restore: the resumed
    session reproduces the uninterrupted trajectory exactly."""
    from autodist_tpu.checkpoint import Saver

    params, loss_fn, batch = _problem()

    def make():
        return _session(Zero1(compressor="Int8Compressor",
                              overlap="pipeline"), params, loss_fn,
                        accum=4, opt=optax.sgd(0.1))

    a = make()
    a.run(batch); a.run(batch)
    state_leaves = jax.tree_util.tree_leaves(a.sync_state)
    assert any(float(jnp.abs(leaf).max()) > 0 for leaf in state_leaves), \
        "quantized EF residual should be non-zero on off-grid gradients"
    path = Saver(a).save(str(tmp_path / "ck"))
    assert Saver.read_meta(path)["has_sync_state"]
    oracle = [float(a.run(batch)["loss"]) for _ in range(3)]

    b = make()
    Saver(b).restore(path)
    # the residual state restored bit-for-bit is proven by trajectory
    # equality: a resumed step consumes the EF residual first.
    resumed = [float(b.run(batch)["loss"]) for _ in range(3)]
    np.testing.assert_allclose(resumed, oracle, rtol=1e-6, atol=1e-7)


def test_saturation_counter_trips_guard_on_injected_inf(monkeypatch):
    """An Inf injected into the gradient is observed INSIDE the sync
    path — post-quantization sat_count and/or the finiteness bit — and
    the step skips (params bit-identical)."""
    monkeypatch.setenv("AUTODIST_CHAOS", "inf_grad@step=0")
    params, loss_fn, batch = _problem()
    sess = _session(Zero1(compressor="Int8Compressor", overlap="none"),
                    params, loss_fn,
                    numerics={"clip_norm": None, "loss_scale": None,
                              "on_nonfinite": "skip"})
    before = jax.tree_util.tree_map(np.asarray, sess.params)
    h = sess.run(batch)["grad_health"]
    assert not bool(h.all_finite)
    assert int(h.skipped_steps) == 1
    (entry,) = [e for k, e in h.per_bucket.items() if "sat_count" in e]
    assert float(entry["sat_count"]) >= 0.0   # counter present per bucket
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), y),
        sess.params, before)
    # clean step afterwards: finite again, counter zero
    monkeypatch.delenv("AUTODIST_CHAOS")
    sess2 = _session(Zero1(compressor="Int8Compressor", overlap="none"),
                     params, loss_fn,
                     numerics={"clip_norm": None, "loss_scale": None})
    h2 = sess2.run(batch)["grad_health"]
    assert bool(h2.all_finite)
    (e2,) = [e for k, e in h2.per_bucket.items() if "sat_count" in e]
    assert float(e2["sat_count"]) == 0.0


# -- contract rules: drop reasons, analysis, IR, cost ------------------------

def test_auto_keeps_end_of_step_with_shared_drop_reason():
    why = ov.overlap_drop_reason(
        "auto", accum_steps=4, compressor="Int8Compressor",
        bucketable=True, explicit_path=True)
    assert why and "overlap='pipeline'" in why
    assert ov.overlap_drop_reason(
        "pipeline", accum_steps=4, compressor="Int8Compressor",
        bucketable=True, explicit_path=True) is None
    assert ov.overlap_drop_reason(
        "full", accum_steps=4, compressor="Fp8Compressor",
        bucketable=True, explicit_path=True) is None
    # cast compressors keep the strict contract under every mode
    for mode in ("auto", "pipeline", "full"):
        assert ov.overlap_drop_reason(
            mode, accum_steps=4, compressor="HorovodCompressorEF",
            bucketable=True, explicit_path=True)
    # the analysis WARN carries the exact runtime string
    from autodist_tpu.analysis import analyze
    from autodist_tpu.graph_item import GraphItem
    from autodist_tpu.resource_spec import ResourceSpec

    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 8, "chief": True}]})
    gi = GraphItem({"w": jnp.zeros((64, 64), jnp.float32)}, accum_steps=4)
    report = analyze(
        Zero1(compressor="Int8Compressor").build(gi, spec), gi,
        mesh={"data": 8})
    warns = report.by_rule("sync/overlap-fallback")
    assert warns and why in warns[0].message
    # explicit pipeline: clean
    ok = analyze(
        Zero1(compressor="Int8Compressor", overlap="pipeline").build(
            gi, spec), gi, mesh={"data": 8})
    assert not ok.by_rule("sync/overlap-fallback")
    assert not [d for d in ok.errors if d.rule.startswith("schedule/")]


def _entries(comp, mode="reduce_scatter", n=4, shape=(256, 256)):
    return [(f"l{i}/w", shape, "float32", comp, 0, mode) for i in range(n)]


def _ir(entries, *, d=8, accum=1, mode="auto"):
    buckets = bucketing.assign_buckets(entries, bucket_bytes=256 << 10,
                                       shard_divisor=d)
    plan = ov.resolve_overlap([mode], accum_steps=accum, buckets=buckets,
                              d=d, has_rs=any(
                                  b.mode == "reduce_scatter"
                                  for b in buckets))
    return sir.build_schedule_ir(axes={"data": d}, accum_steps=accum,
                                 buckets=buckets, plan=plan)


def _errors(ir):
    return [v for v in sir.verify(ir) if v.severity == sir.SEV_ERROR]


def _with_legs(ir, legs):
    clone = sir.ScheduleIR.from_dict(ir.to_dict())
    clone.legs = legs
    return clone


def test_pipelined_quantized_ir_verifies_clean_and_slots_cover():
    ir = _ir(_entries("Int8Compressor"), d=8, accum=4, mode="pipeline")
    assert not _errors(ir)
    quant_legs = [l for l in ir.legs if sir.is_quantizing(l.compressor)
                  and l.kind in sir.COLLECTIVE_KINDS]
    assert {l.slot for l in quant_legs} == {0, 1, 2, 3}
    for key in {l.bucket for l in quant_legs}:
        assert len([l for l in quant_legs if l.bucket == key]) == 4


def test_mutation_missing_slot_rejected():
    ir = _ir(_entries("Int8Compressor"), d=8, accum=4, mode="pipeline")
    legs = [l for l in ir.legs
            if not (sir.is_quantizing(l.compressor) and l.slot == 2
                    and l.kind in sir.COLLECTIVE_KINDS)]
    # drop dangling deps on the removed legs so only the slot rule fires
    kept = {l.id for l in legs}
    legs = [dataclasses.replace(
        l, deps=tuple(dd for dd in l.deps if dd in kept)) for l in legs]
    bad = _with_legs(ir, legs)
    errs = _errors(bad)
    assert sir.RULE_QUANTIZED_PIPELINED in {v.rule for v in errs}
    assert any("not one per slot" in v.message for v in errs)


def test_mutation_duplicate_slot_collective_rejected():
    ir = _ir(_entries("Int8Compressor"), d=8, accum=4, mode="pipeline")
    legs = list(ir.legs)
    q = next(l for l in legs if sir.is_quantizing(l.compressor)
             and l.slot == 1 and l.kind in sir.COLLECTIVE_KINDS)
    legs.append(dataclasses.replace(q, id=q.id + "~dup", deps=(q.id,)))
    errs = _errors(_with_legs(ir, legs))
    assert any(v.rule == sir.RULE_QUANTIZED_PIPELINED
               and "microbatch slot 1" in v.message for v in errs)


def test_mutation_slot_eos_mix_rejected():
    ir = _ir(_entries("Int8Compressor"), d=8, accum=4, mode="pipeline")
    legs = list(ir.legs)
    q = next(l for l in legs if sir.is_quantizing(l.compressor)
             and l.slot == 0 and l.kind in sir.COLLECTIVE_KINDS)
    legs.append(dataclasses.replace(q, id=q.id + "~eos",
                                    slot=sir.END_OF_STEP, deps=(q.id,)))
    errs = _errors(_with_legs(ir, legs))
    assert any(v.rule == sir.RULE_QUANTIZED_PIPELINED
               and "mixes slotted and end-of-step" in v.message
               for v in errs)


def test_mutation_noncapable_compressor_in_slot_rejected():
    ir = _ir(_entries("Int8Compressor"), d=8, accum=4, mode="pipeline")
    legs = [dataclasses.replace(l, compressor="HorovodCompressorEF")
            if (sir.is_quantizing(l.compressor) and l.slot == 0
                and l.kind in sir.COLLECTIVE_KINDS) else l
            for l in ir.legs]
    errs = _errors(_with_legs(ir, legs))
    assert any(v.rule == sir.RULE_QUANTIZED_PIPELINED
               and "quantizes once per bucket per step" in v.message
               for v in errs)


def test_quantized_ring_ir_admits_chains_and_prices_scale_bytes():
    """Explicit ring: quantized ring chains verify clean, hop legs carry
    payload + per-chunk scale bytes, and the IR cost shows the >=3.5x
    wire reduction vs the f32 schedule (all_reduce mode: both legs
    quantize; ZeRO-1's reduce leg alone shows the same ratio — its
    param gather stays full-precision by design)."""
    from autodist_tpu.strategy.cost_model import estimate_ir_cost

    d = 8
    ir_q = _ir(_entries("Int8Compressor"), d=d, mode="ring")
    assert not _errors(ir_q)
    hops = [l for l in ir_q.legs if l.kind == sir.LEG_PPERMUTE_HOP]
    assert hops
    (node,) = [b for b in ir_q.buckets][:1]
    per_hop_elems = node["padded_total"] // d
    assert hops[0].nbytes == qr.wire_nbytes(per_hop_elems, qr.WIRE_INT8)
    assert node["requantize_per_hop"]

    # all_reduce mode: the whole program quantizes -> >=3.5x end to end
    ar_q = _ir(_entries("Int8Compressor", mode="all_reduce"), d=d,
               mode="ring")
    ar_f = _ir(_entries("NoneCompressor", mode="all_reduce"), d=d,
               mode="ring")
    assert not _errors(ar_q)
    ratio = estimate_ir_cost(ar_f).wire_bytes / \
        estimate_ir_cost(ar_q).wire_bytes
    assert ratio >= 3.5, ratio

    # ZeRO-1: the GRAD reduce leg alone (exclude the f32 param gather)
    def reduce_bytes(ir):
        return sum(l.nbytes for l in ir.legs
                   if l.kind in sir.COLLECTIVE_KINDS
                   and "@gather" not in l.id and "@gather" not in l.chain)
    ir_f = _ir(_entries("NoneCompressor"), d=d, mode="ring")
    assert reduce_bytes(ir_f) / reduce_bytes(ir_q) >= 3.5


def test_fp8_priced_without_unknown_compressor_warn(caplog):
    from autodist_tpu.graph_item import GraphItem
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.strategy.cost_model import estimate_cost

    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 8, "chief": True}]})
    gi = GraphItem({"w": jnp.zeros((512, 512), jnp.float32)})
    import logging as pylog
    with caplog.at_level(pylog.WARNING, logger="autodist_tpu"):
        full = estimate_cost(AllReduce().build(gi, spec), gi, spec)
        for comp in ("Int8Compressor", "Fp8Compressor"):
            rep = estimate_cost(
                AllReduce(compressor=comp).build(gi, spec), gi, spec)
            assert rep.wire_bytes == pytest.approx(full.wire_bytes / 4)
    assert not [r for r in caplog.records
                if "unknown compressor" in r.getMessage()]


def test_search_picks_quantized_pipelined_plan_on_comm_bound_fixture():
    """Acceptance: AutoStrategy(search=True) with a quantized compressor
    opt-in selects Int8 + ZeRO-1 + pipelined overlap on the comm-bound
    accumulation fixture."""
    from autodist_tpu.graph_item import GraphItem
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.strategy import AutoStrategy

    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 8, "chief": True}]})
    gi = GraphItem({"w": jnp.zeros((2048, 2048), jnp.float32),
                    "b": jnp.zeros((2048,), jnp.float32)}, accum_steps=4)
    searcher = AutoStrategy(search=True, compressor="Int8Compressor")
    strategy = searcher.build(gi, spec)
    assert searcher.last_choice == "Zero1"
    sync = strategy.node_for("w").synchronizer
    assert sync.sync == "reduce_scatter"
    assert sync.compressor == "Int8Compressor"
    assert ov.pipeline_applies(sync.overlap, accum_steps=4,
                               compressor=sync.compressor)
    # without the opt-in the default search stays numerics-safe
    plain = AutoStrategy(search=True)
    s2 = plain.build(gi, spec)
    assert s2.node_for("w").synchronizer.compressor == "NoneCompressor"
