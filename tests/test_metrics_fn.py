"""capture(metrics_fn=...) — extra metrics in step and evaluate outputs.

The reference fetched extra tensors through ``sess.run`` fetches; Keras
users know this as ``compile(metrics=[...])``.  Here a pure
``metrics_fn(params, batch) -> dict`` captured alongside the loss merges
into every training step's metrics, ``sess.evaluate``, and ``fit``'s
epoch logs — on both the GSPMD and the explicit (compressor) paths, and
with the LOGICAL param view under pad-to-divisible sharding.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.strategy import AllReduce, UnevenPartitionedPS


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


def _classifier(builder):
    rng = np.random.RandomState(0)
    w_true = rng.randn(5, 3).astype(np.float32)
    params = {"w": jnp.zeros((5, 3)), "b": jnp.zeros((3,))}

    def logits(p, batch):
        return batch["x"] @ p["w"] + p["b"]

    def loss_fn(p, batch):
        logz = jax.nn.log_softmax(logits(p, batch))
        onehot = jax.nn.one_hot(batch["y"], 3)
        return -jnp.mean(jnp.sum(onehot * logz, axis=-1))

    def metrics_fn(p, batch):
        pred = jnp.argmax(logits(p, batch), axis=-1)
        return {"accuracy": jnp.mean((pred == batch["y"]).astype(
            jnp.float32))}

    x = rng.randn(32, 5).astype(np.float32)
    y = np.argmax(x @ w_true, axis=-1).astype(np.int32)
    batch = {"x": x, "y": y}

    ad = AutoDist(strategy_builder=builder)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(0.1),
                   loss_fn=loss_fn, metrics_fn=metrics_fn)
    return ad.create_distributed_session(), batch


@pytest.mark.parametrize("builder", [
    AllReduce(),                                   # GSPMD path
    AllReduce(compressor="HorovodCompressor"),     # explicit shard_map path
    UnevenPartitionedPS(),                         # pad-to-divisible path
], ids=["gspmd", "explicit", "padded"])
def test_metrics_in_step_and_evaluate(builder):
    sess, batch = _classifier(builder)
    out = sess.run(batch)
    assert 0.0 <= float(out["accuracy"]) <= 1.0
    for _ in range(30):
        out = sess.run(batch, sync=False)
    acc = float(np.asarray(out["accuracy"]))
    assert acc > 0.9              # converges on a separable problem

    ev = sess.evaluate(batch)
    assert ev["accuracy"] == pytest.approx(acc, abs=1e-6)
    w = np.asarray(sess.params["w"])
    ev2 = sess.evaluate(batch)    # no state change
    np.testing.assert_array_equal(np.asarray(sess.params["w"]), w)
    assert ev2["accuracy"] == ev["accuracy"]


def test_reserved_metric_keys_raise():
    rng = np.random.RandomState(0)
    params = {"w": jnp.zeros((4, 2))}
    batch = {"x": rng.randn(8, 4).astype(np.float32),
             "y": rng.randn(8, 2).astype(np.float32)}
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1),
                   loss_fn=lambda p, b: jnp.mean((b["x"] @ p["w"]
                                                  - b["y"]) ** 2),
                   metrics_fn=lambda p, b: {"loss": jnp.float32(0)})
    sess = ad.create_distributed_session()
    with pytest.raises(ValueError, match="reserved metric key"):
        sess.run(batch)


def test_non_mean_metric_same_on_both_paths():
    """A NON-linear metric (max over the batch) must not depend on which
    execution path the strategy picked: the explicit (compressor) path
    computes metrics_fn OUTSIDE shard_map on the global batch, so it
    matches the GSPMD path instead of pmean-averaging per-shard maxes."""
    rng = np.random.RandomState(1)
    params = {"w": jnp.asarray(rng.randn(4, 2) * 0.1, jnp.float32)}
    batch = {"x": rng.randn(16, 4).astype(np.float32),
             "y": rng.randn(16, 2).astype(np.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    def metrics_fn(p, b):
        return {"max_abs_pred": jnp.max(jnp.abs(b["x"] @ p["w"]))}

    outs = {}
    for tag, builder in [("gspmd", AllReduce()),
                         ("explicit", AllReduce(
                             compressor="HorovodCompressorEF"))]:
        _reset_default_autodist_for_testing()
        ad = AutoDist(strategy_builder=builder)
        with ad.scope():
            ad.capture(params=params, optimizer=optax.sgd(0.0),
                       loss_fn=loss_fn, metrics_fn=metrics_fn)
        sess = ad.create_distributed_session()
        outs[tag] = float(np.asarray(sess.run(batch)["max_abs_pred"]))
    assert outs["gspmd"] == pytest.approx(outs["explicit"], rel=1e-6)


def test_metrics_in_fit_logs():
    sess, batch = _classifier(AllReduce())
    seen = []

    from autodist_tpu.fit import Callback

    class Grab(Callback):
        def on_step_end(self, step, metrics):
            seen.append(set(metrics))

    sess.fit(batch, epochs=1, steps_per_epoch=3, callbacks=[Grab()])
    assert all("accuracy" in s for s in seen)
