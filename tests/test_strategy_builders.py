"""Strategy builder tests (parity: reference tests/test_strategy_base.py and
the per-builder behaviors documented in SURVEY.md §2.3)."""
import jax.numpy as jnp
import pytest

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import (
    AllReduce,
    AllReduceSynchronizerConfig,
    Parallax,
    PartitionedAR,
    PartitionedPS,
    PS,
    PSLoadBalancing,
    PSSynchronizerConfig,
    RandomAxisPartitionAR,
    Strategy,
    UnevenPartitionedPS,
)
from autodist_tpu.strategy.partition_utils import (
    first_non_divisor,
    greedy_load_balance,
    smallest_divisor_gt_one,
)


@pytest.fixture
def spec2():
    return ResourceSpec(resource_info={
        "nodes": [
            {"address": "a", "chips": 4, "chief": True},
            {"address": "b", "chips": 4},
        ]})


@pytest.fixture
def gi():
    params = {
        "dense": {"kernel": jnp.zeros((6, 4)), "bias": jnp.zeros((4,))},
        "emb": {"table": jnp.zeros((100, 8))},
        "scalar": jnp.zeros(()),
    }
    return GraphItem(params, sparse_vars=["emb/table"])


def test_partition_math():
    assert smallest_divisor_gt_one(6) == 2
    assert smallest_divisor_gt_one(9) == 3
    assert smallest_divisor_gt_one(7) == 7
    assert smallest_divisor_gt_one(1) is None
    assert first_non_divisor(6) == 4
    assert first_non_divisor(12) == 5
    assert first_non_divisor(7) == 2
    assert first_non_divisor(2) is None


def test_greedy_load_balance():
    assignment, loads = greedy_load_balance([10, 8, 3, 3, 2], 2)
    assert assignment == [0, 1, 1, 0, 1]
    assert loads == [13.0, 13.0]


def test_ps_strategy(gi, spec2):
    s = PS().build(gi, spec2)
    assert len(s.node_config) == 4  # scalar included, all trainable
    dests = {n.synchronizer.reduction_destination for n in s.node_config}
    assert dests == {"a:CPU:0"}  # first node's CPU, reference ps_strategy.py:21-76
    assert len(s.graph_config.replicas) == 8


def test_ps_lb_strategy(gi, spec2):
    s = PSLoadBalancing().build(gi, spec2)
    dests = [n.synchronizer.reduction_destination for n in s.node_config]
    assert set(dests) <= {"a:CPU:0", "b:CPU:0"}
    assert len(set(dests)) == 2  # balanced across both nodes


def test_partitioned_ps(gi, spec2):
    s = PartitionedPS().build(gi, spec2)
    node = s.node_for("dense/kernel")
    assert node.partitioner == "2,1"  # smallest divisor of 6
    assert len(node.part_config) == 2
    assert all(isinstance(p.synchronizer, PSSynchronizerConfig)
               for p in node.part_config)
    # bias (4,) partitions into 2; scalar cannot partition
    assert s.node_for("scalar").partitioner == ""
    emb = s.node_for("emb/table")
    assert emb.partitioner == "2,1"


def test_uneven_partitioned_ps(gi, spec2):
    s = UnevenPartitionedPS().build(gi, spec2)
    node = s.node_for("dense/kernel")
    assert node.partitioner == "4,1"  # first non-divisor of 6
    emb = s.node_for("emb/table")
    assert emb.partitioner == "3,1"  # first non-divisor of 100


def test_all_reduce(gi, spec2):
    s = AllReduce(chunk_size=2).build(gi, spec2)
    assert all(isinstance(n.synchronizer, AllReduceSynchronizerConfig)
               for n in s.node_config)
    groups = [n.synchronizer.group for n in s.node_config]
    assert groups == [0, 0, 1, 1]  # chunked by 2


def test_partitioned_ar(gi, spec2):
    s = PartitionedAR().build(gi, spec2)
    node = s.node_for("dense/kernel")
    assert node.partitioner == "2,1"
    assert isinstance(node.synchronizer, AllReduceSynchronizerConfig)


def test_random_axis_ar(gi, spec2):
    s1 = RandomAxisPartitionAR(seed=600).build(gi, spec2)
    s2 = RandomAxisPartitionAR(seed=600).build(gi, spec2)
    # deterministic under the same seed
    assert [n.partitioner for n in s1.node_config] == \
           [n.partitioner for n in s2.node_config]
    emb = s1.node_for("emb/table")
    # sparse vars forced to axis 0 (reference random_axis...py:26-141)
    assert emb.partitioner.startswith("2,") or emb.partitioner == ""


def test_parallax(gi, spec2):
    s = Parallax().build(gi, spec2)
    assert isinstance(s.node_for("emb/table").synchronizer, PSSynchronizerConfig)
    assert isinstance(s.node_for("dense/kernel").synchronizer,
                      AllReduceSynchronizerConfig)


def test_strategy_serialize_roundtrip(gi, spec2, tmp_path):
    s = PartitionedPS().build(gi, spec2)
    path = s.serialize(str(tmp_path / s.id))
    s2 = Strategy.deserialize(s.id, base_dir=str(tmp_path))
    assert s2.id == s.id
    assert [n.to_dict() for n in s2.node_config] == \
           [n.to_dict() for n in s.node_config]
    assert s2.graph_config.replicas == s.graph_config.replicas


def test_every_builder_roundtrips_exactly(gi, spec2, tmp_path):
    """IR fidelity across ALL nine builders (the chief-serializes /
    worker-deserializes contract must lose nothing for any of them —
    partitioner strings, compressors, groups, destinations, staleness)."""
    from autodist_tpu.strategy import AutoStrategy

    builders = [PS(), PSLoadBalancing(), PartitionedPS(),
                UnevenPartitionedPS(),
                AllReduce(chunk_size=2, compressor="Int8Compressor"),
                PartitionedAR(), RandomAxisPartitionAR(seed=3), Parallax(),
                AutoStrategy(partition_threshold=64)]
    for b in builders:
        s = b.build(gi, spec2)
        s.serialize(str(tmp_path / s.id))
        s2 = Strategy.deserialize(s.id, base_dir=str(tmp_path))
        assert [n.to_dict() for n in s2.node_config] == \
               [n.to_dict() for n in s.node_config], type(b).__name__
        assert s2.graph_config.replicas == s.graph_config.replicas
        assert s2.graph_config.mesh_axes == s.graph_config.mesh_axes
