"""Analytic strategy cost model (the AutoSync-style pre-compile ranking
the OSS reference reduced to byte-size load balancing,
``ps_lb_strategy.py:91-117``)."""
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import (
    AllReduce,
    Parallax,
    PSLoadBalancing,
    estimate_cost,
    rank_strategies,
)
from autodist_tpu.strategy.cost_model import _ring_factor


@pytest.fixture
def spec8():
    return ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 8, "chief": True}]})


@pytest.fixture
def spec2x4():
    return ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 4, "chief": True},
                  {"address": "b", "chips": 4}],
        "network_bandwidth": 100})


def make_gi(vocab=100_000, dim=64):
    params = {
        "dense": {"kernel": jnp.zeros((512, 256)), "bias": jnp.zeros((256,))},
        "emb": {"table": jnp.zeros((vocab, dim))},
    }
    return GraphItem(params, sparse_vars=["emb/table"])


def test_allreduce_ring_volume_exact(spec8):
    gi = make_gi()
    report = estimate_cost(AllReduce().build(gi, spec8), gi, spec8)
    ring = _ring_factor(8)
    expected = ring * (512 * 256 * 4 + 256 * 4 + 100_000 * 64 * 4)
    assert report.wire_bytes == pytest.approx(expected)
    # every var shares one fusion group by default chunking or forms
    # few collectives — never more than one per var
    assert report.num_collectives <= 3
    assert report.time_s > 0


def test_sparse_embedding_makes_parallax_beat_allreduce(spec8):
    """The Parallax argument, quantified: AR must move the DENSIFIED
    100k x 64 table every step; sparse-PS moves only touched rows."""
    gi = make_gi()
    ar = estimate_cost(AllReduce().build(gi, spec8), gi, spec8)
    px = estimate_cost(Parallax().build(gi, spec8), gi, spec8)
    assert px.wire_bytes < ar.wire_bytes / 10
    emb_row = [v for v in px.per_var if v.name == "emb/table"][0]
    assert emb_row.sync == "ps_sparse"
    # touched rows (4096 hint) x row bytes x ring factor
    assert emb_row.wire_bytes == pytest.approx(
        _ring_factor(8) * 4096 * 64 * 4)


def test_sparse_rows_hint_caps_at_vocab(spec8):
    gi = make_gi(vocab=128, dim=8)
    px = estimate_cost(Parallax().build(gi, spec8), gi, spec8,
                       sparse_rows_hint=10_000)
    emb_row = [v for v in px.per_var if v.name == "emb/table"][0]
    assert emb_row.wire_bytes == pytest.approx(_ring_factor(8) * 128 * 8 * 4)


def test_compressor_halves_wire_bytes(spec8):
    gi = make_gi()
    full = estimate_cost(AllReduce().build(gi, spec8), gi, spec8)
    half = estimate_cost(
        AllReduce(compressor="HorovodCompressor").build(gi, spec8),
        gi, spec8)
    assert half.wire_bytes == pytest.approx(full.wire_bytes / 2)


def test_ps_shards_optimizer_state(spec8):
    gi = make_gi()
    ar = estimate_cost(AllReduce().build(gi, spec8), gi, spec8)
    ps = estimate_cost(PSLoadBalancing().build(gi, spec8), gi, spec8)
    # AR replicates Adam slots on every chip; the PS family (weight-update
    # sharding) and the vocab-sharded embedding keep them sharded.
    assert ps.opt_state_bytes < ar.opt_state_bytes


def test_ici_connected_pod_keeps_ici_bandwidth(spec8):
    """A TPU pod slice spans hosts on ONE interconnect domain
    (`ici_connected: true`): cross-host collectives must not be clocked
    at NIC/DCN bandwidth like the reference's GPU clusters."""
    gi = make_gi()
    pod = ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 4, "chief": True},
                  {"address": "b", "chips": 4}],
        "ici_connected": True, "network_bandwidth": 1})
    nic = ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 4, "chief": True},
                  {"address": "b", "chips": 4}],
        "network_bandwidth": 1})
    t_pod = estimate_cost(AllReduce().build(gi, pod), gi, pod).time_s
    t_nic = estimate_cost(AllReduce().build(gi, nic), gi, nic).time_s
    t_one = estimate_cost(AllReduce().build(gi, spec8), gi, spec8).time_s
    assert t_pod == pytest.approx(t_one)     # same ring volume, ICI clock
    assert t_nic > 10 * t_pod                # 1 Gbps NIC vs ICI


def test_dcn_bottleneck_slows_multinode(spec8, spec2x4):
    gi = make_gi()
    strat = AllReduce().build(gi, spec8)
    one_node = estimate_cost(strat, gi, spec8)
    two_node = estimate_cost(AllReduce().build(gi, spec2x4), gi, spec2x4)
    # 100 Gbps DCN (12.5 GB/s) < ICI: same ring volume, slower clock.
    assert two_node.time_s > one_node.time_s
    assert two_node.wire_bytes == pytest.approx(one_node.wire_bytes)


def test_single_chip_no_traffic_no_phantom_latency():
    """d == 1: no collectives execute, so no wire bytes AND no launch
    latency — every strategy ranks identically free."""
    gi = make_gi()
    spec1 = ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 1, "chief": True}]})
    for builder in (AllReduce(), PSLoadBalancing(), Parallax()):
        report = estimate_cost(builder.build(gi, spec1), gi, spec1)
        assert report.wire_bytes == 0.0
        assert report.num_collectives == 0
        assert report.time_s == 0.0


def test_unknown_compressor_warns_and_assumes_uncompressed(caplog):
    gi = make_gi()
    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 8, "chief": True}]})
    full = estimate_cost(AllReduce().build(gi, spec), gi, spec)
    typo = estimate_cost(
        AllReduce(compressor="Int8compressor").build(gi, spec), gi, spec)
    assert typo.wire_bytes == pytest.approx(full.wire_bytes)


def test_rank_covers_all_shipped_builders(spec8):
    names = {name for name, _ in rank_strategies(make_gi(), spec8)}
    assert names == {"PS", "PSLoadBalancing", "PartitionedPS",
                     "UnevenPartitionedPS", "AllReduce", "PartitionedAR",
                     "RandomAxisPartitionAR", "Parallax", "Zero1",
                     "AutoStrategy"}


def test_rank_strategies_prefers_sparse_aware(spec8):
    gi = make_gi()
    ranked = rank_strategies(gi, spec8)
    names = [name for name, _ in ranked]
    assert set(names) >= {"AllReduce", "Parallax", "PSLoadBalancing"}
    # sparse-aware strategies must outrank plain AllReduce on an
    # embedding-dominated model
    assert names.index("Parallax") < names.index("AllReduce")
    assert names.index("AutoStrategy") < names.index("AllReduce")
    # reports are sorted by estimated time
    times = [r.time_s for _, r in ranked]
    assert times == sorted(times)
    assert ranked[0][1].summary()  # human-readable summary renders
