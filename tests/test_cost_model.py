"""Analytic strategy cost model (the AutoSync-style pre-compile ranking
the OSS reference reduced to byte-size load balancing,
``ps_lb_strategy.py:91-117``)."""
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import (
    AllReduce,
    Parallax,
    PSLoadBalancing,
    estimate_cost,
    rank_strategies,
)
from autodist_tpu.strategy.cost_model import _ring_factor


@pytest.fixture
def spec8():
    return ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 8, "chief": True}]})


@pytest.fixture
def spec2x4():
    return ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 4, "chief": True},
                  {"address": "b", "chips": 4}],
        "network_bandwidth": 100})


def make_gi(vocab=100_000, dim=64):
    params = {
        "dense": {"kernel": jnp.zeros((512, 256)), "bias": jnp.zeros((256,))},
        "emb": {"table": jnp.zeros((vocab, dim))},
    }
    return GraphItem(params, sparse_vars=["emb/table"])


def test_allreduce_ring_volume_exact(spec8):
    gi = make_gi()
    report = estimate_cost(AllReduce().build(gi, spec8), gi, spec8)
    ring = _ring_factor(8)
    expected = ring * (512 * 256 * 4 + 256 * 4 + 100_000 * 64 * 4)
    assert report.wire_bytes == pytest.approx(expected)
    # every var shares one fusion group by default chunking or forms
    # few collectives — never more than one per var
    assert report.num_collectives <= 3
    assert report.time_s > 0


def test_sparse_embedding_makes_parallax_beat_allreduce(spec8):
    """The Parallax argument, quantified: AR must move the DENSIFIED
    100k x 64 table every step; sparse-PS moves only touched rows."""
    gi = make_gi()
    ar = estimate_cost(AllReduce().build(gi, spec8), gi, spec8)
    px = estimate_cost(Parallax().build(gi, spec8), gi, spec8)
    assert px.wire_bytes < ar.wire_bytes / 10
    emb_row = [v for v in px.per_var if v.name == "emb/table"][0]
    assert emb_row.sync == "ps_sparse"
    # touched rows (4096 hint) x row bytes x ring factor
    assert emb_row.wire_bytes == pytest.approx(
        _ring_factor(8) * 4096 * 64 * 4)


def test_sparse_rows_hint_caps_at_vocab(spec8):
    gi = make_gi(vocab=128, dim=8)
    px = estimate_cost(Parallax().build(gi, spec8), gi, spec8,
                       sparse_rows_hint=10_000)
    emb_row = [v for v in px.per_var if v.name == "emb/table"][0]
    assert emb_row.wire_bytes == pytest.approx(_ring_factor(8) * 128 * 8 * 4)


def test_compressor_halves_wire_bytes(spec8):
    gi = make_gi()
    full = estimate_cost(AllReduce().build(gi, spec8), gi, spec8)
    half = estimate_cost(
        AllReduce(compressor="HorovodCompressor").build(gi, spec8),
        gi, spec8)
    assert half.wire_bytes == pytest.approx(full.wire_bytes / 2)


def test_ps_shards_optimizer_state(spec8):
    gi = make_gi()
    ar = estimate_cost(AllReduce().build(gi, spec8), gi, spec8)
    ps = estimate_cost(PSLoadBalancing().build(gi, spec8), gi, spec8)
    # AR replicates Adam slots on every chip; the PS family (weight-update
    # sharding) and the vocab-sharded embedding keep them sharded.
    assert ps.opt_state_bytes < ar.opt_state_bytes


def test_ici_connected_pod_keeps_ici_bandwidth(spec8):
    """A TPU pod slice spans hosts on ONE interconnect domain
    (`ici_connected: true`): cross-host collectives must not be clocked
    at NIC/DCN bandwidth like the reference's GPU clusters."""
    gi = make_gi()
    pod = ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 4, "chief": True},
                  {"address": "b", "chips": 4}],
        "ici_connected": True, "network_bandwidth": 1})
    nic = ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 4, "chief": True},
                  {"address": "b", "chips": 4}],
        "network_bandwidth": 1})
    t_pod = estimate_cost(AllReduce().build(gi, pod), gi, pod).time_s
    t_nic = estimate_cost(AllReduce().build(gi, nic), gi, nic).time_s
    t_one = estimate_cost(AllReduce().build(gi, spec8), gi, spec8).time_s
    assert t_pod == pytest.approx(t_one)     # same ring volume, ICI clock
    assert t_nic > 10 * t_pod                # 1 Gbps NIC vs ICI


def test_dcn_bottleneck_slows_multinode(spec8, spec2x4):
    gi = make_gi()
    strat = AllReduce().build(gi, spec8)
    one_node = estimate_cost(strat, gi, spec8)
    two_node = estimate_cost(AllReduce().build(gi, spec2x4), gi, spec2x4)
    # 100 Gbps DCN (12.5 GB/s) < ICI: same ring volume, slower clock.
    assert two_node.time_s > one_node.time_s
    assert two_node.wire_bytes == pytest.approx(one_node.wire_bytes)


def test_single_chip_no_traffic_no_phantom_latency():
    """d == 1: no collectives execute, so no wire bytes AND no launch
    latency — every strategy ranks identically free."""
    gi = make_gi()
    spec1 = ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 1, "chief": True}]})
    for builder in (AllReduce(), PSLoadBalancing(), Parallax()):
        report = estimate_cost(builder.build(gi, spec1), gi, spec1)
        assert report.wire_bytes == 0.0
        assert report.num_collectives == 0
        assert report.time_s == 0.0


def test_unknown_compressor_warns_and_assumes_uncompressed(caplog):
    gi = make_gi()
    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 8, "chief": True}]})
    full = estimate_cost(AllReduce().build(gi, spec), gi, spec)
    typo = estimate_cost(
        AllReduce(compressor="Int8compressor").build(gi, spec), gi, spec)
    assert typo.wire_bytes == pytest.approx(full.wire_bytes)


def test_rank_covers_all_shipped_builders(spec8):
    names = {name for name, _ in rank_strategies(make_gi(), spec8)}
    assert names == {"PS", "PSLoadBalancing", "PartitionedPS",
                     "UnevenPartitionedPS", "AllReduce", "PartitionedAR",
                     "RandomAxisPartitionAR", "Parallax", "Zero1",
                     "AutoStrategy"}


def _large_dense_gi(accum=1):
    """Large dense fixture: one 4M-param f32 matrix (+ bias), optionally
    under gradient accumulation."""
    return GraphItem({"w": jnp.zeros((2048, 2048), jnp.float32),
                      "b": jnp.zeros((2048,), jnp.float32)},
                     accum_steps=accum)


def test_pipelined_zero1_outranks_unpipelined(spec8):
    """The calibration regression of the PR issue: with accumulation
    active, the overlap-aware estimate (max(compute, exposed_comm)) must
    rank pipelined ZeRO-1 above the phase-serial schedule — the additive
    compute+comm model cannot see the difference."""
    from autodist_tpu.strategy import Zero1

    gi = _large_dense_gi(accum=4)
    piped = estimate_cost(Zero1(overlap="auto").build(gi, spec8), gi, spec8)
    serial = estimate_cost(Zero1(overlap="none").build(gi, spec8), gi, spec8)
    # same wire volume, but the pipeline hides 3/4 of the reduce leg and
    # prefetch hides half the param gather
    assert piped.wire_bytes == pytest.approx(serial.wire_bytes)
    assert piped.exposed_wire_bytes < 0.5 * serial.exposed_wire_bytes
    assert serial.overlap_fraction == 0.0
    assert piped.overlap_fraction > 0.5
    assert piped.time_s < serial.time_s
    # without accumulation only the prefetch term remains
    gi1 = _large_dense_gi(accum=1)
    p1 = estimate_cost(Zero1(overlap="auto").build(gi1, spec8), gi1, spec8)
    assert 0.0 < p1.overlap_fraction < piped.overlap_fraction


def test_overlap_estimate_degrades_to_additive_without_overlap(spec8):
    """overlap='none' (or a plain GSPMD AllReduce) reproduces the PR 2
    additive estimate exactly: exposed == wire."""
    gi = _large_dense_gi(accum=4)
    rep = estimate_cost(AllReduce().build(gi, spec8), gi, spec8)
    assert rep.exposed_wire_bytes == pytest.approx(rep.wire_bytes)
    assert rep.overlap_fraction == 0.0


def test_compute_time_floor_caps_hidden_comm(spec8):
    """max(compute, exposed_comm): a compute hint larger than the
    exposed comm becomes the critical path."""
    gi = _large_dense_gi(accum=4)
    from autodist_tpu.strategy import Zero1

    strat = Zero1(overlap="auto").build(gi, spec8)
    fast = estimate_cost(strat, gi, spec8)
    slow = estimate_cost(strat, gi, spec8, compute_time_s=1.0)
    assert slow.time_s == pytest.approx(1.0 + fast.update_bytes / 8.1e11)
    assert fast.time_s < slow.time_s


def test_auto_strategy_search_selects_overlapped_mode(spec8):
    """Acceptance: AutoStrategy(search=True) picks an overlapped mode on
    the large dense fixture — the winning strategy's sync carries an
    overlap schedule that actually applies under accumulation."""
    from autodist_tpu.kernel.synchronization import overlap as ov
    from autodist_tpu.strategy import AutoStrategy, Zero1

    gi = _large_dense_gi(accum=4)
    searcher = AutoStrategy(search=True)
    strategy = searcher.build(gi, spec8)
    assert searcher.last_choice == "Zero1"
    sync = strategy.node_for("w").synchronizer
    assert sync.sync == "reduce_scatter"
    assert ov.pipeline_applies(sync.overlap, accum_steps=gi.accum_steps,
                               compressor=sync.compressor)
    # and the overlapped candidate strictly beats an explicitly serial
    # one (the serial candidate is listed first, so it wins ties)
    searcher2 = AutoStrategy(search=True, candidates=[
        Zero1(overlap="none"), Zero1(overlap="auto")])
    chosen = searcher2.build(gi, spec8)
    assert chosen.node_for("w").synchronizer.overlap == "auto"


def test_rank_strategies_deterministic_tiebreak_and_dedupe(spec8):
    """Deterministic ranking: ties break by (cost, builder name) and
    dedupe=True drops candidates with identical plan fingerprints."""
    from autodist_tpu.strategy import PS, PSLoadBalancing
    from autodist_tpu.strategy.cost_model import plan_fingerprint

    gi = make_gi()
    a = rank_strategies(gi, spec8)
    b = rank_strategies(gi, spec8)
    assert [n for n, _ in a] == [n for n, _ in b]
    keys = [(r.time_s, n) for n, r in a]
    assert keys == sorted(keys)
    # PS and PSLoadBalancing degenerate to the same plan on a
    # single-destination spec: same fingerprint, deduped when asked.
    ps = PS().build(gi, spec8)
    lb = PSLoadBalancing().build(gi, spec8)
    assert plan_fingerprint(ps) == plan_fingerprint(lb)
    deduped = rank_strategies(gi, spec8,
                              builders=[PS(), PSLoadBalancing()],
                              dedupe=True)
    assert len(deduped) == 1


def test_estimate_ir_cost_per_kind_breakdown(spec8):
    """estimate_ir_cost attributes exposed cost per leg kind (the
    search explain surface's breakdown) and the kinds sum to the comm
    estimate."""
    from autodist_tpu.kernel.synchronization import schedule_ir as sir
    from autodist_tpu.strategy.cost_model import estimate_ir_cost

    facts = [sir.PlanFact(name="w", shape=(1024, 1024), dtype="float32",
                          sync_kind="AllReduce")]
    ir = sir.ir_from_facts(facts, axes={"data": 8})
    report = estimate_ir_cost(ir)
    assert set(report.per_kind) == {"all_reduce"}
    assert report.per_kind["all_reduce"] == pytest.approx(
        report.time_s)


def test_unfitted_ps_exchange_borrows_all_reduce_constants():
    """A calibration that never measured a PS plan must not price PS
    exchanges at optimistic defaults: they borrow the fitted all-reduce
    constants (same ring volume by construction)."""
    from autodist_tpu.kernel.synchronization import schedule_ir as sir
    from autodist_tpu.strategy.cost_model import leg_cost_s
    from autodist_tpu.telemetry.calibration import LegCalibration

    cal = LegCalibration(bandwidths={"all_reduce": 1e8},
                         alphas={"all_reduce": 1e-4})
    facts = [sir.PlanFact(name="w", shape=(1024, 1024), dtype="float32",
                          sync_kind="PS")]
    ir = sir.ir_from_facts(facts, axes={"data": 8})
    leg = next(l for l in ir.legs if l.kind == sir.LEG_PS_EXCHANGE)
    t = leg_cost_s(leg, ir, cal)
    wire = 2.0 * 7 / 8 * 1024 * 1024 * 4
    assert t == pytest.approx(wire / 1e8 + 1e-4)


def test_planted_calibration_json_flips_auto_strategy_beam(
        tmp_path, monkeypatch):
    """The satellite acceptance: planted calibration.json constants
    (comm-bound vs compute-bound) flip AutoStrategy(search="beam")'s
    winner through the ENV discovery path, and each winner's IR passes
    the verifier."""
    import json as _json

    from autodist_tpu.analysis.search import facts_for_candidate
    from autodist_tpu.kernel.synchronization import schedule_ir as sir
    from autodist_tpu.strategy import AutoStrategy
    from autodist_tpu.telemetry.calibration import (
        LEG_KINDS,
        reset_calibration_cache_for_testing,
    )

    gi = _large_dense_gi(accum=4)
    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "a", "chips": 8, "chief": True}]})

    def plant(bandwidth, quant_overhead):
        d = {"version": 1, "scale": 1.0,
             "quant_overhead_per_byte": quant_overhead,
             "alphas": {k: 1e-7 for k in LEG_KINDS},
             "bandwidths": {k: bandwidth for k in LEG_KINDS}}
        path = tmp_path / "calibration.json"
        path.write_text(_json.dumps(d))
        monkeypatch.setenv("AUTODIST_CALIBRATION", str(path))
        reset_calibration_cache_for_testing()

    winners = {}
    for name, (bw, qo) in {"comm_bound": (1e8, 0.0),
                           "quant_hostile": (1e12, 1e-6)}.items():
        plant(bw, qo)
        b = AutoStrategy(search="beam", compressor="Int8Compressor")
        strategy = b.build(gi, spec)
        winners[name] = b.last_search.best.fingerprint
        # the winner's IR passes the verifier
        facts, _, guard, prune = facts_for_candidate(
            strategy, gi, {"data": 8})
        assert prune is None
        ir = sir.ir_from_facts(facts, axes={"data": 8}, accum_steps=4,
                               guard=guard)
        assert not sir.errors(sir.verify(ir))
    assert winners["comm_bound"] != winners["quant_hostile"]
    monkeypatch.delenv("AUTODIST_CALIBRATION")
    reset_calibration_cache_for_testing()


def test_rank_strategies_prefers_sparse_aware(spec8):
    gi = make_gi()
    ranked = rank_strategies(gi, spec8)
    names = [name for name, _ in ranked]
    assert set(names) >= {"AllReduce", "Parallax", "PSLoadBalancing"}
    # sparse-aware strategies must outrank plain AllReduce on an
    # embedding-dominated model
    assert names.index("Parallax") < names.index("AllReduce")
    assert names.index("AutoStrategy") < names.index("AllReduce")
    # reports are sorted by estimated time
    times = [r.time_s for _, r in ranked]
    assert times == sorted(times)
    assert ranked[0][1].summary()  # human-readable summary renders
