"""Hierarchical ICI+DCN grad sync (docs/strategies.md "Two-tier sync
and --simulate").

Runtime parity of the two-level lowering (within-slice reduce-scatter,
cross-slice DCN exchange, within-slice all-gather) against the flat
ring on a simulated 2-slice CPU mesh — plain AllReduce and ZeRO-1, f32
exact and int8-DCN within quantizer tolerance; static-vs-runtime
schedule fingerprint equality; the ResourceSpec slice fields and the
``legality/slice-mismatch`` fail-fast; the beam search's ``hier`` gene
flipping flat -> hierarchical when the DCN narrows; the ``--simulate``
sweep (in-process and the CLI subprocess, including the over-HBM
exit-1 contract); and the telemetry compare report with leg kinds
present in only one run.
"""
import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.kernel.synchronization import schedule_ir as sir
from autodist_tpu.resource_spec import (
    RULE_SLICE_MISMATCH,
    ResourceSpec,
    ResourceSpecError,
    slice_mismatch_reason,
)
from autodist_tpu.strategy import AllReduce, Zero1

pytestmark = pytest.mark.hier

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset(monkeypatch):
    monkeypatch.delenv("AUTODIST_DCN_WIRE", raising=False)
    _reset_default_autodist_for_testing()
    yield
    _reset_default_autodist_for_testing()


def _spec(num_slices=1, dcn_gbps=25):
    info = {"nodes": [{"address": "localhost", "chips": 8,
                       "chief": True}],
            "mesh": {"data": 8}}
    if num_slices > 1:
        info["num_slices"] = num_slices
        info["dcn_gbps"] = dcn_gbps
    return ResourceSpec(resource_info=info)


def _problem():
    rng = np.random.RandomState(3)
    params = {"a": {"w": jnp.asarray(rng.randn(13, 9) * 0.1, jnp.float32),
                    "b": jnp.asarray(rng.randn(9) * 0.1, jnp.float32)},
              "out": {"w": jnp.asarray(rng.randn(9, 4) * 0.1, jnp.float32)}}
    batch = {"x": rng.randn(16, 13).astype(np.float32),
             "y": rng.randn(16, 4).astype(np.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["a"]["w"] + p["a"]["b"])
        return jnp.mean((h @ p["out"]["w"] - b["y"]) ** 2)

    return params, loss_fn, batch


def _session(builder, spec, params, loss_fn):
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=builder, resource_spec=spec)
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2),
                   loss_fn=loss_fn)
    return ad, ad.create_distributed_session()


def _assert_parity(flat_builder, hier_builder, tol):
    params, loss_fn, batch = _problem()
    _, flat = _session(flat_builder, _spec(1), params, loss_fn)
    _, hier = _session(hier_builder, _spec(2), params, loss_fn)
    ir = hier.schedule_ir
    kinds = {l.kind for l in ir.legs}
    assert kinds & set(sir.HIER_KINDS), \
        f"no hierarchical legs in the runtime IR: {sorted(kinds)}"
    assert any(l.tier == sir.TIER_DCN for l in ir.legs)
    assert not sir.errors(sir.verify(ir))
    for _ in range(5):
        lf = float(flat.run(batch)["loss"])
        lh = float(hier.run(batch)["loss"])
        np.testing.assert_allclose(lh, lf, rtol=tol, atol=tol)
    for k, leaves in flat.params.items():
        for kk in leaves:
            np.testing.assert_allclose(
                np.asarray(hier.params[k][kk]),
                np.asarray(flat.params[k][kk]), rtol=tol, atol=tol)
    return ir


# -- runtime parity: two-tier lowering == flat ring --------------------------

@pytest.mark.sync
def test_hier_allreduce_parity_f32():
    ir = _assert_parity(AllReduce(bucket_bytes=1 << 20),
                        AllReduce(bucket_bytes=1 << 20, hier=True),
                        tol=1e-6)
    kinds = {l.kind for l in ir.legs}
    assert sir.LEG_DCN_ALL_REDUCE in kinds
    assert sir.LEG_HIER_ALL_GATHER in kinds


@pytest.mark.sync
def test_hier_zero1_parity_f32():
    ir = _assert_parity(Zero1(), Zero1(hier=True), tol=1e-6)
    kinds = {l.kind for l in ir.legs}
    assert sir.LEG_DCN_EXCHANGE in kinds
    # the ZeRO-1 two-tier param gather: DCN then ICI
    ag_tiers = {l.tier for l in ir.legs
                if l.kind == sir.LEG_HIER_ALL_GATHER}
    assert ag_tiers == {sir.TIER_DCN, sir.TIER_ICI}


@pytest.mark.sync
def test_hier_allreduce_parity_int8_dcn(monkeypatch):
    monkeypatch.setenv("AUTODIST_DCN_WIRE", "int8")
    ir = _assert_parity(AllReduce(bucket_bytes=1 << 20),
                        AllReduce(bucket_bytes=1 << 20, hier=True),
                        tol=2e-2)
    dcn = [l for l in ir.legs if l.kind == sir.LEG_DCN_ALL_REDUCE]
    assert dcn and all(sir.is_quantizing(l.compressor) for l in dcn)


@pytest.mark.sync
def test_hier_zero1_parity_int8_dcn(monkeypatch):
    monkeypatch.setenv("AUTODIST_DCN_WIRE", "int8")
    _assert_parity(Zero1(), Zero1(hier=True), tol=2e-2)


def test_static_and_runtime_fingerprints_match():
    """ir_from_facts (the analysis/search side) and the runtime's
    build_schedule_ir emit the identical two-tier program."""
    from autodist_tpu.analysis.search import facts_for_candidate

    params, loss_fn, _ = _problem()
    spec = _spec(2)
    builder = AllReduce(bucket_bytes=1 << 20, hier=True)
    ad, sess = _session(builder, spec, params, loss_fn)
    runtime_ir = sess.schedule_ir
    strategy = builder.build(ad.graph_item, spec)
    facts, _, guard, prune = facts_for_candidate(
        strategy, ad.graph_item, {"data": 8}, resource_spec=spec)
    assert prune is None
    static_ir = sir.ir_from_facts(facts, axes={"data": 8}, guard=guard,
                                  num_slices=2)
    assert static_ir.fingerprint() == runtime_ir.fingerprint()


# -- ResourceSpec: slice fields + divisibility fail-fast ---------------------

def test_resource_spec_two_tier_fields():
    spec = _spec(2, dcn_gbps=50)
    assert spec.num_slices == 2
    assert spec.dcn_gbps == 50
    assert spec.dcn_bytes_per_s == 50e9 / 8
    flat = _spec(1)
    assert flat.num_slices == 1


def test_slice_mismatch_is_one_shared_rule_string():
    reason = slice_mismatch_reason(8, 3)
    assert reason is not None and reason.startswith(RULE_SLICE_MISMATCH)
    assert slice_mismatch_reason(8, 4) is None
    assert slice_mismatch_reason(8, 1) is None
    with pytest.raises(ResourceSpecError, match="legality/slice-mismatch"):
        ResourceSpec(resource_info={
            "nodes": [{"address": "localhost", "chips": 8,
                       "chief": True}],
            "num_slices": 3})


# -- beam search: the hier gene ----------------------------------------------

def _flat_cal(bandwidth=45e9, alpha=5e-6):
    from autodist_tpu.telemetry.calibration import LEG_KINDS, LegCalibration

    cal = LegCalibration()
    for kind in LEG_KINDS:
        cal.bandwidths[kind] = float(bandwidth)
        cal.alphas[kind] = alpha
    return cal


def test_beam_flips_to_hier_on_narrow_dcn():
    """Planted flat calibration, multi-slice spec with a narrow DCN:
    the flat ring books every byte at DCN speed while the hierarchy
    ships only the 1/d_in shard across — beam must pick hier.  The
    same fixture on a single-slice spec must keep flat and never set
    the gene."""
    from autodist_tpu.strategy.search import SearchSpace, beam_search

    gi = GraphItem({"w": jnp.zeros((2048, 2048), jnp.float32),
                    "b": jnp.zeros((2048,), jnp.float32)},
                   accum_steps=4)
    cal = _flat_cal()
    space = SearchSpace(max_rounds=2)
    narrow = beam_search(gi, _spec(2, dcn_gbps=10), space=space,
                         constants=cal)
    assert any(g.hier for _, g in narrow.best.genes), narrow.best.name
    single = beam_search(gi, _spec(1), space=space, constants=cal)
    assert not any(g.hier for _, g in single.best.genes)
    assert not any(g.hier for ev in single.evaluated
                   for _, g in ev.genes)


# -- the --simulate sweep ----------------------------------------------------

def _sweep_gi():
    return GraphItem({"w": jnp.zeros((1024, 1024), jnp.float32)})


def _make_strategy(gi):
    def make(spec, hier):
        return (AllReduce(hier=True) if hier else AllReduce()).build(
            gi, spec)
    return make


def test_simulate_sweep_ranks_and_prunes():
    from autodist_tpu.analysis.simulate import parse_sweep_spec, run_sweep

    gi = _sweep_gi()
    config = parse_sweep_spec("mesh=data=8;slices=2,3;dcn=10,100")
    report = run_sweep(gi, _make_strategy(gi), config)
    assert report["n_points"] == 4
    by_key = {(p["num_slices"], p["dcn_gbps"]): p
              for p in report["points"]}
    # slices=3 cannot tile 8 chips: pruned with the shared rule string
    for dcn in (10.0, 100.0):
        assert by_key[(3, dcn)]["pruned_by"].startswith(
            RULE_SLICE_MISMATCH)
    # narrow DCN favors the hierarchy; modes are priced and ranked
    narrow = by_key[(2, 10.0)]
    assert narrow["best_mode"] in ("hier", "hier_int8")
    assert set(narrow["ranking"]) == {"flat", "hier", "hier_int8"}
    flat_cell = narrow["modes"]["flat"]
    hier_cell = narrow["modes"]["hier"]
    assert hier_cell["predicted_step_s"] < flat_cell["predicted_step_s"]
    # the two-tier decomposition moves wire off the DCN
    assert hier_cell["wire_by_tier"]["dcn"] \
        < flat_cell["wire_by_tier"]["dcn"]
    # goodput rides every priced cell (the checkpoint stall dominates
    # these micro step times, so the ratio is small but well-formed)
    for cell in (flat_cell, hier_cell):
        ratio = cell["goodput"]["goodput_ratio"]
        assert ratio is not None and 0 < ratio <= 1


def test_simulate_prunes_over_hbm_point():
    from autodist_tpu.analysis.simulate import parse_sweep_spec, run_sweep

    gi = _sweep_gi()
    config = parse_sweep_spec("mesh=data=8;slices=1;dcn=25;hbm=0.0001")
    report = run_sweep(gi, _make_strategy(gi), config)
    assert report["n_over_hbm"] == 1
    (point,) = report["points"]
    assert "memory/watermark-exceeds-hbm" in point["pruned_by"]


def test_simulate_large_topology_is_fast():
    """A 1024-chip 2-level sweep point prices through the pure model in
    well under the 30 s budget (no mesh, no jax trace)."""
    import time

    from autodist_tpu.analysis.simulate import parse_sweep_spec, run_sweep

    gi = _sweep_gi()
    config = parse_sweep_spec("mesh=data=1024;slices=4;dcn=25,100")
    t0 = time.perf_counter()
    report = run_sweep(gi, _make_strategy(gi), config)
    assert time.perf_counter() - t0 < 30
    assert all("best_mode" in p for p in report["points"])


@pytest.mark.analysis
def test_simulate_cli_subprocess_exit_codes(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    base = [sys.executable, "-m", "autodist_tpu.analysis", "mlp",
            "AllReduce", "--json"]
    ok = subprocess.run(
        base + ["--simulate", "mesh=data=8;slices=1,2;dcn=25"],
        capture_output=True, env=env, cwd=REPO, timeout=300)
    assert ok.returncode == 0, ok.stderr.decode()
    report = json.loads(ok.stdout.decode())
    assert report["n_points"] == 2 and report["n_over_hbm"] == 0
    over = subprocess.run(
        base + ["--simulate",
                "mesh=data=8;slices=1;dcn=25;hbm=0.0000001"],
        capture_output=True, env=env, cwd=REPO, timeout=300)
    assert over.returncode == 1, over.stdout.decode()
    report = json.loads(over.stdout.decode())
    assert report["n_over_hbm"] == 1


# -- telemetry compare: one-sided leg kinds ----------------------------------

@pytest.mark.telemetry
def test_compare_reports_new_and_removed_leg_kinds(tmp_path, capsys):
    """Flipping a run to two-tier sync changes its leg-kind set; the
    compare report must label the one-sided kinds instead of crashing
    or silently dropping them."""
    from autodist_tpu.telemetry import profiler as prof
    from autodist_tpu.telemetry import timeline as tl
    from autodist_tpu.telemetry.__main__ import main

    def write_run(name, kinds):
        run = tmp_path / name
        run.mkdir()
        with open(run / "steps-host-1.jsonl", "w") as f:
            for i in range(4):
                rec = tl.StepRecord(step=i, time_unix=1000.0 + i * 0.01,
                                    step_time_s=0.01, host="host")
                f.write(rec.to_json() + "\n")
        prof.write_leg_samples(
            [prof.LegSample(schedule_fingerprint="fp", leg_id=f"{k}/0",
                            kind=k, measured_s=1e-3, nbytes=1 << 20,
                            time_unix=1000.0) for k in kinds], str(run))
        return run

    run_a = write_run("flat", ["all_reduce"])
    run_b = write_run("hier", [sir.LEG_HIER_REDUCE_SCATTER,
                               sir.LEG_DCN_ALL_REDUCE])
    assert main([str(run_a), "--compare", str(run_b), "--json"]) == 0
    cmp = json.loads(capsys.readouterr().out)
    assert cmp["leg_kinds"]["all_reduce"]["status"] == "removed"
    assert cmp["leg_kinds"][sir.LEG_DCN_ALL_REDUCE]["status"] == "new"
    assert main([str(run_a), "--compare", str(run_b)]) == 0
    human = capsys.readouterr().out
    assert "(new in b)" in human and "(removed in b)" in human
