"""Schedule dataflow sanitizer: races, leaks, liveness watermark.

Mirrors the PR acceptance criteria (docs/schedule-ir.md "Dataflow"):

* **happens-before units** — the packed-bitset reachability structure
  agrees with brute-force closure on hand and planner graphs;
* **mutation goldens** — a planted unordered write, read-write race,
  buffer leak, donated-``param:``/``opt:`` late read, and watermark
  overflow are each rejected/flagged with their distinct rule id;
* **fuzz** — planner-emitted IRs (incl. fused-kernel legs and
  quantized per-hop chains) show ZERO race/leak findings, and a fuzz
  axis that randomly deletes dep edges must match a brute-force oracle
  exactly: every ordering the deletion breaks between conflicting
  accesses is caught (no false negatives), nothing more is reported
  (no false positives);
* **wiring** — the memory pass's watermark budget rules, beam-search
  OOM pruning (a candidate the coarse footprint sum admitted), the
  tuner's hot-swap veto, elastic preflight on the resized mesh, the
  byte-stable diagnostics ordering, and the CLI
  ``--watermark --dump-ir json`` end-to-end smoke;
* **budget** — verify (races included) + watermark stay under the 1 s
  pre-trace-gate budget on the 9k-leg fixture.
"""
import dataclasses
import json
import os
import subprocess
import sys
import time
from itertools import combinations

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.analysis import analyze, dataflow
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.kernel.synchronization import bucketing, overlap
from autodist_tpu.kernel.synchronization import schedule_ir as sir
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import Strategy

sys.path.insert(0, os.path.dirname(__file__))
from _analysis_fixtures import AXES8, ar_node  # noqa: E402

pytestmark = pytest.mark.schedule

_MiB = 1 << 20


def _entries(n=6, shape=(256, 256), dtype="float32", comp="NoneCompressor",
             mode="reduce_scatter", prefix="l"):
    return [(f"{prefix}{i}/w", shape, dtype, comp, 0, mode)
            for i in range(n)]


def _ir(entries, *, bucket_bytes=256 << 10, d=8, accum=1, mode="auto",
        guard=False, donated=(), stateful_keys=(), fused_kernels=(),
        moe=(), expert_ax=1):
    buckets = bucketing.assign_buckets(entries, bucket_bytes=bucket_bytes,
                                       shard_divisor=d)
    plan = overlap.resolve_overlap(
        [mode], accum_steps=accum, buckets=buckets, d=d,
        has_rs=any(b.mode == "reduce_scatter" for b in buckets))
    axes = {"data": d}
    if expert_ax > 1:
        axes["expert"] = expert_ax
    return sir.build_schedule_ir(
        axes=axes, accum_steps=accum, buckets=buckets, plan=plan,
        guard=guard, donated=donated, stateful_keys=stateful_keys,
        fused_kernels=fused_kernels, moe=moe)


def _with_legs(ir, legs):
    clone = sir.ScheduleIR.from_dict(ir.to_dict())
    clone.legs = list(legs)
    return clone


def _errors(ir):
    return [v for v in sir.verify(ir) if v.severity == sir.SEV_ERROR]


def _rules(violations):
    return {v.rule for v in violations}


# -- happens-before units -----------------------------------------------------

def _leg(id, deps=(), reads=(), writes=(), kind=sir.LEG_UPDATE, **kw):
    return sir.Leg(id=id, kind=kind, deps=tuple(deps), reads=tuple(reads),
                   writes=tuple(writes), **kw)


def test_happens_before_bitset_matches_hand_graph():
    legs = [_leg("a"), _leg("b", deps=("a",)), _leg("c", deps=("b",)),
            _leg("d")]
    order = sir._topo_order(legs)
    hb = dataflow.HappensBefore(legs, order)
    assert hb.reaches("a", "c") and hb.reaches("a", "b")
    assert not hb.reaches("c", "a")
    assert hb.ordered("a", "c") and not hb.ordered("a", "d")
    assert not hb.reaches("a", "a")


def _brute_force_reach(legs):
    adj = {l.id: [] for l in legs}
    for l in legs:
        for d in l.deps:
            if d in adj:
                adj[d].append(l.id)
    reach = {}
    for src in adj:
        seen, stack = set(), [src]
        while stack:
            for nxt in adj[stack.pop()]:
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        reach[src] = seen
    return reach


def _oracle_races(ir):
    """Brute-force mirror of the detector's race semantics: the multiset
    of (rule, leg, location) it must report."""
    legs = list(ir.legs)
    reach = _brute_force_reach(legs)

    def ordered(a, b):
        return b in reach[a] or a in reach[b]

    readers, writers = {}, {}
    for l in legs:
        for b in l.reads:
            readers.setdefault(b, []).append(l)
        for b in l.writes:
            writers.setdefault(b, []).append(l)
    out = []
    for buf in writers:
        for a, b in combinations(writers[buf], 2):
            if a.id != b.id and not ordered(a.id, b.id):
                out.append((sir.RULE_RACE_WRITE, min(a.id, b.id), buf))
        for w in writers[buf]:
            for r in readers.get(buf, ()):
                if r.id == w.id or buf in r.writes:
                    continue
                if not ordered(w.id, r.id):
                    out.append((sir.RULE_RACE_READ_WRITE, r.id, buf))
    return sorted(out)


def _detector_races(ir):
    return sorted(
        (v.rule, v.leg, v.location) for v in sir.verify(ir)
        if v.rule in (sir.RULE_RACE_WRITE, sir.RULE_RACE_READ_WRITE))


# -- mutation goldens ---------------------------------------------------------

def test_planner_schedules_have_zero_race_findings():
    ir = _ir(_entries(), d=8, accum=4, guard=True)
    assert not _detector_races(ir)
    assert not [v for v in sir.verify(ir)
                if v.rule == sir.RULE_BUFFER_LEAK]


def test_mutation_planted_unordered_write():
    ir = _ir(_entries(n=2))
    buf = f"red:{ir.buckets[0]['key']}"
    legs = list(ir.legs) + [_leg("rogue-writer", writes=(buf,))]
    bad = _with_legs(ir, legs)
    errs = _errors(bad)
    assert sir.RULE_RACE_WRITE in _rules(errs)
    assert any(v.location == buf for v in errs
               if v.rule == sir.RULE_RACE_WRITE)


def test_mutation_planted_read_write_race():
    ir = _ir(_entries(n=2))
    buf = f"red:{ir.buckets[0]['key']}"
    legs = list(ir.legs) + [_leg("rogue-reader", reads=(buf,))]
    bad = _with_legs(ir, legs)
    errs = _errors(bad)
    assert sir.RULE_RACE_READ_WRITE in _rules(errs)
    assert sir.RULE_RACE_WRITE not in _rules(errs)
    assert any(v.leg == "rogue-reader" for v in errs)


def test_mutation_planted_buffer_leak():
    ir = _ir(_entries(n=1, shape=(8, 8)))
    # drop every reader of the reduced gradient: the reduce is dead work
    buf = f"red:{ir.buckets[0]['key']}"
    legs = [l for l in ir.legs if buf not in l.reads]
    bad = _with_legs(ir, legs)
    leaks = [v for v in sir.verify(bad) if v.rule == sir.RULE_BUFFER_LEAK]
    assert leaks and all(v.severity == sir.SEV_WARN for v in leaks)
    assert any(v.location == buf for v in leaks)


def test_param_and_opt_outputs_are_not_leaks():
    """param:/opt: step outputs are written and never read — by design,
    not a leak."""
    ir = _ir(_entries(n=2), d=8)
    assert not [v for v in sir.verify(ir)
                if v.rule == sir.RULE_BUFFER_LEAK]
    assert any("param:" in b for l in ir.legs for b in l.writes)


def test_read_after_donate_covers_param_and_opt_namespaces():
    ir = _ir(_entries(n=2), d=8)
    key = next(b["key"] for b in ir.buckets
               if b["mode"] == "reduce_scatter")
    for buf in (f"param:{key}", f"opt:{key}"):
        clone = sir.ScheduleIR.from_dict(ir.to_dict())
        clone.donated = (buf,)
        writer = max((l for l in clone.legs if buf in l.writes),
                     key=lambda l: len(l.deps))
        clone.legs = list(clone.legs) + [
            _leg("late-inspect", deps=(writer.id,), reads=(buf,))]
        assert sir.RULE_READ_AFTER_DONATE in _rules(_errors(clone)), buf


# -- fuzz: delete dep edges, compare against the brute-force oracle ----------

_FUZZ_COMPRESSORS = ("NoneCompressor", "HorovodCompressorEF",
                     "Int8Compressor")


def test_fuzz_dep_edge_deletion_matches_oracle():
    """Randomly delete dep edges from planner-emitted IRs — the expert
    axis included (MoE dispatch/combine a2a pairs, multi-layer and
    multi-slot): the race detector must report EXACTLY the conflicting
    pairs whose ordering the deletion broke (brute-force oracle) —
    every mutation the runtime lowering would miscompile is caught, and
    nothing else."""
    rng = np.random.RandomState(20260805)
    caught = 0
    for trial in range(60):
        entries = []
        for i in range(int(rng.randint(1, 5))):
            entries.append(
                (f"v{i}", (int(rng.choice([64, 256])), 64), "float32",
                 str(rng.choice(_FUZZ_COMPRESSORS)), 0,
                 str(rng.choice(["all_reduce", "reduce_scatter"]))))
        expert_ax = int(rng.choice([1, 2, 4]))
        moe = tuple(
            sir.MoEFact(key=f"layers_{j}/moe", groups=2,
                        seq=int(rng.choice([256, 1024])), d_model=64,
                        num_experts=int(rng.choice([4, 8])),
                        capacity_factor=2.0,
                        compressor=str(rng.choice(
                            ["NoneCompressor", "Int8Compressor"])))
            for j in range(int(rng.randint(0, 3))))
        ir = _ir(entries,
                 bucket_bytes=int(rng.choice([16 << 10, 256 << 10])),
                 d=int(rng.choice([2, 4, 8])),
                 accum=int(rng.choice([1, 3])),
                 mode=str(rng.choice(list(overlap.OVERLAP_MODES))),
                 guard=bool(rng.randint(0, 2)),
                 moe=moe, expert_ax=expert_ax)
        legs = list(ir.legs)
        assert _detector_races(ir) == []        # clean before mutation
        for _ in range(int(rng.randint(1, 4))):
            with_deps = [i for i, l in enumerate(legs) if l.deps]
            if not with_deps:
                break
            i = int(rng.choice(with_deps))
            deps = list(legs[i].deps)
            deps.pop(int(rng.randint(len(deps))))
            legs[i] = dataclasses.replace(legs[i], deps=tuple(deps))
        mutated = _with_legs(ir, legs)
        expected = _oracle_races(mutated)
        assert _detector_races(mutated) == expected, trial
        caught += bool(expected)
    # the axis must actually exercise the detector, not only clean runs
    assert caught >= 10


def test_fused_and_quantized_schedules_race_clean():
    """Zero false positives on the PR 11 fused-kernel legs and the PR 8
    quantized per-hop chains."""
    entries = (_entries(n=2, comp="Int8Compressor", mode="all_reduce",
                        prefix="q")
               + _entries(n=2, mode="reduce_scatter", prefix="z"))
    buckets = bucketing.assign_buckets(entries, bucket_bytes=256 << 10,
                                       shard_divisor=8)
    for fused in ((), ("guard",), ("guard", "update", "quant_hop")):
        ir = _ir(entries, d=8, accum=4, mode="full", guard=True,
                 donated=tuple(f"sync:{b.key}" for b in buckets
                               if b.compressor == "Int8Compressor"),
                 stateful_keys=[b.key for b in buckets
                                if b.compressor == "Int8Compressor"],
                 fused_kernels=fused)
        if fused:
            assert any(l.kind in (sir.LEG_FUSED_DETECT,
                                  sir.LEG_FUSED_UPDATE,
                                  sir.LEG_FUSED_HOP) for l in ir.legs)
        errs = _errors(ir)
        assert not errs, (fused, [str(v) for v in errs])
        assert not [v for v in sir.verify(ir)
                    if v.rule == sir.RULE_BUFFER_LEAK]


# -- deterministic diagnostics ordering ---------------------------------------

def test_verify_output_is_sorted_and_stable():
    ir = _ir(_entries(n=2))
    buf = f"red:{ir.buckets[0]['key']}"
    legs = list(ir.legs) + [_leg("rogue-writer", writes=(buf,)),
                            _leg("rogue-reader", reads=(buf,))]
    bad = _with_legs(ir, legs)
    first = [(v.rule, v.leg, v.location, v.message)
             for v in sir.verify(bad)]
    again = [(v.rule, v.leg, v.location, v.message)
             for v in sir.verify(sir.ScheduleIR.from_dict(bad.to_dict()))]
    assert len(first) > 2
    assert first == again
    assert first == sorted(first)


def test_analyze_output_is_stable_across_runs():
    gi = GraphItem({"a": jnp.zeros((64, 64)), "b": jnp.zeros((64, 64))},
                   optimizer=optax.adam(1e-3))
    s = Strategy(node_config=[ar_node("a"), ar_node("b")])
    t1 = analyze(s, gi, mesh=AXES8, budget_bytes=1024).format_table()
    t2 = analyze(s, gi, mesh=AXES8, budget_bytes=1024).format_table()
    assert t1 == t2


# -- the liveness watermark ---------------------------------------------------

def test_watermark_opens_at_write_closes_at_last_read():
    legs = [
        _leg("r1", kind=sir.LEG_ALL_REDUCE, nbytes=10,
             reads=("grad:A",), writes=("red:A",)),
        _leg("u1", deps=("r1",), nbytes=10, reads=("red:A",)),
        _leg("r2", kind=sir.LEG_ALL_REDUCE, deps=("u1",), nbytes=200,
             reads=("grad:B",), writes=("red:B",)),
        _leg("u2", deps=("r2",), nbytes=200, reads=("red:B",)),
    ]
    ir = sir.ScheduleIR(axes={"data": 2}, legs=legs)
    wm = dataflow.watermark(ir)
    # gradients are step inputs (live from t=0); red:A opens at its
    # write (r1) and closes at its last read (u1), so the peak is at
    # r2: grad:B (input) + red:B, with A's buffers all closed.
    assert wm.peak_bytes == 400
    assert wm.peak_leg == "r2"
    assert wm.per_slot[sir.END_OF_STEP] == 400
    # ... and at r1 the A buffers plus the not-yet-consumed grad:B
    # input are live: 10 + 10 + 200 = 220 < 400 (no false peak).
    assert wm.buffer_bytes["grad:B"] == 200


def test_watermark_donation_closes_early():
    def legs():
        return [
            _leg("r1", kind=sir.LEG_ALL_REDUCE, nbytes=10,
                 reads=("grad:A", "sync:A"), writes=("red:A", "sync:A")),
            _leg("u1", deps=("r1",), nbytes=10, reads=("red:A",)),
            _leg("r2", kind=sir.LEG_ALL_REDUCE, deps=("u1",), nbytes=1000,
                 reads=("grad:B",), writes=("red:B",)),
            _leg("u2", deps=("r2",), nbytes=1000, reads=("red:B",)),
        ]
    plain = sir.ScheduleIR(axes={"data": 2}, legs=legs())
    gifted = sir.ScheduleIR(axes={"data": 2}, legs=legs(),
                            donated=("sync:A",))
    wm_plain = dataflow.watermark(plain)
    wm_gifted = dataflow.watermark(gifted)
    # non-donated sync state stays resident to step end (the next step
    # reads it): peak at r2 = sync:A + grad:B + red:B = 2010; donation
    # aliases it away after its last access (r1), so the peak drops.
    assert wm_plain.peak_bytes == 2010 and wm_plain.peak_leg == "r2"
    assert wm_gifted.peak_bytes == 2000
    assert wm_gifted.peak_bytes < wm_plain.peak_bytes


def test_watermark_base_and_pipelined_slots():
    ir = _ir(_entries(), d=8, accum=4)
    wm = dataflow.watermark(ir, base_bytes=1000)
    assert wm.base_bytes == 1000
    assert wm.peak_bytes > 1000
    assert set(wm.per_slot) >= {0, 1, 2, 3}
    d = wm.to_dict()
    assert d["peak_bytes"] == wm.peak_bytes
    assert d["per_slot"] and d["top_buffers"]


def test_watermark_zero1_red_shard_is_fractional():
    """ZeRO-1 reduce-scatter results are 1/d buffers; the all-reduce
    result is full size — the watermark sizes them differently."""
    rs = dataflow.watermark(_ir(_entries(n=1), d=8))
    ar = dataflow.watermark(_ir(_entries(n=1, mode="all_reduce"), d=8))
    key_rs = next(b for b in rs.buffer_bytes if b.startswith("red:"))
    key_ar = next(b for b in ar.buffer_bytes if b.startswith("red:"))
    assert rs.buffer_bytes[key_rs] * 8 == ar.buffer_bytes[key_ar]


def test_watermark_none_on_cyclic_graph():
    legs = [_leg("a", deps=("b",)), _leg("b", deps=("a",))]
    ir = sir.ScheduleIR(axes={"data": 2}, legs=legs)
    assert dataflow.watermark(ir) is None


# -- memory pass / search / tuner / elastic wiring ----------------------------

def _big_gi():
    return GraphItem({"w": jnp.zeros((1024, 1024), jnp.float32)},
                     optimizer=optax.adam(1e-3))


def test_watermark_catches_oom_the_coarse_sum_admitted():
    """THE planted acceptance fixture: params 4 MiB + grads 4 MiB +
    Adam moments 8 MiB = 16 MiB coarse sum fits a 17.5 MiB budget, but
    the schedule's liveness (gradient AND reduce buffer live at the
    reduce leg) peaks at 20 MiB — only the watermark rejects it."""
    gi = _big_gi()
    s = Strategy(node_config=[ar_node("w")])
    budget = int(17.5 * _MiB)
    report = analyze(s, gi, mesh=AXES8, budget_bytes=budget)
    # the coarse sum admitted it...
    msg = report.by_rule("memory/hbm-breakdown")[0].message
    coarse = float(msg.split("≈")[1].split("MiB")[0]) * _MiB
    assert coarse < budget
    # ...the watermark rejects it.
    assert [d.rule for d in report.errors] \
        == ["memory/watermark-exceeds-hbm"]


def test_search_prunes_watermark_oom_before_pricing():
    from autodist_tpu.strategy.search import (
        SYNC_AR,
        VarGene,
        evaluate_candidate,
    )

    gi = _big_gi()
    genes = (("w", VarGene(sync=SYNC_AR)),)

    def spec(hbm_gb):
        return ResourceSpec(resource_info={
            "nodes": [{"address": "localhost", "chips": 8}],
            "hbm_gb": hbm_gb})

    # fact base 12 MiB + grad 4 + red 4 = 20 MiB > 17.5 MiB: pruned
    # BEFORE pricing, with the watermark rule in the verdict.
    ev, strat = evaluate_candidate(
        "planted", genes, gi, spec(17.5 / 1024.0), {"data": 8})
    assert strat is None and ev.cost_s is None
    assert "memory/watermark-exceeds-hbm" in ev.pruned_by
    # a generous budget admits and prices the same candidate.
    ev2, strat2 = evaluate_candidate(
        "planted", genes, gi, spec(16.0), {"data": 8})
    assert ev2.pruned_by is None and ev2.cost_s is not None


def test_beam_search_routes_around_oom_candidates():
    """With a budget only sharded-state schedules fit, the search still
    returns a winner — and it is NOT a replicated-moment AR plan."""
    from autodist_tpu.strategy.search import SearchSpace, beam_search

    gi = _big_gi()
    spec = ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": 8}],
        "hbm_gb": 17.5 / 1024.0})
    result = beam_search(
        gi, spec, space=SearchSpace(max_rounds=1, max_evals=40,
                                    wall_budget_s=15.0))
    assert result.best is not None
    assert any("memory/watermark-exceeds-hbm" in (e.pruned_by or "")
               for e in result.pruned)
    (_, gene), = result.best.genes
    assert not (gene.sync == "ar")


def test_tuner_watermark_veto():
    from autodist_tpu.strategy.tuner import ScheduleTuner

    gi = _big_gi()
    strat = Strategy(node_config=[ar_node("w")])
    tiny = ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": 8}],
        "hbm_gb": 17.5 / 1024.0})
    why = ScheduleTuner(gi, tiny).watermark_veto(strat, {"data": 8})
    assert why is not None and "watermark" in why
    roomy = ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": 8}],
        "hbm_gb": 16.0})
    assert ScheduleTuner(gi, roomy).watermark_veto(
        strat, {"data": 8}) is None


def test_elastic_preflight_runs_watermark_on_resized_mesh():
    """The --elastic-from / preflight_elastic path: the watermark is
    re-simulated on the NEW mesh, where the shrunken data axis holds a
    larger optimizer slice — an OOM resume is rejected statically."""
    gi = _big_gi()
    s = Strategy(node_config=[ar_node("w")])
    tiny = ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "chips": 2}],
        "hbm_gb": 17.5 / 1024.0})
    report = analyze(s, gi, mesh={"data": 2}, resource_spec=tiny,
                     elastic={"from_axes": {"data": 8}})
    assert any(d.rule == "memory/watermark-exceeds-hbm"
               for d in report.errors)
    assert report.by_rule("memory/watermark")


# -- budget -------------------------------------------------------------------

def test_race_detector_and_watermark_hold_verifier_budget():
    """verify() now includes the happens-before closure + race sweep;
    together with the watermark it must stay under the 1 s pre-trace
    budget on the transformer-scale (9k-leg) fixture."""
    entries = [(f"blk{i}/w", (512, 512), "float32", "NoneCompressor",
                0, "reduce_scatter") for i in range(256)]
    ir = _ir(entries, bucket_bytes=1 << 20, d=8, accum=4, guard=True)
    assert len(ir.legs) > 9_000
    t0 = time.perf_counter()
    violations = sir.verify(ir)
    wm = dataflow.watermark(ir)
    dt = time.perf_counter() - t0
    assert not [v for v in violations if v.severity == sir.SEV_ERROR]
    assert wm is not None and wm.peak_bytes > 0
    assert dt < 1.0, f"verify+watermark took {dt:.2f}s on {len(ir.legs)} legs"


# -- CLI end-to-end smoke (tier-1) -------------------------------------------

def test_cli_watermark_dump_ir_end_to_end():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "autodist_tpu.analysis", "mlp", "Zero1",
         "--mesh", "data=8", "--watermark", "--dump-ir", "json"],
        capture_output=True, text=True, timeout=240, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = json.loads(proc.stdout)
    assert payload["schedule_ir"]["legs"]
    wm = payload["watermark"]
    assert wm["peak_bytes"] > 0 and wm["peak_leg"] and wm["per_slot"]


def test_cli_watermark_budget_exit_code(capsys):
    from autodist_tpu.analysis.__main__ import main

    rc = main(["mlp", "Zero1", "--mesh", "data=8", "--watermark",
               "--budget-gb", "0.000001"])
    assert rc == 1
    out = capsys.readouterr().out
    assert "EXCEEDED" in out
    rc = main(["mlp", "Zero1", "--mesh", "data=8", "--watermark",
               "--json"])
    assert rc == 0
