"""LoRA finetuning: adapter init/merge math, zero-start equivalence,
frozen-base training through the session, strategy composition, and
merge-for-serving."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.models.lora import lora_init, lora_merge, lora_setup
from autodist_tpu.strategy import AllReduce, PSLoadBalancing


def _toy_params(rng):
    return {"enc": {"w1": jnp.asarray(rng.randn(6, 8), jnp.float32),
                    "b1": jnp.zeros((8,))},
            "head": {"w2": jnp.asarray(rng.randn(8, 3), jnp.float32)}}


def _toy_loss(p, b):
    h = jnp.tanh(b["x"] @ p["enc"]["w1"] + p["enc"]["b1"])
    return jnp.mean((h @ p["head"]["w2"] - b["y"]) ** 2)


def test_init_targets_and_validation():
    rng = np.random.RandomState(0)
    params = _toy_params(rng)
    ad = lora_init(jax.random.PRNGKey(0), params, rank=4)
    assert set(ad) == {"enc.w1", "head.w2"}          # 2-D leaves only
    assert ad["enc.w1"]["a"].shape == (6, 4)
    assert ad["enc.w1"]["b"].shape == (4, 3 - 3 + 8)  # (rank, out)
    ad2 = lora_init(jax.random.PRNGKey(0), params, rank=2,
                    targets=("head",))
    assert set(ad2) == {"head.w2"}
    with pytest.raises(ValueError, match="2 dims"):
        lora_init(jax.random.PRNGKey(0), params, rank=2,
                  targets=("enc/b1",))
    with pytest.raises(ValueError, match="matched"):
        lora_init(jax.random.PRNGKey(0), params, rank=2,
                  targets=("nope",))
    with pytest.raises(ValueError, match="rank"):
        lora_init(jax.random.PRNGKey(0), params, rank=0)


def test_zero_start_and_merge_math():
    rng = np.random.RandomState(1)
    params = _toy_params(rng)
    adapters = lora_init(jax.random.PRNGKey(1), params, rank=4)
    merged = lora_merge(params, adapters, alpha=8.0, rank=4)
    # B starts at zero => merged == base exactly.
    for a, b in zip(jax.tree_util.tree_leaves(merged),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Non-zero B: closed-form delta on one leaf.
    adapters["enc.w1"]["b"] = jnp.ones((4, 8), jnp.float32)
    merged = lora_merge(params, adapters, alpha=8.0, rank=4)
    want = np.asarray(params["enc"]["w1"]) + 2.0 * np.asarray(
        adapters["enc.w1"]["a"] @ adapters["enc.w1"]["b"])
    np.testing.assert_allclose(np.asarray(merged["enc"]["w1"]), want,
                               rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(merged["head"]["w2"]),
                                  np.asarray(params["head"]["w2"]))


@pytest.mark.parametrize("builder", [AllReduce(), PSLoadBalancing()])
def test_lora_trains_and_base_stays_frozen(builder):
    _reset_default_autodist_for_testing()
    rng = np.random.RandomState(2)
    params = _toy_params(rng)
    batch = {"x": rng.randn(16, 6).astype(np.float32),
             "y": rng.randn(16, 3).astype(np.float32)}
    setup = lora_setup(params, _toy_loss, rng=jax.random.PRNGKey(2),
                       rank=4)
    ad = AutoDist(strategy_builder=builder)
    with ad.scope():
        ad.capture(**setup.capture_args, optimizer=optax.adamw(5e-2))
    sess = ad.create_distributed_session()
    losses = [float(sess.run(batch)["loss"]) for _ in range(20)]
    assert losses[-1] < losses[0] * 0.9, losses   # adapters learn
    after = sess.params
    for a, b in zip(jax.tree_util.tree_leaves(after["base"]),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # No optimizer state for the base tree (LoRA's memory claim):
    # derive the frozen shapes from the ACTUAL base tree, excluding any
    # that an adapter leaf could coincidentally share.
    base_shapes = {tuple(x.shape) for x in
                   jax.tree_util.tree_leaves(params)
                   if len(x.shape) == 2}
    opt_shapes = [tuple(x.shape) for x in
                  jax.tree_util.tree_leaves(sess.opt_state)
                  if hasattr(x, "shape") and len(getattr(x, "shape", ()))]
    for s in base_shapes:
        assert opt_shapes.count(s) == 0, (s, opt_shapes)
    # Merge-for-serving: merged loss equals the session's training loss
    # view at the current adapters.
    merged = setup.merge(after)
    got = float(_toy_loss(merged, batch))
    want = float(setup.capture_args["loss_fn"](after, batch))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_lora_on_transformer_lm_decodes():
    """End-to-end on the LM family: finetune adapters on the attention
    and MLP kernels, merge, and decode with the plain generator."""
    from autodist_tpu.models.generate import make_generator
    from autodist_tpu.models.transformer import dense_attention
    from autodist_tpu.models.transformer_lm import transformer_lm

    _reset_default_autodist_for_testing()
    spec = transformer_lm(vocab_size=61, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=32, max_len=32, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    setup = lora_setup(params, spec.loss_fn, rng=jax.random.PRNGKey(3),
                       rank=2, targets=[("*/attn/out/*", 2),
                                        "*/attn/*", "*/mlp/*"])
    assert setup.num_adapter_params < sum(
        x.size for x in jax.tree_util.tree_leaves(params)) * 0.2
    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(**setup.capture_args, optimizer=optax.adam(1e-2))
    sess = ad.create_distributed_session()
    batch = spec.sample_batch(8)
    l0 = float(sess.run(batch)["loss"])
    for _ in range(10):
        out = sess.run(batch)
    assert float(out["loss"]) < l0
    merged = setup.merge(sess.params)
    gen = make_generator(spec)
    toks = np.asarray(gen(merged, np.zeros((1, 2), np.int32), 4))
    assert toks.shape == (1, 6)
