"""Chunked-vocab cross entropy vs the dense reference loss."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.models.base import cross_entropy_loss
from autodist_tpu.ops.chunked_xent import chunked_softmax_cross_entropy


def _data(n=24, e=16, v=512, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    h = jnp.asarray(rng.randn(n, e) * 0.5, dtype)
    w = jnp.asarray(rng.randn(v, e) * 0.5, dtype)
    y = jnp.asarray(rng.randint(0, v, (n,)), jnp.int32)
    return h, w, y


def _dense_loss(h, w, y):
    return cross_entropy_loss(jnp.einsum("ne,ve->nv", h, w), y)


@pytest.mark.parametrize("chunk", [64, 128, 512])
def test_forward_matches_dense(chunk):
    h, w, y = _data()
    dense = _dense_loss(h, w, y)
    chunked = chunked_softmax_cross_entropy(h, w, y, chunk=chunk)
    np.testing.assert_allclose(chunked, dense, rtol=1e-6)


def test_gradients_match_dense():
    h, w, y = _data()
    gd_h, gd_w = jax.grad(_dense_loss, argnums=(0, 1))(h, w, y)
    gc_h, gc_w = jax.grad(
        lambda h, w: chunked_softmax_cross_entropy(h, w, y, chunk=128),
        argnums=(0, 1))(h, w)
    np.testing.assert_allclose(gc_h, gd_h, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(gc_w, gd_w, rtol=1e-5, atol=1e-7)


def test_bf16_features_fp32_accumulation():
    h, w, y = _data(dtype=jnp.bfloat16)
    dense = _dense_loss(h.astype(jnp.float32), w.astype(jnp.float32), y)
    chunked = chunked_softmax_cross_entropy(h, w, y, chunk=128)
    np.testing.assert_allclose(float(chunked), float(dense), rtol=2e-2)
    g = jax.grad(lambda h, w: chunked_softmax_cross_entropy(
        h, w, y, chunk=128), argnums=(0, 1))(h, w)
    assert g[0].dtype == jnp.bfloat16 and g[1].dtype == jnp.bfloat16


def test_leading_shape_flattens():
    h, w, y = _data(n=24)
    hb = h.reshape(4, 6, -1)
    yb = y.reshape(4, 6)
    np.testing.assert_allclose(
        chunked_softmax_cross_entropy(hb, w, yb, chunk=128),
        chunked_softmax_cross_entropy(h, w, y, chunk=128), rtol=1e-7)


def test_indivisible_vocab_pads_and_masks():
    """V=500 with chunk=128 pads the table to 512; pad columns carry
    exactly zero probability and the result matches dense — including
    gradients (the pad rows of dW are sliced away by the pad's VJP)."""
    h, w, y = _data(v=500)
    np.testing.assert_allclose(
        chunked_softmax_cross_entropy(h, w, y, chunk=128),
        _dense_loss(h, w, y), rtol=1e-6)
    gd = jax.grad(_dense_loss, argnums=(0, 1))(h, w, y)
    gc = jax.grad(lambda h, w: chunked_softmax_cross_entropy(
        h, w, y, chunk=128), argnums=(0, 1))(h, w)
    np.testing.assert_allclose(gc[0], gd[0], rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(gc[1], gd[1], rtol=1e-5, atol=1e-7)
    assert gc[1].shape == w.shape


def test_lm1b_default_vocab_is_chunkable():
    """The lm1b default vocab (793472 = 2^7 * 6199) has no large
    power-of-two divisor; the op must handle it via padding, not demand
    divisibility (which only chunk<=128 could satisfy)."""
    h, w, y = _data(n=8, e=4, v=6199)   # 793472 = 128 * 6199
    assert (793472 % 8192) != 0         # the trap this guards
    loss = chunked_softmax_cross_entropy(h, w, y, chunk=512)
    np.testing.assert_allclose(loss, _dense_loss(h, w, y), rtol=1e-6)


def test_chunk_capped_at_vocab():
    h, w, y = _data(v=256)
    np.testing.assert_allclose(
        chunked_softmax_cross_entropy(h, w, y, chunk=8192),
        _dense_loss(h, w, y), rtol=1e-6)


def test_compiled_avoids_full_logits():
    """The point: peak temp memory must not contain an [N, V] logits
    buffer.  Compare compiled temp bytes for a vocab where dense logits
    would dominate (N=128, V=32768 -> 16.8 MB fp32 logits)."""
    h, w, y = _data(n=128, e=32, v=32768)

    dense = jax.jit(jax.grad(_dense_loss, argnums=(0, 1)))
    chunked = jax.jit(jax.grad(
        lambda h, w, y: chunked_softmax_cross_entropy(h, w, y, chunk=1024),
        argnums=(0, 1)))
    db = dense.lower(h, w, y).compile().memory_analysis().temp_size_in_bytes
    cb = chunked.lower(h, w, y).compile().memory_analysis().temp_size_in_bytes
    assert cb < db / 4, (cb, db)
