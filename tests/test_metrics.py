"""Session throughput/MFU instrumentation (utils/metrics.py).

The reference measured throughput only in example scripts
(``examples/benchmark/imagenet.py:85-120`` TimeHistory); here it is a
DistributedSession feature, plus MFU from XLA cost analysis."""
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.strategy import AllReduce
from autodist_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


def _session():
    params = {"w": jnp.zeros((8, 4))}

    def loss(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    ad = AutoDist(strategy_builder=AllReduce())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1), loss_fn=loss)
    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(16, 8).astype(np.float32),
             "y": rng.randn(16, 4).astype(np.float32)}
    return ad.create_distributed_session(), batch


def test_throughput_meter_window():
    m = metrics.ThroughputMeter(window=4)
    assert m.step_time() is None
    import time

    for _ in range(6):
        m.tick()
        time.sleep(0.001)
    assert m.steps_recorded == 4  # window-bounded
    st = m.step_time()
    assert st is not None and st > 0
    s = m.stats(items_per_step=32)
    assert s["steps_per_sec"] > 0 and s["items_per_sec"] > 0


def test_session_throughput_and_flops():
    sess, batch = _session()
    assert sess.throughput()["step_time_ms"] is None  # no steps yet
    for _ in range(4):
        sess.run(batch)
    t = sess.throughput(items_per_step=16)
    assert t["steps_measured"] == 3
    assert t["step_time_ms"] > 0 and t["items_per_sec"] > 0
    flops = sess.flops_per_step()
    assert flops is None or flops > 0
    assert sess.flops_per_step() is flops  # cached


def test_session_mfu_none_on_cpu():
    sess, batch = _session()
    for _ in range(3):
        sess.run(batch)
    # CPU has no known peak -> None (on TPU this returns a fraction).
    assert sess.mfu() is None


def test_peak_flops_table():
    class FakeDev:
        device_kind = "TPU v5 lite"

    assert metrics.peak_flops_per_chip(FakeDev()) == 197e12
    # 19.7 TFLOP in 1 s on a 197-TFLOP/s chip = 10% MFU.
    assert metrics.mfu(19.7e12, 1.0, [FakeDev()]) == pytest.approx(0.1)
    # two chips halve it
    assert metrics.mfu(19.7e12, 1.0,
                       [FakeDev(), FakeDev()]) == pytest.approx(0.05)
