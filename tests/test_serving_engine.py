"""Continuous-batching DecodeEngine vs per-request `generate` (oracle).

The engine's claim is token-exactness: slot-based continuous batching
with a uniform cache tick and per-slot offset masks must reproduce the
single-request KV-cache decode bit-for-bit (greedy).  Plus scheduler
behavior: slot reuse, early-eos harvest, ring wrap, utilization
accounting, and validation errors.
"""
import jax
import numpy as np
import pytest

from autodist_tpu.models.generate import make_generator
from autodist_tpu.models.transformer import dense_attention
from autodist_tpu.models.transformer_lm import transformer_lm
from autodist_tpu.serving import DecodeEngine

VOCAB = 61


@pytest.fixture(scope="module")
def lm():
    spec = transformer_lm(vocab_size=VOCAB, num_layers=2, num_heads=2,
                          head_dim=8, d_ff=32, max_len=48, seq_len=16,
                          attn_fn=dense_attention)
    params = spec.init(jax.random.PRNGKey(0))
    return spec, params


def _oracle(spec, params, prompt, n, eos_id=None):
    gen = make_generator(spec)
    out = gen(params, prompt[None, :], n, eos_id=eos_id)
    return np.asarray(out)[0]


@pytest.mark.slow
@pytest.mark.parametrize("prefill", [False, True])
def test_engine_matches_generate_exactly(lm, prefill):
    """Varied prompt/output lengths across fewer slots than requests:
    every harvested sequence equals the per-request oracle decode —
    with sequential admission and with parallel prefill."""
    spec, params = lm
    rng = np.random.RandomState(1)
    reqs = [(rng.randint(0, VOCAB, p).astype(np.int32), n)
            for p, n in [(3, 5), (1, 9), (6, 2), (4, 7), (2, 4), (5, 6)]]
    eng = DecodeEngine(spec, params, slots=2, window=24, chunk=4,
                       prefill=prefill)
    ids = [eng.submit(p, n) for p, n in reqs]
    results = eng.run()
    assert sorted(results) == sorted(ids)
    for rid, (prompt, n) in zip(ids, reqs):
        want = _oracle(spec, params, prompt, n)
        np.testing.assert_array_equal(
            results[rid], want,
            err_msg=f"request {rid} (P={prompt.size}, N={n})")
    assert eng.stats.completed == len(reqs)
    # 6 requests through 2 slots: slots were reused.
    assert eng.stats.completed > 2
    assert 0 < eng.stats.slot_utilization <= 1.0
    assert eng.stats.generated_tokens == sum(n for _, n in reqs)
    if prefill:
        # later admissions happen mid-window, behind the tick
        assert eng.stats.prefill_admissions > 0
        assert eng.stats.prefilled_tokens > 0
    else:
        assert eng.stats.prefill_admissions == 0


@pytest.mark.slow
def test_engine_ring_wraps_without_reset(lm):
    """Requests whose spans exceed the remaining window admit anyway —
    the ring wraps each slot's writes mod window (the pre-ring design
    drained the whole pool and rewound the tick here).  Results must
    still be exact (slot/cache ring reuse without zeroing)."""
    spec, params = lm
    rng = np.random.RandomState(2)
    # window 16, spans 13: the ring wraps multiple times over 5 requests
    reqs = [(rng.randint(0, VOCAB, 6).astype(np.int32), 7)
            for _ in range(5)]
    eng = DecodeEngine(spec, params, slots=2, window=16, chunk=5)
    ids = [eng.submit(p, n) for p, n in reqs]
    results = eng.run()
    for rid, (prompt, n) in zip(ids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(spec, params, prompt, n))
    # Both slots decode concurrently throughout (no drain stalls): with
    # 5 requests x 6 busy ticks on 2 slots the odd request runs solo at
    # the tail and chunk quantization pads a little, so the ceiling is
    # ~0.75; the old drain-and-rewind design degraded to ~0.5 here.
    assert eng.stats.slot_utilization > 0.6


@pytest.mark.slow
def test_engine_tick_rebase_under_sustained_load(lm):
    """The absolute tick rebases by a multiple of window mid-stream
    (guarding int32 growth under sustained load) without disturbing
    results: ring positions and offset math are invariant under shifts
    that are 0 mod window."""
    spec, params = lm
    rng = np.random.RandomState(11)
    eng = DecodeEngine(spec, params, slots=2, window=16, chunk=4)
    eng._REBASE_AT = 24            # force rebases every few requests
    reqs = [(rng.randint(0, VOCAB, 3).astype(np.int32), 6)
            for _ in range(16)]
    ids, results = [], {}
    max_tick = 0
    for p, n in reqs:              # steady stream: pool never idles
        ids.append(eng.submit(p, n))
        eng.step()
        max_tick = max(max_tick, eng._tick)
        results.update(eng.results())
    while eng.step():
        max_tick = max(max_tick, eng._tick)
    results.update(eng.results())
    # Mid-stream (never at the drained rewind, which zeroes _tick
    # unconditionally): total ticks executed far exceed the rebase
    # threshold, yet the ABSOLUTE tick stayed clamped to
    # < REBASE_AT + window + chunk — the rebase fired.  Without
    # _rebase_tick, max_tick tracks stats.ticks and busts the bound.
    bound = 24 + eng._window + eng._chunk          # 44
    assert eng.stats.ticks > bound + 16
    assert max_tick < bound
    for rid, (p, n) in zip(ids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(spec, params, p, n))


@pytest.mark.slow
def test_engine_no_head_of_line_blocking(lm):
    """One long request must not stall the pool: short requests keep
    cycling through the other slot while it runs, so total engine ticks
    stay near the LONG request's span even though total decoded work is
    several times that (the round-4 drain-and-reset design serialized
    here once the tick outgrew the window)."""
    spec, params = lm
    rng = np.random.RandomState(7)
    long_p = rng.randint(0, VOCAB, 4).astype(np.int32)
    long_n = 40                       # span 44 of a 48 window
    shorts = [(rng.randint(0, VOCAB, 3).astype(np.int32), 7)
              for _ in range(6)]      # 6 x span 10 on the other slot
    eng = DecodeEngine(spec, params, slots=2, window=48, chunk=4)
    rid_long = eng.submit(long_p, long_n)
    rid_shorts = [eng.submit(p, n) for p, n in shorts]
    results = eng.run()
    np.testing.assert_array_equal(
        results[rid_long], _oracle(spec, params, long_p, long_n))
    for rid, (p, n) in zip(rid_shorts, shorts):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(spec, params, p, n))
    # All six shorts (60 slot-ticks of work) rode alongside the long
    # request: total ticks ~ long span, nowhere near the serialized sum.
    assert eng.stats.ticks <= long_n + 4 + 3 * 4


def test_engine_eos_early_stop(lm):
    """A generated eos truncates the result (eos kept) and frees the
    slot early; prompt-resident eos is data, not a stop."""
    spec, params = lm
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, VOCAB, 4).astype(np.int32)
    # Find the greedy continuation, then use its SECOND generated token
    # as the eos id so the engine must stop after two tokens.
    free = _oracle(spec, params, prompt, 6)
    eos = int(free[prompt.size + 1])
    if eos == free[prompt.size]:  # pragma: no cover - degenerate repeat
        pytest.skip("greedy repeats a token; eos choice ambiguous")
    eng = DecodeEngine(spec, params, slots=2, window=24, chunk=3,
                       eos_id=eos)
    # prompt containing the eos token must not stop the row
    prompt_with_eos = np.concatenate(
        [[np.int32(eos)], prompt]).astype(np.int32)
    r1 = eng.submit(prompt, 6)
    r2 = eng.submit(prompt_with_eos, 3)
    results = eng.run()
    want = _oracle(spec, params, prompt, 6, eos_id=eos)
    # oracle pads with eos after the stop; engine truncates after it
    np.testing.assert_array_equal(results[r1],
                                  want[:prompt.size + 2])
    assert results[r1][-1] == eos
    assert results[r2].size == prompt_with_eos.size + 3 or \
        results[r2][-1] == eos


def test_engine_interleaved_submit(lm):
    """step()/results(): submitting while decoding is in flight — the
    continuous-batching loop proper."""
    spec, params = lm
    rng = np.random.RandomState(4)
    p1 = rng.randint(0, VOCAB, 3).astype(np.int32)
    p2 = rng.randint(0, VOCAB, 2).astype(np.int32)
    eng = DecodeEngine(spec, params, slots=2, window=32, chunk=2)
    r1 = eng.submit(p1, 4)
    assert eng.step()            # starts decoding r1
    r2 = eng.submit(p2, 5)       # lands mid-flight
    while eng.step():
        pass
    results = eng.results()
    np.testing.assert_array_equal(results[r1], _oracle(spec, params, p1, 4))
    np.testing.assert_array_equal(results[r2], _oracle(spec, params, p2, 5))


def test_engine_partial_streaming(lm):
    """partial(): an in-flight request's tokens-so-far grow between
    chunks and are a prefix of the final result."""
    spec, params = lm
    rng = np.random.RandomState(9)
    prompt = rng.randint(0, VOCAB, 3).astype(np.int32)
    eng = DecodeEngine(spec, params, slots=1, window=32, chunk=2)
    rid = eng.submit(prompt, 8)
    assert eng.partial(rid) is None          # still queued
    snapshots = []
    while eng.step():
        part = eng.partial(rid)
        if part is not None:
            snapshots.append(part.copy())
    final = eng.results()[rid]
    assert eng.partial(rid) is None          # completed -> not partial
    assert len(snapshots) >= 2
    assert any(s.size < final.size for s in snapshots)
    for s in snapshots:
        np.testing.assert_array_equal(s, final[:s.size])


@pytest.mark.slow
def test_engine_mesh_sharded_slots(lm):
    """Multi-chip serving: the slot pool sharded over a mesh axis gives
    exactly the per-request oracle results, and the state buffers keep
    their shardings chunk to chunk (donation preserves placement)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    spec, params = lm
    devices = np.array(jax.devices()[:4])
    mesh = Mesh(devices, ("data",))
    rng = np.random.RandomState(12)
    reqs = [(rng.randint(0, VOCAB, p).astype(np.int32), n)
            for p, n in [(3, 5), (1, 7), (4, 4), (2, 6), (5, 3), (2, 8)]]
    eng = DecodeEngine(spec, params, slots=4, window=24, chunk=4,
                       mesh=mesh)
    ids = [eng.submit(p, n) for p, n in reqs]
    results = eng.run()
    for rid, (prompt, n) in zip(ids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(spec, params, prompt, n))
    # the slot axis stays sharded after many chunk/prefill programs —
    # both the caches and the token buffer (the latter is also mutated
    # by the host-driven prompt-write program)
    want = NamedSharding(mesh, PartitionSpec(None, None, "data"))
    assert eng._kc.sharding.is_equivalent_to(want, eng._kc.ndim)
    want_row = NamedSharding(mesh, PartitionSpec("data"))
    assert eng._tokens.sharding.is_equivalent_to(want_row,
                                                 eng._tokens.ndim)

    with pytest.raises(ValueError, match="must divide"):
        DecodeEngine(spec, params, slots=3, window=24, mesh=mesh)
    with pytest.raises(ValueError, match="not in mesh axes"):
        DecodeEngine(spec, params, slots=4, window=24, mesh=mesh,
                     slot_axis="model")


@pytest.mark.slow
def test_engine_tp_params_with_sharded_slots(lm):
    """The composition the docstring promises: model-axis (TP) sharded
    params AND a data-axis sharded slot pool on one 2-D mesh, token-
    exact vs host-layout per-request decode."""
    import optax

    from autodist_tpu.autodist import (AutoDist,
                                       _reset_default_autodist_for_testing)
    from autodist_tpu.strategy import Parallax

    spec, params = lm
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=Parallax(),
                  mesh_axes={"model": 2, "data": 4})
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.01),
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars)
    sess = ad.create_distributed_session()

    rng = np.random.RandomState(13)
    reqs = [(rng.randint(0, VOCAB, p).astype(np.int32), n)
            for p, n in [(3, 5), (2, 7), (4, 3), (1, 6)]]
    eng = DecodeEngine(spec, sess.sharded_params, slots=4, window=24,
                       chunk=4, mesh=sess.mesh, slot_axis="data")
    ids = [eng.submit(p, n) for p, n in reqs]
    results = eng.run()
    for rid, (prompt, n) in zip(ids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(spec, params, prompt, n))


def test_engine_cancel(lm):
    """cancel(): queued requests vanish; an in-flight request frees its
    slot for the next admission; completed/unknown ids return False."""
    spec, params = lm
    rng = np.random.RandomState(10)
    p1 = rng.randint(0, VOCAB, 3).astype(np.int32)
    p2 = rng.randint(0, VOCAB, 2).astype(np.int32)
    p3 = rng.randint(0, VOCAB, 2).astype(np.int32)
    eng = DecodeEngine(spec, params, slots=1, window=32, chunk=2)
    r1 = eng.submit(p1, 10)
    r2 = eng.submit(p2, 4)
    r3 = eng.submit(p3, 3)
    assert eng.cancel(r2)                    # still queued
    assert eng.step()                        # r1 now in flight
    assert eng.cancel(r1)                    # in flight -> freed
    results = eng.run()
    assert sorted(results) == [r3]           # only r3 completes
    np.testing.assert_array_equal(results[r3], _oracle(spec, params, p3, 3))
    assert not eng.cancel(r3)                # completed
    assert not eng.cancel(99)                # unknown


def test_engine_sampling_smoke(lm):
    """Temperature path: shapes/ranges sane (the key schedule differs
    from generate's, so no token parity is claimed)."""
    spec, params = lm
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, VOCAB, 3).astype(np.int32)
    eng = DecodeEngine(spec, params, slots=1, window=16, chunk=4,
                       temperature=0.8, top_k=10,
                       rng=jax.random.PRNGKey(7))
    rid = eng.submit(prompt, 5)
    (seq,) = eng.run().values()
    assert seq.shape == (8,)
    np.testing.assert_array_equal(seq[:3], prompt)
    assert np.all((seq >= 0) & (seq < VOCAB))
    del rid


@pytest.mark.slow
def test_engine_batched_prefill_single_dispatch(lm):
    """Two slots retiring at the same boundary admit their replacements
    through ONE batched prefill program (prefill_dispatches counts
    dispatches; prefill_admissions counts requests)."""
    spec, params = lm
    rng = np.random.RandomState(14)
    eng = DecodeEngine(spec, params, slots=2, window=32, chunk=16)
    # wave 1: identical spans -> both slots retire at the same tick
    wave1 = [(rng.randint(0, VOCAB, 3).astype(np.int32), 5)
             for _ in range(2)]
    # wave 2: admitted together at that boundary, behind the tick
    wave2 = [(rng.randint(0, VOCAB, 2).astype(np.int32), 4)
             for _ in range(2)]
    ids = [eng.submit(p, n) for p, n in wave1 + wave2]
    results = eng.run()
    for rid, (prompt, n) in zip(ids, wave1 + wave2):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(spec, params, prompt, n))
    # Ring admission prefills EVERY wave (wave 1 lands behind tick 0 at
    # wrapped ring positions): 4 admissions in exactly 2 batched
    # dispatches — one per boundary, never one per request.
    assert eng.stats.prefill_admissions == 4
    assert eng.stats.prefill_dispatches == 2


def test_engine_prefill_dedup_shared_prompt(lm):
    """Identical prompts admitted at one boundary (the n-samples-per-
    prompt / system-prompt fan-out case) compute their prefill ONCE:
    dedup hits recorded, greedy results still oracle-exact, and under
    temperature the slots draw independent samples."""
    spec, params = lm
    rng = np.random.RandomState(15)
    shared = rng.randint(0, VOCAB, 3).astype(np.int32)
    eng = DecodeEngine(spec, params, slots=2, window=32, chunk=16)
    # wave 1 occupies both slots to push the tick past the prompt size
    w1 = [(rng.randint(0, VOCAB, 3).astype(np.int32), 5)
          for _ in range(2)]
    ids1 = [eng.submit(p, n) for p, n in w1]
    # wave 2: the SAME prompt twice -> one prefill row, two slots
    ids2 = [eng.submit(shared, 4) for _ in range(2)]
    results = eng.run()
    for rid, (p, n) in zip(ids1, w1):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(spec, params, p, n))
    want = _oracle(spec, params, shared, 4)
    for rid in ids2:
        np.testing.assert_array_equal(results[rid], want)
    assert eng.stats.prefill_dedup_hits == 1
    # one dispatch per admission boundary (wave 1 + wave 2)
    assert eng.stats.prefill_dispatches == 2

    # temperature: shared prefill row, but per-slot independent draws
    eng2 = DecodeEngine(spec, params, slots=2, window=32, chunk=16,
                        temperature=1.0, rng=jax.random.PRNGKey(3))
    w1b = [(rng.randint(0, VOCAB, 3).astype(np.int32), 5)
           for _ in range(2)]
    for p, n in w1b:
        eng2.submit(p, n)
    ids2b = [eng2.submit(shared, 8) for _ in range(2)]
    res2 = eng2.run()
    a, bseq = res2[ids2b[0]], res2[ids2b[1]]
    assert eng2.stats.prefill_dedup_hits >= 1
    # overwhelmingly likely to differ somewhere over 8 sampled tokens
    assert not np.array_equal(a, bseq)


@pytest.mark.slow
def test_engine_prefill_single_token_requests(lm):
    """max_new_tokens=1 through the prefill path finishes a request AT
    admission — the scheduler must keep draining the queue without
    running idle chunks."""
    spec, params = lm
    rng = np.random.RandomState(7)
    # a longer opener so later admissions happen at tick >= P
    opener = rng.randint(0, VOCAB, 4).astype(np.int32)
    reqs = [(opener, 6)] + [
        (rng.randint(0, VOCAB, 3).astype(np.int32), 1) for _ in range(5)]
    eng = DecodeEngine(spec, params, slots=2, window=32, chunk=4)
    ids = [eng.submit(p, n) for p, n in reqs]
    results = eng.run()
    assert sorted(results) == sorted(ids)
    for rid, (prompt, n) in zip(ids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(spec, params, prompt, n))
    assert eng.stats.prefill_admissions >= 4


@pytest.mark.slow
def test_engine_with_session_sharded_params(lm):
    """The engine decodes straight off a session's mesh-sharded params
    (vocab-sharded embed under Parallax on a model-axis mesh), exactly
    matching host-layout results — continuous batching composes with the
    training shardings (GSPMD propagates through the chunk program)."""
    import optax

    from autodist_tpu.autodist import (AutoDist,
                                       _reset_default_autodist_for_testing)
    from autodist_tpu.strategy import Parallax

    spec, params = lm
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=Parallax(),
                  mesh_axes={"model": 2, "data": 4})
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.01),
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars)
    sess = ad.create_distributed_session()

    rng = np.random.RandomState(8)
    reqs = [(rng.randint(0, VOCAB, p).astype(np.int32), n)
            for p, n in [(3, 5), (2, 6), (4, 4)]]
    eng = DecodeEngine(spec, sess.sharded_params, slots=2, window=24,
                       chunk=4)
    ids = [eng.submit(p, n) for p, n in reqs]
    results = eng.run()
    for rid, (prompt, n) in zip(ids, reqs):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(spec, params, prompt, n))


@pytest.mark.slow
def test_engine_long_prompt_prefill(lm):
    """A long (130-token) prompt stays oracle-exact through prefill;
    its pow-2 bucket overruns the window so it also exercises the
    exact-size fallback."""
    spec_long = transformer_lm(vocab_size=VOCAB, num_layers=2,
                               num_heads=2, head_dim=8, d_ff=32,
                               max_len=200, seq_len=16,
                               attn_fn=dense_attention)
    params = spec_long.init(jax.random.PRNGKey(4))
    rng = np.random.RandomState(16)
    short = rng.randint(0, VOCAB, 2).astype(np.int32)
    long_p = rng.randint(0, VOCAB, 130).astype(np.int32)
    eng = DecodeEngine(spec_long, params, slots=1, window=192, chunk=32)
    r1 = eng.submit(short, 140)          # drives the tick past 130
    r2 = eng.submit(long_p, 6)           # prefill-admitted, P=130
    results = eng.run()
    np.testing.assert_array_equal(
        results[r1], _oracle(spec_long, params, short, 140))
    np.testing.assert_array_equal(
        results[r2], _oracle(spec_long, params, long_p, 6))
    # both requests prefill under ring admission (130 + 2 tokens)
    assert eng.stats.prefill_admissions == 2
    assert eng.stats.prefilled_tokens == 132


@pytest.mark.slow
def test_engine_quantized_params(lm):
    """Weight-only int8 tree through the engine: matches the int8
    generate() oracle exactly (the tick math routes through the same
    quantized kernels)."""
    from autodist_tpu.models.quantize import quantize_lm_params
    spec, params = lm
    qp = quantize_lm_params(params)
    rng = np.random.RandomState(6)
    reqs = [(rng.randint(0, VOCAB, p).astype(np.int32), n)
            for p, n in [(3, 4), (2, 6), (5, 3)]]
    eng = DecodeEngine(spec, qp, slots=2, window=20, chunk=4)
    ids = [eng.submit(p, n) for p, n in reqs]
    results = eng.run()
    gen = make_generator(spec)
    for rid, (prompt, n) in zip(ids, reqs):
        want = np.asarray(gen(qp, prompt[None, :], n))[0]
        np.testing.assert_array_equal(results[rid], want)


def test_engine_poisoned_after_failed_dispatch(lm, monkeypatch):
    """A device dispatch failing mid-flight (buffers already donated)
    must poison the engine with a clear error, not decode garbage."""
    import autodist_tpu.serving.engine as eng_mod

    spec, params = lm
    eng = DecodeEngine(spec, params, slots=1, window=16, chunk=2)
    eng.submit(np.arange(2, dtype=np.int32), 4)

    def boom(*a, **k):
        raise RuntimeError("tunnel dropped")

    monkeypatch.setattr(eng_mod, "_chunk_program", boom)
    with pytest.raises(RuntimeError, match="tunnel dropped"):
        eng.run()
    monkeypatch.undo()
    with pytest.raises(RuntimeError, match="poisoned"):
        eng.step()
    with pytest.raises(RuntimeError, match="poisoned"):
        eng.submit(np.arange(2, dtype=np.int32), 2)
    with pytest.raises(RuntimeError, match="poisoned"):
        eng.partial(0)
    assert eng.results() == {}   # host-side salvage still works

    # reset() revives the engine: fresh buffers, same compiled programs
    eng.reset()
    prompt = np.arange(3, dtype=np.int32)
    rid = eng.submit(prompt, 4)
    out = eng.run()
    np.testing.assert_array_equal(out[rid],
                                  _oracle(spec, params, prompt, 4))


def test_engine_validation(lm):
    spec, params = lm
    eng = DecodeEngine(spec, params, slots=1, window=8)
    with pytest.raises(ValueError, match="exceeds the engine window"):
        eng.submit(np.arange(5, dtype=np.int32), 10)
    with pytest.raises(ValueError, match="at least one token"):
        eng.submit(np.zeros(0, np.int32), 2)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(np.arange(2, dtype=np.int32), 0)
    with pytest.raises(ValueError, match="out of vocab"):
        eng.submit(np.array([VOCAB + 3], np.int32), 2)
    with pytest.raises(ValueError, match="needs temperature"):
        DecodeEngine(spec, params, window=8, top_k=5)
    with pytest.raises(ValueError, match="max_len"):
        DecodeEngine(spec, params, window=4096)


def test_engine_per_request_sampling_knobs(lm):
    """temperature/eos_id are PER-REQUEST (traced per-slot vectors, one
    compiled program): a greedy request stays oracle-exact while a
    sampled request decodes in the adjacent slot, and a per-request eos
    stops only its own slot."""
    spec, params = lm
    rng = np.random.RandomState(21)
    p1 = rng.randint(0, VOCAB, 3).astype(np.int32)
    p2 = rng.randint(0, VOCAB, 4).astype(np.int32)

    eng = DecodeEngine(spec, params, slots=2, window=24, chunk=4,
                       rng=jax.random.PRNGKey(7))
    r_greedy = eng.submit(p1, 8)                      # default temp 0
    r_sampled = eng.submit(p2, 8, temperature=1.0)    # per-request
    results = eng.run()
    np.testing.assert_array_equal(results[r_greedy],
                                  _oracle(spec, params, p1, 8))
    sampled = results[r_sampled]
    assert sampled.size == p2.size + 8
    assert np.all((sampled >= 0) & (sampled < VOCAB))

    # per-request eos: pick the greedy continuation's 3rd token as eos
    # for ONE of two otherwise-identical greedy requests.
    free = _oracle(spec, params, p1, 8)
    eos = int(free[p1.size + 2])
    if eos in (int(free[p1.size]), int(free[p1.size + 1])):
        pytest.skip("greedy repeats; eos choice ambiguous")
    eng2 = DecodeEngine(spec, params, slots=2, window=24, chunk=4)
    r_stop = eng2.submit(p1, 8, eos_id=eos)
    r_full = eng2.submit(p1, 8)
    out = eng2.run()
    np.testing.assert_array_equal(out[r_stop], free[:p1.size + 3])
    assert out[r_stop][-1] == eos
    np.testing.assert_array_equal(out[r_full], free)  # untouched slot


def test_engine_per_request_temperature_needs_rng(lm):
    """A greedy-built engine without an explicit rng refuses a sampled
    request loudly (a silent fixed key would sample identical streams)."""
    spec, params = lm
    eng = DecodeEngine(spec, params, slots=1, window=16)
    with pytest.raises(ValueError, match="rng"):
        eng.submit(np.arange(2, dtype=np.int32), 4, temperature=0.7)
    with pytest.raises(ValueError, match="temperature"):
        eng.submit(np.arange(2, dtype=np.int32), 4, temperature=-1.0)
    with pytest.raises(ValueError, match="eos_id"):
        eng.submit(np.arange(2, dtype=np.int32), 4, eos_id=VOCAB + 3)


@pytest.mark.slow
def test_engine_per_request_validation_edges(lm):
    """NaN/inf/f32-underflow temperatures are rejected; eos_id=-1
    explicitly disables an engine-default eos for one request."""
    spec, params = lm
    rng = np.random.RandomState(31)
    # find a prompt whose greedy continuation has a usable (non-tied,
    # non-initial) eos candidate
    for _ in range(20):
        prompt = rng.randint(0, VOCAB, 3).astype(np.int32)
        free = _oracle(spec, params, prompt, 6)
        eos = int(free[prompt.size + 1])
        if eos not in (int(free[prompt.size]), *prompt.tolist()):
            break
    else:  # pragma: no cover - wildly unlikely
        pytest.skip("no unambiguous eos candidate found")
    eng = DecodeEngine(spec, params, slots=2, window=24, chunk=3,
                       eos_id=eos, rng=jax.random.PRNGKey(1))
    for bad in (float("nan"), float("inf"), 1e-300):
        with pytest.raises(ValueError):
            eng.submit(np.arange(2, dtype=np.int32), 4, temperature=bad)
    r_default = eng.submit(prompt, 6)
    r_noeos = eng.submit(prompt, 6, eos_id=-1)
    out = eng.run()
    assert out[r_default][-1] == eos and out[r_default].size < 9
    np.testing.assert_array_equal(out[r_noeos], free)   # ran to length


@pytest.mark.parametrize("prefill", [False, True])
def test_engine_prefix_cache_token_exact(lm, prefill):
    """A registered shared prefix (system prompt) is held ONCE and
    attended as cached context: each request's output equals the full
    generate over concat(prefix, prompt) with the prefix stripped —
    through both admission paths, with a non-prefix request decoding in
    the adjacent slot concurrently."""
    spec, params = lm
    rng = np.random.RandomState(17)
    prefix = rng.randint(0, VOCAB, 5).astype(np.int32)
    p1 = rng.randint(0, VOCAB, 3).astype(np.int32)
    p2 = rng.randint(0, VOCAB, 4).astype(np.int32)

    eng = DecodeEngine(spec, params, slots=2, window=24, chunk=4,
                       prefill=prefill)
    assert eng.set_prefix(prefix) == 5
    assert eng.prefix_len == 5
    r_pre = eng.submit(p1, 7, use_prefix=True)
    r_plain = eng.submit(p2, 6)                   # no prefix, same batch
    results = eng.run()

    want_full = _oracle(spec, params, np.concatenate([prefix, p1]), 7)
    np.testing.assert_array_equal(results[r_pre], want_full[prefix.size:],
                                  err_msg="prefix-cached decode")
    np.testing.assert_array_equal(results[r_plain],
                                  _oracle(spec, params, p2, 6),
                                  err_msg="non-prefix slot disturbed")
    # prefix K/V were not recomputed per admission
    assert eng.stats.prompt_tokens == p1.size + p2.size

    # slot REUSE under the prefix: a second wave still exact
    r3 = eng.submit(p2, 5, use_prefix=True)
    out2 = eng.run()
    want3 = _oracle(spec, params, np.concatenate([prefix, p2]), 5)
    np.testing.assert_array_equal(out2[r3], want3[prefix.size:])

    # clear_prefix restores plain behavior
    eng.clear_prefix()
    r4 = eng.submit(p1, 4)
    np.testing.assert_array_equal(eng.run()[r4],
                                  _oracle(spec, params, p1, 4))


def test_engine_prefix_validation(lm):
    spec, params = lm
    eng = DecodeEngine(spec, params, slots=1, window=16, chunk=2)
    with pytest.raises(ValueError, match="no prefix"):
        eng.submit(np.arange(2, dtype=np.int32), 3, use_prefix=True)
    eng.set_prefix(np.arange(4, dtype=np.int32))
    # prefix + span must fit the model's pos_embed rows (max_len 48)
    with pytest.raises(ValueError, match="max_len"):
        eng.set_prefix(np.arange(47, dtype=np.int32))
    # a busy engine accepts a prefix swap: in-flight requests keep the
    # generation they pinned (exactness pinned in
    # tests/test_serving_scheduler.py::
    # test_slot_engine_mid_flight_prefix_swap_pins_readers)
    eng.submit(np.arange(2, dtype=np.int32), 6)
    assert eng.step()
    eng.set_prefix(np.arange(3, dtype=np.int32))
    while eng.step():
        pass
    eng.results()
    assert eng.prefix_len == 3


@pytest.mark.slow
def test_engine_prefix_bucket_edges(lm):
    """The pow-2 buckets must not outrun pos_embed (max_len 48 here):
    (a) a prompt whose bucket extends past max_len under a prefix —
    position ids clip, pad-row K/V are overwritten before any read;
    (b) a prefix whose own bucket exceeds max_len falls back to exact
    size.  Both stay token-exact vs the concat oracle."""
    spec, params = lm
    rng = np.random.RandomState(23)

    # (a) the clip path proper: prefix 35 + prompt 9 (bucket 16 fits
    # window 16, so no exact-size fallback) -> pad bucket positions
    # 44..50 overrun max_len 48 and CLIP; their K/V land at ring >= t0
    # and are overwritten before any read.  Real rows stay exact.
    prefix = rng.randint(0, VOCAB, 35).astype(np.int32)
    prompt = rng.randint(0, VOCAB, 9).astype(np.int32)
    eng = DecodeEngine(spec, params, slots=2, window=16, chunk=4)
    eng.set_prefix(prefix)
    rid = eng.submit(prompt, 3, use_prefix=True)
    out = eng.run()
    want = _oracle(spec, params, np.concatenate([prefix, prompt]), 3)
    np.testing.assert_array_equal(out[rid], want[prefix.size:])

    # (b) prefix 40: pow-2 bucket 64 > max_len 48 -> exact fallback
    prefix_b = rng.randint(0, VOCAB, 40).astype(np.int32)
    eng2 = DecodeEngine(spec, params, slots=1, window=8, chunk=2)
    assert eng2.set_prefix(prefix_b) == 40
    p_small = rng.randint(0, VOCAB, 2).astype(np.int32)
    rid2 = eng2.submit(p_small, 3, use_prefix=True)
    out2 = eng2.run()
    want2 = _oracle(spec, params,
                    np.concatenate([prefix_b, p_small]), 3)
    np.testing.assert_array_equal(out2[rid2], want2[prefix_b.size:])


def test_engine_rejects_below_floor_temperature(lm):
    """Temperatures in (0, 1e-6) are rejected at submit — the sampler's
    divide floor would otherwise silently clamp them (ADVICE r5 low #1);
    0 (greedy) and the floor itself stay accepted."""
    spec, params = lm
    eng = DecodeEngine(spec, params, slots=1, window=16, chunk=2,
                       rng=jax.random.PRNGKey(0))
    prompt = np.arange(2, dtype=np.int32)
    for bad in (1e-7, 9.9e-7, 1e-20):
        with pytest.raises(ValueError, match="floor"):
            eng.submit(prompt, 2, temperature=bad)
    eng.submit(prompt, 2, temperature=0.0)      # greedy: fine
    eng.submit(prompt, 2, temperature=1e-6)     # exactly the floor: fine
    eng.run()


def test_engine_rebase_resets_inactive_slot_bounds(lm):
    """_rebase_tick zeroes inactive slots' start/p_end/end instead of
    shifting them: a slot that never re-admits can no longer accumulate
    -shift per rebase toward int32 wrap (ADVICE r5 low #2)."""
    spec, params = lm
    rng = np.random.RandomState(21)
    eng = DecodeEngine(spec, params, slots=3, window=16, chunk=4)
    eng._REBASE_AT = 24
    # slot pool wider than the stream: slot 2 admits once, then idles
    first = eng.submit(rng.randint(0, VOCAB, 3).astype(np.int32), 4)
    while eng.step():
        pass
    eng.results()
    assert not eng._active.any()
    # sustained single-slot stream forces repeated rebases
    ids = []
    for _ in range(12):
        ids.append(eng.submit(rng.randint(0, VOCAB, 3).astype(np.int32), 6))
        eng.step()
        eng.results()
    while eng.step():
        pass
    eng.results()
    # every inactive slot's bounds were reset at the last rebase: they
    # can never be more negative than one rebase window's shift.
    inactive = ~eng._active
    assert inactive.all()
    for arr in (eng._start, eng._p_end, eng._end):
        assert int(arr[inactive].min()) > -(1 << 24), arr
    del first, ids


@pytest.mark.parametrize("wrap", [False, True])
def test_engine_prefill_contiguous_and_wrapped_paths_token_exact(lm, wrap):
    """Token-exactness pin for BOTH prefill cache-write paths: the
    contiguous dynamic_update_slice fast path (no ring wrap) and the
    mod-window scatter path (wrapped admission).  The wrapped case
    arises only once the tick outgrows the window (t0 % window < P)."""
    spec, params = lm
    rng = np.random.RandomState(33)
    eng = DecodeEngine(spec, params, slots=1, window=16, chunk=4)
    reqs = [(rng.randint(0, VOCAB, 6).astype(np.int32), 7)]
    if wrap:
        # run enough sequential requests that an admission lands with
        # t0 % 16 < 6 (the single slot serializes them, walking t0
        # through every residue)
        reqs = [(rng.randint(0, VOCAB, 6).astype(np.int32), 7)
                for _ in range(5)]
    ids = [eng.submit(p, n) for p, n in reqs]
    results = eng.run()
    for rid, (p, n) in zip(ids, reqs):
        np.testing.assert_array_equal(
            results[rid], _oracle(spec, params, p, n),
            err_msg=f"wrap={wrap} request {rid}")
    if wrap:
        assert eng.stats.prefill_dispatches >= 2


@pytest.mark.slow
def test_engine_prefill_mixed_wrapness_boundary(lm):
    """One boundary admitting a wrapping and a non-wrapping prompt
    dispatches them as separate (static-wrapness) programs and both
    stay oracle-exact."""
    spec, params = lm
    rng = np.random.RandomState(35)
    eng = DecodeEngine(spec, params, slots=2, window=16, chunk=4)
    # opener pair retires together at a tick t0 with 0 < t0 % 16 < 8
    openers = [(rng.randint(0, VOCAB, 3).astype(np.int32), 7)
               for _ in range(2)]
    # next wave: one long prompt (wraps when t0 % 16 < 8) + one short
    wave2 = [(rng.randint(0, VOCAB, 8).astype(np.int32), 3),
             (rng.randint(0, VOCAB, 1).astype(np.int32), 3)]
    ids = [eng.submit(p, n) for p, n in openers + wave2]
    results = eng.run()
    for rid, (p, n) in zip(ids, openers + wave2):
        np.testing.assert_array_equal(results[rid],
                                      _oracle(spec, params, p, n))
