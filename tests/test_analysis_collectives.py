"""Analyzer collectives pass: the static deadlock lint goldens."""
import jax.numpy as jnp
import pytest

from autodist_tpu.analysis import analyze
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.strategy.base import (
    PSSynchronizerConfig,
    Strategy,
    VarConfig,
)

from _analysis_fixtures import ar_node, full_cover, make_gi, ps_node

pytestmark = pytest.mark.analysis


def _stage_gi():
    params = {
        "stage0": {"w": jnp.zeros((8, 8)), "b": jnp.zeros((8,))},
        "stage1": {"w": jnp.zeros((8, 8)), "b": jnp.zeros((8,))},
    }
    return GraphItem(params)


def test_stage_collective_mismatch_is_exactly_one_error():
    gi = _stage_gi()
    s = Strategy(node_config=[
        ar_node("stage0/w", compressor="HorovodCompressorEF"),
        ar_node("stage0/b", compressor="HorovodCompressorEF"),
        ar_node("stage1/w"),          # plain psum: sequence diverges
        ar_node("stage1/b"),
    ])
    report = analyze(s, gi, mesh={"pipe": 2, "data": 4})
    errors = [d for d in report.errors
              if d.rule == "collectives/stage-collective-mismatch"]
    assert len(errors) == 1
    assert "stage" in errors[0].message


def test_stage_sync_kind_mismatch_is_error():
    gi = _stage_gi()
    s = Strategy(node_config=[
        ar_node("stage0/w"), ar_node("stage0/b"),
        ar_node("stage1/w"),
        VarConfig("stage1/b", synchronizer=PSSynchronizerConfig()),
    ])
    report = analyze(s, gi, mesh={"pipe": 2, "data": 4})
    assert any(d.rule == "collectives/stage-collective-mismatch"
               for d in report.errors)


def test_uniform_stages_are_clean():
    gi = _stage_gi()
    s = Strategy(node_config=[
        ar_node("stage0/w"), ar_node("stage0/b"),
        ar_node("stage1/w"), ar_node("stage1/b")])
    report = analyze(s, gi, mesh={"pipe": 2, "data": 4})
    assert not report.has_errors()


def test_expert_groups_lint_too():
    """The per-index group lint covers expert<k> naming as well."""
    gi = GraphItem({
        "expert0": {"w": jnp.zeros((8, 8))},
        "expert1": {"w": jnp.zeros((8, 8))},
    })
    s = Strategy(node_config=[
        ar_node("expert0/w", compressor="Int8Compressor"),
        ar_node("expert1/w")])
    report = analyze(s, gi, mesh={"expert": 2, "data": 4})
    assert any(d.rule == "collectives/stage-collective-mismatch"
               and "expert" in d.location for d in report.errors)


def test_stacked_pipeline_heterogeneous_stack_warns():
    gi = GraphItem({"a": jnp.zeros((4, 8, 8)), "b": jnp.zeros((8, 8, 8))},
                   pipeline_vars=["a", "b"])
    s = Strategy(node_config=[ar_node("a"), ar_node("b")])
    report = analyze(s, gi, mesh={"pipe": 4, "data": 2})
    assert any(d.rule == "collectives/stage-stack-heterogeneous"
               for d in report.warnings)


def test_interleaved_virtual_stage_multiple_is_allowed_shapewise():
    """A uniform S*V stack (all vars agree) does not warn."""
    gi = GraphItem({"a": jnp.zeros((8, 8, 8)), "b": jnp.zeros((8, 8))},
                   pipeline_vars=["a", "b"])
    s = Strategy(node_config=[ar_node("a"), ar_node("b")])
    report = analyze(s, gi, mesh={"pipe": 4, "data": 2})
    assert not any(d.rule == "collectives/stage-stack-heterogeneous"
                   for d in report.warnings)


def test_unused_pipe_axis_warns():
    gi = make_gi()
    report = analyze(full_cover(gi), gi, mesh={"pipe": 4, "data": 2})
    assert any(d.rule == "collectives/unused-parallel-axis"
               for d in report.warnings)


def test_pipe_axis_used_by_stacked_vars_is_quiet():
    gi = GraphItem({"stages": jnp.zeros((4, 8, 8)),
                    "head": jnp.zeros((8, 8))},
                   pipeline_vars=["stages"])
    s = Strategy(node_config=[ar_node("stages"), ar_node("head")])
    report = analyze(s, gi, mesh={"pipe": 4, "data": 2})
    assert not any(d.rule == "collectives/unused-parallel-axis"
                   for d in report.warnings)


def test_mixed_staleness_warns():
    gi = make_gi()
    names = [v.name for v in gi.trainable_var_infos]
    s = Strategy(node_config=[
        ps_node(names[0], staleness=2),
        *[ps_node(n) for n in names[1:]]])
    report = analyze(s, gi, mesh={"data": 8})
    assert any(d.rule == "collectives/staleness-mixed"
               for d in report.warnings)
