"""AutoStrategy: heuristic per-variable strategy selection (beyond the OSS
reference's fixed builders; the paper's auto-strategizer motivates it)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.mesh import build_mesh
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import AutoStrategy, StrategyCompiler


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


def _spec():
    return ResourceSpec(
        resource_info={"nodes": [{"address": "localhost", "chips": 8}]})


def _params():
    return {
        "emb": {"table": jnp.zeros((512, 16))},           # sparse
        "big": {"w": jnp.zeros((512, 640))},              # 1.25 MiB dense
        "small": {"w": jnp.zeros((16, 8)), "b": jnp.zeros(8)},
    }


def test_tier_assignment():
    gi = GraphItem(_params(), sparse_vars=["emb/table"])
    s = AutoStrategy().build(gi, _spec())
    kinds = {n.var_name: (n.synchronizer.kind, n.partitioner)
             for n in s.node_config}
    assert kinds["emb/table"][0] == "PS"          # sparse -> PS
    assert kinds["emb/table"][1] == ""            # vocab sharding by compiler
    assert kinds["big/w"][0] == "PS"              # large dense -> PS
    assert kinds["big/w"][1] != ""                # partitioned on largest axis
    assert kinds["small/w"][0] == "AllReduce"     # small dense -> AR
    assert kinds["small/b"][0] == "AllReduce"


def test_lowering_shards_big_and_sparse():
    gi = GraphItem(_params(), sparse_vars=["emb/table"])
    mesh = build_mesh({"data": 8})
    cs = StrategyCompiler(mesh).compile(AutoStrategy().build(gi, _spec()), gi)
    assert cs.plan_for("emb/table").param_spec == P("data")
    big = cs.plan_for("big/w")
    assert big.param_spec != P()                  # physically partitioned
    small = cs.plan_for("small/w")
    assert small.param_spec == P()                # replicated, psum'd


def test_auto_strategy_trains_to_parity():
    params = _params()

    def loss(p, b):
        h = jnp.take(p["emb"]["table"], b["ids"], axis=0).mean(axis=1)
        h = jnp.tanh(h @ p["small"]["w"] + p["small"]["b"])
        z = (h @ p["big"]["w"][:8, :8].T)          # touch the big var
        return jnp.mean((z - b["y"]) ** 2)

    rng = np.random.RandomState(0)
    batch = {"ids": rng.randint(0, 512, (16, 4)).astype(np.int32),
             "y": rng.randn(16, 8).astype(np.float32)}

    opt = optax.adam(1e-2)
    p, s = params, opt.init(params)
    ref = []
    for _ in range(3):
        l, g = jax.value_and_grad(loss)(p, batch)
        u, s = opt.update(g, s, p)
        p = optax.apply_updates(p, u)
        ref.append(float(l))

    ad = AutoDist(strategy_builder=AutoStrategy())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2), loss_fn=loss,
                   sparse_vars=["emb/table"])
    sess = ad.create_distributed_session()
    losses = [float(sess.run(batch)["loss"]) for _ in range(3)]
    np.testing.assert_allclose(losses, ref, rtol=1e-4)


def test_threshold_moves_the_boundary():
    gi = GraphItem(_params(), sparse_vars=["emb/table"])
    s = AutoStrategy(partition_threshold=64).build(gi, _spec())
    kinds = {n.var_name: n.synchronizer.kind for n in s.node_config}
    assert kinds["small/w"] == "PS"   # now above the tiny threshold
    s2 = AutoStrategy(partition_threshold=1 << 30).build(gi, _spec())
    kinds2 = {n.var_name: n.synchronizer.kind for n in s2.node_config}
    assert kinds2["big/w"] == "AllReduce"  # below the huge threshold
    assert kinds2["emb/table"] == "PS"     # sparse stays PS regardless


@pytest.mark.integration
def test_auto_measured_within_tolerance_of_best_fixed():
    """VERDICT r3 #5 — the AutoSync pitch as EVIDENCE, not heuristic
    argument: on two contrasting workloads, AutoStrategy's measured
    wall-clock step time (real session path, 8-device CPU mesh) lands
    within tolerance of the best fixed builder's.

    Tolerance is 1.25x: the ~10% target plus CPU-mesh host noise
    (the min-over-repeats measurement still jitters ~10% between
    whole-suite runs).  Integration-gated (--run-integration) because a
    wall-clock assertion on a loaded shared host is inherently noisy —
    the default suite stays deterministic; the companion bench section
    (auto_vs_best_pct in BENCH_r04) records the same comparison on TPU
    hardware where the timing floor is stable."""
    from test_cost_model_calibration import _measure

    from autodist_tpu.strategy import (AllReduce, Parallax, PartitionedAR,
                                       PS, PSLoadBalancing)

    rng = np.random.RandomState(0)

    # Workload 1 — embedding-heavy (the regime where the choice MATTERS:
    # densifying builders move the whole 200k x 32 table every step).
    vocab, dim = 200_000, 32
    emb_params = {
        "emb": {"table": jnp.asarray(rng.randn(vocab, dim) * 0.01,
                                     jnp.float32)},
        "head": {"w": jnp.asarray(rng.randn(dim, 1) * 0.1, jnp.float32)},
    }
    emb_batch = {"ids": rng.randint(0, vocab, (256,)).astype(np.int32),
                 "y": rng.randn(256).astype(np.float32)}

    def emb_loss(p, b):
        rows = jnp.take(p["emb"]["table"], b["ids"], axis=0)
        return jnp.mean(((rows @ p["head"]["w"])[:, 0] - b["y"]) ** 2)

    # Workload 2 — dense MLP (near-tie regime: every ring lowering moves
    # the same bytes; auto must simply not pick something pathological).
    dense_params = {
        "l1": {"w": jnp.asarray(rng.randn(512, 512) * 0.05, jnp.float32)},
        "l2": {"w": jnp.asarray(rng.randn(512, 512) * 0.05, jnp.float32)},
        "out": {"w": jnp.asarray(rng.randn(512, 1) * 0.1, jnp.float32)},
    }
    dense_batch = {"x": rng.randn(128, 512).astype(np.float32),
                   "y": rng.randn(128).astype(np.float32)}

    def dense_loss(p, b):
        h = jnp.tanh(b["x"] @ p["l1"]["w"])
        h = jnp.tanh(h @ p["l2"]["w"])
        return jnp.mean(((h @ p["out"]["w"])[:, 0] - b["y"]) ** 2)

    # Per-case tolerance: sparse is the regime where the claim MATTERS
    # (wrong = orders of magnitude) and holds tightly; dense is a
    # near-tie regime where the CPU backend's lowering quirks dominate
    # (gloo measures the PS reduce-scatter+all-gather ~25% faster than
    # one psum, while TPU favors the fused psum) — there the assertion
    # is only "not pathological".
    cases = [
        ("sparse", emb_params, emb_loss, emb_batch, ("emb/table",), 1.25,
         [AllReduce(), PartitionedAR(), Parallax(), PSLoadBalancing()]),
        ("dense", dense_params, dense_loss, dense_batch, (), 1.5,
         [AllReduce(), PS(), PSLoadBalancing(), PartitionedAR()]),
    ]
    for name, params, loss_fn, batch, sparse, tol, fixed in cases:
        fixed_times = [_measure(b, params, loss_fn, batch,
                                sparse_vars=sparse) for b in fixed]
        best = min(fixed_times)
        for auto in (AutoStrategy(), AutoStrategy(search=True)):
            auto_time = _measure(auto, params, loss_fn, batch,
                                 sparse_vars=sparse)
            assert auto_time <= tol * best, (
                name, type(auto).__name__, auto.last_choice, auto_time,
                dict(zip([type(b).__name__ for b in fixed], fixed_times)))


def test_search_mode_picks_sparse_aware_and_reports_choice():
    """AutoStrategy(search=True): on a genuinely embedding-heavy
    workload (200k x 32 table, batches touch <= 4096 rows) the
    cost-model search must route the table through PS — densifying
    AllReduce candidates move the whole 24 MB gradient — and expose
    which candidate won.  (On TINY tables AllReduce legitimately wins
    the estimate; that is the point of searching instead of hard
    rules.)"""
    params = {"emb": {"table": jnp.zeros((200_000, 32))},
              "head": {"w": jnp.zeros((32, 1))}}
    gi = GraphItem(params, sparse_vars=["emb/table"])
    b = AutoStrategy(search=True)
    s = b.build(gi, _spec())
    assert b.last_choice, "search did not record a choice"
    kinds = {n.var_name: n.synchronizer.kind for n in s.node_config}
    assert kinds["emb/table"] == "PS", (b.last_choice, kinds)


def test_search_mode_trains_to_parity():
    """End-to-end: a session built from the searched strategy trains
    identically to the plain single-device optax loop."""
    rng = np.random.RandomState(0)
    params = {"emb": {"table": jnp.zeros((128, 8))},
              "head": {"w": jnp.asarray(rng.randn(8, 4) * 0.1,
                                        jnp.float32)}}

    def loss(p, b):
        h = jnp.take(p["emb"]["table"], b["ids"], axis=0).mean(axis=1)
        return jnp.mean((h @ p["head"]["w"] - b["y"]) ** 2)

    batch = {"ids": rng.randint(0, 128, (16, 4)).astype(np.int32),
             "y": rng.randn(16, 4).astype(np.float32)}

    opt = optax.adam(1e-2)
    p, s = params, opt.init(params)
    ref = []
    for _ in range(3):
        l, g = jax.value_and_grad(loss)(p, batch)
        u, s = opt.update(g, s, p)
        p = optax.apply_updates(p, u)
        ref.append(float(l))

    ad = AutoDist(strategy_builder=AutoStrategy(search=True))
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-2), loss_fn=loss,
                   sparse_vars=["emb/table"])
    sess = ad.create_distributed_session()
    losses = [float(sess.run(batch)["loss"]) for _ in range(3)]
    np.testing.assert_allclose(losses, ref, rtol=1e-4)


def test_search_mode_custom_candidates():
    from autodist_tpu.strategy import PS, PSLoadBalancing

    gi = GraphItem(_params(), sparse_vars=["emb/table"])
    b = AutoStrategy(search=True, candidates=[PS(), PSLoadBalancing()])
    b.build(gi, _spec())
    assert b.last_choice in ("PS", "PSLoadBalancing")
