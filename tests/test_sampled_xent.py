"""Sampled softmax (the reference lm1b's loss) vs the exact loss."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.models.base import cross_entropy_loss
from autodist_tpu.ops.sampled_xent import sampled_softmax_cross_entropy


def _data(n=64, e=16, v=512, seed=0):
    rng = np.random.RandomState(seed)
    h = jnp.asarray(rng.randn(n, e) * 0.5, jnp.float32)
    w = jnp.asarray(rng.randn(v, e) * 0.5, jnp.float32)
    y = jnp.asarray(rng.randint(0, v, (n,)), jnp.int32)
    return h, w, y


def _exact(h, w, y):
    return cross_entropy_loss(jnp.einsum("ne,ve->nv", h, w), y)


def test_approaches_exact_with_many_samples():
    """Averaged over keys, the sampled loss tracks the exact loss when k
    covers most of the vocabulary."""
    h, w, y = _data()
    exact = float(_exact(h, w, y))
    ests = [float(sampled_softmax_cross_entropy(
        h, w, y, jax.random.PRNGKey(i), num_sampled=480)) for i in range(8)]
    assert abs(np.mean(ests) - exact) < 0.15 * exact, (np.mean(ests), exact)


def test_gradients_touch_only_sampled_rows():
    """The estimator's selling point: dW is zero outside the true+sampled
    rows (a sparse update — why the reference paired it with sharded PS)."""
    h, w, y = _data(n=8, v=512)
    key = jax.random.PRNGKey(3)
    dw = jax.grad(lambda w: sampled_softmax_cross_entropy(
        h, w, y, key, num_sampled=16))(w)
    touched = set(np.asarray(jax.random.randint(key, (16,), 0, 512)).tolist())
    touched |= set(np.asarray(y).tolist())
    nz_rows = set(np.nonzero(np.abs(np.asarray(dw)).sum(axis=1))[0].tolist())
    assert nz_rows <= touched, nz_rows - touched
    assert len(nz_rows) >= len(set(np.asarray(y).tolist()))


def test_training_converges():
    h, w, y = _data(n=32, v=256)
    exact0 = float(_exact(h, w, y))
    for i in range(60):
        g_h, g_w = jax.grad(lambda h, w: sampled_softmax_cross_entropy(
            h, w, y, jax.random.PRNGKey(i), num_sampled=64),
            argnums=(0, 1))(h, w)
        h, w = h - 0.3 * g_h, w - 0.3 * g_w
    assert float(_exact(h, w, y)) < 0.5 * exact0


def test_accidental_hits_masked():
    """A negative equal to the row's label must not double-count: with
    every sample forced to hit (vocab=1), the loss is exactly zero
    (only the true class remains)."""
    h = jnp.ones((4, 8)); w = jnp.ones((1, 8)); y = jnp.zeros((4,), jnp.int32)
    loss = sampled_softmax_cross_entropy(h, w, y, jax.random.PRNGKey(0),
                                         num_sampled=4)
    assert float(loss) == pytest.approx(0.0, abs=1e-6)


def test_leading_shape_flattens():
    h, w, y = _data(n=24)
    key = jax.random.PRNGKey(1)
    a = sampled_softmax_cross_entropy(h.reshape(4, 6, -1), w,
                                      y.reshape(4, 6), key, num_sampled=64)
    b = sampled_softmax_cross_entropy(h, w, y, key, num_sampled=64)
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_lm1b_sampled_option_trains():
    """lm1b(sampled_softmax=k) — the reference's actual loss — trains
    through a full session."""
    import optax

    from autodist_tpu.autodist import (AutoDist,
                                       _reset_default_autodist_for_testing)
    from autodist_tpu.models.lm1b import lm1b
    from autodist_tpu.strategy import Parallax

    _reset_default_autodist_for_testing()
    spec = lm1b(vocab_size=1024, emb_dim=16, hidden_dim=32, seq_len=8,
                sampled_softmax=64)
    params = spec.init(jax.random.PRNGKey(0))
    ad = AutoDist(strategy_builder=Parallax())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adagrad(0.5),
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars)
    sess = ad.create_distributed_session()
    batch = spec.sample_batch(16)
    losses = [float(sess.run(batch)["loss"]) for _ in range(10)]
    assert losses[-1] < losses[0]
