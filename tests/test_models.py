"""Model zoo smoke + distributed-training tests (tiny configs).

Mirrors the reference's case files (tests/integration/cases/) which exercise
model×strategy combinations with real training steps.
"""
import jax
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu import models as zoo
from autodist_tpu.strategy import AllReduce, Parallax, PartitionedPS


@pytest.fixture(autouse=True)
def _testing_env(monkeypatch):
    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    _reset_default_autodist_for_testing()


TINY = {
    "resnet50": lambda: zoo.resnet50(num_classes=8, image_size=32),
    "vgg16": lambda: zoo.vgg16(num_classes=8, image_size=32),
    "densenet121": lambda: zoo.densenet121(num_classes=8, image_size=32),
    "inception_v3": lambda: zoo.inception_v3(num_classes=8, image_size=96),
    "bert": lambda: zoo.bert(vocab_size=512, num_layers=2, num_heads=2,
                             head_dim=16, d_ff=64, max_len=64, seq_len=16),
    "lm1b": lambda: zoo.lm1b(vocab_size=512, emb_dim=32, hidden_dim=64,
                             num_layers=1, seq_len=8),
    "ncf": lambda: zoo.ncf(num_users=64, num_items=32, mf_dim=8,
                           mlp_dims=(16, 16, 8)),
    "transformer_lm": lambda: zoo.transformer_lm(
        vocab_size=512, num_layers=2, num_heads=2, head_dim=16, d_ff=64,
        max_len=32, seq_len=16),
}

# Compile-heavy conv nets run in the integration matrix, not the default suite.
_SLOW = {"vgg16", "densenet121", "inception_v3"}


@pytest.mark.parametrize(
    "name",
    [n if n not in _SLOW else pytest.param(n, marks=pytest.mark.integration)
     for n in sorted(TINY)])
def test_model_trains_distributed(name):
    spec = TINY[name]()
    params = spec.init(jax.random.PRNGKey(0))
    batch = spec.sample_batch(16)

    ad = AutoDist(strategy_builder=Parallax())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.adam(1e-3),
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars)
    sess = ad.create_distributed_session()
    first = float(sess.run(batch)["loss"])
    for _ in range(4):
        metrics = sess.run(batch)
    assert np.isfinite(first)
    assert np.isfinite(float(metrics["loss"]))
    if name in ("bert", "lm1b", "ncf", "transformer_lm"):
        # small dense models memorize a fixed batch monotonically enough;
        # deep conv nets on random noise need more than 5 steps for that.
        assert float(metrics["loss"]) < first


def test_sparse_vars_detected():
    spec = TINY["lm1b"]()
    params = spec.init(jax.random.PRNGKey(0))
    ad = AutoDist(strategy_builder=Parallax())
    with ad.scope():
        gi = ad.capture(params=params, optimizer=optax.sgd(0.1),
                        loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars)
    sparse = {v.name for v in gi.info.variables if v.sparse}
    assert "embedding" in sparse
    assert "softmax_embedding" in sparse
    s = ad.build_strategy()
    from autodist_tpu.strategy import PSSynchronizerConfig
    assert isinstance(s.node_for("embedding").synchronizer,
                      PSSynchronizerConfig)


def test_transformer_lm_chunked_xent_matches_dense():
    """xent_chunk trains with the streamed loss: identical param tree,
    same loss and gradients as the dense branch (guards the
    features-method binding and the tied params['embed'] pairing)."""
    import jax.numpy as jnp
    import numpy as np

    from autodist_tpu.models.transformer_lm import transformer_lm

    kw = dict(vocab_size=250, num_layers=2, num_heads=2, head_dim=8,
              d_ff=32, max_len=16, seq_len=16)
    dense = transformer_lm(**kw)
    chunked = transformer_lm(**kw, xent_chunk=128)
    params = dense.init(jax.random.PRNGKey(0))
    assert (jax.tree_util.tree_structure(params)
            == jax.tree_util.tree_structure(
                chunked.init(jax.random.PRNGKey(0))))
    batch = dense.sample_batch(4)
    np.testing.assert_allclose(float(dense.loss_fn(params, batch)),
                               float(chunked.loss_fn(params, batch)),
                               rtol=1e-5)
    gd = jax.grad(dense.loss_fn)(params, batch)
    gc = jax.grad(chunked.loss_fn)(params, batch)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4,
                                                atol=1e-6), gd, gc)


def test_transformer_lm_partitioned_model_axis():
    spec = TINY["transformer_lm"]()
    params = spec.init(jax.random.PRNGKey(0))
    batch = spec.sample_batch(8)
    ad = AutoDist(strategy_builder=PartitionedPS(),
                  mesh_axes={"data": 4, "model": 2})
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.01),
                   loss_fn=spec.loss_fn, sparse_vars=spec.sparse_vars)
    sess = ad.create_distributed_session()
    m1 = sess.run(batch)
    m2 = sess.run(batch)
    assert float(m2["loss"]) < float(m1["loss"])
    # embedding sharded over the model axis
    emb = sess.sharded_params["embed"]
    assert "model" in str(emb.sharding.spec)


def test_resnet_s2d_stem_equivalent():
    """The space-to-depth stem computes EXACTLY the 7x7/s2 stem's
    function: convert_stem_params remaps the conv7 kernel into the
    [4,4,4C,64] layout and the two models' logits match."""
    from autodist_tpu.models.resnet import convert_stem_params

    spec7 = zoo.resnet50(num_classes=8, image_size=32)
    spec_s2d = zoo.resnet50(num_classes=8, image_size=32, stem="s2d")
    params7 = spec7.init(jax.random.PRNGKey(0))
    params_s2d = convert_stem_params(params7)
    # shape sanity: the remapped kernel matches the s2d init tree
    init_s2d = spec_s2d.init(jax.random.PRNGKey(1))
    assert params_s2d["conv_init"]["kernel"].shape == \
        init_s2d["conv_init"]["kernel"].shape
    rng = np.random.RandomState(0)
    x = rng.randn(2, 32, 32, 3).astype(np.float32)
    y7 = spec7.apply_fn(params7, x)
    y4 = spec_s2d.apply_fn(params_s2d, x)
    np.testing.assert_allclose(np.asarray(y4), np.asarray(y7),
                               rtol=2e-4, atol=2e-5)
