"""Pad-to-divisible (uneven) partitioning.

Parity target: the reference's uneven partitioner physically splits
non-divisible shard counts (``autodist/kernel/partitioner.py:376-426``);
here indivisible dims are padded to the next multiple of the mesh axis,
physically sharded, and pad rows are masked to zero each step."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.mesh import build_mesh
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import (
    Parallax,
    PartitionedPS,
    StrategyCompiler,
    UnevenPartitionedPS,
)


@pytest.fixture(autouse=True)
def _reset():
    _reset_default_autodist_for_testing()


def _spec():
    return ResourceSpec(
        resource_info={"nodes": [{"address": "localhost", "chips": 8}]})


def _params7():
    # dim0 = 7: not divisible by (and smaller than) the 8-way axis.
    return {"linear": {"w": jnp.arange(21.0).reshape(7, 3) / 10.0,
                       "b": jnp.zeros(3)}}


def _loss(params, batch):
    pred = batch["x"] @ params["linear"]["w"] + params["linear"]["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def _batch(n=16, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 7).astype(np.float32)
    y = rng.randn(n, 3).astype(np.float32)
    return {"x": x, "y": y}


def test_compiler_emits_pad_plan():
    gi = GraphItem(_params7())
    mesh = build_mesh({"data": 8})
    cs = StrategyCompiler(mesh).compile(
        UnevenPartitionedPS().build(gi, _spec()), gi)
    plan = cs.plan_for("linear/w")
    assert plan.param_spec == P("data")
    assert plan.pad_axis == 0 and plan.pad_dim == 8
    assert cs.pad_plans()["linear/w"] == (0, 8)


def test_seven_rows_physically_sharded_on_eight_way_axis():
    """The VERDICT done-criterion: a (7, ...) variable physically sharded
    on an 8-way axis."""
    ad = AutoDist(strategy_builder=UnevenPartitionedPS())
    with ad.scope():
        ad.capture(params=_params7(), optimizer=optax.adam(1e-2),
                   loss_fn=_loss)
    sess = ad.create_distributed_session()
    w_phys = sess.sharded_params["linear"]["w"]
    assert w_phys.shape == (8, 3)                      # physical: padded
    shard_shapes = {s.data.shape for s in w_phys.addressable_shards}
    assert shard_shapes == {(1, 3)}                    # one row per device
    assert sess.params["linear"]["w"].shape == (7, 3)  # logical view


def test_uneven_training_matches_single_device():
    batch = _batch()
    ad = AutoDist(strategy_builder=UnevenPartitionedPS())
    with ad.scope():
        ad.capture(params=_params7(), optimizer=optax.adam(1e-2),
                   loss_fn=_loss)
    sess = ad.create_distributed_session()

    opt = optax.adam(1e-2)
    p = _params7()
    s = opt.init(p)
    for i in range(5):
        dist_loss = sess.run(batch)["loss"]
        (ref_loss, g) = jax.value_and_grad(_loss)(p, batch)
        u, s = opt.update(g, s, p)
        p = optax.apply_updates(p, u)
        np.testing.assert_allclose(dist_loss, ref_loss, rtol=2e-5)
    np.testing.assert_allclose(
        sess.params["linear"]["w"], p["linear"]["w"], rtol=2e-5, atol=1e-6)


def test_pad_rows_stay_zero():
    ad = AutoDist(strategy_builder=UnevenPartitionedPS())
    with ad.scope():
        ad.capture(params=_params7(),
                   optimizer=optax.chain(
                       optax.add_decayed_weights(1e-2), optax.sgd(0.1)),
                   loss_fn=_loss)
    sess = ad.create_distributed_session()
    for _ in range(3):
        sess.run(_batch())
    w_phys = np.asarray(jax.device_get(sess.sharded_params["linear"]["w"]))
    np.testing.assert_array_equal(w_phys[7:], 0.0)


def test_indivisible_sparse_embedding_shards():
    """Parallax embeddings with vocab % mesh != 0 now shard (vocab padded)."""
    vocab = 13
    params = {"emb": {"table": jnp.ones((vocab, 4))},
              "dense": {"w": jnp.ones((4, 2))}}

    def loss(params, batch):
        h = params["emb"]["table"][batch["ids"]]
        return jnp.mean((h @ params["dense"]["w"]) ** 2)

    ad = AutoDist(strategy_builder=Parallax())
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.01), loss_fn=loss,
                   sparse_vars=["emb/table"])
    sess = ad.create_distributed_session()
    t = sess.sharded_params["emb"]["table"]
    assert t.shape == (16, 4)  # padded to 8-multiple
    assert {s.data.shape for s in t.addressable_shards} == {(2, 4)}
    ids = np.array([0, 3, 12, 7] * 4, np.int32).reshape(16)
    loss0 = sess.run({"ids": ids})["loss"]
    assert np.isfinite(loss0)
    assert sess.params["emb"]["table"].shape == (vocab, 4)


def test_checkpoint_interchange_with_padding(tmp_path):
    """A padded 8-way run checkpoints in LOGICAL layout; a plain program and
    a 2-way mesh both consume it (the reference's interchange invariant)."""
    from autodist_tpu.checkpoint import Saver

    batch = _batch()
    ad = AutoDist(strategy_builder=UnevenPartitionedPS())
    with ad.scope():
        ad.capture(params=_params7(), optimizer=optax.adam(1e-2),
                   loss_fn=_loss)
    sess = ad.create_distributed_session()
    for _ in range(2):
        sess.run(batch)
    w_after = sess.params["linear"]["w"]
    saver = Saver(sess)
    path = saver.save(str(tmp_path / "ckpt"))

    # Plain-program interchange: logical shapes on restore.
    restored = Saver.restore_params(path)
    assert restored["linear"]["w"].shape == (7, 3)
    np.testing.assert_allclose(restored["linear"]["w"], w_after, rtol=1e-6)

    # Cross-topology restore: 2-way data mesh (7 pads to 8 differently).
    _reset_default_autodist_for_testing()
    ad2 = AutoDist(strategy_builder=UnevenPartitionedPS(),
                   mesh_axes={"data": 2})
    with ad2.scope():
        ad2.capture(params=_params7(), optimizer=optax.adam(1e-2),
                    loss_fn=_loss)
    sess2 = ad2.create_distributed_session(mesh=build_mesh({"data": 2}))
    step = saver.restore(path, session=sess2)
    assert step == 2
    np.testing.assert_allclose(sess2.params["linear"]["w"], w_after,
                               rtol=1e-6)
    # Training continues identically from the restored state.
    l1 = sess.run(batch)["loss"]
    l2 = sess2.run(batch)["loss"]
    np.testing.assert_allclose(l1, l2, rtol=2e-5)


def test_divisible_vars_have_no_padding():
    params = {"linear": {"w": jnp.ones((8, 4)), "b": jnp.zeros(4)}}
    gi = GraphItem(params)
    mesh = build_mesh({"data": 8})
    cs = StrategyCompiler(mesh).compile(
        PartitionedPS().build(gi, _spec()), gi)
    assert cs.pad_plans() == {}
