"""ENV registry tests (parity: reference const.py:55-89 usage)."""
import os

from autodist_tpu.const import ENV, is_chief, is_worker


def test_env_defaults(monkeypatch):
    for name in ("AUTODIST_WORKER", "AUTODIST_IS_TESTING", "AUTODIST_NUM_PROCESSES"):
        monkeypatch.delenv(name, raising=False)
    assert ENV.AUTODIST_WORKER.val == ""
    assert ENV.AUTODIST_IS_TESTING.val is False
    assert ENV.AUTODIST_NUM_PROCESSES.val == 1
    assert ENV.AUTODIST_MIN_LOG_LEVEL.val == "INFO"
    assert is_chief() and not is_worker()


def test_env_parsing(monkeypatch):
    monkeypatch.setenv("AUTODIST_WORKER", "10.0.0.2")
    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    monkeypatch.setenv("AUTODIST_NUM_PROCESSES", "16")
    assert ENV.AUTODIST_WORKER.val == "10.0.0.2"
    assert ENV.AUTODIST_IS_TESTING.val is True
    assert ENV.AUTODIST_NUM_PROCESSES.val == 16
    assert is_worker() and not is_chief()
