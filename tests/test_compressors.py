"""Compressor + explicit sync path tests (parity: reference
kernel/synchronization/compressor.py behaviors)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.autodist import AutoDist, _reset_default_autodist_for_testing
from autodist_tpu.kernel.synchronization.compressor import get_compressor
from autodist_tpu.strategy import AllReduce


@pytest.fixture(autouse=True)
def _testing_env(monkeypatch):
    monkeypatch.setenv("AUTODIST_IS_TESTING", "True")
    _reset_default_autodist_for_testing()


def _make_problem(seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(16, 8).astype(np.float32)
    true_w = rng.randn(8, 4).astype(np.float32)
    y = (x @ true_w).astype(np.float32)
    params = {"linear": {"w": jnp.zeros((8, 4), jnp.float32),
                         "b": jnp.zeros((4,), jnp.float32)}}

    def loss_fn(params, batch):
        pred = batch["x"] @ params["linear"]["w"] + params["linear"]["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    return params, loss_fn, {"x": x, "y": y}


def _reference_losses(params, loss_fn, batch, lr, steps):
    opt = optax.sgd(lr)
    state = opt.init(params)
    losses = []
    for _ in range(steps):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, state = opt.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        losses.append(float(loss))
    return params, losses


def _run_with_compressor(name, steps=5, lr=0.1):
    params, loss_fn, batch = _make_problem()
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=AllReduce(compressor=name))
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(lr), loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    losses = [float(sess.run(batch)["loss"]) for _ in range(steps)]
    return sess, losses


def test_unknown_compressor_rejected():
    with pytest.raises(ValueError):
        get_compressor("BogusCompressor")


def test_none_compressor_exact():
    """Explicit shard_map path with identity compression must match the
    single-device loop exactly — validates the manual pmean plumbing."""
    params, loss_fn, batch = _make_problem()
    _, ref_losses = _reference_losses(params, loss_fn, batch, 0.1, 5)
    # Force the explicit path by building with a real compressor var plan,
    # but identity: use HorovodCompressor on a separate assertion below;
    # here we check the GSPMD path against itself via NoneCompressor.
    sess, losses = _run_with_compressor("NoneCompressor")
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)


def test_explicit_path_exact_gradient_scale():
    """The EXPLICIT shard_map path must match the single-device loop
    exactly when no lossy compression is involved (fused NoneCompressor
    groups).  Any divergence means the gradient collective is mis-scaled —
    e.g. jax's vma transpose psum double-reducing ahead of the manual pmean
    (a real bug check_vma=False guards against).  The bf16-wire compressor
    variant is held to a loose tolerance."""
    params, loss_fn, batch = _make_problem()
    _, ref_losses = _reference_losses(params, loss_fn, batch, 0.1, 5)

    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=AllReduce(chunk_size=2, fused_groups=True))
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    from autodist_tpu.kernel.synchronization import explicit_sync

    assert explicit_sync.uses_explicit_path(sess._step.compiled_strategy)
    losses = [float(sess.run(batch)["loss"]) for _ in range(5)]
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)

    # bf16 wire: per-step losses track the reference to cast precision.
    _, h_losses = _run_with_compressor("HorovodCompressor")
    np.testing.assert_allclose(h_losses, ref_losses, rtol=5e-3)


@pytest.mark.parametrize("comp", ["HorovodCompressor", "HorovodCompressorEF"])
def test_cast_compressors_converge(comp):
    sess, losses = _run_with_compressor(comp, steps=60)
    # bf16 wire: not bit-exact, but must converge on least squares
    assert losses[-1] < losses[0] * 0.05, losses


def test_error_feedback_beats_plain_cast():
    _, plain = _run_with_compressor("HorovodCompressor", steps=30)
    _, ef = _run_with_compressor("HorovodCompressorEF", steps=30)
    # error feedback should not be (meaningfully) worse
    assert ef[-1] <= plain[-1] * 1.5


def test_powersgd_converges():
    sess, losses = _run_with_compressor("PowerSGDCompressor", steps=60)
    assert losses[-1] < losses[0] * 0.2, losses
    # sync state carries per-var factors
    assert any("w" in k for k in ("linear/w",))


def test_compressor_units():
    """Direct unit semantics of cast + EF compressors via shard_map."""
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))

    g_local = np.linspace(-1, 1, 8 * 4).reshape(8, 4).astype(np.float32)

    def f(g):
        comp = get_compressor("NoneCompressor")
        out, _ = comp.reduce(g, None, "data")
        return out

    out = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=jax.sharding.PartitionSpec("data"),
        out_specs=jax.sharding.PartitionSpec()))(g_local)
    np.testing.assert_allclose(np.asarray(out), g_local.mean(0, keepdims=True),
                               rtol=1e-6)


def test_compressor_on_modelonly_mesh_falls_back():
    """No data axis → nothing to compress → GSPMD path, no crash."""
    params, loss_fn, batch = _make_problem()
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=AllReduce(compressor="HorovodCompressorEF"),
                  mesh_axes={"model": 8})
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    ref_losses = _reference_losses(params, loss_fn, batch, 0.1, 3)[1]
    losses = [float(sess.run(batch)["loss"]) for _ in range(3)]
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)


def test_int8_compressor_unit_semantics():
    """Exact on per-chunk grid values, and the WIRE collectives are int8:
    the jitted program's all_to_all/all_gather operate on i8 tensors (no
    int8-typed psum/all-reduce fallback).

    Grid-exact fixture for the per-chunk scale rule (quant_ring): every
    device contributes ``c_d * v`` where ``v`` is integer-valued with
    each scale block's amax pinned at 127 — every quantize event (stage
    1 on ``c_d * v``, stage 2 on ``sum(c) * v``) then lands exactly on
    its block grid, so the quantized mean equals the true mean."""
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    comp = get_compressor("Int8Compressor")

    rng = np.random.RandomState(0)
    n, per_dev = 8, 128
    chunk = per_dev // n                 # the all_to_all chunk length
    v = rng.randint(-126, 127, per_dev).astype(np.float32)
    v[::chunk] = 127.0                   # every block's amax on the rail
    c = (2.0 ** rng.randint(-2, 3, n)).astype(np.float32)
    g_local = c[:, None] * v[None, :]

    f = jax.jit(jax.shard_map(
        lambda g: comp.reduce(g, jnp.zeros_like(g), "data")[0],
        mesh=mesh, in_specs=jax.sharding.PartitionSpec("data"),
        out_specs=jax.sharding.PartitionSpec(), check_vma=False))
    out = f(g_local)
    np.testing.assert_allclose(np.asarray(out),
                               g_local.mean(0, keepdims=True), atol=1e-5)
    txt = f.lower(g_local).as_text()
    assert "all_to_all" in txt and "i8" in txt  # int8 is on the wire


def test_int8_error_feedback_carries_quantization_error():
    comp = get_compressor("Int8Compressor")
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    # Off-grid interior values: a 1.0 in every scale chunk sets that
    # chunk's grid (the all_to_all chunk is 64/8 = 8 elements, under the
    # 256-element scale block); 0.3 lies between steps (scale = 1/127,
    # 0.3*127 = 38.1) -> genuine quantization error.
    g_local = np.full((8, 64), 0.3, np.float32)
    g_local[:, ::8] = 1.0

    out, st = jax.jit(jax.shard_map(
        lambda g: comp.reduce(g, jnp.zeros_like(g), "data"),
        mesh=mesh, in_specs=jax.sharding.PartitionSpec("data"),
        out_specs=(jax.sharding.PartitionSpec(),
                   jax.sharding.PartitionSpec("data")),
        check_vma=False))(g_local)
    st = np.asarray(st)
    interior = np.ones(64, bool)
    interior[::8] = False    # the 1.0 grid sentinels quantize exactly
    # residual ~ distance to the nearest grid point (|0.3 - 38/127| ~ 8e-4)
    assert 1e-4 < np.abs(st[:, interior]).max() < 1.0 / 127
    np.testing.assert_allclose(np.asarray(out)[:, interior], 0.3, rtol=2e-2)
    np.testing.assert_allclose(np.asarray(out)[:, ~interior], 1.0, rtol=2e-2)


def test_int8_compressor_converges():
    sess, losses = _run_with_compressor("Int8Compressor", steps=60)
    assert losses[-1] < losses[0] * 0.05, losses


def test_partitioned_vars_compose_with_compressor():
    """PartitionedAR + compressor keeps its partitioning (VERDICT r4 #6;
    reference-expressible config, proto/synchronizers.proto:24-57): on a
    (data x model) mesh the partitioned var stays MODEL-SHARDED outside
    the explicit step while its data-axis reduction is compressed
    per-shard.  bf16 cast and EF are elementwise, so per-shard
    compression equals whole-tensor compression: losses must match the
    replicated compressor run to float tolerance."""
    from autodist_tpu.kernel.synchronization import explicit_sync
    from autodist_tpu.strategy import PartitionedAR

    params, loss_fn, batch = _make_problem()

    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=PartitionedAR(
        chunk_size=1, compressor="HorovodCompressorEF"),
        mesh_axes={"data": 4, "model": 2})
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    assert explicit_sync.uses_explicit_path(sess._step.compiled_strategy)

    # the partitioned var is REALLY sharded over the model axis
    w = sess.sharded_params["linear"]["w"]
    w_spec = w.sharding.spec
    assert any("model" in (e if isinstance(e, tuple) else (e,))
               for e in w_spec if e is not None), w_spec
    # ...and so are its param-shaped optimizer slots (sgd has none, but
    # sync residuals exist for EF): residual sharded over data x model
    sync = sess.sync_state
    res_spec = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda x: x.sharding.spec, sync))[0]
    flat = []
    for e in res_spec:
        flat.extend(e if isinstance(e, tuple) else (e,))
    assert "data" in flat and "model" in flat, res_spec

    losses = [float(sess.run(batch)["loss"]) for _ in range(20)]

    # Oracle: same compressor, same (data x model) mesh, REPLICATED
    # params (AllReduce) — identical local grads and identical bf16
    # rounding, so per-shard compression must reproduce whole-tensor
    # compression to float tolerance.
    _reset_default_autodist_for_testing()
    ad2 = AutoDist(strategy_builder=AllReduce(
        compressor="HorovodCompressorEF"),
        mesh_axes={"data": 4, "model": 2})
    with ad2.scope():
        ad2.capture(params=params, optimizer=optax.sgd(0.1),
                    loss_fn=loss_fn)
    sess2 = ad2.create_distributed_session()
    repl_losses = [float(sess2.run(batch)["loss"]) for _ in range(20)]
    np.testing.assert_allclose(losses, repl_losses, rtol=1e-4)
    assert losses[-1] < losses[0] * 0.25


def test_partitioned_powersgd_falls_back_to_replication():
    """PowerSGD state is not grad-shaped: a partitioned var under it
    replicates (warned) but still trains correctly."""
    from autodist_tpu.strategy import PartitionedAR

    params, loss_fn, batch = _make_problem()
    _reset_default_autodist_for_testing()
    ad = AutoDist(strategy_builder=PartitionedAR(
        chunk_size=1, compressor="PowerSGDCompressor"),
        mesh_axes={"data": 4, "model": 2})
    with ad.scope():
        ad.capture(params=params, optimizer=optax.sgd(0.1), loss_fn=loss_fn)
    sess = ad.create_distributed_session()
    losses = [float(sess.run(batch)["loss"]) for _ in range(40)]
    assert losses[-1] < losses[0] * 0.3, losses
