"""Cluster: the multi-host process fabric.

TPU-native replacement for the reference's gRPC-server mesh
(``autodist/cluster.py:51-374`` + ``autodist/utils/server_starter.py:29-125``).
The reference had to *run a server per node* because TF sessions talk to a
gRPC cluster; JAX processes instead rendezvous through the PJRT distributed
runtime — so ``Cluster.start()`` here does not spawn servers, it initializes
``jax.distributed`` on the local process and remembers how workers must be
told to do the same (coordinator address, process count/ids).

What carries over from the reference design:

* ``Cluster`` abstract / ``SSHCluster`` concrete split (``cluster.py:51,271``);
* remote_exec / remote_copy / remote_file_write primitives — here via
  ``ssh``/``scp`` subprocesses built from the ResourceSpec's SSHConfig
  (the reference used paramiko, ``cluster.py:271-374``);
* ``AUTODIST_DEBUG_REMOTE`` prints commands instead of executing them
  (``cluster.py:340-341``);
* ``terminate()`` kills every launched process group at exit
  (``cluster.py:176, 212-216``).

A ``TPUPodCluster`` subclass covers Cloud-TPU pod slices where the runtime
performs its own topology discovery: ``jax.distributed.initialize()`` with no
arguments reads the TPU metadata, so no per-node bootstrap is needed at all —
only the script fan-out (done by the Coordinator).
"""
from __future__ import annotations

import atexit
import os
import shlex
import signal
import subprocess
from typing import Dict, List, Optional

from autodist_tpu.const import ENV
from autodist_tpu.resilience.backoff import Backoff
from autodist_tpu.resource_spec import ResourceSpec, SSHConfig
from autodist_tpu.utils import logging
from autodist_tpu.utils.network import is_local_address

# Port for the PJRT coordination service on the chief, from the reference's
# 15000-16000 server port range (autodist/const.py:38).
DEFAULT_COORDINATOR_PORT = 15000

# Transient-failure schedule for the ssh/scp primitives: an SSH flake or
# connection reset during fan-out should not kill a pod-sized launch.
# Shares the supervisor's backoff helper (resilience/backoff.py) so every
# retry in the stack follows one tested rule.
DEFAULT_REMOTE_RETRY = Backoff(max_tries=3, base=0.5, cap=10.0)


class Cluster:
    """Process fabric over the nodes of a ResourceSpec."""

    def __init__(self, resource_spec: ResourceSpec,
                 remote_retry: Optional[Backoff] = None):
        self._spec = resource_spec
        self._subprocesses: List[subprocess.Popen] = []
        self._started = False
        self._retry = remote_retry or DEFAULT_REMOTE_RETRY
        atexit.register(self.terminate)

    # -- identity ----------------------------------------------------------
    @property
    def resource_spec(self) -> ResourceSpec:
        return self._spec

    @property
    def chief_address(self) -> str:
        return self._spec.chief

    @property
    def coordinator_address(self) -> str:
        """``host:port`` of the PJRT coordination service (on the chief)."""
        env_addr = ENV.AUTODIST_COORDINATOR_ADDRESS.val
        if env_addr:
            return env_addr
        return f"{self.chief_address}:{DEFAULT_COORDINATOR_PORT}"

    @property
    def num_processes(self) -> int:
        """One JAX process per node (TPU-VM worker host)."""
        n = ENV.AUTODIST_NUM_PROCESSES.val
        if n > 1:
            return n
        return self._spec.num_nodes

    def process_id_for(self, address: str) -> int:
        """Deterministic process id: chief is 0, others in spec order
        (parity with the reference's task-index assignment,
        ``cluster.py:54-68``)."""
        ordered = [self.chief_address] + [
            n.address for n in self._spec.nodes if n.address != self.chief_address
        ]
        return ordered.index(address)

    @property
    def local_process_id(self) -> int:
        # Prefer the id the chief shipped explicitly — it is authoritative
        # even if this process reconstructs the ResourceSpec with a
        # different node ordering.
        if ENV.AUTODIST_WORKER.val:
            pid = os.environ.get(ENV.AUTODIST_PROCESS_ID.name)
            if pid is not None:
                return int(pid)
            return self.process_id_for(ENV.AUTODIST_WORKER.val)
        return 0

    def is_chief(self, address: Optional[str] = None) -> bool:
        if address is None:
            return not bool(ENV.AUTODIST_WORKER.val)
        return address == self.chief_address

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Join the distributed runtime.

        Single-node: no-op (one process owns all chips).  Multi-node: call
        ``jax.distributed.initialize(coordinator, num, pid)`` — the TPU-native
        analog of starting/connecting to the gRPC server mesh
        (``cluster.py:160-210``).  Idempotent.
        """
        if self._started:
            return
        self._started = True
        if self.num_processes <= 1:
            logging.debug("Cluster.start: single process, nothing to do")
            return
        if ENV.AUTODIST_DEBUG_REMOTE.val:
            logging.info(
                "DEBUG_REMOTE: would jax.distributed.initialize(%s, %d, %d)",
                self.coordinator_address, self.num_processes,
                self.local_process_id)
            return
        import jax

        try:
            jax.distributed.initialize(
                coordinator_address=self.coordinator_address,
                num_processes=self.num_processes,
                process_id=self.local_process_id,
            )
        except RuntimeError as e:
            # Most common cause: the local backend was already used (e.g.
            # params built as jax arrays before create_distributed_session).
            raise RuntimeError(
                "jax.distributed.initialize failed — on multi-node specs, "
                "build params as numpy arrays (or call "
                "AutoDist.cluster.start() first) so no JAX computation runs "
                f"before the distributed runtime is up: {e}") from e
        logging.info("jax.distributed initialized: process %d/%d via %s",
                     self.local_process_id, self.num_processes,
                     self.coordinator_address)

    def terminate(self) -> None:
        """Kill every process group this cluster launched
        (reference ``cluster.py:212-216``)."""
        for proc in self._subprocesses:
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError, OSError):
                    proc.terminate()
        self._subprocesses = []

    # -- remote primitives -------------------------------------------------
    def _ssh_base(self, address: str) -> List[str]:
        conf = self._spec.ssh_config_for(address) or SSHConfig()
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no",
               "-o", "BatchMode=yes", "-p", str(conf.port)]
        if conf.key_file:
            cmd += ["-i", os.path.expanduser(conf.key_file)]
        target = f"{conf.username}@{address}" if conf.username else address
        return cmd + [target]

    def _scp_base(self, address: str, remote_path: str) -> List[str]:
        conf = self._spec.ssh_config_for(address) or SSHConfig()
        cmd = ["scp", "-o", "StrictHostKeyChecking=no",
               "-o", "BatchMode=yes", "-P", str(conf.port)]
        if conf.key_file:
            cmd += ["-i", os.path.expanduser(conf.key_file)]
        target = (f"{conf.username}@{address}" if conf.username else address)
        return cmd + ["__SRC__", f"{target}:{remote_path}"]

    def remote_exec(self, args: List[str], address: str,
                    env: Optional[Dict[str, str]] = None) -> Optional[subprocess.Popen]:
        """Run a command on ``address`` (reference ``cluster.py:304-341``).

        Local addresses run via the shell directly; remote ones through ssh.
        Returns the Popen handle, or None under ``AUTODIST_DEBUG_REMOTE``.
        """
        conf = self._spec.ssh_config_for(address) or SSHConfig()
        env = {**conf.env, **(env or {})}
        env_prefix = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        inner = " ".join(shlex.quote(a) for a in args)
        # Env assignments must prefix the *command*, after any venv
        # activation — `FOO=bar source venv; cmd` drops FOO before cmd runs.
        if env_prefix:
            inner = f"{env_prefix} {inner}"
        if conf.python_venv:
            inner = f"{conf.python_venv}; {inner}"

        if is_local_address(address):
            full = ["bash", "-c", inner]
        else:
            full = self._ssh_base(address) + [inner]

        if ENV.AUTODIST_DEBUG_REMOTE.val:
            logging.info("DEBUG_REMOTE exec on %s: %s", address, inner)
            return None
        logging.debug("remote_exec on %s: %s", address, inner)
        # Only the SPAWN can be retried here (fork/exec resource errors);
        # an ssh session dying later surfaces through the coordinator's
        # watcher, not this call.
        proc = self._retry.retry(
            lambda: subprocess.Popen(full, start_new_session=True,
                                     stdout=None, stderr=None),
            retryable=(OSError,), label=f"remote_exec {address}")
        self._subprocesses.append(proc)
        return proc

    def remote_copy(self, local_path: str, remote_path: str,
                    address: str) -> None:
        """Copy a file to ``address`` (reference ``cluster.py:343-360``)."""
        if ENV.AUTODIST_DEBUG_REMOTE.val:
            logging.info("DEBUG_REMOTE copy %s -> %s:%s", local_path, address,
                         remote_path)
            return
        if is_local_address(address):
            if os.path.abspath(local_path) != os.path.abspath(remote_path):
                os.makedirs(os.path.dirname(remote_path) or ".", exist_ok=True)
                import shutil

                shutil.copy(local_path, remote_path)
            return
        mkdir = self._ssh_base(address) + [
            f"mkdir -p {shlex.quote(os.path.dirname(remote_path) or '.')}"]
        scp = [local_path if a == "__SRC__" else a
               for a in self._scp_base(address, remote_path)]

        def _copy():
            subprocess.run(mkdir, check=True)
            subprocess.run(scp, check=True)

        # SSH flakes / connection resets are transient; retry the whole
        # mkdir+scp unit (idempotent) with backoff, logging each attempt.
        self._retry.retry(
            _copy, retryable=(subprocess.CalledProcessError, OSError),
            label=f"remote_copy {local_path} -> {address}:{remote_path}")

    def remote_fetch(self, remote_path: str, local_path: str,
                     address: str) -> None:
        """Copy a file FROM ``address`` — the inverse of
        :meth:`remote_copy`, added for the peer checkpoint tier: a
        replaced host pulls its mirrored RAM snapshot from the buddy
        that survived (``checkpoint/tiers.py``).  Same retry schedule
        as the push side."""
        if ENV.AUTODIST_DEBUG_REMOTE.val:
            logging.info("DEBUG_REMOTE fetch %s:%s -> %s", address,
                         remote_path, local_path)
            return
        if is_local_address(address):
            if os.path.abspath(local_path) != os.path.abspath(remote_path):
                os.makedirs(os.path.dirname(local_path) or ".",
                            exist_ok=True)
                import shutil

                shutil.copy(remote_path, local_path)
            return
        conf = self._spec.ssh_config_for(address) or SSHConfig()
        cmd = ["scp", "-o", "StrictHostKeyChecking=no",
               "-o", "BatchMode=yes", "-P", str(conf.port)]
        if conf.key_file:
            cmd += ["-i", os.path.expanduser(conf.key_file)]
        target = (f"{conf.username}@{address}" if conf.username else address)
        scp = cmd + [f"{target}:{remote_path}", local_path]

        def _fetch():
            os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
            subprocess.run(scp, check=True)

        self._retry.retry(
            _fetch, retryable=(subprocess.CalledProcessError, OSError),
            label=f"remote_fetch {address}:{remote_path} -> {local_path}")

    def remote_file_write(self, remote_path: str, data: str,
                          address: str) -> None:
        """Write ``data`` into a file on ``address``
        (reference ``cluster.py:362-374``)."""
        if ENV.AUTODIST_DEBUG_REMOTE.val:
            logging.info("DEBUG_REMOTE write %d bytes -> %s:%s", len(data),
                         address, remote_path)
            return
        if is_local_address(address):
            os.makedirs(os.path.dirname(remote_path) or ".", exist_ok=True)
            with open(remote_path, "w") as f:
                f.write(data)
            return
        cmd = self._ssh_base(address) + [
            f"mkdir -p {shlex.quote(os.path.dirname(remote_path) or '.')} && "
            f"cat > {shlex.quote(remote_path)}"]
        self._retry.retry(
            lambda: subprocess.run(cmd, input=data.encode(), check=True),
            retryable=(subprocess.CalledProcessError, OSError),
            label=f"remote_file_write {address}:{remote_path}")


class SSHCluster(Cluster):
    """Cluster over plain SSH-reachable TPU-VM hosts — the direct analog of
    the reference's ``SSHCluster`` (``cluster.py:271-276``)."""


class TPUPodCluster(Cluster):
    """Cloud-TPU pod slice: the runtime discovers topology from TPU metadata,
    so ``jax.distributed.initialize()`` needs no arguments."""

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        if ENV.AUTODIST_DEBUG_REMOTE.val:
            logging.info("DEBUG_REMOTE: would jax.distributed.initialize()")
            return
        import jax

        jax.distributed.initialize()
        logging.info("jax.distributed initialized from TPU metadata: "
                     "process %d/%d", jax.process_index(), jax.process_count())


def make_cluster(resource_spec: ResourceSpec) -> Cluster:
    """Choose the cluster flavor for a spec: TPU-pod metadata discovery when
    requested via env, SSH fan-out otherwise."""
    if ENV.AUTODIST_TPU_POD.val:
        return TPUPodCluster(resource_spec)
    return SSHCluster(resource_spec)
