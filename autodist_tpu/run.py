"""``python -m autodist_tpu.run`` — the multi-host launcher CLI.

The reference's execution model re-runs the SAME user script on every
worker host (``autodist/coordinator.py:46-90``); the chief-side
:class:`~autodist_tpu.autodist.AutoDist` already performs that fan-out at
``create_distributed_session``.  What the launcher adds is the missing
front door (SURVEY §2.9: an "``ad run``-style launcher"): it binds a
resource spec to an UNMODIFIED training script and executes it as the
chief, so

    python -m autodist_tpu.run -r pod.yml train.py --epochs 3

distributes a script whose only framework code is ``AutoDist()`` +
``scope()`` (or nothing at all beyond plain optax, with implicit capture).
The spec path rides the reference's own ``SYS_RESOURCE_PATH`` env
(``autodist/const.py:55-89``), consumed by a bare ``ResourceSpec()``.
"""
from __future__ import annotations

import argparse
import os
import runpy
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m autodist_tpu.run",
        description="Run a training script under autodist_tpu: the script "
                    "executes as the chief; worker hosts are launched "
                    "automatically when the resource spec has them.")
    parser.add_argument("-r", "--resource-spec", metavar="YAML",
                        help="cluster resource spec (omit for single-host "
                             "auto-derivation from local devices)")
    parser.add_argument("--tpu-pod", action="store_true",
                        help="Cloud-TPU pod slice: rendezvous via TPU "
                             "metadata (jax.distributed.initialize() "
                             "without arguments)")
    parser.add_argument("--debug-remote", action="store_true",
                        help="print worker launch commands instead of "
                             "executing them (AUTODIST_DEBUG_REMOTE)")
    parser.add_argument("script", help="training script to run")
    parser.add_argument("script_args", nargs=argparse.REMAINDER,
                        help="arguments passed to the script")
    args = parser.parse_args(argv)

    from autodist_tpu.const import ENV

    if args.resource_spec:
        path = os.path.abspath(args.resource_spec)
        if not os.path.exists(path):
            parser.error(f"resource spec not found: {path}")
        os.environ[ENV.SYS_RESOURCE_PATH.name] = path
    if args.tpu_pod:
        os.environ[ENV.AUTODIST_TPU_POD.name] = "1"
    if args.debug_remote:
        os.environ[ENV.AUTODIST_DEBUG_REMOTE.name] = "True"

    script = os.path.abspath(args.script)
    if not os.path.exists(script):
        parser.error(f"script not found: {script}")
    # The Coordinator re-launches `sys.argv` on workers; make argv[0] the
    # SCRIPT (workers re-enter through plain `python script.py`, with env
    # carrying worker identity + the shipped spec path).
    sys.argv = [script] + list(args.script_args)
    runpy.run_path(script, run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(main())
