"""Schedule-aware flight recorder: progress cursors, hang localization,
crash bundles (docs/observability.md "Flight recorder").

The heartbeat monitor (resilience/heartbeat.py) can classify a worker
as WEDGED-in-a-collective, but not say *where* — yet the schedule IR
(docs/schedule-ir.md) plus the happens-before closure
(analysis/dataflow.py) describe exactly which leg each host should be
in and who blocks whom.  This module is the always-on black box that
turns DEAD/WEDGED/crash verdicts into localized diagnoses:

* **Progress cursors** — each process stamps :class:`Cursor`\\ s
  (schedule fingerprint, leg id, microbatch slot, monotonic timestamp)
  into a lock-free in-process :class:`CursorRing`.  The host loop
  stamps step/checkpoint phase cursors (near-zero cost: one object +
  one list store per stamp); under ``AUTODIST_FLIGHTREC=legs`` (the
  automatic choice on TPU backends) the explicit sync path additionally
  stamps leg-group boundaries from inside the traced step via
  :func:`traced_stamp` host callbacks.  The latest cursor rides the
  existing heartbeat beacon (:func:`beacon_cursor`), so the chief sees
  per-host cursors without any new transport.
* **Hang localization** — :func:`localize_hang` diffs per-host cursors
  against the IR's happens-before relation (the packed-bitset closure
  from :mod:`autodist_tpu.analysis.dataflow` when importable, a pure
  ancestor-set fallback on jax-free hosts) and names the frontier
  leg(s) and the culprit host(s) — the host whose unentered leg is a
  dependency of everyone else's blocked collective.  The supervisor
  emits the diagnosis as a ``flightrec/hang`` journal event.
* **Crash bundles** — :func:`dump_bundle` snapshots the event-journal
  tail, StepRecord tail, per-host cursor rings, all-thread
  faulthandler stacks, the published schedule IR + fingerprint, and
  the monitor verdicts into one ``bundle-<ts>/`` directory; the
  supervisor attaches the bundle path to every attempt failure, and
  :func:`install_fatal_handlers` arms faulthandler + an excepthook
  bundle for fatal signals and uncaught crashes.  ``python -m
  autodist_tpu.telemetry --hang-report <bundle>`` renders one.

Everything here imports without jax (the CLI contract); the traced
stamp helpers import jax lazily at call time only.
"""
from __future__ import annotations

import faulthandler
import glob
import json
import os
import shutil
import socket
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: default cursor-ring capacity (cursors kept per process).
CURSOR_RING_SIZE = 512
#: microbatch-slot value for end-of-step (non-pipelined) cursors —
#: mirrors schedule_ir.END_OF_STEP without importing it (jax-free).
END_OF_STEP = -1
#: journal event kind carrying a hang diagnosis.
EVENT_HANG = "flightrec/hang"
#: crash-bundle directory prefix under the run directory.
BUNDLE_PREFIX = "bundle-"

_CURSOR_KINDS = ("leg", "phase")


def _host() -> str:
    return socket.gethostname().replace("/", "_").replace(":", "_")


@dataclass
class Cursor:
    """One progress stamp: where this process was, when.

    ``leg`` is a schedule-IR leg id for ``kind="leg"`` cursors (the
    runtime-path stamps and chaos-planted wedges) or a host-phase name
    (``"step"``, ``"checkpoint/save"``) for ``kind="phase"``.
    ``t_mono`` is the process monotonic clock — ages computed by the
    SAME process (the beacon writer) are exact; ``t_unix`` is advisory
    wall time for cross-host display only."""

    leg: str
    kind: str = "leg"
    leg_kind: str = ""              # IR leg kind when known (all_reduce, ...)
    slot: int = END_OF_STEP
    event: str = "enter"            # enter | exit
    step: Optional[int] = None
    fingerprint: Optional[str] = None
    t_mono: float = 0.0
    t_unix: float = 0.0
    seq: int = 0

    def to_dict(self) -> dict:
        d = {"leg": self.leg, "kind": self.kind, "slot": int(self.slot),
             "event": self.event, "t_mono": self.t_mono,
             "t_unix": self.t_unix, "seq": int(self.seq)}
        if self.leg_kind:
            d["leg_kind"] = self.leg_kind
        if self.step is not None:
            d["step"] = int(self.step)
        if self.fingerprint:
            d["fingerprint"] = self.fingerprint
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Cursor":
        known = set(cls.__dataclass_fields__)
        return cls(**{k: v for k, v in d.items() if k in known})


class CursorRing:
    """Lock-free in-process cursor ring.

    ``record`` is one attribute store + one list store under the GIL —
    no lock, no allocation beyond the cursor itself — so it is safe to
    call from the training loop, from heartbeat daemon threads, and
    from jax host callbacks concurrently.  Overwrite semantics: the
    ring keeps the most recent ``capacity`` cursors; ``cursors()``
    returns them oldest-first."""

    def __init__(self, capacity: int = CURSOR_RING_SIZE):
        self._cap = max(int(capacity), 1)
        self._buf: List[Optional[Cursor]] = [None] * self._cap
        self._seq = 0

    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def seq(self) -> int:
        """Total cursors ever recorded (monotone)."""
        return self._seq

    def record(self, cur: Cursor) -> Cursor:
        seq = self._seq
        cur.seq = seq
        self._buf[seq % self._cap] = cur
        self._seq = seq + 1
        return cur

    def latest(self) -> Optional[Cursor]:
        seq = self._seq
        return self._buf[(seq - 1) % self._cap] if seq else None

    def cursors(self) -> List[Cursor]:
        """Oldest-first view of the retained cursors."""
        seq = self._seq
        if seq <= self._cap:
            return [c for c in self._buf[:seq] if c is not None]
        start = seq % self._cap
        out = self._buf[start:] + self._buf[:start]
        return [c for c in out if c is not None]

    def clear(self) -> None:
        self._buf = [None] * self._cap
        self._seq = 0

    def dump(self, path: str) -> Optional[str]:
        """Write the retained cursors as JSONL (never raises)."""
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                for c in self.cursors():
                    f.write(json.dumps(c.to_dict()) + "\n")
            return path
        except OSError:
            return None


# -- the process recorder ----------------------------------------------------

_ring = CursorRing()
_fingerprint: Optional[str] = None


def ring() -> CursorRing:
    return _ring


def set_fingerprint(fp: Optional[str]) -> None:
    """Stamp the active schedule fingerprint onto subsequent cursors
    (set once per session build)."""
    global _fingerprint
    _fingerprint = fp


def enabled() -> bool:
    """Recording is on unless telemetry is off or
    ``AUTODIST_FLIGHTREC=0``."""
    try:
        from autodist_tpu.const import ENV
        from autodist_tpu.telemetry.registry import telemetry_enabled

        if not telemetry_enabled():
            return False
        return (ENV.AUTODIST_FLIGHTREC.val or "").strip() != "0"
    except Exception:  # pragma: no cover - defensive
        return False


def record_cursor(leg: str, *, kind: str = "leg", leg_kind: str = "",
                  slot: int = END_OF_STEP, event: str = "enter",
                  step: Optional[int] = None) -> Optional[Cursor]:
    """Stamp one cursor into the process ring (no-op when disabled;
    never raises — the recorder must not kill training)."""
    try:
        if not enabled():
            return None
        return _ring.record(Cursor(
            leg=str(leg), kind=kind, leg_kind=leg_kind, slot=int(slot),
            event=event, step=step, fingerprint=_fingerprint,
            t_mono=time.monotonic(), t_unix=time.time()))
    except Exception:  # pragma: no cover - defensive
        return None


def latest_cursor() -> Optional[Cursor]:
    return _ring.latest()


def beacon_cursor() -> Optional[dict]:
    """The latest cursor as a beacon-sized dict with its age computed
    on THIS process's monotonic clock (``age_s``) — what heartbeat
    beacons carry so the monitor sees per-host progress without new
    transport.  Also refreshes the
    ``autodist_flightrec_cursor_age_seconds`` gauge."""
    cur = _ring.latest()
    if cur is None:
        return None
    age = max(time.monotonic() - cur.t_mono, 0.0)
    try:
        from autodist_tpu.telemetry.registry import gauge

        gauge("autodist_flightrec_cursor_age_seconds",
              "seconds since this process stamped a flight-recorder "
              "cursor").set(age)
    except Exception:  # pragma: no cover - defensive
        pass
    out = cur.to_dict()
    out["age_s"] = round(age, 3)
    return out


def cursor_line(cursor: Optional[dict],
                extra_age_s: float = 0.0) -> str:
    """Human rendering of a beacon cursor dict: ``"in
    ring_reduce_scatter leg rs:f32:0 slot 2 for 41 s"`` ('' when
    absent).  ``extra_age_s`` adds the beacon's own age (the cursor's
    ``age_s`` was computed when the beacon was written)."""
    if not cursor or not cursor.get("leg"):
        return ""
    age = float(cursor.get("age_s") or 0.0) + max(extra_age_s, 0.0)
    if cursor.get("kind") == "phase":
        head = f"in phase {cursor['leg']}"
    else:
        lk = cursor.get("leg_kind") or ""
        head = (f"in {lk} leg {cursor['leg']}" if lk
                else f"in leg {cursor['leg']}")
    slot = cursor.get("slot")
    if slot is not None and int(slot) >= 0:
        head += f" slot {int(slot)}"
    if cursor.get("step") is not None:
        head += f" (step {int(cursor['step'])})"
    return head + f" for {age:.0f} s"


def dump_cursors(directory: str) -> Optional[str]:
    """Flush this process's ring as ``cursors-<host>-<pid>.jsonl``
    under ``directory`` (the per-host half of a crash bundle)."""
    if not directory:
        return None
    return _ring.dump(os.path.join(
        directory, f"cursors-{_host()}-{os.getpid()}.jsonl"))


def load_cursors(path: str) -> List[Cursor]:
    out: List[Cursor] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(Cursor.from_dict(json.loads(line)))
                except (ValueError, TypeError):
                    continue
    except OSError:
        pass
    return out


def reset_for_testing() -> None:
    global _fingerprint
    _ring.clear()
    _fingerprint = None


# -- traced stamps (the runtime-path half) -----------------------------------

def trace_stamps_enabled() -> bool:
    """Should the explicit sync path compile leg-boundary host
    callbacks into the step?  ``AUTODIST_FLIGHTREC=legs`` forces on,
    ``host`` forces off; the default (``auto``) enables them only on
    TPU backends, where the callback rides async dispatch instead of
    serializing a CPU step (BENCH_flightrec.json measures both)."""
    if not enabled():
        return False
    try:
        from autodist_tpu.const import ENV

        mode = (ENV.AUTODIST_FLIGHTREC.val or "auto").strip().lower()
    except Exception:  # pragma: no cover - defensive
        return False
    if mode in ("legs", "trace"):
        return True
    if mode in ("host", "1", "on"):
        return False
    try:   # auto
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def traced_stamp(leg: str, *, slot: Any = None, leg_kind: str = "") -> None:
    """Stamp a leg-boundary cursor from INSIDE a traced program via a
    host callback.  ``slot`` may be a traced integer (the pipelined
    microbatch index) — ``leg`` may then contain a ``{slot}``
    placeholder resolved when the callback fires, so per-slot leg ids
    stay exact.  Call sites gate on :func:`trace_stamps_enabled` at
    build time; the stamp itself never raises."""
    import jax

    if slot is None:
        jax.debug.callback(
            lambda _leg=leg, _lk=leg_kind: record_cursor(_leg, leg_kind=_lk))
    else:
        jax.debug.callback(
            lambda s, _leg=leg, _lk=leg_kind: record_cursor(
                _leg.format(slot=int(s)) if "{slot}" in _leg else _leg,
                slot=int(s), leg_kind=_lk),
            slot)


# -- schedule-IR publication -------------------------------------------------

def publish_ir(ir, directory: str) -> Optional[str]:
    """Write the session's schedule IR as ``schedule-<fp>.json`` under
    the run directory (once per fingerprint), so the chief — a separate
    process — can localize hangs against the exact program the workers
    lowered.  ``ir`` needs ``fingerprint()`` + ``to_json()``; never
    raises."""
    try:
        if not directory:
            return None
        fp = ir.fingerprint()
        path = os.path.join(directory, f"schedule-{fp}.json")
        if os.path.exists(path):
            return path
        os.makedirs(directory, exist_ok=True)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(ir.to_json())
        os.replace(tmp, path)
        return path
    except Exception:  # pragma: no cover - advisory
        return None


def load_published_ir(run_dir: str,
                      fingerprint: Optional[str] = None) -> Optional[dict]:
    """The newest published ``schedule-*.json`` under ``run_dir``
    (recursive) as a raw dict — jax-free, so the CLI can localize."""
    pattern = f"schedule-{fingerprint}.json" if fingerprint \
        else "schedule-*.json"
    paths = glob.glob(os.path.join(run_dir, "**", pattern), recursive=True)
    for path in sorted(paths, key=lambda p: os.path.getmtime(p),
                       reverse=True):
        try:
            with open(path, "r", encoding="utf-8") as f:
                d = json.load(f)
            if isinstance(d, dict) and d.get("legs"):
                return d
        except (OSError, ValueError):
            continue
    return None


# -- hang localization -------------------------------------------------------

class _LegView:
    """Minimal leg adapter (id/deps/kind/stage) over IR legs or raw
    dicts — what the happens-before structures consume; ``stage`` lets
    the hang report name the wedged pipeline stage."""

    __slots__ = ("id", "deps", "kind", "stage")

    def __init__(self, id: str, deps: Tuple[str, ...], kind: str,
                 stage: str = ""):
        self.id = id
        self.deps = deps
        self.kind = kind
        self.stage = stage


def leg_views(legs_or_ir) -> List[_LegView]:
    legs = getattr(legs_or_ir, "legs", None)
    if legs is None and isinstance(legs_or_ir, dict):
        legs = legs_or_ir.get("legs", ())
    if legs is None:
        legs = legs_or_ir
    out = []
    for l in legs:
        if isinstance(l, dict):
            out.append(_LegView(str(l.get("id", "")),
                                tuple(l.get("deps", ()) or ()),
                                str(l.get("kind", "")),
                                str(l.get("stage", "") or "")))
        else:
            out.append(_LegView(l.id, tuple(l.deps), l.kind,
                                str(getattr(l, "stage", "") or "")))
    return out


def _topo(views: Sequence[_LegView]) -> Optional[List[str]]:
    """Deterministic Kahn topological order (deps first); None on a
    cycle.  Unknown dep ids are ignored (a published IR is already
    verifier-clean; tolerance keeps hand-built test fixtures easy)."""
    ids = {v.id for v in views}
    indeg: Dict[str, int] = {v.id: 0 for v in views}
    succs: Dict[str, List[str]] = {v.id: [] for v in views}
    for v in views:
        for dep in v.deps:
            if dep in ids and dep != v.id:
                indeg[v.id] += 1
                succs[dep].append(v.id)
    frontier = [v.id for v in views if indeg[v.id] == 0]
    order: List[str] = []
    while frontier:
        nid = frontier.pop(0)
        order.append(nid)
        for s in succs[nid]:
            indeg[s] -= 1
            if indeg[s] == 0:
                frontier.append(s)
    return order if len(order) == len(views) else None


class _PureReach:
    """Ancestor-set reachability — the jax-free fallback when
    ``analysis.dataflow.HappensBefore`` (the packed-bitset closure the
    verifier uses) cannot be imported.  Same ``reaches`` contract."""

    def __init__(self, views: Sequence[_LegView], order: Sequence[str]):
        by_id = {v.id: v for v in views}
        self._anc: Dict[str, set] = {}
        for lid in order:
            anc: set = set()
            for dep in by_id[lid].deps:
                if dep in self._anc:
                    anc.add(dep)
                    anc |= self._anc[dep]
            self._anc[lid] = anc

    def reaches(self, a: str, b: str) -> bool:
        return a in self._anc.get(b, ())


def happens_before(legs_or_ir):
    """The happens-before relation over ``legs_or_ir`` (an IR object,
    its dict form, or a bare leg list): ``analysis.dataflow
    .HappensBefore`` when importable, :class:`_PureReach` on jax-free
    hosts.  None when the dep graph is cyclic."""
    views = leg_views(legs_or_ir)
    order = _topo(views)
    if order is None:
        return None
    try:
        from autodist_tpu.analysis.dataflow import HappensBefore

        return HappensBefore(views, order)
    except Exception:
        return _PureReach(views, order)


@dataclass
class HangDiagnosis:
    """Where a hang localizes: the frontier leg(s) no one has passed
    and the culprit host(s) that have not entered them."""

    frontier_leg: Optional[str] = None
    frontier_legs: Tuple[str, ...] = ()
    culprits: Tuple[str, ...] = ()
    tie: bool = False
    detail: str = ""
    fingerprint: Optional[str] = None
    per_host: Dict[str, dict] = field(default_factory=dict)
    #: pipeline stage of the frontier leg ("" when the schedule has no
    #: per-stage legs) — names the wedged stage in the MPMD hang report.
    stage: str = ""

    def to_dict(self) -> dict:
        return {"frontier_leg": self.frontier_leg,
                "frontier_legs": list(self.frontier_legs),
                "culprits": list(self.culprits), "tie": self.tie,
                "detail": self.detail, "fingerprint": self.fingerprint,
                "per_host": self.per_host,
                **({"stage": self.stage} if self.stage else {})}

    @classmethod
    def from_dict(cls, d: dict) -> "HangDiagnosis":
        return cls(frontier_leg=d.get("frontier_leg"),
                   frontier_legs=tuple(d.get("frontier_legs", ())),
                   culprits=tuple(d.get("culprits", ())),
                   tie=bool(d.get("tie", False)),
                   detail=str(d.get("detail", "")),
                   fingerprint=d.get("fingerprint"),
                   per_host=dict(d.get("per_host", {})),
                   stage=str(d.get("stage", "") or ""))


def localize_hang(legs_or_ir, cursors: Dict[str, Optional[dict]],
                  fingerprint: Optional[str] = None
                  ) -> Optional[HangDiagnosis]:
    """Diff per-host cursors against the schedule's happens-before
    relation and name the frontier leg and culprit host(s).

    ``cursors`` maps host/worker name → beacon cursor dict (None
    entries tolerated).  Rules, in order:

    1. hosts at DIFFERENT steps: the minimum-step host(s) are the
       culprits — they have not finished a step every peer completed
       (the frontier is their cursor leg when it names one);
    2. same step: among the distinct cursor legs the IR knows, the
       frontier is the happens-before-minimal set; culprits are the
       hosts stuck at a frontier leg.  When NO ordering separates the
       hosts (everyone at one leg, or mutually unordered legs) the
       diagnosis is a ``tie`` — all hosts are equally blocked, which
       points at an external cause (fabric, a peer outside the cursor
       set) rather than one straggler.

    Returns None when no host carries a usable cursor."""
    per_host = {h: dict(c) for h, c in (cursors or {}).items()
                if isinstance(c, dict) and c.get("leg")}
    if not per_host:
        return None
    diag = HangDiagnosis(fingerprint=fingerprint, per_host=per_host)

    def _stamp_stage(d: HangDiagnosis) -> HangDiagnosis:
        """Name the wedged pipeline stage (and call out a transport
        frontier — the cross-slice MPMD wedge) from the frontier leg's
        IR metadata."""
        if d.frontier_leg is None or legs_or_ir is None:
            return d
        for v in leg_views(legs_or_ir):
            if v.id == d.frontier_leg:
                if v.stage:
                    d.stage = v.stage
                    extra = f" — wedged at pipeline stage {v.stage!r}"
                    if v.kind in ("send_act", "recv_act"):
                        extra += (f" on {v.kind} leg {v.id!r} (cross-"
                                  "slice activation transport)")
                    d.detail += extra
                break
        return d

    steps = {h: int(c["step"]) for h, c in per_host.items()
             if c.get("step") is not None}
    if steps and len(set(steps.values())) > 1:
        lo, hi = min(steps.values()), max(steps.values())
        culprits = tuple(sorted(h for h, s in steps.items() if s == lo))
        diag.culprits = culprits
        legs = sorted({per_host[h]["leg"] for h in culprits})
        diag.frontier_legs = tuple(legs)
        diag.frontier_leg = legs[0] if legs else None
        diag.detail = (
            f"host(s) {', '.join(culprits)} still at step {lo} while "
            f"peers reached step {hi}"
            + (f" — last cursor {cursor_line(per_host[culprits[0]])}"
               if culprits else ""))
        return _stamp_stage(diag)

    views = leg_views(legs_or_ir) if legs_or_ir is not None else []
    known_ids = {v.id for v in views}
    known = {h: c["leg"] for h, c in per_host.items()
             if c["leg"] in known_ids}
    if not known:
        hosts = tuple(sorted(per_host))
        diag.culprits = hosts
        diag.tie = len(hosts) > 1
        diag.detail = ("no cursor names a leg of the published schedule "
                       "(host-phase cursors only) — cannot separate hosts "
                       "beyond step parity")
        return diag
    hb = happens_before(views)
    distinct = sorted(set(known.values()))
    if hb is None:
        frontier = distinct
    else:
        frontier = [L for L in distinct
                    if not any(hb.reaches(L2, L)
                               for L2 in distinct if L2 != L)]
    diag.frontier_legs = tuple(frontier)
    diag.frontier_leg = frontier[0] if frontier else None
    culprits = tuple(sorted(h for h, L in known.items() if L in frontier))
    diag.culprits = culprits
    # A tie needs MULTIPLE equally-blocked hosts: one host wedged at a
    # schedule leg while its peers only show host-phase cursors is a
    # unique culprit, not a tie.
    diag.tie = len(known) > 1 and set(culprits) == set(known)
    if diag.tie:
        diag.detail = (
            f"all hosts blocked at frontier leg(s) "
            f"{', '.join(frontier)} — no unique culprit (peer outside "
            "the cursor set, or the fabric itself)")
    else:
        blocked = sorted(set(known.values()) - set(frontier))
        diag.detail = (
            f"host(s) {', '.join(culprits)} never completed frontier "
            f"leg {diag.frontier_leg}, a happens-before dependency of "
            f"the leg(s) every peer is blocked in ({', '.join(blocked)})")
    return _stamp_stage(diag)


# -- crash bundles -----------------------------------------------------------

def find_bundles(run_dir: str) -> List[str]:
    """``bundle-*/`` directories under ``run_dir`` (recursive), oldest
    first."""
    if not run_dir:
        return []
    out = [p for p in glob.glob(os.path.join(
        run_dir, "**", BUNDLE_PREFIX + "*"), recursive=True)
        if os.path.isdir(p)]
    return sorted(out, key=lambda p: (os.path.getmtime(p), p))


def _verdict_dict(h) -> dict:
    """A WorkerHealth (or plain dict) as a JSON-ready verdict row."""
    if isinstance(h, dict):
        return dict(h)
    out = {}
    for k in ("worker", "state", "age", "step", "pid", "detail", "phase",
              "snapshot", "cursor"):
        v = getattr(h, k, None)
        if v is not None:
            out[k] = v
    return out


def dump_bundle(run_dir: str, *, reason: str = "", ir=None,
                verdicts: Optional[Dict[str, Any]] = None,
                tail: int = 200) -> Optional[str]:
    """Snapshot the black box into ``<run_dir>/bundle-<ts>/``.

    Contents (each best-effort — a failing artifact is recorded in the
    MANIFEST, never raised): this process's cursor ring + any
    ``cursors-*.jsonl`` peers already flushed under ``run_dir``, the
    monitor ``verdicts`` (WorkerHealth rows, with their beacon-carried
    cursors), the merged event-journal and StepRecord tails, all-thread
    faulthandler stacks, the schedule IR (the ``ir`` argument or the
    newest published ``schedule-*.json``), and — when the verdict
    cursors localize — a ``hang.json`` diagnosis, also emitted as a
    ``flightrec/hang`` journal event.  Returns the bundle path."""
    if not run_dir:
        return None
    stamp = time.strftime("%Y%m%d-%H%M%S")
    bundle = os.path.join(run_dir, f"{BUNDLE_PREFIX}{stamp}-{os.getpid()}")
    n = 0
    while os.path.exists(bundle):   # same second, same pid: suffix
        n += 1
        bundle = os.path.join(
            run_dir, f"{BUNDLE_PREFIX}{stamp}-{os.getpid()}.{n}")
    try:
        os.makedirs(bundle, exist_ok=True)
    except OSError:
        return None
    files: List[str] = []
    errors: List[str] = []

    def _try(name, fn):
        try:
            out = fn()
            if out:
                files.append(name)
            return out
        except Exception as e:
            errors.append(f"{name}: {e!r}")
            return None

    # 1. cursor rings: this process's, plus every peer ring already
    # flushed under the run dir (each process dumps its own on fatal
    # paths; the chief collects whatever exists).
    _try("cursors", lambda: dump_cursors(bundle))
    for path in glob.glob(os.path.join(run_dir, "**", "cursors-*.jsonl"),
                          recursive=True):
        if os.path.dirname(path).startswith(bundle):
            continue
        name = os.path.basename(path)
        _try(name, lambda p=path, nm=name: shutil.copy2(
            p, os.path.join(bundle, nm)))

    # 2. monitor verdicts (beacon cursors ride each row).
    verdict_rows = {w: _verdict_dict(h) for w, h in (verdicts or {}).items()}
    if verdict_rows:
        def _write_verdicts():
            with open(os.path.join(bundle, "verdicts.json"), "w",
                      encoding="utf-8") as f:
                json.dump(verdict_rows, f, indent=2, default=str)
            return True
        _try("verdicts.json", _write_verdicts)

    # 3. journal + StepRecord tails.
    def _write_events():
        from autodist_tpu.telemetry.events import load_run_events

        evs = load_run_events(run_dir, tail=tail)
        if not evs:
            return False
        with open(os.path.join(bundle, "events_tail.jsonl"), "w",
                  encoding="utf-8") as f:
            for e in evs:
                f.write(json.dumps(e, default=str) + "\n")
        return True
    _try("events_tail.jsonl", _write_events)

    def _write_steps():
        from autodist_tpu.telemetry.timeline import load_step_records

        recs = load_step_records(run_dir)[-max(tail, 0):]
        if not recs:
            return False
        with open(os.path.join(bundle, "steps_tail.jsonl"), "w",
                  encoding="utf-8") as f:
            for r in recs:
                f.write(r.to_json() + "\n")
        return True
    _try("steps_tail.jsonl", _write_steps)

    # 4. all-thread stacks of THIS process (on a wedge, the chief's
    # stacks show the watch loop; each worker's fatal handler dumps its
    # own — see install_fatal_handlers).
    def _write_stacks():
        path = os.path.join(bundle, f"stacks-{_host()}-{os.getpid()}.txt")
        with open(path, "w", encoding="utf-8") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
        return True
    _try("stacks", _write_stacks)

    # 5. schedule IR + fingerprint.
    ir_dict = None

    def _write_ir():
        nonlocal ir_dict
        if ir is not None:
            ir_dict = ir.to_dict() if hasattr(ir, "to_dict") else dict(ir)
        else:
            ir_dict = load_published_ir(run_dir)
        if ir_dict is None:
            return False
        with open(os.path.join(bundle, "schedule_ir.json"), "w",
                  encoding="utf-8") as f:
            json.dump(ir_dict, f, sort_keys=True)
        return True
    _try("schedule_ir.json", _write_ir)

    # 6. hang localization from the beacon-carried cursors.
    diagnosis = None

    def _write_hang():
        nonlocal diagnosis
        cursors = {w: row.get("cursor") for w, row in verdict_rows.items()}
        if not any(cursors.values()):
            return False
        fp = next((c.get("fingerprint") for c in cursors.values()
                   if c and c.get("fingerprint")), None)
        diagnosis = localize_hang(ir_dict, cursors, fingerprint=fp)
        if diagnosis is None:
            return False
        with open(os.path.join(bundle, "hang.json"), "w",
                  encoding="utf-8") as f:
            json.dump(diagnosis.to_dict(), f, indent=2)
        return True
    _try("hang.json", _write_hang)

    manifest = {
        "time": time.time(), "reason": reason, "host": _host(),
        "pid": os.getpid(), "run_dir": run_dir, "files": files,
        "fingerprint": (diagnosis.fingerprint if diagnosis else None)
        or _fingerprint,
        **({"errors": errors} if errors else {}),
        **({"diagnosis": diagnosis.to_dict()} if diagnosis else {}),
    }
    try:
        with open(os.path.join(bundle, "MANIFEST.json"), "w",
                  encoding="utf-8") as f:
            json.dump(manifest, f, indent=2, default=str)
    except OSError:
        pass
    if diagnosis is not None:
        try:
            from autodist_tpu.telemetry.events import emit_event

            emit_event(EVENT_HANG, bundle=bundle, reason=reason,
                       **diagnosis.to_dict())
        except Exception:  # pragma: no cover - defensive
            pass
    return bundle


def read_bundle(bundle_dir: str) -> dict:
    """Parse a bundle back into dicts: manifest, diagnosis, verdicts,
    per-file cursors, events/steps tails (missing pieces omitted)."""
    out: dict = {"path": bundle_dir}
    for name, key in (("MANIFEST.json", "manifest"),
                      ("hang.json", "diagnosis"),
                      ("verdicts.json", "verdicts")):
        try:
            with open(os.path.join(bundle_dir, name), encoding="utf-8") as f:
                out[key] = json.load(f)
        except (OSError, ValueError):
            pass
    cursors: Dict[str, List[dict]] = {}
    for path in sorted(glob.glob(os.path.join(bundle_dir,
                                              "cursors-*.jsonl"))):
        name = os.path.basename(path)[len("cursors-"):-len(".jsonl")]
        cursors[name] = [c.to_dict() for c in load_cursors(path)]
    if cursors:
        out["cursors"] = cursors
    stacks: Dict[str, str] = {}
    for path in sorted(glob.glob(os.path.join(bundle_dir, "stacks-*.txt"))):
        try:
            with open(path, encoding="utf-8") as f:
                stacks[os.path.basename(path)] = f.read()
        except OSError:
            continue
    if stacks:
        out["stacks"] = stacks
    return out


def render_hang_report(bundle_dir: str, stack_lines: int = 12) -> str:
    """The human bundle report (``python -m autodist_tpu.telemetry
    --hang-report <bundle>``): per-host cursor table, frontier leg,
    culprit verdict, stack excerpts."""
    b = read_bundle(bundle_dir)
    man = b.get("manifest") or {}
    lines = [f"flight-recorder bundle: {bundle_dir}"]
    if man:
        when = time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(man.get("time", 0)))
        lines.append(f"  reason: {man.get('reason') or 'unspecified'}"
                     f"  (host {man.get('host')}, pid {man.get('pid')},"
                     f" {when})")
        if man.get("fingerprint"):
            lines.append(f"  schedule fingerprint: {man['fingerprint']}")
    verdicts = b.get("verdicts") or {}
    if verdicts:
        lines.append("  per-host cursors:")
        for w in sorted(verdicts):
            row = verdicts[w]
            cur = row.get("cursor")
            doing = cursor_line(cur, float(row.get("age") or 0.0)) \
                if cur else "(no cursor)"
            lines.append(f"    {w:16s} {row.get('state', '?'):8s}"
                         f" step {row.get('step')}  {doing}")
    diag = b.get("diagnosis")
    if diag:
        lines.append(f"  frontier leg: {diag.get('frontier_leg')}"
                     + (f"  (frontier set: "
                        f"{', '.join(diag.get('frontier_legs', []))})"
                        if len(diag.get("frontier_legs", [])) > 1 else ""))
        if diag.get("stage"):
            lines.append(f"  wedged stage: {diag['stage']}")
        verdict = "TIE — no unique culprit" if diag.get("tie") \
            else f"culprit: {', '.join(diag.get('culprits', []))}"
        lines.append(f"  {verdict}")
        lines.append(f"  {diag.get('detail', '')}")
    else:
        lines.append("  no hang diagnosis in this bundle (no leg cursors"
                     " or no schedule IR)")
    for name, text in sorted((b.get("stacks") or {}).items()):
        head = text.strip().splitlines()[:max(stack_lines, 1)]
        lines.append(f"  {name} (first {len(head)} line(s)):")
        lines.extend(f"    {ln}" for ln in head)
    cursors = b.get("cursors") or {}
    for name in sorted(cursors):
        tail = cursors[name][-3:]
        lines.append(f"  ring {name}: {len(cursors[name])} cursor(s),"
                     " last "
                     + "; ".join(cursor_line(c) or c.get("leg", "?")
                                 for c in tail))
    return "\n".join(lines)


# -- fatal-path arming -------------------------------------------------------

_fatal_lock = threading.Lock()
_fatal_armed: Optional[str] = None
_fatal_file = None


def install_fatal_handlers(run_dir: str) -> bool:
    """Arm the fatal paths for this process: faulthandler writes
    all-thread stacks to ``fatal-<host>-<pid>.log`` under ``run_dir``
    on SIGSEGV/SIGABRT/SIGFPE/SIGBUS/SIGILL, and an ``sys.excepthook``
    wrapper dumps a crash bundle (plus this process's cursor ring) on
    any uncaught exception before chaining to the previous hook.
    Idempotent per process; never raises."""
    global _fatal_armed, _fatal_file
    if not run_dir:
        return False
    with _fatal_lock:
        if _fatal_armed is not None:
            return True
        try:
            os.makedirs(run_dir, exist_ok=True)
            path = os.path.join(run_dir,
                                f"fatal-{_host()}-{os.getpid()}.log")
            _fatal_file = open(path, "w", encoding="utf-8")
            faulthandler.enable(file=_fatal_file, all_threads=True)
        except Exception:
            return False
        prev_hook = sys.excepthook

        def _hook(exc_type, exc, tb, _prev=prev_hook, _dir=run_dir):
            try:
                dump_cursors(_dir)
                dump_bundle(_dir,
                            reason=f"uncaught {exc_type.__name__}: {exc}")
            except Exception:
                pass
            _prev(exc_type, exc, tb)

        sys.excepthook = _hook
        _fatal_armed = run_dir
        return True
