"""CLI: ``python -m autodist_tpu.telemetry <run_dir>``.

Summarize a recorded run directory — the JSONL a
:class:`~autodist_tpu.telemetry.timeline.StepRecorder` and the event
journal flushed (``AUTODIST_TELEMETRY_DIR``), or what bench.py emitted
next to its BENCH_*.json artifacts:

* step-time percentiles (p50/p90/p99) and throughput,
* host-phase breakdown (data_load / dispatch / blocking_fetch ...),
* the structured event timeline (supervisor, heartbeat, chaos,
  checkpoint, numerics events),
* the predicted-vs-measured table with the ``telemetry/model-drift``
  verdict, and — with ``--fit`` — calibrated cost-model constants
  (:func:`~autodist_tpu.telemetry.calibration.fit_constants`, plus the
  per-leg-kind :func:`fit_leg_constants` when the run holds leg
  samples; ``--save-calibration`` persists the result as
  ``calibration.json`` where ``estimate_ir_cost`` and
  ``AutoStrategy(search=True)`` discover it),
* cross-host aggregation (per-host step-time skew + the
  ``telemetry/straggler`` verdict) whenever records carry more than
  one host,
* ``--export-trace`` — merge StepRecords, leg samples, the event
  journal and serving request spans into ONE Chrome-trace/Perfetto
  JSON with per-host tracks (``trace_export.py``),
* ``--compare <run_b>`` — the two-run regression report: step-time
  percentile deltas, per-phase and per-leg-kind regressions, drift
  verdicts,
* ``--hang-report <bundle>`` — render a flight-recorder crash bundle
  (``telemetry/flightrec.py``): per-host cursor table, frontier leg,
  culprit verdict, stack excerpts.  The default report gains a hang
  section whenever ``bundle-*/`` directories exist under the run dir.

Deliberately jax-free (numpy + stdlib): runs on any host that can read
the files.  Exits 0 on success, 2 when the directory holds no telemetry.

Examples::

    python -m autodist_tpu.telemetry /tmp/autodist_tpu/telemetry/run1
    python -m autodist_tpu.telemetry ./telemetry_run --fit --json
    python -m autodist_tpu.telemetry ./run --events 50
    python -m autodist_tpu.telemetry ./run --export-trace
    python -m autodist_tpu.telemetry ./run_a --compare ./run_b
    python -m autodist_tpu.telemetry --hang-report ./run/bundle-<ts>
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

import numpy as np

from autodist_tpu.telemetry.calibration import (
    fit_constants,
    fit_leg_constants,
    leg_drift_reason,
    predicted_vs_measured,
    save_calibration,
)
from autodist_tpu.telemetry.events import load_run_events
from autodist_tpu.telemetry.profiler import load_leg_samples
from autodist_tpu.telemetry.timeline import StepRecord, load_step_records


def _percentiles(values: List[float]) -> dict:
    arr = np.asarray(values, dtype=np.float64)
    return {
        "n": int(arr.size),
        "mean_ms": round(float(arr.mean()) * 1e3, 3),
        "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 3),
        "p90_ms": round(float(np.percentile(arr, 90)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 3),
        "max_ms": round(float(arr.max()) * 1e3, 3),
    }


def summarize_steps(records: List[StepRecord]) -> Optional[dict]:
    """Step-time percentiles, throughput, phase breakdown, health
    counters — the machine half of the report (also the ``--json``
    payload)."""
    times = [r.step_time_s for r in records if r.step_time_s]
    if not records:
        return None
    out: dict = {"steps": len(records)}
    if times:
        out["step_time"] = _percentiles(times)
    items = [r.items_per_s for r in records if r.items_per_s]
    if items:
        out["items_per_s_mean"] = round(float(np.mean(items)), 2)
    tokens = [r.tokens_per_s for r in records if r.tokens_per_s]
    if tokens:
        out["tokens_per_s_mean"] = round(float(np.mean(tokens)), 2)
    phases: dict = {}
    for r in records:
        for name, s in (r.phases or {}).items():
            acc = phases.setdefault(name, [0.0, 0])
            acc[0] += s
            acc[1] += 1
    if phases:
        total_time = sum(t for t in times) or None
        out["phases"] = {
            name: {
                "total_s": round(tot, 6),
                "mean_ms": round(tot / n * 1e3, 3),
                "fraction_of_step_time": (
                    round(tot / total_time, 4) if total_time else None),
            }
            for name, (tot, n) in sorted(phases.items())}
    skipped = [r.skipped_steps for r in records
               if r.skipped_steps is not None]
    if skipped:
        out["skipped_steps"] = int(max(skipped))
    if any(r.rolled_back for r in records):
        out["rollbacks_observed"] = True
    pm = predicted_vs_measured(records)
    if pm:
        out["predicted_vs_measured"] = pm
    return out


def leg_kind_totals(samples) -> dict:
    """Per-leg-kind measured/predicted second totals over profiler
    samples — the ``leg_kinds`` analysis provenance and the compare
    report's per-kind rows."""
    out: dict = {}
    for s in samples:
        kind = getattr(s, "kind", None)
        t = getattr(s, "measured_s", None)
        if not kind or not t or t <= 0:
            continue
        row = out.setdefault(kind, {"measured_s": 0.0, "predicted_s": 0.0,
                                    "n": 0, "_pred_n": 0})
        row["measured_s"] += float(t)
        row["n"] += 1
        pred = getattr(s, "predicted_s", None)
        if pred:
            row["predicted_s"] += float(pred)
            row["_pred_n"] += 1
    for row in out.values():
        if row.pop("_pred_n") == 0:
            row["predicted_s"] = None
    return out


#: fractional step-time/phase/leg growth that counts as a regression in
#: the two-run compare report.
REGRESSION_THRESHOLD = 0.10


def _pct(a: Optional[float], b: Optional[float]) -> Optional[float]:
    if not a or b is None:
        return None
    return round((b - a) / a, 4)


def compare_runs(dir_a: str, dir_b: str) -> Optional[dict]:
    """The two-run regression report (``--compare``): step-time
    percentile deltas, per-phase and per-leg-kind deltas, drift
    verdicts, and a ``regressions`` list of everything that grew past
    :data:`REGRESSION_THRESHOLD`.  ``dir_a`` is the baseline.  None
    when either run holds no step records."""
    rec_a = load_step_records(dir_a)
    rec_b = load_step_records(dir_b)
    sum_a = summarize_steps(rec_a)
    sum_b = summarize_steps(rec_b)
    if not sum_a or not sum_b:
        return None
    out: dict = {"run_a": dir_a, "run_b": dir_b,
                 "steps": [sum_a.get("steps"), sum_b.get("steps")]}
    regressions: List[str] = []
    st_a, st_b = sum_a.get("step_time") or {}, sum_b.get("step_time") or {}
    steps: dict = {}
    for key in ("p50_ms", "p90_ms", "p99_ms", "mean_ms"):
        a, b = st_a.get(key), st_b.get(key)
        delta = _pct(a, b)
        steps[key] = {"a": a, "b": b, "delta_pct": delta}
        if delta is not None and delta > REGRESSION_THRESHOLD:
            regressions.append(
                f"step time {key} regressed {delta:+.1%}: "
                f"{a} ms -> {b} ms")
    out["step_time"] = steps
    phases: dict = {}
    ph_a, ph_b = sum_a.get("phases") or {}, sum_b.get("phases") or {}
    for name in sorted(set(ph_a) | set(ph_b)):
        a = (ph_a.get(name) or {}).get("mean_ms")
        b = (ph_b.get(name) or {}).get("mean_ms")
        delta = _pct(a, b)
        phases[name] = {"a_mean_ms": a, "b_mean_ms": b,
                        "delta_pct": delta}
        if delta is not None and delta > REGRESSION_THRESHOLD:
            regressions.append(
                f"phase {name} regressed {delta:+.1%}: "
                f"{a} ms -> {b} ms per step")
    if phases:
        out["phases"] = phases
    legs_a = leg_kind_totals(load_leg_samples(dir_a))
    legs_b = leg_kind_totals(load_leg_samples(dir_b))
    if legs_a or legs_b:
        kinds: dict = {}
        for kind in sorted(set(legs_a) | set(legs_b)):
            a = (legs_a.get(kind) or {}).get("measured_s")
            b = (legs_b.get(kind) or {}).get("measured_s")
            delta = _pct(a, b)
            kinds[kind] = {
                "a_measured_ms": round(a * 1e3, 4) if a else None,
                "b_measured_ms": round(b * 1e3, 4) if b else None,
                "delta_pct": delta}
            # Kinds on one side only (e.g. hier/dcn legs after flipping a
            # run to two-tier sync) are not deltas — label instead of crash.
            if kind not in legs_a:
                kinds[kind]["status"] = "new"
            elif kind not in legs_b:
                kinds[kind]["status"] = "removed"
            if delta is not None and delta > REGRESSION_THRESHOLD:
                regressions.append(
                    f"leg kind {kind} regressed {delta:+.1%}: "
                    f"{a * 1e3:.3f} ms -> {b * 1e3:.3f} ms measured")
            drift = leg_drift_reason(
                kind, b, (legs_b.get(kind) or {}).get("predicted_s"))
            if drift:
                kinds[kind]["drift"] = drift
        out["leg_kinds"] = kinds
    for tag, summary in (("a", sum_a), ("b", sum_b)):
        pm = summary.get("predicted_vs_measured") or {}
        if pm.get("drift"):
            out[f"drift_{tag}"] = pm["drift"]
    out["regressions"] = regressions
    return out


def _print_compare(cmp: dict) -> None:
    print(f"compare: {cmp['run_a']} (baseline) vs {cmp['run_b']}")
    for key, row in cmp["step_time"].items():
        if row["a"] is None or row["b"] is None:
            continue
        delta = row["delta_pct"]
        print(f"  step {key:8s} {row['a']:10.3f} -> {row['b']:10.3f} ms"
              + (f"  ({delta:+.1%})" if delta is not None else ""))
    for name, row in (cmp.get("phases") or {}).items():
        if row["a_mean_ms"] is None or row["b_mean_ms"] is None:
            continue
        delta = row["delta_pct"]
        print(f"  phase {name:16s} {row['a_mean_ms']:9.3f} -> "
              f"{row['b_mean_ms']:9.3f} ms"
              + (f"  ({delta:+.1%})" if delta is not None else ""))
    for kind, row in (cmp.get("leg_kinds") or {}).items():
        a, b = row.get("a_measured_ms"), row.get("b_measured_ms")
        if row.get("status") == "new":
            print(f"  legs  {kind:16s} {'-':>9s} -> "
                  f"{b if b is not None else 0.0:9.3f} ms  (new in b)")
            continue
        if row.get("status") == "removed":
            print(f"  legs  {kind:16s} "
                  f"{a if a is not None else 0.0:9.3f} -> {'-':>9s} ms"
                  "  (removed in b)")
            continue
        if a is None or b is None:
            continue
        delta = row["delta_pct"]
        print(f"  legs  {kind:16s} {a:9.3f} -> {b:9.3f} ms"
              + (f"  ({delta:+.1%})" if delta is not None else ""))
    for tag in ("a", "b"):
        if cmp.get(f"drift_{tag}"):
            print(f"  WARN telemetry/model-drift [{tag}]: "
                  f"{cmp[f'drift_{tag}']}")
    if cmp["regressions"]:
        print(f"  REGRESSIONS ({len(cmp['regressions'])}):")
        for r in cmp["regressions"]:
            print(f"    - {r}")
    else:
        print("  no regressions past "
              f"{REGRESSION_THRESHOLD:.0%}")


def _fmt_event(rec: dict, t0: float) -> str:
    extras = {k: v for k, v in rec.items()
              if k not in ("time", "kind", "host", "pid")}
    detail = " ".join(f"{k}={v}" for k, v in sorted(extras.items()))
    return (f"  +{rec.get('time', t0) - t0:10.3f}s  "
            f"{rec.get('kind', '?'):32s} {detail}"[:120])


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m autodist_tpu.telemetry",
        description="Summarize a telemetry run directory "
                    "(StepRecord JSONL + event journal).")
    p.add_argument("run_dir", nargs="?", default=None,
                   help="directory holding steps-*.jsonl / "
                        "events-*.jsonl (searched recursively)")
    p.add_argument("--hang-report", metavar="BUNDLE", default=None,
                   help="render a flight-recorder crash bundle "
                        "(bundle-<ts>/ directory — or a run dir, whose "
                        "newest bundle is used)")
    p.add_argument("--events", type=int, default=20, metavar="N",
                   help="show at most N timeline events (default 20)")
    p.add_argument("--fit", action="store_true",
                   help="fit cost-model constants from the records "
                        "(telemetry.calibration.fit_constants; with leg "
                        "samples also fit_leg_constants)")
    p.add_argument("--save-calibration", metavar="PATH", default=None,
                   help="with --fit: persist the leg calibration as "
                        "calibration.json at PATH (or '-' for "
                        "<run_dir>/calibration.json)")
    p.add_argument("--export-trace", nargs="?", const="-", default=None,
                   metavar="PATH",
                   help="merge steps/legs/events/spans into one Chrome-"
                        "trace JSON (default <run_dir>/trace.json)")
    p.add_argument("--compare", metavar="RUN_B", default=None,
                   help="two-run regression report: RUN_DIR is the "
                        "baseline, RUN_B the candidate")
    p.add_argument("--json", action="store_true",
                   help="emit one machine-readable JSON object instead "
                        "of the human report")
    args = p.parse_args(argv)

    from autodist_tpu.telemetry import flightrec

    if args.hang_report:
        target = args.hang_report
        if not os.path.isfile(os.path.join(target, "MANIFEST.json")):
            bundles = flightrec.find_bundles(target)
            if not bundles:
                print(f"no flight-recorder bundle under {target} "
                      "(expected a bundle-<ts>/ directory)",
                      file=sys.stderr)
                return 2
            target = bundles[-1]
        print(flightrec.render_hang_report(target))
        return 0

    if args.run_dir is None:
        p.error("run_dir is required (or pass --hang-report <bundle>)")

    if args.compare:
        cmp = compare_runs(args.run_dir, args.compare)
        if cmp is None:
            print(f"compare: no step records under {args.run_dir} and/or "
                  f"{args.compare}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(cmp, default=str))
        else:
            _print_compare(cmp)
        return 0

    if args.export_trace is not None:
        from autodist_tpu.telemetry.trace_export import export_trace

        out_path = None if args.export_trace == "-" else args.export_trace
        path = export_trace(args.run_dir, out_path)
        if path is None:
            print(f"no telemetry under {args.run_dir} — nothing to "
                  "export", file=sys.stderr)
            return 2
        print(f"wrote {path}")
        return 0

    records = load_step_records(args.run_dir)
    events = load_run_events(args.run_dir)
    if not records and not events:
        print(f"no telemetry under {args.run_dir} (expected steps-*.jsonl "
              "or events-*.jsonl; set AUTODIST_TELEMETRY_DIR when running)",
              file=sys.stderr)
        return 2

    summary = summarize_steps(records) or {}
    leg_samples = load_leg_samples(args.run_dir)
    if leg_samples:
        summary["leg_kinds"] = {
            k: {kk: (round(vv, 6) if isinstance(vv, float) else vv)
                for kk, vv in row.items()}
            for k, row in leg_kind_totals(leg_samples).items()}
    # Goodput section (docs/observability.md): useful step time vs wall
    # time with the restart / checkpoint-stall / rollback decomposition,
    # plus the recovery-gap verdict over the observed checkpoint cadence
    # (the same pure rule the resilience/recovery-gap analysis fires).
    from autodist_tpu.telemetry.goodput import (
        checkpoint_cadence,
        goodput_from_run,
        recovery_gap_reason,
    )

    gp = goodput_from_run(records, events)
    if gp:
        cadence = checkpoint_cadence(records, events)
        if cadence:
            gp["cadence"] = cadence
            gap = recovery_gap_reason(
                cadence["checkpoint_interval_steps"],
                cadence["step_time_s"],
                snapshot_every=cadence.get("snapshot_every"))
            if gap:
                gp["recovery_gap"] = gap
        summary["goodput"] = gp

    # Hang section (docs/observability.md "Flight recorder"): whenever
    # a crash bundle exists under the run dir, surface the newest one's
    # diagnosis — frontier leg, culprit verdict, bundle path.
    bundles = flightrec.find_bundles(args.run_dir)
    if bundles:
        newest = flightrec.read_bundle(bundles[-1])
        hang: dict = {"bundle": bundles[-1], "bundle_count": len(bundles)}
        man = newest.get("manifest") or {}
        if man.get("reason"):
            hang["reason"] = man["reason"]
        if newest.get("diagnosis"):
            hang["diagnosis"] = newest["diagnosis"]
        summary["hang"] = hang

    # Cross-host section whenever records carry more than one host.
    from autodist_tpu.telemetry.aggregate import per_host_step_stats
    from autodist_tpu.telemetry.calibration import straggler_reason

    hosts = per_host_step_stats(records)
    if len(hosts) > 1:
        medians = {h: s["median_s"] for h, s in hosts.items()}
        summary["hosts"] = hosts
        summary["step_skew_ratio"] = round(
            max(medians.values()) / min(medians.values()), 4)
        straggler = straggler_reason(medians)
        if straggler:
            summary["straggler"] = straggler
    fit = fit_constants(records) if args.fit and records else None
    if fit is not None:
        summary["calibration"] = {
            "ici_bandwidth": fit.ici_bandwidth,
            "alpha": fit.alpha,
            "n_records": fit.n_records,
            "mean_abs_error_ms": round(fit.mean_abs_error_s * 1e3, 4),
            "baseline_mean_abs_error_ms": round(
                fit.baseline_mean_abs_error_s * 1e3, 4),
            "improved": fit.improved,
        }
    if args.fit and leg_samples:
        leg_cal = fit_leg_constants(leg_samples, records)
        if leg_cal is not None:
            summary["leg_calibration"] = {
                "alphas": leg_cal.alphas,
                "bandwidths": leg_cal.bandwidths,
                "quant_overhead_per_byte":
                    leg_cal.quant_overhead_per_byte,
                "scale": leg_cal.scale,
                "n_samples": leg_cal.n_samples,
                "n_records": leg_cal.n_records,
                "mean_abs_error_ms": round(
                    leg_cal.mean_abs_error_s * 1e3, 4)
                if leg_cal.mean_abs_error_s is not None else None,
                "step_fit_mean_abs_error_ms": round(
                    leg_cal.step_fit_mean_abs_error_s * 1e3, 4)
                if leg_cal.step_fit_mean_abs_error_s is not None
                else None,
                "improved": leg_cal.improved,
            }
            if args.save_calibration:
                import os as _os

                dest = args.save_calibration
                if dest == "-":
                    dest = _os.path.join(args.run_dir, "calibration.json")
                save_calibration(leg_cal, dest)
                summary["leg_calibration"]["path"] = dest

    if args.json:
        payload = dict(summary)
        payload["events"] = events
        print(json.dumps(payload, default=str))
        return 0

    print(f"telemetry summary: {args.run_dir}")
    if summary.get("steps"):
        st = summary.get("step_time") or {}
        print(f"  steps: {summary['steps']}"
              + (f"  |  step time p50 {st.get('p50_ms')} ms  "
                 f"p90 {st.get('p90_ms')} ms  p99 {st.get('p99_ms')} ms"
                 if st else ""))
        if "items_per_s_mean" in summary:
            print(f"  throughput: {summary['items_per_s_mean']} items/s"
                  + (f", {summary['tokens_per_s_mean']} tokens/s"
                     if "tokens_per_s_mean" in summary else ""))
        for name, ph in (summary.get("phases") or {}).items():
            frac = ph["fraction_of_step_time"]
            print(f"  phase {name:16s} mean {ph['mean_ms']:9.3f} ms"
                  + (f"  ({frac:.1%} of step time)"
                     if frac is not None else ""))
        if "skipped_steps" in summary:
            print(f"  numerics: {summary['skipped_steps']} skipped step(s)"
                  + (" + rollback(s)" if summary.get("rollbacks_observed")
                     else ""))
        pm = summary.get("predicted_vs_measured")
        if pm and pm.get("predicted_step_time_s"):
            print(f"  predicted vs measured: "
                  f"{pm['predicted_step_time_s'] * 1e3:.3f} ms predicted, "
                  f"{pm['measured_step_time_s'] * 1e3:.3f} ms measured "
                  f"(x{pm['ratio']:.2f})")
            if pm.get("drift"):
                print(f"  WARN telemetry/model-drift: {pm['drift']}")
        for kind, row in (summary.get("leg_kinds") or {}).items():
            pred = row.get("predicted_s")
            print(f"  leg {kind:18s} measured "
                  f"{row['measured_s'] * 1e3:9.3f} ms over {row['n']} "
                  "sample(s)"
                  + (f"  (predicted {pred * 1e3:.3f} ms)"
                     if pred else ""))
        for host, st in (summary.get("hosts") or {}).items():
            print(f"  host {host:20s} median "
                  f"{st['median_s'] * 1e3:9.3f} ms over {st['n']} step(s)")
        if summary.get("step_skew_ratio"):
            print(f"  cross-host step skew: "
                  f"x{summary['step_skew_ratio']:.2f}")
        if summary.get("straggler"):
            print(f"  WARN telemetry/straggler: {summary['straggler']}")
    gp = summary.get("goodput")
    if gp:
        # Printed even for an events-only directory: the decomposition
        # (restart gaps, checkpoint stalls) lives in the journal.
        ratio = gp.get("goodput_ratio")
        print("  goodput: "
              + (f"{ratio:.1%}" if ratio is not None else "n/a")
              + f"  ({gp['useful_step_s']:.3f}s useful"
              + (f" / {gp['wall_s']:.3f}s wall" if gp.get("wall_s")
                 else "")
              + (f", {gp['attempts']} attempt(s)"
                 if gp.get("attempts") else "") + ")")
        losses = gp.get("losses") or {}
        for name in ("restart_s", "checkpoint_stall_s", "rollback_s",
                     "other_s"):
            v = losses.get(name)
            if v:
                print(f"    loss {name[:-2]:18s} {v:9.3f} s")
        if gp.get("recovery_gap"):
            print("  WARN resilience/recovery-gap: "
                  f"{gp['recovery_gap']}")
    hang = summary.get("hang")
    if hang:
        print(f"  hang: {hang['bundle_count']} crash bundle(s); newest "
              f"{hang['bundle']}")
        if hang.get("reason"):
            print(f"    reason: {hang['reason']}")
        diag = hang.get("diagnosis")
        if diag:
            verdict = "TIE — no unique culprit" if diag.get("tie") else \
                f"culprit {', '.join(diag.get('culprits', []))}"
            print(f"    frontier leg {diag.get('frontier_leg')}  "
                  f"({verdict})")
            print(f"    {diag.get('detail', '')}")
        print("    render: python -m autodist_tpu.telemetry "
              f"--hang-report {hang['bundle']}")
    cal = summary.get("calibration")
    if cal:
        print(f"  calibrated: bandwidth {cal['ici_bandwidth']:.3e} B/s, "
              f"alpha {cal['alpha']:.3e} s/collective "
              f"({cal['n_records']} records; mean abs error "
              f"{cal['mean_abs_error_ms']} ms vs "
              f"{cal['baseline_mean_abs_error_ms']} ms uncalibrated)")
    leg_cal = summary.get("leg_calibration")
    if leg_cal:
        kinds = ", ".join(sorted(leg_cal["bandwidths"]))
        print(f"  leg-calibrated: {len(leg_cal['bandwidths'])} kind(s) "
              f"[{kinds}] from {leg_cal['n_samples']} sample(s)"
              + (f"; record mean abs error {leg_cal['mean_abs_error_ms']}"
                 f" ms vs {leg_cal['step_fit_mean_abs_error_ms']} ms "
                 "whole-step fit"
                 if leg_cal.get("mean_abs_error_ms") is not None else ""))
        if leg_cal.get("path"):
            print(f"  wrote {leg_cal['path']}")
    if events:
        t0 = events[0].get("time", time.time())
        shown = events[:max(args.events, 0)]
        print(f"  events ({len(events)} total, showing {len(shown)}):")
        for rec in shown:
            print(_fmt_event(rec, t0))
    return 0


if __name__ == "__main__":
    sys.exit(main())
