"""CLI: ``python -m autodist_tpu.telemetry <run_dir>``.

Summarize a recorded run directory — the JSONL a
:class:`~autodist_tpu.telemetry.timeline.StepRecorder` and the event
journal flushed (``AUTODIST_TELEMETRY_DIR``), or what bench.py emitted
next to its BENCH_*.json artifacts:

* step-time percentiles (p50/p90/p99) and throughput,
* host-phase breakdown (data_load / dispatch / blocking_fetch ...),
* the structured event timeline (supervisor, heartbeat, chaos,
  checkpoint, numerics events),
* the predicted-vs-measured table with the ``telemetry/model-drift``
  verdict, and — with ``--fit`` — calibrated cost-model constants
  (:func:`~autodist_tpu.telemetry.calibration.fit_constants`).

Deliberately jax-free (numpy + stdlib): runs on any host that can read
the files.  Exits 0 on success, 2 when the directory holds no telemetry.

Examples::

    python -m autodist_tpu.telemetry /tmp/autodist_tpu/telemetry/run1
    python -m autodist_tpu.telemetry ./telemetry_run --fit --json
    python -m autodist_tpu.telemetry ./run --events 50
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional

import numpy as np

from autodist_tpu.telemetry.calibration import (
    fit_constants,
    predicted_vs_measured,
)
from autodist_tpu.telemetry.events import load_run_events
from autodist_tpu.telemetry.timeline import StepRecord, load_step_records


def _percentiles(values: List[float]) -> dict:
    arr = np.asarray(values, dtype=np.float64)
    return {
        "n": int(arr.size),
        "mean_ms": round(float(arr.mean()) * 1e3, 3),
        "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 3),
        "p90_ms": round(float(np.percentile(arr, 90)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 3),
        "max_ms": round(float(arr.max()) * 1e3, 3),
    }


def summarize_steps(records: List[StepRecord]) -> Optional[dict]:
    """Step-time percentiles, throughput, phase breakdown, health
    counters — the machine half of the report (also the ``--json``
    payload)."""
    times = [r.step_time_s for r in records if r.step_time_s]
    if not records:
        return None
    out: dict = {"steps": len(records)}
    if times:
        out["step_time"] = _percentiles(times)
    items = [r.items_per_s for r in records if r.items_per_s]
    if items:
        out["items_per_s_mean"] = round(float(np.mean(items)), 2)
    tokens = [r.tokens_per_s for r in records if r.tokens_per_s]
    if tokens:
        out["tokens_per_s_mean"] = round(float(np.mean(tokens)), 2)
    phases: dict = {}
    for r in records:
        for name, s in (r.phases or {}).items():
            acc = phases.setdefault(name, [0.0, 0])
            acc[0] += s
            acc[1] += 1
    if phases:
        total_time = sum(t for t in times) or None
        out["phases"] = {
            name: {
                "total_s": round(tot, 6),
                "mean_ms": round(tot / n * 1e3, 3),
                "fraction_of_step_time": (
                    round(tot / total_time, 4) if total_time else None),
            }
            for name, (tot, n) in sorted(phases.items())}
    skipped = [r.skipped_steps for r in records
               if r.skipped_steps is not None]
    if skipped:
        out["skipped_steps"] = int(max(skipped))
    if any(r.rolled_back for r in records):
        out["rollbacks_observed"] = True
    pm = predicted_vs_measured(records)
    if pm:
        out["predicted_vs_measured"] = pm
    return out


def _fmt_event(rec: dict, t0: float) -> str:
    extras = {k: v for k, v in rec.items()
              if k not in ("time", "kind", "host", "pid")}
    detail = " ".join(f"{k}={v}" for k, v in sorted(extras.items()))
    return (f"  +{rec.get('time', t0) - t0:10.3f}s  "
            f"{rec.get('kind', '?'):32s} {detail}"[:120])


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m autodist_tpu.telemetry",
        description="Summarize a telemetry run directory "
                    "(StepRecord JSONL + event journal).")
    p.add_argument("run_dir", help="directory holding steps-*.jsonl / "
                                   "events-*.jsonl (searched recursively)")
    p.add_argument("--events", type=int, default=20, metavar="N",
                   help="show at most N timeline events (default 20)")
    p.add_argument("--fit", action="store_true",
                   help="fit cost-model constants from the records "
                        "(telemetry.calibration.fit_constants)")
    p.add_argument("--json", action="store_true",
                   help="emit one machine-readable JSON object instead "
                        "of the human report")
    args = p.parse_args(argv)

    records = load_step_records(args.run_dir)
    events = load_run_events(args.run_dir)
    if not records and not events:
        print(f"no telemetry under {args.run_dir} (expected steps-*.jsonl "
              "or events-*.jsonl; set AUTODIST_TELEMETRY_DIR when running)",
              file=sys.stderr)
        return 2

    summary = summarize_steps(records) or {}
    fit = fit_constants(records) if args.fit and records else None
    if fit is not None:
        summary["calibration"] = {
            "ici_bandwidth": fit.ici_bandwidth,
            "alpha": fit.alpha,
            "n_records": fit.n_records,
            "mean_abs_error_ms": round(fit.mean_abs_error_s * 1e3, 4),
            "baseline_mean_abs_error_ms": round(
                fit.baseline_mean_abs_error_s * 1e3, 4),
            "improved": fit.improved,
        }

    if args.json:
        payload = dict(summary)
        payload["events"] = events
        print(json.dumps(payload, default=str))
        return 0

    print(f"telemetry summary: {args.run_dir}")
    if summary.get("steps"):
        st = summary.get("step_time") or {}
        print(f"  steps: {summary['steps']}"
              + (f"  |  step time p50 {st.get('p50_ms')} ms  "
                 f"p90 {st.get('p90_ms')} ms  p99 {st.get('p99_ms')} ms"
                 if st else ""))
        if "items_per_s_mean" in summary:
            print(f"  throughput: {summary['items_per_s_mean']} items/s"
                  + (f", {summary['tokens_per_s_mean']} tokens/s"
                     if "tokens_per_s_mean" in summary else ""))
        for name, ph in (summary.get("phases") or {}).items():
            frac = ph["fraction_of_step_time"]
            print(f"  phase {name:16s} mean {ph['mean_ms']:9.3f} ms"
                  + (f"  ({frac:.1%} of step time)"
                     if frac is not None else ""))
        if "skipped_steps" in summary:
            print(f"  numerics: {summary['skipped_steps']} skipped step(s)"
                  + (" + rollback(s)" if summary.get("rollbacks_observed")
                     else ""))
        pm = summary.get("predicted_vs_measured")
        if pm and pm.get("predicted_step_time_s"):
            print(f"  predicted vs measured: "
                  f"{pm['predicted_step_time_s'] * 1e3:.3f} ms predicted, "
                  f"{pm['measured_step_time_s'] * 1e3:.3f} ms measured "
                  f"(x{pm['ratio']:.2f})")
            if pm.get("drift"):
                print(f"  WARN telemetry/model-drift: {pm['drift']}")
    cal = summary.get("calibration")
    if cal:
        print(f"  calibrated: bandwidth {cal['ici_bandwidth']:.3e} B/s, "
              f"alpha {cal['alpha']:.3e} s/collective "
              f"({cal['n_records']} records; mean abs error "
              f"{cal['mean_abs_error_ms']} ms vs "
              f"{cal['baseline_mean_abs_error_ms']} ms uncalibrated)")
    if events:
        t0 = events[0].get("time", time.time())
        shown = events[:max(args.events, 0)]
        print(f"  events ({len(events)} total, showing {len(shown)}):")
        for rec in shown:
            print(_fmt_event(rec, t0))
    return 0


if __name__ == "__main__":
    sys.exit(main())
