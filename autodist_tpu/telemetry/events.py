"""Structured event journal: the durable record of what a run DID.

Supervisor restarts, heartbeat DEAD/WEDGED verdicts, chaos injections,
checkpoint save/verify/restore durations, elastic resizes, numerics
skip/rollback decisions — before this module every one of those died in
a log line.  The journal captures them as structured JSONL events so the
``python -m autodist_tpu.telemetry`` CLI (and any later tooling) can
reconstruct a run's timeline without parsing logs.

Layout: ONE writer per process — ``events-<host>-<pid>.jsonl`` under the
run directory (``AUTODIST_TELEMETRY_DIR`` or an explicit
:func:`configure`), append-only, one JSON object per line with
``time``/``kind``/``host``/``pid`` plus event-specific fields.  The
chief merges by reading every ``events-*.jsonl`` in the directory and
sorting by timestamp (:func:`load_run_events`) — no coordination
needed, which is the point: events must survive the process that
emitted them dying mid-write (each line is flushed).

Emission is failure-proof by contract: :func:`emit_event` never raises
(a full disk must not kill training) and is a near-zero-cost no-op when
telemetry is disabled.  Without a run directory events still accumulate
in a bounded in-memory ring (programmatic access in tests and
notebooks).
"""
from __future__ import annotations

import glob
import json
import os
import socket
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: in-memory ring size when no run directory is configured.
MEMORY_EVENTS = 4096


class EventJournal:
    """Append-only structured event writer (see module docstring)."""

    def __init__(self, directory: Optional[str] = None,
                 host: Optional[str] = None):
        self._dir = directory
        self._host = host or socket.gethostname()
        self._pid = os.getpid()
        self._lock = threading.Lock()
        self._memory: deque = deque(maxlen=MEMORY_EVENTS)
        self._fh = None
        self._path: Optional[str] = None
        if directory:
            safe = self._host.replace("/", "_").replace(":", "_")
            self._path = os.path.join(
                directory, f"events-{safe}-{self._pid}.jsonl")

    @property
    def path(self) -> Optional[str]:
        return self._path

    @property
    def events(self) -> List[dict]:
        """The in-memory view (bounded to the last MEMORY_EVENTS)."""
        with self._lock:
            return list(self._memory)

    def emit(self, kind: str, **fields: Any) -> Optional[dict]:
        """Record one event; returns the record, or None on write-path
        failure (never raises — telemetry must not kill training)."""
        record: Dict[str, Any] = {"time": time.time(), "kind": str(kind),
                                  "host": self._host, "pid": self._pid}
        record.update(fields)
        try:
            with self._lock:
                self._memory.append(record)
                if self._path is not None:
                    if self._fh is None:
                        os.makedirs(os.path.dirname(self._path) or ".",
                                    exist_ok=True)
                        self._fh = open(self._path, "a", encoding="utf-8")
                    self._fh.write(json.dumps(record, default=str) + "\n")
                    self._fh.flush()
            return record
        except Exception:
            return None

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# -- the process journal -----------------------------------------------------

_journal: Optional[EventJournal] = None
_journal_lock = threading.Lock()


def _run_directory() -> Optional[str]:
    from autodist_tpu.const import ENV

    return ENV.AUTODIST_TELEMETRY_DIR.val or None


def get_journal() -> EventJournal:
    """The process-wide journal, created on first use from
    ``AUTODIST_TELEMETRY_DIR`` (in-memory-only when unset)."""
    global _journal
    with _journal_lock:
        if _journal is None:
            _journal = EventJournal(directory=_run_directory())
        return _journal


def configure(directory: Optional[str]) -> EventJournal:
    """(Re)point the process journal at ``directory`` (None = in-memory
    only).  Closes the previous writer."""
    global _journal
    with _journal_lock:
        if _journal is not None:
            _journal.close()
        _journal = EventJournal(directory=directory)
        return _journal


def emit_event(kind: str, **fields: Any) -> Optional[dict]:
    """Emit one structured event on the process journal.  No-op when
    telemetry is disabled; never raises."""
    from autodist_tpu.telemetry.registry import telemetry_enabled

    try:
        if not telemetry_enabled():
            return None
        return get_journal().emit(kind, **fields)
    except Exception:  # pragma: no cover - defensive
        return None


def reset_for_testing() -> None:
    global _journal
    with _journal_lock:
        if _journal is not None:
            _journal.close()
        _journal = None


# -- reading / merging -------------------------------------------------------

def read_events(path: str) -> List[dict]:
    """Parse one events JSONL file (corrupt/truncated lines skipped —
    a writer may have died mid-line)."""
    out: List[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def load_run_events(run_dir: str,
                    tail: Optional[int] = None) -> List[dict]:
    """The chief-side merge: every ``events-*.jsonl`` under ``run_dir``
    (recursive), time-sorted into one timeline.  ``tail`` keeps only
    the newest N events after the merge — what a crash bundle snapshots
    (``telemetry/flightrec.py``)."""
    merged: List[dict] = []
    for path in glob.glob(os.path.join(run_dir, "**", "events-*.jsonl"),
                          recursive=True):
        merged.extend(read_events(path))
    merged.sort(key=lambda r: r.get("time", 0.0))
    if tail is not None:
        merged = merged[-max(int(tail), 0):]
    return merged
