"""Schedule-aware profiler: per-leg measured timings + request spans.

PR 6's telemetry measures the step as ONE number and the calibration
bridge regresses two global constants from it; PR 7's schedule IR names
every collective leg (kind, bytes, dtype, axis, slot) and
``estimate_ir_cost`` prices them individually.  Prediction happens at
leg granularity, measurement at step granularity — so calibration
cannot tell a slow ring hop from a slow optimizer update, and the 5-7%
guard overhead in BENCH_guard.json stays unattributed.  This module is
the measurement half of closing that gap (the Automap argument,
arXiv:2112.02958: search quality tracks measured, fine-grained
calibration):

* :class:`LegSample` — one measured timing for one schedule-IR leg,
  keyed by ``schedule_fingerprint`` + ``leg_id``, JSONL-persisted as
  ``legs-<host>-<pid>.jsonl`` next to the StepRecord stream (bench runs
  and real runs feed the same files).
* :class:`LegProfiler` — produces LegSamples two ways:

  - **timed micro-runs** (:meth:`LegProfiler.profile_ir`): the IR's
    legs are grouped by ``(kind, alg, dtype, compressor, axis,
    nbytes)`` and each group's representative operation (psum_scatter /
    all_gather / psum / one ppermute hop / an Adam-shaped update) is
    jitted at the leg's actual byte size on the session mesh and timed
    (interleaved warmup + min-of-repeats).  Every leg in the group gets
    the measured time — the per-leg resolution the calibration
    regression needs;
  - **profiler-trace parsing** (:meth:`LegProfiler.parse_trace`): when
    a jax profiler capture window exists (``AUTODIST_TRACE_STEPS`` /
    ``AUTODIST_TRACE_AT``), the ``autodist_sync/*`` named-scope spans
    the sync path already carries (explicit_sync.py / overlap.py /
    quant_ring.py) are read out of the Chrome-trace JSON and mapped to
    leg kinds — measured device time with zero extra instrumentation.

* request spans (:func:`record_span` / :func:`load_spans`) — the
  serving trace plane: router/server/scheduler record durational spans
  (queue-wait, prefill chunk, decode, whole request) tagged with a
  propagated trace id into ``spans-<host>-<pid>.jsonl``; the trace
  exporter merges them into the same Chrome-trace file as training
  steps and leg samples (docs/observability.md).

Cost discipline: nothing here rides the training step.  Micro-runs are
explicit calls outside the step loop, trace parsing is offline, and
span recording happens on serving completion paths that already pay a
host sync — the <1 % profiler-overhead budget BENCH_profiler.json
verifies.  Everything except :meth:`profile_ir` imports without jax.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import socket
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: in-memory span ring size when no run directory is configured.
MEMORY_SPANS = 4096

#: micro-run timing defaults (interleaved; min over repeats).
MICRO_WARMUP = 2
MICRO_REPEATS = 10

#: sample sources.
SOURCE_MICROBENCH = "microbench"
SOURCE_TRACE = "trace"


@dataclass
class LegSample:
    """One measured timing for one schedule-IR leg.

    ``(schedule_fingerprint, leg_id)`` is the key that joins a sample
    back to the exact program that was measured; ``kind``/``alg``/
    ``dtype``/``compressor``/``axis``/``slot``/``nbytes`` are copied
    from the leg so the calibration regression (and the CLI compare
    report) never needs the IR in hand.  ``predicted_s`` carries the
    leg-priced cost-model estimate under the DEFAULT constants — the
    measured-vs-predicted pair at leg granularity."""

    schedule_fingerprint: str
    leg_id: str
    kind: str
    measured_s: float
    alg: str = ""
    dtype: str = "float32"
    compressor: str = "NoneCompressor"
    axis: str = ""
    slot: int = -1
    nbytes: int = 0
    predicted_s: Optional[float] = None
    source: str = SOURCE_MICROBENCH
    host: str = ""
    time_unix: float = 0.0

    def to_json(self) -> str:
        d = {k: v for k, v in asdict(self).items() if v is not None}
        return json.dumps(d)

    @classmethod
    def from_dict(cls, d: dict) -> "LegSample":
        known = set(cls.__dataclass_fields__)
        return cls(**{k: v for k, v in d.items() if k in known})


def write_leg_samples(samples: Sequence[LegSample],
                      directory: str) -> Optional[str]:
    """Append samples as JSONL (``legs-<host>-<pid>.jsonl``) under
    ``directory``; returns the path (None on write failure — profiling
    must never kill the run)."""
    if not samples:
        return None
    host = socket.gethostname().replace("/", "_").replace(":", "_")
    path = os.path.join(directory, f"legs-{host}-{os.getpid()}.jsonl")
    try:
        os.makedirs(directory, exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            for s in samples:
                f.write(s.to_json() + "\n")
        return path
    except OSError:
        return None


def load_leg_samples(run_dir: str) -> List[LegSample]:
    """Every ``legs-*.jsonl`` sample under ``run_dir`` (recursive),
    time-ordered — the calibrator's and the exporter's input.  Corrupt
    lines are skipped (a writer may have died mid-line)."""
    out: List[LegSample] = []
    for path in sorted(glob.glob(
            os.path.join(run_dir, "**", "legs-*.jsonl"), recursive=True)):
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(LegSample.from_dict(json.loads(line)))
                    except (ValueError, TypeError):
                        continue
        except OSError:
            continue
    out.sort(key=lambda s: (s.time_unix, s.leg_id))
    return out


# -- span-name -> leg-kind mapping (the autodist_sync/* vocabulary) ----------

#: named-scope prefix the sync path stamps (timeline.sync_span).
SYNC_SCOPE_PREFIX = "autodist_sync/"

#: ordered (name-fragment, leg-kind) rules for trace-span attribution —
#: first match wins; fragments mirror the sync_span call sites in
#: explicit_sync.py / overlap.py / quant_ring.py.
_SPAN_KIND_RULES: Tuple[Tuple[str, str], ...] = (
    ("quant_ring_fused/", "fused_hop"),
    ("fused_pack_detect", "fused_detect"),
    ("fused_shard_update", "fused_update"),
    ("ring_reduce_scatter/", "ppermute_hop"),
    ("ring_all_gather/", "ppermute_hop"),
    ("quant_ring_reduce_scatter/", "ppermute_hop"),
    ("quant_ring_all_gather/", "ppermute_hop"),
    ("param_gather/", "all_gather"),
    ("quant_all_gather", "all_gather"),
    ("guard_rollup", "psum_guard"),
    ("zero1_shard_update", "update"),
    ("tree_update", "update"),
    ("quant_all_to_all_reduce_scatter", "reduce_scatter"),
    ("moe_dispatch", "all_to_all"),
    ("moe_combine", "all_to_all"),
    ("expert_all_to_all", "all_to_all"),
    ("bucket_quant_reduce/", "all_reduce"),
    ("bucket_compressed_reduce/", "all_reduce"),
    ("bucket_reduce/", "all_reduce"),
    ("per_var_reduce/", "all_reduce"),
    ("one_shot_all_reduce", "all_reduce"),
)


def span_leg_kind(name: str) -> Optional[str]:
    """Leg kind an ``autodist_sync/*`` span name implies, or None for
    a name outside the sync vocabulary."""
    if SYNC_SCOPE_PREFIX in name:
        name = name.split(SYNC_SCOPE_PREFIX, 1)[1]
    for fragment, kind in _SPAN_KIND_RULES:
        if fragment in name:
            return kind
    return None


class LegProfiler:
    """Produce per-leg measured timings for a schedule IR.

    ``mesh`` (a ``jax.sharding.Mesh``) enables real collective
    micro-runs; without one (or on a degenerate axis) the group's
    operation runs locally — still a measurement of the host's compute/
    memory cost at the leg's byte size, which is what a single-process
    test environment can honestly provide.  Never raises from the
    measurement path: a group whose micro-program fails to build is
    skipped (profiling is advisory)."""

    def __init__(self, mesh: Any = None, *, warmup: int = MICRO_WARMUP,
                 repeats: int = MICRO_REPEATS):
        self._mesh = mesh
        self._warmup = max(int(warmup), 0)
        self._repeats = max(int(repeats), 1)
        self._host = socket.gethostname()

    # -- micro-runs --------------------------------------------------------
    def profile_ir(self, ir, *, include_update: bool = True
                   ) -> List[LegSample]:
        """Timed micro-runs over the IR's leg groups; one
        :class:`LegSample` per leg (legs in one group share the group's
        measured time).  ``predicted_s`` is stamped from the leg-priced
        cost model under the default constants."""
        from autodist_tpu.strategy.cost_model import leg_cost_s

        fingerprint = ir.fingerprint()
        groups: Dict[Tuple, List[Any]] = {}
        for leg in ir.legs:
            if leg.kind in ("update", "fused_update") and not include_update:
                continue
            key = (leg.kind, leg.alg, leg.dtype, leg.compressor,
                   leg.axis, int(leg.nbytes))
            groups.setdefault(key, []).append(leg)
        out: List[LegSample] = []
        now = time.time()
        for (kind, alg, dtype, compressor, axis, nbytes), legs \
                in groups.items():
            d = max(int(ir.axes.get(axis, 1)), 1) if axis else 1
            t = self._time_group(kind, dtype, nbytes, axis, d)
            if t is None:
                continue
            for leg in legs:
                out.append(LegSample(
                    schedule_fingerprint=fingerprint, leg_id=leg.id,
                    kind=kind, measured_s=t, alg=alg, dtype=dtype,
                    compressor=compressor, axis=axis, slot=int(leg.slot),
                    nbytes=int(nbytes),
                    predicted_s=leg_cost_s(leg, ir),
                    source=SOURCE_MICROBENCH, host=self._host,
                    time_unix=now))
        self._set_kind_gauges(out)
        return out

    def _time_group(self, kind: str, dtype: str, nbytes: int,
                    axis: str, d: int) -> Optional[float]:
        """Min-of-repeats wall time of one leg group's representative
        operation, or None when the micro-program cannot build."""
        try:
            fn, arg = self._build_micro(kind, dtype, nbytes, axis, d)
        except Exception:
            return None
        try:
            for _ in range(self._warmup):
                _block(fn(arg))
            best = None
            for _ in range(self._repeats):
                t0 = time.perf_counter()
                _block(fn(arg))
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            return best
        except Exception:
            return None

    def _build_micro(self, kind: str, dtype: str, nbytes: int,
                     axis: str, d: int):
        """(jitted fn, placed arg) for one leg group.  Collective kinds
        lower to their real primitive inside shard_map when the mesh
        has the axis at size > 1; otherwise (and for update legs) the
        micro-program is the equivalent local computation."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        dt = np.dtype(dtype)
        n = max(int(nbytes) // dt.itemsize, 1)
        mesh = self._mesh
        collective = kind in ("reduce_scatter", "all_gather", "all_reduce",
                              "ppermute_hop", "fused_hop", "psum_guard",
                              "ps_exchange", "all_to_all",
                              "hier_reduce_scatter", "dcn_all_reduce",
                              "dcn_exchange", "hier_all_gather")
        if collective and mesh is not None and axis \
                and int(dict(mesh.shape).get(axis, 1)) > 1:
            from jax.sharding import PartitionSpec as P

            from autodist_tpu.utils import compat

            d = int(dict(mesh.shape)[axis])
            n = ((n + d - 1) // d) * d
            if kind in ("reduce_scatter", "hier_reduce_scatter",
                        "dcn_exchange"):
                # The hier/dcn RS-shaped kinds run the same scatter
                # primitive — the micro-run times its wire on THIS
                # mesh's links (a CPU simulated-slice mesh has no DCN;
                # real per-tier constants come from pod traces).
                body = lambda x: jax.lax.psum_scatter(  # noqa: E731
                    x, axis, scatter_dimension=0, tiled=True)
                out_spec = P(axis)
            elif kind in ("all_gather", "hier_all_gather"):
                # per-device shard gathers back to the full vector
                body = lambda x: jax.lax.all_gather(  # noqa: E731
                    x, axis, tiled=True)
                out_spec = P()
            elif kind == "all_to_all":
                # MoE dispatch/combine: every device re-slices its
                # per-device capacity buffer across the expert axis —
                # the honest wire shape of the expert a2a pair.
                body = lambda x: jax.lax.all_to_all(  # noqa: E731
                    x.reshape(d, -1), axis, split_axis=0, concat_axis=0,
                    tiled=False).reshape(-1)
                out_spec = P(axis)
            elif kind in ("ppermute_hop", "fused_hop"):
                # A fused hop is still one ppermute on the wire; its
                # compute boundary rides the kernel, so the micro-run's
                # wire cost is the honest shared part.
                perm = [(i, (i + 1) % d) for i in range(d)]
                body = lambda x: jax.lax.ppermute(  # noqa: E731
                    x, axis, perm)
                out_spec = P(axis)
            else:  # all_reduce / psum_guard / ps_exchange / dcn_all_reduce
                body = lambda x: jax.lax.psum(x, axis)  # noqa: E731
                out_spec = P()
            fn = jax.jit(compat.shard_map(
                body, mesh=mesh, in_specs=P(axis), out_specs=out_spec,
                check_vma=False))
            arg = jnp.zeros((n,), dt)
            return fn, arg
        if kind in ("update", "fused_update"):
            # Adam-shaped: read param+2 slots, write param+2 slots — the
            # HBM-bound memory traffic the update leg models.  The
            # fused_update micro-run times the same arithmetic XLA-fused
            # (the kernel's one-pass cost on real TPU shows up in its
            # own fitted constant instead).
            def body(p):
                m = p * 0.9
                v = p * p * 0.999
                return p - 1e-3 * m / (jnp.sqrt(v) + 1e-8)
        elif kind == "fused_detect":
            # The guard statistics pass: one read of the bucket
            # producing both the finite count and the squared sum.
            def body(p):
                return (jnp.sum(p * p),
                        jnp.sum(1.0 - jnp.isfinite(p).astype(jnp.float32)))
        else:
            # Degenerate-axis collective: the data movement collapses;
            # time the local touch of the buffer (honest lower bound).
            def body(p):
                return p + p
        fn = jax.jit(body)
        arg = jnp.zeros((n,), dt if dt.kind == "f" else np.dtype("float32"))
        return fn, arg

    # -- trace parsing -----------------------------------------------------
    def parse_trace(self, trace_dir: str,
                    schedule_fingerprint: str = "") -> List[LegSample]:
        """LegSamples from the ``autodist_sync/*`` named-scope spans in
        a jax profiler capture under ``trace_dir`` (the
        ``AUTODIST_TRACE_STEPS``/``AUTODIST_TRACE_AT`` output): every
        ``*.trace.json[.gz]`` is searched recursively, Chrome-trace
        duration events whose names carry the sync vocabulary become
        samples with ``source="trace"``.  Device time attributed BY
        NAME — no extra per-step instrumentation."""
        out: List[LegSample] = []
        now = time.time()
        paths = sorted(
            glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
            + glob.glob(os.path.join(trace_dir, "**", "*.trace.json"),
                        recursive=True))
        for path in paths:
            try:
                opener = gzip.open if path.endswith(".gz") else open
                with opener(path, "rt", encoding="utf-8",
                            errors="replace") as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                continue
            events = payload.get("traceEvents", payload) \
                if isinstance(payload, dict) else payload
            if not isinstance(events, list):
                continue
            for ev in events:
                if not isinstance(ev, dict):
                    continue
                name = str(ev.get("name", ""))
                kind = span_leg_kind(name)
                if kind is None or "dur" not in ev:
                    continue
                try:
                    dur_s = float(ev["dur"]) / 1e6
                except (TypeError, ValueError):
                    continue
                leg = name.split(SYNC_SCOPE_PREFIX, 1)[-1]
                out.append(LegSample(
                    schedule_fingerprint=schedule_fingerprint,
                    leg_id=leg, kind=kind, measured_s=dur_s,
                    source=SOURCE_TRACE, host=self._host, time_unix=now))
        self._set_kind_gauges(out)
        return out

    # -- gauges ------------------------------------------------------------
    def _set_kind_gauges(self, samples: Sequence[LegSample]) -> None:
        """Surface per-leg-kind measured (exposed) milliseconds as
        gauges on the process registry (docs/observability.md catalog:
        ``autodist_leg_exposed_ms{kind=...}``) — slotted legs before
        the final microbatch ride behind compute, so only end-of-step /
        final-slot samples count as exposed."""
        if not samples:
            return
        from autodist_tpu.telemetry import registry as _reg
        last_slot = max((s.slot for s in samples
                         if s.slot is not None and s.slot >= 0),
                        default=0)
        totals: Dict[str, float] = {}
        for s in samples:
            if s.slot is not None and 0 <= s.slot < last_slot:
                continue            # hidden behind the next microbatch
            totals[s.kind] = totals.get(s.kind, 0.0) + s.measured_s
        for kind, total in totals.items():
            _reg.gauge(
                "autodist_leg_exposed_ms",
                "measured exposed milliseconds per schedule-IR leg kind",
                labels={"kind": kind}).set(round(total * 1e3, 6))


def _block(x):
    import jax

    jax.block_until_ready(x)


# -- request spans (the serving trace plane) ---------------------------------

class _SpanWriter:
    """One durational-span JSONL writer per process
    (``spans-<host>-<pid>.jsonl``), modeled on the event journal:
    append-only, flushed per line, never raises, bounded in-memory ring
    without a run directory."""

    def __init__(self, directory: Optional[str] = None):
        self._dir = directory
        self._host = socket.gethostname()
        self._pid = os.getpid()
        self._lock = threading.Lock()
        self._memory: deque = deque(maxlen=MEMORY_SPANS)
        self._fh = None
        self._path: Optional[str] = None
        if directory:
            safe = self._host.replace("/", "_").replace(":", "_")
            self._path = os.path.join(
                directory, f"spans-{safe}-{self._pid}.jsonl")

    @property
    def spans(self) -> List[dict]:
        with self._lock:
            return list(self._memory)

    def record(self, name: str, *, start_unix: float, dur_s: float,
               trace_id: str = "", **args: Any) -> Optional[dict]:
        rec: Dict[str, Any] = {
            "name": str(name), "trace_id": str(trace_id),
            "start_unix": float(start_unix), "dur_s": float(dur_s),
            "host": self._host, "pid": self._pid}
        if args:
            rec["args"] = args
        try:
            with self._lock:
                self._memory.append(rec)
                if self._path is not None:
                    if self._fh is None:
                        os.makedirs(os.path.dirname(self._path) or ".",
                                    exist_ok=True)
                        self._fh = open(self._path, "a", encoding="utf-8")
                    self._fh.write(json.dumps(rec, default=str) + "\n")
                    self._fh.flush()
            return rec
        except Exception:
            return None

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


_spans: Optional[_SpanWriter] = None
_spans_lock = threading.Lock()


def _span_directory() -> Optional[str]:
    from autodist_tpu.const import ENV

    return ENV.AUTODIST_TELEMETRY_DIR.val or None


def get_span_writer() -> _SpanWriter:
    global _spans
    with _spans_lock:
        if _spans is None:
            _spans = _SpanWriter(directory=_span_directory())
        return _spans


def configure_spans(directory: Optional[str]) -> _SpanWriter:
    """(Re)point the process span writer at ``directory`` (None =
    in-memory only).  Closes the previous writer."""
    global _spans
    with _spans_lock:
        if _spans is not None:
            _spans.close()
        _spans = _SpanWriter(directory=directory)
        return _spans


def record_span(name: str, *, start_unix: float, dur_s: float,
                trace_id: str = "", **args: Any) -> Optional[dict]:
    """Record one durational span on the process writer.  No-op when
    telemetry is disabled; never raises (a full disk must not fail a
    request)."""
    from autodist_tpu.telemetry.registry import telemetry_enabled

    try:
        if not telemetry_enabled():
            return None
        return get_span_writer().record(
            name, start_unix=start_unix, dur_s=dur_s, trace_id=trace_id,
            **args)
    except Exception:  # pragma: no cover - defensive
        return None


def reset_spans_for_testing() -> None:
    global _spans
    with _spans_lock:
        if _spans is not None:
            _spans.close()
        _spans = None


def load_spans(run_dir: str) -> List[dict]:
    """Every ``spans-*.jsonl`` record under ``run_dir`` (recursive),
    start-time-ordered — the trace exporter's serving input."""
    out: List[dict] = []
    for path in glob.glob(os.path.join(run_dir, "**", "spans-*.jsonl"),
                          recursive=True):
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        out.append(rec)
        except OSError:
            continue
    out.sort(key=lambda r: r.get("start_unix", 0.0))
    return out
