"""Goodput accounting: useful step time vs wall time, decomposed.

At pod scale the number that matters is not step time but **goodput** —
the fraction of wall-clock the job spent making forward progress, after
subtracting what failure handling cost: restart gaps (process death →
relaunch → resume), checkpoint stalls (synchronous persistence blocking
the loop), and rollback/re-run loss (steps trained, then discarded or
re-trained after a failure or numerics rollback).  This module is the
pure math half (numpy + stdlib, no jax): ``fit`` emits per-attempt
``goodput/attempt`` events and sets the ``autodist_goodput_ratio``
gauge from :func:`attempt_goodput`; the telemetry CLI reconstructs the
cross-attempt decomposition from a run directory's merged records +
events with :func:`goodput_from_run`; the ``resilience/recovery-gap``
analysis rule shares :func:`recovery_gap_reason` so the lint, the CLI,
and the docs can never disagree about what counts as a gap
(docs/observability.md).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

#: default recovery-loss budget (seconds of lost work per failure) the
#: recovery-gap rule checks the checkpoint cadence against.
RECOVERY_BUDGET_S = 120.0

#: event kinds that count toward checkpoint-stall loss (their
#: duration_s blocks — or races with — the training loop on host).
_STALL_KINDS = ("checkpoint/save", "checkpoint/ram_snapshot")


def recovery_gap_reason(checkpoint_interval_steps: Optional[float],
                        step_time_s: Optional[float],
                        budget_s: float = RECOVERY_BUDGET_S,
                        snapshot_every: Optional[int] = None
                        ) -> Optional[str]:
    """Why the checkpoint cadence exposes too much work to a failure
    (None when it does not).

    The exposure of a cadence of N steps at t seconds/step is N×t: a
    failure right before the next checkpoint loses that much work.  A
    RAM tier snapshotting every K steps caps the exposure at K×t
    regardless of the persistent cadence — so the rule only fires when
    the EFFECTIVE (cheapest-tier) exposure exceeds the budget."""
    if not checkpoint_interval_steps or not step_time_s:
        return None
    interval = float(checkpoint_interval_steps)
    t = float(step_time_s)
    exposure = interval * t
    effective = exposure
    tier = "persistent checkpoints"
    if snapshot_every:
        effective = min(exposure, float(snapshot_every) * t)
        tier = f"RAM snapshots every {int(snapshot_every)} step(s)"
    if effective <= budget_s:
        return None
    return (f"recovery exposure {effective:.1f}s exceeds the "
            f"{budget_s:.0f}s recovery-loss budget: the cheapest tier "
            f"({tier}) leaves up to {effective / t:.0f} step(s) x "
            f"{t * 1e3:.1f} ms/step of work unprotected — shorten the "
            "checkpoint interval or enable/raise the RAM snapshot tier "
            "(AUTODIST_SNAPSHOT_EVERY)")


def attempt_goodput(wall_s: float, useful_s: Optional[float],
                    ckpt_stall_s: float = 0.0,
                    rollback_s: float = 0.0,
                    steps: Optional[int] = None) -> Dict[str, Any]:
    """One attempt's goodput summary (what ``fit`` emits/gauges).

    ``useful_s`` is the summed measured step time when telemetry
    recorded it; falling back to ``wall - stalls`` would flatter the
    ratio, so when it is unknown the ratio is reported as None rather
    than wrong."""
    wall_s = max(float(wall_s), 0.0)
    out: Dict[str, Any] = {
        "wall_s": round(wall_s, 6),
        "useful_step_s": round(useful_s, 6) if useful_s else None,
        "checkpoint_stall_s": round(max(ckpt_stall_s, 0.0), 6),
        "rollback_s": round(max(rollback_s, 0.0), 6),
        "steps": steps,
    }
    if useful_s and wall_s > 0:
        out["goodput_ratio"] = round(min(useful_s / wall_s, 1.0), 4)
    else:
        out["goodput_ratio"] = None
    return out


def _event_time_span(events: List[dict]) -> Optional[float]:
    times = [e["time"] for e in events if isinstance(e.get("time"),
                                                     (int, float))]
    if len(times) < 2:
        return None
    return max(times) - min(times)


def goodput_from_run(records: List[Any], events: List[dict],
                     wall_time_s: Optional[float] = None
                     ) -> Optional[dict]:
    """Cross-attempt goodput decomposition over a merged run directory.

    * **useful** — summed measured step time over all StepRecords,
      MINUS the re-run tail: steps recorded more than once (the replay
      after a restart/rollback resumed below the failure step) count
      once as useful, once as ``rollback_loss``.
    * **restart loss** — for each ``supervisor/attempt_start`` after
      the first, the gap since the previous attempt's last journaled
      event (detection + terminate + backoff + relaunch + restore).
    * **checkpoint stall** — summed ``duration_s`` of synchronous
      ``checkpoint/save`` events plus RAM-snapshot captures (async
      saves report their dispatch half, which is what actually blocked
      the loop).

    Returns None when there is nothing to account (no records and no
    events)."""
    if not records and not events:
        return None
    events = sorted((e for e in events if isinstance(e, dict)),
                    key=lambda e: e.get("time", 0.0))
    wall = wall_time_s or _event_time_span(events)

    # useful vs re-run: a (host, step) pair measured twice means the
    # second run REPLAYED work lost to a restart/rollback.
    useful = 0.0
    rerun = 0.0
    seen = set()
    n_steps = 0
    for r in records:
        t = getattr(r, "step_time_s", None)
        if not t:
            continue
        key = (getattr(r, "host", None), getattr(r, "step", None))
        if key in seen:
            rerun += float(t)
        else:
            seen.add(key)
            useful += float(t)
            n_steps += 1

    stall = 0.0
    for e in events:
        if e.get("kind") in _STALL_KINDS and e.get("duration_s"):
            stall += float(e["duration_s"])

    restart = 0.0
    attempts = 0
    prev_time: Optional[float] = None
    for e in events:
        if e.get("kind") == "supervisor/attempt_start":
            attempts += 1
            if prev_time is not None and e.get("time"):
                restart += max(float(e["time"]) - prev_time, 0.0)
        if e.get("time"):
            prev_time = float(e["time"])

    # rollback loss reported by the numerics path directly (steps
    # discarded between the rollback anchor and the failure step).
    step_t = (useful / n_steps) if n_steps else None
    rollback = rerun
    for e in events:
        if e.get("kind") == "numerics/rollback" and step_t:
            lost = max(int(e.get("step", 0))
                       - int(e.get("restored_step", 0)), 0)
            rollback += lost * step_t

    out: Dict[str, Any] = {
        "steps": n_steps,
        "attempts": attempts or None,
        "useful_step_s": round(useful, 6),
        "losses": {
            "restart_s": round(restart, 6),
            "checkpoint_stall_s": round(stall, 6),
            "rollback_s": round(rollback, 6),
        },
    }
    if wall:
        out["wall_s"] = round(wall, 6)
        accounted = useful + restart + stall + rollback
        out["losses"]["other_s"] = round(max(wall - accounted, 0.0), 6)
        out["goodput_ratio"] = round(min(useful / wall, 1.0), 4) \
            if wall > 0 else None
    return out


def checkpoint_cadence(records: List[Any],
                       events: List[dict]) -> Optional[dict]:
    """Observed persistent-checkpoint cadence of a run — the measured
    inputs to :func:`recovery_gap_reason` (step interval between
    ``checkpoint/save`` events, median measured step time, and the RAM
    snapshot cadence when the tier ran)."""
    saves = sorted(int(e["step"]) for e in events
                   if e.get("kind") == "checkpoint/save"
                   and e.get("step") is not None)
    snaps = sorted(int(e["step"]) for e in events
                   if e.get("kind") == "checkpoint/ram_snapshot"
                   and e.get("step") is not None)
    times = sorted(float(r.step_time_s) for r in records
                   if getattr(r, "step_time_s", None))
    if len(saves) < 2 or not times:
        return None
    gaps = [b - a for a, b in zip(saves, saves[1:]) if b > a]
    if not gaps:
        return None
    snap_every = None
    if len(snaps) >= 2:
        sg = [b - a for a, b in zip(snaps, snaps[1:]) if b > a]
        snap_every = min(sg) if sg else None
    return {
        "checkpoint_interval_steps": min(gaps),
        "step_time_s": times[len(times) // 2],
        "snapshot_every": snap_every,
    }
