"""Chief-side cross-host aggregation: registries, timelines, stragglers.

A multi-host run writes one stream per process into a shared run
directory (steps/events/legs/spans JSONL — the per-file-per-writer
layout that needs no coordination).  This module is the chief's merge
half:

* **metrics registries** — :func:`write_registry_snapshot` dumps one
  process's registry as ``metrics-<host>-<pid>.json`` and
  :func:`merge_registry_snapshots` folds every snapshot into one
  registry.  The merge is EXACT by construction (fixed histogram
  bounds, docs/observability.md): merged bucket counts equal what a
  single global histogram would have observed.
* **step timelines** — :func:`per_host_step_stats` groups StepRecords
  by their stamped host; :func:`aggregate_run` computes per-host
  step-time skew (slowest/fastest median) and the straggler verdict
  through the SHARED pure rule
  :func:`~autodist_tpu.telemetry.calibration.straggler_reason` — the
  same string the ``telemetry/straggler`` analysis WARN and the CLI
  print.
* **gauges** — the verdict lands on the process registry as
  ``autodist_host_step_skew_ratio`` and ``autodist_straggler_count``
  so a chief-side Prometheus scrape sees fleet health without parsing
  JSONL.

Everything is numpy + stdlib (jax-free): the chief may be a CPU-only
coordinator host.
"""
from __future__ import annotations

import glob
import json
import os
import socket
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from autodist_tpu.telemetry.calibration import (
    STRAGGLER_THRESHOLD,
    straggler_reason,
)
from autodist_tpu.telemetry.registry import MetricsRegistry

_UNKNOWN_HOST = "host-0"


# -- registry snapshots ------------------------------------------------------

def write_registry_snapshot(directory: str,
                            registry: Optional[MetricsRegistry] = None
                            ) -> Optional[str]:
    """Dump one process's registry (default: the process registry) as
    ``metrics-<host>-<pid>.json`` under ``directory``; None on write
    failure (telemetry never kills the run)."""
    from autodist_tpu.telemetry.registry import DEFAULT_REGISTRY

    registry = DEFAULT_REGISTRY if registry is None else registry
    host = socket.gethostname().replace("/", "_").replace(":", "_")
    path = os.path.join(directory, f"metrics-{host}-{os.getpid()}.json")
    try:
        os.makedirs(directory, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(registry.to_dict(), f)
            f.write("\n")
        return path
    except OSError:
        return None


def merge_registry_snapshots(run_dir: str) -> MetricsRegistry:
    """Fold every ``metrics-*.json`` under ``run_dir`` (recursive) into
    one registry — counters and fixed-bound histograms merge exactly;
    corrupt snapshots are skipped."""
    merged = MetricsRegistry()
    for path in sorted(glob.glob(
            os.path.join(run_dir, "**", "metrics-*.json"),
            recursive=True)):
        try:
            with open(path, "r", encoding="utf-8") as f:
                snapshot = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(snapshot, list):
            try:
                merged.merge_dict(snapshot)
            except (ValueError, KeyError, TypeError):
                continue
    return merged


# -- per-host step timelines -------------------------------------------------

def per_host_step_stats(records: Sequence[Any]) -> Dict[str, dict]:
    """Group StepRecords by stamped host: ``{host: {n, median_s,
    mean_s, p90_s}}``.  Records from before the host field existed
    group under a single synthetic host (a one-host run is never a
    straggler)."""
    by_host: Dict[str, List[float]] = {}
    for r in records:
        st = getattr(r, "step_time_s", None) if not isinstance(r, dict) \
            else r.get("step_time_s")
        if not st or st <= 0:
            continue
        host = (getattr(r, "host", None) if not isinstance(r, dict)
                else r.get("host")) or _UNKNOWN_HOST
        by_host.setdefault(host, []).append(float(st))
    out: Dict[str, dict] = {}
    for host, times in sorted(by_host.items()):
        arr = np.asarray(times, np.float64)
        out[host] = {
            "n": int(arr.size),
            "median_s": float(np.median(arr)),
            "mean_s": float(arr.mean()),
            "p90_s": float(np.percentile(arr, 90)),
        }
    return out


def aggregate_run(run_dir: str, *,
                  threshold: float = STRAGGLER_THRESHOLD) -> dict:
    """The chief-side roll-up of one run directory: per-host step
    stats, skew ratio, the straggler verdict (shared pure rule), the
    exactly-merged registry snapshot, and journal/span counts.  Also
    sets the fleet gauges on the process registry (see module
    docstring)."""
    from autodist_tpu.telemetry import registry as _reg
    from autodist_tpu.telemetry.events import load_run_events
    from autodist_tpu.telemetry.profiler import load_leg_samples
    from autodist_tpu.telemetry.timeline import load_step_records

    records = load_step_records(run_dir)
    hosts = per_host_step_stats(records)
    medians = {h: s["median_s"] for h, s in hosts.items()}
    skew = (max(medians.values()) / min(medians.values())
            if len(medians) >= 2 and min(medians.values()) > 0 else 1.0)
    verdict = straggler_reason(medians, threshold=threshold)
    stragglers = 0
    if verdict is not None and medians:
        fastest = min(medians.values())
        stragglers = sum(1 for t in medians.values()
                         if t > threshold * fastest)
    merged = merge_registry_snapshots(run_dir)
    journal = load_run_events(run_dir)
    legs = load_leg_samples(run_dir)
    _reg.gauge(
        "autodist_host_step_skew_ratio",
        "slowest/fastest per-host median step time").set(round(skew, 6))
    _reg.gauge(
        "autodist_straggler_count",
        "hosts whose median step time exceeds the straggler "
        "threshold x the fastest host's").set(stragglers)
    return {
        "run_dir": os.path.abspath(run_dir),
        "hosts": hosts,
        "n_hosts": len(hosts),
        "step_skew_ratio": round(skew, 4),
        "straggler": verdict,
        "straggler_count": stragglers,
        "n_records": len(records),
        "n_journal_events": len(journal),
        "n_leg_samples": len(legs),
        "merged_metrics": merged.to_dict(),
    }
