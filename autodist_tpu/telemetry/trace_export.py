"""Cross-host trace export: one run directory → one Chrome-trace JSON.

A recorded run scatters its story across four JSONL streams — StepRecord
timelines (host phases), leg samples (measured sync legs), the event
journal (supervisor / chaos / saver / numerics events), and serving
request spans — each chief-mergeable on its own but never visible as ONE
timeline.  :func:`export_trace` merges them into a single
`Chrome Trace Event Format <https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU>`_
JSON file (the ``traceEvents`` array form) that ``chrome://tracing`` and
`Perfetto <https://ui.perfetto.dev>`_ open directly:

* one **pid row per host** (Perfetto renders pids as process groups, so
  a 4-host run shows four aligned tracks);
* per host, a ``train/steps`` thread of complete (``ph: "X"``) step
  events with the host-phase breakdown (data_load / dispatch /
  blocking_fetch) nested inside each step's window, annotated with
  loss/fingerprint/throughput in ``args``;
* a ``sync/legs (measured)`` thread of leg-sample events (micro-run or
  trace-derived timings, laid out at their measurement timestamps) with
  kind/alg/bytes/predicted-vs-measured in ``args``;
* an ``events`` thread of instant (``ph: "i"``) journal events;
* a ``serving/<track>`` thread per span name family (queue_wait /
  prefill / decode / request / route), each event carrying its
  propagated ``trace_id`` so one request's spans correlate across
  router and replica hosts.

Timestamps are microseconds relative to the run's earliest record (the
``ts``/``dur`` contract), so traces from any wall-clock era align at 0.
Pure stdlib + the sibling telemetry readers — jax-free like the rest of
the CLI (``python -m autodist_tpu.telemetry <run_dir> --export-trace``).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

#: synthetic thread ids per track family (stable ordering in the UI).
TID_STEPS = 1
TID_PHASES = 2
TID_LEGS = 3
TID_EVENTS = 4
TID_SERVING_BASE = 10

_UNKNOWN_HOST = "host-0"


def _us(t: float, t0: float) -> float:
    return round((t - t0) * 1e6, 3)


class _Pids:
    """host name → stable synthetic pid, with process_name metadata."""

    def __init__(self, events: List[dict]):
        self._events = events
        self._pids: Dict[str, int] = {}

    def pid(self, host: Optional[str]) -> int:
        host = host or _UNKNOWN_HOST
        if host not in self._pids:
            pid = len(self._pids) + 1
            self._pids[host] = pid
            self._events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": host}})
        return self._pids[host]


def _thread_meta(events: List[dict], pid: int, tid: int,
                 name: str, seen: set) -> None:
    if (pid, tid) in seen:
        return
    seen.add((pid, tid))
    events.append({"name": "thread_name", "ph": "M", "pid": pid,
                   "tid": tid, "args": {"name": name}})


def chrome_trace_events(records: Sequence[Any] = (),
                        leg_samples: Sequence[Any] = (),
                        journal: Sequence[dict] = (),
                        spans: Sequence[dict] = ()) -> List[dict]:
    """Merge the four streams into one ``traceEvents`` list (see module
    docstring).  Pure function of already-loaded data — the unit the
    golden test locks down."""
    out: List[dict] = []
    pids = _Pids(out)
    threads: set = set()

    # Common time origin: earliest wall timestamp across every stream.
    starts: List[float] = []
    for r in records:
        t = getattr(r, "time_unix", None)
        st = getattr(r, "step_time_s", None) or 0.0
        if t:
            starts.append(float(t) - float(st))
    starts += [float(e["time"]) for e in journal if e.get("time")]
    starts += [float(s["start_unix"]) for s in spans
               if s.get("start_unix")]
    starts += [float(getattr(s, "time_unix", 0.0)) for s in leg_samples
               if getattr(s, "time_unix", 0.0)]
    t0 = min(starts) if starts else 0.0

    # -- training steps + nested host phases ------------------------------
    for r in records:
        t_end = getattr(r, "time_unix", None)
        dt = getattr(r, "step_time_s", None)
        if not t_end or not dt:
            continue
        pid = pids.pid(getattr(r, "host", None))
        _thread_meta(out, pid, TID_STEPS, "train/steps", threads)
        args: Dict[str, Any] = {"step": getattr(r, "step", None)}
        for k in ("loss", "items_per_s", "tokens_per_s",
                  "schedule_fingerprint", "predicted_step_time_s",
                  "skipped_steps"):
            v = getattr(r, k, None)
            if v is not None:
                args[k] = v
        start = float(t_end) - float(dt)
        out.append({"name": f"step {getattr(r, 'step', '?')}",
                    "cat": "train", "ph": "X", "pid": pid,
                    "tid": TID_STEPS, "ts": _us(start, t0),
                    "dur": round(float(dt) * 1e6, 3), "args": args})
        # Phases have durations, not offsets: lay them out sequentially
        # inside the step window (their sum is <= the step time; the
        # remainder is device execution the host did not observe).
        cursor = start
        _thread_meta(out, pid, TID_PHASES, "train/host-phases", threads)
        for name, sec in sorted((getattr(r, "phases", None) or {}).items()):
            if not sec or sec <= 0:
                continue
            out.append({"name": name, "cat": "phase", "ph": "X",
                        "pid": pid, "tid": TID_PHASES,
                        "ts": _us(cursor, t0),
                        "dur": round(float(sec) * 1e6, 3),
                        "args": {"step": getattr(r, "step", None)}})
            cursor += float(sec)

    # -- measured sync legs ------------------------------------------------
    cursor_by_host: Dict[str, float] = {}
    for s in leg_samples:
        host = getattr(s, "host", None)
        pid = pids.pid(host)
        _thread_meta(out, pid, TID_LEGS, "sync/legs (measured)", threads)
        t = getattr(s, "time_unix", 0.0) or t0
        # Samples measured in one batch share a timestamp: advance a
        # per-host cursor so they render side by side, not stacked.
        cursor = max(cursor_by_host.get(host or "", 0.0), float(t))
        dur = float(getattr(s, "measured_s", 0.0) or 0.0)
        args = {"kind": getattr(s, "kind", ""),
                "alg": getattr(s, "alg", ""),
                "nbytes": getattr(s, "nbytes", 0),
                "slot": getattr(s, "slot", -1),
                "compressor": getattr(s, "compressor", ""),
                "source": getattr(s, "source", ""),
                "schedule_fingerprint":
                    getattr(s, "schedule_fingerprint", "")}
        pred = getattr(s, "predicted_s", None)
        if pred is not None:
            args["predicted_s"] = pred
        out.append({"name": getattr(s, "leg_id", "leg"), "cat": "leg",
                    "ph": "X", "pid": pid, "tid": TID_LEGS,
                    "ts": _us(cursor, t0),
                    "dur": round(dur * 1e6, 3), "args": args})
        cursor_by_host[host or ""] = cursor + dur

    # -- journal events (instants) ----------------------------------------
    for e in journal:
        t = e.get("time")
        if not t:
            continue
        pid = pids.pid(e.get("host"))
        _thread_meta(out, pid, TID_EVENTS, "events", threads)
        args = {k: v for k, v in e.items()
                if k not in ("time", "kind", "host")}
        out.append({"name": str(e.get("kind", "event")), "cat": "event",
                    "ph": "i", "s": "t", "pid": pid, "tid": TID_EVENTS,
                    "ts": _us(float(t), t0), "args": args})

    # -- serving request spans --------------------------------------------
    serving_tids: Dict[str, int] = {}
    for s in spans:
        t = s.get("start_unix")
        if t is None:
            continue
        pid = pids.pid(s.get("host"))
        name = str(s.get("name", "span"))
        family = name.split("/", 1)[0]
        tid = serving_tids.setdefault(
            family, TID_SERVING_BASE + len(serving_tids))
        _thread_meta(out, pid, tid, f"serving/{family}", threads)
        args = dict(s.get("args") or {})
        if s.get("trace_id"):
            args["trace_id"] = s["trace_id"]
        out.append({"name": name, "cat": "serving", "ph": "X",
                    "pid": pid, "tid": tid, "ts": _us(float(t), t0),
                    "dur": round(float(s.get("dur_s", 0.0)) * 1e6, 3),
                    "args": args})
    return out


def export_trace(run_dir: str, out_path: Optional[str] = None
                 ) -> Optional[str]:
    """Load every stream under ``run_dir``, merge, and write the
    Chrome-trace file (default ``<run_dir>/trace.json``).  Returns the
    path, or None when the directory holds nothing to export."""
    from autodist_tpu.telemetry.events import load_run_events
    from autodist_tpu.telemetry.profiler import (
        load_leg_samples,
        load_spans,
    )
    from autodist_tpu.telemetry.timeline import load_step_records

    records = load_step_records(run_dir)
    legs = load_leg_samples(run_dir)
    journal = load_run_events(run_dir)
    spans = load_spans(run_dir)
    events = chrome_trace_events(records, legs, journal, spans)
    if not any(e.get("ph") != "M" for e in events):
        return None
    payload = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "exporter": "autodist_tpu.telemetry.trace_export",
            "run_dir": os.path.abspath(run_dir),
            "streams": {"step_records": len(records),
                        "leg_samples": len(legs),
                        "journal_events": len(journal),
                        "serving_spans": len(spans)},
        },
    }
    path = out_path or os.path.join(run_dir, "trace.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f)
        f.write("\n")
    return path
