"""Step timelines: per-step records, host-side phase timers, profiler
spans.

Three instruments, one per time scale (docs/observability.md):

* :class:`StepRecord` / :class:`StepRecorder` — ONE structured record
  per training step: wall step time, host-phase breakdown (data_load /
  dispatch / blocking_fetch), throughput, the numerics guard's health
  summary when the host has it, and the cost model's PREDICTION for the
  active strategy (step time, exposed wire bytes, collective count) —
  the calibration bridge :mod:`autodist_tpu.telemetry.calibration`
  regresses against.  Records ride a bounded ring buffer and flush
  periodically as JSONL (rotated) into the run directory, so bench runs
  and real runs feed the same files.
* :func:`host_span` — ``jax.profiler.TraceAnnotation`` for HOST-side
  phases (data load, step dispatch, blocking fetch): these show as
  named host events in a profiler capture window next to the device
  timeline.
* :func:`sync_span` — ``jax.named_scope`` for code inside traced
  programs (the bucket sync legs in ``explicit_sync.py``/
  ``overlap.py``): named scopes prefix the lowered HLO ops, so a
  profiler trace attributes device time to reduce-scatter vs
  all-gather vs optimizer-update *by name*.  (A TraceAnnotation there
  would time TRACING, not execution — the two span helpers exist
  because the right tool differs inside vs outside ``jit``.)

Cost discipline: when telemetry is disabled, :meth:`StepRecorder.create`
returns None and every call site gates on that one identity check;
enabled, the per-step work is two ``perf_counter`` reads, one dataclass,
and two deque appends — the <1 % overhead budget BENCH_telemetry.json
verifies.  ``sync_span`` is trace-time-only metadata and costs nothing
per step on any path.
"""
from __future__ import annotations

import contextlib
import json
import os
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, List, Optional

from autodist_tpu.telemetry import flightrec
from autodist_tpu.telemetry.registry import telemetry_enabled

#: JSONL rotation threshold: records per ``steps-*.jsonl`` segment.
ROTATE_RECORDS = 50_000
#: ring-buffer capacity (records kept in memory for snapshots/analysis).
RING_RECORDS = 1024
#: flush cadence (records between JSONL appends).
FLUSH_EVERY = 50


@dataclass
class StepRecord:
    """One training step, as the host saw it.

    ``phases`` holds seconds per host-side phase (``data_load``,
    ``dispatch``, ``blocking_fetch``, ...).  Health fields
    (``loss``/``all_finite``/``global_norm``/``loss_scale``/
    ``skipped_steps``) are filled only at points that already pay a
    host sync — fetching them per step would serialize dispatch.
    ``predicted_*``/``sync_bytes`` carry the analytic cost model's
    estimate for the active strategy, stamped once per session — the
    measured-vs-predicted pair every record contributes to calibration.
    """

    step: int
    time_unix: float
    step_time_s: Optional[float] = None
    phases: Dict[str, float] = field(default_factory=dict)
    items_per_s: Optional[float] = None
    tokens_per_s: Optional[float] = None
    loss: Optional[float] = None
    all_finite: Optional[bool] = None
    global_norm: Optional[float] = None
    loss_scale: Optional[float] = None
    skipped_steps: Optional[int] = None
    rolled_back: bool = False
    sync_bytes: Optional[float] = None          # predicted wire B/chip/step
    exposed_bytes: Optional[float] = None       # predicted exposed wire B
    num_collectives: Optional[int] = None
    predicted_step_time_s: Optional[float] = None
    # Short hash of the step's sync-schedule IR (docs/schedule-ir.md):
    # records stamped with a different fingerprint than the checkpoint
    # they resumed from executed a DIFFERENT schedule than planned.
    schedule_fingerprint: Optional[str] = None
    # Emitting host (stamped once per recorder) — the cross-host
    # aggregator keys per-host step-time skew and the trace exporter's
    # per-host tracks on it; None in records written before this field
    # existed.
    host: Optional[str] = None

    def to_json(self) -> str:
        d = {k: v for k, v in asdict(self).items() if v not in (None, {})}
        return json.dumps(d)

    @classmethod
    def from_dict(cls, d: dict) -> "StepRecord":
        known = {f for f in cls.__dataclass_fields__}  # noqa: C401
        return cls(**{k: v for k, v in d.items() if k in known})


class StepRecorder:
    """Per-session step-timeline recorder (see module docstring).

    ``predictor`` is a zero-arg callable returning the cost model's
    estimate dict (``time_s``/``wire_bytes``/``exposed_wire_bytes``/
    ``num_collectives``) or None; it is invoked lazily ONCE (first
    record) so sessions that never run pay nothing.
    """

    def __init__(self, run_id: str, directory: Optional[str] = None,
                 ring: int = RING_RECORDS, flush_every: int = FLUSH_EVERY,
                 rotate_records: int = ROTATE_RECORDS,
                 predictor: Optional[Callable[[], Optional[dict]]] = None):
        import socket

        self.run_id = run_id
        self._host = socket.gethostname()
        self._dir = directory
        self._ring: deque = deque(maxlen=max(ring, 1))
        self._unflushed: List[StepRecord] = []
        self._flush_every = max(int(flush_every), 1)
        self._rotate = max(int(rotate_records), 1)
        self._predictor = predictor
        self._predicted: Any = _UNSET
        self._pending_phases: Dict[str, float] = {}
        self._last_t: Optional[float] = None
        self._last_loss: Optional[float] = None
        self._file_index = 0
        self._lines_in_file = 0
        # Default-registry instrumentation (no-ops when disabled).
        from autodist_tpu.telemetry import registry as _reg
        self._m_steps = _reg.counter(
            "autodist_steps_total", "training steps run by this process")
        self._m_step_time = _reg.histogram(
            "autodist_step_time_seconds", "wall time between step ends")

    @classmethod
    def create(cls, run_id: str,
               predictor: Optional[Callable[[], Optional[dict]]] = None,
               directory: Optional[str] = None,
               **kwargs) -> Optional["StepRecorder"]:
        """The gated constructor: None when telemetry is disabled (call
        sites pay one identity check per step).  ``directory`` defaults
        to ``$AUTODIST_TELEMETRY_DIR/<run_id>`` when that env var is
        set; without it, records stay in the ring (no disk I/O)."""
        if not telemetry_enabled():
            return None
        if directory is None:
            from autodist_tpu.const import ENV
            base = ENV.AUTODIST_TELEMETRY_DIR.val
            if base:
                directory = os.path.join(base, run_id)
        return cls(run_id, directory=directory, predictor=predictor,
                   **kwargs)

    # -- phase timing ------------------------------------------------------
    def add_phase(self, name: str, seconds: float) -> None:
        """Accumulate host time into the NEXT record's phase ``name``."""
        self._pending_phases[name] = \
            self._pending_phases.get(name, 0.0) + seconds

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_phase(name, time.perf_counter() - t0)

    # -- recording ---------------------------------------------------------
    def _prediction(self) -> Optional[dict]:
        if self._predicted is _UNSET:
            try:
                self._predicted = self._predictor() if self._predictor \
                    else None
            except Exception:   # prediction is advisory, never fatal
                self._predicted = None
        return self._predicted

    def record_step(self, step: int, *, items: Optional[int] = None,
                    tokens: Optional[int] = None) -> StepRecord:
        """Finalize one step: wall time since the previous record, the
        accumulated phases, throughput from ``items``/``tokens``."""
        now = time.perf_counter()
        dt = None if self._last_t is None else now - self._last_t
        self._last_t = now
        pred = self._prediction() or {}
        rec = StepRecord(
            step=int(step), time_unix=time.time(), step_time_s=dt,
            phases=self._pending_phases,
            items_per_s=(items / dt) if items and dt else None,
            tokens_per_s=(tokens / dt) if tokens and dt else None,
            sync_bytes=pred.get("wire_bytes"),
            exposed_bytes=pred.get("exposed_wire_bytes"),
            num_collectives=pred.get("num_collectives"),
            predicted_step_time_s=pred.get("time_s"),
            schedule_fingerprint=pred.get("schedule_fingerprint"),
            host=self._host)
        self._pending_phases = {}
        self._ring.append(rec)
        # Host-phase flight-recorder cursor (flightrec.py): the step
        # boundary is the coarsest progress beacon — the one every path
        # (GSPMD included) gets for free.  The session stamps the
        # matching "enter" before dispatch.
        flightrec.record_cursor("step", kind="phase", event="exit",
                                step=int(step))
        self._m_steps.inc()
        if dt is not None:
            self._m_step_time.observe(dt)
        if self._dir is not None:
            self._unflushed.append(rec)
            if len(self._unflushed) >= self._flush_every:
                self.flush()
        return rec

    def annotate(self, step: Optional[int] = None, **fields: Any) -> None:
        """Attach host-synced observations (loss, GradHealth summary,
        rollback flags) to the record for ``step`` (default: the most
        recent).  Searches the ring from the newest end — annotations
        always target a recent step."""
        target = None
        for rec in reversed(self._ring):
            if step is None or rec.step == step:
                target = rec
                break
        if target is None:
            return
        for k, v in fields.items():
            if hasattr(target, k) and v is not None:
                setattr(target, k, v)
        if fields.get("loss") is not None:
            self._last_loss = float(fields["loss"])

    # -- views -------------------------------------------------------------
    @property
    def records(self) -> List[StepRecord]:
        return list(self._ring)

    @property
    def directory(self) -> Optional[str]:
        return self._dir

    def snapshot(self) -> Optional[dict]:
        """A tiny host-cheap summary of the latest step — what heartbeat
        beacons carry so the monitor can report what a worker was DOING
        when it died (resilience/heartbeat.py).  Never touches device
        arrays."""
        if not self._ring:
            return None
        rec = self._ring[-1]
        out: Dict[str, Any] = {"step": rec.step}
        if rec.step_time_s is not None:
            out["step_time_ms"] = round(rec.step_time_s * 1e3, 3)
        loss = rec.loss if rec.loss is not None else self._last_loss
        if loss is not None:
            out["loss"] = round(float(loss), 6)
        return out

    # -- persistence -------------------------------------------------------
    def _segment_path(self) -> str:
        # Host in the filename (like events-*.jsonl): multi-host runs
        # share one directory over network FS, and two hosts can share
        # a pid.  The loader's steps-*.jsonl glob matches both formats.
        pid = os.getpid()
        safe = self._host.replace("/", "_").replace(":", "_")
        suffix = "" if self._file_index == 0 else f".{self._file_index}"
        return os.path.join(self._dir, f"steps-{safe}-{pid}{suffix}.jsonl")

    def flush(self) -> Optional[str]:
        """Append unflushed records as JSONL; rotates to a new segment
        every ``rotate_records`` lines.  Returns the segment path (None
        when there is no directory/nothing to write); never raises."""
        if self._dir is None or not self._unflushed:
            return None
        f = None
        try:
            os.makedirs(self._dir, exist_ok=True)
            path = self._segment_path()
            f = open(path, "a", encoding="utf-8")
            for rec in self._unflushed:
                f.write(rec.to_json() + "\n")
                self._lines_in_file += 1
                if self._lines_in_file >= self._rotate:
                    f.close()
                    self._file_index += 1
                    self._lines_in_file = 0
                    path = self._segment_path()
                    f = open(path, "a", encoding="utf-8")
            self._unflushed = []
            return path
        except OSError:
            self._unflushed = []
            return None
        finally:
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass


class _Unset:
    pass


_UNSET = _Unset()


# -- profiler spans ----------------------------------------------------------

def host_span(name: str):
    """Named host-side span (``jax.profiler.TraceAnnotation``) for
    phases OUTSIDE traced code — shows as a named event when a capture
    window (AUTODIST_TRACE_STEPS / AUTODIST_TRACE_AT) is open."""
    import jax

    return jax.profiler.TraceAnnotation(name)


def sync_span(name: str):
    """Named scope for code INSIDE traced programs: prefixes the lowered
    HLO op names, so profiler traces attribute device time to the sync
    leg by name (``autodist_sync/<name>``).  Trace-time-only — zero
    per-step cost."""
    import jax

    return jax.named_scope(f"autodist_sync/{name}")


def load_step_records(run_dir: str) -> List[StepRecord]:
    """Every ``steps-*.jsonl`` record under ``run_dir`` (recursive),
    step/time-ordered — the CLI's and the calibrator's input."""
    import glob as _glob

    out: List[StepRecord] = []
    for path in sorted(_glob.glob(
            os.path.join(run_dir, "**", "steps-*.jsonl"), recursive=True)):
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(StepRecord.from_dict(json.loads(line)))
                    except (ValueError, TypeError):
                        continue
        except OSError:
            continue
    out.sort(key=lambda r: (r.time_unix, r.step))
    return out
