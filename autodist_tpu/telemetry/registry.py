"""Process-local metrics registry: counters, gauges, histograms.

The observability layer's lowest tier (docs/observability.md).  Three
design constraints drive everything here:

* **Exact cross-host merge.**  A multi-host run has one registry per
  process; the chief merges them for reporting.  Counters and gauges
  merge trivially; histograms merge exactly ONLY when every host uses
  the same fixed bucket bounds — so bounds are immutable per metric,
  :meth:`Histogram.merge` refuses mismatched bounds, and the merged
  bucket counts equal what a single global histogram would have
  observed (no re-binning, no approximation).
* **Near-zero-cost disabled paths.**  With ``AUTODIST_TELEMETRY=0`` the
  module-level accessors (:func:`counter` / :func:`gauge` /
  :func:`histogram`) hand back shared null objects whose methods are
  empty — one attribute lookup and a no-op call per instrumentation
  site, no dict updates, no allocation.  Explicitly constructed
  :class:`MetricsRegistry` instances (e.g. the serving server's) are
  always live: they ARE the feature, not instrumentation riding a hot
  path.
* **No dependencies.**  Pure stdlib, importable without jax — the
  ``python -m autodist_tpu.telemetry`` CLI summarizes run directories
  on hosts with no accelerator stack.

Prometheus text exposition (:func:`render_prometheus`) follows the
standard format: histograms render cumulative ``_bucket{le=...}``
series plus ``_sum``/``_count``, so any scraper computes quantiles.
"""
from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: default histogram bounds for second-denominated timings (step time,
#: request latency): 1 ms .. 60 s, roughly log-spaced.
TIME_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
#: default bounds for small nonnegative integer quantities (queue depth).
DEPTH_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


def telemetry_enabled() -> bool:
    """The ``AUTODIST_TELEMETRY`` master switch (default on)."""
    from autodist_tpu.const import ENV

    return ENV.AUTODIST_TELEMETRY.val


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = threading.Lock()


class Counter(_Metric):
    """Monotone counter (name should end in ``_total`` by convention)."""

    kind = "counter"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self.value += amount

    def merge(self, other: "Counter") -> None:
        with self._lock:
            self.value += other.value

    def to_dict(self) -> dict:
        return {"kind": self.kind, "name": self.name, "help": self.help,
                "labels": self.labels, "value": self.value}


class Gauge(_Metric):
    """Last-written value (set/inc/dec); merge keeps the other's value
    when it is newer by write sequence (monotonic per process — for
    cross-host merge the CALLER decides which side wins by merge order:
    later merges overwrite)."""

    kind = "gauge"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def merge(self, other: "Gauge") -> None:
        with self._lock:
            self.value = other.value

    def to_dict(self) -> dict:
        return {"kind": self.kind, "name": self.name, "help": self.help,
                "labels": self.labels, "value": self.value}


class Histogram(_Metric):
    """Fixed-bound histogram: ``len(bounds)+1`` buckets (the last is
    +Inf).  Bounds are frozen at construction so cross-host merge is
    EXACT — merged counts equal a single histogram observing the union
    of samples."""

    kind = "histogram"

    def __init__(self, name, help="", labels=None,
                 buckets: Sequence[float] = TIME_BUCKETS):
        super().__init__(name, help, labels)
        bounds = tuple(float(b) for b in buckets)
        if list(bounds) != sorted(set(bounds)):
            raise ValueError(f"histogram bounds must be strictly "
                             f"increasing, got {bounds}")
        if not bounds:
            raise ValueError("histogram needs at least one bound")
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self.counts[idx] += 1
            self.sum += value
            self.count += 1

    def merge(self, other: "Histogram") -> None:
        if other.bounds != self.bounds:
            raise ValueError(
                f"histogram {self.name}: bucket bounds differ "
                f"({self.bounds} vs {other.bounds}) — cross-host merge "
                "requires identical fixed bounds")
        with self._lock:
            for i, c in enumerate(other.counts):
                self.counts[i] += c
            self.sum += other.sum
            self.count += other.count

    def percentile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (0..1) by linear interpolation inside
        the containing bucket (the standard Prometheus
        ``histogram_quantile`` estimate); None when empty.  Values in
        the +Inf bucket clamp to the largest finite bound."""
        if self.count == 0:
            return None
        rank = q * self.count
        seen = 0.0
        lo = 0.0
        for i, c in enumerate(self.counts):
            hi = self.bounds[i] if i < len(self.bounds) else self.bounds[-1]
            if seen + c >= rank:
                if c == 0 or i >= len(self.bounds):
                    return hi
                frac = (rank - seen) / c
                return lo + (hi - lo) * frac
            seen += c
            lo = hi
        return self.bounds[-1]

    def to_dict(self) -> dict:
        return {"kind": self.kind, "name": self.name, "help": self.help,
                "labels": self.labels, "bounds": list(self.bounds),
                "counts": list(self.counts), "sum": self.sum,
                "count": self.count}


class _NullMetric:
    """Shared no-op standing in for every metric when telemetry is
    disabled: one attribute lookup + an empty call per site."""

    def inc(self, amount: float = 1.0) -> None: ...

    def dec(self, amount: float = 1.0) -> None: ...

    def set(self, value: float) -> None: ...

    def observe(self, value: float) -> None: ...


NULL_METRIC = _NullMetric()


class MetricsRegistry:
    """A named collection of metrics.  ``counter``/``gauge``/``histogram``
    are get-or-create (idempotent by ``(name, labels)``) so call sites
    need no registration phase."""

    def __init__(self):
        self._metrics: Dict[Tuple[str, Tuple], _Metric] = {}
        self._lock = threading.Lock()

    def _key(self, name: str, labels) -> Tuple[str, Tuple]:
        return (name, tuple(sorted((labels or {}).items())))

    def _get_or_create(self, cls, name, help, labels, **kwargs) -> _Metric:
        key = self._key(name, labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help=help, labels=labels, **kwargs)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  buckets: Sequence[float] = TIME_BUCKETS) -> Histogram:
        h = self._get_or_create(Histogram, name, help, labels,
                                buckets=buckets)
        if h.bounds != tuple(float(b) for b in buckets):
            raise ValueError(
                f"histogram {name!r} already registered with bounds "
                f"{h.bounds}; fixed bounds cannot change")
        return h

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def merge(self, other: "MetricsRegistry") -> None:
        """Fold ``other`` into this registry (exact for counters and
        histograms — see class docstrings).  Metrics missing here are
        deep-copied in."""
        for m in other.metrics():
            if isinstance(m, Counter):
                self.counter(m.name, m.help, m.labels).merge(m)
            elif isinstance(m, Gauge):
                self.gauge(m.name, m.help, m.labels).merge(m)
            elif isinstance(m, Histogram):
                self.histogram(m.name, m.help, m.labels,
                               buckets=m.bounds).merge(m)

    def to_dict(self) -> List[dict]:
        """JSON-portable snapshot (the cross-host transport format)."""
        return [m.to_dict() for m in self.metrics()]

    def merge_dict(self, snapshot: Iterable[dict]) -> None:
        """Merge a :meth:`to_dict` snapshot (e.g. shipped from another
        host as JSON) — the chief-side merge half."""
        for d in snapshot:
            kind = d.get("kind")
            if kind == "counter":
                self.counter(d["name"], d.get("help", ""),
                             d.get("labels")).inc(float(d["value"]))
            elif kind == "gauge":
                self.gauge(d["name"], d.get("help", ""),
                           d.get("labels")).set(float(d["value"]))
            elif kind == "histogram":
                h = self.histogram(d["name"], d.get("help", ""),
                                   d.get("labels"),
                                   buckets=d["bounds"])
                src = Histogram(d["name"], buckets=d["bounds"])
                src.counts = [int(c) for c in d["counts"]]
                src.sum = float(d["sum"])
                src.count = int(d["count"])
                h.merge(src)


#: the process-default registry the instrumentation accessors feed.
DEFAULT_REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "",
            labels: Optional[Dict[str, str]] = None):
    """Get-or-create a counter on the default registry — or the shared
    no-op when telemetry is disabled."""
    if not telemetry_enabled():
        return NULL_METRIC
    return DEFAULT_REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "",
          labels: Optional[Dict[str, str]] = None):
    if not telemetry_enabled():
        return NULL_METRIC
    return DEFAULT_REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None,
              buckets: Sequence[float] = TIME_BUCKETS):
    if not telemetry_enabled():
        return NULL_METRIC
    return DEFAULT_REGISTRY.histogram(name, help, labels, buckets=buckets)


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """Prometheus text exposition (format 0.0.4) of ``registry``
    (default: the process registry).  Histograms render cumulative
    ``_bucket`` series + ``_sum``/``_count``."""
    registry = DEFAULT_REGISTRY if registry is None else registry
    lines: List[str] = []
    seen_headers = set()
    for m in registry.metrics():
        if m.name not in seen_headers:
            seen_headers.add(m.name)
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
        if isinstance(m, Histogram):
            cum = 0
            for i, bound in enumerate(tuple(m.bounds) + (math.inf,)):
                cum += m.counts[i]
                labels = dict(m.labels)
                labels["le"] = _fmt_value(bound)
                lines.append(
                    f"{m.name}_bucket{_fmt_labels(labels)} {cum}")
            lines.append(f"{m.name}_sum{_fmt_labels(m.labels)} "
                         f"{_fmt_value(m.sum)}")
            lines.append(f"{m.name}_count{_fmt_labels(m.labels)} "
                         f"{m.count}")
        else:
            lines.append(f"{m.name}{_fmt_labels(m.labels)} "
                         f"{_fmt_value(m.value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def reset_for_testing() -> None:
    """Drop every metric on the default registry (test isolation)."""
    DEFAULT_REGISTRY._metrics.clear()
