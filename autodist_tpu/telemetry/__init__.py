"""Unified telemetry: metrics registry, step timelines, event journal,
calibration bridge (docs/observability.md).

The repo could *predict* what a strategy costs (the analytic cost
model) but not *see* what a step actually did — sync vs compute vs
update time, exposed wire bytes, guard overhead, restart churn, serving
latency.  This package is the seeing half, in four tiers:

* :mod:`~autodist_tpu.telemetry.registry` — process-local counters /
  gauges / fixed-bound histograms with exact cross-host merge and
  near-zero-cost disabled paths; Prometheus text exposition via
  :func:`render_prometheus`.
* :mod:`~autodist_tpu.telemetry.timeline` — per-step
  :class:`StepRecord`s (ring-buffered, JSONL-flushed) with host-phase
  timers and profiler span helpers for the sync legs.
* :mod:`~autodist_tpu.telemetry.events` — the structured event journal
  (supervisor restarts, heartbeat verdicts, chaos injections,
  checkpoint durations, elastic resizes, numerics decisions).
* :mod:`~autodist_tpu.telemetry.calibration` — regress the cost
  model's bandwidth/overhead constants from accumulated records;
  shared ``telemetry/model-drift`` rule.

``python -m autodist_tpu.telemetry <run_dir>`` summarizes a recorded
run (step-time percentiles, phase breakdown, event timeline,
predicted-vs-measured).  Master switch: ``AUTODIST_TELEMETRY`` (default
on); JSONL output lands under ``AUTODIST_TELEMETRY_DIR`` when set.

This ``__init__`` (and everything except ``timeline``'s span helpers)
imports without jax, so the CLI runs on accelerator-free hosts.
"""
from autodist_tpu.telemetry.calibration import (
    CalibratedConstants,
    DRIFT_THRESHOLD,
    fit_constants,
    model_drift_reason,
    predicted_vs_measured,
    prediction_error,
)
from autodist_tpu.telemetry.events import (
    EventJournal,
    configure as configure_events,
    emit_event,
    get_journal,
    load_run_events,
    read_events,
)
from autodist_tpu.telemetry.registry import (
    DEFAULT_REGISTRY,
    MetricsRegistry,
    counter,
    gauge,
    histogram,
    render_prometheus,
    telemetry_enabled,
)
from autodist_tpu.telemetry.timeline import (
    StepRecord,
    StepRecorder,
    host_span,
    load_step_records,
    sync_span,
)

__all__ = [
    "CalibratedConstants",
    "DRIFT_THRESHOLD",
    "DEFAULT_REGISTRY",
    "EventJournal",
    "MetricsRegistry",
    "StepRecord",
    "StepRecorder",
    "configure_events",
    "counter",
    "emit_event",
    "fit_constants",
    "gauge",
    "get_journal",
    "histogram",
    "host_span",
    "load_run_events",
    "load_step_records",
    "model_drift_reason",
    "predicted_vs_measured",
    "prediction_error",
    "read_events",
    "render_prometheus",
    "sync_span",
    "telemetry_enabled",
]
