"""Unified telemetry: metrics registry, step timelines, event journal,
calibration bridge (docs/observability.md).

The repo could *predict* what a strategy costs (the analytic cost
model) but not *see* what a step actually did — sync vs compute vs
update time, exposed wire bytes, guard overhead, restart churn, serving
latency.  This package is the seeing half, in four tiers:

* :mod:`~autodist_tpu.telemetry.registry` — process-local counters /
  gauges / fixed-bound histograms with exact cross-host merge and
  near-zero-cost disabled paths; Prometheus text exposition via
  :func:`render_prometheus`.
* :mod:`~autodist_tpu.telemetry.timeline` — per-step
  :class:`StepRecord`s (ring-buffered, JSONL-flushed) with host-phase
  timers and profiler span helpers for the sync legs.
* :mod:`~autodist_tpu.telemetry.events` — the structured event journal
  (supervisor restarts, heartbeat verdicts, chaos injections,
  checkpoint durations, elastic resizes, numerics decisions).
* :mod:`~autodist_tpu.telemetry.calibration` — regress the cost
  model's bandwidth/overhead constants from accumulated records;
  shared ``telemetry/model-drift`` rule.
* :mod:`~autodist_tpu.telemetry.flightrec` — the schedule-aware flight
  recorder: leg-level progress cursors riding the heartbeat beacons,
  happens-before hang localization (frontier leg + culprit host), and
  crash bundles (``dump_bundle`` / ``--hang-report``).

``python -m autodist_tpu.telemetry <run_dir>`` summarizes a recorded
run (step-time percentiles, phase breakdown, event timeline,
predicted-vs-measured).  Master switch: ``AUTODIST_TELEMETRY`` (default
on); JSONL output lands under ``AUTODIST_TELEMETRY_DIR`` when set.

This ``__init__`` (and everything except ``timeline``'s span helpers)
imports without jax, so the CLI runs on accelerator-free hosts.
"""
from autodist_tpu.telemetry.aggregate import (
    aggregate_run,
    merge_registry_snapshots,
    per_host_step_stats,
    write_registry_snapshot,
)
from autodist_tpu.telemetry.calibration import (
    CalibratedConstants,
    DRIFT_THRESHOLD,
    LEG_DRIFT_THRESHOLD,
    LegCalibration,
    STRAGGLER_THRESHOLD,
    drifted_leg_kinds,
    fit_constants,
    fit_leg_constants,
    leg_drift_reason,
    load_calibration,
    load_default_calibration,
    model_drift_reason,
    predicted_vs_measured,
    prediction_error,
    save_calibration,
    straggler_reason,
)
from autodist_tpu.telemetry.goodput import (
    RECOVERY_BUDGET_S,
    attempt_goodput,
    checkpoint_cadence,
    goodput_from_run,
    recovery_gap_reason,
)
from autodist_tpu.telemetry.events import (
    EventJournal,
    configure as configure_events,
    emit_event,
    get_journal,
    load_run_events,
    read_events,
)
from autodist_tpu.telemetry.flightrec import (
    Cursor,
    CursorRing,
    HangDiagnosis,
    beacon_cursor,
    cursor_line,
    dump_bundle,
    dump_cursors,
    find_bundles,
    install_fatal_handlers,
    latest_cursor,
    localize_hang,
    record_cursor,
    render_hang_report,
)
from autodist_tpu.telemetry.registry import (
    DEFAULT_REGISTRY,
    MetricsRegistry,
    counter,
    gauge,
    histogram,
    render_prometheus,
    telemetry_enabled,
)
from autodist_tpu.telemetry.profiler import (
    LegProfiler,
    LegSample,
    configure_spans,
    load_leg_samples,
    load_spans,
    record_span,
    write_leg_samples,
)
from autodist_tpu.telemetry.timeline import (
    StepRecord,
    StepRecorder,
    host_span,
    load_step_records,
    sync_span,
)
from autodist_tpu.telemetry.trace_export import (
    chrome_trace_events,
    export_trace,
)

__all__ = [
    "CalibratedConstants",
    "Cursor",
    "CursorRing",
    "DRIFT_THRESHOLD",
    "DEFAULT_REGISTRY",
    "EventJournal",
    "HangDiagnosis",
    "LEG_DRIFT_THRESHOLD",
    "LegCalibration",
    "LegProfiler",
    "LegSample",
    "MetricsRegistry",
    "RECOVERY_BUDGET_S",
    "STRAGGLER_THRESHOLD",
    "StepRecord",
    "StepRecorder",
    "aggregate_run",
    "attempt_goodput",
    "beacon_cursor",
    "checkpoint_cadence",
    "chrome_trace_events",
    "configure_events",
    "configure_spans",
    "counter",
    "cursor_line",
    "dump_bundle",
    "dump_cursors",
    "drifted_leg_kinds",
    "emit_event",
    "export_trace",
    "find_bundles",
    "fit_constants",
    "fit_leg_constants",
    "gauge",
    "get_journal",
    "goodput_from_run",
    "histogram",
    "host_span",
    "install_fatal_handlers",
    "latest_cursor",
    "leg_drift_reason",
    "load_calibration",
    "load_default_calibration",
    "load_leg_samples",
    "load_run_events",
    "load_spans",
    "load_step_records",
    "localize_hang",
    "merge_registry_snapshots",
    "model_drift_reason",
    "per_host_step_stats",
    "predicted_vs_measured",
    "prediction_error",
    "read_events",
    "record_cursor",
    "record_span",
    "recovery_gap_reason",
    "render_hang_report",
    "render_prometheus",
    "save_calibration",
    "straggler_reason",
    "sync_span",
    "telemetry_enabled",
    "write_leg_samples",
    "write_registry_snapshot",
]
