"""The calibration bridge: measured StepRecords → cost-model constants.

The analytic cost model (``strategy/cost_model.py``) prices a strategy
as ``max(compute, exposed_bytes / bandwidth + alpha · collectives) +
update`` with hand-set constants (``ICI_BANDWIDTH``,
``COLLECTIVE_ALPHA``).  Its own docstring is honest: times are
order-of-magnitude, for ranking.  Automap (arXiv:2112.02958) and the
MLPerf TPU-pod report (arXiv:1909.09756) both attribute search quality
to MEASURED calibration — so every :class:`~autodist_tpu.telemetry.
timeline.StepRecord` carries the model's prediction next to the
measured step time, and :func:`fit_constants` regresses the constants
from accumulated records (bench runs and real runs emit the same JSONL,
so both feed this path).

The regression is deliberately tiny: ordinary least squares of
``step_time ≈ exposed_bytes · (1/bandwidth) + collectives · alpha``
over the records, with positivity fallbacks for degenerate inputs (one
run has constant bytes per step; a compute-bound CPU host has comm ≈ 0).
Whatever it returns plugs straight into
``estimate_cost(..., ici_bandwidth=..., alpha=...)``.

:func:`model_drift_reason` is the shared pure rule behind the
``telemetry/model-drift`` analysis WARN (the ``bucket_drop_reason``
pattern: one string, used by the lint, the CLI, and any runtime check —
they cannot drift from each other).

This module is numpy-only (no jax): the CLI runs it on hosts with no
accelerator stack.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

#: measured/predicted step-time ratio beyond which the model is
#: declared drifted (in either direction) — the ``telemetry/model-drift``
#: threshold.
DRIFT_THRESHOLD = 3.0

# Defaults mirrored from strategy/cost_model.py without importing it
# (cost_model pulls in jax via GraphItem; this module must stay light).
DEFAULT_ICI_BANDWIDTH = 45e9
DEFAULT_ALPHA = 5e-6

_MIN_BANDWIDTH = 1e6       # 1 MB/s: slower than any real interconnect
_MAX_BANDWIDTH = 1e15      # effectively "comm is free on this host"

#: records whose step time exceeds this multiple of the run's median are
#: excluded from fitting/error: compile steps, open profiler-trace
#: windows, and checkpoint stalls are host hiccups, not the steady-state
#: step time the model predicts (one 4-second trace write would
#: otherwise dominate a least-squares fit over hundreds of 2 ms steps).
OUTLIER_FACTOR = 10.0


def model_drift_reason(predicted_s: Optional[float],
                       measured_s: Optional[float],
                       threshold: float = DRIFT_THRESHOLD
                       ) -> Optional[str]:
    """Why the cost model has drifted from measurement, or None.

    Fires when the measured/predicted step-time ratio exceeds
    ``threshold`` in EITHER direction — an overestimating model
    mis-ranks strategies just as surely as an underestimating one.
    Quiet when either side is missing or nonpositive (no measurement ≠
    drift)."""
    if not predicted_s or not measured_s:
        return None
    if predicted_s <= 0 or measured_s <= 0:
        return None
    ratio = measured_s / predicted_s
    if ratio > threshold:
        return (f"measured step time {measured_s * 1e3:.3f} ms is "
                f"{ratio:.1f}x the cost model's {predicted_s * 1e3:.3f} ms "
                f"prediction (threshold {threshold:g}x); recalibrate with "
                "telemetry.calibration.fit_constants on this run's records")
    if ratio < 1.0 / threshold:
        return (f"measured step time {measured_s * 1e3:.3f} ms is "
                f"{1 / ratio:.1f}x BELOW the cost model's "
                f"{predicted_s * 1e3:.3f} ms prediction (threshold "
                f"{threshold:g}x); the model overprices this strategy — "
                "recalibrate with telemetry.calibration.fit_constants")
    return None


@dataclass
class CalibratedConstants:
    """What :func:`fit_constants` returns — drop-in overrides for
    ``estimate_cost(ici_bandwidth=..., alpha=...)``."""

    ici_bandwidth: float
    alpha: float
    n_records: int
    mean_abs_error_s: float            # with the fitted constants
    baseline_mean_abs_error_s: float   # with the defaults

    @property
    def improved(self) -> bool:
        return self.mean_abs_error_s <= self.baseline_mean_abs_error_s

    def as_cost_kwargs(self) -> dict:
        return {"ici_bandwidth": self.ici_bandwidth, "alpha": self.alpha}


def _rows(records) -> np.ndarray:
    """(exposed_bytes, collectives, step_time) rows for usable records:
    a positive measured step time and a known (possibly zero) predicted
    byte count.  Steady-state only: rows beyond
    :data:`OUTLIER_FACTOR` x the median step time (compiles, trace
    windows, checkpoint stalls) are dropped."""
    rows = []
    for r in records:
        step_time = getattr(r, "step_time_s", None) if not isinstance(
            r, dict) else r.get("step_time_s")
        exposed = getattr(r, "exposed_bytes", None) if not isinstance(
            r, dict) else r.get("exposed_bytes")
        ncoll = getattr(r, "num_collectives", None) if not isinstance(
            r, dict) else r.get("num_collectives")
        if step_time is None or step_time <= 0 or exposed is None:
            continue
        rows.append((float(exposed), float(ncoll or 0), float(step_time)))
    arr = np.asarray(rows, dtype=np.float64)
    if arr.size:
        keep = arr[:, 2] <= OUTLIER_FACTOR * float(np.median(arr[:, 2]))
        arr = arr[keep]
    return arr


def comm_time_s(exposed_bytes: float, num_collectives: float,
                ici_bandwidth: float, alpha: float) -> float:
    """The model's exposed-communication time under given constants."""
    return exposed_bytes / ici_bandwidth + alpha * num_collectives


def prediction_error(records: Sequence,
                     ici_bandwidth: float = DEFAULT_ICI_BANDWIDTH,
                     alpha: float = DEFAULT_ALPHA) -> Optional[float]:
    """Mean |measured − modeled| step time (seconds) over the records'
    communication model under the given constants; None without usable
    records.  The figure calibration must reduce."""
    rows = _rows(records)
    if rows.size == 0:
        return None
    pred = comm_time_s(rows[:, 0], rows[:, 1], ici_bandwidth, alpha)
    return float(np.mean(np.abs(rows[:, 2] - pred)))


def fit_constants(records: Sequence,
                  default_bandwidth: float = DEFAULT_ICI_BANDWIDTH,
                  default_alpha: float = DEFAULT_ALPHA
                  ) -> Optional[CalibratedConstants]:
    """Least-squares fit of (bandwidth, alpha) from StepRecords (objects
    or dicts).  Returns None without usable records.

    Degenerate inputs are handled explicitly rather than by blowing up:

    * one run ⇒ constant (bytes, collectives) per row — the normal
      matrix is rank-1 and ``lstsq``'s min-norm solution splits the
      observed time between the two terms; the fit is exact for THAT
      workload, which is precisely what "calibrated on this run's
      records" promises;
    * nonpositive solutions (a compute-bound host where time does not
      grow with bytes) clamp: bandwidth into
      [:data:`_MIN_BANDWIDTH`, :data:`_MAX_BANDWIDTH`], alpha to ≥ 0,
      each refit with the other term held.
    """
    rows = _rows(records)
    if rows.size == 0:
        return None
    x, n, y = rows[:, 0], rows[:, 1], rows[:, 2]
    A = np.stack([x, n], axis=1)
    sol, *_ = np.linalg.lstsq(A, y, rcond=None)
    inv_bw, alpha = float(sol[0]), float(sol[1])
    if alpha < 0:
        alpha = 0.0
        denom = float(np.dot(x, x))
        inv_bw = float(np.dot(x, y) / denom) if denom > 0 else 0.0
    if inv_bw <= 0:
        # Comm time does not grow with bytes here (compute-bound):
        # bandwidth pegs at "free" and alpha absorbs what it can.
        inv_bw = 1.0 / _MAX_BANDWIDTH
        denom = float(np.dot(n, n))
        alpha = max(float(np.dot(n, y - x * inv_bw) / denom), 0.0) \
            if denom > 0 else 0.0
    bandwidth = float(np.clip(1.0 / inv_bw, _MIN_BANDWIDTH, _MAX_BANDWIDTH))
    fitted_err = prediction_error(records, bandwidth, alpha)
    baseline_err = prediction_error(records, default_bandwidth,
                                    default_alpha)
    return CalibratedConstants(
        ici_bandwidth=bandwidth, alpha=alpha, n_records=int(len(rows)),
        mean_abs_error_s=float(fitted_err),
        baseline_mean_abs_error_s=float(baseline_err))


def predicted_vs_measured(records: Sequence) -> Optional[dict]:
    """Aggregate comparison for reporting: MEDIAN measured step time
    (robust to compile/trace-window outliers — one 4 s profiler flush
    must not declare the model drifted) vs the records' carried
    full-model prediction, plus the drift verdict.  None without usable
    records."""
    steps: List[float] = []
    preds: List[float] = []
    for r in records:
        get = (lambda k, rr=r: rr.get(k)) if isinstance(r, dict) \
            else (lambda k, rr=r: getattr(rr, k, None))
        st = get("step_time_s")
        if st is None or st <= 0:
            continue
        steps.append(float(st))
        p = get("predicted_step_time_s")
        if p:
            preds.append(float(p))
    if not steps:
        return None
    measured = float(np.median(steps))
    predicted = float(np.median(preds)) if preds else None
    return {
        "n_steps": len(steps),
        "measured_step_time_s": measured,
        "predicted_step_time_s": predicted,
        "ratio": (measured / predicted) if predicted else None,
        "drift": model_drift_reason(predicted, measured),
    }
