"""The calibration bridge: measured StepRecords → cost-model constants.

The analytic cost model (``strategy/cost_model.py``) prices a strategy
as ``max(compute, exposed_bytes / bandwidth + alpha · collectives) +
update`` with hand-set constants (``ICI_BANDWIDTH``,
``COLLECTIVE_ALPHA``).  Its own docstring is honest: times are
order-of-magnitude, for ranking.  Automap (arXiv:2112.02958) and the
MLPerf TPU-pod report (arXiv:1909.09756) both attribute search quality
to MEASURED calibration — so every :class:`~autodist_tpu.telemetry.
timeline.StepRecord` carries the model's prediction next to the
measured step time, and :func:`fit_constants` regresses the constants
from accumulated records (bench runs and real runs emit the same JSONL,
so both feed this path).

The regression is deliberately tiny: ordinary least squares of
``step_time ≈ exposed_bytes · (1/bandwidth) + collectives · alpha``
over the records, with positivity fallbacks for degenerate inputs (one
run has constant bytes per step; a compute-bound CPU host has comm ≈ 0).
Whatever it returns plugs straight into
``estimate_cost(..., ici_bandwidth=..., alpha=...)``.

:func:`model_drift_reason` is the shared pure rule behind the
``telemetry/model-drift`` analysis WARN (the ``bucket_drop_reason``
pattern: one string, used by the lint, the CLI, and any runtime check —
they cannot drift from each other).

This module is numpy-only (no jax): the CLI runs it on hosts with no
accelerator stack.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

#: measured/predicted step-time ratio beyond which the model is
#: declared drifted (in either direction) — the ``telemetry/model-drift``
#: threshold.
DRIFT_THRESHOLD = 3.0

#: per-leg-kind measured/predicted ratio beyond which a single leg kind
#: is declared drifted — the ``telemetry/leg-drift`` threshold.  Looser
#: than the step threshold would be wrong: leg timings are micro-runs
#: with less noise than whole steps, so the same 3x bar applies.
LEG_DRIFT_THRESHOLD = 3.0

#: max/min per-host median step time ratio beyond which the slowest
#: host is declared a straggler — the ``telemetry/straggler`` threshold.
STRAGGLER_THRESHOLD = 1.5

#: calibration.json schema version (docs/observability.md).
CALIBRATION_VERSION = 1

# Defaults mirrored from strategy/cost_model.py without importing it
# (cost_model pulls in jax via GraphItem; this module must stay light).
DEFAULT_ICI_BANDWIDTH = 45e9
DEFAULT_ALPHA = 5e-6

_MIN_BANDWIDTH = 1e6       # 1 MB/s: slower than any real interconnect
_MAX_BANDWIDTH = 1e15      # effectively "comm is free on this host"

#: records whose step time exceeds this multiple of the run's median are
#: excluded from fitting/error: compile steps, open profiler-trace
#: windows, and checkpoint stalls are host hiccups, not the steady-state
#: step time the model predicts (one 4-second trace write would
#: otherwise dominate a least-squares fit over hundreds of 2 ms steps).
OUTLIER_FACTOR = 10.0


def model_drift_reason(predicted_s: Optional[float],
                       measured_s: Optional[float],
                       threshold: float = DRIFT_THRESHOLD
                       ) -> Optional[str]:
    """Why the cost model has drifted from measurement, or None.

    Fires when the measured/predicted step-time ratio exceeds
    ``threshold`` in EITHER direction — an overestimating model
    mis-ranks strategies just as surely as an underestimating one.
    Quiet when either side is missing or nonpositive (no measurement ≠
    drift)."""
    if not predicted_s or not measured_s:
        return None
    if predicted_s <= 0 or measured_s <= 0:
        return None
    ratio = measured_s / predicted_s
    if ratio > threshold:
        return (f"measured step time {measured_s * 1e3:.3f} ms is "
                f"{ratio:.1f}x the cost model's {predicted_s * 1e3:.3f} ms "
                f"prediction (threshold {threshold:g}x); recalibrate with "
                "telemetry.calibration.fit_constants on this run's records")
    if ratio < 1.0 / threshold:
        return (f"measured step time {measured_s * 1e3:.3f} ms is "
                f"{1 / ratio:.1f}x BELOW the cost model's "
                f"{predicted_s * 1e3:.3f} ms prediction (threshold "
                f"{threshold:g}x); the model overprices this strategy — "
                "recalibrate with telemetry.calibration.fit_constants")
    return None


@dataclass
class CalibratedConstants:
    """What :func:`fit_constants` returns — drop-in overrides for
    ``estimate_cost(ici_bandwidth=..., alpha=...)``."""

    ici_bandwidth: float
    alpha: float
    n_records: int
    mean_abs_error_s: float            # with the fitted constants
    baseline_mean_abs_error_s: float   # with the defaults

    @property
    def improved(self) -> bool:
        return self.mean_abs_error_s <= self.baseline_mean_abs_error_s

    def as_cost_kwargs(self) -> dict:
        return {"ici_bandwidth": self.ici_bandwidth, "alpha": self.alpha}


def _rows(records) -> np.ndarray:
    """(exposed_bytes, collectives, step_time) rows for usable records:
    a positive measured step time and a known (possibly zero) predicted
    byte count.  Steady-state only: rows beyond
    :data:`OUTLIER_FACTOR` x the median step time (compiles, trace
    windows, checkpoint stalls) are dropped."""
    rows = []
    for r in records:
        step_time = getattr(r, "step_time_s", None) if not isinstance(
            r, dict) else r.get("step_time_s")
        exposed = getattr(r, "exposed_bytes", None) if not isinstance(
            r, dict) else r.get("exposed_bytes")
        ncoll = getattr(r, "num_collectives", None) if not isinstance(
            r, dict) else r.get("num_collectives")
        if step_time is None or step_time <= 0 or exposed is None:
            continue
        rows.append((float(exposed), float(ncoll or 0), float(step_time)))
    arr = np.asarray(rows, dtype=np.float64)
    if arr.size:
        keep = arr[:, 2] <= OUTLIER_FACTOR * float(np.median(arr[:, 2]))
        arr = arr[keep]
    return arr


def comm_time_s(exposed_bytes: float, num_collectives: float,
                ici_bandwidth: float, alpha: float) -> float:
    """The model's exposed-communication time under given constants."""
    return exposed_bytes / ici_bandwidth + alpha * num_collectives


def prediction_error(records: Sequence,
                     ici_bandwidth: float = DEFAULT_ICI_BANDWIDTH,
                     alpha: float = DEFAULT_ALPHA) -> Optional[float]:
    """Mean |measured − modeled| step time (seconds) over the records'
    communication model under the given constants; None without usable
    records.  The figure calibration must reduce."""
    rows = _rows(records)
    if rows.size == 0:
        return None
    pred = comm_time_s(rows[:, 0], rows[:, 1], ici_bandwidth, alpha)
    return float(np.mean(np.abs(rows[:, 2] - pred)))


def fit_constants(records: Sequence,
                  default_bandwidth: float = DEFAULT_ICI_BANDWIDTH,
                  default_alpha: float = DEFAULT_ALPHA
                  ) -> Optional[CalibratedConstants]:
    """Least-squares fit of (bandwidth, alpha) from StepRecords (objects
    or dicts).  Returns None without usable records.

    Degenerate inputs are handled explicitly rather than by blowing up:

    * one run ⇒ constant (bytes, collectives) per row — the normal
      matrix is rank-1 and ``lstsq``'s min-norm solution splits the
      observed time between the two terms; the fit is exact for THAT
      workload, which is precisely what "calibrated on this run's
      records" promises;
    * nonpositive solutions (a compute-bound host where time does not
      grow with bytes) clamp: bandwidth into
      [:data:`_MIN_BANDWIDTH`, :data:`_MAX_BANDWIDTH`], alpha to ≥ 0,
      each refit with the other term held.
    """
    rows = _rows(records)
    if rows.size == 0:
        return None
    x, n, y = rows[:, 0], rows[:, 1], rows[:, 2]
    A = np.stack([x, n], axis=1)
    sol, *_ = np.linalg.lstsq(A, y, rcond=None)
    inv_bw, alpha = float(sol[0]), float(sol[1])
    if alpha < 0:
        alpha = 0.0
        denom = float(np.dot(x, x))
        inv_bw = float(np.dot(x, y) / denom) if denom > 0 else 0.0
    if inv_bw <= 0:
        # Comm time does not grow with bytes here (compute-bound):
        # bandwidth pegs at "free" and alpha absorbs what it can.
        inv_bw = 1.0 / _MAX_BANDWIDTH
        denom = float(np.dot(n, n))
        alpha = max(float(np.dot(n, y - x * inv_bw) / denom), 0.0) \
            if denom > 0 else 0.0
    bandwidth = float(np.clip(1.0 / inv_bw, _MIN_BANDWIDTH, _MAX_BANDWIDTH))
    fitted_err = prediction_error(records, bandwidth, alpha)
    baseline_err = prediction_error(records, default_bandwidth,
                                    default_alpha)
    return CalibratedConstants(
        ici_bandwidth=bandwidth, alpha=alpha, n_records=int(len(rows)),
        mean_abs_error_s=float(fitted_err),
        baseline_mean_abs_error_s=float(baseline_err))


# -- shared pure rules: leg drift and stragglers -----------------------------

def leg_drift_reason(kind: str, measured_s: Optional[float],
                     predicted_s: Optional[float],
                     threshold: float = LEG_DRIFT_THRESHOLD
                     ) -> Optional[str]:
    """Why one leg KIND's measured time has drifted from the leg-priced
    prediction, or None.  The ``telemetry/leg-drift`` rule string (the
    ``bucket_drop_reason`` pattern: one string shared by the lint, the
    CLI compare report, and any runtime check).  Quiet when either side
    is missing or nonpositive."""
    if not predicted_s or not measured_s:
        return None
    if predicted_s <= 0 or measured_s <= 0:
        return None
    ratio = measured_s / predicted_s
    if ratio > threshold:
        return (f"leg kind {kind!r}: measured {measured_s * 1e3:.3f} ms is "
                f"{ratio:.1f}x the leg-priced {predicted_s * 1e3:.3f} ms "
                f"prediction (threshold {threshold:g}x); refit with "
                "telemetry.calibration.fit_leg_constants on this run's "
                "leg samples")
    if ratio < 1.0 / threshold:
        return (f"leg kind {kind!r}: measured {measured_s * 1e3:.3f} ms is "
                f"{1 / ratio:.1f}x BELOW the leg-priced "
                f"{predicted_s * 1e3:.3f} ms prediction (threshold "
                f"{threshold:g}x); the model overprices this leg kind — "
                "refit with telemetry.calibration.fit_leg_constants")
    return None


def drifted_leg_kinds(samples: Sequence, constants=None,
                      threshold: float = LEG_DRIFT_THRESHOLD
                      ) -> Dict[str, str]:
    """Per-leg-kind drift verdicts over live LegSamples — the pure rule
    behind the ScheduleTuner's re-search trigger (and the same
    ``telemetry/leg-drift`` strings the analysis pass prints).

    Each kind's MEASURED total is compared against its PREDICTED total:
    under ``constants`` (a :class:`LegCalibration` — the constants the
    running schedule was priced with) when given, else each sample's
    carried ``predicted_s``.  Returns ``{kind: reason}`` for kinds past
    ``threshold``; {} when nothing drifted."""
    measured: Dict[str, float] = {}
    predicted: Dict[str, float] = {}
    for s in samples:
        kind = _sample_get(s, "kind")
        t = _sample_get(s, "measured_s")
        if kind not in LEG_KINDS or t is None or t <= 0:
            continue
        if constants is not None:
            comp = _sample_get(s, "compressor", "NoneCompressor") \
                or "NoneCompressor"
            p = constants.leg_time_s(
                kind, float(_sample_get(s, "nbytes", 0) or 0),
                quantized=comp not in _LINEAR_COMPRESSORS)
        else:
            p = _sample_get(s, "predicted_s")
        if p is None or p <= 0:
            continue
        measured[kind] = measured.get(kind, 0.0) + float(t)
        predicted[kind] = predicted.get(kind, 0.0) + float(p)
    out: Dict[str, str] = {}
    for kind in sorted(measured):
        why = leg_drift_reason(kind, measured[kind], predicted.get(kind),
                               threshold=threshold)
        if why is not None:
            out[kind] = why
    return out


def straggler_reason(per_host_step_time_s: Optional[Dict[str, float]],
                     threshold: float = STRAGGLER_THRESHOLD
                     ) -> Optional[str]:
    """Why this run has a straggler host, or None.  The
    ``telemetry/straggler`` rule string: fires when the slowest host's
    median step time exceeds ``threshold`` x the fastest host's (an
    SPMD step runs at the slowest participant's pace — every other
    chip idles the difference).  Quiet below two hosts."""
    if not per_host_step_time_s or len(per_host_step_time_s) < 2:
        return None
    usable = {h: float(t) for h, t in per_host_step_time_s.items()
              if t and t > 0}
    if len(usable) < 2:
        return None
    slow_host = max(usable, key=usable.get)
    fast_host = min(usable, key=usable.get)
    ratio = usable[slow_host] / usable[fast_host]
    if ratio <= threshold:
        return None
    return (f"host {slow_host!r} medians {usable[slow_host] * 1e3:.3f} ms "
            f"per step, {ratio:.2f}x host {fast_host!r}'s "
            f"{usable[fast_host] * 1e3:.3f} ms (threshold {threshold:g}x): "
            "every other host idles the difference inside each collective "
            "— inspect that host's input pipeline, thermals, and "
            "background load")


# -- leg-granular calibration ------------------------------------------------

#: leg kinds the per-kind regression fits (the schedule-IR vocabulary,
#: mirrored here as strings so this module stays jax-free and
#: import-light).  The fused kinds (docs/kernels.md) are first-class:
#: a fused_hop / fused_detect / fused_update sample fits ITS OWN
#: constants, so ``estimate_ir_cost`` and ``AutoStrategy(search=True)``
#: see fused-vs-unfused as distinct priced alternatives and
#: ``telemetry/leg-drift`` watches each independently.
LEG_KINDS = ("reduce_scatter", "all_gather", "all_reduce",
             "ppermute_hop", "psum_guard", "ps_exchange", "update",
             "fused_hop", "fused_detect", "fused_update", "all_to_all",
             "hier_reduce_scatter", "dcn_all_reduce", "dcn_exchange",
             "hier_all_gather")

#: compressor names whose wire is full-precision: any other compressor
#: tag on a sample marks it quantized for the quantize-overhead term.
_LINEAR_COMPRESSORS = ("", "NoneCompressor")

_MIN_ALPHA = 0.0
_MAX_ALPHA = 1.0          # one second per launch: slower than any bug


@dataclass
class LegCalibration:
    """Per-leg-kind measured constants — what :func:`fit_leg_constants`
    returns and ``calibration.json`` persists (schema in
    docs/observability.md).

    ``alphas``/``bandwidths`` map leg kind → launch latency (s) /
    effective bytes-per-second over that kind's RAW leg bytes (ring
    hops arrive with per-hop bytes, so the ring-hop alpha here is the
    per-hop launch cost — distinct from the one-shot alpha, which was
    the whole point).  ``quant_overhead_per_byte`` prices the
    quantize/dequantize work a quantized leg adds per wire byte.
    ``scale`` is a step-level correction fitted from StepRecords
    (median measured/leg-predicted ratio): micro-runs measure legs in
    isolation, and the scale absorbs what composition adds.
    ``ici_bandwidth``/``alpha`` carry the whole-step
    :func:`fit_constants` pair so one file calibrates BOTH cost-model
    entry points (``estimate_cost`` via :meth:`as_cost_kwargs`,
    ``estimate_ir_cost`` via per-kind constants)."""

    alphas: Dict[str, float] = field(default_factory=dict)
    bandwidths: Dict[str, float] = field(default_factory=dict)
    quant_overhead_per_byte: float = 0.0
    scale: float = 1.0
    ici_bandwidth: float = DEFAULT_ICI_BANDWIDTH
    alpha: float = DEFAULT_ALPHA
    #: per-schedule-fingerprint leg-predicted step time (s) under these
    #: constants — lets record-level prediction skip re-pricing the IR.
    fingerprints: Dict[str, float] = field(default_factory=dict)
    n_samples: int = 0
    n_records: int = 0
    mean_abs_error_s: Optional[float] = None
    step_fit_mean_abs_error_s: Optional[float] = None
    version: int = CALIBRATION_VERSION

    def leg_time_s(self, kind: str, nbytes: float,
                   quantized: bool = False) -> float:
        """One leg's calibrated time: per-kind alpha + bytes/bandwidth
        (+ the quantize overhead for quantized wire)."""
        a = self.alphas.get(kind, DEFAULT_ALPHA)
        bw = self.bandwidths.get(kind, DEFAULT_ICI_BANDWIDTH)
        t = a + float(nbytes) / bw
        if quantized:
            t += self.quant_overhead_per_byte * float(nbytes)
        return t

    def predict_step_time_s(self, fingerprint: Optional[str]
                            ) -> Optional[float]:
        """Scale-corrected leg-predicted step time for a recorded
        fingerprint (None for an unknown schedule)."""
        if not fingerprint:
            return None
        base = self.fingerprints.get(fingerprint)
        if base is None:
            return None
        return self.scale * base

    def as_cost_kwargs(self) -> dict:
        """Whole-step overrides for ``estimate_cost`` — the pair
        ``AutoStrategy(search=True)`` feeds its ranking."""
        return {"ici_bandwidth": self.ici_bandwidth, "alpha": self.alpha}

    @property
    def improved(self) -> bool:
        """Leg-calibrated record error no worse than the whole-step
        fit's (the acceptance bar; True when either side is unknown —
        absence of records is not a regression)."""
        if self.mean_abs_error_s is None \
                or self.step_fit_mean_abs_error_s is None:
            return True
        return self.mean_abs_error_s <= self.step_fit_mean_abs_error_s

    # -- persistence -------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "alphas": {k: float(v) for k, v in self.alphas.items()},
            "bandwidths": {k: float(v)
                           for k, v in self.bandwidths.items()},
            "quant_overhead_per_byte": float(self.quant_overhead_per_byte),
            "scale": float(self.scale),
            "ici_bandwidth": float(self.ici_bandwidth),
            "alpha": float(self.alpha),
            "fingerprints": {k: float(v)
                             for k, v in self.fingerprints.items()},
            "n_samples": int(self.n_samples),
            "n_records": int(self.n_records),
            "mean_abs_error_s": self.mean_abs_error_s,
            "step_fit_mean_abs_error_s": self.step_fit_mean_abs_error_s,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LegCalibration":
        known = set(cls.__dataclass_fields__)
        return cls(**{k: v for k, v in d.items() if k in known})


def _fit_affine(nbytes: np.ndarray, t: np.ndarray,
                default_bandwidth: float, default_alpha: float
                ) -> Tuple[float, float]:
    """(alpha, bandwidth) least squares of ``t ≈ alpha + nbytes/bw``
    with the same positivity fallbacks as :func:`fit_constants`:
    negative alpha clamps to 0 (refit bandwidth), nonpositive slope
    pegs bandwidth at "free" and alpha at the mean time."""
    if t.size == 0:
        return default_alpha, default_bandwidth
    if t.size == 1 or float(np.ptp(nbytes)) == 0.0:
        # One byte size: split the observation — alpha gets the
        # default share, bandwidth absorbs the rest (exact for THIS
        # leg size, which is what a micro-run can promise).
        mean_t = float(np.mean(t))
        alpha = min(default_alpha, mean_t)
        resid = max(mean_t - alpha, 0.0)
        mean_b = float(np.mean(nbytes))
        if resid > 0 and mean_b > 0:
            bw = mean_b / resid
        else:
            bw = _MAX_BANDWIDTH
        return alpha, float(np.clip(bw, _MIN_BANDWIDTH, _MAX_BANDWIDTH))
    A = np.stack([np.ones_like(nbytes), nbytes], axis=1)
    sol, *_ = np.linalg.lstsq(A, t, rcond=None)
    alpha, inv_bw = float(sol[0]), float(sol[1])
    if alpha < 0:
        alpha = 0.0
        denom = float(np.dot(nbytes, nbytes))
        inv_bw = float(np.dot(nbytes, t) / denom) if denom > 0 else 0.0
    if inv_bw <= 0:
        inv_bw = 1.0 / _MAX_BANDWIDTH
        alpha = max(float(np.mean(t - nbytes * inv_bw)), 0.0)
    bw = float(np.clip(1.0 / inv_bw, _MIN_BANDWIDTH, _MAX_BANDWIDTH))
    return float(np.clip(alpha, _MIN_ALPHA, _MAX_ALPHA)), bw


def _sample_get(s, key, default=None):
    if isinstance(s, dict):
        return s.get(key, default)
    return getattr(s, key, default)


def fit_leg_constants(samples: Sequence, records: Sequence = (),
                      ) -> Optional[LegCalibration]:
    """Regress per-leg-kind constants from :class:`LegSample`s (objects
    or dicts), optionally correcting and scoring against StepRecords.

    Per kind: ``t ≈ alpha_kind + nbytes / bandwidth_kind`` over the
    kind's full-precision samples (ring hops fit their PER-HOP alpha —
    the launch cost a ring chain pays d-1 times where one-shot pays
    once).  Quantized samples then fit ``quant_overhead_per_byte`` on
    their residual vs the full-precision model.  With ``records``, the
    per-fingerprint leg-predicted step times are computed (exposed
    legs only: slotted legs before the last microbatch ride behind
    compute) and a median-ratio ``scale`` plus the leg-calibrated /
    whole-step mean-absolute-error pair land on the result — the
    acceptance comparison ``LegCalibration.improved`` checks.
    Returns None without usable samples."""
    rows: Dict[str, List[Tuple[float, float]]] = {}
    quant_rows: List[Tuple[float, float]] = []
    n_used = 0
    for s in samples:
        kind = _sample_get(s, "kind")
        t = _sample_get(s, "measured_s")
        nb = _sample_get(s, "nbytes", 0)
        if kind not in LEG_KINDS or t is None or t <= 0:
            continue
        n_used += 1
        comp = _sample_get(s, "compressor", "NoneCompressor") \
            or "NoneCompressor"
        if comp in _LINEAR_COMPRESSORS:
            rows.setdefault(kind, []).append((float(nb or 0), float(t)))
        else:
            quant_rows.append((float(nb or 0), float(t), kind))
    if n_used == 0:
        return None
    cal = LegCalibration(n_samples=n_used)
    for kind in LEG_KINDS:
        data = rows.get(kind)
        if not data:
            continue
        arr = np.asarray(data, dtype=np.float64)
        alpha, bw = _fit_affine(arr[:, 0], arr[:, 1],
                                DEFAULT_ICI_BANDWIDTH, DEFAULT_ALPHA)
        cal.alphas[kind] = alpha
        cal.bandwidths[kind] = bw
    if quant_rows:
        resid, nb = [], []
        for b, t, kind in quant_rows:
            base = cal.leg_time_s(kind, b)
            resid.append(t - base)
            nb.append(b)
            # Kinds seen ONLY quantized still need constants: seed from
            # the quantized observation itself (overhead folds to 0).
            if kind not in cal.bandwidths:
                arr_b = np.asarray([b], np.float64)
                arr_t = np.asarray([t], np.float64)
                a, w = _fit_affine(arr_b, arr_t, DEFAULT_ICI_BANDWIDTH,
                                   DEFAULT_ALPHA)
                cal.alphas[kind], cal.bandwidths[kind] = a, w
        nb_arr = np.asarray(nb, np.float64)
        resid_arr = np.asarray(resid, np.float64)
        denom = float(np.dot(nb_arr, nb_arr))
        if denom > 0:
            cal.quant_overhead_per_byte = max(
                float(np.dot(nb_arr, resid_arr) / denom), 0.0)
    # Per-fingerprint exposed-leg step prediction under the new
    # constants (jax-free: pure arithmetic over the samples).  Slotted
    # legs before the final microbatch ride behind the next backward
    # (the cost model's rule); the final slot is exposed — the per-
    # fingerprint accumulation depth is inferred as max(slot)+1.
    max_slot: Dict[str, int] = {}
    for s in samples:
        fp = _sample_get(s, "schedule_fingerprint") or ""
        slot = _sample_get(s, "slot", -1)
        if fp and slot is not None and slot >= 0:
            max_slot[fp] = max(max_slot.get(fp, 0), int(slot))
    fp_time: Dict[str, float] = {}
    for s in samples:
        fp = _sample_get(s, "schedule_fingerprint") or ""
        kind = _sample_get(s, "kind")
        if not fp or kind not in LEG_KINDS:
            continue
        slot = _sample_get(s, "slot", -1)
        if slot is not None and 0 <= slot < max_slot.get(fp, 0):
            continue                      # hidden behind the pipeline
        comp = _sample_get(s, "compressor", "NoneCompressor") \
            or "NoneCompressor"
        fp_time[fp] = fp_time.get(fp, 0.0) + cal.leg_time_s(
            kind, float(_sample_get(s, "nbytes", 0) or 0),
            quantized=comp not in _LINEAR_COMPRESSORS)
    cal.fingerprints = fp_time
    # Step-record correction + the acceptance error pair.
    if records:
        pairs = []
        for r in records:
            st = _sample_get(r, "step_time_s")
            fp = _sample_get(r, "schedule_fingerprint")
            base = fp_time.get(fp or "")
            if st and st > 0 and base and base > 0:
                pairs.append((float(st), float(base)))
        if pairs:
            arr = np.asarray(pairs, np.float64)
            keep = arr[:, 0] <= OUTLIER_FACTOR * float(
                np.median(arr[:, 0]))
            arr = arr[keep]
            if arr.size:
                cal.scale = float(np.median(arr[:, 0] / arr[:, 1]))
                cal.n_records = int(arr.shape[0])
                cal.mean_abs_error_s = float(np.mean(
                    np.abs(arr[:, 0] - cal.scale * arr[:, 1])))
        step_fit = fit_constants(records)
        if step_fit is not None:
            cal.ici_bandwidth = step_fit.ici_bandwidth
            cal.alpha = step_fit.alpha
            cal.step_fit_mean_abs_error_s = step_fit.mean_abs_error_s
    return cal


# -- calibration.json persistence + automatic discovery ----------------------

def save_calibration(cal: LegCalibration, path: str) -> str:
    """Write ``calibration.json`` (atomic: temp file + rename so a
    concurrent loader never reads a torn file).  The in-process default
    cache is invalidated so a same-process refit (the ScheduleTuner
    path) is picked up immediately, even on filesystems whose mtime
    granularity cannot distinguish two writes in one tick."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(cal.to_dict(), f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    reset_calibration_cache_for_testing()
    return path


def load_calibration(path: str) -> Optional[LegCalibration]:
    """Parse one ``calibration.json``; None on any failure (a corrupt
    calibration must degrade to defaults, not kill the search)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            d = json.load(f)
        if not isinstance(d, dict):
            return None
        return LegCalibration.from_dict(d)
    except (OSError, ValueError, TypeError):
        return None


def default_calibration_path() -> Optional[str]:
    """Where the automatic loaders look: ``AUTODIST_CALIBRATION``
    (explicit file path) first, else ``calibration.json`` inside
    ``AUTODIST_TELEMETRY_DIR``.  None when neither is set — automatic
    calibration is an explicit environment opt-in, so an estimate is
    reproducible from the env alone."""
    from autodist_tpu.const import ENV

    explicit = ENV.AUTODIST_CALIBRATION.val
    if explicit:
        return explicit
    base = ENV.AUTODIST_TELEMETRY_DIR.val
    if base:
        candidate = os.path.join(base, "calibration.json")
        if os.path.exists(candidate):
            return candidate
    return None


_default_cache: Tuple[Optional[str], Optional[tuple],
                      Optional[LegCalibration]] = (None, None, None)


def load_default_calibration() -> Optional[LegCalibration]:
    """The constants ``estimate_ir_cost`` and ``AutoStrategy(search=
    ...)`` pick up automatically (no flags): cached by the resolved
    path plus a stat signature so the per-candidate search loop pays
    one stat, not one parse.

    The cache key is the RESOLVED path — so flipping
    ``AUTODIST_CALIBRATION`` between an explicit file and
    ``AUTODIST_TELEMETRY_DIR`` run-dir discovery mid-process reloads
    whenever the resolution lands somewhere new — and the stat
    signature is ``(mtime_ns, size, inode)``, not the float mtime: an
    atomic rewrite (``save_calibration``'s temp-file + rename) always
    changes the inode, so a refit landing within one mtime tick can
    never serve stale constants to the tuner."""
    global _default_cache
    path = default_calibration_path()
    if path is None:
        return None
    try:
        st = os.stat(path)
    except OSError:
        return None
    sig = (st.st_mtime_ns, st.st_size, st.st_ino)
    cached_path, cached_sig, cached = _default_cache
    if cached_path == path and cached_sig == sig:
        return cached
    cal = load_calibration(path)
    _default_cache = (path, sig, cal)
    return cal


def reset_calibration_cache_for_testing() -> None:
    global _default_cache
    _default_cache = (None, None, None)


def predicted_vs_measured(records: Sequence) -> Optional[dict]:
    """Aggregate comparison for reporting: MEDIAN measured step time
    (robust to compile/trace-window outliers — one 4 s profiler flush
    must not declare the model drifted) vs the records' carried
    full-model prediction, plus the drift verdict.  None without usable
    records."""
    steps: List[float] = []
    preds: List[float] = []
    for r in records:
        get = (lambda k, rr=r: rr.get(k)) if isinstance(r, dict) \
            else (lambda k, rr=r: getattr(rr, k, None))
        st = get("step_time_s")
        if st is None or st <= 0:
            continue
        steps.append(float(st))
        p = get("predicted_step_time_s")
        if p:
            preds.append(float(p))
    if not steps:
        return None
    measured = float(np.median(steps))
    predicted = float(np.median(preds)) if preds else None
    return {
        "n_steps": len(steps),
        "measured_step_time_s": measured,
        "predicted_step_time_s": predicted,
        "ratio": (measured / predicted) if predicted else None,
        "drift": model_drift_reason(predicted, measured),
    }
