"""JAX version-compatibility shims.

The framework targets the current ``jax.shard_map`` / ``jax.set_mesh``
API surface; older jaxlibs (0.4.x) spell these
``jax.experimental.shard_map.shard_map`` (with ``check_rep``/``auto``
instead of ``check_vma``/``axis_names``) and have no ``set_mesh``.  All
internal call sites route through this module so the framework runs
unmodified on both; each shim forwards verbatim when the modern API
exists.
"""
from __future__ import annotations

import contextlib

import jax


def _native(name):
    """The real jax attribute, ignoring any compat alias installed onto
    the jax module (e.g. by tests/conftest.py) — prevents recursion."""
    fn = getattr(jax, name, None)
    if fn is not None and not getattr(fn, "_autodist_compat", False):
        return fn
    return None


def has_native(name: str) -> bool:
    """True when the REAL modern jax API exists (compat aliases a test
    harness may have installed onto the jax module don't count)."""
    return _native(name) is not None


def require_native(name: str, feature: str) -> None:
    """Raise cleanly when ``feature`` needs the modern API — for code
    whose legacy-API fallback is known to hard-abort XLA (a crash is
    strictly worse than a NotImplementedError)."""
    if not has_native(name):
        raise NotImplementedError(
            f"{feature} requires the native jax.{name} API; this jax "
            "version only has the legacy spelling, whose lowering is "
            "known to miscompile this program")


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=None, **kwargs):
    """``jax.shard_map`` with graceful fallback to the 0.4.x
    ``jax.experimental.shard_map`` spelling.

    ``axis_names`` (the MANUAL axes; everything else stays auto) maps to
    the legacy ``auto=`` complement; ``check_vma`` maps to the legacy
    ``check_rep`` (both disable the replication/varying checker)."""
    native = _native("shard_map")
    if native is not None:
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return native(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)

    from jax.experimental.shard_map import shard_map as _legacy

    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kwargs["auto"] = auto
    return _legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   **kwargs)


shard_map._autodist_compat = True


def set_mesh(mesh):
    """``jax.set_mesh`` context; on 0.4.x jaxlibs falls back to the
    Mesh's own context manager (the legacy global-mesh mechanism the
    sharding-in-types mesh replaced)."""
    native = _native("set_mesh")
    if native is not None:
        return native(mesh)
    if hasattr(jax.sharding, "use_mesh"):  # pragma: no cover - 0.5.x
        return jax.sharding.use_mesh(mesh)
    return _legacy_mesh_context(mesh)


set_mesh._autodist_compat = True


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` (static size of a bound mesh axis inside
    shard_map); 0.4.x jaxlibs expose it as ``jax.core.axis_frame``."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    from jax import core

    return core.axis_frame(axis_name)  # 0.4.x returns the size directly


axis_size._autodist_compat = True


def pcast(x, axis_name, *, to="varying"):
    """``jax.lax.pcast`` (vma cast).  Older jaxlibs either spell the
    varying cast ``pvary`` or (0.4.x) have no varying-mesh-axis tracking
    at all, where the cast is semantically an identity."""
    from jax import lax

    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to=to)
    if to == "varying" and hasattr(lax, "pvary"):
        return lax.pvary(x, axis_name)
    return x


pcast._autodist_compat = True


@contextlib.contextmanager
def _legacy_mesh_context(mesh):
    with mesh:
        yield mesh
