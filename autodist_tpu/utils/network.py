"""Network helpers.

Parity with reference ``autodist/utils/network.py:1-75`` (``is_local_address``,
local-ip discovery) without the ``netifaces`` dependency: stdlib ``socket``
enumeration covers the hostname/loopback cases, and a UDP-connect probe
recovers the primary outbound interface address.
"""
from __future__ import annotations

import functools
import socket
from typing import Set

_LOCAL_SYNONYMS = {"localhost", "127.0.0.1", "0.0.0.0", "::1"}


@functools.lru_cache(maxsize=1)
def local_addresses() -> Set[str]:
    """All addresses that refer to this host.  Cached: DNS lookups and the
    UDP probe can each block for seconds on resolver-less hosts, and the
    coordinator calls this several times per node during bootstrap."""
    addrs = set(_LOCAL_SYNONYMS)
    hostname = socket.gethostname()
    addrs.add(hostname)
    try:
        addrs.add(socket.gethostbyname(hostname))
    except OSError:
        pass
    try:
        for info in socket.getaddrinfo(hostname, None):
            addrs.add(info[4][0])
    except OSError:
        pass
    # Primary outbound interface (no packets are sent by connect() on UDP).
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            addrs.add(s.getsockname()[0])
    except OSError:
        pass
    return addrs


def is_local_address(address: str) -> bool:
    """Whether ``address`` (ip or hostname, optionally ``host:port``) is this
    machine.  Reference ``autodist/utils/network.py`` semantics."""
    host = address.rsplit(":", 1)[0] if _looks_like_host_port(address) else address
    if host in _LOCAL_SYNONYMS:
        return True
    locals_ = local_addresses()
    if host in locals_:
        return True
    try:
        resolved = socket.gethostbyname(host)
    except OSError:
        return False
    return resolved in locals_


def _looks_like_host_port(address: str) -> bool:
    if address.count(":") != 1:
        return False
    host, port = address.split(":")
    return port.isdigit()
