"""Sharding visualization — the TPU analog of the reference's graph
visualizer (``autodist/utils/visualization_util.py:24-36``, which wrote
TensorBoard event files of each transform stage).

A sharded-training program's "graph picture" is its placement: which mesh
coordinates hold which slice of every variable.  ``sharding_table``
renders that as text — one row per variable with its PartitionSpec,
physical shard shape, and per-shard device map — and
``log_shardings`` writes it through the tracing dump machinery next to
the plan-table/StableHLO/HLO artifacts.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np


def _leaf_rows(path: str, arr: Any) -> str:
    sh = getattr(arr, "sharding", None)
    spec = getattr(sh, "spec", None)
    shards = getattr(arr, "addressable_shards", None)
    if not shards:
        # None: a host value (unplaced).  Empty: every shard lives on
        # another process's devices (multi-controller) — still a row, not
        # a crash.
        tag = "(unplaced)" if shards is None else "(no local shards)"
        return f"{path:<40} {str(np.shape(arr)):<18} {tag}"
    shard_shape = tuple(shards[0].data.shape)
    n_dev = len(getattr(sh, "device_set", ())) or len(shards)
    dev0 = shards[0].device
    kind = getattr(dev0, "platform", "?")
    return (f"{path:<40} {str(tuple(arr.shape)):<18} "
            f"spec={str(spec):<28} shard={str(shard_shape):<18} "
            f"{n_dev}x{kind}")


def sharding_table(tree: Any, title: str = "shardings") -> str:
    """Text table of every leaf's global shape, PartitionSpec, physical
    shard shape, and device count."""
    lines = [f"# {title}",
             f"{'variable':<40} {'global':<18} "
             f"{'spec':<33} {'shard':<24} devices"]
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in flat:
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        lines.append(_leaf_rows(name, leaf))
    return "\n".join(lines) + "\n"


def ascii_device_grid(arr: Any) -> str:
    """Per-shard device map of one array (a text
    ``jax.debug.visualize_array_sharding``): each addressable shard's
    index range and device."""
    shards = getattr(arr, "addressable_shards", None)
    if not shards:
        return "(no addressable shards)"
    out = []
    for s in shards:
        idx = tuple(
            f"{sl.start or 0}:{sl.stop if sl.stop is not None else 'end'}"
            if isinstance(sl, slice) else str(sl)
            for sl in (s.index if isinstance(s.index, tuple) else (s.index,)))
        out.append(f"  [{', '.join(idx) or ':'}] -> {s.device}")
    return "\n".join(out)


def log_shardings(session, tag: str = "4-placement") -> Optional[str]:
    """Write the session's parameter-placement table through the staged
    dump machinery (enabled by ``AUTODIST_DUMP_GRAPHS``); returns the
    dump path, or None when dumps are disabled."""
    from autodist_tpu.utils import tracing

    if not tracing.dumps_enabled():
        return None
    table = sharding_table(session.sharded_params,
                           title=f"mesh={dict(session.mesh.shape)}")
    return tracing.dump_stage(session._run_id, tag, table)
