"""Tracing & per-stage program dumps.

Parity target: reference auxiliary subsystem #1 (SURVEY §5.1) —
Chrome-trace timelines per ``session.run`` when tracing is on
(``autodist/runner.py:64-75, 117-132`` → ``/tmp/autodist/traces``) and graph
snapshots at each transform stage (``kernel/graph_transformer.py:62-90`` →
TensorBoard files under ``/tmp/autodist/graphs``).

TPU-native translation:

* run tracing → ``jax.profiler`` device traces (TensorBoard/perfetto
  format — the XLA/TPU replacement for TF Chrome timelines), capturing the
  first ``AUTODIST_TRACE_STEPS`` session steps under
  ``$AUTODIST_TPU_WORKDIR/traces/<run-id>``, each step wrapped in a
  ``StepTraceAnnotation``;
* graph snapshots → staged *program* dumps under
  ``$AUTODIST_TPU_WORKDIR/graphs/<run-id>/`` when ``AUTODIST_DUMP_GRAPHS``
  is set: the strategy's per-variable plan table (the analog of
  "1-after-partition"), the step's StableHLO right after tracing (the
  "transformed graph"), and the XLA-optimized HLO after compilation (what
  actually runs — sharded, fused, with collectives inserted).
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from autodist_tpu.const import (
    DEFAULT_GRAPH_DIR,
    DEFAULT_TRACE_DIR,
    ENV,
)
from autodist_tpu.utils import logging


def dumps_enabled() -> bool:
    return ENV.AUTODIST_DUMP_GRAPHS.val


def dump_stage(run_id: str, tag: str, text: str) -> Optional[str]:
    """Write one staged program dump; returns the path (None when off)."""
    if not dumps_enabled():
        return None
    d = os.path.join(DEFAULT_GRAPH_DIR, run_id)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{tag}.txt")
    with open(path, "w") as f:
        f.write(text)
    logging.info("dumped %s (%d bytes)", path, len(text))
    return path


def plan_table(compiled) -> str:
    """Human-readable per-variable plan table (the partition/placement
    snapshot — reference stage '1-after-partition')."""
    lines = [f"mesh: {dict(compiled.mesh.shape)}",
             f"batch axes: {compiled.batch_axes}", ""]
    header = (f"{'variable':40s} {'sync':10s} {'param_spec':28s} "
              f"{'opt_spec':28s} {'reduce':12s} extras")
    lines.append(header)
    lines.append("-" * len(header))
    for name in sorted(compiled.var_plans):
        p = compiled.var_plans[name]
        extras = []
        if p.compressor not in ("", "NoneCompressor"):
            extras.append(f"compressor={p.compressor}")
        if p.staleness:
            extras.append(f"staleness={p.staleness}")
        if p.num_shards > 1:
            extras.append(f"shards={p.num_shards}@axis{p.partition_axis}")
        if p.sparse:
            extras.append("sparse")
        lines.append(
            f"{name:40s} {p.sync_kind:10s} {str(p.param_spec):28s} "
            f"{str(p.opt_spec):28s} {','.join(p.grad_reduce_axes):12s} "
            f"{' '.join(extras)}")
    return "\n".join(lines) + "\n"


# The JAX profiler allows one active trace per process; track the owner so
# a second session (or interpreter exit) flushes a partial window instead of
# losing it / crashing the next start_trace.
_active_tracer: Optional["RunTracer"] = None
_atexit_registered = False


def flush_active_trace() -> None:
    """Stop and write whichever trace window is currently open (no-op when
    none is).  Called before a new window opens and at interpreter exit, so
    sessions that run fewer steps than AUTODIST_TRACE_STEPS still produce a
    (partial) trace."""
    global _active_tracer
    t = _active_tracer
    _active_tracer = None
    if t is not None and t._active:
        t._active = False
        jax.profiler.stop_trace()
        logging.info("profiler trace written → %s", t._dir)


def _parse_trace_at(spec: str) -> tuple:
    """``AUTODIST_TRACE_AT="120,5000"`` → sorted unique step numbers at
    which a capture window opens (empty tuple when unset)."""
    steps = set()
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            steps.add(int(part))
        except ValueError:
            raise ValueError(
                f"AUTODIST_TRACE_AT must be comma-separated step numbers, "
                f"got {spec!r}")
    return tuple(sorted(steps))


class RunTracer:
    """Profiler-trace controller with re-armable capture windows.

    ``AUTODIST_TRACE_STEPS=N`` captures steps 0..N-1 of every
    DistributedSession into one ``jax.profiler`` trace (the original
    behavior).  ``AUTODIST_TRACE_AT=<step>[,<step>...]`` instead opens a
    window at each listed step MID-RUN — e.g. ``AUTODIST_TRACE_AT=5000``
    profiles the steady state instead of the compile-skewed warmup —
    each window spanning ``AUTODIST_TRACE_STEPS`` steps (min 1) and
    written to its own ``step<K>/`` subdirectory.  Windows never
    overlap: an open window is flushed (``flush_active_trace``) before
    the next one starts, and the JAX profiler's one-active-trace
    invariant is preserved across sessions and interpreter exit.
    Viewable with TensorBoard's profile plugin or perfetto.
    """

    def __init__(self, run_id: str):
        self._steps = ENV.AUTODIST_TRACE_STEPS.val
        self._at = _parse_trace_at(ENV.AUTODIST_TRACE_AT.val)
        # Window starts: the explicit re-arm list, else the legacy
        # steps-0..N-1 single window.
        self._starts = set(self._at) if self._at \
            else ({0} if self._steps > 0 else set())
        self._window_len = max(self._steps, 1) if self._starts else 0
        self._base_dir = os.path.join(DEFAULT_TRACE_DIR, run_id)
        self._dir = self._base_dir
        self._active = False
        self._window_end = -1

    @property
    def enabled(self) -> bool:
        return bool(self._starts)

    def step(self, step_count: int):
        """Returns a context manager annotating this step; starts/stops the
        trace session at the capture-window edges."""
        if not self.enabled:
            return _NULL_CTX
        if step_count in self._starts:
            global _active_tracer, _atexit_registered
            # Flush whichever window is open — a prior session's partial
            # window, or THIS tracer's still-open window when two start
            # steps sit closer than the window length (no overlap, ever).
            flush_active_trace()
            self._active = False
            if not _atexit_registered:
                import atexit
                atexit.register(flush_active_trace)
                _atexit_registered = True
            # Re-armable windows land in per-window subdirectories so a
            # later window never clobbers an earlier capture.
            self._dir = os.path.join(self._base_dir, f"step{step_count}") \
                if self._at else self._base_dir
            os.makedirs(self._dir, exist_ok=True)
            jax.profiler.start_trace(self._dir)
            self._active = True
            self._window_end = step_count + self._window_len
            _active_tracer = self
            logging.info("profiler trace started → %s (%d steps)",
                         self._dir, self._window_len)
        return jax.profiler.StepTraceAnnotation("autodist_step",
                                                step_num=step_count)

    def after_step(self, step_count: int) -> None:
        if self._active and step_count + 1 >= self._window_end:
            flush_active_trace()


class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()
