"""Tracing & per-stage program dumps.

Parity target: reference auxiliary subsystem #1 (SURVEY §5.1) —
Chrome-trace timelines per ``session.run`` when tracing is on
(``autodist/runner.py:64-75, 117-132`` → ``/tmp/autodist/traces``) and graph
snapshots at each transform stage (``kernel/graph_transformer.py:62-90`` →
TensorBoard files under ``/tmp/autodist/graphs``).

TPU-native translation:

* run tracing → ``jax.profiler`` device traces (TensorBoard/perfetto
  format — the XLA/TPU replacement for TF Chrome timelines), capturing the
  first ``AUTODIST_TRACE_STEPS`` session steps under
  ``$AUTODIST_TPU_WORKDIR/traces/<run-id>``, each step wrapped in a
  ``StepTraceAnnotation``;
* graph snapshots → staged *program* dumps under
  ``$AUTODIST_TPU_WORKDIR/graphs/<run-id>/`` when ``AUTODIST_DUMP_GRAPHS``
  is set: the strategy's per-variable plan table (the analog of
  "1-after-partition"), the step's StableHLO right after tracing (the
  "transformed graph"), and the XLA-optimized HLO after compilation (what
  actually runs — sharded, fused, with collectives inserted).
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from autodist_tpu.const import (
    DEFAULT_GRAPH_DIR,
    DEFAULT_TRACE_DIR,
    ENV,
)
from autodist_tpu.utils import logging


def dumps_enabled() -> bool:
    return ENV.AUTODIST_DUMP_GRAPHS.val


def dump_stage(run_id: str, tag: str, text: str) -> Optional[str]:
    """Write one staged program dump; returns the path (None when off)."""
    if not dumps_enabled():
        return None
    d = os.path.join(DEFAULT_GRAPH_DIR, run_id)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{tag}.txt")
    with open(path, "w") as f:
        f.write(text)
    logging.info("dumped %s (%d bytes)", path, len(text))
    return path


def plan_table(compiled) -> str:
    """Human-readable per-variable plan table (the partition/placement
    snapshot — reference stage '1-after-partition')."""
    lines = [f"mesh: {dict(compiled.mesh.shape)}",
             f"batch axes: {compiled.batch_axes}", ""]
    header = (f"{'variable':40s} {'sync':10s} {'param_spec':28s} "
              f"{'opt_spec':28s} {'reduce':12s} extras")
    lines.append(header)
    lines.append("-" * len(header))
    for name in sorted(compiled.var_plans):
        p = compiled.var_plans[name]
        extras = []
        if p.compressor not in ("", "NoneCompressor"):
            extras.append(f"compressor={p.compressor}")
        if p.staleness:
            extras.append(f"staleness={p.staleness}")
        if p.num_shards > 1:
            extras.append(f"shards={p.num_shards}@axis{p.partition_axis}")
        if p.sparse:
            extras.append("sparse")
        lines.append(
            f"{name:40s} {p.sync_kind:10s} {str(p.param_spec):28s} "
            f"{str(p.opt_spec):28s} {','.join(p.grad_reduce_axes):12s} "
            f"{' '.join(extras)}")
    return "\n".join(lines) + "\n"


# The JAX profiler allows one active trace per process; track the owner so
# a second session (or interpreter exit) flushes a partial window instead of
# losing it / crashing the next start_trace.
_active_tracer: Optional["RunTracer"] = None
_atexit_registered = False


def flush_active_trace() -> None:
    """Stop and write whichever trace window is currently open (no-op when
    none is).  Called before a new window opens and at interpreter exit, so
    sessions that run fewer steps than AUTODIST_TRACE_STEPS still produce a
    (partial) trace."""
    global _active_tracer
    t = _active_tracer
    _active_tracer = None
    if t is not None and t._active:
        t._active = False
        jax.profiler.stop_trace()
        logging.info("profiler trace written → %s", t._dir)


class RunTracer:
    """Profiler-trace controller for a session's first N steps.

    ``AUTODIST_TRACE_STEPS=N`` captures steps 0..N-1 of every
    DistributedSession into one ``jax.profiler`` trace.  Viewable with
    TensorBoard's profile plugin or perfetto.
    """

    def __init__(self, run_id: str):
        self._steps = ENV.AUTODIST_TRACE_STEPS.val
        self._dir = os.path.join(DEFAULT_TRACE_DIR, run_id)
        self._active = False

    @property
    def enabled(self) -> bool:
        return self._steps > 0

    def step(self, step_count: int):
        """Returns a context manager annotating this step; starts/stops the
        trace session at the capture-window edges."""
        if not self.enabled:
            return _NULL_CTX
        if step_count == 0 and not self._active:
            global _active_tracer, _atexit_registered
            flush_active_trace()  # a prior session's partial window
            if not _atexit_registered:
                import atexit
                atexit.register(flush_active_trace)
                _atexit_registered = True
            os.makedirs(self._dir, exist_ok=True)
            jax.profiler.start_trace(self._dir)
            self._active = True
            _active_tracer = self
            logging.info("profiler trace started → %s (%d steps)",
                         self._dir, self._steps)
        return jax.profiler.StepTraceAnnotation("autodist_step",
                                                step_num=step_count)

    def after_step(self, step_count: int) -> None:
        if self._active and step_count + 1 >= self._steps:
            flush_active_trace()


class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()
