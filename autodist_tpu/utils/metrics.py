"""Throughput and MFU instrumentation.

The reference measured throughput only in example scripts (TimeHistory,
``examples/benchmark/imagenet.py:85-120``); here it is a framework feature:
:class:`ThroughputMeter` is fed by every ``DistributedSession.run`` call,
and :func:`session_mfu` turns XLA's compiled cost analysis into a
model-FLOPs-utilization figure against the chip's peak — the metric TPU
work is judged by (bench.py reports the same numbers for the headline run).
"""
from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Optional

# Peak dense bf16 FLOP/s per chip, keyed by PJRT device_kind substring.
PEAK_FLOPS_BY_KIND = {
    "v5 lite": 197e12, "v5e": 197e12,
    "v5p": 459e12, "v5": 459e12,
    "v4": 275e12,
    "v6 lite": 918e12, "v6e": 918e12, "trillium": 918e12,
    "v3": 123e12, "v2": 46e12,
}


def peak_flops_per_chip(device) -> float:
    """Peak dense bf16 FLOP/s of ``device`` (0.0 when unknown/non-TPU)."""
    kind = (getattr(device, "device_kind", "") or "").lower()
    for key, peak in PEAK_FLOPS_BY_KIND.items():
        if key in kind:
            return peak
    return 0.0


class ThroughputMeter:
    """Sliding-window step-time tracker (last ``window`` steps).

    Wall-clock between consecutive ``tick()`` calls — with async dispatch
    (``sess.run(sync=False)``) this measures the DISPATCH rate until the
    pipeline fills, then converges to true step time; synchronous runs
    measure it directly."""

    def __init__(self, window: int = 50):
        self._times: deque = deque(maxlen=window + 1)

    def tick(self) -> None:
        self._times.append(time.perf_counter())

    @property
    def steps_recorded(self) -> int:
        return max(0, len(self._times) - 1)

    def step_time(self) -> Optional[float]:
        """Mean seconds/step over the window (None until 2 ticks)."""
        if len(self._times) < 2:
            return None
        return (self._times[-1] - self._times[0]) / (len(self._times) - 1)

    def stats(self, items_per_step: Optional[int] = None) -> Dict[str, Any]:
        st = self.step_time()
        out: Dict[str, Any] = {
            "steps_measured": self.steps_recorded,
            "step_time_ms": None if st is None else round(st * 1e3, 3),
            "steps_per_sec": None if st in (None, 0.0) else round(1.0 / st, 3),
        }
        if items_per_step is not None and st not in (None, 0.0):
            out["items_per_sec"] = round(items_per_step / st, 2)
        return out


def step_flops(step_fn, *args) -> Optional[float]:
    """Model FLOPs of one compiled step from XLA's cost analysis (exact for
    the program that runs); None when the backend doesn't expose it.

    Note: ``lower().compile()`` is AOT — on a cold jit cache this compiles
    the program a second time, so call it once and cache the result."""
    try:
        cost = step_fn.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:  # pragma: no cover - backend-dependent
        return None


def mfu(flops_per_step: float, step_time_s: float, devices) -> Optional[float]:
    """Model FLOPs utilization: per-step model FLOPs over what the mesh's
    chips could do in that wall time (None for unknown chips)."""
    peak = sum(peak_flops_per_chip(d) for d in devices)
    if not peak or not step_time_s:
        return None
    return flops_per_step / step_time_s / peak
