"""Logging for autodist_tpu.

Parity target: reference ``autodist/utils/logging.py:30-146`` — a module-level
logger writing to stderr and a timestamped file under the working directory,
verbosity controlled by ``AUTODIST_MIN_LOG_LEVEL``.
"""
from __future__ import annotations

import logging as _logging
import os
import sys
import time

from autodist_tpu.const import DEFAULT_LOG_DIR, ENV

_LOGGER_NAME = "autodist_tpu"
_logger = None


def _get_logger() -> _logging.Logger:
    global _logger
    if _logger is not None:
        return _logger
    logger = _logging.getLogger(_LOGGER_NAME)
    logger.propagate = False
    level_name = str(ENV.AUTODIST_MIN_LOG_LEVEL.val).upper()
    level = getattr(_logging, level_name, _logging.INFO)
    logger.setLevel(level)
    fmt = _logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s: %(message)s", datefmt="%H:%M:%S"
    )
    sh = _logging.StreamHandler(sys.stderr)
    sh.setFormatter(fmt)
    logger.addHandler(sh)
    # Timestamped logfile, like the reference's /tmp/autodist/logs/ files.
    try:
        os.makedirs(DEFAULT_LOG_DIR, exist_ok=True)
        fh = _logging.FileHandler(
            os.path.join(DEFAULT_LOG_DIR, time.strftime("%Y%m%d-%H%M%S") + ".log")
        )
        fh.setFormatter(fmt)
        logger.addHandler(fh)
    except OSError:
        pass
    _logger = logger
    return logger


def set_verbosity(level) -> None:
    _get_logger().setLevel(level)


def debug(msg, *args, **kwargs):
    _get_logger().debug(msg, *args, **kwargs)


def info(msg, *args, **kwargs):
    _get_logger().info(msg, *args, **kwargs)


def warning(msg, *args, **kwargs):
    _get_logger().warning(msg, *args, **kwargs)


def error(msg, *args, **kwargs):
    _get_logger().error(msg, *args, **kwargs)
