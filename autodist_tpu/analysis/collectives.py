"""Collective-schedule consistency pass: a static deadlock lint for
pipeline and MoE plans.

SPMD programs cannot deadlock on strategy choices — every device runs
the same program.  The hazard lives exactly where this framework leaves
SPMD: the ``shard_map``-manual pipeline schedules (``parallel/pipeline``,
``pipeline_1f1b``) and hand-laid per-stage parameter groups, where each
stage's devices issue their own collective sequence.  If stage 0's
variables all-reduce through a compressor while stage 1's do a plain
psum, or one stage fuses its group into a single concat-and-pmean while
another issues per-variable reductions, the stages disagree on the
*number and order* of collectives — the classic SPMD hang.

The pass reconstructs, per stage/expert group, the ordered collective
sequence the plan implies (catalog order: one entry per synced variable
— kind, compressor wire, fused-group id, reduce axes, staleness) and
requires the sequences to be identical across groups.  Stage identity
comes from two sources:

* **stacked** parameters (``pipeline_vars``/``expert_vars``): one
  variable spans all stages, so its collective is uniform by
  construction — only the stack shapes are checked for agreement;
* **named** per-stage parameter groups — a path component matching
  ``stage<k>`` / ``expert<k>`` (e.g. ``stage0/attn/kernel``) — the
  layout of hand-built non-stacked pipelines, where the lint has real
  teeth.

Rules (docs/analysis.md):

* ``collectives/stage-collective-mismatch`` (ERROR) — per-stage groups
  issue different ordered collective sequences (length or entry).
* ``collectives/stage-stack-heterogeneous`` (WARN) — stacked pipeline
  (or expert) variables disagree on the stage/expert stack size.
* ``collectives/unused-parallel-axis`` (WARN) — the mesh carries a
  pipe/expert axis of size > 1 but no variable uses it.
* ``collectives/staleness-mixed`` (WARN) — some-but-not-all PS plans use
  bounded staleness: stale and fresh gradients interleave on one update
  schedule (legal, rarely intended).
"""
from __future__ import annotations

import re
from typing import Dict, List, Tuple

from autodist_tpu.analysis.analyzer import (
    AnalysisContext,
    PlanLite,
    register_pass,
)
from autodist_tpu.analysis.diagnostics import Diagnostic, Severity, diag
from autodist_tpu.const import MESH_AXIS_EXPERT, MESH_AXIS_PIPE

_GROUP_RE = re.compile(r"(?:^|/)(stage|expert)[_-]?(\d+)(?=/|$)")


def _collective_entry(plan: PlanLite) -> Tuple:
    """One variable's contribution to the static collective schedule.
    ``sync_mode`` is part of the identity: a stage reduce-scattering
    what another stage all-reduces issues a different collective."""
    return (plan.sync_kind, plan.compressor or "NoneCompressor",
            bool(plan.fused), plan.group, tuple(plan.grad_reduce_axes),
            int(plan.staleness), tuple(sorted(plan.placement.items())),
            getattr(plan, "sync_mode", "all_reduce"))


def _named_groups(ctx: AnalysisContext
                  ) -> Dict[str, Dict[int, List[Tuple[str, PlanLite]]]]:
    """{kind: {index: [(name-with-index-erased, plan), ...]}} in catalog
    order — the per-stage sequences to compare."""
    groups: Dict[str, Dict[int, List[Tuple[str, PlanLite]]]] = {}
    for var in ctx.graph_item.info.variables:  # catalog order = schedule order
        plan = ctx.plans.get(var.name)
        if plan is None or plan.sync_kind is None:
            continue
        m = _GROUP_RE.search(var.name)
        if not m:
            continue
        kind, idx = m.group(1), int(m.group(2))
        erased = var.name[:m.start()] + f"/{kind}<i>" + var.name[m.end():]
        groups.setdefault(kind, {}).setdefault(idx, []).append(
            (erased.lstrip("/"), plan))
    return groups


def _check_named_groups(ctx: AnalysisContext) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for kind, by_idx in _named_groups(ctx).items():
        if len(by_idx) < 2:
            continue
        sequences = {
            idx: [(name, _collective_entry(plan)) for name, plan in entries]
            for idx, entries in by_idx.items()}
        base_idx = min(sequences)
        base = sequences[base_idx]
        for idx in sorted(sequences):
            if idx == base_idx:
                continue
            seq = sequences[idx]
            if len(seq) != len(base):
                diags.append(diag(
                    "collectives/stage-collective-mismatch", Severity.ERROR,
                    f"{kind} {idx} issues {len(seq)} collective(s) but "
                    f"{kind} {base_idx} issues {len(base)}: the manual "
                    "schedule's shards would block on unmatched "
                    "collectives",
                    location=f"{kind}{idx}",
                    fix=f"give every {kind} the same synced variables"))
                continue
            for (n_a, e_a), (n_b, e_b) in zip(base, seq):
                if e_a != e_b:
                    diags.append(diag(
                        "collectives/stage-collective-mismatch",
                        Severity.ERROR,
                        f"{kind} {idx} syncs {n_b!r} as {e_b} but "
                        f"{kind} {base_idx} syncs {n_a!r} as {e_a}: "
                        "shards would issue different collective "
                        "sequences (deadlock under manual scheduling)",
                        location=f"{kind}{idx}",
                        fix="use one synchronizer/compressor/grouping "
                            f"config across all {kind}s"))
                    break
    return diags


def _check_stacked(ctx: AnalysisContext) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for flag, axis_name, dim_of in (
            ("pipeline", MESH_AXIS_PIPE, lambda v: 0),
            ("expert", MESH_AXIS_EXPERT,
             lambda v: 1 if v.pipeline else 0)):
        stacked = [p.var for p in ctx.plans.values()
                   if getattr(p.var, flag) and p.var.shape]
        sizes = {v.shape[dim_of(v)] for v in stacked
                 if len(v.shape) > dim_of(v)}
        if len(sizes) > 1:
            diags.append(diag(
                "collectives/stage-stack-heterogeneous", Severity.WARN,
                f"{flag}-stacked variables disagree on the stack size "
                f"({sorted(sizes)}): only interleaved virtual stages "
                "legitimately multiply it — check the stacking",
                location=axis_name,
                fix=f"stack every {flag} variable to the same leading "
                    "size (x virtual-stage factor)"))
        size = int(ctx.axes.get(axis_name, 1))
        if size > 1 and not stacked and axis_name not in {
                a for p in ctx.plans.values()
                for a in p.placement.values()}:
            diags.append(diag(
                "collectives/unused-parallel-axis", Severity.WARN,
                f"mesh carries a {axis_name!r} axis of size {size} but no "
                f"variable is {flag}-stacked or sharded over it: those "
                "devices replicate all work",
                location=axis_name,
                fix=f"flag the stacked variables via {flag}_vars=, or "
                    f"drop the {axis_name!r} axis"))
    return diags


def _check_staleness(ctx: AnalysisContext) -> List[Diagnostic]:
    ps = [p for p in ctx.plans.values() if p.sync_kind == "PS"]
    stale = [p for p in ps if p.staleness > 0]
    if stale and len(stale) != len(ps):
        return [diag(
            "collectives/staleness-mixed", Severity.WARN,
            f"{len(stale)} of {len(ps)} PS plans use bounded staleness: "
            "stale and fresh gradients interleave on one update schedule",
            var=stale[0].var.name,
            fix="use one staleness bound for all PS variables")]
    return []


@register_pass("collectives")
def run(ctx: AnalysisContext) -> List[Diagnostic]:
    diags = _check_named_groups(ctx)
    diags += _check_stacked(ctx)
    diags += _check_staleness(ctx)
    return diags
