"""Collective-schedule consistency pass: a static deadlock lint for
pipeline and MoE plans.

SPMD programs cannot deadlock on strategy choices — every device runs
the same program.  The hazard lives exactly where this framework leaves
SPMD: the ``shard_map``-manual pipeline schedules (``parallel/pipeline``,
``pipeline_1f1b``) and hand-laid per-stage parameter groups, where each
stage's devices issue their own collective sequence.  If stage 0's
variables all-reduce through a compressor while stage 1's do a plain
psum, or one stage fuses its group into a single concat-and-pmean while
another issues per-variable reductions, the stages disagree on the
*number and order* of collectives — the classic SPMD hang.

The pass used to reconstruct per-stage collective sequences from a
lossy plan tuple; it now consumes the **sync-schedule IR**
(``kernel/synchronization/schedule_ir.py``, shared with the runtime
lowerings and the ``schedule`` verifier pass), whose legs carry the
bucketed collective schedule the runtime will actually issue — with
microbatch slots, ring hop chains, and per-bucket algorithms — so the
cross-stage comparison is exact instead of heuristic.  Stage identity
comes from two sources:

* **stacked** parameters (``pipeline_vars``/``expert_vars``): one
  variable spans all stages, so its collective is uniform by
  construction — only the stack shapes are checked for agreement;
* **named** per-stage parameter groups — a path component matching
  ``stage<k>`` / ``expert<k>`` (e.g. ``stage0/attn/kernel``) — the
  layout of hand-built non-stacked pipelines, where the lint has real
  teeth.  Stage-tagged IR legs (per-stage buckets and per-variable
  fallbacks) must form identical ordered sequences per microbatch
  slot; a bucket spanning every stage is uniform by construction.

Rules (docs/analysis.md):

* ``collectives/stage-collective-mismatch`` (ERROR) — per-stage groups
  issue different ordered collective sequences (length, entry, or
  microbatch slot) — the IR-level ``schedule/collective-mismatch``
  check surfaced under this pass's established rule id.
* ``collectives/stage-stack-heterogeneous`` (WARN) — stacked pipeline
  (or expert) variables disagree on the stage/expert stack size.
* ``collectives/unused-parallel-axis`` (WARN) — the mesh carries a
  pipe/expert axis of size > 1 but no variable uses it.
* ``collectives/staleness-mixed`` (WARN) — some-but-not-all PS plans use
  bounded staleness: stale and fresh gradients interleave on one update
  schedule (legal, rarely intended).
"""
from __future__ import annotations

import re
from typing import List

from autodist_tpu.analysis.analyzer import (
    AnalysisContext,
    register_pass,
)
from autodist_tpu.analysis.diagnostics import Diagnostic, Severity, diag
from autodist_tpu.const import MESH_AXIS_EXPERT, MESH_AXIS_PIPE


def _check_named_groups(ctx: AnalysisContext) -> List[Diagnostic]:
    """Exact cross-stage deadlock check over the shared schedule IR:
    the verifier's ``schedule/collective-mismatch`` violations surface
    here under this pass's established rule id (the IR is built once
    and cached on the context — see ``analysis.schedule.ir_for``)."""
    from autodist_tpu.analysis.schedule import ir_for
    from autodist_tpu.kernel.synchronization import schedule_ir as sir

    ir = ir_for(ctx)
    if ir is None:
        return []
    diags: List[Diagnostic] = []
    for v in sir.verify(ir):
        if v.rule != sir.RULE_COLLECTIVE_MISMATCH:
            continue
        kind = re.match(r"[a-z]+", v.location or "stage").group(0)
        diags.append(diag(
            "collectives/stage-collective-mismatch", Severity.ERROR,
            v.message, location=v.location,
            fix="use one synchronizer/compressor/grouping/overlap "
                f"config across all {kind}s"))
    return diags


def _check_stacked(ctx: AnalysisContext) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    for flag, axis_name, dim_of in (
            ("pipeline", MESH_AXIS_PIPE, lambda v: 0),
            ("expert", MESH_AXIS_EXPERT,
             lambda v: 1 if v.pipeline else 0)):
        stacked = [p.var for p in ctx.plans.values()
                   if getattr(p.var, flag) and p.var.shape]
        sizes = {v.shape[dim_of(v)] for v in stacked
                 if len(v.shape) > dim_of(v)}
        if len(sizes) > 1:
            diags.append(diag(
                "collectives/stage-stack-heterogeneous", Severity.WARN,
                f"{flag}-stacked variables disagree on the stack size "
                f"({sorted(sizes)}): only interleaved virtual stages "
                "legitimately multiply it — check the stacking",
                location=axis_name,
                fix=f"stack every {flag} variable to the same leading "
                    "size (x virtual-stage factor)"))
        size = int(ctx.axes.get(axis_name, 1))
        if size > 1 and not stacked and axis_name not in {
                a for p in ctx.plans.values()
                for a in p.placement.values()}:
            diags.append(diag(
                "collectives/unused-parallel-axis", Severity.WARN,
                f"mesh carries a {axis_name!r} axis of size {size} but no "
                f"variable is {flag}-stacked or sharded over it: those "
                "devices replicate all work",
                location=axis_name,
                fix=f"flag the stacked variables via {flag}_vars=, or "
                    f"drop the {axis_name!r} axis"))
    return diags


def _check_staleness(ctx: AnalysisContext) -> List[Diagnostic]:
    ps = [p for p in ctx.plans.values() if p.sync_kind == "PS"]
    stale = [p for p in ps if p.staleness > 0]
    if stale and len(stale) != len(ps):
        return [diag(
            "collectives/staleness-mixed", Severity.WARN,
            f"{len(stale)} of {len(ps)} PS plans use bounded staleness: "
            "stale and fresh gradients interleave on one update schedule",
            var=stale[0].var.name,
            fix="use one staleness bound for all PS variables")]
    return []


@register_pass("collectives")
def run(ctx: AnalysisContext) -> List[Diagnostic]:
    diags = _check_named_groups(ctx)
    diags += _check_stacked(ctx)
    diags += _check_staleness(ctx)
    return diags
