"""Static HBM-footprint pass: will this plan fit per-device memory?

An OOM-by-construction plan (replicated optimizer state on a model that
only fits sharded, a compressor whose error-feedback residuals double
gradient memory, no remat on a long sequence) surfaces today as an XLA
allocation error minutes into compilation.  Everything in that sum is
statically known: parameter and optimizer-state bytes come from the
catalog and ``jax.eval_shape`` over the captured optimizer (dtype-aware,
so ``ops/opt_state_dtype.cast_opt_state`` bf16 moments are counted at 2
bytes), per-device denominators from the plan placements, compressor
state from each compressor's own ``init_state`` probed abstractly, and
activations from the batch shapes with a remat-aware multiplier.

Rules (docs/analysis.md):

* ``memory/hbm-breakdown`` (INFO) — always emitted: the per-device sum
  ``params + optimizer + gradients + sync-state + activations`` with
  each term listed.
* ``memory/watermark`` (INFO) — when the plan lowers to a schedule IR:
  the **liveness-based watermark** (``analysis/dataflow.py``) — walk
  the legs in a verified topological order, open each transient
  buffer (``grad:``/``red:``/``sync:``) at its first write and close
  it at its last read (donation closes early), stacked on the static
  base ``params + optimizer + activations``.  Reports per-device peak
  bytes, the leg at the peak, and per-microbatch-slot peaks.
* ``memory/watermark-exceeds-hbm`` (ERROR) — the watermark peak
  exceeds the per-device budget (``analyze(budget_bytes=...)``, or the
  resource spec's ``hbm_gb`` yaml key).  This replaces the coarse-sum
  budget comparison whenever a schedule IR exists: the schedule's
  actual liveness (gradient and reduce buffers live simultaneously,
  pipelined slots, donation) is what the device allocates, not the
  flat whole-step sum.
* ``memory/watermark-near-hbm`` (WARN) — the watermark peak exceeds
  90% of the budget.
* ``memory/hbm-over-budget`` (ERROR) — no schedule IR (no synced
  trainables): the coarse sum exceeds the per-device budget.
* ``memory/hbm-near-budget`` (WARN) — no schedule IR: the coarse sum
  exceeds 90% of the budget.
* ``memory/zero1-unused`` (WARN) — the footprint is within 10% of the
  budget (or over it), the mesh has a data axis, and AllReduce plans
  keep replicated optimizer state that ZeRO-1 (``sync=
  "reduce_scatter"`` / the ``Zero1`` builder) could legally shard 1/d —
  emitted with the estimated per-device saving.

Optimizer state under ZeRO-1 plans is counted at ``state_bytes /
data-axis size``: the explicit path carries those slots as flat bucket
shards, one 1/d slice per device (arXiv:2004.13336).

The activation term is a deliberate coarse bound — ``multiplier ×
per-device batch bytes``, with the multiplier shrunk under remat
(``full`` 2×, ``dots``/``dots_no_batch`` 4×, none 8×) — and is skipped
(with a note) when no batch shapes are provided.  The other terms are
exact up to XLA temporaries.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from autodist_tpu.analysis.analyzer import (
    AnalysisContext,
    PlanLite,
    register_pass,
)
from autodist_tpu.analysis.diagnostics import Diagnostic, Severity, diag

#: activation-estimate multipliers over per-device batch bytes, by remat
#: policy (None = no remat).  Coarse by design; documented in
#: docs/analysis.md.
ACTIVATION_MULTIPLIERS = {None: 8.0, "none": 8.0, "full": 2.0,
                          "dots": 4.0, "dots_no_batch": 4.0}

_MiB = float(1 << 20)


def _mib(x: float) -> str:
    return f"{x / _MiB:.1f} MiB"


def _param_and_grad_bytes(ctx: AnalysisContext) -> Dict[str, float]:
    params = grads = 0.0
    for plan in ctx.plans.values():
        b = plan.param_bytes_per_device(ctx.axes)
        params += b
        if plan.var.trainable:
            grads += b
    return {"params": params, "gradients": grads}


def _opt_state_bytes(ctx: AnalysisContext) -> Optional[float]:
    """Exact per-device optimizer-state bytes via ``eval_shape`` over the
    captured optimizer (None when no optimizer was captured)."""
    gi = ctx.graph_item
    if gi.optimizer is None or gi.params is None:
        return None
    import jax
    import numpy as np

    from autodist_tpu.graph_item import path_name
    from autodist_tpu.kernel import sharding_utils as su

    try:
        opt_shapes = jax.eval_shape(gi.frozen_aware_optimizer().init,
                                    gi.params)
    except Exception:  # pragma: no cover - exotic optimizers
        return None
    # params-shaped tree of variable names, projected onto the opt state:
    # every param-shaped block (mu/nu/...) resolves each leaf to its var.
    name_tree = jax.tree_util.tree_map_with_path(
        lambda p, _: path_name(p), gi.params)
    mapped = su.opt_spec_tree(opt_shapes, gi.params, name_tree, default="")
    total = 0.0
    d = max(ctx.data_axis_size, 1)
    for leaf, name in zip(jax.tree_util.tree_leaves(opt_shapes),
                          jax.tree_util.tree_leaves(mapped)):
        size = float(np.prod(tuple(leaf.shape) or (1,)))
        bytes_ = size * np.dtype(leaf.dtype).itemsize
        plan = ctx.plans.get(name) if name else None
        if plan is not None:
            logical = float(np.prod(plan.var.shape or (1,)))
            phys = float(np.prod(plan.physical_shape() or (1,)))
            ratio = phys / logical if logical else 1.0
            denom = plan.opt_denominator(ctx.axes)
            if getattr(plan, "zero1", False):
                # Weight-update sharding (sync="reduce_scatter"): the
                # explicit path carries this var's slots as flat bucket
                # shards, 1/d per device (the placement dict cannot
                # express a flat sharding, so it is accounted here).
                denom = max(denom, 1) * d
            bytes_ = bytes_ * ratio / denom
        total += bytes_
    return total


def _sync_state_bytes(ctx: AnalysisContext) -> float:
    """Compressor (error-feedback / PowerSGD / int8 residual) state per
    device, probed through each compressor's own ``init_state`` so the
    estimate cannot drift from the implementation."""
    import jax
    import numpy as np

    from autodist_tpu.const import MESH_AXIS_DATA
    from autodist_tpu.kernel.synchronization.compressor import get_compressor

    total = 0.0
    for plan in ctx.plans.values():
        if plan.sync_kind != "AllReduce" or \
                (plan.compressor or "NoneCompressor") == "NoneCompressor":
            continue
        shape = list(plan.var.shape)
        # Supported per-shard state layouts keep the shard shape; every
        # fallback case replicates (explicit_sync module docstring).
        if (len(plan.placement) == 1 and plan.pad is None):
            (dim, axis_name), = plan.placement.items()
            n = int(ctx.axes.get(axis_name, 1))
            if axis_name != MESH_AXIS_DATA and n > 1 \
                    and shape[dim] % n == 0:
                shape[dim] //= n
        try:
            comp = get_compressor(plan.compressor)
        except ValueError:
            continue  # the precision pass reports unknown compressors
        probe = jax.eval_shape(
            comp.init_state,
            jax.ShapeDtypeStruct(tuple(shape), plan.var.dtype))
        for leaf in jax.tree_util.tree_leaves(probe):
            total += float(np.prod(tuple(leaf.shape) or (1,))) \
                * np.dtype(leaf.dtype).itemsize
    return total


def _activation_bytes(ctx: AnalysisContext) -> Optional[float]:
    if ctx.batch is None:
        return None
    import jax
    import numpy as np

    batch_bytes = 0.0
    for leaf in jax.tree_util.tree_leaves(ctx.batch):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        dtype = getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
        batch_bytes += float(np.prod(shape or (1,))) * np.dtype(dtype).itemsize
    d = max(ctx.data_axis_size, 1)
    mult = ACTIVATION_MULTIPLIERS.get(
        ctx.graph_item.remat, ACTIVATION_MULTIPLIERS[None])
    return mult * batch_bytes / d


@register_pass("memory")
def run(ctx: AnalysisContext) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    terms = _param_and_grad_bytes(ctx)
    opt = _opt_state_bytes(ctx)
    sync = _sync_state_bytes(ctx)
    act = _activation_bytes(ctx)

    total = terms["params"] + terms["gradients"] + sync
    parts = [f"params {_mib(terms['params'])}",
             f"gradients {_mib(terms['gradients'])}"]
    if opt is None:
        parts.append("optimizer ? (no optimizer captured)")
    else:
        total += opt
        parts.append(f"optimizer {_mib(opt)}")
    parts.append(f"sync-state {_mib(sync)}")
    if act is None:
        parts.append("activations ? (pass batch= for the estimate)")
    else:
        total += act
        remat = ctx.graph_item.remat or "none"
        parts.append(f"activations ~{_mib(act)} (remat={remat})")

    budget = ctx.budget_bytes
    budget_note = f"; budget {_mib(budget)}" if budget else ""
    diags.append(diag(
        "memory/hbm-breakdown", Severity.INFO,
        f"per-device HBM ≈ {_mib(total)} = " + " + ".join(parts)
        + budget_note))

    # Liveness watermark over the schedule IR (analysis/dataflow.py):
    # the static base (params + optimizer + activations) plus the
    # schedule's transient buffers walked leg-by-leg.  Gradients and
    # sync state are NOT in the base — they are the grad:/red:/sync:
    # buffers whose live intervals the simulator opens and closes.
    base = terms["params"] + (opt or 0.0) + (act or 0.0)
    wm = _watermark(ctx, base)
    if wm is not None:
        diags.append(diag(
            "memory/watermark", Severity.INFO,
            f"schedule liveness watermark: {wm.summary()}"
            + budget_note, location=wm.peak_leg))

    if budget:
        fix_over = ("shard more state (PS/weight-update sharding or "
                    "ZeRO-1 sync='reduce_scatter'), cast optimizer "
                    "moments to bf16 (cast_opt_state), enable remat, or "
                    "shrink the per-device batch")
        fix_near = "leave headroom: shard or remat before scaling up"
        if wm is not None:
            if wm.peak_bytes > budget:
                diags.append(diag(
                    "memory/watermark-exceeds-hbm", Severity.ERROR,
                    f"schedule watermark peak ≈ {_mib(wm.peak_bytes)} at "
                    f"leg {wm.peak_leg!r} exceeds the {_mib(budget)} "
                    "budget (liveness-exact: the device really allocates "
                    "this much while that leg runs)",
                    location=wm.peak_leg, fix=fix_over))
            elif wm.peak_bytes > 0.9 * budget:
                diags.append(diag(
                    "memory/watermark-near-hbm", Severity.WARN,
                    f"schedule watermark peak ≈ {_mib(wm.peak_bytes)} at "
                    f"leg {wm.peak_leg!r} is within 10% of the "
                    f"{_mib(budget)} budget (XLA temporaries may tip it "
                    "over)", location=wm.peak_leg, fix=fix_near))
        elif total > budget:
            diags.append(diag(
                "memory/hbm-over-budget", Severity.ERROR,
                f"per-device footprint ≈ {_mib(total)} exceeds the "
                f"{_mib(budget)} budget", fix=fix_over))
        elif total > 0.9 * budget:
            diags.append(diag(
                "memory/hbm-near-budget", Severity.WARN,
                f"per-device footprint ≈ {_mib(total)} is within 10% of "
                f"the {_mib(budget)} budget (XLA temporaries may tip it "
                "over)", fix=fix_near))
        watermark_total = wm.peak_bytes if wm is not None else total
        if watermark_total > 0.9 * budget and opt is not None:
            diags += _zero1_unused(ctx, opt)
    return diags


def _watermark(ctx: AnalysisContext, base_bytes: float):
    """The liveness watermark of the schedule IR this plan lowers to
    (None when the plan has no synced trainables, the IR cannot be
    built, or its dep graph is unexecutable — the schedule pass owns
    those ERRORs)."""
    try:
        from autodist_tpu.analysis.schedule import ir_for
        ir = ir_for(ctx)
    except Exception:  # pragma: no cover - projection failure
        return None
    if ir is None:
        return None
    from autodist_tpu.analysis import dataflow
    return dataflow.watermark(ir, base_bytes=int(base_bytes))


def _zero1_unused(ctx: AnalysisContext, opt_actual: float
                  ) -> List[Diagnostic]:
    """WARN when the HBM pass is within 10% of budget while AllReduce
    plans keep replicated optimizer state that ZeRO-1 could legally
    shard (eligibility via the runtime's own bucket rule)."""
    from autodist_tpu.kernel.synchronization.bucketing import (
        bucket_drop_reason,
    )

    d = max(ctx.data_axis_size, 1)
    if d <= 1:
        return []
    eligible = [
        p for p in ctx.plans.values()
        if p.sync_kind == "AllReduce" and p.var.trainable
        and not getattr(p, "zero1", False)
        and bucket_drop_reason(sorted(p.placement.items()),
                               p.pad is not None,
                               p.compressor) is None]
    if not eligible:
        return []
    # Exact saving: re-run the eval_shape accounting with the eligible
    # plans hypothetically sharded (restored afterwards).
    for p in eligible:
        p.zero1 = True
    try:
        opt_sharded = _opt_state_bytes(ctx)
    finally:
        for p in eligible:
            p.zero1 = False
    if opt_sharded is None:
        return []
    saving = opt_actual - opt_sharded
    if saving <= 0:
        return []
    return [diag(
        "memory/zero1-unused", Severity.WARN,
        f"{len(eligible)} AllReduce variable(s) replicate optimizer "
        f"state that ZeRO-1 weight-update sharding could legally cut to "
        f"1/{d} per device (≈{_mib(saving)} saved) while the footprint "
        "is within 10% of the HBM budget",
        fix="use the Zero1 strategy builder or sync='reduce_scatter' "
            "on the AllReduce config")]
