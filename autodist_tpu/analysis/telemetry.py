"""Telemetry pass: is the cost model still telling the truth?

The analyzer's other passes reason about what a strategy WILL do; this
one closes the loop with what a run actually DID.  Feed
``analyze(..., telemetry=...)`` a measurement summary — most usefully
:func:`autodist_tpu.telemetry.calibration.predicted_vs_measured` over a
recorded run's StepRecords — and the pass checks the analytic cost
model's step-time prediction against the measurement.  Inert without
provenance (the ``elastic`` pass pattern): a plain pre-flight run never
sees these rules.

Rules (docs/observability.md):

* ``telemetry/model-drift`` (WARN) — measured step time diverges from
  the model's prediction by more than
  :data:`~autodist_tpu.telemetry.calibration.DRIFT_THRESHOLD` in either
  direction.  The reason string is the SHARED pure rule
  :func:`~autodist_tpu.telemetry.calibration.model_drift_reason` (the
  ``bucket_drop_reason`` pattern), so the lint, the CLI report, and any
  runtime check can never disagree about what counts as drift.  An
  AutoStrategy search ranked by a drifted model picks wrong — the fix
  is ``telemetry.calibration.fit_constants`` on the run's records.
* ``telemetry/no-measurement`` (INFO) — telemetry provenance was passed
  but holds no usable measured/predicted pair (e.g. a run recorded with
  the cost predictor unavailable); the drift check could not run.
* ``telemetry/leg-drift`` (WARN) — one leg KIND's measured time (from
  the schedule-aware profiler's LegSamples) diverges from the
  leg-priced prediction beyond
  :data:`~autodist_tpu.telemetry.calibration.LEG_DRIFT_THRESHOLD`.
  Shared pure rule
  :func:`~autodist_tpu.telemetry.calibration.leg_drift_reason` — the
  CLI compare report prints the identical string.  Whole-step drift
  says "something is off"; leg drift says WHICH leg kind.
* ``telemetry/straggler`` (WARN) — the slowest host's median step time
  exceeds
  :data:`~autodist_tpu.telemetry.calibration.STRAGGLER_THRESHOLD` x
  the fastest host's.  Shared pure rule
  :func:`~autodist_tpu.telemetry.calibration.straggler_reason` (the
  cross-host aggregator surfaces the same verdict as a gauge).

``telemetry`` provenance dict keys: ``measured_step_time_s``,
``predicted_step_time_s`` (both seconds; the
``predicted_vs_measured()`` output is accepted directly), optional
``threshold`` override; ``leg_kinds`` (``{kind: {"measured_s": ...,
"predicted_s": ...}}`` — per-leg-kind totals from profiler samples);
``per_host_step_time_s`` (``{host: median_s}``) or an
``aggregate_run()`` output's ``hosts`` mapping.
"""
from __future__ import annotations

from typing import List

from autodist_tpu.analysis.analyzer import AnalysisContext, register_pass
from autodist_tpu.analysis.diagnostics import Diagnostic, Severity, diag


@register_pass("telemetry")
def run(ctx: AnalysisContext) -> List[Diagnostic]:
    from autodist_tpu.telemetry.calibration import (
        DRIFT_THRESHOLD,
        LEG_DRIFT_THRESHOLD,
        STRAGGLER_THRESHOLD,
        leg_drift_reason,
        model_drift_reason,
        straggler_reason,
    )

    tel = getattr(ctx, "telemetry", None)
    if not tel:
        return []
    out: List[Diagnostic] = []
    measured = tel.get("measured_step_time_s")
    predicted = tel.get("predicted_step_time_s")
    if not measured or not predicted:
        out.append(diag(
            "telemetry/no-measurement", Severity.INFO,
            "telemetry provenance has no usable measured/predicted "
            "step-time pair — the model-drift check did not run",
            fix="record a run with telemetry enabled (StepRecords carry "
                "the cost model's prediction) and pass "
                "predicted_vs_measured() output"))
    else:
        threshold = float(tel.get("threshold", DRIFT_THRESHOLD))
        why = model_drift_reason(float(predicted), float(measured),
                                 threshold=threshold)
        if why is not None:
            out.append(diag(
                "telemetry/model-drift", Severity.WARN, why,
                fix="refit ICI_BANDWIDTH/COLLECTIVE_ALPHA via "
                    "telemetry.calibration.fit_constants(records) and "
                    "pass them to estimate_cost/AutoStrategy"))

    # Per-leg-kind drift: the profiler's measured legs vs the
    # leg-priced model — attributes WHICH kind the step drift hides in.
    leg_threshold = float(tel.get("leg_threshold", LEG_DRIFT_THRESHOLD))
    for kind, pair in sorted((tel.get("leg_kinds") or {}).items()):
        why = leg_drift_reason(kind, pair.get("measured_s"),
                               pair.get("predicted_s"),
                               threshold=leg_threshold)
        if why is not None:
            out.append(diag(
                "telemetry/leg-drift", Severity.WARN, why,
                location=kind,
                fix="refit per-kind constants via telemetry.calibration"
                    ".fit_leg_constants(samples) and persist "
                    "calibration.json where AUTODIST_CALIBRATION / "
                    "AUTODIST_TELEMETRY_DIR finds it"))

    # Straggler verdict: per-host medians from the provenance directly
    # or from an aggregate_run() output's hosts mapping.
    per_host = tel.get("per_host_step_time_s")
    if not per_host and isinstance(tel.get("hosts"), dict):
        per_host = {h: s.get("median_s")
                    for h, s in tel["hosts"].items()
                    if isinstance(s, dict)}
    why = straggler_reason(
        per_host, threshold=float(tel.get("straggler_threshold",
                                          STRAGGLER_THRESHOLD)))
    if why is not None:
        out.append(diag(
            "telemetry/straggler", Severity.WARN, why,
            fix="an SPMD step runs at the slowest host's pace — check "
                "that host's input pipeline, thermals, and background "
                "load before touching the strategy"))
    return out
