"""Telemetry pass: is the cost model still telling the truth?

The analyzer's other passes reason about what a strategy WILL do; this
one closes the loop with what a run actually DID.  Feed
``analyze(..., telemetry=...)`` a measurement summary — most usefully
:func:`autodist_tpu.telemetry.calibration.predicted_vs_measured` over a
recorded run's StepRecords — and the pass checks the analytic cost
model's step-time prediction against the measurement.  Inert without
provenance (the ``elastic`` pass pattern): a plain pre-flight run never
sees these rules.

Rules (docs/observability.md):

* ``telemetry/model-drift`` (WARN) — measured step time diverges from
  the model's prediction by more than
  :data:`~autodist_tpu.telemetry.calibration.DRIFT_THRESHOLD` in either
  direction.  The reason string is the SHARED pure rule
  :func:`~autodist_tpu.telemetry.calibration.model_drift_reason` (the
  ``bucket_drop_reason`` pattern), so the lint, the CLI report, and any
  runtime check can never disagree about what counts as drift.  An
  AutoStrategy search ranked by a drifted model picks wrong — the fix
  is ``telemetry.calibration.fit_constants`` on the run's records.
* ``telemetry/no-measurement`` (INFO) — telemetry provenance was passed
  but holds no usable measured/predicted pair (e.g. a run recorded with
  the cost predictor unavailable); the drift check could not run.

``telemetry`` provenance dict keys: ``measured_step_time_s``,
``predicted_step_time_s`` (both seconds; the
``predicted_vs_measured()`` output is accepted directly), optional
``threshold`` override.
"""
from __future__ import annotations

from typing import List

from autodist_tpu.analysis.analyzer import AnalysisContext, register_pass
from autodist_tpu.analysis.diagnostics import Diagnostic, Severity, diag


@register_pass("telemetry")
def run(ctx: AnalysisContext) -> List[Diagnostic]:
    from autodist_tpu.telemetry.calibration import (
        DRIFT_THRESHOLD,
        model_drift_reason,
    )

    tel = getattr(ctx, "telemetry", None)
    if not tel:
        return []
    measured = tel.get("measured_step_time_s")
    predicted = tel.get("predicted_step_time_s")
    if not measured or not predicted:
        return [diag(
            "telemetry/no-measurement", Severity.INFO,
            "telemetry provenance has no usable measured/predicted "
            "step-time pair — the model-drift check did not run",
            fix="record a run with telemetry enabled (StepRecords carry "
                "the cost model's prediction) and pass "
                "predicted_vs_measured() output")]
    threshold = float(tel.get("threshold", DRIFT_THRESHOLD))
    why = model_drift_reason(float(predicted), float(measured),
                             threshold=threshold)
    if why is None:
        return []
    return [diag(
        "telemetry/model-drift", Severity.WARN, why,
        fix="refit ICI_BANDWIDTH/COLLECTIVE_ALPHA via "
            "telemetry.calibration.fit_constants(records) and pass them "
            "to estimate_cost/AutoStrategy")]
