"""Schedule-verifier pass: model-check the sync-schedule IR statically.

The passes before this one lint the *plan projection* (PlanLite); this
pass lints the *program*: it constructs the same sync-schedule IR the
runtime lowers (``kernel/synchronization/schedule_ir.py`` — built from
the identical pure planner, so it cannot drift) and runs the static
schedule verifier over the leg partial order.  Rules surface under the
verifier's own ids (docs/schedule-ir.md):

* ``schedule/unknown-dep`` / ``schedule/dep-cycle`` (ERROR) — the leg
  partial order is malformed / unexecutable.
* ``schedule/ring-degenerate`` (ERROR) — ppermute ring hops over an
  axis of size <= 1.
* ``schedule/ring-hop-order`` (ERROR) — a ring hop chain is not the
  consecutive dep-ordered 1..n-1 sequence (swapped/duplicated/missing
  hops deadlock the ppermute).
* ``schedule/quantized-pipelined`` (ERROR) — a quantized bucket's
  collectives violate the pipelining contract: anything other than one
  end-of-step quantized collective, or (int8/fp8 under an explicit
  pipeline request) exactly one quantized collective per microbatch
  slot ``0..accum-1``.
* ``schedule/read-after-donate`` (ERROR) — a donated buffer (any
  namespace: ``sync:``/``param:``/``opt:``) with a read reachable
  after a write by a leg outside its read-modify-write chain.
* ``schedule/race-unordered-write`` / ``schedule/race-read-write``
  (ERROR) — the happens-before race detector
  (``analysis/dataflow.py``): two accesses of one buffer, at least one
  a write, with no ordering path in the dep closure.
* ``schedule/buffer-leak`` (WARN) — a transient buffer written but
  never read nor donated.
* ``schedule/reduction-order-divergence`` (WARN) — a low-precision or
  compressed bucket whose ring order diverges from the GSPMD psum
  tree.
* ``schedule/fused-inconsistent`` (ERROR) — fused-kernel legs
  (docs/kernels.md) that disagree with the IR's ``fused_kernels``
  record.
* ``schedule/fused-fallback`` (WARN) — a kernel requested via
  ``AUTODIST_FUSED_KERNELS`` that this program must lower unfused,
  with the runtime's exact drop-reason string
  (``ops.fused_kernels.fused_drop_reason``).
* ``schedule/elastic-resize`` (INFO) — under elastic provenance
  (``--elastic-from`` / ``preflight_elastic``): the exact leg-level
  delta of the resize (ring hop counts, leg totals), emitted after the
  NEW mesh's schedule verified cleanly.
* ``schedule/fingerprint-drift`` (WARN) — elastic provenance carries a
  recorded ``schedule_fingerprint``, the mesh did NOT change, and this
  program's IR hashes differently: the sync config itself drifted from
  what the checkpoint executed.
* ``schedule/hier-tier-order`` (ERROR) — a hierarchical bucket's
  ICI→DCN→ICI chain is malformed: cross-slice DCN leg missing (silent
  divergence — slices never exchange), out of order against its
  slice-local reduce-scatter/all-gather, duplicated, or hier legs on a
  topology where ``num_slices`` cannot tile the axis.
* ``moe/capacity-overflow`` (WARN) — the IR's MoE routing facts
  predict token drops: ``capacity_factor`` keeps fewer expert slots
  than balanced top-2 demand (the shared pure rule
  ``schedule_ir.moe_capacity_drop_fraction``, also warned by the
  runtime ``moe_ffn`` fallback path).

Cross-stage sequence violations (``schedule/collective-mismatch``) are
deliberately NOT emitted here — the ``collectives`` pass consumes the
same IR and reports them under its established rule id
``collectives/stage-collective-mismatch``.
"""
from __future__ import annotations

from typing import List, Optional

from autodist_tpu.analysis.analyzer import AnalysisContext, register_pass
from autodist_tpu.analysis.diagnostics import Diagnostic, Severity, diag


def ir_for(ctx: AnalysisContext):
    """The schedule IR for this context, built once and cached.

    A :class:`CompiledStrategy` run uses the runtime's own lowered plan
    facts; a plain Strategy run uses the legality pass's PlanLite
    projection — both feed ``schedule_ir.ir_from_facts``, which routes
    through the SAME ``assign_buckets``/``resolve_overlap`` planner the
    runtime executes."""
    cached = getattr(ctx, "schedule_ir", None)
    if cached is not None:
        return cached
    ir = _build_ir(ctx, ctx.axes)
    ctx.schedule_ir = ir
    return ir


def _build_ir(ctx: AnalysisContext, axes) -> Optional[object]:
    from autodist_tpu.kernel.synchronization import schedule_ir as sir

    facts = []
    guard = False
    for var in ctx.graph_item.info.variables:   # catalog order
        plan = ctx.plans.get(var.name)
        if plan is None or plan.sync_kind is None or not var.trainable:
            continue
        facts.append(sir.fact_from_planlite(var.name, plan))
        guard = guard or bool(getattr(plan, "guard", False))
    if not facts:
        return None
    accum = int(getattr(ctx.graph_item, "accum_steps", 1) or 1)
    active, drops = _resolve_fused(ctx, facts, guard)
    ctx.fused_drops = drops
    # MoE expert a2as: the same expert-flagged catalog projection the
    # runtime lowerings use (schedule_ir.moe_facts_from_vars), so the
    # analysis IR carries the dispatch/combine legs — and the capacity
    # transient — the runtime will execute.
    moe = sir.moe_facts_from_vars(
        ctx.graph_item.info.variables, axes=dict(axes),
        capacity_factor=getattr(ctx, "moe_capacity_factor", None),
        tokens_per_group=getattr(ctx, "moe_tokens_per_group", None))
    num_slices = int(getattr(ctx.resource_spec, "num_slices", 1) or 1)
    return sir.ir_from_facts(facts, axes=dict(axes), accum_steps=accum,
                             guard=guard, fused_kernels=active, moe=moe,
                             num_slices=num_slices)


def _resolve_fused(ctx: AnalysisContext, facts, guard: bool):
    """The SAME fused-kernel resolution the runtime applies
    (``ops.fused_kernels.resolve_fused``) so the analysis IR — and its
    fingerprint — matches what ``make_explicit_step`` lowers, and the
    drop reasons surface here as ``schedule/fused-fallback`` WARNs with
    the runtime's exact strings."""
    from autodist_tpu.kernel.synchronization import quant_ring
    from autodist_tpu.ops import fused_kernels as fk

    if not fk.requested_kernels():
        return (), []
    optimizer = getattr(ctx.graph_item, "optimizer", None)
    opt_fusable = getattr(optimizer, "fused_spec", None) is not None
    adam_shaped = True
    has_rs = any(f.sync_mode == "reduce_scatter" for f in facts)
    if opt_fusable and has_rs:
        try:
            import jax

            import jax.numpy as jnp
            probe = jax.eval_shape(
                optimizer.init,
                {"x": jax.ShapeDtypeStruct((8,), jnp.float32)})
            adam_shaped = fk.find_adam_state(probe) is not None
        except Exception:  # pragma: no cover - defensive
            adam_shaped = False
    return fk.resolve_fused(
        guard=guard, has_rs=has_rs,
        has_quant_ring=any(
            quant_ring.wire_format_of(f.compressor) is not None
            for f in facts),
        optimizer_fusable=opt_fusable, adam_state_shaped=adam_shaped,
        f32_buckets=all(str(f.dtype) == "float32" for f in facts
                        if f.sync_mode == "reduce_scatter"))


_SEVERITY = {"error": Severity.ERROR, "warn": Severity.WARN}

_FIXES = {
    "schedule/ring-hop-order":
        "restore the consecutive hop order the planner emits "
        "(overlap.ring_reduce_scatter)",
    "schedule/ring-degenerate":
        "grow the axis past 1 or drop the ring decomposition",
    "schedule/quantized-pipelined":
        "a quantized bucket owes ONE quantized collective per step, or "
        "— int8/fp8 under explicit overlap='pipeline'/'full' — exactly "
        "one per microbatch slot; restore one of those shapes",
    "schedule/read-after-donate":
        "undonate the sync state or move the read before the write",
    "schedule/dep-cycle": "break the dependency cycle",
    "schedule/unknown-dep": "fix the dangling dep edge",
    "schedule/reduction-order-divergence":
        "expect >1e-6 explicit-vs-GSPMD divergence for this bucket, or "
        "keep it f32/uncompressed",
    "schedule/fused-inconsistent":
        "rebuild the IR through build_schedule_ir(fused_kernels=...) so "
        "the fused legs and the program record agree",
    "schedule/race-unordered-write":
        "add a dep edge ordering the two writers (the builder chains "
        "every collective a stage issues — a hand-edited program must "
        "preserve that order)",
    "schedule/race-read-write":
        "order the reader against the writer with a dep edge",
    "schedule/buffer-leak":
        "consume the buffer (update/guard/gather) or drop the leg "
        "producing it",
    "moe/capacity-overflow":
        "raise capacity_factor to >= 2.0 (top-2 routing), shrink the "
        "expert count, or accept the predicted token drops knowingly",
    "schedule/hier-tier-order":
        "restore the per-bucket ICI->DCN->ICI chain the hierarchical "
        "builder emits (slice-local reduce-scatter, cross-slice "
        "exchange, slice-local all-gather, dep-ordered)",
}


@register_pass("schedule")
def run(ctx: AnalysisContext) -> List[Diagnostic]:
    from autodist_tpu.kernel.synchronization import schedule_ir as sir

    ir = ir_for(ctx)
    if ir is None:
        return []
    diags: List[Diagnostic] = []
    for v in sir.verify(ir):
        if v.rule == sir.RULE_COLLECTIVE_MISMATCH:
            continue   # reported by the collectives pass (same IR)
        diags.append(diag(
            v.rule, _SEVERITY.get(v.severity, Severity.WARN), v.message,
            location=v.location or v.leg, fix=_FIXES.get(v.rule)))
    for kernel, why in getattr(ctx, "fused_drops", ()) or ():
        diags.append(diag(
            "schedule/fused-fallback", Severity.WARN,
            f"requested fused kernel {kernel!r} falls back to the "
            f"unfused lowering: {why}",
            fix="fix the blocking config, or drop the kernel from "
                "AUTODIST_FUSED_KERNELS"))
    diags.extend(_elastic_recheck(ctx, ir))
    return diags


def _elastic_recheck(ctx: AnalysisContext, new_ir) -> List[Diagnostic]:
    """Elastic-resume provenance: re-verify is already done (the pass
    ran on the NEW mesh); here we report the exact leg-level delta the
    resize causes and flag schedule drift on a same-mesh resume."""
    info = getattr(ctx, "elastic", None)
    if not info:
        return []
    diags: List[Diagnostic] = []
    from_axes = {str(k): int(v)
                 for k, v in (info.get("from_axes") or {}).items()}
    axes_changed = any(
        from_axes.get(a, 1) != ctx.axes.get(a, 1)
        for a in set(from_axes) | set(ctx.axes)) if from_axes else False

    if from_axes and axes_changed:
        old_ir = _build_ir(ctx, from_axes)
        if old_ir is not None:
            from autodist_tpu.kernel.synchronization import schedule_ir \
                as sir

            def hops(ir):
                return sum(1 for l in ir.legs
                           if l.kind == sir.LEG_PPERMUTE_HOP)
            diags.append(diag(
                "schedule/elastic-resize", Severity.INFO,
                f"resize re-verified exactly: schedule "
                f"{old_ir.fingerprint()} -> {new_ir.fingerprint()}, "
                f"{len(old_ir.legs)} -> {len(new_ir.legs)} leg(s), "
                f"{hops(old_ir)} -> {hops(new_ir)} ring hop(s); the new "
                "mesh's full leg order passed the schedule verifier",
                location="->".join(
                    f"{k}={v}" for k, v in sorted(from_axes.items()))))

    recorded = info.get("schedule_fingerprint")
    if recorded and not axes_changed \
            and recorded != new_ir.fingerprint():
        diags.append(diag(
            "schedule/fingerprint-drift", Severity.WARN,
            f"checkpoint recorded sync schedule {recorded} but this "
            f"program plans {new_ir.fingerprint()} on the SAME mesh: "
            "the sync config (bucket_bytes / overlap / compressor / "
            "guard) drifted from what the checkpoint executed",
            fix="resume with the writer's sync config, or accept the "
                "schedule change knowingly"))
    return diags
