"""Sync-coverage pass: every trainable variable has exactly one live
sync rule; nothing is dead, shadowed, or syncing frozen state.

The compiler is forgiving here by design — it prunes dead nodes with a
debug log, lets a later duplicate silently shadow an earlier one, and
backfills untouched trainables with replicate+psum.  Pre-flight is where
forgiveness becomes a bug: a strategy that *meant* to cover a variable
and missed (renamed layer, typo'd pattern) trains that variable with the
default plan and nobody notices.  Rules (docs/analysis.md):

* ``sync/unsynced-trainable`` (ERROR) — a trainable variable with no
  strategy node at all (the compiler would backfill replicate+psum).
* ``sync/missing-synchronizer`` (ERROR) — a node without a synchronizer
  (the compiler raises mid-build).
* ``sync/shadowed-node`` (ERROR) — two nodes for one variable; the
  compiler silently keeps the LAST.
* ``sync/dead-node`` (WARN) — a node naming a variable the program does
  not have (pruned silently).
* ``sync/frozen-var-synced`` (WARN) — a node naming an untrainable
  (frozen) variable: it gets zero updates and no optimizer state, so
  synchronizing it is dead weight.

Overlap-schedule rules (the ``overlap=`` knob, docs/overlap.md; reason
strings shared with the runtime via
``kernel.synchronization.overlap.overlap_drop_reason``, the
``bucket_drop_reason`` pattern):

* ``sync/overlap-unknown`` (ERROR) — ``overlap=`` value outside the
  mode vocabulary (the builders validate it; hand-built plans land
  here).
* ``sync/ring-degenerate`` (ERROR) — ring decomposition
  (``overlap="ring"``/``"full"``) requested while the data (reduction)
  axis has size 1: there is no ring to permute over, and the explicit
  ppermute lowering the request asks for cannot exist.
* ``sync/overlap-fallback`` (WARN) — an overlap schedule was requested
  (or ``"auto"`` had a win available) but this variable cannot join it:
  per-variable fallback path (PowerSGD / partitioned), a cast-based
  compressor blocking pipelined reduction (quantized-ring int8/fp8
  compressors DO pipeline under an explicit ``"pipeline"``/``"full"`` —
  one quantized collective per microbatch slot — and only fall back
  under ``"auto"``), or ``overlap="pipeline"`` with no microbatch loop
  (``accum_steps=1``).
"""
from __future__ import annotations

from typing import List

from autodist_tpu.analysis.analyzer import AnalysisContext, register_pass
from autodist_tpu.analysis.diagnostics import Diagnostic, Severity, diag


def _overlap_rules(ctx: AnalysisContext) -> List[Diagnostic]:
    from autodist_tpu.const import MESH_AXIS_DATA
    from autodist_tpu.kernel.synchronization import overlap as ov
    from autodist_tpu.kernel.synchronization import schedule_ir as sir

    diags: List[Diagnostic] = []
    d = ctx.data_axis_size
    accum = int(getattr(ctx.graph_item, "accum_steps", 1) or 1)
    for name, plan in ctx.plans.items():
        if plan.sync_kind != "AllReduce" or plan.synthesized:
            continue
        mode = getattr(plan, "overlap", "auto") or "auto"
        if mode not in ov.OVERLAP_MODES:
            diags.append(diag(
                "sync/overlap-unknown", Severity.ERROR,
                f"overlap={mode!r} is not a schedule mode; expected one "
                f"of {ov.OVERLAP_MODES}",
                var=name, fix="use auto, none, pipeline, ring, or full"))
            continue
        if mode in (ov.OVERLAP_RING, ov.OVERLAP_FULL) and d <= 1:
            diags.append(diag(
                "sync/ring-degenerate", Severity.ERROR,
                f"ring decomposition requested (overlap={mode!r}) but "
                f"the {MESH_AXIS_DATA!r} axis has size {d}: there is no "
                "ring to permute over — the requested lowering cannot "
                "exist on this mesh",
                var=name, location=f"{MESH_AXIS_DATA}={d}",
                fix="grow the data axis past 1 or drop the ring request"))
            continue
        # Routing projection shared with the schedule IR builder
        # (schedule_ir.plan_route) — one rule, no reconstruction here.
        bucketable, explicit = sir.plan_route(
            sir.fact_from_planlite(name, plan))
        why = ov.overlap_drop_reason(
            mode, accum_steps=accum, compressor=plan.compressor,
            bucketable=bucketable, explicit_path=explicit,
            dtype=plan.var.dtype)
        if why is not None:
            diags.append(diag(
                "sync/overlap-fallback", Severity.WARN,
                f"overlap schedule does not apply: {why}",
                var=name,
                fix="see docs/overlap.md for what each mode requires"))
    return diags


@register_pass("sync")
def run(ctx: AnalysisContext) -> List[Diagnostic]:
    diags: List[Diagnostic] = _overlap_rules(ctx)
    gi = ctx.graph_item
    known = {v.name: v for v in gi.info.variables}
    seen: dict = {}

    for node in ctx.strategy.node_config:
        name = node.var_name
        if name in seen:
            diags.append(diag(
                "sync/shadowed-node", Severity.ERROR,
                "duplicate strategy node: the compiler silently keeps the "
                "last one, shadowing the earlier config",
                var=name, fix="keep exactly one node per variable"))
            continue
        seen[name] = node
        var = known.get(name)
        if var is None:
            diags.append(diag(
                "sync/dead-node", Severity.WARN,
                "strategy node names a variable the program does not have "
                "(the compiler prunes it silently)",
                var=name, fix="remove the node or fix the variable name"))
            continue
        if not var.trainable:
            diags.append(diag(
                "sync/frozen-var-synced", Severity.WARN,
                "strategy node targets a frozen (untrainable) variable: "
                "it receives zero updates and no optimizer state, so the "
                "sync rule is dead weight",
                var=name, fix="drop the node or unfreeze the variable"))
            continue
        if node.synchronizer is None:
            diags.append(diag(
                "sync/missing-synchronizer", Severity.ERROR,
                "strategy node has no synchronizer; the compiler raises "
                "ValueError on it",
                var=name, fix="set a PS or AllReduce synchronizer config"))

    for name, var in known.items():
        if var.trainable and name not in seen:
            diags.append(diag(
                "sync/unsynced-trainable", Severity.ERROR,
                "trainable variable has no sync rule; the compiler would "
                "backfill replicate+psum, which may not be what the "
                "strategy intended",
                var=name,
                fix="add a node for it (or an explicit AllReduce default)"))
    return diags
