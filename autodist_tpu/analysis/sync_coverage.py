"""Sync-coverage pass: every trainable variable has exactly one live
sync rule; nothing is dead, shadowed, or syncing frozen state.

The compiler is forgiving here by design — it prunes dead nodes with a
debug log, lets a later duplicate silently shadow an earlier one, and
backfills untouched trainables with replicate+psum.  Pre-flight is where
forgiveness becomes a bug: a strategy that *meant* to cover a variable
and missed (renamed layer, typo'd pattern) trains that variable with the
default plan and nobody notices.  Rules (docs/analysis.md):

* ``sync/unsynced-trainable`` (ERROR) — a trainable variable with no
  strategy node at all (the compiler would backfill replicate+psum).
* ``sync/missing-synchronizer`` (ERROR) — a node without a synchronizer
  (the compiler raises mid-build).
* ``sync/shadowed-node`` (ERROR) — two nodes for one variable; the
  compiler silently keeps the LAST.
* ``sync/dead-node`` (WARN) — a node naming a variable the program does
  not have (pruned silently).
* ``sync/frozen-var-synced`` (WARN) — a node naming an untrainable
  (frozen) variable: it gets zero updates and no optimizer state, so
  synchronizing it is dead weight.
"""
from __future__ import annotations

from typing import List

from autodist_tpu.analysis.analyzer import AnalysisContext, register_pass
from autodist_tpu.analysis.diagnostics import Diagnostic, Severity, diag


@register_pass("sync")
def run(ctx: AnalysisContext) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    gi = ctx.graph_item
    known = {v.name: v for v in gi.info.variables}
    seen: dict = {}

    for node in ctx.strategy.node_config:
        name = node.var_name
        if name in seen:
            diags.append(diag(
                "sync/shadowed-node", Severity.ERROR,
                "duplicate strategy node: the compiler silently keeps the "
                "last one, shadowing the earlier config",
                var=name, fix="keep exactly one node per variable"))
            continue
        seen[name] = node
        var = known.get(name)
        if var is None:
            diags.append(diag(
                "sync/dead-node", Severity.WARN,
                "strategy node names a variable the program does not have "
                "(the compiler prunes it silently)",
                var=name, fix="remove the node or fix the variable name"))
            continue
        if not var.trainable:
            diags.append(diag(
                "sync/frozen-var-synced", Severity.WARN,
                "strategy node targets a frozen (untrainable) variable: "
                "it receives zero updates and no optimizer state, so the "
                "sync rule is dead weight",
                var=name, fix="drop the node or unfreeze the variable"))
            continue
        if node.synchronizer is None:
            diags.append(diag(
                "sync/missing-synchronizer", Severity.ERROR,
                "strategy node has no synchronizer; the compiler raises "
                "ValueError on it",
                var=name, fix="set a PS or AllReduce synchronizer config"))

    for name, var in known.items():
        if var.trainable and name not in seen:
            diags.append(diag(
                "sync/unsynced-trainable", Severity.ERROR,
                "trainable variable has no sync rule; the compiler would "
                "backfill replicate+psum, which may not be what the "
                "strategy intended",
                var=name,
                fix="add a node for it (or an explicit AllReduce default)"))
    return diags
