"""autodist_tpu.analysis — static strategy/sharding analysis ("shardlint").

A pre-flight pass pipeline over ``(Strategy | CompiledStrategy,
GraphItem, mesh axes, resource spec)`` that rejects bad distribution
plans in milliseconds with rule-tagged diagnostics, instead of minutes
into an XLA compile.  The passes: sharding legality, sync coverage,
static per-device HBM footprint, collective-schedule consistency
(pipeline/MoE deadlock lint, exact over the sync-schedule IR), the
static schedule verifier (docs/schedule-ir.md), precision lint, and
the provenance-gated elastic-resume and telemetry passes.  See
docs/analysis.md for every rule id and the severity semantics.

Entry points:

* :func:`analyze` — run the pipeline, get an :class:`AnalysisReport`.
* :func:`preflight` / :func:`preflight_session` — the ``validate=``
  hook bodies used by ``AutoDist.create_distributed_session`` and
  ``fit``: raise :class:`StrategyValidationError` on ERROR diagnostics,
  log WARNs once.
* ``python -m autodist_tpu.analysis <model> <strategy>`` — the CLI:
  prints a diagnostics table, exits nonzero on ERROR.
"""
from autodist_tpu.analysis.analyzer import (
    AnalysisContext,
    PASS_ORDER,
    PlanLite,
    analyze,
    log_report,
    preflight,
    preflight_session,
)
from autodist_tpu.analysis.diagnostics import (
    AnalysisReport,
    Diagnostic,
    Severity,
    StrategyValidationError,
)

__all__ = [
    "AnalysisContext", "AnalysisReport", "Diagnostic", "PASS_ORDER",
    "PlanLite", "Severity", "StrategyValidationError", "analyze",
    "log_report", "preflight", "preflight_session",
]
