"""Pod-scale sweep over the PURE cost/watermark model (``--simulate``).

``python -m autodist_tpu.analysis <model> <strategy> --simulate <spec>``
sweeps mesh shape x slice count x DCN bandwidth WITHOUT building a mesh,
tracing, or compiling: every point runs the same mesh-free pipeline the
strategy search uses — legality projection, ``ir_from_facts``, the
static schedule verifier, the liveness HBM watermark, and the
leg-priced ``estimate_ir_cost`` — so a 1024-chip topology prices in
seconds on a laptop.  Per point it reports, for each applicable sync
mode (``flat`` / ``hier`` / ``hier_int8``):

* predicted step time (calibrated when a ``calibration.json`` is
  discovered, the default clocks otherwise);
* exposed wire per network tier (``ici`` / ``dcn``) — the honest
  two-tier decomposition, flat data-axis collectives on a multi-slice
  pod booking as DCN-bound;
* the schedule's watermark HBM peak against the spec's budget — an
  over-budget point is PRUNED (reported with the watermark rule, and
  the CLI exits 1), exactly like the search's OOM gate;
* goodput under preemption (:mod:`autodist_tpu.telemetry.goodput`):
  a deterministic failure model — one preemption per ``mtbf_s`` of
  wall clock, each costing the :data:`~autodist_tpu.telemetry.goodput.
  RECOVERY_BUDGET_S` restart plus half a checkpoint interval of lost
  steps, with checkpoint stalls at their own cadence.

Points whose slice count cannot tile the device count are pruned with
the shared ``legality/slice-mismatch`` rule (``resource_spec.
slice_mismatch_reason`` — one rule string everywhere).

Everything here is numpy + stdlib; jax is never imported.
"""
from __future__ import annotations

import itertools
import json
import os
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

from autodist_tpu.const import MESH_AXIS_DATA
from autodist_tpu.resource_spec import (
    ResourceSpec,
    slice_mismatch_reason,
)

#: sweep sync modes: the flat single-tier lowering, the two-tier
#: ICI+DCN hierarchy, and the hierarchy with an int8 cross-slice wire.
MODE_FLAT = "flat"
MODE_HIER = "hier"
MODE_HIER_INT8 = "hier_int8"
SWEEP_MODES = (MODE_FLAT, MODE_HIER, MODE_HIER_INT8)

#: deterministic preemption model defaults (overridable per sweep).
DEFAULT_MTBF_S = 3600.0          # one preemption per hour of wall clock
DEFAULT_CKPT_INTERVAL_STEPS = 100
DEFAULT_CKPT_WRITE_S = 5.0       # synchronous persist stall per save


def parse_sweep_spec(spec: str) -> Dict[str, Any]:
    """Parse the ``--simulate`` argument: a JSON file path, or an
    inline ``key=value`` spec with ``;``-separated groups::

        mesh=data=1024;slices=1,2,4;dcn=12.5,25,100;stages=1,2,4

    Inline keys: ``mesh`` (repeatable, ``axis=size[,axis=size...]``),
    ``slices``, ``dcn`` (Gbit/s values), ``hbm`` (GiB), ``mtbf``,
    ``ckpt`` (interval steps), ``stages`` (MPMD pipeline stage counts —
    each ``S > 1`` point composes a :class:`~autodist_tpu.kernel.
    synchronization.schedule_ir.PipelineFact` into the program and
    reports 1F1B bubble fraction + DCN activation bytes), ``mb``
    (pipeline microbatches; default ``2*S``), ``act`` (per-boundary
    activation MiB; default 1).  JSON files carry the same content as
    ``{"meshes": [{"data": 1024}], "slices": [...], "dcn_gbps": [...],
    "hbm_gb": ..., "mtbf_s": ..., "ckpt_interval_steps": ...,
    "stages": [...], "microbatches": ..., "act_mib": ...}``."""
    if os.path.exists(spec):
        with open(spec, "r", encoding="utf-8") as f:
            cfg = json.load(f)
        if not isinstance(cfg, dict):
            raise ValueError(f"sweep JSON {spec!r} must be an object")
        return cfg
    cfg: Dict[str, Any] = {"meshes": []}
    for group in spec.split(";"):
        group = group.strip()
        if not group:
            continue
        if "=" not in group:
            raise ValueError(
                f"bad --simulate group {group!r}: use key=value "
                "(mesh=data=1024;slices=1,2,4;dcn=25,100)")
        key, val = group.split("=", 1)
        key = key.strip()
        if key == "mesh":
            axes: Dict[str, int] = {}
            for part in val.split(","):
                name, size = part.split("=", 1)
                axes[name.strip()] = int(size)
            cfg["meshes"].append(axes)
        elif key == "slices":
            cfg["slices"] = [int(x) for x in val.split(",") if x.strip()]
        elif key == "dcn":
            cfg["dcn_gbps"] = [float(x) for x in val.split(",")
                               if x.strip()]
        elif key == "hbm":
            cfg["hbm_gb"] = float(val)
        elif key == "mtbf":
            cfg["mtbf_s"] = float(val)
        elif key == "ckpt":
            cfg["ckpt_interval_steps"] = int(val)
        elif key == "stages":
            cfg["stages"] = [int(x) for x in val.split(",") if x.strip()]
        elif key == "mb":
            cfg["microbatches"] = int(val)
        elif key == "act":
            cfg["act_mib"] = float(val)
        else:
            raise ValueError(f"unknown --simulate key {key!r}")
    if not cfg["meshes"]:
        raise ValueError("--simulate spec names no mesh "
                         "(mesh=data=<chips>)")
    return cfg


def _fabricated_spec(axes: Dict[str, int], num_slices: int,
                     dcn_gbps: Optional[float],
                     hbm_gb: Optional[float]) -> ResourceSpec:
    """A single-node virtual spec sized to the swept mesh — the same
    fabrication the analysis CLI uses, plus the two-tier fields."""
    import math

    info: Dict[str, Any] = {
        "nodes": [{"address": "localhost",
                   "chips": math.prod(axes.values())}],
        "mesh": dict(axes),
    }
    if num_slices > 1:
        info["num_slices"] = int(num_slices)
    if dcn_gbps is not None:
        info["dcn_gbps"] = float(dcn_gbps)
    if hbm_gb is not None:
        info["hbm_gb"] = float(hbm_gb)
    return ResourceSpec(resource_info=info)


def goodput_under_preemption(step_time_s: float, *,
                             mtbf_s: float = DEFAULT_MTBF_S,
                             ckpt_interval_steps: int =
                             DEFAULT_CKPT_INTERVAL_STEPS,
                             ckpt_write_s: float = DEFAULT_CKPT_WRITE_S
                             ) -> Dict[str, Any]:
    """Deterministic goodput over one MTBF window of wall clock.

    One preemption per window costs the recovery budget (restart gap)
    plus, in expectation, half a checkpoint interval of re-trained
    steps; synchronous saves stall the loop every
    ``ckpt_interval_steps``.  Reuses :func:`telemetry.goodput.
    attempt_goodput` so the decomposition fields match what the
    telemetry CLI reports from real runs."""
    from autodist_tpu.telemetry.goodput import (
        RECOVERY_BUDGET_S,
        attempt_goodput,
    )

    step_time_s = max(float(step_time_s), 1e-12)
    wall = max(float(mtbf_s), step_time_s)
    rollback = RECOVERY_BUDGET_S \
        + 0.5 * float(ckpt_interval_steps) * step_time_s
    rollback = min(rollback, wall)
    # Amortized save cost: each step carries its share of the periodic
    # synchronous persist, so the step budget inside the window is
    # ``step + write/interval`` — exact in the long-window limit and
    # well-behaved when the write dwarfs the interval.
    per_step = step_time_s \
        + float(ckpt_write_s) / max(int(ckpt_interval_steps), 1)
    steps_in_window = int(max(wall - rollback, 0.0) / per_step)
    useful = steps_in_window * step_time_s
    stall = max(wall - rollback - useful, 0.0)
    return attempt_goodput(wall, useful, ckpt_stall_s=stall,
                           rollback_s=rollback, steps=steps_in_window)


def simulate_mode(graph_item, strategy, resource_spec: ResourceSpec,
                  axes: Dict[str, int], *, dcn_wire: Optional[str] = None,
                  constants=None, compute_time_s: float = 0.0,
                  mtbf_s: float = DEFAULT_MTBF_S,
                  ckpt_interval_steps: int = DEFAULT_CKPT_INTERVAL_STEPS,
                  pipeline=()) -> Dict[str, Any]:
    """Price ONE (point, sync-mode) cell through the search's own
    mesh-free pipeline; returns the cell dict (``pruned_by`` set when
    legality, the verifier, or the watermark killed it).  ``pipeline``
    composes MPMD :class:`~autodist_tpu.kernel.synchronization.
    schedule_ir.PipelineFact`\\ s into the program: the cell then runs
    with the pipeline's ``send_act``/``recv_act`` legs in the IR (same
    verifier, same watermark) and reports ``bubble_fraction`` plus the
    DCN activation bytes column."""
    from autodist_tpu.analysis import dataflow
    from autodist_tpu.analysis.search import facts_for_candidate
    from autodist_tpu.kernel.synchronization import schedule_ir as sir
    from autodist_tpu.strategy.cost_model import (
        DCN_BANDWIDTH,
        act_transport_bytes,
        estimate_ir_cost,
    )

    facts, priced_facts, guard, prune = facts_for_candidate(
        strategy, graph_item, axes, resource_spec=resource_spec)
    if prune is not None:
        return {"pruned_by": prune}
    num_slices = int(getattr(resource_spec, "num_slices", 1) or 1)
    accum = int(getattr(graph_item, "accum_steps", 1) or 1)
    pipeline = list(pipeline or ())
    for pf in pipeline:
        # A pipeline point IS a grad-accumulation point: one optimizer
        # step spans the schedule's microbatches.
        accum = max(accum, int(pf.num_microbatches))

    # The DCN wire format is the runtime's AUTODIST_DCN_WIRE knob; the
    # sweep pins it per mode so flat/hier/hier_int8 cells are
    # reproducible regardless of the caller's environment.
    prev = os.environ.get("AUTODIST_DCN_WIRE")
    os.environ["AUTODIST_DCN_WIRE"] = dcn_wire or ""
    try:
        ir = sir.ir_from_facts(facts, axes=dict(axes), accum_steps=accum,
                               guard=guard, num_slices=num_slices,
                               pipeline=pipeline)
    finally:
        if prev is None:
            os.environ.pop("AUTODIST_DCN_WIRE", None)
        else:
            os.environ["AUTODIST_DCN_WIRE"] = prev
    errs = sir.errors(sir.verify(ir))
    if errs:
        return {"fingerprint": ir.fingerprint(),
                "pruned_by": f"{errs[0].rule}: {errs[0].message}"}
    cell: Dict[str, Any] = {"fingerprint": ir.fingerprint()}
    wm = dataflow.watermark_for_facts(facts, ir, dict(axes))
    hbm = getattr(resource_spec, "hbm_bytes_per_chip", None)
    if wm is not None:
        cell["watermark_peak_bytes"] = int(wm.peak_bytes)
        cell["watermark_peak_leg"] = wm.peak_leg
        if hbm and wm.peak_bytes > hbm:
            cell["pruned_by"] = (
                f"{dataflow.RULE_WATERMARK_EXCEEDS}: watermark peak "
                f"{wm.peak_bytes / (1 << 30):.2f} GiB exceeds the "
                f"{hbm / (1 << 30):.2f} GiB per-chip HBM budget")
            return cell
    dcn_bw = getattr(resource_spec, "dcn_bytes_per_s", None) \
        or DCN_BANDWIDTH
    report = estimate_ir_cost(ir, constants=constants,
                              compute_time_s=compute_time_s,
                              dcn_bandwidth=dcn_bw)
    step_s = float(report.time_s)
    if ir.pipeline:
        total_act, exposed_act = act_transport_bytes(ir)
        cell["bubble_fraction"] = float(report.bubble_fraction)
        cell["dcn_act_bytes"] = {"total": float(total_act),
                                 "exposed": float(exposed_act)}
    cell.update({
        "predicted_step_s": step_s,
        "exposed_wire_by_tier": {k: float(v) for k, v in sorted(
            report.exposed_wire_by_tier.items())},
        "wire_by_tier": {k: float(v) for k, v in sorted(
            report.wire_by_tier.items())},
        "num_collectives": int(report.num_collectives),
        "goodput": goodput_under_preemption(
            step_s, mtbf_s=mtbf_s,
            ckpt_interval_steps=ckpt_interval_steps),
    })
    return cell


def run_sweep(graph_item,
              make_strategy: Callable[[ResourceSpec, bool], Any],
              config: Dict[str, Any], *,
              constants=None) -> Dict[str, Any]:
    """Run the full sweep; returns the machine-readable report.

    ``make_strategy(resource_spec, hier)`` builds the strategy for one
    point (``hier`` selects the two-tier variant; builders that cannot
    express it may raise TypeError, which skips the hier modes for the
    whole sweep).  ``config`` is :func:`parse_sweep_spec` output."""
    meshes: List[Dict[str, int]] = [
        {str(k): int(v) for k, v in m.items()}
        for m in (config.get("meshes") or [])]
    slices: List[int] = [int(s) for s in (config.get("slices") or [1])]
    dcn_list: List[Optional[float]] = [
        float(x) for x in (config.get("dcn_gbps") or [])] or [None]
    hbm_gb = config.get("hbm_gb")
    mtbf_s = float(config.get("mtbf_s", DEFAULT_MTBF_S))
    ckpt = int(config.get("ckpt_interval_steps",
                          DEFAULT_CKPT_INTERVAL_STEPS))
    compute_s = float(config.get("compute_time_s", 0.0))
    stages_list: List[int] = [int(x) for x in
                              (config.get("stages") or [1])]
    microbatches = int(config.get("microbatches", 0) or 0)
    act_mib = float(config.get("act_mib", 1.0))

    t0 = time.perf_counter()
    points: List[Dict[str, Any]] = []
    over_hbm = 0
    from autodist_tpu.kernel.synchronization import schedule_ir as sir

    hier_applies = sir.hier_applies

    for axes, s, dcn, st in itertools.product(meshes, slices, dcn_list,
                                              stages_list):
        point: Dict[str, Any] = {
            "mesh": dict(axes), "num_slices": int(s),
            "dcn_gbps": dcn, "stages": int(st),
        }
        points.append(point)
        import math
        chips = math.prod(axes.values())
        reason = slice_mismatch_reason(chips, s)
        if reason is not None:
            point["pruned_by"] = reason
            continue
        # The pipeline dimension prunes with the SAME rule string the
        # MPMD partitioner raises (pipeline/stage-mismatch).
        mb = microbatches if microbatches else 2 * max(int(st), 1)
        reason = sir.stage_mismatch_reason(st, mb)
        if reason is not None:
            point["pruned_by"] = reason
            continue
        pipe = [] if st <= 1 else [sir.PipelineFact(
            key="pipe", num_stages=int(st), num_microbatches=mb,
            act_nbytes=int(act_mib * (1 << 20)))]
        if pipe:
            point["microbatches"] = mb
        spec = _fabricated_spec(axes, s, dcn, hbm_gb)
        d = int(axes.get(MESH_AXIS_DATA, 1))
        modes: Dict[str, Dict[str, Any]] = {}
        point["modes"] = modes
        for mode in SWEEP_MODES:
            hier = mode != MODE_FLAT
            if hier and not hier_applies(d, s):
                continue
            try:
                strategy = make_strategy(spec, hier)
            except TypeError:
                # builder has no two-tier variant: flat cell only
                continue
            modes[mode] = simulate_mode(
                graph_item, strategy, spec, axes,
                dcn_wire="int8" if mode == MODE_HIER_INT8 else None,
                constants=constants, compute_time_s=compute_s,
                mtbf_s=mtbf_s, ckpt_interval_steps=ckpt,
                pipeline=pipe)
        priced = {m: c for m, c in modes.items()
                  if "predicted_step_s" in c}
        if priced:
            point["best_mode"] = min(
                priced.items(),
                key=lambda kv: (kv[1]["predicted_step_s"], kv[0]))[0]
            point["ranking"] = sorted(
                priced, key=lambda m: (priced[m]["predicted_step_s"], m))
        elif all("pruned_by" in c for c in modes.values()) and modes:
            point["pruned_by"] = next(iter(modes.values()))["pruned_by"]
        if any("watermark" in (c.get("pruned_by") or "")
               for c in modes.values()):
            over_hbm += 1

    return {
        "config": {"meshes": meshes, "slices": slices,
                   "dcn_gbps": dcn_list, "hbm_gb": hbm_gb,
                   "mtbf_s": mtbf_s, "ckpt_interval_steps": ckpt,
                   "stages": stages_list,
                   "microbatches": microbatches or None,
                   "act_mib": act_mib},
        "calibrated": constants is not None,
        "points": points,
        "n_points": len(points),
        "n_over_hbm": over_hbm,
        "wall_time_s": round(time.perf_counter() - t0, 3),
    }


def format_sweep_report(report: Dict[str, Any]) -> str:
    """Human rendering of :func:`run_sweep` (the CLI table)."""
    lines: List[str] = []
    lines.append(
        f"simulate sweep: {report['n_points']} point(s) in "
        f"{report['wall_time_s']:.2f} s"
        f"{' (calibrated)' if report.get('calibrated') else ''}"
        + (f", {report['n_over_hbm']} over HBM budget"
           if report.get("n_over_hbm") else ""))
    for p in report["points"]:
        mesh = ",".join(f"{k}={v}" for k, v in sorted(p["mesh"].items()))
        head = (f"[{mesh}] slices={p['num_slices']} "
                f"dcn={p['dcn_gbps'] if p['dcn_gbps'] is not None else '-'}"
                f" Gbit/s")
        if int(p.get("stages", 1) or 1) > 1:
            head += (f" stages={p['stages']}"
                     f" mb={p.get('microbatches', '-')}")
        if "pruned_by" in p and "modes" not in p:
            lines.append(f"  {head}: PRUNED ({p['pruned_by']})")
            continue
        lines.append(f"  {head}  best={p.get('best_mode', '-')}")
        for mode, c in sorted((p.get("modes") or {}).items()):
            if "pruned_by" in c:
                lines.append(f"    {mode:10s} PRUNED ({c['pruned_by']})")
                continue
            tiers = "  ".join(
                f"{t}={b / 1e6:.2f}MB"
                for t, b in c["exposed_wire_by_tier"].items())
            gp = c["goodput"].get("goodput_ratio")
            pipe = ""
            if "bubble_fraction" in c:
                act = c.get("dcn_act_bytes") or {}
                pipe = (f"  bubble {c['bubble_fraction']:.3f}"
                        f"  act dcn "
                        f"{act.get('exposed', 0.0) / 1e6:.2f}MB exposed"
                        f"/{act.get('total', 0.0) / 1e6:.2f}MB")
            lines.append(
                f"    {mode:10s} step {c['predicted_step_s'] * 1e3:9.3f}"
                f" ms  exposed {tiers or '-'}  "
                f"hbm {c.get('watermark_peak_bytes', 0) / (1 << 30):.2f}"
                f" GiB  goodput "
                f"{gp if gp is not None else '-'}{pipe}")
    return "\n".join(lines)
