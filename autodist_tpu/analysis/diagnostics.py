"""Diagnostic records for the static strategy analyzer ("shardlint").

A :class:`Diagnostic` is one finding of one rule: a stable rule id
(``"legality/indivisible-partition"``), a severity, the variable/axis it
anchors to, a human message, and a fix hint.  An :class:`AnalysisReport`
is the ordered list a full pass pipeline produced, with table rendering
for the CLI and ``raise_for_errors`` for the pre-flight hooks.

Severity semantics (docs/analysis.md):

* **ERROR** — the plan is wrong by construction: it will raise inside the
  compiler, produce a program that does not match the strategy's stated
  intent (silently-dropped partitions), deadlock a manual-collective
  schedule, or OOM before the first step.  Pre-flight (``validate=``)
  raises :class:`StrategyValidationError`.
* **WARN** — the plan runs but costs something the user probably did not
  intend (dead strategy nodes, compression fallbacks, precision risks).
  Pre-flight logs each once.
* **INFO** — advisory facts worth surfacing (pad-to-divisible coverage,
  the per-device HBM breakdown).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional


class Severity(enum.IntEnum):
    """Ordered so ``max()`` over a report gives the worst finding."""

    INFO = 0
    WARN = 1
    ERROR = 2


@dataclass
class Diagnostic:
    """One rule finding."""

    rule: str                      # stable id, "<pass>/<rule-name>"
    severity: Severity
    message: str
    var_name: str = ""             # variable (or "" for whole-plan findings)
    location: str = ""             # axis / dim / stage the finding anchors to
    fix_hint: str = ""

    def format(self) -> str:
        where = self.var_name or "<plan>"
        if self.location:
            where += f"[{self.location}]"
        out = f"{self.severity.name:5s} {self.rule:40s} {where}: {self.message}"
        if self.fix_hint:
            out += f"  (fix: {self.fix_hint})"
        return out

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity.name,
                "var_name": self.var_name, "location": self.location,
                "message": self.message, "fix_hint": self.fix_hint}


class StrategyValidationError(ValueError):
    """Raised by pre-flight validation when a plan has ERROR diagnostics.

    Carries the full :class:`AnalysisReport` so callers can render every
    finding, not just the first."""

    def __init__(self, report: "AnalysisReport"):
        self.report = report
        errors = report.errors
        lines = [d.format() for d in errors]
        super().__init__(
            f"strategy failed pre-flight analysis with {len(errors)} "
            "error(s):\n" + "\n".join(lines))


@dataclass
class AnalysisReport:
    """Ordered diagnostics from one analyzer run."""

    diagnostics: List[Diagnostic] = field(default_factory=list)

    def extend(self, diags) -> None:
        self.diagnostics.extend(diags)

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.ERROR]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.WARN]

    @property
    def infos(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == Severity.INFO]

    def has_errors(self) -> bool:
        return any(d.severity == Severity.ERROR for d in self.diagnostics)

    def by_rule(self, rule: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.rule == rule]

    def raise_for_errors(self) -> None:
        if self.has_errors():
            raise StrategyValidationError(self)

    def summary(self) -> str:
        return (f"{len(self.errors)} error(s), {len(self.warnings)} "
                f"warning(s), {len(self.infos)} info")

    def format_table(self, min_severity: Severity = Severity.INFO) -> str:
        """Fixed-width table, worst findings first (stable within a
        severity — pass order is the narrative order)."""
        rows = [d for d in self.diagnostics if d.severity >= min_severity]
        rows.sort(key=lambda d: -int(d.severity))
        if not rows:
            return "analysis: clean (no findings)"
        headers = ("SEV", "RULE", "WHERE", "MESSAGE")
        table = [(d.severity.name, d.rule,
                  (d.var_name or "<plan>")
                  + (f"[{d.location}]" if d.location else ""),
                  d.message + (f"  fix: {d.fix_hint}" if d.fix_hint else ""))
                 for d in rows]
        widths = [max(len(headers[i]), *(len(r[i]) for r in table))
                  for i in range(3)]
        lines = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))
                 + "  " + headers[3]]
        lines.append("  ".join("-" * w for w in widths) + "  " + "-" * 7)
        for r in table:
            lines.append("  ".join(r[i].ljust(widths[i]) for i in range(3))
                         + "  " + r[3])
        lines.append(self.summary())
        return "\n".join(lines)

    def to_dict(self) -> dict:
        return {"diagnostics": [d.to_dict() for d in self.diagnostics],
                "errors": len(self.errors), "warnings": len(self.warnings)}


def diag(rule: str, severity: Severity, message: str, *, var: str = "",
         location: str = "", fix: str = "") -> Diagnostic:
    """Terse constructor used by the passes."""
    return Diagnostic(rule=rule, severity=severity, message=message,
                      var_name=var, location=location, fix_hint=fix)
