"""Precision lint: compressor × dtype × sharding combinations that are
lossy, pointless, or silently fall back.

The compressor layer (``kernel/synchronization/compressor.py``) and the
explicit sync path (``explicit_sync.py``) are deliberately forgiving:
PowerSGD quietly pmean-falls-back on non-matrix gradients, the explicit
path drops a partitioned var to replication when the composition is
undefined, and a bf16 wire on bf16 storage reduces nothing.  Each
fallback is correct-but-surprising; pre-flight is where the surprise
belongs.  The supported-combination matrix is documented in
docs/analysis.md; the fallback logic itself is SHARED with the runtime
(``explicit_sync.partition_drop_reason``) so lint and behavior cannot
drift.

Rules (docs/analysis.md):

* ``precision/unknown-compressor`` (ERROR) — the compressor name is not
  registered; ``get_compressor`` raises at build time.
* ``precision/compressor-integer-dtype`` (ERROR) — a cast-based
  compressor on a non-floating variable: the bf16/int8 wire round-trip
  corrupts integer gradients.
* ``precision/bf16-wire-no-error-feedback`` (WARN) — ``HorovodCompressor``
  (bf16 wire, no error feedback) on f32/f64 variables: quantization
  error accumulates step over step; ``HorovodCompressorEF`` carries the
  residual for the same wire bytes.
* ``precision/compressor-partition-dropped`` (WARN) — a partitioned
  variable whose sharding the explicit path will drop (pad-to-divisible,
  multi-axis, data-axis sharded, or non-grad-shaped compressor state):
  the memory the partitioning was buying silently comes back.
* ``precision/compressor-wire-noop`` (INFO) — wire dtype equals storage
  dtype (bf16 model through a bf16-wire compressor): no bytes saved.
* ``precision/powersgd-rank-fallback`` (INFO) — PowerSGD on a gradient
  of rank ≠ 2 falls back to a plain pmean.
* ``precision/sparse-compressed`` (WARN) — a compressor on a
  sparse-gradient (embedding) variable densifies the scatter-structured
  gradient before compressing it.

Numerics rules (docs/numerics.md; the guard/loss-scale projection is
stamped onto :class:`PlanLite` by the legality pass from the program's
``capture(numerics=...)`` config, via the runtime's own resolution):

* ``numerics/loss-scale-saturates-wire`` (ERROR) — a quantizing
  compressor whose float wire dtype the configured loss scale can
  saturate: a saturated wire value dequantizes to a FINITE number, so
  the post-dequantize guard cannot see the overflow — the one overflow
  class detection-inside-the-sync-path exists for.  Shares
  ``numerics.loss_scale.scale_saturates_wire`` with the runtime's
  build-time check.
* ``numerics/no-loss-scale`` (WARN) — an fp16/bf16 gradient reduced
  without the numerics guard (or with loss scaling resolved off): a
  low-precision overflow/underflow poisons the parameters silently;
  ``capture(numerics=True)`` turns on detection + auto scaling.
"""
from __future__ import annotations

from typing import List

from autodist_tpu.analysis.analyzer import AnalysisContext, register_pass
from autodist_tpu.analysis.diagnostics import Diagnostic, Severity, diag

#: compressors whose wire format is a bf16 downcast of the gradient.
_BF16_WIRE = ("HorovodCompressor", "HorovodCompressorEF")


def _is_float(dtype: str) -> bool:
    import numpy as np
    try:
        return np.issubdtype(np.dtype(dtype), np.floating) or \
            str(dtype) == "bfloat16"
    except TypeError:
        return str(dtype).startswith(("bfloat", "float"))


@register_pass("precision")
def run(ctx: AnalysisContext) -> List[Diagnostic]:
    from autodist_tpu.kernel.synchronization.compressor import _REGISTRY
    from autodist_tpu.kernel.synchronization.explicit_sync import (
        partition_drop_reason,
    )

    diags: List[Diagnostic] = []
    compressed = [p for p in ctx.plans.values()
                  if p.sync_kind == "AllReduce"
                  and (p.compressor or "NoneCompressor") != "NoneCompressor"]
    explicit_path = bool(compressed) or any(
        p.fused for p in ctx.plans.values())

    for plan in compressed:
        name, comp = plan.var.name, plan.compressor
        dtype = str(plan.var.dtype)
        if comp not in _REGISTRY:
            diags.append(diag(
                "precision/unknown-compressor", Severity.ERROR,
                f"compressor {comp!r} is not registered "
                f"(available: {sorted(_REGISTRY)}); the build will raise",
                var=name, fix="pick a registered compressor"))
            continue
        if not _is_float(dtype):
            diags.append(diag(
                "precision/compressor-integer-dtype", Severity.ERROR,
                f"{comp} on a {dtype} variable: the compressed wire "
                "round-trip corrupts non-floating gradients",
                var=name, fix="use NoneCompressor for integer variables"))
            continue
        if comp in _BF16_WIRE and dtype == "bfloat16":
            diags.append(diag(
                "precision/compressor-wire-noop", Severity.INFO,
                f"{comp}'s bf16 wire equals the variable's storage dtype: "
                "the collective moves the same bytes either way",
                var=name, fix="drop the compressor for bf16 variables"))
        elif comp == "HorovodCompressor" and _is_float(dtype):
            diags.append(diag(
                "precision/bf16-wire-no-error-feedback", Severity.WARN,
                f"bf16-wire all-reduce of a {dtype} gradient without f32 "
                "accumulation or error feedback: quantization error "
                "accumulates step over step",
                var=name,
                fix="use HorovodCompressorEF (same wire bytes, residual "
                    "carried) or NoneCompressor"))
        if comp == "PowerSGDCompressor" and len(plan.var.shape) != 2:
            diags.append(diag(
                "precision/powersgd-rank-fallback", Severity.INFO,
                f"PowerSGD only compresses rank-2 gradients; this rank-"
                f"{len(plan.var.shape)} variable falls back to plain pmean",
                var=name))
        if plan.var.sparse:
            diags.append(diag(
                "precision/sparse-compressed", Severity.WARN,
                f"{comp} on a sparse-gradient variable densifies the "
                "scatter-structured gradient before compressing it",
                var=name,
                fix="route sparse variables through PS (Parallax rule)"))

    if explicit_path:
        from autodist_tpu.kernel.synchronization.compressor import (
            get_compressor,
        )
        for plan in ctx.plans.values():
            if not plan.placement or plan.sync_kind is None:
                continue
            comp_name = plan.compressor or "NoneCompressor"
            if comp_name not in _REGISTRY:
                continue
            why = partition_drop_reason(
                sorted(plan.placement.items()), plan.var.shape,
                plan.var.dtype, ctx.axes, plan.pad is not None,
                get_compressor(comp_name))
            if why is not None:
                diags.append(diag(
                    "precision/compressor-partition-dropped", Severity.WARN,
                    "the explicit (compressed/fused) sync path will "
                    f"replicate this partitioned variable ({why}): the "
                    "partitioning's memory win silently disappears",
                    var=plan.var.name,
                    fix="uncompress it, or keep the program on the GSPMD "
                        "path"))

    # -- numerics/* rules (docs/numerics.md) -------------------------------
    from autodist_tpu.numerics.loss_scale import (
        LossScale,
        is_low_precision,
        scale_saturates_wire,
    )
    for plan in ctx.plans.values():
        if plan.sync_kind != "AllReduce":
            continue
        comp = plan.compressor or "NoneCompressor"
        if plan.guard and plan.loss_scale > 0:
            why = scale_saturates_wire(
                LossScale(init=plan.loss_scale, dynamic=False), comp)
            if why is not None:
                diags.append(diag(
                    "numerics/loss-scale-saturates-wire", Severity.ERROR,
                    f"{why}; the saturated wire value dequantizes to a "
                    "FINITE number, so the post-dequantize guard cannot "
                    "see the overflow",
                    var=plan.var.name,
                    fix="lower max_scale/init below the wire dtype's "
                        "range (headroom included) or drop the "
                        "quantizing compressor"))
        if is_low_precision(plan.var.dtype) and (
                not plan.guard or plan.loss_scale <= 0):
            diags.append(diag(
                "numerics/no-loss-scale", Severity.WARN,
                f"{plan.var.dtype} gradients reduce without "
                + ("the numerics guard" if not plan.guard
                   else "loss scaling")
                + ": a low-precision overflow/underflow poisons the "
                  "parameters silently (no detection, no skip)",
                var=plan.var.name,
                fix="capture(numerics=True) — fused non-finite "
                    "detection plus auto loss scaling for low-precision "
                    "programs"))
    return diags
