"""CLI: ``python -m autodist_tpu.analysis <model> <strategy>``.

Analyze a strategy against a model's variable catalog WITHOUT building a
mesh, tracing, or compiling anything — the whole point is a sub-second
verdict on a plan that would otherwise cost minutes of XLA compile to
reject.  Prints the diagnostics table and exits 1 when any ERROR rule
fires (0 otherwise; 2 on usage errors).

``model`` is a builtin demo catalog (``--list-models``) or a path to a
GraphItem catalog JSON (``GraphItem.serialize()`` output).  ``strategy``
is a builder class name from ``autodist_tpu.strategy`` (built against
the virtual resource spec) or a path to a serialized Strategy JSON.

Examples::

    python -m autodist_tpu.analysis linear_regression PSLoadBalancing \
        --mesh data=8
    python -m autodist_tpu.analysis pipeline AllReduce --mesh pipe=4,data=2
    python -m autodist_tpu.analysis my_catalog.json /tmp/strategy.json \
        --mesh data=8 --budget-gb 16 --json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict


def _demo_models() -> Dict[str, dict]:
    """Builtin demo catalogs, mirroring the examples/ programs (shapes
    chosen so every shipped builder lowers cleanly on an 8-chip mesh)."""
    return {
        # examples/linear_regression.py: two scalars
        "linear_regression": {
            "params": {"w": ((), "float32"), "b": ((), "float32")},
        },
        # a small dense net (examples/image_classifier.py scale)
        "mlp": {
            "params": {
                "dense1": {"kernel": ((128, 64), "float32"),
                           "bias": ((64,), "float32")},
                "dense2": {"kernel": ((64, 8), "float32"),
                           "bias": ((8,), "float32")},
            },
        },
        # the same net in bf16 storage — the numerics/* rules' demo
        # (docs/numerics.md): low-precision gradients want the guard.
        "mlp_bf16": {
            "params": {
                "dense1": {"kernel": ((128, 64), "bfloat16"),
                           "bias": ((64,), "bfloat16")},
                "dense2": {"kernel": ((64, 8), "bfloat16"),
                           "bias": ((8,), "bfloat16")},
            },
        },
        # embedding LM slice (examples/lm1b): sparse vocab table
        "embedding_lm": {
            "params": {
                "emb": {"table": ((800, 64), "float32")},
                "proj": {"kernel": ((64, 64), "float32")},
            },
            "sparse_vars": ["emb/table"],
        },
        # examples/pipeline_1f1b.py: stage-stacked transformer blocks
        "pipeline": {
            "params": {
                "stages": {"w1": ((4, 32, 32), "float32"),
                           "w2": ((4, 32, 32), "float32")},
                "head": {"kernel": ((32, 64), "float32")},
            },
            "pipeline_vars": ["stages"],
        },
        # examples/moe_pipeline.py: expert-stacked FFN
        "moe": {
            "params": {
                "router": ((32, 4), "float32"),
                "wi": ((4, 32, 64), "float32"),
                "wo": ((4, 64, 32), "float32"),
            },
            "expert_vars": ["wi", "wo"],
        },
    }


def _build_graph_item(model_arg: str):
    import jax

    from autodist_tpu.graph_item import GraphItem

    def from_spec(spec: dict) -> GraphItem:
        def leafify(node):
            if isinstance(node, dict):
                return {k: leafify(v) for k, v in node.items()}
            shape, dtype = node
            return jax.ShapeDtypeStruct(tuple(shape), dtype)

        return GraphItem(
            leafify(spec["params"]),
            sparse_vars=spec.get("sparse_vars", ()),
            untrainable_vars=spec.get("untrainable_vars", ()),
            pipeline_vars=spec.get("pipeline_vars", ()),
            expert_vars=spec.get("expert_vars", ()))

    demos = _demo_models()
    if model_arg in demos:
        return from_spec(demos[model_arg])
    if os.path.exists(model_arg):
        with open(model_arg, "r", encoding="utf-8") as f:
            d = json.load(f)
        if "variables" in d:  # GraphItem.serialize() catalog
            params = {v["name"]: jax.ShapeDtypeStruct(
                tuple(v["shape"]), v["dtype"]) for v in d["variables"]}
            return GraphItem(
                params,
                sparse_vars=[v["name"] for v in d["variables"]
                             if v.get("sparse")],
                untrainable_vars=[v["name"] for v in d["variables"]
                                  if not v.get("trainable", True)],
                pipeline_vars=[v["name"] for v in d["variables"]
                               if v.get("pipeline")],
                expert_vars=[v["name"] for v in d["variables"]
                             if v.get("expert")])
        return from_spec(d)  # {"params": {...}, "sparse_vars": [...]} form
    raise SystemExit(
        f"unknown model {model_arg!r}: not a builtin "
        f"({', '.join(sorted(demos))}) and not a file")


def _build_strategy(strategy_arg: str, graph_item, resource_spec):
    import autodist_tpu.strategy as S

    if os.path.exists(strategy_arg):
        with open(strategy_arg, "r", encoding="utf-8") as f:
            return S.Strategy.from_dict(json.load(f))
    builder_cls = getattr(S, strategy_arg, None)
    if builder_cls is None or not (isinstance(builder_cls, type)
                                   and issubclass(builder_cls,
                                                  S.StrategyBuilder)):
        names = sorted(n for n in dir(S)
                       if isinstance(getattr(S, n), type)
                       and issubclass(getattr(S, n), S.StrategyBuilder)
                       and getattr(S, n) is not S.StrategyBuilder)
        raise SystemExit(
            f"unknown strategy {strategy_arg!r}: not a builder "
            f"({', '.join(names)}) and not a file")
    return builder_cls().build(graph_item, resource_spec)


def _parse_numerics(spec: str):
    """``--numerics`` grammar → a NumericsConfig (or None for 'off'):
    ``on`` / ``off`` / an on_nonfinite policy name / comma-separated
    ``field=value`` pairs (``loss_scale`` takes auto|none|<float>;
    ``clip_norm``/``spike_zscore`` floats; ``rollback_after`` int)."""
    from autodist_tpu.numerics.policy import ON_NONFINITE, NumericsConfig

    s = spec.strip()
    if s in ("off", "false", "0"):
        return None
    if s in ("on", "true", "1", "auto"):
        return NumericsConfig()
    if s in ON_NONFINITE:
        return NumericsConfig(on_nonfinite=s)
    fields: Dict[str, object] = {}
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise SystemExit(
                f"bad --numerics entry {part!r}: use field=value, e.g. "
                "loss_scale=65536,clip_norm=1.0 (or on/off/skip/raise/"
                "rollback)")
        k, v = (x.strip() for x in part.split("=", 1))
        if k == "loss_scale":
            fields[k] = None if v in ("none", "off") else (
                v if v == "auto" else float(v))
        elif k in ("clip_norm", "spike_zscore"):
            fields[k] = None if v == "none" else float(v)
        elif k in ("rollback_after", "spike_window", "max_rollbacks"):
            fields[k] = int(v)
        elif k in ("guard", "reseed_on_rollback"):
            fields[k] = v in ("1", "true", "on", "yes")
        elif k == "on_nonfinite":
            fields[k] = v
        else:
            raise SystemExit(f"unknown --numerics field {k!r}")
    try:
        return NumericsConfig(**fields)
    except (TypeError, ValueError) as e:
        raise SystemExit(f"bad --numerics spec {spec!r}: {e}")


def _parse_mesh(mesh_arg: str) -> Dict[str, int]:
    axes: Dict[str, int] = {}
    for part in mesh_arg.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise SystemExit(
                f"bad --mesh entry {part!r}: use name=size, e.g. "
                "data=8,model=2")
        name, size = part.split("=", 1)
        axes[name.strip()] = int(size)
    if not axes:
        raise SystemExit("--mesh parsed to no axes")
    return axes


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m autodist_tpu.analysis",
        description="Static strategy/sharding analyzer (shardlint): "
                    "pre-flight legality, sync-coverage, HBM, collective "
                    "and precision checks.  See docs/analysis.md.")
    parser.add_argument("model", nargs="?",
                        help="builtin demo model or catalog JSON path")
    parser.add_argument("strategy", nargs="?",
                        help="builder class name or Strategy JSON path")
    parser.add_argument("--mesh", default=None,
                        help="logical mesh axes, e.g. data=8 or "
                             "pipe=4,data=2 (default: resource spec / "
                             "local device count)")
    parser.add_argument("--resource-spec", default=None,
                        help="resource spec yaml (mesh hint + hbm_gb "
                             "budget)")
    parser.add_argument("--budget-gb", type=float, default=None,
                        help="per-device HBM budget in GiB (overrides "
                             "the spec)")
    parser.add_argument("--overlap", default=None,
                        help="stamp this overlap schedule mode (auto | "
                             "none | pipeline | ring | full) onto every "
                             "AllReduce node before analyzing — lint a "
                             "schedule request against the mesh "
                             "(docs/overlap.md)")
    parser.add_argument("--elastic-from", default=None, metavar="AXES",
                        help="validate an ELASTIC RESUME: the checkpoint "
                             "was written at these mesh axes (e.g. "
                             "data=8) and resumes at --mesh — runs the "
                             "elastic/* rules plus the normal passes on "
                             "the new mesh (ring degeneracy re-check, "
                             "HBM at the new 1/M; docs/resilience.md)")
    parser.add_argument("--numerics", default=None, metavar="SPEC",
                        help="stamp a numerics-guard config onto the "
                             "program before analyzing (docs/numerics.md)"
                             ": 'on'/'off', an on_nonfinite policy "
                             "(skip|raise|rollback), or comma-separated "
                             "fields like 'loss_scale=1e36,clip_norm=1' "
                             "— lint loss scaling against quantizing "
                             "compressors (numerics/* rules)")
    parser.add_argument("--passes", default=None,
                        help="comma-separated subset of passes "
                             "(default: all)")
    parser.add_argument("--dump-ir", nargs="?", const="json",
                        choices=("json", "dot"), default=None,
                        metavar="FORMAT",
                        help="emit the sync-schedule IR this plan lowers "
                             "to (docs/schedule-ir.md) instead of the "
                             "diagnostics table: 'json' (default) or "
                             "'dot' for a Graphviz dep-graph view; the "
                             "printed JSON carries the schedule_fingerprint "
                             "telemetry and checkpoints stamp")
    parser.add_argument("--watermark", action="store_true",
                        help="emit the schedule's liveness-based HBM "
                             "watermark (docs/analysis.md): walk the "
                             "sync-schedule IR in topological order, "
                             "open/close buffer live intervals, and "
                             "report per-device peak bytes, the leg at "
                             "the peak, and per-microbatch-slot peaks "
                             "on top of the static params+optimizer "
                             "base.  Combines with --dump-ir json "
                             "(one JSON object with schedule_ir + "
                             "watermark keys); exits 1 when a budget "
                             "(--budget-gb / the spec's hbm_gb) is "
                             "exceeded")
    parser.add_argument("--simulate", default=None, metavar="SWEEP",
                        help="sweep mesh shape x slice count x DCN "
                             "bandwidth over the pure cost/watermark "
                             "model (docs/strategies.md 'Two-tier sync "
                             "and --simulate'): SWEEP is a JSON file or "
                             "an inline spec like "
                             "'mesh=data=1024;slices=1,2,4;dcn=25,100"
                             ";hbm=32'.  Per point, per sync mode "
                             "(flat/hier/hier_int8): predicted step "
                             "time, exposed wire per tier, watermark "
                             "HBM, goodput under preemption.  Nothing "
                             "traces or compiles; exits 1 when any "
                             "point exceeds the HBM budget")
    parser.add_argument("--search-report", action="store_true",
                        help="run the leg-calibrated strategy search "
                             "(docs/strategies.md 'Search') on the model "
                             "and dump the top-K candidates with their "
                             "per-leg-kind cost breakdown plus the "
                             "legality rule that pruned each rejected "
                             "branch; the strategy argument is ignored.  "
                             "Constants come from the discovered "
                             "calibration.json (AUTODIST_CALIBRATION / "
                             "AUTODIST_TELEMETRY_DIR) when present")
    parser.add_argument("--topk", type=int, default=5, metavar="K",
                        help="candidates to show in --search-report "
                             "(default 5)")
    parser.add_argument("--json", action="store_true",
                        help="emit the report as JSON")
    parser.add_argument("--warn-as-error", action="store_true",
                        help="exit nonzero on WARN findings too")
    parser.add_argument("--list-models", action="store_true")
    parser.add_argument("--list-rules", action="store_true",
                        help="print each pass's rule documentation")
    args = parser.parse_args(argv)

    if args.list_models:
        for name in sorted(_demo_models()):
            print(name)
        return 0
    if args.list_rules:
        from autodist_tpu.analysis import analyzer
        analyzer._load_passes()
        for name in analyzer.PASS_ORDER:
            fn = analyzer.PASS_REGISTRY[name]
            print(f"== pass: {name} ==")
            print((sys.modules[fn.__module__].__doc__ or "").strip())
            print()
        return 0
    if not args.model or (not args.strategy and not args.search_report):
        parser.error("model and strategy are required "
                     "(or use --list-models / --list-rules / "
                     "--search-report, which needs only the model)")

    from autodist_tpu.analysis import Severity, analyze
    from autodist_tpu.resource_spec import ResourceSpec

    axes = _parse_mesh(args.mesh) if args.mesh else None
    resource_spec = None
    if args.resource_spec:
        resource_spec = ResourceSpec(args.resource_spec)
    if axes is None and resource_spec is None:
        import jax
        axes = {"data": jax.device_count()}

    # Builders need a resource spec; fabricate a single-node one sized to
    # the mesh when none was given (pure analysis — nothing launches).
    if resource_spec is None:
        import math
        chips = math.prod(axes.values())
        resource_spec = ResourceSpec(resource_info={
            "nodes": [{"address": "localhost", "chips": chips}],
            "mesh": dict(axes)})

    graph_item = _build_graph_item(args.model)
    if args.numerics:
        graph_item.numerics = _parse_numerics(args.numerics)

    if args.search_report:
        from autodist_tpu.analysis.search import (
            format_search_report,
            search_report,
        )
        report = search_report(graph_item, resource_spec, axes=axes,
                               top_k=args.topk)
        if args.json:
            print(json.dumps(report, indent=1))
        else:
            print(format_search_report(report))
        return 0 if report.get("best") else 1

    if args.simulate:
        import autodist_tpu.strategy as S
        from autodist_tpu.analysis.simulate import (
            format_sweep_report,
            parse_sweep_spec,
            run_sweep,
        )
        from autodist_tpu.telemetry.calibration import (
            load_default_calibration,
        )

        try:
            config = parse_sweep_spec(args.simulate)
        except ValueError as e:
            raise SystemExit(str(e))
        if args.budget_gb and "hbm_gb" not in config:
            config["hbm_gb"] = float(args.budget_gb)
        builder_cls = getattr(S, args.strategy, None)
        if builder_cls is None or not (
                isinstance(builder_cls, type)
                and issubclass(builder_cls, S.StrategyBuilder)):
            raise SystemExit(
                f"--simulate needs a builder class name, got "
                f"{args.strategy!r}")

        def make_strategy(spec, hier):
            builder = builder_cls(hier=True) if hier else builder_cls()
            return builder.build(graph_item, spec)

        report = run_sweep(graph_item, make_strategy, config,
                           constants=load_default_calibration())
        if args.json:
            print(json.dumps(report, indent=1))
        else:
            print(format_sweep_report(report))
        priced_any = any("best_mode" in p for p in report["points"])
        if report["n_over_hbm"] or not priced_any:
            return 1
        return 0

    strategy = _build_strategy(args.strategy, graph_item, resource_spec)
    if args.overlap:
        from autodist_tpu.strategy.base import AllReduceSynchronizerConfig
        for node in strategy.node_config:
            if isinstance(node.synchronizer, AllReduceSynchronizerConfig):
                node.synchronizer.overlap = args.overlap
    budget = int(args.budget_gb * (1 << 30)) if args.budget_gb else None
    passes = tuple(p.strip() for p in args.passes.split(",")) \
        if args.passes else None
    elastic = {"from_axes": _parse_mesh(args.elastic_from)} \
        if args.elastic_from else None

    if args.dump_ir or args.watermark:
        # Build the plan projection (legality lowering) and emit the
        # schedule IR it lowers to and/or its liveness watermark — no
        # diagnostics table, exit 0 unless the projection itself cannot
        # be built (or --watermark finds a budget exceeded).
        from autodist_tpu.analysis import analyzer as _an
        from autodist_tpu.analysis.schedule import ir_for
        _an._load_passes()
        strategy_r, compiled, axes_r = _an._resolve_axes(
            strategy, axes, resource_spec)
        ctx = _an.AnalysisContext(strategy=strategy_r,
                                  graph_item=graph_item, axes=axes_r,
                                  compiled=compiled,
                                  resource_spec=resource_spec)
        _an.PASS_REGISTRY["legality"](ctx)
        ir = ir_for(ctx)
        if ir is None:
            print("no synced variables: the plan lowers to an empty "
                  "schedule", file=sys.stderr)
            return 1
        wm = None
        eff_budget = budget or getattr(resource_spec,
                                       "hbm_bytes_per_chip", None)
        if args.watermark:
            from autodist_tpu.analysis import dataflow
            from autodist_tpu.analysis import memory as _mem
            base = _mem._param_and_grad_bytes(ctx)["params"] \
                + (_mem._opt_state_bytes(ctx) or 0.0) \
                + (_mem._activation_bytes(ctx) or 0.0)
            wm = dataflow.watermark(ir, base_bytes=int(base))
            if wm is None:
                print("schedule is unexecutable (dep cycle): no "
                      "topological order to simulate", file=sys.stderr)
                return 1
        if args.dump_ir == "dot":
            print(ir.to_dot())
            if wm is not None:
                print(wm.summary(), file=sys.stderr)
        elif args.dump_ir:
            if wm is not None:
                print(json.dumps({"schedule_ir": ir.to_dict(),
                                  "watermark": wm.to_dict()}, indent=1))
            else:
                print(ir.to_json(indent=1))
        elif wm is not None:
            if args.json:
                print(json.dumps(wm.to_dict(), indent=1))
            else:
                mib = float(1 << 20)
                print(f"schedule watermark [{ir.fingerprint()}]: "
                      f"{wm.summary()}")
                for buf, n in wm.top_buffers():
                    print(f"  {buf:40s} {n / mib:8.2f} MiB")
                if eff_budget:
                    verdict = "EXCEEDED" if wm.peak_bytes > eff_budget \
                        else "ok"
                    print(f"  budget {eff_budget / mib:.1f} MiB: "
                          f"{verdict}")
        if wm is not None and eff_budget and wm.peak_bytes > eff_budget:
            return 1
        return 0

    report = analyze(strategy, graph_item, mesh=axes,
                     resource_spec=resource_spec, budget_bytes=budget,
                     passes=passes, elastic=elastic)

    if args.json:
        print(json.dumps(report.to_dict(), indent=1))
    else:
        print(report.format_table())
    if report.has_errors():
        return 1
    if args.warn_as_error and report.warnings:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
