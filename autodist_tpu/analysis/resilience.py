"""Resilience pass: does the checkpoint cadence bound work loss?

Provenance-gated like the elastic and telemetry passes: feed
``analyze(..., resilience={...})`` the run's recovery configuration —
most usefully :func:`autodist_tpu.telemetry.goodput.checkpoint_cadence`
over a recorded run, or the planned config before launch — and the pass
checks the recovery exposure (checkpoint interval × calibrated step
time, capped by the RAM snapshot tier when one is configured) against a
recovery-loss budget.  Inert without provenance.

Rules (docs/resilience.md, docs/observability.md):

* ``resilience/recovery-gap`` (WARN) — the cheapest configured tier
  leaves more than ``recovery_budget_s`` (default
  :data:`~autodist_tpu.telemetry.goodput.RECOVERY_BUDGET_S`) of work
  exposed to a single failure.  Shared pure rule
  :func:`~autodist_tpu.telemetry.goodput.recovery_gap_reason` — the
  telemetry CLI's goodput section prints the identical string.
* ``resilience/no-measurement`` (INFO) — resilience provenance was
  passed but holds no usable interval/step-time pair; the gap check
  could not run.

Provenance dict keys: ``checkpoint_interval_steps`` (steps between
persistent saves), ``step_time_s`` (measured or leg-calibrated),
optional ``snapshot_every`` (RAM tier cadence, steps) and
``recovery_budget_s`` (budget override).
"""
from __future__ import annotations

from typing import List

from autodist_tpu.analysis.analyzer import AnalysisContext, register_pass
from autodist_tpu.analysis.diagnostics import Diagnostic, Severity, diag


@register_pass("resilience")
def run(ctx: AnalysisContext) -> List[Diagnostic]:
    from autodist_tpu.telemetry.goodput import (
        RECOVERY_BUDGET_S,
        recovery_gap_reason,
    )

    res = getattr(ctx, "resilience", None)
    if not res:
        return []
    out: List[Diagnostic] = []
    interval = res.get("checkpoint_interval_steps")
    step_time = res.get("step_time_s")
    if not interval or not step_time:
        out.append(diag(
            "resilience/no-measurement", Severity.INFO,
            "resilience provenance has no usable checkpoint-interval/"
            "step-time pair — the recovery-gap check did not run",
            fix="pass checkpoint_interval_steps and step_time_s (e.g. "
                "telemetry.goodput.checkpoint_cadence over a recorded "
                "run, or the planned cadence with a leg-calibrated "
                "step-time estimate)"))
        return out
    why = recovery_gap_reason(
        float(interval), float(step_time),
        budget_s=float(res.get("recovery_budget_s", RECOVERY_BUDGET_S)),
        snapshot_every=res.get("snapshot_every"))
    if why is not None:
        out.append(diag(
            "resilience/recovery-gap", Severity.WARN, why,
            fix="checkpoint more often, or enable the RAM snapshot "
                "tier (fit(snapshot_every=...) / "
                "AUTODIST_SNAPSHOT_EVERY) so a failure loses at most "
                "snapshot_every steps (docs/resilience.md)"))
    return out
