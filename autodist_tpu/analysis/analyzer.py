"""The analyzer pipeline: Strategy × GraphItem × mesh axes → diagnostics.

The central idea (PAPER.md: distribution is a *compilation* problem) is
that everything a Strategy will do to the program is decidable before any
tracing: which mesh axis each tensor dim lands on, whether the dims
divide, what optimizer/compressor state materializes per device, and
which collectives each shard issues, are all functions of
``(Strategy, VarInfo catalog, mesh axis sizes)``.  The analyzer computes
exactly that projection — :class:`PlanLite`, a mesh-free mirror of the
compiler's :class:`~autodist_tpu.strategy.compiler.VarPlan` lowering —
and runs rule passes over it, so a bad plan is rejected in milliseconds
with a rule-tagged diagnostic instead of minutes into an XLA compile
(the Automap/ergonomics argument, arXiv:2112.02958).

Inputs are deliberately loose: ``mesh`` may be a real
``jax.sharding.Mesh``, a plain ``{axis: size}`` dict (no devices needed —
how the auto-strategy search prunes candidates before any mesh exists),
or omitted (derived from ``resource_spec``).  Passing a
:class:`CompiledStrategy` analyzes the *actual* lowered plans instead of
the projection, which also catches hand-built plan drift.
"""
from __future__ import annotations

from collections.abc import Mapping
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from autodist_tpu.analysis.diagnostics import (
    AnalysisReport,
    Diagnostic,
    Severity,
    StrategyValidationError,
    diag,
)
from autodist_tpu.graph_item import GraphItem, VarInfo
from autodist_tpu.utils import logging

#: pass name -> rule-id prefix, populated by register_pass below.
PASS_REGISTRY: Dict[str, Any] = {}


@dataclass
class PlanLite:
    """Mesh-free projection of one variable's lowered plan.

    ``placement`` maps tensor dim → mesh axis name for the parameter
    layout; ``opt_placement`` for same-shaped optimizer slots.  ``pad``
    is ``(dim, padded_size)`` when pad-to-divisible sharding covers an
    indivisible dim.  ``synthesized`` marks plans the compiler would
    create by default (no strategy node)."""

    var: VarInfo
    sync_kind: Optional[str] = None          # "AllReduce" | "PS" | None
    placement: Dict[int, str] = field(default_factory=dict)
    opt_placement: Dict[int, str] = field(default_factory=dict)
    pad: Optional[Tuple[int, int]] = None
    compressor: str = "NoneCompressor"
    fused: bool = False
    group: int = 0
    staleness: int = 0
    grad_reduce_axes: Tuple[str, ...] = ()
    synthesized: bool = False
    # AllReduce collective lowering ("all_reduce" | "reduce_scatter") and
    # whether the ZeRO-1 weight-update sharding actually takes effect for
    # this var (reduce_scatter requested AND the bucketed path can absorb
    # it — set by the legality lowering via bucketing.bucket_drop_reason,
    # the same rule the runtime uses).  When True, the memory pass counts
    # optimizer slots at 1/data-axis-size.
    sync_mode: str = "all_reduce"
    zero1: bool = False
    bucket_bytes: int = 0
    # Bucket-collective overlap schedule requested by the strategy
    # (overlap.OVERLAP_MODES); the sync pass checks it against the mesh
    # and the program (sync/ring-degenerate, sync/overlap-fallback).
    overlap: str = "auto"
    # Numerics projection (docs/numerics.md), stamped by the legality
    # pass from the program's NumericsConfig: is the fused guard active
    # for this var's sync, and the PEAK loss scale its gradient can ride
    # (0.0 = scaling off) — what the numerics/* precision rules check
    # against quantizing compressors' wire dtypes.
    guard: bool = False
    loss_scale: float = 0.0
    # Two-tier hierarchical sync requested (ICI within slice, DCN
    # across) — effective only on a multi-slice spec whose slice count
    # tiles the data axis (schedule_ir.hier_applies).
    hier: bool = False

    def physical_shape(self) -> Tuple[int, ...]:
        shape = list(self.var.shape)
        if self.pad is not None:
            shape[self.pad[0]] = self.pad[1]
        return tuple(shape)

    def _denominator(self, placement: Dict[int, str],
                     axes: Mapping[str, int]) -> int:
        denom = 1
        for axis_name in placement.values():
            denom *= max(int(axes.get(axis_name, 1)), 1)
        return denom

    def param_bytes_per_device(self, axes: Mapping[str, int]) -> float:
        import numpy as np
        size = float(np.prod(self.physical_shape() or (1,)))
        item = np.dtype(self.var.dtype).itemsize
        return size * item / self._denominator(self.placement, axes)

    def opt_denominator(self, axes: Mapping[str, int]) -> int:
        return self._denominator(self.opt_placement, axes)


@dataclass
class AnalysisContext:
    """Everything a pass may consult.  ``plans`` is filled by the
    legality pass (which owns the lowering) before later passes run."""

    strategy: Any                            # Strategy (never None)
    graph_item: GraphItem
    axes: Dict[str, int]
    compiled: Any = None                     # CompiledStrategy | None
    resource_spec: Any = None
    budget_bytes: Optional[int] = None
    batch: Any = None                        # pytree of arrays/shapes | None
    plans: Dict[str, PlanLite] = field(default_factory=dict)
    # Elastic-resume provenance ({"from_axes": {...}, "buckets": [...]})
    # — enables the elastic/* rules; None outside a resume pre-flight.
    elastic: Optional[dict] = None
    # Telemetry provenance ({"measured_step_time_s": ...,
    # "predicted_step_time_s": ...} — predicted_vs_measured() output)
    # — enables the telemetry/* rules; None without a recorded run.
    telemetry: Optional[dict] = None
    # Resilience provenance ({"checkpoint_interval_steps": ...,
    # "step_time_s": ..., "snapshot_every": ...}) — enables the
    # resilience/* rules (recovery-gap); None without a recovery config.
    resilience: Optional[dict] = None
    # Sync-schedule IR cache (built once by analysis.schedule.ir_for;
    # shared with the collectives pass and the CLI --dump-ir).
    schedule_ir: Any = None

    @property
    def data_axis_size(self) -> int:
        from autodist_tpu.const import MESH_AXIS_DATA
        return int(self.axes.get(MESH_AXIS_DATA, 1))


def _resolve_axes(strategy_or_compiled, mesh, resource_spec
                  ) -> Tuple[Any, Any, Dict[str, int]]:
    """Normalize (strategy, compiled, axes) from the loose inputs."""
    from autodist_tpu.strategy.compiler import CompiledStrategy

    compiled = None
    strategy = strategy_or_compiled
    if isinstance(strategy_or_compiled, CompiledStrategy):
        compiled = strategy_or_compiled
        strategy = compiled.strategy
        axes = {str(k): int(v) for k, v in dict(compiled.mesh.shape).items()}
        return strategy, compiled, axes

    if mesh is not None:
        if isinstance(mesh, Mapping):
            axes = {str(k): int(v) for k, v in mesh.items()}
        else:  # a real jax.sharding.Mesh
            axes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
    elif resource_spec is not None:
        from autodist_tpu.const import MESH_AXIS_DATA
        axes = dict(resource_spec.mesh_hint) or \
            {MESH_AXIS_DATA: max(resource_spec.num_chips, 1)}
    else:
        from autodist_tpu.const import MESH_AXIS_DATA
        axes = {MESH_AXIS_DATA: 1}
    return strategy, compiled, axes


def register_pass(name: str):
    def deco(fn):
        PASS_REGISTRY[name] = fn
        return fn
    return deco


def _load_passes() -> None:
    """Import the pass modules once (each registers itself).  Keyed on
    the full pass set, not mere non-emptiness: importing one pass
    module directly (e.g. ``analysis.schedule`` for ``ir_for``) must
    not short-circuit loading the rest."""
    if all(name in PASS_REGISTRY for name in PASS_ORDER):
        return
    from autodist_tpu.analysis import (  # noqa: F401
        collectives,
        elastic,
        legality,
        memory,
        precision,
        resilience,
        schedule,
        sync_coverage,
        telemetry,
    )


#: canonical pass order: legality first (it builds ctx.plans), then the
#: coverage/resource rules over the projection, the collectives pass
#: (which consumes the schedule IR for its exact cross-stage check),
#: the schedule verifier over the IR itself, precision, then the
#: elastic-resume and telemetry rules (each inert without its
#: provenance).
PASS_ORDER = ("legality", "sync", "memory", "collectives", "schedule",
              "precision", "elastic", "telemetry", "resilience")


def analyze(strategy_or_compiled, graph_item: GraphItem, *,
            mesh=None, resource_spec=None, budget_bytes: Optional[int] = None,
            batch=None, passes: Optional[Tuple[str, ...]] = None,
            elastic: Optional[dict] = None,
            telemetry: Optional[dict] = None,
            resilience: Optional[dict] = None
            ) -> AnalysisReport:
    """Run the static pass pipeline and return an :class:`AnalysisReport`.

    Args:
      strategy_or_compiled: a :class:`Strategy` or a
        :class:`CompiledStrategy` (the latter analyzes actual lowered
        plans and enables the compiled-only consistency rules).
      graph_item: the captured program (variable catalog; optimizer and
        params improve the HBM estimate when present).
      mesh: a ``jax.sharding.Mesh`` or plain ``{axis: size}`` dict;
        ignored for CompiledStrategy input (its mesh wins).  Defaults to
        ``resource_spec.mesh_hint`` or pure data parallelism over the
        spec's chips.
      resource_spec: optional cluster description — supplies the default
        mesh axes and the per-chip HBM budget (``hbm_gb`` yaml key).
      budget_bytes: explicit per-device HBM budget; overrides the spec.
      batch: optional batch pytree (arrays or ShapeDtypeStructs) for the
        activation-footprint estimate.
      passes: subset of :data:`PASS_ORDER` to run (e.g. only
        ``("legality", "sync")`` for the auto-strategy candidate pruner).
      elastic: elastic-resume provenance — ``{"from_axes": {axis: size},
        "buckets": [...]}`` (the checkpoint's mesh and recorded ZeRO-1
        bucket layout) — enabling the ``elastic/*`` rules; the rest of
        the pipeline runs against the NEW mesh, which is exactly the
        re-check elastic resume needs (ring degeneracy, HBM at 1/M).
      telemetry: measurement provenance — a
        ``telemetry.calibration.predicted_vs_measured()`` summary of a
        recorded run — enabling the ``telemetry/*`` rules
        (``telemetry/model-drift``); inert when None.
      resilience: recovery-config provenance —
        ``{"checkpoint_interval_steps": ..., "step_time_s": ...[,
        "snapshot_every": ..., "recovery_budget_s": ...]}`` (e.g.
        ``telemetry.goodput.checkpoint_cadence`` over a recorded run)
        — enabling the ``resilience/*`` rules
        (``resilience/recovery-gap``); inert when None.
    """
    _load_passes()
    strategy, compiled, axes = _resolve_axes(
        strategy_or_compiled, mesh, resource_spec)
    if budget_bytes is None and resource_spec is not None:
        budget_bytes = getattr(resource_spec, "hbm_bytes_per_chip", None)
    ctx = AnalysisContext(strategy=strategy, graph_item=graph_item,
                          axes=axes, compiled=compiled,
                          resource_spec=resource_spec,
                          budget_bytes=budget_bytes, batch=batch,
                          elastic=elastic, telemetry=telemetry,
                          resilience=resilience)
    report = AnalysisReport()
    selected = PASS_ORDER if passes is None else tuple(passes)
    for name in selected:
        if name not in PASS_REGISTRY:
            raise ValueError(f"unknown analysis pass {name!r}; "
                             f"available: {sorted(PASS_REGISTRY)}")
    # Legality always runs first when selected — it builds ctx.plans,
    # which every later pass consumes; when the caller skips it we still
    # build the projection (without emitting its diagnostics).
    if "legality" not in selected:
        PASS_REGISTRY["legality"](ctx)
    for name in PASS_ORDER:
        if name in selected:
            diags = list(PASS_REGISTRY[name](ctx))
            # Deterministic output: findings sort by (rule id, anchor)
            # within each pass, so CLI tables and mutation goldens are
            # byte-stable across runs and dict/set iteration orders.
            diags.sort(key=lambda d: (d.rule, d.var_name, d.location,
                                      d.message))
            report.extend(diags)
    return report


_warned_reports: set = set()


def log_report(report: AnalysisReport, context: str = "") -> None:
    """Log WARN/INFO diagnostics once per (context, rule, var)."""
    for d in report.diagnostics:
        if d.severity == Severity.ERROR:
            continue
        key = (context, d.rule, d.var_name, d.location)
        if key in _warned_reports:
            continue
        _warned_reports.add(key)
        if d.severity == Severity.WARN:
            logging.warning("analysis: %s", d.format())
        else:
            logging.info("analysis: %s", d.format())


def preflight(strategy_or_compiled, graph_item: GraphItem, *,
              mesh=None, resource_spec=None, batch=None,
              context: str = "preflight") -> AnalysisReport:
    """The ``validate=`` hook body: analyze, log WARNs once, raise
    :class:`StrategyValidationError` on any ERROR — all before tracing."""
    report = analyze(strategy_or_compiled, graph_item, mesh=mesh,
                     resource_spec=resource_spec, batch=batch)
    log_report(report, context)
    report.raise_for_errors()
    return report


def preflight_session(session, batch=None) -> AnalysisReport:
    """Pre-flight an already-built DistributedSession (the ``fit(...,
    validate=True)`` path): analyzes the session's compiled strategy
    before any step dispatch."""
    compiled = session._step.compiled_strategy
    return preflight(compiled, session._gi, batch=batch,
                     context=f"session:{compiled.strategy.id}")
