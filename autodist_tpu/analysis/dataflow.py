"""Schedule dataflow sanitizer: happens-before races + liveness watermark.

The schedule IR (docs/schedule-ir.md) gives every leg explicit
``reads``/``writes``/``donated`` buffer sets, but until this module the
verifier exploited them for exactly one rule (read-after-donate) and
the memory pass priced a coarse whole-step footprint.  This module is
the full buffer-dataflow discipline over the leg partial order — the
same static safety net Automap (arXiv:2112.02958) uses to prune its
search space, at the granularity GSPMD weight-update sharding
(arXiv:2004.13336) made matter:

* :class:`HappensBefore` — the happens-before relation over legs: the
  transitive closure of the dep graph, computed as a packed **sparse
  bitset reachability** matrix (numpy ``uint64`` rows, one pass in
  reverse topological order), so ``ordered(a, b)`` is a constant-time
  bit test and the whole structure stays inside the verifier's <1 s
  budget on the 9k-leg fixture.  Per-stage issue order and
  microbatch-slot ordering materialize as dep edges from the builder
  (``schedule_ir._Emitter`` chains every collective a stage issues and
  threads slot ``k`` into slot ``k+1``), so the dep closure IS the
  happens-before relation of the program the runtime lowers — and a
  deleted dep edge shows up here exactly as it would miscompile.
* :func:`race_violations` — the **race detector**:
  ``schedule/race-unordered-write`` (ERROR) for two unordered writes
  to one buffer, ``schedule/race-read-write`` (ERROR) for an unordered
  read/write pair, ``schedule/buffer-leak`` (WARN) for a buffer
  written but never read nor donated, plus the
  ``schedule/read-after-donate`` rule re-based on the shared
  reachability structure — which makes it cheap to cover ALL donated
  buffer namespaces (``sync:``, ``param:``, ``opt:``), not just sync
  state.
* :func:`watermark` — the **liveness-based HBM watermark simulator**:
  walk the legs in a verified topological order, open each buffer's
  live interval at its first write (step inputs like ``grad:`` open at
  step start; cross-step ``sync:`` state opens at step start too) and
  close it at its last read — donation closes early (the buffer is
  aliased into its consumer), while non-donated ``sync:`` state stays
  resident to step end for the next step.  The result is a
  :class:`WatermarkReport` with per-device ``peak_bytes`` (including a
  caller-supplied static base: params + optimizer + activations),
  ``peak_leg``, and per-microbatch-slot peaks — what the memory pass
  compares against ``ResourceSpec.hbm_gb``
  (``memory/watermark-exceeds-hbm`` / ``memory/watermark-near-hbm``),
  what ``AutoStrategy(search="beam")`` uses to reject OOM schedules
  before pricing, what the ``ScheduleTuner`` checks before a hot-swap,
  and what the CLI ``--watermark`` prints.

Everything here is numpy-only and mesh-free — safe inside the
pre-trace verifier gate, the beam search inner loop, and bench.

:class:`HappensBefore` has a second consumer beyond the verifier: the
flight recorder's hang localizer
(:func:`autodist_tpu.telemetry.flightrec.localize_hang`) diffs
per-host progress cursors against this exact relation to name the
frontier leg and the culprit host of a WEDGED verdict — the legs it
passes are lightweight views carrying only ``id``/``deps``, which is
all the closure reads.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from itertools import combinations
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from autodist_tpu.const import MESH_AXIS_DATA
from autodist_tpu.kernel.synchronization import schedule_ir as sir

#: memory rule ids the watermark consumers share (the schedule/* race
#: rule ids live in schedule_ir with the other verifier rules).
RULE_WATERMARK_EXCEEDS = "memory/watermark-exceeds-hbm"
RULE_WATERMARK_NEAR = "memory/watermark-near-hbm"

#: buffer namespaces accounted in the caller's STATIC base (parameter
#: and optimizer storage exists whether or not the schedule runs) —
#: excluded from the transient liveness sweep and from the leak rule
#: (writing them is the step's output, not dead work).
PERSISTENT_NAMESPACES = ("param", "opt")
#: namespaces carrying cross-step state: resident from step start, and
#: resident to step end unless donated (donation aliases the old
#: buffer into the update, closing its interval at the last access).
CROSS_STEP_NAMESPACES = ("sync",)

_MiB = float(1 << 20)

_BITS = np.uint64(1) << np.arange(64, dtype=np.uint64)


def buffer_namespace(buf: str) -> str:
    """``"red"`` for ``"red:layer0"``; ``""`` for un-namespaced names."""
    return buf.split(":", 1)[0] if ":" in buf else ""


def topo_order(ir) -> Optional[List[str]]:
    """A verified (deterministic) topological order of ``ir``'s legs,
    or None when the dep graph is cyclic or ids are ambiguous."""
    legs = list(ir.legs)
    if len({l.id for l in legs}) != len(legs):
        return None
    return sir._topo_order(legs)


class HappensBefore:
    """Packed-bitset transitive closure of a leg dep graph.

    ``order`` must be a valid topological order (deps first) of exactly
    the legs' ids; reachability is then computed in one reverse pass:
    ``reach[i] = union(reach[succ] | bit(succ) for succ of i)``.  Rows
    are ``ceil(n/64)`` ``uint64`` words, so the whole structure for the
    9k-leg fixture is a few MB and queries are single bit tests."""

    def __init__(self, legs: Sequence, order: Sequence[str]):
        self._pos: Dict[str, int] = {lid: i for i, lid in enumerate(order)}
        n = len(order)
        self._n = n
        words = max((n + 63) >> 6, 1)
        self._reach = np.zeros((n, words), dtype=np.uint64)
        succs: List[List[int]] = [[] for _ in range(n)]
        for l in legs:
            i = self._pos.get(l.id)
            if i is None:
                continue
            for dep in l.deps:
                j = self._pos.get(dep)
                if j is not None and j != i:
                    succs[j].append(i)
        for i in range(n - 1, -1, -1):
            row = self._reach[i]
            for j in succs[i]:
                np.bitwise_or(row, self._reach[j], out=row)
                row[j >> 6] |= _BITS[j & 63]

    def pos(self, leg_id: str) -> int:
        return self._pos[leg_id]

    def reaches(self, a: str, b: str) -> bool:
        """Is there a dep path from leg ``a`` to leg ``b`` (a strictly
        happens-before b)?"""
        ia, ib = self._pos.get(a), self._pos.get(b)
        if ia is None or ib is None or ia == ib:
            return False
        return bool(self._reach[ia, ib >> 6] & _BITS[ib & 63])

    def ordered(self, a: str, b: str) -> bool:
        """Are ``a`` and ``b`` ordered either way by happens-before?"""
        return self.reaches(a, b) or self.reaches(b, a)


def _accesses(legs: Sequence) -> Tuple[Dict[str, List], Dict[str, List]]:
    """``(readers, writers)`` per buffer, in leg emission order."""
    readers: Dict[str, List] = {}
    writers: Dict[str, List] = {}
    for l in legs:
        for b in l.reads:
            readers.setdefault(b, []).append(l)
        for b in l.writes:
            writers.setdefault(b, []).append(l)
    return readers, writers


def race_violations(ir, hb: Optional[HappensBefore] = None,
                    order: Optional[Sequence[str]] = None) -> List:
    """The race detector + leak rule + all-namespace donation race.

    Returns ``schedule_ir.Violation``s (empty on a cyclic/ambiguous
    graph — ``schedule/dep-cycle`` / ``schedule/unknown-dep`` already
    fired and no happens-before relation exists to judge against):

    * ``schedule/race-unordered-write`` (ERROR) — two legs write one
      buffer with no ordering path between them: the lowered programs
      may commit them in either order and ranks can disagree.
    * ``schedule/race-read-write`` (ERROR) — a read and a write of one
      buffer with no ordering path: the reader may observe either the
      old or the new value depending on issue timing.
    * ``schedule/buffer-leak`` (WARN) — a transient buffer written but
      never read nor donated: the sync work producing it is dead
      (persistent ``param:``/``opt:`` outputs are exempt).
    * ``schedule/read-after-donate`` (ERROR) — a donated buffer (ANY
      namespace: ``sync:``, ``param:``, ``opt:``) with a pure read
      reachable after a write: the donated input's old handle is
      deleted by then.
    """
    legs = list(ir.legs)
    if order is None:
        order = topo_order(ir)
    if order is None:
        return []
    if hb is None:
        hb = HappensBefore(legs, order)
    readers, writers = _accesses(legs)
    donated = set(ir.donated)
    out: List = []

    for buf in sorted(writers):
        ws = writers[buf]
        rs = readers.get(buf, [])
        for a, b in combinations(ws, 2):
            if a.id != b.id and not hb.ordered(a.id, b.id):
                first, second = sorted((a.id, b.id))
                out.append(sir.Violation(
                    sir.RULE_RACE_WRITE, sir.SEV_ERROR,
                    f"legs {first!r} and {second!r} both write buffer "
                    f"{buf!r} with no happens-before path between them: "
                    "the lowerings may commit the writes in either order",
                    leg=first, location=buf))
        for w in ws:
            for r in rs:
                if r.id == w.id or buf in r.writes:
                    continue    # in-place accessors are judged as writers
                if not hb.ordered(w.id, r.id):
                    out.append(sir.Violation(
                        sir.RULE_RACE_READ_WRITE, sir.SEV_ERROR,
                        f"leg {r.id!r} reads buffer {buf!r} unordered "
                        f"against the write in {w.id!r}: the read may "
                        "observe either value depending on issue timing",
                        leg=r.id, location=buf))

    for buf in sorted(writers):
        if buf in readers or buf in donated:
            continue
        if buffer_namespace(buf) in PERSISTENT_NAMESPACES:
            continue            # step outputs, accounted in the base
        last = max(writers[buf], key=lambda l: hb.pos(l.id))
        out.append(sir.Violation(
            sir.RULE_BUFFER_LEAK, sir.SEV_WARN,
            f"buffer {buf!r} is written by leg {last.id!r} but never "
            "read nor donated: the sync work producing it is dead and "
            "its bytes stay live to the end of the step",
            leg=last.id, location=buf))

    # A read strictly ordered after a write observes the NEW value —
    # safe for donation — when the reader is a link of the buffer's own
    # read-modify-write chain: its (bucket, slot) group also writes the
    # buffer (the quantized-ring error-feedback threading, where slot
    # k+1's hop 1 reads the residual slot k's gather chain wrote).  A
    # reader OUTSIDE every writing group wants the pre-donation handle,
    # which is deleted by then — the PR 3 audit case, still an ERROR.
    group_writes: Dict[str, set] = {}
    for l in legs:
        for b in l.writes:
            group_writes.setdefault(b, set()).add((l.bucket, l.slot))
    for buf in sorted(donated):
        ws = writers.get(buf, ())
        pure = [l for l in readers.get(buf, ())
                if buf not in l.writes
                and (l.bucket, l.slot) not in group_writes.get(buf, ())]
        hit = sorted((r.id for r in pure
                      if any(hb.reaches(w.id, r.id) for w in ws)),
                     key=hb.pos)
        if hit:
            out.append(sir.Violation(
                sir.RULE_READ_AFTER_DONATE, sir.SEV_ERROR,
                f"donated buffer {buf!r} is read by leg {hit[0]!r} "
                "after a write: the donated input's old handle is "
                "deleted by then — undonate it or drop the late read",
                leg=hit[0], location=buf))
    return out


# -- the liveness watermark ---------------------------------------------------

@dataclass
class WatermarkReport:
    """Per-device peak HBM of one schedule's buffer liveness.

    ``peak_bytes`` includes ``base_bytes`` (the caller's static floor:
    params + optimizer + activations); ``schedule_bytes`` is the
    transient-buffer component at the peak; ``per_slot`` maps each
    microbatch slot (−1 = end-of-step) to the peak while its legs
    execute."""

    peak_bytes: int = 0
    peak_leg: str = ""
    base_bytes: int = 0
    per_slot: Dict[int, int] = field(default_factory=dict)
    buffer_bytes: Dict[str, int] = field(default_factory=dict)

    @property
    def schedule_bytes(self) -> int:
        return self.peak_bytes - self.base_bytes

    def top_buffers(self, k: int = 8) -> List[Tuple[str, int]]:
        return sorted(self.buffer_bytes.items(),
                      key=lambda kv: (-kv[1], kv[0]))[:k]

    def to_dict(self) -> dict:
        return {
            "peak_bytes": int(self.peak_bytes),
            "peak_mib": round(self.peak_bytes / _MiB, 3),
            "peak_leg": self.peak_leg,
            "base_bytes": int(self.base_bytes),
            "schedule_bytes": int(self.schedule_bytes),
            "per_slot": {str(s): int(v)
                         for s, v in sorted(self.per_slot.items())},
            "top_buffers": [{"buffer": b, "bytes": int(n)}
                            for b, n in self.top_buffers()],
        }

    def summary(self) -> str:
        slots = ", ".join(
            f"slot {s}: {v / _MiB:.1f} MiB"
            for s, v in sorted(self.per_slot.items()))
        return (f"peak ≈ {self.peak_bytes / _MiB:.1f} MiB at leg "
                f"{self.peak_leg!r} (static base "
                f"{self.base_bytes / _MiB:.1f} MiB + schedule buffers "
                f"{self.schedule_bytes / _MiB:.1f} MiB; {slots})")


def _buffer_sizes(ir, legs) -> Dict[str, int]:
    """Per-device byte size of every transient buffer the legs touch.

    Bucket-keyed buffers resolve through the bucket nodes: ``grad:`` is
    the full f32-equivalent gradient vector, ``red:`` its reduce result
    (1/d under ZeRO-1 reduce-scatter), ``sync:`` the gradient-shaped
    f32 residual.  Per-variable legs (and hand-built programs) fall
    back to the largest wire size of a touching leg; persistent
    ``param:``/``opt:`` buffers are sized 0 here — they live in the
    static base."""
    d = max(int(ir.axes.get(MESH_AXIS_DATA, 1)), 1)
    s = max(int(getattr(ir, "num_slices", 1) or 1), 1)
    sizes: Dict[str, int] = {}
    for node in ir.buckets:
        key, nb = node["key"], int(node["nbytes"])
        sizes[f"grad:{key}"] = nb
        if node["mode"] == sir.MODE_REDUCE_SCATTER:
            # ZeRO-1 reduce result: 1/d of the bucket — except a
            # hierarchical bucket, whose slice-local RS first lands the
            # LARGER 1/(d/s) intermediate (the cross-slice exchange
            # shrinks it to 1/d afterwards); the watermark must cover
            # the honest peak.
            if node.get("hier") and s > 1 and d % s == 0 and d // s > 1:
                sizes[f"red:{key}"] = nb // (d // s)
            else:
                sizes[f"red:{key}"] = nb // d
        else:
            sizes[f"red:{key}"] = nb
        sizes[f"sync:{key}"] = int(node["padded_total"]) * 4
    for l in legs:
        for buf in tuple(l.reads) + tuple(l.writes):
            if buffer_namespace(buf) in PERSISTENT_NAMESPACES:
                sizes[buf] = 0
            elif buf not in sizes:
                sizes[buf] = int(l.nbytes)
    return sizes


def watermark(ir, *, base_bytes: int = 0,
              order: Optional[Sequence[str]] = None
              ) -> Optional[WatermarkReport]:
    """Simulate the schedule's per-device HBM watermark (module
    docstring).  Returns None when the dep graph is cyclic or ids are
    ambiguous (no topological order exists to walk)."""
    legs = list(ir.legs)
    if order is None:
        order = topo_order(ir)
    if order is None:
        return None
    if not legs:
        return WatermarkReport(peak_bytes=int(base_bytes),
                               base_bytes=int(base_bytes))
    pos = {lid: i for i, lid in enumerate(order)}
    by_id = {l.id: l for l in legs}
    n = len(order)
    readers, writers = _accesses(legs)
    donated = set(ir.donated)
    sizes = _buffer_sizes(ir, legs)

    opens = np.zeros(n, dtype=np.int64)
    closes = np.zeros(n, dtype=np.int64)
    tracked: Dict[str, int] = {}
    for buf in set(readers) | set(writers):
        size = int(sizes.get(buf, 0))
        if size <= 0:
            continue
        ns = buffer_namespace(buf)
        ws = [pos[l.id] for l in writers.get(buf, ())]
        rs = [pos[l.id] for l in readers.get(buf, ())]
        # open: first write materializes the buffer; step inputs
        # (read-only grad:) and cross-step sync: state exist from t=0.
        if not ws or ns in CROSS_STEP_NAMESPACES:
            open_at = 0
        else:
            open_at = min(ws)
        # close: the last read; donation closes at the last access
        # (aliased into its consumer), non-donated cross-step state and
        # unread (leaked) buffers stay resident to step end.
        if buf in donated:
            close_at = max(rs + ws) if (rs or ws) else n - 1
        elif ns in CROSS_STEP_NAMESPACES or not rs:
            close_at = n - 1
        else:
            close_at = max(rs)
        close_at = max(close_at, open_at)
        opens[open_at] += size
        if close_at + 1 < n:
            closes[close_at + 1] += size
        tracked[buf] = size

    cur = int(base_bytes)
    peak, peak_at = cur, 0
    per_slot: Dict[int, int] = {}
    for i in range(n):
        cur += int(opens[i]) - int(closes[i])
        slot = by_id[order[i]].slot
        if cur > per_slot.get(slot, -1):
            per_slot[slot] = cur
        if cur > peak:
            peak, peak_at = cur, i
    return WatermarkReport(
        peak_bytes=int(peak), peak_leg=order[peak_at],
        base_bytes=int(base_bytes), per_slot=per_slot,
        buffer_bytes=tracked)


def fact_base_bytes(facts: Sequence, axes: Dict[str, int]) -> int:
    """Coarse mesh-free static base for watermark gating in the
    strategy search: parameters replicated per device plus Adam-shaped
    optimizer moments (2× params), with ZeRO-1 (``reduce_scatter``) and
    PS (weight-update-sharded) facts cutting their moments to 1/d.
    Deliberately simple — the search's OOM gate needs a floor the
    schedule buffers stack on, not the memory pass's eval_shape
    accounting (which needs a captured optimizer and a mesh)."""
    d = max(int(axes.get(MESH_AXIS_DATA, 1)), 1)
    total = 0.0
    for f in facts:
        nb = float(f.nbytes)
        total += nb                                   # params, replicated
        opt = 2.0 * nb                                # Adam mu + nu
        if f.sync_kind == "PS" or f.sync_mode == "reduce_scatter":
            opt /= d
        total += opt
    return int(total)


def watermark_for_facts(facts: Sequence, ir,
                        axes: Dict[str, int]) -> Optional[WatermarkReport]:
    """The search/tuner gate: the liveness watermark of ``ir`` stacked
    on the coarse fact base — what ``AutoStrategy(search="beam")``
    compares against ``ResourceSpec.hbm_gb`` to reject OOM schedules
    before pricing, and what the ``ScheduleTuner`` checks before
    hot-swapping onto a winner."""
    return watermark(ir, base_bytes=fact_base_bytes(facts, axes))
