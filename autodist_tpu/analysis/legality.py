"""Sharding-legality pass: does the plan lower onto the mesh at all?

This pass owns the *projection*: it mirrors the compiler's lowering rules
(``strategy/compiler.py``) symbolically over ``{axis: size}`` — no mesh,
no devices — filling ``ctx.plans`` with :class:`PlanLite` records that
the later passes (memory, collectives, precision) consume.  Given a
:class:`CompiledStrategy` it instead audits the *actual* ``VarPlan``s,
which also catches hand-built plan drift the compiler never saw.

Rules (docs/analysis.md):

* ``legality/invalid-partitioner`` (ERROR) — unparseable partitioner,
  more than one active axis, axis beyond the variable's rank, or a
  dim < 2: the compiler raises ``ValueError`` on these mid-build.
* ``legality/indivisible-partition`` (ERROR) — a partitioned dim neither
  divides its mesh axis nor is covered by pad-to-divisible sharding
  (padding would at least double the variable, so the compiler silently
  replicates — the plan that runs is NOT the plan that was asked for).
* ``legality/padded-partition`` (INFO) — indivisible dim covered by the
  pad-to-divisible path (pad rows zero-masked each step).
* ``legality/unknown-mesh-axis`` (ERROR) — a spec names an axis the mesh
  does not carry (hand-built plans only; the projection cannot emit it).
* ``legality/duplicate-mesh-axis`` (ERROR) — one spec uses the same mesh
  axis on two tensor dims.
* ``legality/structural-axis-claimed`` (WARN) — a partitioner claims a
  pipeline/expert structural axis; the compiler drops the claim.
* ``legality/structural-indivisible`` (WARN) — a stage/expert stack dim
  not divisible by its mesh axis; the compiler keeps it replicated.
* ``legality/ar-partition-colocated`` (INFO) — an AllReduce partitioner
  on a mesh without a model axis: shards stay colocated with replicas
  (the reference layout), i.e. the partitioner is a no-op.
* ``legality/batch-axis-mismatch`` (ERROR) — compiled batch axes missing
  from the mesh, or a trainable plan whose gradient is NOT reduced over
  the data axis while the batch is sharded over it (silent divergence).
* ``legality/batch-indivisible`` (WARN) — a provided batch leaf whose
  leading dim does not divide the data axis (the step will replicate it).
* ``legality/mesh-hint-mismatch`` (WARN) — the strategy's
  ``graph_config.mesh_axes`` hint names axes the mesh does not carry.
* ``legality/zero1-fallback`` (WARN) — ``sync="reduce_scatter"`` (ZeRO-1)
  requested for a variable the bucketed path cannot absorb (partitioned,
  padded, or non-bucketable compressor); it falls back to a per-variable
  collective with replicated optimizer state.  Shares
  ``bucketing.bucket_drop_reason`` with the runtime.
* ``legality/slice-mismatch`` (ERROR) — the resource spec's ``num_slices``
  does not divide the mesh's device count: a two-tier topology would
  leave a ragged slice.  Shares ``resource_spec.slice_mismatch_reason``
  with the session-build fail-fast, so the CLI and ``AutoDist`` can
  never disagree.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from autodist_tpu.analysis.analyzer import (
    AnalysisContext,
    PlanLite,
    register_pass,
)
from autodist_tpu.analysis.diagnostics import Diagnostic, Severity, diag
from autodist_tpu.const import (
    MESH_AXIS_DATA,
    MESH_AXIS_EXPERT,
    MESH_AXIS_MODEL,
    MESH_AXIS_PIPE,
)
from autodist_tpu.graph_item import VarInfo


def _structural_axes(var: VarInfo) -> Tuple[int, ...]:
    axes = []
    if var.pipeline:
        axes.append(0)
    if var.expert:
        axes.append(1 if var.pipeline else 0)
    return tuple(axes)


def _partition(var: VarInfo, axis: Optional[int], target: Optional[str],
               mesh_axes: Dict[str, int], diags: List[Diagnostic]
               ) -> Tuple[Dict[int, str], Optional[Tuple[int, int]]]:
    """Mirror of ``StrategyCompiler._partition_spec`` over axis sizes."""
    if axis is None or target is None:
        return {}, None
    size = int(mesh_axes.get(target, 1))
    if size <= 1:
        return {}, None
    dim = var.shape[axis]
    if dim % size:
        padded = -(-dim // size) * size
        if padded >= 2 * dim:
            diags.append(diag(
                "legality/indivisible-partition", Severity.ERROR,
                f"dim {axis} (size {dim}) cannot shard over {target!r} "
                f"(size {size}): padding to {padded} would at least double "
                "the variable, so the compiler silently replicates it",
                var=var.name, location=f"dim{axis}->{target}",
                fix=f"use a dim divisible by {size}, shrink the {target!r} "
                    "axis, or drop the partitioner"))
            return {}, None
        diags.append(diag(
            "legality/padded-partition", Severity.INFO,
            f"dim {axis} (size {dim}) pads to {padded} for even {target!r} "
            "sharding (pad rows zero-masked each step)",
            var=var.name, location=f"dim{axis}->{target}"))
        return {axis: target}, (axis, padded)
    return {axis: target}, None


def _apply_structural(var: VarInfo, placement: Dict[int, str],
                      mesh_axes: Dict[str, int],
                      diags: List[Diagnostic]) -> None:
    """Mirror of ``_apply_structural_specs``: pipe on dim 0, expert on
    the next structural dim, when they divide."""
    def one(dim: int, axis_name: str, label: str) -> None:
        size = int(mesh_axes.get(axis_name, 1))
        if size <= 1 or len(var.shape) <= dim:
            return
        if var.shape[dim] % size:
            diags.append(diag(
                "legality/structural-indivisible", Severity.WARN,
                f"{label} dim {dim} (size {var.shape[dim]}) is not "
                f"divisible by the {axis_name!r} axis (size {size}); the "
                "compiler keeps it replicated",
                var=var.name, location=f"dim{dim}->{axis_name}",
                fix=f"make the {label} stack a multiple of {size}"))
            return
        placement[dim] = axis_name

    if var.pipeline:
        one(0, MESH_AXIS_PIPE, "pipeline")
    if var.expert:
        one(1 if var.pipeline else 0, MESH_AXIS_EXPERT, "expert")


def _wus_opt(var: VarInfo, placement: Dict[int, str],
             mesh_axes: Dict[str, int]) -> Dict[int, str]:
    """Mirror of ``_wus_opt_spec``: shard the largest free dim over
    ``data`` when it divides evenly."""
    d = int(mesh_axes.get(MESH_AXIS_DATA, 1))
    if d <= 1 or not var.shape:
        return dict(placement)
    if MESH_AXIS_DATA in placement.values():
        return dict(placement)
    best, best_dim = None, 0
    for i, dim in enumerate(var.shape):
        if i not in placement and dim % d == 0 and dim > best_dim:
            best, best_dim = i, dim
    if best is None:
        return dict(placement)
    out = dict(placement)
    out[best] = MESH_AXIS_DATA
    return out


def _zero1_effective(mode: str, placement: Dict[int, str],
                     pad: Optional[Tuple[int, int]], compressor: str,
                     d: int, diags: List[Diagnostic],
                     var: VarInfo) -> bool:
    """Does the requested ``sync="reduce_scatter"`` actually shard this
    var's weight update?  Shares the bucket-eligibility rule with the
    runtime (``bucketing.bucket_drop_reason``) so the lint cannot drift;
    emits the fallback WARN the explicit path logs at trace time."""
    if mode != "reduce_scatter" or d <= 1:
        return False
    from autodist_tpu.kernel.synchronization.bucketing import (
        bucket_drop_reason,
    )
    why = bucket_drop_reason(sorted(placement.items()), pad is not None,
                             compressor or "NoneCompressor")
    if why is None:
        return True
    diags.append(diag(
        "legality/zero1-fallback", Severity.WARN,
        f"sync='reduce_scatter' requested but this variable cannot join "
        f"a flat gradient bucket ({why}); it falls back to its "
        "per-variable/per-shard collective with replicated optimizer "
        "state",
        var=var.name,
        fix="drop the partitioner or use a bucketable compressor"))
    return False


def _lower_from_strategy(ctx: AnalysisContext
                         ) -> Tuple[Dict[str, PlanLite], List[Diagnostic]]:
    from autodist_tpu.strategy.base import (
        AllReduceSynchronizerConfig,
        PSSynchronizerConfig,
    )
    from autodist_tpu.strategy.compiler import parse_partitioner

    diags: List[Diagnostic] = []
    axes = ctx.axes
    gi = ctx.graph_item
    known = {v.name: v for v in gi.info.variables}
    model_axis = MESH_AXIS_MODEL \
        if int(axes.get(MESH_AXIS_MODEL, 1)) > 1 else None
    d = int(axes.get(MESH_AXIS_DATA, 1))
    grad_axes = (MESH_AXIS_DATA,) if d > 1 else ()
    plans: Dict[str, PlanLite] = {}

    for node in ctx.strategy.node_config:
        var = known.get(node.var_name)
        if var is None or not var.trainable:
            continue  # dead / frozen nodes: the sync pass reports them
        try:
            axis, num_shards = parse_partitioner(node.partitioner)
        except ValueError as e:
            diags.append(diag(
                "legality/invalid-partitioner", Severity.ERROR, str(e),
                var=var.name, location=node.partitioner,
                fix="use one active axis, e.g. \"1,4,1\""))
            axis, num_shards = None, 1
        if axis is not None and axis in _structural_axes(var):
            diags.append(diag(
                "legality/structural-axis-claimed", Severity.WARN,
                f"partitioner {node.partitioner!r} claims structural dim "
                f"{axis} (owned by the pipe/expert stacking); the compiler "
                "drops the claim",
                var=var.name, location=f"dim{axis}",
                fix="partition a non-structural dim"))
            axis = None
        if axis is not None and (len(var.shape) <= axis
                                 or var.shape[axis] < 2):
            diags.append(diag(
                "legality/invalid-partitioner", Severity.ERROR,
                f"partitioner {node.partitioner!r} is invalid for shape "
                f"{var.shape}: the compiler raises on it",
                var=var.name, location=node.partitioner,
                fix="partition an existing dim of size >= 2"))
            axis = None

        sync = node.synchronizer
        if isinstance(sync, AllReduceSynchronizerConfig):
            placement: Dict[int, str] = {}
            pad = None
            if axis is not None:
                if model_axis is None:
                    diags.append(diag(
                        "legality/ar-partition-colocated", Severity.INFO,
                        f"AllReduce partitioner {node.partitioner!r} on a "
                        "mesh with no model axis: shards stay colocated "
                        "with replicas (the partitioner is a layout no-op)",
                        var=var.name, location=node.partitioner))
                else:
                    placement, pad = _partition(var, axis, model_axis,
                                                axes, diags)
            _apply_structural(var, placement, axes, diags)
            mode = getattr(sync, "sync", "all_reduce") or "all_reduce"
            plans[var.name] = PlanLite(
                var=var, sync_kind="AllReduce", placement=placement,
                opt_placement=dict(placement), pad=pad,
                compressor=sync.compressor or "NoneCompressor",
                fused=bool(getattr(sync, "fused", False)), group=sync.group,
                grad_reduce_axes=grad_axes,
                sync_mode=mode,
                zero1=_zero1_effective(mode, placement, pad,
                                       sync.compressor, d, diags, var),
                bucket_bytes=int(getattr(sync, "bucket_bytes", 0) or 0),
                overlap=getattr(sync, "overlap", "auto") or "auto",
                hier=bool(getattr(sync, "hier", False)))
        elif isinstance(sync, PSSynchronizerConfig):
            shard_axis = model_axis or (
                MESH_AXIS_DATA if axis is not None else None)
            placement, pad = _partition(var, axis, shard_axis, axes, diags)
            if (var.sparse and axis is None and var.shape
                    and not (var.pipeline or var.expert)):
                placement, pad = _partition(
                    var, 0, model_axis or MESH_AXIS_DATA, axes, diags)
            if var.pipeline or var.expert:
                _apply_structural(var, placement, axes, diags)
                opt = _wus_opt(var, placement, axes)
            else:
                opt = dict(placement) if placement \
                    else _wus_opt(var, placement, axes)
            plans[var.name] = PlanLite(
                var=var, sync_kind="PS", placement=placement,
                opt_placement=opt, pad=pad, staleness=sync.staleness,
                grad_reduce_axes=grad_axes)
        # nodes with no/unknown synchronizer: the sync pass errors on them

    for name, var in known.items():
        if name in plans:
            continue
        if var.trainable:
            placement = {}
            _apply_structural(var, placement, axes, diags)
            plans[name] = PlanLite(
                var=var, sync_kind="AllReduce", placement=placement,
                opt_placement=dict(placement), grad_reduce_axes=grad_axes,
                synthesized=True)
        else:
            plans[name] = PlanLite(var=var, sync_kind=None)
    return plans, diags


def _spec_axes(spec) -> List[Tuple[int, str]]:
    """PartitionSpec → [(dim, axis_name)] with tuple entries flattened."""
    out: List[Tuple[int, str]] = []
    for dim, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        names = [entry] if isinstance(entry, str) else list(entry)
        out.extend((dim, str(n)) for n in names)
    return out


def _audit_spec(ctx: AnalysisContext, var: VarInfo, spec, pad,
                label: str, diags: List[Diagnostic]) -> Dict[int, str]:
    """Validate one lowered spec; return its placement dict."""
    pairs = _spec_axes(spec)
    placement: Dict[int, str] = {}
    seen: Dict[str, int] = {}
    for dim, axis_name in pairs:
        if axis_name in seen:
            diags.append(diag(
                "legality/duplicate-mesh-axis", Severity.ERROR,
                f"{label} spec uses mesh axis {axis_name!r} on dims "
                f"{seen[axis_name]} and {dim}",
                var=var.name, location=axis_name,
                fix="each mesh axis may shard at most one tensor dim"))
            continue
        seen[axis_name] = dim
        if axis_name not in ctx.axes:
            diags.append(diag(
                "legality/unknown-mesh-axis", Severity.ERROR,
                f"{label} spec names mesh axis {axis_name!r}; the mesh "
                f"carries {sorted(ctx.axes)}",
                var=var.name, location=axis_name,
                fix="add the axis to the mesh or fix the spec"))
            continue
        size = int(ctx.axes[axis_name])
        if dim >= len(var.shape):
            diags.append(diag(
                "legality/unknown-mesh-axis", Severity.ERROR,
                f"{label} spec shards dim {dim} of a rank-"
                f"{len(var.shape)} variable",
                var=var.name, location=f"dim{dim}"))
            continue
        phys = pad[1] if (pad is not None and pad[0] == dim) \
            else var.shape[dim]
        if size > 1 and phys % size:
            diags.append(diag(
                "legality/indivisible-partition", Severity.ERROR,
                f"{label} dim {dim} (size {phys}) is not divisible by "
                f"mesh axis {axis_name!r} (size {size}) and no pad plan "
                "covers it",
                var=var.name, location=f"dim{dim}->{axis_name}",
                fix="pad the dim, change the axis size, or replicate"))
        placement[dim] = axis_name
    return placement


def _lower_from_compiled(ctx: AnalysisContext
                         ) -> Tuple[Dict[str, PlanLite], List[Diagnostic]]:
    diags: List[Diagnostic] = []
    gi = ctx.graph_item
    known = {v.name: v for v in gi.info.variables}
    plans: Dict[str, PlanLite] = {}

    for name, vp in ctx.compiled.var_plans.items():
        var = known.get(name)
        if var is None:
            diags.append(diag(
                "legality/unknown-mesh-axis", Severity.WARN,
                "compiled plan names a variable absent from the program "
                "catalog", var=name,
                fix="rebuild the plan against the current GraphItem"))
            continue
        pad = (vp.pad_axis, vp.pad_dim) if vp.pad_axis is not None else None
        if pad is not None:
            diags.append(diag(
                "legality/padded-partition", Severity.INFO,
                f"dim {pad[0]} (size {var.shape[pad[0]]}) pads to "
                f"{pad[1]} for even sharding (pad rows zero-masked)",
                var=name, location=f"dim{pad[0]}"))
        placement = _audit_spec(ctx, var, vp.param_spec, pad, "param", diags)
        opt_placement = _audit_spec(ctx, var, vp.opt_spec, pad, "opt", diags)
        if (vp.partition_axis is not None and vp.num_shards > 1
                and vp.partition_axis not in placement):
            diags.append(diag(
                "legality/indivisible-partition", Severity.ERROR,
                f"the strategy partitioned dim {vp.partition_axis} "
                f"({vp.num_shards} shards) but the lowered plan replicates "
                "it (indivisible dim, pad not worthwhile): the plan that "
                "runs is not the plan that was asked for",
                var=name, location=f"dim{vp.partition_axis}",
                fix="fix the partitioner or accept replication explicitly"))
        for ax in vp.grad_reduce_axes:
            if ax not in ctx.axes:
                diags.append(diag(
                    "legality/unknown-mesh-axis", Severity.ERROR,
                    f"grad_reduce_axes names unknown mesh axis {ax!r}",
                    var=name, location=ax))
        mode = getattr(vp, "sync_mode", "all_reduce") or "all_reduce"
        d = int(ctx.axes.get(MESH_AXIS_DATA, 1))
        plans[name] = PlanLite(
            var=var, sync_kind=vp.sync_kind, placement=placement,
            opt_placement=opt_placement, pad=pad,
            compressor=vp.compressor or "NoneCompressor",
            fused=bool(vp.fused), group=vp.group, staleness=vp.staleness,
            grad_reduce_axes=tuple(vp.grad_reduce_axes),
            sync_mode=mode,
            zero1=_zero1_effective(mode, placement, pad, vp.compressor,
                                   d, diags, var),
            bucket_bytes=int(getattr(vp, "bucket_bytes", 0) or 0),
            overlap=getattr(vp, "overlap", "auto") or "auto",
            hier=bool(getattr(vp, "hier", False)))

    for name, var in known.items():
        if name not in plans:
            plans[name] = PlanLite(
                var=var, sync_kind="AllReduce" if var.trainable else None,
                synthesized=var.trainable)
    return plans, diags


def _check_batch_layout(ctx: AnalysisContext) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    d = ctx.data_axis_size
    if ctx.compiled is not None:
        for ax in ctx.compiled.batch_axes:
            if ax not in ctx.axes:
                diags.append(diag(
                    "legality/batch-axis-mismatch", Severity.ERROR,
                    f"batch_axes names mesh axis {ax!r}; the mesh carries "
                    f"{sorted(ctx.axes)}", location=str(ax)))
        if d > 1 and MESH_AXIS_DATA in ctx.compiled.batch_axes:
            for name, plan in ctx.plans.items():
                if (plan.sync_kind is not None and not plan.synthesized
                        and MESH_AXIS_DATA not in plan.grad_reduce_axes):
                    diags.append(diag(
                        "legality/batch-axis-mismatch", Severity.ERROR,
                        "batch is sharded over 'data' but this plan never "
                        "reduces its gradient over 'data': replicas would "
                        "silently diverge", var=name,
                        fix="add 'data' to grad_reduce_axes"))
        elif d > 1 and MESH_AXIS_DATA not in ctx.compiled.batch_axes:
            diags.append(diag(
                "legality/batch-axis-mismatch", Severity.WARN,
                f"mesh has a data axis of size {d} but the batch is not "
                "sharded over it: every chip computes the full batch",
                fix="set batch_axes=('data',) or drop the data axis"))
    if ctx.batch is not None and d > 1:
        import jax
        for leaf in jax.tree_util.tree_leaves(ctx.batch):
            shape = tuple(getattr(leaf, "shape", ()) or ())
            if shape and shape[0] % d:
                diags.append(diag(
                    "legality/batch-indivisible", Severity.WARN,
                    f"batch leaf with leading dim {shape[0]} does not "
                    f"divide the data axis (size {d}); it will be "
                    "replicated on every chip",
                    location=f"batch[{shape}]",
                    fix=f"pad the global batch to a multiple of {d}"))
                break  # one finding is enough; the step warns per leaf
    return diags


def _check_mesh_hint(ctx: AnalysisContext) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    hint = getattr(ctx.strategy.graph_config, "mesh_axes", None) or {}
    for name, size in hint.items():
        if name not in ctx.axes:
            diags.append(diag(
                "legality/mesh-hint-mismatch", Severity.WARN,
                f"strategy mesh hint names axis {name!r} (size {size}) "
                f"but the mesh carries {sorted(ctx.axes)}",
                location=str(name)))
    return diags


def _stamp_numerics(ctx: AnalysisContext, plans) -> None:
    """Project the program-level numerics config onto each plan: is the
    fused guard active for this variable's sync, and what loss scale
    rides its gradient?  Shares the runtime's exact resolution
    (``numerics.loss_scale.resolve_loss_scale``) so the ``numerics/*``
    precision rules can never drift from what the step would build."""
    cfg = getattr(ctx.graph_item, "numerics", None)
    if cfg is None or not cfg.guard:
        return
    from autodist_tpu.numerics.loss_scale import resolve_loss_scale

    dtypes = [str(p.var.dtype) for p in plans.values()]
    ls = resolve_loss_scale(cfg.loss_scale, dtypes)
    peak = 0.0 if ls is None else (ls.max_scale if ls.dynamic else ls.init)
    for plan in plans.values():
        if plan.sync_kind is not None:
            plan.guard = True
            plan.loss_scale = float(peak)


def _check_slices(ctx: AnalysisContext) -> List[Diagnostic]:
    """The ``legality/slice-mismatch`` rule: a multi-slice spec whose
    slice count cannot tile this mesh's device count.  Same pure rule
    (``slice_mismatch_reason``) as the ``ResourceSpec`` fail-fast —
    here it additionally catches spec-vs-mesh drift (a spec validated
    against its own chip count, analyzed against different axes)."""
    from autodist_tpu.resource_spec import slice_mismatch_reason

    spec = ctx.resource_spec
    if spec is None:
        return []
    s = int(getattr(spec, "num_slices", 1) or 1)
    total = 1
    for size in ctx.axes.values():
        total *= max(int(size), 1)
    reason = slice_mismatch_reason(total, s)
    if reason is None:
        return []
    return [diag(
        "legality/slice-mismatch", Severity.ERROR, reason,
        location=f"axes={dict(ctx.axes)}",
        fix="pick a num_slices that divides the device count, or "
            "resize the mesh to a multiple of the slice count")]


@register_pass("legality")
def run(ctx: AnalysisContext) -> List[Diagnostic]:
    if ctx.compiled is not None:
        plans, diags = _lower_from_compiled(ctx)
    else:
        plans, diags = _lower_from_strategy(ctx)
    _stamp_numerics(ctx, plans)
    ctx.plans = plans
    diags += _check_batch_layout(ctx)
    diags += _check_mesh_hint(ctx)
    diags += _check_slices(ctx)
    return diags
