"""Elastic-resume pass: validate resuming a checkpoint on a resized mesh.

Runs only when the analyzer is given elastic provenance —
``analyze(..., elastic={"from_axes": {...}[, "buckets": [...]]})`` from
:func:`autodist_tpu.resilience.elastic.preflight_elastic`, or the CLI's
``--elastic-from data=8`` — and answers "can the plan written at the OLD
axes resume at the NEW ones, and what does it cost?"  The companion
re-checks come for free from the normal pipeline running against the
NEW mesh: ``sync/ring-degenerate`` re-fires if the shrunken data axis
can no longer host a ring schedule, and the memory pass re-estimates
HBM with optimizer state at 1/M.

Rules (docs/resilience.md):

* ``elastic/axis-resize`` (INFO) — the reshard plan: how many ZeRO-1
  buckets reslice, the old→new padded lengths, and the per-device flat
  optimizer-shard growth factor.  Emitted whenever the data axis
  changes; the reshard itself is always exact (only zero padding
  changes — ``resilience/elastic.py``).
* ``elastic/bucket-mismatch`` (ERROR) — the checkpoint's recorded
  bucket layout (``"buckets"``) does not match what this program plans:
  membership/dtype/element-count drift (changed ``bucket_bytes`` or
  variable catalog) makes the flat shards unrecoverable by slicing.
* ``elastic/hbm-grows`` (WARN) — the data axis SHRANK under ZeRO-1
  plans: every surviving device now holds a larger (1/M > 1/N) slice of
  the flat optimizer state; read the memory pass breakdown on the new
  mesh before committing.
* ``elastic/sync-state-reset`` (WARN) — compressor state (error-feedback
  residuals etc.) exists and an axis changed size: per-device sync
  state cannot be resharded and reinitializes, so resume is approximate
  ON THE COMPRESSOR PATH (params/opt stay exact).
"""
from __future__ import annotations

from typing import List

from autodist_tpu.analysis.analyzer import AnalysisContext, register_pass
from autodist_tpu.analysis.diagnostics import Diagnostic, Severity, diag

_MiB = float(1 << 20)


def _plan_buckets(ctx: AnalysisContext, d: int):
    """Re-plan the ZeRO-1 buckets at data-axis size ``d`` using the
    SAME pure planner the runtime executes (bucketing.assign_buckets),
    so this pass can never drift from the lowering."""
    import numpy as np

    from autodist_tpu.kernel.synchronization import bucketing

    entries = []
    cap = 0
    for name, plan in ctx.plans.items():
        if not getattr(plan, "zero1", False):
            continue
        entries.append((name, tuple(plan.var.shape),
                        str(np.dtype(plan.var.dtype)),
                        plan.compressor or "NoneCompressor",
                        int(plan.group), bucketing.MODE_REDUCE_SCATTER))
        cap = max(cap, int(getattr(plan, "bucket_bytes", 0) or 0))
    if not entries:
        return []
    return bucketing.assign_buckets(
        entries, bucket_bytes=cap or bucketing.DEFAULT_BUCKET_BYTES,
        shard_divisor=max(d, 1))


@register_pass("elastic")
def run(ctx: AnalysisContext) -> List[Diagnostic]:
    info = getattr(ctx, "elastic", None)
    if not info:
        return []
    import numpy as np

    diags: List[Diagnostic] = []
    from_axes = {str(k): int(v)
                 for k, v in (info.get("from_axes") or {}).items()}
    old_d = max(from_axes.get("data", 1), 1)
    new_d = max(ctx.data_axis_size, 1)

    old_buckets = _plan_buckets(ctx, old_d)
    new_buckets = _plan_buckets(ctx, new_d)

    recorded = info.get("buckets")
    if recorded:
        from autodist_tpu.resilience.elastic import layout_mismatch

        why = layout_mismatch(recorded, new_buckets)
        if why is not None:
            diags.append(diag(
                "elastic/bucket-mismatch", Severity.ERROR,
                f"checkpoint bucket layout cannot map onto this plan: "
                f"{why}",
                fix="resume with the same bucket_bytes and variable "
                    "catalog the checkpoint was written with (bucket "
                    "membership is axis-independent, so only config "
                    "drift causes this)"))

    changed_axes = {a for a in set(from_axes) | set(ctx.axes)
                    if from_axes.get(a, 1) != ctx.axes.get(a, 1)}
    if old_d != new_d and old_buckets:
        new_by_key = {b.key: b for b in new_buckets}
        moved = sum(b.nbytes for b in old_buckets)
        resized = sum(1 for b in old_buckets
                      if b.key in new_by_key
                      and new_by_key[b.key].padded_total != b.padded_total)
        # per-device flat shard bytes: sum(padded/d) * itemsize
        def shard_bytes(buckets, d):
            return sum(b.padded_total // max(d, 1)
                       * np.dtype(b.dtype).itemsize for b in buckets)
        old_pd = shard_bytes(old_buckets, old_d)
        new_pd = shard_bytes(new_buckets, new_d)
        diags.append(diag(
            "elastic/axis-resize", Severity.INFO,
            f"resuming data={old_d} -> data={new_d}: {len(old_buckets)} "
            f"ZeRO-1 bucket(s) ({moved / _MiB:.1f} MiB of flat optimizer "
            f"state) reslice 1/{new_d}, {resized} re-padded; per-device "
            f"flat shard {old_pd / _MiB:.2f} -> {new_pd / _MiB:.2f} MiB "
            f"per state leaf — exact (only zero padding changes)",
            location=f"data={old_d}->{new_d}"))
        if new_d < old_d:
            diags.append(diag(
                "elastic/hbm-grows", Severity.WARN,
                f"the data axis shrank {old_d} -> {new_d}: each surviving "
                f"device holds a {old_d / new_d:.2g}x larger slice of the "
                "ZeRO-1 optimizer state (see memory/hbm-breakdown on the "
                "new mesh)",
                fix="confirm the per-device HBM budget on the shrunken "
                    "mesh before resuming"))
    elif old_d != new_d:
        diags.append(diag(
            "elastic/axis-resize", Severity.INFO,
            f"resuming data={old_d} -> data={new_d}: no ZeRO-1 flat "
            "state; params and tree optimizer state reshard natively",
            location=f"data={old_d}->{new_d}"))

    if changed_axes and any(
            (p.compressor or "NoneCompressor") != "NoneCompressor"
            for p in ctx.plans.values()):
        diags.append(diag(
            "elastic/sync-state-reset", Severity.WARN,
            f"mesh axes {sorted(changed_axes)} changed size and compressor "
            "state exists: per-device residuals reinitialize on resume — "
            "exact on params/optimizer, approximate on the compressed "
            "gradient stream for the first steps",
            fix="checkpoint at a step where residual magnitude is small, "
                "or accept the transient"))
    return diags
