"""Search support pass surface: legality pruning hooks + explain report.

The strategy search (:mod:`autodist_tpu.strategy.search`) prunes every
candidate through the analyzer's pure ``legality``/``sync`` rules BEFORE
paying for IR construction and pricing — no mesh, no tracing, one
projection per candidate.  This module owns that hook
(:func:`project_plans` / :func:`facts_for_candidate`) plus the human
surface: :func:`search_report` runs the beam search and packages the
top-K candidates with their per-leg-kind cost breakdown and the exact
legality rule that killed each pruned branch — what
``python -m autodist_tpu.analysis <model> --search-report`` prints.
"""
from __future__ import annotations

from dataclasses import replace as _replace
from typing import Dict, List, Optional, Tuple

from autodist_tpu.graph_item import GraphItem


def project_plans(strategy, graph_item: GraphItem,
                  axes: Dict[str, int], *,
                  resource_spec=None) -> Tuple[dict, Optional[str]]:
    """Run the analyzer's pure legality+sync passes over one candidate.

    Returns ``(plans, prune_reason)``: the PlanLite projection keyed by
    variable name, and — when any ERROR rule fired — a
    ``"rule: message"`` string naming the first one (the search's
    prune verdict; the explain surface prints it verbatim)."""
    from autodist_tpu.analysis.analyzer import (
        AnalysisContext,
        PASS_REGISTRY,
        _load_passes,
    )

    # One context, two passes — analyze() would work too, but building
    # the context directly keeps the projection (ctx.plans) in hand for
    # fact construction without a second lowering.
    _load_passes()
    ctx = AnalysisContext(strategy=strategy, graph_item=graph_item,
                          axes={str(k): int(v) for k, v in axes.items()},
                          resource_spec=resource_spec)
    diags = list(PASS_REGISTRY["legality"](ctx))
    diags += PASS_REGISTRY["sync"](ctx)
    from autodist_tpu.analysis.diagnostics import Severity
    for d in diags:
        if d.severity == Severity.ERROR:
            return ctx.plans, f"{d.rule}: {d.message}"
    return ctx.plans, None


def facts_for_candidate(strategy, graph_item: GraphItem,
                        axes: Dict[str, int], *,
                        sparse_rows_hint: int = 4096,
                        resource_spec=None):
    """The search's prune+project step for one candidate strategy.

    Returns ``(facts, priced_facts, guard, prune_reason)``:

    * ``facts`` — canonical :class:`PlanFact` list in catalog order
      (the IR/fingerprint substrate);
    * ``priced_facts`` — the pricing shadow: sparse PS variables shrink
      to their touched rows (``min(sparse_rows_hint, vocab)`` — the
      Parallax rule the plan-level ``estimate_cost`` already applies),
      so the leg-priced estimate sees the honest wire; identical object
      to ``facts`` when nothing shrinks;
    * ``guard`` — whether the numerics guard is active on any plan;
    * ``prune_reason`` — the legality/sync ERROR that kills the branch,
      or None."""
    from autodist_tpu.kernel.synchronization import schedule_ir as sir

    plans, prune = project_plans(strategy, graph_item, axes,
                                 resource_spec=resource_spec)
    if prune is not None:
        return [], [], False, prune
    facts, priced, guard = [], [], False
    shrunk = False
    for var in graph_item.info.variables:       # catalog order
        plan = plans.get(var.name)
        if plan is None or plan.sync_kind is None or not var.trainable:
            continue
        fact = sir.fact_from_planlite(var.name, plan)
        facts.append(fact)
        guard = guard or bool(getattr(plan, "guard", False))
        if var.sparse and plan.sync_kind == "PS" and fact.shape:
            rows = min(int(sparse_rows_hint), int(fact.shape[0] or 1))
            priced.append(_replace(
                fact, shape=(rows,) + tuple(fact.shape[1:])))
            shrunk = True
        else:
            priced.append(fact)
    if not facts:
        return [], [], False, ("sync/empty-plan: no trainable variable "
                               "lowers to a sync collective")
    return facts, (priced if shrunk else facts), guard, None


def search_report(graph_item: GraphItem, resource_spec, *,
                  axes: Optional[Dict[str, int]] = None,
                  top_k: int = 5, space=None, constants=None) -> dict:
    """Run the beam search and package the explain report: top-K
    candidates with per-leg-kind cost breakdown, every pruned branch
    with the rule that killed it, and the search provenance."""
    from autodist_tpu.strategy.search import beam_search, resolve_axes

    if axes is None:
        axes = resolve_axes(graph_item, resource_spec)
    result = beam_search(graph_item, resource_spec, axes=axes,
                         space=space, constants=constants)
    report = result.to_dict(top_k)
    report["axes"] = dict(axes)
    return report


def format_search_report(report: dict) -> str:
    """Human rendering of :func:`search_report` (the CLI table)."""
    lines: List[str] = []
    axes = ",".join(f"{k}={v}" for k, v in sorted(
        (report.get("axes") or {}).items()))
    lines.append(
        f"strategy search: {report['n_evals']} candidate(s) priced, "
        f"{report['n_pruned']} pruned, {report['rounds']} round(s), "
        f"{report['wall_time_s']:.2f} s on mesh [{axes}]"
        f"{' (calibrated)' if report.get('calibrated') else ''}")
    best = report.get("best")
    if best is None:
        lines.append("no candidate survived legality pruning")
        return "\n".join(lines)
    lines.append("")
    lines.append("top candidates (cheapest first):")
    for i, c in enumerate(report.get("top") or []):
        marker = "*" if c["fingerprint"] == best["fingerprint"] else " "
        lines.append(
            f" {marker} #{i + 1} {c['name']}  cost {c['cost_ms']:.4f} ms  "
            f"exposed {c['exposed_wire_bytes'] / 1e6:.2f} MB  "
            f"{c['num_collectives']} collectives  [{c['fingerprint']}]")
        per_kind = c.get("per_kind_ms") or {}
        if per_kind:
            breakdown = "  ".join(
                f"{k}={v:.4f}ms" for k, v in sorted(
                    per_kind.items(), key=lambda kv: -kv[1]))
            lines.append(f"      per-leg-kind: {breakdown}")
    pruned = report.get("pruned") or []
    if pruned:
        lines.append("")
        lines.append(f"pruned branches ({len(pruned)}):")
        for c in pruned:
            lines.append(f"   {c['name']}: {c.get('pruned_by')}")
    return "\n".join(lines)
