"""Strategy IR: the per-variable distribution plan.

Shape parity with the reference protobufs (``autodist/proto/strategy.proto:30-69``,
``synchronizers.proto:24-57``): a Strategy is a list of per-variable node
configs — each an exclusive choice of synchronizer (PS or AllReduce) plus an
optional partitioner string ``"1,2,1"`` with per-shard part configs — and a
graph config listing the replica devices.  Serialization is JSON (the
reference used binary protos written to ``/tmp/autodist/strategies/<id>``,
``strategy/base.py:78-99``); ids are UTC timestamps, same scheme.
"""
from __future__ import annotations

import datetime
import json
import os
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from autodist_tpu.const import DEFAULT_STRATEGY_DIR
from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.utils import logging


@dataclass
class PSSynchronizerConfig:
    """Parameter-server sync (reference synchronizers.proto:40-57).

    On TPU, PS semantics compile to *weight-update sharding*: gradients are
    reduce-scattered to the shard that owns the variable's optimizer state,
    the update runs sharded, and fresh params are all-gathered — the XLA-era
    equivalent of "aggregate on the PS device and broadcast"
    (cf. arxiv 2004.13336)."""

    reduction_destination: str = ""  # DeviceSpec string, e.g. "10.0.0.1:CPU:0"
    local_replication: bool = False  # proxy-variable caching (reference ProxyVariable)
    sync: bool = True
    staleness: int = 0

    kind: str = "PS"


@dataclass
class AllReduceSynchronizerConfig:
    """All-reduce sync (reference synchronizers.proto:24-39).

    ``spec`` keeps the reference's AUTO/RING/NCCL vocabulary as a hint; on
    TPU all variants lower to ``psum`` over the data axis and XLA picks the
    ICI algorithm.  ``group`` merges small variables into one fused collective
    (the reference's scoped-allocator chunking, all_reduce_strategy.py:21-90):
    on the GSPMD path it sets XLA's all-reduce combiner threshold; with
    ``fused`` the program routes through the explicit shard_map path where
    each group is concatenated into ONE ``pmean``.

    ``sync`` picks the collective lowering of the gradient reduction:
    ``"all_reduce"`` (default — every replica gets the averaged gradient
    and applies the update redundantly) or ``"reduce_scatter"`` — ZeRO-1
    weight-update sharding (arXiv:2004.13336): each gradient bucket is
    reduce-scattered, the optimizer update runs on the local
    optimizer-state shard only (state HBM / data-axis size), and fresh
    parameters are all-gathered.  ``bucket_bytes`` caps the size of the
    dtype-grouped gradient buckets the explicit path concatenates into
    one collective (0 = the kernel default,
    ``bucketing.DEFAULT_BUCKET_BYTES``); any non-zero value routes the
    program through the explicit shard_map path.

    ``overlap`` schedules the bucket collectives against compute
    (``kernel/synchronization/overlap.py``): ``"auto"`` (default) turns
    on whatever overlaps without changing numerics — accumulation
    pipelining when ``accum_steps > 1`` and the bucket is uncompressed,
    ring decomposition for large buckets, reverse-order ZeRO-1 param
    prefetch; ``"pipeline"`` / ``"ring"`` request one mechanism,
    ``"full"`` all of them, ``"none"`` the phase-serial schedule.  A
    non-default value routes the program through the explicit path."""

    spec: str = "AUTO"  # AUTO | RING | NCCL (hint only on TPU)
    compressor: str = "NoneCompressor"  # NoneCompressor | HorovodCompressor | HorovodCompressorEF
    group: int = 0
    fused: bool = False  # explicit concat-and-pmean group fusion
    sync: str = "all_reduce"  # all_reduce | reduce_scatter (ZeRO-1)
    bucket_bytes: int = 0     # gradient-bucket size cap (0 = default)
    overlap: str = "auto"     # auto | none | pipeline | ring | full
    # Two-tier hierarchical sync: reduce-scatter within each ICI slice,
    # exchange across slices over DCN, all-gather back.  Only takes
    # effect on a multi-slice ResourceSpec (num_slices > 1) whose slice
    # count tiles the data axis; routes through the explicit path.
    hier: bool = False

    kind: str = "AllReduce"


def _synchronizer_from_dict(d: dict):
    kind = d.get("kind")
    if kind == "PS":
        return PSSynchronizerConfig(**{k: v for k, v in d.items() if k != "kind"})
    if kind == "AllReduce":
        return AllReduceSynchronizerConfig(**{k: v for k, v in d.items() if k != "kind"})
    raise ValueError(f"unknown synchronizer kind {kind!r}")


@dataclass
class VarConfig:
    """Per-variable node config (reference strategy.proto Node, :41-58)."""

    var_name: str
    synchronizer: object = None  # PSSynchronizerConfig | AllReduceSynchronizerConfig
    # "a,b,c" — shard counts per tensor axis; at most one entry > 1
    # (reference PartitionerConfig, kernel/partitioner.py:38-150).
    partitioner: str = ""
    part_config: List["VarConfig"] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "var_name": self.var_name,
            "synchronizer": asdict(self.synchronizer) if self.synchronizer else None,
            "partitioner": self.partitioner,
            "part_config": [p.to_dict() for p in self.part_config],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "VarConfig":
        return cls(
            var_name=d["var_name"],
            synchronizer=_synchronizer_from_dict(d["synchronizer"])
            if d.get("synchronizer") else None,
            partitioner=d.get("partitioner", ""),
            part_config=[cls.from_dict(p) for p in d.get("part_config", [])],
        )


@dataclass
class GraphConfig:
    """Whole-graph config (reference strategy.proto:60-68): replica devices.

    On TPU this also carries the logical mesh axes the strategy wants, which
    the compiler intersects with the physical mesh."""

    replicas: List[str] = field(default_factory=list)  # DeviceSpec strings
    mesh_axes: Dict[str, int] = field(default_factory=dict)


class Strategy:
    """A distribution plan: ``node_config`` per variable + ``graph_config``.

    Parity: reference ``Strategy`` wrapper (strategy/base.py:28-99)."""

    def __init__(self, node_config: Optional[List[VarConfig]] = None,
                 graph_config: Optional[GraphConfig] = None,
                 strategy_id: Optional[str] = None):
        self.node_config: List[VarConfig] = node_config or []
        self.graph_config: GraphConfig = graph_config or GraphConfig()
        # Same id scheme as the reference: UTC timestamp (strategy/base.py:40).
        self.id = strategy_id or datetime.datetime.now(
            datetime.timezone.utc).strftime("%Y%m%dT%H%M%SM%f")
        self.path = os.path.join(DEFAULT_STRATEGY_DIR, self.id)

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "node_config": [n.to_dict() for n in self.node_config],
            "graph_config": {
                "replicas": list(self.graph_config.replicas),
                "mesh_axes": dict(self.graph_config.mesh_axes),
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Strategy":
        return cls(
            node_config=[VarConfig.from_dict(n) for n in d["node_config"]],
            graph_config=GraphConfig(
                replicas=d["graph_config"].get("replicas", []),
                mesh_axes=d["graph_config"].get("mesh_axes", {})),
            strategy_id=d["id"],
        )

    def serialize(self, path: Optional[str] = None) -> str:
        """Write to disk so workers can load the chief-built plan
        (reference strategy/base.py:78-87)."""
        path = path or self.path
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=1)
        logging.debug("Strategy %s serialized to %s", self.id, path)
        return path

    @classmethod
    def deserialize(cls, strategy_id: str, base_dir: Optional[str] = None) -> "Strategy":
        path = os.path.join(base_dir or DEFAULT_STRATEGY_DIR, strategy_id)
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_dict(json.load(f))

    def node_for(self, var_name: str) -> Optional[VarConfig]:
        for n in self.node_config:
            if n.var_name == var_name:
                return n
        return None

    def __repr__(self) -> str:  # pragma: no cover
        kinds = {}
        for n in self.node_config:
            k = getattr(n.synchronizer, "kind", None) or "None"
            if n.partitioner:
                k = "Partitioned" + k
            kinds[k] = kinds.get(k, 0) + 1
        return f"Strategy(id={self.id}, vars={len(self.node_config)}, {kinds})"


class StrategyBuilder:
    """Base builder (reference strategy/base.py:102-117): map
    ``(GraphItem, ResourceSpec) -> Strategy``."""

    def build(self, graph_item: GraphItem, resource_spec: ResourceSpec) -> Strategy:
        raise NotImplementedError

    # -- shared helpers ----------------------------------------------------
    @staticmethod
    def replica_devices(resource_spec: ResourceSpec) -> List[str]:
        """All compute devices: TPU chips, or CPUs of chip-less nodes
        (reference ps_strategy.py:45-60)."""
        return [d.name_string() for d in resource_spec.devices]

    @staticmethod
    def reduction_device_names(resource_spec: ResourceSpec) -> List[str]:
        """Candidate PS destinations: one CPU device per node (the reference
        places PS shards on node CPUs, ps_lb_strategy.py:42-62)."""
        return [d.name_string() for d in resource_spec.cpu_devices]
