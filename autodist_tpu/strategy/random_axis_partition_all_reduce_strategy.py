"""RandomAxisPartitionAR: shard along a RANDOM non-1 axis, all-reduce shards.

Parity: reference
``autodist/strategy/random_axis_partition_all_reduce_strategy.py:26-141`` —
a seeded RNG picks any axis with length > 1 (axis 0 forced for sparse
variables, since embedding shards must follow the vocab axis).
"""
from __future__ import annotations

import random

from autodist_tpu.strategy.partitioned_all_reduce_strategy import PartitionedAR
from autodist_tpu.strategy.partition_utils import smallest_divisor_gt_one


class RandomAxisPartitionAR(PartitionedAR):
    def __init__(self, chunk_size: int = 128, seed: int = 600,
                 all_reduce_spec: str = "AUTO", compressor: str = "NoneCompressor"):
        super().__init__(chunk_size=chunk_size, all_reduce_spec=all_reduce_spec,
                         compressor=compressor)
        self._rng = random.Random(seed)

    def _choose_axis_and_shards(self, var, cap: int):
        if var.sparse:
            candidates = [0] if var.shape and var.shape[0] > 1 else []
        else:
            candidates = [i for i, d in enumerate(var.shape) if d > 1]
        if not candidates:
            return None, None
        axis = self._rng.choice(candidates)
        n = smallest_divisor_gt_one(var.shape[axis])
        if n is None or n > cap:
            return None, None
        return axis, n
