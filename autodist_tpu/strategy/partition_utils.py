"""Shared partitioning arithmetic for strategy builders.

Parity: the shard-count heuristics of the reference's partitioned strategies
(``autodist/strategy/partitioned_ps_strategy.py:28-135`` — smallest divisor,
``uneven_partition_ps_strategy.py:28-135`` — first non-divisor)."""
from __future__ import annotations

from typing import Optional, Sequence

from autodist_tpu.graph_item import VarInfo


def smallest_divisor_gt_one(n: int) -> Optional[int]:
    """Smallest divisor of ``n`` greater than 1, or None if n <= 1."""
    if n <= 1:
        return None
    i = 2
    while i * i <= n:
        if n % i == 0:
            return i
        i += 1
    return n  # prime


def first_non_divisor(n: int) -> Optional[int]:
    """Smallest integer > 1 that does NOT divide ``n`` (uneven sharding)."""
    if n <= 1:
        return None
    if n == 2:  # every int >2 is a non-divisor; reference picks the smallest
        return None  # cannot shard a length-2 axis unevenly into >1 useful parts
    i = 2
    while n % i == 0:
        i += 1
    return i if i <= n else None


def partition_str(shape: Sequence[int], axis: int, num_shards: int) -> str:
    """Build the ``"1,4,1"`` partitioner string (one active axis only,
    reference kernel/partitioner.py:38-150)."""
    parts = ["1"] * len(shape)
    parts[axis] = str(num_shards)
    return ",".join(parts)


def partitionable(var: VarInfo, axis: int = 0) -> bool:
    """A variable can be partitioned along ``axis`` if that dim exists and
    has length > 1 (reference partitioned_ps_strategy.py:90-110 skips scalars
    and dim-1 axes; its control-flow-op exclusion has no JAX analog — there
    is no graph to collide with)."""
    return len(var.shape) > axis and var.shape[axis] > 1


def greedy_load_balance(sizes, num_bins: int):
    """Assign items to the currently least-loaded bin, in input order —
    the reference's byte-size load balancing (ps_lb_strategy.py:91-117).

    Returns (assignments, loads): assignments[i] = bin index of item i.
    """
    loads = [0.0] * num_bins
    assignment = []
    for s in sizes:
        b = loads.index(min(loads))
        assignment.append(b)
        loads[b] += float(s)
    return assignment, loads
