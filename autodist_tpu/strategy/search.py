"""Leg-calibrated strategy search: beam search over per-variable plans.

``AutoStrategy(search=True)`` RANKS a fixed candidate list; this module
SEARCHES the configuration space the paper's strategy layer exists for
(the Automap argument, arXiv:2112.02958: cost-model-guided search over a
pruned partition space recovers expert-level parallelism decisions).
The space is per-variable

    partition axis x sync mode (AR / RS+ZeRO-1 / PS) x overlap
    (none/pipeline/ring/full) x compressor (none/int8/fp8/PowerSGD)
    x bucket_bytes x expert placement (expert-flagged variables only:
    expert-parallel over the ``expert`` mesh axis — 1/E grads plus the
    dispatch/combine all_to_all pair — vs dense replication)
    x two-tier hier sync (multi-slice specs only: slice-local ICI legs
    plus one cross-slice DCN leg per bucket vs the flat collective)

encoded as one :class:`VarGene` per trainable variable; a search state
is the gene map, i.e. a :class:`~autodist_tpu.kernel.synchronization.
schedule_ir.PlanFact` set.  Every candidate is:

(a) **pruned by shardlint legality** before any pricing — the analyzer's
    pure ``legality``/``sync`` rules via
    :func:`autodist_tpu.analysis.search.project_plans` (no mesh, no
    tracing, milliseconds per candidate); the pruning rule id is kept so
    the explain surface can say WHY a branch died;
(b) **lowered to its schedule IR** via ``ir_from_facts`` — the SAME
    planner the runtime executes — and gated by the static schedule
    verifier (an unverifiable schedule can never win on price) AND by
    the liveness HBM watermark (``analysis/dataflow.py``) against the
    spec's ``hbm_gb``: an OOM-by-construction schedule is pruned
    before pricing, with the watermark peak in its prune verdict;
(c) **priced leg-by-leg** through ``estimate_ir_cost`` with the
    discovered ``calibration.json`` constants, so fused-vs-unfused,
    quantized-vs-f32, and pipelined-vs-exposed alternatives are priced
    as the distinct legs they are (sparse PS variables are priced at
    their touched-row wire size — the Parallax rule — through a pricing
    shadow of the fact set; the canonical facts keep the full shape so
    fingerprints stay honest).

The search itself is a seeded beam search: the shipped fixed builders'
strategies are projected into gene maps as seeds (which makes the
winner's estimated cost <= every fixed builder's by construction), each
round expands every beam state through a deterministic move list
(single-variable sync/partition flips on the largest variables, global
compressor/overlap/bucket_bytes knob turns), candidates deduplicate on
their fact fingerprint, and the beam keeps the ``beam_width`` cheapest
by ``(cost, name)`` — fully deterministic run-to-run.  Budgets: rounds,
evaluations, and wall time (``wall_budget_s``).

Everything here is mesh-free (the analyzer's ``{axis: size}`` world);
nothing traces or compiles.  The self-tuning loop around it lives in
:mod:`autodist_tpu.strategy.tuner`.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import (
    AllReduceSynchronizerConfig,
    GraphConfig,
    PSSynchronizerConfig,
    Strategy,
    StrategyBuilder,
    VarConfig,
)
from autodist_tpu.strategy.partition_utils import (
    greedy_load_balance,
    partition_str,
)
from autodist_tpu.utils import logging

#: sync-mode gene values.
SYNC_AR = "ar"            # AllReduce, sync="all_reduce"
SYNC_RS = "rs"            # AllReduce, sync="reduce_scatter" (ZeRO-1)
SYNC_PS = "ps"            # PS / weight-update sharding
SYNC_MODES = (SYNC_AR, SYNC_RS, SYNC_PS)


@dataclass(frozen=True)
class VarGene:
    """One variable's point in the search space."""

    sync: str = SYNC_AR
    partition: Optional[int] = None      # PS partition axis (None = unpartitioned)
    compressor: str = "NoneCompressor"
    overlap: str = "auto"
    bucket_bytes: int = 0
    #: expert-parallel execution for an expert-flagged variable: shard
    #: the expert stack over the ``expert`` mesh axis (grads shrink to
    #: 1/E, the schedule gains the dispatch/combine all_to_all pair) vs
    #: dense replication (full-size grads, no a2a).  Ignored — and kept
    #: False — for variables without the catalog ``expert`` flag.
    expert: bool = False
    #: two-tier ICI+DCN sync (AllReduce-family genes only): slice-local
    #: reduce-scatter + one cross-slice DCN leg + slice-local gather.
    #: Only meaningful on a multi-slice spec — the move generator never
    #: toggles it when ``resource_spec.num_slices <= 1``.
    hier: bool = False

    def key(self) -> Tuple:
        return (self.sync, self.partition, self.compressor, self.overlap,
                self.bucket_bytes, self.expert, self.hier)


@dataclass
class SearchSpace:
    """The searched axes and the search budgets.

    ``compressors`` defaults to full precision only — a quantizing wire
    is an accuracy opt-in, exactly like ``AutoStrategy``'s existing
    rule; callers (and ``AutoStrategy(search="beam",
    compressor=...)``) widen it explicitly."""

    sync_modes: Tuple[str, ...] = SYNC_MODES
    compressors: Tuple[str, ...] = ("NoneCompressor",)
    overlaps: Tuple[str, ...] = ("none", "pipeline", "ring", "full")
    bucket_bytes: Tuple[int, ...] = (0, 256 << 10, 1 << 20, 4 << 20)
    beam_width: int = 6
    max_rounds: int = 4
    max_evals: int = 400
    wall_budget_s: float = 25.0
    #: per-variable moves only touch the N largest variables — the move
    #: that matters is almost always on the byte-dominant tensors.
    max_var_moves: int = 8
    sparse_rows_hint: int = 4096
    compute_time_s: float = 0.0
    #: MoE routing overrides for expert-parallel candidates; None reads
    #: the shared env defaults (``AUTODIST_MOE_CAPACITY_FACTOR`` /
    #: ``AUTODIST_MOE_TOKENS``) exactly like the runtime lowering.
    moe_capacity_factor: Optional[float] = None
    moe_tokens_per_group: Optional[int] = None


@dataclass
class CandidateEval:
    """One evaluated (or pruned) candidate."""

    name: str
    fingerprint: str = ""
    cost_s: Optional[float] = None
    exposed_wire_bytes: float = 0.0
    num_collectives: int = 0
    per_kind_ms: Dict[str, float] = field(default_factory=dict)
    pruned_by: Optional[str] = None      # "rule: message" when pruned
    genes: Optional[Tuple[Tuple[str, VarGene], ...]] = None

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "fingerprint": self.fingerprint,
            "cost_ms": round(self.cost_s * 1e3, 6)
            if self.cost_s is not None else None,
            "exposed_wire_bytes": self.exposed_wire_bytes,
            "num_collectives": self.num_collectives,
            "per_kind_ms": {k: round(v, 6)
                            for k, v in sorted(self.per_kind_ms.items())},
        }
        if self.pruned_by:
            d["pruned_by"] = self.pruned_by
        if self.genes is not None:
            d["genes"] = {name: {"sync": g.sync, "partition": g.partition,
                                 "compressor": g.compressor,
                                 "overlap": g.overlap,
                                 "bucket_bytes": g.bucket_bytes,
                                 "expert": g.expert,
                                 "hier": g.hier}
                          for name, g in self.genes}
        return d


@dataclass
class SearchResult:
    """What :func:`beam_search` returns."""

    best: Optional[CandidateEval]
    best_strategy: Optional[Strategy]
    evaluated: List[CandidateEval] = field(default_factory=list)
    pruned: List[CandidateEval] = field(default_factory=list)
    n_evals: int = 0
    rounds: int = 0
    wall_time_s: float = 0.0
    calibrated: bool = False

    def top(self, k: int = 5) -> List[CandidateEval]:
        """The k cheapest evaluated candidates, ``(cost, name)``-ordered
        (the deterministic ranking order of the whole search)."""
        ranked = sorted((e for e in self.evaluated if e.cost_s is not None),
                        key=lambda e: (e.cost_s, e.name))
        return ranked[:k]

    def to_dict(self, top_k: int = 5) -> dict:
        return {
            "best": self.best.to_dict() if self.best else None,
            "top": [e.to_dict() for e in self.top(top_k)],
            "pruned": [e.to_dict() for e in self.pruned],
            "n_evals": self.n_evals,
            "n_pruned": len(self.pruned),
            "rounds": self.rounds,
            "wall_time_s": round(self.wall_time_s, 3),
            "calibrated": self.calibrated,
        }


# -- genes <-> Strategy -------------------------------------------------------

def genes_from_strategy(strategy: Strategy,
                        graph_item: GraphItem
                        ) -> Tuple[Tuple[str, VarGene], ...]:
    """Project a built Strategy into the search's gene encoding (the
    seed path: every fixed builder enters the beam through here)."""
    from autodist_tpu.strategy.compiler import parse_partitioner

    out: List[Tuple[str, VarGene]] = []
    for var in graph_item.trainable_var_infos:
        node = strategy.node_for(var.name)
        sync = getattr(node, "synchronizer", None) if node else None
        axis = None
        if node is not None and node.partitioner:
            try:
                axis, _ = parse_partitioner(node.partitioner)
            except ValueError:
                axis = None
        if isinstance(sync, PSSynchronizerConfig):
            gene = VarGene(sync=SYNC_PS, partition=axis)
        elif isinstance(sync, AllReduceSynchronizerConfig):
            mode = getattr(sync, "sync", "all_reduce") or "all_reduce"
            gene = VarGene(
                sync=SYNC_RS if mode == "reduce_scatter" else SYNC_AR,
                partition=None,
                compressor=sync.compressor or "NoneCompressor",
                overlap=getattr(sync, "overlap", "auto") or "auto",
                bucket_bytes=int(getattr(sync, "bucket_bytes", 0) or 0),
                hier=bool(getattr(sync, "hier", False)))
        else:
            gene = VarGene()
        if getattr(var, "expert", False):
            # Seeds mirror the runtime lowering, which shards every
            # expert-flagged stack over the expert axis and emits the
            # dispatch/combine a2a pair; the dense alternative enters
            # the beam through the all:expert=off move.
            gene = replace(gene, expert=True)
        out.append((var.name, gene))
    return tuple(out)


def strategy_from_genes(genes: Sequence[Tuple[str, VarGene]],
                        graph_item: GraphItem,
                        resource_spec: ResourceSpec) -> Strategy:
    """Materialize a gene map as a Strategy the compiler can lower."""
    infos = {v.name: v for v in graph_item.trainable_var_infos}
    ps_devices = StrategyBuilder.reduction_device_names(resource_spec)
    ps_vars = [name for name, g in genes if g.sync == SYNC_PS]
    assignment, _ = greedy_load_balance(
        [infos[n].byte_size for n in ps_vars], len(ps_devices))
    destination = {n: ps_devices[b] for n, b in zip(ps_vars, assignment)}

    node_config: List[VarConfig] = []
    for name, g in genes:
        var = infos.get(name)
        if var is None:
            continue
        if g.sync == SYNC_PS:
            partitioner = ""
            if (not var.sparse and var.shape and g.partition is not None
                    and 0 <= g.partition < len(var.shape)):
                axis = g.partition
                shards = min(var.shape[axis], resource_spec.num_chips)
                if shards >= 2:
                    partitioner = partition_str(var.shape, axis, shards)
            node_config.append(VarConfig(
                var_name=name,
                synchronizer=PSSynchronizerConfig(
                    reduction_destination=destination[name]),
                partitioner=partitioner))
        else:
            node_config.append(VarConfig(
                var_name=name,
                synchronizer=AllReduceSynchronizerConfig(
                    compressor=g.compressor,
                    sync="reduce_scatter" if g.sync == SYNC_RS
                    else "all_reduce",
                    bucket_bytes=g.bucket_bytes,
                    overlap=g.overlap,
                    hier=g.hier)))
    return Strategy(
        node_config=node_config,
        graph_config=GraphConfig(
            replicas=StrategyBuilder.replica_devices(resource_spec)))


# -- evaluation: prune -> lower -> verify -> price ----------------------------

def evaluate_candidate(name: str,
                       genes: Sequence[Tuple[str, VarGene]],
                       graph_item: GraphItem,
                       resource_spec: ResourceSpec,
                       axes: Dict[str, int],
                       constants=None, *,
                       sparse_rows_hint: int = 4096,
                       compute_time_s: float = 0.0,
                       seen_facts: Optional[set] = None,
                       moe_capacity_factor: Optional[float] = None,
                       moe_tokens_per_group: Optional[int] = None
                       ) -> Tuple[Optional[CandidateEval],
                                  Optional[Strategy]]:
    """Run one candidate through the prune/lower/verify/price pipeline.
    Returns ``(eval, strategy)``; a pruned candidate has
    ``eval.pruned_by`` set and ``strategy=None``.  ``seen_facts`` is
    the dedupe set of fact fingerprints: a candidate whose facts match
    one already priced returns ``(None, None)`` BEFORE any IR is built
    (``schedule_ir.facts_fingerprint`` — the builder is pure, so equal
    inputs mean byte-identical IRs)."""
    from autodist_tpu.analysis.search import facts_for_candidate
    from autodist_tpu.kernel.synchronization import schedule_ir as sir
    from autodist_tpu.strategy.cost_model import estimate_ir_cost

    genes = tuple(genes)
    strategy = strategy_from_genes(genes, graph_item, resource_spec)
    facts, priced_facts, guard, prune = facts_for_candidate(
        strategy, graph_item, axes, sparse_rows_hint=sparse_rows_hint,
        resource_spec=resource_spec)
    if prune is not None:
        return CandidateEval(name=name, pruned_by=prune, genes=genes), None
    accum = int(getattr(graph_item, "accum_steps", 1) or 1)
    num_slices = int(getattr(resource_spec, "num_slices", 1) or 1)
    # Expert-parallel lens: a gene with expert=True keeps its variable
    # on the runtime's expert-sharded lowering — the schedule gains the
    # dispatch/combine a2a pair (and its capacity transient, which the
    # watermark gate below sees) while the grad collective shrinks to
    # the 1/E local expert shard in the pricing shadow.  expert=False
    # densifies: full-size grads, no a2a legs.
    from autodist_tpu.const import MESH_AXIS_EXPERT
    expert_on = {n for n, g in genes if g.expert}
    e_ax = int(axes.get(MESH_AXIS_EXPERT, 1))
    moe: tuple = ()
    if expert_on:
        moe = tuple(sir.moe_facts_from_vars(
            [v for v in graph_item.info.variables
             if not getattr(v, "expert", False) or v.name in expert_on],
            axes=dict(axes), capacity_factor=moe_capacity_factor,
            tokens_per_group=moe_tokens_per_group))
    if expert_on and e_ax > 1:
        from dataclasses import replace as _dreplace
        evars = {v.name: v for v in graph_item.info.variables
                 if getattr(v, "expert", False)}
        shrunk, changed = [], False
        for f in priced_facts:
            v = evars.get(f.name)
            if v is not None and f.name in expert_on and f.shape:
                dim = 1 if getattr(v, "pipeline", False) else 0
                if dim < len(f.shape) and int(f.shape[dim]) > 1:
                    sh = list(f.shape)
                    sh[dim] = max(1, int(sh[dim]) // e_ax)
                    f = _dreplace(f, shape=tuple(sh))
                    changed = True
            shrunk.append(f)
        if changed:
            priced_facts = shrunk
    fact_fp = sir.facts_fingerprint(facts, axes=dict(axes),
                                    accum_steps=accum, guard=guard,
                                    moe=moe, num_slices=num_slices)
    if seen_facts is not None:
        if fact_fp in seen_facts:
            return None, None
        seen_facts.add(fact_fp)
    ir = sir.ir_from_facts(facts, axes=dict(axes), accum_steps=accum,
                           guard=guard, moe=moe, num_slices=num_slices)
    errs = sir.errors(sir.verify(ir))
    if errs:
        v = errs[0]
        return CandidateEval(
            name=name, fingerprint=ir.fingerprint(),
            pruned_by=f"{v.rule}: {v.message}", genes=genes), None
    # OOM gate BEFORE pricing (docs/strategies.md "Search"): the
    # liveness watermark of this candidate's schedule, stacked on the
    # coarse fact base, against the spec's per-chip HBM — a schedule
    # that cannot fit is rejected here, where legality pruning already
    # happens, instead of winning on wire cost and OOMing at step 1.
    hbm = getattr(resource_spec, "hbm_bytes_per_chip", None)
    if hbm:
        from autodist_tpu.analysis import dataflow
        wm = dataflow.watermark_for_facts(facts, ir, dict(axes))
        if wm is not None and wm.peak_bytes > hbm:
            return CandidateEval(
                name=name, fingerprint=ir.fingerprint(),
                pruned_by=(
                    f"{dataflow.RULE_WATERMARK_EXCEEDS}: schedule "
                    f"watermark peak ≈ {wm.peak_bytes / (1 << 20):.1f} "
                    f"MiB at leg {wm.peak_leg!r} exceeds the "
                    f"{hbm / (1 << 20):.1f} MiB per-chip HBM budget"),
                genes=genes), None
    # Pricing shadow: sparse PS facts shrink to touched rows (the
    # Parallax rule) so the leg-priced estimate sees the honest wire.
    priced_ir = ir if priced_facts is facts else sir.ir_from_facts(
        priced_facts, axes=dict(axes), accum_steps=accum, guard=guard,
        moe=moe, num_slices=num_slices)
    from autodist_tpu.strategy.cost_model import DCN_BANDWIDTH
    dcn_bw = getattr(resource_spec, "dcn_bytes_per_s", None) \
        or DCN_BANDWIDTH
    report = estimate_ir_cost(priced_ir, constants=constants,
                              compute_time_s=compute_time_s,
                              dcn_bandwidth=dcn_bw)
    return CandidateEval(
        name=name, fingerprint=ir.fingerprint(),
        cost_s=float(report.time_s),
        exposed_wire_bytes=float(report.exposed_wire_bytes),
        num_collectives=int(report.num_collectives),
        per_kind_ms={k: v * 1e3 for k, v in report.per_kind.items()},
        genes=genes), strategy


def _seed_builders() -> List[Tuple[str, StrategyBuilder]]:
    """The fixed builders whose strategies seed the beam (every one of
    them, so the search result can never be worse than the ranked list
    under the same pricing)."""
    from autodist_tpu.strategy import (
        AllReduce, AutoStrategy, Parallax, PartitionedAR, PartitionedPS,
        PS, PSLoadBalancing, RandomAxisPartitionAR, UnevenPartitionedPS,
        Zero1)

    return [
        ("AutoStrategy", AutoStrategy()),
        ("PSLoadBalancing", PSLoadBalancing()),
        ("PS", PS()),
        ("PartitionedPS", PartitionedPS()),
        ("UnevenPartitionedPS", UnevenPartitionedPS()),
        ("AllReduce", AllReduce()),
        ("PartitionedAR", PartitionedAR()),
        ("RandomAxisPartitionAR", RandomAxisPartitionAR()),
        ("Parallax", Parallax()),
        ("Zero1", Zero1()),
    ]


def _moves(genes: Tuple[Tuple[str, VarGene], ...],
           graph_item: GraphItem,
           space: SearchSpace,
           num_slices: int = 1
           ) -> List[Tuple[str, Tuple[Tuple[str, VarGene], ...]]]:
    """The deterministic neighbor list of one beam state: global knob
    turns first (they move the most bytes), then single-variable flips
    on the byte-dominant variables."""
    out: List[Tuple[str, Tuple[Tuple[str, VarGene], ...]]] = []
    by_name = dict(genes)
    infos = {v.name: v for v in graph_item.trainable_var_infos}

    def with_all(tag: str, fn) -> None:
        new = tuple((n, fn(n, g)) for n, g in genes)
        if new != genes:
            out.append((tag, new))

    # Global sync-mode sweeps (sparse variables keep PS under a global
    # PS move only; a global AR/RS move densifies them knowingly).
    for mode in space.sync_modes:
        with_all(f"all:sync={mode}",
                 lambda n, g, m=mode: replace(g, sync=m))
    # Global compressor / overlap / bucket_bytes knobs (AllReduce-family
    # genes only; PS genes ignore them).
    for comp in space.compressors:
        with_all(f"all:compressor={comp}",
                 lambda n, g, c=comp: replace(g, compressor=c)
                 if g.sync != SYNC_PS else g)
    for ov in space.overlaps:
        with_all(f"all:overlap={ov}",
                 lambda n, g, o=ov: replace(g, overlap=o)
                 if g.sync != SYNC_PS else g)
    for bb in space.bucket_bytes:
        with_all(f"all:bucket_bytes={bb}",
                 lambda n, g, b=bb: replace(g, bucket_bytes=b)
                 if g.sync != SYNC_PS else g)
    # Two-tier hierarchy toggle: meaningful only on multi-slice specs
    # (PS genes ignore it — single-slice candidates never grow the
    # gene, so flat fingerprints stay stable).
    if num_slices > 1:
        for flag in (True, False):
            with_all(f"all:hier={'on' if flag else 'off'}",
                     lambda n, g, f=flag: replace(g, hier=f)
                     if g.sync != SYNC_PS else g)
    # Expert-parallel toggle: only expert-flagged variables move (an
    # expert bit on a dense variable is meaningless and would only
    # bloat the dedupe space).
    if any(getattr(infos[n], "expert", False) for n, _ in genes):
        for flag in (True, False):
            with_all(f"all:expert={'on' if flag else 'off'}",
                     lambda n, g, f=flag: replace(g, expert=f)
                     if getattr(infos[n], "expert", False) else g)

    # Per-variable flips on the largest variables.
    big = sorted((n for n, _ in genes),
                 key=lambda n: (-infos[n].byte_size, n))[:space.max_var_moves]
    for n in big:
        g = by_name[n]
        for mode in space.sync_modes:
            if mode == g.sync:
                continue
            new = tuple((m, replace(gg, sync=mode) if m == n else gg)
                        for m, gg in genes)
            out.append((f"{n}:sync={mode}", new))
        if g.sync == SYNC_PS and not infos[n].sparse:
            shape = infos[n].shape
            axes_to_try = sorted(range(len(shape)),
                                 key=lambda i: (-shape[i], i))[:2] + [None]
            for ax in axes_to_try:
                if ax == g.partition:
                    continue
                new = tuple((m, replace(gg, partition=ax) if m == n else gg)
                            for m, gg in genes)
                out.append((f"{n}:partition={ax}", new))
    return out


def resolve_axes(graph_item: GraphItem,
                 resource_spec: ResourceSpec) -> Dict[str, int]:
    """The mesh axes the search prunes and prices against — the
    analyzer's own default resolution (spec mesh hint, else pure data
    parallelism over the spec's chips)."""
    from autodist_tpu.const import MESH_AXIS_DATA

    axes = dict(getattr(resource_spec, "mesh_hint", None) or {})
    if not axes:
        axes = {MESH_AXIS_DATA: max(resource_spec.num_chips, 1)}
    return {str(k): int(v) for k, v in axes.items()}


def beam_search(graph_item: GraphItem, resource_spec: ResourceSpec, *,
                axes: Optional[Dict[str, int]] = None,
                space: Optional[SearchSpace] = None,
                constants=None,
                extra_seeds: Sequence[Tuple[str, Strategy]] = ()
                ) -> SearchResult:
    """Search the per-variable plan space (module docstring).

    ``constants`` is a ``telemetry.calibration.LegCalibration``; None
    discovers ``calibration.json`` from the environment exactly like
    ``estimate_ir_cost`` does.  ``extra_seeds`` lets callers (the tuner)
    inject the currently-running strategy as a seed so a re-search can
    keep it when it still wins."""
    from autodist_tpu.telemetry import emit_event
    from autodist_tpu.telemetry.calibration import load_default_calibration

    t0 = time.perf_counter()
    space = space or SearchSpace()
    if constants is None:
        constants = load_default_calibration()
    if axes is None:
        axes = resolve_axes(graph_item, resource_spec)

    result = SearchResult(best=None, best_strategy=None,
                          calibrated=constants is not None)
    seen_facts: set = set()                  # fact fingerprints priced
    seen_genes: set = set()

    def over_budget() -> bool:
        return (result.n_evals >= space.max_evals
                or time.perf_counter() - t0 >= space.wall_budget_s)

    def consider(name: str, genes) -> Optional[CandidateEval]:
        gkey = tuple(g.key() for _, g in genes)
        if gkey in seen_genes:
            return None
        seen_genes.add(gkey)
        result.n_evals += 1
        ev, strategy = evaluate_candidate(
            name, genes, graph_item, resource_spec, axes, constants,
            sparse_rows_hint=space.sparse_rows_hint,
            compute_time_s=space.compute_time_s, seen_facts=seen_facts,
            moe_capacity_factor=space.moe_capacity_factor,
            moe_tokens_per_group=space.moe_tokens_per_group)
        if ev is None:                   # identical plan, different route
            return None
        if ev.pruned_by is not None:
            result.pruned.append(ev)
            emit_event("search/pruned", candidate=name, rule=ev.pruned_by)
            return None
        result.evaluated.append(ev)
        emit_event("search/candidate", candidate=name,
                   fingerprint=ev.fingerprint,
                   cost_ms=round(ev.cost_s * 1e3, 6))
        if result.best is None or (ev.cost_s, ev.name) < (
                result.best.cost_s, result.best.name):
            result.best = ev
            result.best_strategy = strategy
        return ev

    # Seeds: every fixed builder + caller-injected strategies.
    for name, strategy in list(extra_seeds):
        consider(f"seed:{name}", genes_from_strategy(strategy, graph_item))
    for name, builder in _seed_builders():
        if over_budget():
            break
        try:
            strategy = builder.build(graph_item, resource_spec)
        except Exception as e:      # a builder that cannot express this
            logging.info("search: seed %s failed to build (%s)", name, e)
            continue
        consider(f"seed:{name}", genes_from_strategy(strategy, graph_item))

    # Beam rounds.
    beam: List[CandidateEval] = sorted(
        result.evaluated, key=lambda e: (e.cost_s, e.name)
    )[:space.beam_width]
    for rnd in range(space.max_rounds):
        if over_budget() or not beam:
            break
        result.rounds = rnd + 1
        improved = False
        frontier: List[CandidateEval] = []
        for state in beam:
            if over_budget():
                break
            for tag, genes in _moves(
                    state.genes, graph_item, space,
                    int(getattr(resource_spec, "num_slices", 1) or 1)):
                if over_budget():
                    break
                ev = consider(f"{state.name}+{tag}", genes)
                if ev is not None:
                    frontier.append(ev)
                    if (ev.cost_s, ev.name) < (beam[0].cost_s, beam[0].name):
                        improved = True
        beam = sorted(beam + frontier,
                      key=lambda e: (e.cost_s, e.name))[:space.beam_width]
        emit_event("search/round", round=rnd + 1,
                   best=beam[0].name if beam else None,
                   best_cost_ms=round(beam[0].cost_s * 1e3, 6)
                   if beam else None,
                   n_evals=result.n_evals)
        if not improved:
            break

    result.wall_time_s = time.perf_counter() - t0
    if result.best is not None:
        emit_event("search/result", winner=result.best.name,
                   fingerprint=result.best.fingerprint,
                   cost_ms=round(result.best.cost_s * 1e3, 6),
                   n_evals=result.n_evals, n_pruned=len(result.pruned),
                   rounds=result.rounds, calibrated=result.calibrated,
                   wall_time_s=round(result.wall_time_s, 3))
        logging.info(
            "strategy search: %s wins at %.3f ms (%d candidates priced, "
            "%d pruned, %d round(s), %.2f s%s)",
            result.best.name, result.best.cost_s * 1e3, result.n_evals,
            len(result.pruned), result.rounds, result.wall_time_s,
            ", calibrated" if result.calibrated else "")
    return result
