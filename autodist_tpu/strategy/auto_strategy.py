"""AutoStrategy: heuristic per-variable strategy selection.

The AutoDist paper's core pitch is automatic, per-variable strategy choice;
the OSS reference shipped only fixed builders (``autodist/strategy/``) and
left the learned strategizer out.  This builder is the heuristic stand-in —
BEYOND the OSS reference's surface — using the standard TPU cost model:

* **sparse embeddings** → vocab-sharded PS: the gradient scatter-add lands
  on the owning shard; all-reducing a dense ``[vocab, d]`` gradient would
  move orders of magnitude more bytes (the Parallax rule,
  ``parallax_strategy.py:24-71``).
* **large dense variables** (``>= partition_threshold`` bytes) →
  axis-partitioned PS: weight-update sharding spreads optimizer state and
  update FLOPs, and the partitioner shards the largest axis so fresh
  parameters all-gather instead of all-reducing gradients twice.
* **small dense variables** → AllReduce, chunk-grouped: one fused psum has
  lower launch latency than per-variable reductions, and replicated
  optimizer state for small tensors costs almost nothing.

Byte-size load balancing across reduction destinations follows the
reference's greedy rule (``ps_lb_strategy.py:91-117``).
"""
from __future__ import annotations

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import (
    AllReduceSynchronizerConfig,
    GraphConfig,
    PSSynchronizerConfig,
    Strategy,
    StrategyBuilder,
    VarConfig,
)
from autodist_tpu.strategy.partition_utils import (
    greedy_load_balance,
    partition_str,
)


class AutoStrategy(StrategyBuilder):
    """Pick a per-variable strategy from variable structure and size.

    Args:
      partition_threshold: dense variables at least this many bytes get
        axis-partitioned weight-update sharding (default 1 MiB).
      chunk_size: collective group width for the small-variable AllReduce
        tier (reference chunking semantics).
      compressor: optional gradient compressor for the AllReduce tier.
      search: cost-model search instead of (only) the tier heuristic —
        the AutoSync move the paper pitches.  ``True`` (or ``"rank"``)
        RANKS a fixed candidate list: build every candidate fixed
        builder's strategy PLUS the tier heuristic's, estimate each with
        the rank-calibrated cost model
        (``tests/test_cost_model_calibration.py``), and return the
        cheapest.  ``"beam"`` runs the real search
        (:mod:`autodist_tpu.strategy.search`): seeded beam search over
        the per-variable partition x sync x overlap x compressor x
        bucket_bytes space, every candidate pruned by shardlint
        legality, verified through its schedule IR, and priced
        leg-by-leg from the discovered ``calibration.json``.  The
        chosen candidate's name lands in ``last_choice`` and the log
        (``last_search`` holds the full
        :class:`~autodist_tpu.strategy.search.SearchResult` for
        ``"beam"``).  Deterministic run-to-run: candidates with
        identical plan fingerprints dedupe and ties resolve by
        ``(cost, candidate name)``.
      candidates: optional builder list for ``search=True`` (defaults to
        the tier heuristic + every shipped fixed builder; ignored by
        ``search="beam"``, whose seeds are the shipped builders).
    """

    SEARCH_MODES = (False, True, "rank", "beam")

    def __init__(self, partition_threshold: int = 1 << 20,
                 chunk_size: int = 128,
                 compressor: str = "NoneCompressor",
                 search=False, candidates=None):
        if partition_threshold < 1:
            raise ValueError("partition_threshold must be >= 1")
        if chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        if search not in self.SEARCH_MODES:
            raise ValueError(
                f"search must be one of {self.SEARCH_MODES}, "
                f"got {search!r}")
        self._threshold = partition_threshold
        self._chunk_size = chunk_size
        self._compressor = compressor
        self._search = search
        self._candidates = candidates
        self.last_choice: str = ""
        #: the full SearchResult of the last search="beam" build.
        self.last_search = None

    def build(self, graph_item: GraphItem,
              resource_spec: ResourceSpec) -> Strategy:
        if self._search == "beam":
            return self._build_beam(graph_item, resource_spec)
        if self._search:
            return self._build_search(graph_item, resource_spec)
        return self._build_tiers(graph_item, resource_spec)

    def _build_beam(self, graph_item: GraphItem,
                    resource_spec: ResourceSpec) -> Strategy:
        """The real search (docs/strategies.md "Search"): beam over the
        per-variable plan space, legality-pruned, IR-verified, priced
        leg-by-leg from calibration.  A quantizing ``compressor=`` is
        the accuracy opt-in that widens the compressor axis beyond full
        precision (the existing search=True rule, generalized)."""
        from autodist_tpu.strategy.search import (
            SearchSpace,
            beam_search,
        )

        compressors = ["NoneCompressor"]
        if self._compressor and self._compressor != "NoneCompressor":
            compressors.append(self._compressor)
        space = SearchSpace(compressors=tuple(compressors))
        result = beam_search(graph_item, resource_spec, space=space)
        self.last_search = result
        if result.best is None or result.best_strategy is None:
            from autodist_tpu.analysis import StrategyValidationError
            from autodist_tpu.analysis.analyzer import analyze

            report = analyze(
                self._build_tiers(graph_item, resource_spec), graph_item,
                resource_spec=resource_spec, passes=("legality", "sync"))
            raise StrategyValidationError(report)
        self.last_choice = result.best.name
        return result.best_strategy

    def _build_search(self, graph_item: GraphItem,
                      resource_spec: ResourceSpec) -> Strategy:
        from autodist_tpu.analysis import analyze
        from autodist_tpu.strategy.cost_model import estimate_cost
        from autodist_tpu.utils import logging

        if self._candidates is not None:
            candidates = list(self._candidates)
            if not candidates:
                raise ValueError(
                    "AutoStrategy(search=True) needs at least one "
                    "candidate builder")
        else:
            from autodist_tpu.strategy import (
                AllReduce, Parallax, PartitionedAR, PartitionedPS, PS,
                PSLoadBalancing, RandomAxisPartitionAR,
                UnevenPartitionedPS, Zero1)

            heuristic = AutoStrategy(
                partition_threshold=self._threshold,
                chunk_size=self._chunk_size, compressor=self._compressor)
            candidates = [heuristic, PSLoadBalancing(), PS(),
                          PartitionedPS(), UnevenPartitionedPS(),
                          AllReduce(chunk_size=self._chunk_size),
                          PartitionedAR(), RandomAxisPartitionAR(),
                          Parallax(),
                          Zero1(compressor=self._compressor)]
            # A quantizing compressor is an explicit accuracy opt-in, so
            # only then does the search also weigh the fully overlapped
            # quantized plan (one int8/fp8 collective per microbatch
            # slot + quantized ring + param prefetch, docs/overlap.md) —
            # on comm-bound programs with accumulation its exposed wire
            # beats every serial schedule.
            from autodist_tpu.kernel.synchronization import quant_ring
            if quant_ring.is_quant_ring_compressor(self._compressor):
                candidates.append(Zero1(compressor=self._compressor,
                                        overlap="full"))
        # Measured calibration (docs/observability.md): when a
        # calibration.json is discoverable from the environment
        # (AUTODIST_CALIBRATION or AUTODIST_TELEMETRY_DIR), its fitted
        # whole-step constants replace the hand-set defaults — the
        # search ranks candidates with measured numbers, no flags.
        from autodist_tpu.telemetry.calibration import (
            load_default_calibration,
        )
        calibration = load_default_calibration()
        cost_kwargs = calibration.as_cost_kwargs() if calibration else {}
        if calibration is not None:
            logging.info(
                "AutoStrategy(search): using calibrated constants "
                "(bandwidth %.3e B/s, alpha %.3e s) from calibration.json",
                calibration.ici_bandwidth, calibration.alpha)
        from autodist_tpu.strategy.cost_model import plan_fingerprint

        best = None
        pruned = 0
        seen_plans = set()
        for builder in candidates:
            strategy = builder.build(graph_item, resource_spec)
            # Deterministic ranking: candidates that degenerate to the
            # SAME per-variable plan dedupe on their fingerprint, so the
            # winner cannot flip between equal plans run-to-run.
            fp = plan_fingerprint(strategy)
            if fp in seen_plans:
                continue
            seen_plans.add(fp)
            # Static pre-flight (legality + sync coverage) BEFORE paying
            # for cost modeling: an illegal candidate (indivisible
            # partition, uncovered trainable) is pruned here instead of
            # winning on a cost estimate for a plan that cannot lower.
            report = analyze(strategy, graph_item,
                             resource_spec=resource_spec,
                             passes=("legality", "sync"))
            if report.has_errors():
                pruned += 1
                logging.info(
                    "AutoStrategy(search): pruned illegal candidate %s "
                    "(%s)", type(builder).__name__,
                    report.errors[0].rule)
                continue
            cost = estimate_cost(strategy, graph_item, resource_spec,
                                 **cost_kwargs)
            # Ties break by (cost, builder name) — reproducible whatever
            # order the candidate list arrives in.
            name = type(builder).__name__
            if best is None or (cost.time_s, name) < (best[2].time_s,
                                                      best[0]):
                best = (name, strategy, cost)
        if best is None:
            from autodist_tpu.analysis import StrategyValidationError

            # Re-analyze the first candidate so the error carries its
            # diagnostics (all candidates failed; any one illustrates).
            report = analyze(
                candidates[0].build(graph_item, resource_spec),
                graph_item, resource_spec=resource_spec,
                passes=("legality", "sync"))
            raise StrategyValidationError(report)
        self.last_choice = best[0]
        logging.info(
            "AutoStrategy(search): picked %s (est %.3f ms sync) from %d "
            "candidates (%d pruned as illegal)", best[0],
            best[2].time_s * 1e3, len(candidates), pruned)
        return best[1]

    def _build_tiers(self, graph_item: GraphItem,
                     resource_spec: ResourceSpec) -> Strategy:
        ps_devices = self.reduction_device_names(resource_spec)
        variables = graph_item.trainable_var_infos

        ps_vars = [v for v in variables
                   if v.sparse or v.byte_size >= self._threshold]
        assignment, _ = greedy_load_balance(
            [v.byte_size for v in ps_vars], len(ps_devices))
        destination = {v.name: ps_devices[b]
                       for v, b in zip(ps_vars, assignment)}

        node_config = []
        n_small = 0
        for var in variables:
            if var.name in destination:
                partitioner = ""
                if not var.sparse and len(var.shape) >= 1:
                    # Shard the largest axis; the compiler lowers onto the
                    # mesh axis (padding indivisible dims) — the shard count
                    # here is the IR-level intent, sized to the chip count.
                    axis = max(range(len(var.shape)),
                               key=lambda i: var.shape[i])
                    shards = min(var.shape[axis], resource_spec.num_chips)
                    if shards >= 2:  # single-chip specs stay unpartitioned
                        partitioner = partition_str(var.shape, axis, shards)
                node_config.append(VarConfig(
                    var_name=var.name,
                    synchronizer=PSSynchronizerConfig(
                        reduction_destination=destination[var.name]),
                    partitioner=partitioner))
            else:
                node_config.append(VarConfig(
                    var_name=var.name,
                    synchronizer=AllReduceSynchronizerConfig(
                        compressor=self._compressor,
                        group=n_small // self._chunk_size)))
                n_small += 1
        return Strategy(
            node_config=node_config,
            graph_config=GraphConfig(
                replicas=self.replica_devices(resource_spec)))
