"""StrategyCompiler: lower a Strategy onto a device mesh.

Parity: reference ``StrategyCompiler`` (``autodist/strategy/base.py:120-168``)
resolves abstract device names to TF device strings and prunes configs for
variables without update ops.  The TPU-native compiler instead lowers each
per-variable config to a :class:`VarPlan` of ``PartitionSpec``s on a
:class:`jax.sharding.Mesh`:

* **AllReduce** → parameter and optimizer state replicated over ``data``;
  gradient psum over ``data`` (inserted by GSPMD, or explicitly through a
  Compressor on the shard_map path).
* **PS** → parameter replicated for compute, but optimizer state *sharded*
  over ``data`` — weight-update sharding (arxiv 2004.13336): XLA lowers the
  gradient reduction to reduce-scatter, runs the update on the owning shard
  ("the PS"), and all-gathers fresh parameters.  This is the bulk-synchronous
  TPU equivalent of reduce-to-destination-and-broadcast
  (reference ps_synchronizer.py:248-329).
* **partitioner "a,b,c"** → the active tensor axis is sharded over the mesh's
  ``model`` axis (true GSPMD tensor partitioning — what the reference
  approximated with per-shard PS placement, kernel/partitioner.py:153-229).
  On a pure-DP mesh, PS-partitioned variables shard over ``data`` instead
  (parameters live distributed across "servers"), while AR-partitioned
  variables stay replicated (shards colocated with every replica — the
  reference's layout).

Note on load balancing: the reference's byte-size PS assignment decides which
*node* holds each variable.  Under weight-update sharding every variable's
update is spread uniformly across the data axis, so balancing is automatic;
the per-variable ``reduction_destination`` is still resolved (to mesh
coordinates) and drives DCN placement on multi-slice meshes.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from autodist_tpu.const import (
    MESH_AXIS_DATA,
    MESH_AXIS_EXPERT,
    MESH_AXIS_MODEL,
    MESH_AXIS_PIPE,
    MESH_AXIS_SEQ,
)
from autodist_tpu.graph_item import GraphItem, VarInfo
from autodist_tpu.resource_spec import DeviceSpec
from autodist_tpu.strategy.base import (
    AllReduceSynchronizerConfig,
    PSSynchronizerConfig,
    Strategy,
    VarConfig,
)
from autodist_tpu.utils import logging

_warned: set = set()


def _warn_once(fmt: str, *args) -> None:
    key = (fmt,) + args
    if key not in _warned:
        _warned.add(key)
        logging.warning(fmt, *args)


def spec_from_entries(entries: List[Optional[str]]) -> P:
    """Trim trailing Nones and build a PartitionSpec (single normalization
    rule for the whole module)."""
    entries = list(entries)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def parse_partitioner(partitioner: str) -> Tuple[Optional[int], int]:
    """``"1,4,1"`` → (active_axis, num_shards); ("" or all-ones) → (None, 1).

    Enforces the reference's one-active-axis rule
    (kernel/partitioner.py:38-150)."""
    if not partitioner:
        return None, 1
    parts = [int(x) for x in partitioner.split(",")]
    active = [(i, p) for i, p in enumerate(parts) if p > 1]
    if not active:
        return None, 1
    if len(active) > 1:
        raise ValueError(
            f"partitioner {partitioner!r} has more than one active axis")
    return active[0][0], active[0][1]


@dataclass
class VarPlan:
    """Lowered per-variable plan."""

    var_name: str
    sync_kind: str                     # "AllReduce" | "PS"
    param_spec: P                      # parameter layout
    opt_spec: P                        # layout for same-shaped optimizer slots
    grad_reduce_axes: Tuple[str, ...]  # mesh axes the gradient is summed over
    compressor: str = "NoneCompressor"
    group: int = 0
    fused: bool = False                # explicit concat-and-pmean group fusion
    # AllReduce collective lowering: "all_reduce", or "reduce_scatter" for
    # ZeRO-1 weight-update sharding (bucketed reduce-scatter + local-shard
    # update + param all-gather on the explicit path).
    sync_mode: str = "all_reduce"
    bucket_bytes: int = 0              # gradient-bucket cap (0 = default)
    # Bucket-collective schedule (overlap.OVERLAP_MODES): how the explicit
    # path overlaps this var's sync with compute — see docs/overlap.md.
    overlap: str = "auto"
    reduction_destination: str = ""
    destination_coords: Optional[Dict[str, int]] = None
    staleness: int = 0
    local_replication: bool = False
    partition_axis: Optional[int] = None
    num_shards: int = 1
    sparse: bool = False
    # pad-to-divisible sharding: when the partitioned dim does not divide the
    # mesh axis, the variable is physically padded to ``pad_dim`` along
    # ``pad_axis`` (pad rows zero-masked every step); the kernel layer owns
    # the pad/unpad boundary.  Real lowering of the reference's uneven
    # partitioner (kernel/partitioner.py:376-426).
    pad_axis: Optional[int] = None
    pad_dim: int = 0
    # Two-tier hierarchical sync (ICI within a slice, DCN across): the
    # explicit path lowers this var's bucket as RS-within → exchange-
    # across → AG-within when the CompiledStrategy carries num_slices>1.
    hier: bool = False


@dataclass
class CompiledStrategy:
    """A Strategy bound to a mesh: per-variable plans + batch layout."""

    strategy: Strategy
    mesh: Mesh
    var_plans: Dict[str, VarPlan]
    batch_axes: Tuple[str, ...] = (MESH_AXIS_DATA,)
    # Slice count of the two-tier topology (from ResourceSpec.num_slices;
    # 1 = flat single-slice mesh — all pre-hier behavior).
    num_slices: int = 1

    @property
    def data_axis_size(self) -> int:
        return self.mesh.shape.get(MESH_AXIS_DATA, 1)

    def plan_for(self, name: str) -> VarPlan:
        return self.var_plans[name]

    def pad_plans(self) -> Dict[str, Tuple[int, int]]:
        """Vars needing pad-to-divisible sharding: name → (axis, padded_dim)."""
        return {n: (p.pad_axis, p.pad_dim)
                for n, p in self.var_plans.items() if p.pad_axis is not None}

    def fusable_groups(self) -> Dict[int, List[str]]:
        """Collective groups with ≥2 uncompressed replicated AllReduce vars —
        candidates for concat-and-pmean fusion (the reference's
        scoped-allocator chunk merge, all_reduce_strategy.py:21-90)."""
        by_group: Dict[int, List[str]] = {}
        for name, plan in self.var_plans.items():
            if plan.sync_kind != "AllReduce" or plan.param_spec != P():
                continue
            if (plan.compressor or "NoneCompressor") != "NoneCompressor":
                continue
            by_group.setdefault(plan.group, []).append(name)
        return {g: ns for g, ns in by_group.items() if len(ns) >= 2}

    def batch_spec(self) -> P:
        return P(self.batch_axes)

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec())

    def batch_sharding_for_leaf(self, leaf,
                                seq_len: Optional[int] = None) -> NamedSharding:
        """Per-leaf batch layout: leading dim over ``data``; dim 1 over
        ``seq`` for leaves that carry the batch's sequence length
        (sequence/context parallelism — tokens split across chips; GSPMD
        inserts the attention collectives, and the ring/Ulysses kernels in
        autodist_tpu.parallel take over when plugged in).

        ``seq_len``: the batch's sequence length (computed by
        ``batch_shardings`` as the max dim-1 across rank≥2 leaves) — only
        dims equal to it shard over ``seq``, so same-parity non-sequence
        dims (one-hot widths etc.) are left alone."""
        shape = tuple(getattr(leaf, "shape", ()) or ())
        entries: List[Optional[str]] = [None] * len(shape)
        if shape:
            d = self.mesh.shape.get(MESH_AXIS_DATA, 1)
            if d > 1 and self.batch_axes:
                if shape[0] % d == 0:
                    entries[0] = MESH_AXIS_DATA
                else:
                    _warn_once(
                        "batch leaf with leading dim %d is not divisible by "
                        "the data axis (size %d); replicating it on every "
                        "chip — pad the global batch for data parallelism",
                        shape[0], d)
        s = self.mesh.shape.get(MESH_AXIS_SEQ, 1)
        if (len(shape) >= 2 and s > 1 and seq_len is not None
                and shape[1] == seq_len and shape[1] % s == 0):
            entries[1] = MESH_AXIS_SEQ
        return NamedSharding(self.mesh, spec_from_entries(entries))

    def batch_shardings(self, batch) -> "Any":
        """Pytree of per-leaf batch shardings (see batch_sharding_for_leaf)."""
        dims = [s[1] for leaf in jax.tree_util.tree_leaves(batch)
                if len(s := tuple(getattr(leaf, "shape", ()) or ())) >= 2]
        seq_len = max(dims) if dims else None
        return jax.tree_util.tree_map(
            lambda x: self.batch_sharding_for_leaf(x, seq_len), batch)

    def param_sharding_tree(self, params):
        """Pytree of NamedShardings matching ``params``."""
        from autodist_tpu.graph_item import path_name

        def spec_of(path, leaf):
            name = path_name(path)
            plan = self.var_plans.get(name)
            return NamedSharding(self.mesh, plan.param_spec if plan else P())

        return jax.tree_util.tree_map_with_path(spec_of, params)

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


class StrategyCompiler:
    """Compile ``Strategy × GraphItem × Mesh → CompiledStrategy``.

    ``resource_spec`` (optional) lets the compiler resolve abstract
    ``reduction_destination`` device strings to data-axis coordinates —
    the analog of the reference's DeviceResolver
    (kernel/device/resolver.py:47-67)."""

    def __init__(self, mesh: Mesh, resource_spec=None):
        self.mesh = mesh
        self._host_to_data_coord = self._build_host_map(resource_spec)
        # Two-tier topology (validated against the device count at
        # ResourceSpec build; re-checked per-mesh by hier_applies).
        self.num_slices = int(getattr(resource_spec, "num_slices", 1) or 1)

    def _build_host_map(self, resource_spec) -> Dict[str, int]:
        """Map node address → the data-axis coordinate of its first chip,
        assuming mesh devices are laid out in node order (how build_mesh
        arranges them).  Under weight-update sharding this coordinate is the
        canonical 'owner' shard of variables destined to that node."""
        if resource_spec is None:
            return {}
        total = max(resource_spec.num_chips, 1)
        d = self.mesh.shape.get(MESH_AXIS_DATA, 1)
        out: Dict[str, int] = {}
        cum = 0
        for node in resource_spec.nodes:
            out[node.address] = min(cum * d // total, d - 1)
            cum += max(node.chips, 1)
        return out

    # -- helpers -----------------------------------------------------------
    def _grad_axes(self) -> Tuple[str, ...]:
        return (MESH_AXIS_DATA,) \
            if self.mesh.shape.get(MESH_AXIS_DATA, 1) > 1 else ()

    def _model_axis(self) -> Optional[str]:
        if self.mesh.shape.get(MESH_AXIS_MODEL, 1) > 1:
            return MESH_AXIS_MODEL
        return None

    def _resolve_destination(self, dest: str) -> Optional[Dict[str, int]]:
        """DeviceSpec string → owning data-axis coordinate, or None when the
        address is unknown to this mesh (the reduction then rides the data
        axis uniformly)."""
        if not dest:
            return None
        try:
            spec = DeviceSpec.from_string(dest)
        except ValueError:
            return None
        coord = self._host_to_data_coord.get(spec.host_address)
        if coord is None:
            return None
        return {MESH_AXIS_DATA: coord}

    _spec_from_entries = staticmethod(spec_from_entries)

    def _partition_spec(self, var: VarInfo, axis: Optional[int],
                        shard_mesh_axis: Optional[str]
                        ) -> Tuple[P, Optional[Tuple[int, int]]]:
        """Shard ``var``'s ``axis`` over ``shard_mesh_axis``.

        Returns ``(spec, pad)`` where ``pad`` is ``(axis, padded_dim)`` when
        the dim does not divide the mesh axis: jit arg/out shardings require
        even tiling, so indivisible dims are padded to the next multiple and
        physically sharded, with pad rows masked to zero by the kernel layer
        — the real lowering of the reference's uneven partitioner
        (kernel/partitioner.py:376-426), and how indivisible embedding vocabs
        shard instead of replicating."""
        if axis is None or shard_mesh_axis is None:
            return P(), None
        axis_size = self.mesh.shape.get(shard_mesh_axis, 1)
        if axis_size <= 1:
            return P(), None
        entries: List[Optional[str]] = [None] * len(var.shape)
        entries[axis] = shard_mesh_axis
        spec = self._spec_from_entries(entries)
        dim = var.shape[axis]
        if dim % axis_size != 0:
            padded = -(-dim // axis_size) * axis_size
            if padded >= 2 * dim:
                # Padding would at least double the variable (tiny dims on a
                # wide axis): replication is cheaper than the pad waste plus
                # the extra all-gather.
                _warn_once(
                    "variable %s dim %d (size %d) would pad to %d on the %r "
                    "axis (size %d) — more than doubling it; keeping it "
                    "replicated", var.name, axis, dim, padded,
                    shard_mesh_axis, axis_size)
                return P(), None
            logging.info(
                "variable %s dim %d (size %d) padded to %d for even %r-axis "
                "sharding (pad rows are zero-masked each step)",
                var.name, axis, dim, padded, shard_mesh_axis)
            return spec, (axis, padded)
        return spec, None

    def _wus_opt_spec(self, var: VarInfo, param_spec: P) -> P:
        """Weight-update-sharding layout: shard the largest still-unsharded
        dim over ``data`` if it divides evenly; otherwise keep the param
        layout (replicating tiny/odd variables costs nothing)."""
        d = self.mesh.shape.get(MESH_AXIS_DATA, 1)
        if d <= 1 or not var.shape:
            return param_spec
        entries = list(param_spec) + [None] * (len(var.shape) - len(param_spec))
        if MESH_AXIS_DATA in entries:
            # Already data-sharded on some dim (e.g. a PS partitioner lowered
            # onto 'data' on a model-less mesh) — a second entry would be an
            # invalid duplicate.
            return param_spec
        best, best_dim = None, 0
        for i, dim in enumerate(var.shape):
            if entries[i] is None and dim % d == 0 and dim > best_dim:
                best, best_dim = i, dim
        if best is None:
            return param_spec
        entries[best] = MESH_AXIS_DATA
        return self._spec_from_entries(entries)

    # -- main --------------------------------------------------------------
    def compile(self, strategy: Strategy, graph_item: GraphItem) -> CompiledStrategy:
        model_axis = self._model_axis()
        plans: Dict[str, VarPlan] = {}
        known = {v.name: v for v in graph_item.info.variables}

        for node in strategy.node_config:
            var = known.get(node.var_name)
            if var is None:
                # Prune configs with no matching variable (parity with the
                # reference pruning no-update-op nodes, strategy/base.py:128-140).
                logging.debug("pruning strategy node for unknown var %s",
                              node.var_name)
                continue
            if not var.trainable:
                continue
            plans[var.name] = self._compile_node(node, var, model_axis)

        # Untouched trainable vars: replicate + psum (safe default) — but
        # structural pipe/expert axes still apply, so a pipeline/expert stack
        # missing from a hand-built strategy keeps its stage/expert sharding.
        grad_axes = self._grad_axes()
        for name, var in known.items():
            if var.trainable and name not in plans:
                spec = self._apply_structural_specs(var, P())
                plans[name] = VarPlan(
                    var_name=name, sync_kind="AllReduce", param_spec=spec,
                    opt_spec=spec, grad_reduce_axes=grad_axes)
        return CompiledStrategy(strategy=strategy, mesh=self.mesh,
                                var_plans=plans, batch_axes=grad_axes,
                                num_slices=self.num_slices)

    def _structural_spec(self, var: VarInfo, spec: P, target: int,
                         mesh_axis: str, label: str) -> P:
        """Shard structural dim ``target`` of ``var`` over ``mesh_axis`` if
        it divides evenly; warn and keep the spec otherwise.  Applied after
        synchronizer lowering so it composes with model/data sharding of the
        remaining axes."""
        size = self.mesh.shape.get(mesh_axis, 1)
        if size <= 1 or len(var.shape) <= target:
            return spec
        if var.shape[target] % size != 0:
            _warn_once(
                "%s variable %s dim %d (size %d) is not divisible by the "
                "%r axis (size %d); keeping it replicated", label, var.name,
                target, var.shape[target], mesh_axis, size)
            return spec
        entries = list(spec) + [None] * (len(var.shape) - len(spec))
        entries[target] = mesh_axis
        return self._spec_from_entries(entries)

    def _structural_axes(self, var: VarInfo) -> Tuple[int, ...]:
        """Axes owned by pipeline/expert stacking — strategy partitioners
        must not claim them."""
        axes = []
        if var.pipeline:
            axes.append(0)
        if var.expert:
            axes.append(1 if var.pipeline else 0)
        return tuple(axes)

    def _apply_structural_specs(self, var: VarInfo, spec: P) -> P:
        if var.pipeline:
            # Leading dim = pipeline stages.
            spec = self._structural_spec(var, spec, 0, MESH_AXIS_PIPE,
                                         "pipeline")
        if var.expert:
            # Expert dim: leading, or right after a stage axis.
            spec = self._structural_spec(var, spec, 1 if var.pipeline else 0,
                                         MESH_AXIS_EXPERT, "expert")
        return spec

    def _compile_node(self, node: VarConfig, var: VarInfo,
                      model_axis: Optional[str]) -> VarPlan:
        axis, num_shards = parse_partitioner(node.partitioner)
        if axis in self._structural_axes(var):
            # Stage/expert axes are owned by 'pipe'/'expert'; strategy
            # partitioning must not claim them.
            axis, num_shards = None, 1
        if axis is not None and (len(var.shape) <= axis or var.shape[axis] < 2):
            raise ValueError(
                f"partitioner {node.partitioner!r} invalid for {var.name} "
                f"with shape {var.shape}")
        sync = node.synchronizer
        grad_axes = self._grad_axes()

        if isinstance(sync, AllReduceSynchronizerConfig):
            # Shards stay colocated with replicas (reference layout) —
            # partition over 'model' only when the mesh has one.
            spec, pad = self._partition_spec(var, axis, model_axis)
            spec = self._apply_structural_specs(var, spec)
            return VarPlan(
                var_name=var.name, sync_kind="AllReduce",
                param_spec=spec, opt_spec=spec, grad_reduce_axes=grad_axes,
                compressor=sync.compressor, group=sync.group,
                fused=getattr(sync, "fused", False),
                sync_mode=getattr(sync, "sync", "all_reduce")
                or "all_reduce",
                bucket_bytes=int(getattr(sync, "bucket_bytes", 0) or 0),
                overlap=getattr(sync, "overlap", "auto") or "auto",
                hier=bool(getattr(sync, "hier", False)),
                partition_axis=axis if model_axis else None,
                num_shards=num_shards if model_axis else 1,
                sparse=var.sparse,
                pad_axis=pad[0] if pad else None,
                pad_dim=pad[1] if pad else 0)

        if isinstance(sync, PSSynchronizerConfig):
            shard_axis = model_axis or (MESH_AXIS_DATA if axis is not None else None)
            spec, pad = self._partition_spec(var, axis, shard_axis)
            if (var.sparse and axis is None and var.shape
                    and not (var.pipeline or var.expert)):
                # Sparse embedding on PS: shard the vocab axis so gradient
                # scatter-adds land on the owning shard (Parallax lowering).
                spec, pad = self._partition_spec(
                    var, 0, model_axis or MESH_AXIS_DATA)
            if var.pipeline or var.expert:
                # Structural axes over pipe/expert, then WUS fills a free dim
                # with data (no-op if the spec already carries 'data').
                spec = self._apply_structural_specs(var, spec)
                opt_spec = self._wus_opt_spec(var, spec)
            else:
                opt_spec = spec if spec != P() else self._wus_opt_spec(var, spec)
            return VarPlan(
                var_name=var.name, sync_kind="PS",
                param_spec=spec, opt_spec=opt_spec, grad_reduce_axes=grad_axes,
                reduction_destination=sync.reduction_destination,
                destination_coords=self._resolve_destination(
                    sync.reduction_destination),
                staleness=sync.staleness,
                local_replication=sync.local_replication,
                partition_axis=axis, num_shards=num_shards,
                sparse=var.sparse,
                pad_axis=pad[0] if pad else None,
                pad_dim=pad[1] if pad else 0)

        raise ValueError(f"node {node.var_name} has no synchronizer")
