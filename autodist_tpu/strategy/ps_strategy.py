"""PS strategy: every variable synchronized through a single parameter server.

Parity: reference ``autodist/strategy/ps_strategy.py:21-76`` — all variables
get a PSSynchronizer whose reduction destination is the first node's CPU;
replicas are all compute devices.
"""
from __future__ import annotations

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import (
    GraphConfig,
    PSSynchronizerConfig,
    Strategy,
    StrategyBuilder,
    VarConfig,
)


class PS(StrategyBuilder):
    def __init__(self, local_proxy_variable: bool = False, sync: bool = True,
                 staleness: int = 0):
        self._local_proxy = local_proxy_variable
        self._sync = sync
        self._staleness = staleness

    def build(self, graph_item: GraphItem, resource_spec: ResourceSpec) -> Strategy:
        reduction_device = self.reduction_device_names(resource_spec)[0]
        node_config = [
            VarConfig(
                var_name=var.name,
                synchronizer=PSSynchronizerConfig(
                    reduction_destination=reduction_device,
                    local_replication=self._local_proxy,
                    sync=self._sync,
                    staleness=self._staleness,
                ),
            )
            for var in graph_item.trainable_var_infos
        ]
        return Strategy(
            node_config=node_config,
            graph_config=GraphConfig(replicas=self.replica_devices(resource_spec)),
        )
