"""AllReduce strategy: every dense variable synchronized by all-reduce.

Parity: reference ``autodist/strategy/all_reduce_strategy.py:21-90`` —
variables are assigned AllReduceSynchronizers and merged into collective
groups of ``chunk_size`` consecutive variables (the reference's
scoped-allocator merge; on TPU the grouping becomes a hint for XLA's
all-reduce combiner and for the explicit shard_map sync path).

The reference cannot all-reduce sparse gradients across >1 node (flagged
broken in stock TF, all_reduce_synchronizer.py:129-169); on TPU sparse
embedding gradients are handled by the Parallax builder instead.
"""
from __future__ import annotations

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import (
    AllReduceSynchronizerConfig,
    GraphConfig,
    Strategy,
    StrategyBuilder,
    VarConfig,
)


class AllReduce(StrategyBuilder):
    """``chunk_size`` consecutive variables share a collective group.

    ``fused_groups=False`` (default): grouping is lowered as XLA's
    all-reduce combiner threshold — on TPU the compiler merges the psums
    itself, which subsumes the reference's scoped-allocator merge.
    ``fused_groups=True``: the step runs on the explicit shard_map path and
    each group's gradients are concatenated into ONE ``pmean`` (verifiably
    fewer collectives; see tests/test_allreduce_group.py).

    ``sync="reduce_scatter"`` turns on ZeRO-1 weight-update sharding for
    every variable (see :class:`~autodist_tpu.strategy.Zero1` for the
    dedicated builder); ``bucket_bytes`` caps the explicit path's
    dtype-grouped gradient buckets (non-zero forces the explicit path —
    the way to get trace-time bucketing without a compressor).

    ``overlap`` picks the bucket-collective schedule (``docs/overlap.md``):
    ``"auto"`` | ``"none"`` | ``"pipeline"`` | ``"ring"`` | ``"full"``.

    ``hier=True`` requests the two-tier ICI+DCN lowering on multi-slice
    resource specs (``resource_spec.num_slices > 1``): slice-local
    reduce-scatter, one cross-slice DCN leg, slice-local all-gather.
    No-op on single-slice specs."""

    def __init__(self, chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor",
                 fused_groups: bool = False, sync: str = "all_reduce",
                 bucket_bytes: int = 0, overlap: str = "auto",
                 hier: bool = False):
        if chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        from autodist_tpu.kernel.synchronization.bucketing import SYNC_MODES
        from autodist_tpu.kernel.synchronization.overlap import OVERLAP_MODES
        if sync not in SYNC_MODES:
            raise ValueError(f"sync must be one of {SYNC_MODES}, got {sync!r}")
        if overlap not in OVERLAP_MODES:
            raise ValueError(
                f"overlap must be one of {OVERLAP_MODES}, got {overlap!r}")
        if bucket_bytes < 0:
            raise ValueError("bucket_bytes must be >= 0")
        self._chunk_size = chunk_size
        self._spec = all_reduce_spec
        self._compressor = compressor
        self._fused = fused_groups
        self._sync = sync
        self._bucket_bytes = bucket_bytes
        self._overlap = overlap
        self._hier = hier

    def build(self, graph_item: GraphItem, resource_spec: ResourceSpec) -> Strategy:
        node_config = [
            VarConfig(
                var_name=var.name,
                synchronizer=AllReduceSynchronizerConfig(
                    spec=self._spec,
                    compressor=self._compressor,
                    group=i // self._chunk_size,
                    fused=self._fused,
                    sync=self._sync,
                    bucket_bytes=self._bucket_bytes,
                    overlap=self._overlap,
                    hier=self._hier,
                ),
            )
            for i, var in enumerate(graph_item.trainable_var_infos)
        ]
        return Strategy(
            node_config=node_config,
            graph_config=GraphConfig(replicas=self.replica_devices(resource_spec)),
        )
