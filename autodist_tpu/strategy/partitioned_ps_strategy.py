"""PartitionedPS: shard each variable along axis 0, load-balance shards on PS.

Parity: reference ``autodist/strategy/partitioned_ps_strategy.py:28-135`` —
num_shards is the smallest divisor > 1 of dim 0 (capped at the number of PS
destinations in the reference; we keep the cap optional), shards are greedily
load-balanced, unpartitionable variables fall back to plain PS.
"""
from __future__ import annotations

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import (
    GraphConfig,
    PSSynchronizerConfig,
    Strategy,
    StrategyBuilder,
    VarConfig,
)
from autodist_tpu.strategy.partition_utils import (
    greedy_load_balance,
    partition_str,
    partitionable,
    smallest_divisor_gt_one,
)


class PartitionedPS(StrategyBuilder):
    def __init__(self, local_proxy_variable: bool = False, sync: bool = True,
                 staleness: int = 0, max_shards: int = 0):
        """``max_shards``: cap on shards per variable; 0 ⇒ number of compute
        devices (shards beyond that are useless on a mesh, and a prime-length
        axis must not explode into one shard per element)."""
        self._local_proxy = local_proxy_variable
        self._sync = sync
        self._staleness = staleness
        self._max_shards = max_shards

    def _num_shards(self, dim0: int, cap: int) -> int:
        n = smallest_divisor_gt_one(dim0) or 1
        return n if n <= cap else 1

    def build(self, graph_item: GraphItem, resource_spec: ResourceSpec) -> Strategy:
        ps_devices = self.reduction_device_names(resource_spec)
        cap = self._max_shards or max(len(resource_spec.devices), 2)
        node_config = []
        # Flatten (var, shard) pairs in order, then greedily balance shard
        # bytes across PS devices — parity with the reference's per-shard
        # load balancing (partitioned_ps_strategy.py:95-135).
        pending = []  # (var, num_shards, per_shard_bytes)
        for var in graph_item.trainable_var_infos:
            n = self._num_shards(var.shape[0], cap) if partitionable(var) else 1
            pending.append((var, n, var.byte_size / max(n, 1)))
        shard_sizes = []
        for var, n, per_shard in pending:
            shard_sizes.extend([per_shard] * n)
        assignment, _ = greedy_load_balance(shard_sizes, len(ps_devices))
        cursor = 0
        for var, n, _ in pending:
            if n <= 1:
                node_config.append(VarConfig(
                    var_name=var.name,
                    synchronizer=PSSynchronizerConfig(
                        reduction_destination=ps_devices[assignment[cursor]],
                        local_replication=self._local_proxy,
                        sync=self._sync, staleness=self._staleness)))
                cursor += 1
                continue
            parts = [
                VarConfig(
                    var_name=f"{var.name}/part_{i}",
                    synchronizer=PSSynchronizerConfig(
                        reduction_destination=ps_devices[assignment[cursor + i]],
                        local_replication=self._local_proxy,
                        sync=self._sync, staleness=self._staleness))
                for i in range(n)
            ]
            cursor += n
            node_config.append(VarConfig(
                var_name=var.name,
                partitioner=partition_str(var.shape, 0, n),
                part_config=parts,
                synchronizer=PSSynchronizerConfig(
                    reduction_destination=ps_devices[assignment[cursor - n]],
                    local_replication=self._local_proxy,
                    sync=self._sync, staleness=self._staleness)))
        return Strategy(
            node_config=node_config,
            graph_config=GraphConfig(replicas=self.replica_devices(resource_spec)),
        )
