"""Zero1 strategy: bucketed reduce-scatter weight-update sharding.

The optimizer-state redundancy of plain data parallelism — every replica
carries a full copy of the Adam moments it only ever updates with the
same averaged gradient — is the exact inefficiency "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training"
(arXiv:2004.13336) removes on TPU pods, and what the DeepSpeed line
later named ZeRO stage 1.  This builder makes it a first-class strategy:

* every trainable variable syncs through the explicit bucketed path with
  ``sync="reduce_scatter"`` — gradients are flattened into dtype-grouped
  buckets (``bucket_bytes`` cap), each bucket is reduce-scattered
  ((N−1)/N·bytes on the wire instead of the all-reduce's 2(N−1)/N),
  the optimizer update runs on the local 1/N optimizer-state shard, and
  fresh parameters are all-gathered;
* optimizer-state HBM per device drops by the data-axis size (composes
  with ``ops/opt_state_dtype.cast_opt_state`` for a further 2x);
* a compressor (bf16/int8 wire) quantizes per BUCKET on the reduce leg
  (EQuARX-style, arXiv:2506.17615); the parameter all-gather stays in
  the storage dtype.

Variables the bucketed path cannot absorb (partitioned/model-sharded,
pad-to-divisible, PowerSGD-compressed) fall back to their usual per-
variable collective with replicated optimizer state — the fallback is
warned at trace time and visible to ``autodist_tpu.analysis``.

Numerics (docs/numerics.md): do NOT put ``optax.clip_by_global_norm``
in the optimizer chain under ZeRO-1 — the bucket optimizer updates
LOCAL 1/N shards, so a chained clip would compute shard-local norms and
silently clip differently per device.  Use
``capture(numerics={"clip_norm": ...})`` instead: the fused guard psums
the reduce-scattered shards' squared norms (÷ replication), so the clip
factor is the true global norm's — exact to 1e-6 against unsharded
clipping, including under pipelined overlap.  The guard's per-bucket
finiteness bits and the loss-scale state ride the same bucket chain.

No reference analog: the OSS reference synchronizes one variable at a
time and replicates optimizer state on every replica.
"""
from __future__ import annotations

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.kernel.synchronization.bucketing import DEFAULT_BUCKET_BYTES
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import (
    AllReduceSynchronizerConfig,
    GraphConfig,
    Strategy,
    StrategyBuilder,
    VarConfig,
)


class Zero1(StrategyBuilder):
    """ZeRO-1: bucketed reduce-scatter gradient sync + sharded weight update.

    Args:
      bucket_bytes: gradient-bucket size cap (default
        ``bucketing.DEFAULT_BUCKET_BYTES``); buckets are dtype-grouped
        and the uneven tail bucket is zero-padded to shard evenly.
      chunk_size: variables per collective group (group boundaries also
        bound buckets, mirroring the AllReduce chunking semantics).
        Defaults high so ``bucket_bytes`` is the binding constraint.
      compressor: optional per-bucket gradient compressor for the
        reduce-scatter leg.
      overlap: bucket-collective schedule (``docs/overlap.md``) —
        ``"auto"`` (default) pipelines the reduce-scatter with the
        microbatch loop when gradient accumulation is active,
        ring-decomposes large buckets, and issues the param all-gather
        in reverse bucket order (prefetch); ``"none"`` restores the
        phase-serial schedule; ``"pipeline"``/``"ring"``/``"full"``
        request mechanisms explicitly.
      hier: request the two-tier ICI+DCN lowering on multi-slice
        resource specs — slice-local reduce-scatter, cross-slice DCN
        shard exchange, and a two-stage (DCN then ICI) param gather.
        No-op on single-slice specs.
    """

    def __init__(self, bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                 chunk_size: int = 512,
                 compressor: str = "NoneCompressor",
                 overlap: str = "auto", hier: bool = False):
        from autodist_tpu.kernel.synchronization.overlap import OVERLAP_MODES
        if bucket_bytes < 1:
            raise ValueError("bucket_bytes must be >= 1")
        if chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        if overlap not in OVERLAP_MODES:
            raise ValueError(
                f"overlap must be one of {OVERLAP_MODES}, got {overlap!r}")
        self._bucket_bytes = bucket_bytes
        self._chunk_size = chunk_size
        self._compressor = compressor
        self._overlap = overlap
        self._hier = hier

    def build(self, graph_item: GraphItem,
              resource_spec: ResourceSpec) -> Strategy:
        node_config = [
            VarConfig(
                var_name=var.name,
                synchronizer=AllReduceSynchronizerConfig(
                    compressor=self._compressor,
                    group=i // self._chunk_size,
                    sync="reduce_scatter",
                    bucket_bytes=self._bucket_bytes,
                    overlap=self._overlap,
                    hier=self._hier,
                ),
            )
            for i, var in enumerate(graph_item.trainable_var_infos)
        ]
        return Strategy(
            node_config=node_config,
            graph_config=GraphConfig(
                replicas=self.replica_devices(resource_spec)),
        )
