"""Parallax: hybrid — dense gradients all-reduced, sparse gradients on PS.

Parity: reference ``autodist/strategy/parallax_strategy.py:24-71`` (from the
Parallax paper, arxiv 1808.02621): dense variables get AllReduce; variables
with sparse (embedding) gradients get load-balanced PS synchronizers.  On
TPU the PS half compiles to vocab-axis sharding of the embedding table with
scatter-add gradient placement — the sharded-embedding formulation that
avoids densifying huge vocab gradients (cf. reference lm1b example with
793,471-word vocab, examples/lm1b/language_model.py:21-43).
"""
from __future__ import annotations

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import (
    AllReduceSynchronizerConfig,
    GraphConfig,
    PSSynchronizerConfig,
    Strategy,
    StrategyBuilder,
    VarConfig,
)
from autodist_tpu.strategy.partition_utils import greedy_load_balance


class Parallax(StrategyBuilder):
    def __init__(self, chunk_size: int = 128, local_proxy_variable: bool = False,
                 sync: bool = True, staleness: int = 0,
                 all_reduce_spec: str = "AUTO", compressor: str = "NoneCompressor"):
        if chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        self._chunk_size = chunk_size
        self._local_proxy = local_proxy_variable
        self._sync = sync
        self._staleness = staleness
        self._spec = all_reduce_spec
        self._compressor = compressor

    def build(self, graph_item: GraphItem, resource_spec: ResourceSpec) -> Strategy:
        ps_devices = self.reduction_device_names(resource_spec)
        variables = graph_item.trainable_var_infos
        sparse_vars = [v for v in variables if v.sparse]
        assignment, _ = greedy_load_balance(
            [v.byte_size for v in sparse_vars], len(ps_devices))
        sparse_dest = {v.name: ps_devices[b] for v, b in zip(sparse_vars, assignment)}

        node_config = []
        dense_idx = 0
        for var in variables:
            if var.sparse:
                node_config.append(VarConfig(
                    var_name=var.name,
                    synchronizer=PSSynchronizerConfig(
                        reduction_destination=sparse_dest[var.name],
                        local_replication=self._local_proxy,
                        sync=self._sync, staleness=self._staleness)))
            else:
                node_config.append(VarConfig(
                    var_name=var.name,
                    synchronizer=AllReduceSynchronizerConfig(
                        spec=self._spec, compressor=self._compressor,
                        group=dense_idx // self._chunk_size)))
                dense_idx += 1
        return Strategy(
            node_config=node_config,
            graph_config=GraphConfig(replicas=self.replica_devices(resource_spec)),
        )
