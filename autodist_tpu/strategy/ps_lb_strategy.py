"""PSLoadBalancing: greedy byte-size balancing of variables across PS nodes.

Parity: reference ``autodist/strategy/ps_lb_strategy.py:23-117`` (the
reference's DEFAULT strategy, autodist.py:70).  Each variable is assigned to
the currently least-loaded reduction destination, load measured in bytes.
"""
from __future__ import annotations

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import (
    GraphConfig,
    PSSynchronizerConfig,
    Strategy,
    StrategyBuilder,
    VarConfig,
)
from autodist_tpu.strategy.partition_utils import greedy_load_balance


class PSLoadBalancing(StrategyBuilder):
    def __init__(self, local_proxy_variable: bool = False, sync: bool = True,
                 staleness: int = 0):
        self._local_proxy = local_proxy_variable
        self._sync = sync
        self._staleness = staleness

    def build(self, graph_item: GraphItem, resource_spec: ResourceSpec) -> Strategy:
        ps_devices = self.reduction_device_names(resource_spec)
        variables = graph_item.trainable_var_infos
        assignment, _ = greedy_load_balance(
            [v.byte_size for v in variables], len(ps_devices))
        node_config = [
            VarConfig(
                var_name=var.name,
                synchronizer=PSSynchronizerConfig(
                    reduction_destination=ps_devices[bin_idx],
                    local_replication=self._local_proxy,
                    sync=self._sync,
                    staleness=self._staleness,
                ),
            )
            for var, bin_idx in zip(variables, assignment)
        ]
        return Strategy(
            node_config=node_config,
            graph_config=GraphConfig(replicas=self.replica_devices(resource_spec)),
        )
