"""Strategy builders — parity with ``autodist/strategy/`` (9 modules),
plus :class:`AutoStrategy` (heuristic automatic selection, beyond the OSS
reference's surface)."""
from autodist_tpu.strategy.all_reduce_strategy import AllReduce
from autodist_tpu.strategy.auto_strategy import AutoStrategy
from autodist_tpu.strategy.base import (
    AllReduceSynchronizerConfig,
    GraphConfig,
    PSSynchronizerConfig,
    Strategy,
    StrategyBuilder,
    VarConfig,
)
from autodist_tpu.strategy.compiler import (
    CompiledStrategy,
    StrategyCompiler,
    VarPlan,
    parse_partitioner,
)
from autodist_tpu.strategy.cost_model import (
    CostReport,
    estimate_cost,
    plan_fingerprint,
    rank_strategies,
)
from autodist_tpu.strategy.parallax_strategy import Parallax
from autodist_tpu.strategy.search import (
    SearchResult,
    SearchSpace,
    beam_search,
)
from autodist_tpu.strategy.tuner import ScheduleTuner
from autodist_tpu.strategy.partitioned_all_reduce_strategy import PartitionedAR
from autodist_tpu.strategy.partitioned_ps_strategy import PartitionedPS
from autodist_tpu.strategy.ps_lb_strategy import PSLoadBalancing
from autodist_tpu.strategy.ps_strategy import PS
from autodist_tpu.strategy.random_axis_partition_all_reduce_strategy import (
    RandomAxisPartitionAR,
)
from autodist_tpu.strategy.uneven_partition_ps_strategy import UnevenPartitionedPS
from autodist_tpu.strategy.zero1_strategy import Zero1

__all__ = [
    "AllReduce", "AllReduceSynchronizerConfig", "AutoStrategy",
    "CompiledStrategy", "CostReport",
    "GraphConfig", "PS", "PSLoadBalancing", "PSSynchronizerConfig", "Parallax",
    "PartitionedAR", "PartitionedPS", "RandomAxisPartitionAR",
    "ScheduleTuner", "SearchResult", "SearchSpace", "Strategy",
    "StrategyBuilder", "StrategyCompiler", "UnevenPartitionedPS", "VarConfig",
    "VarPlan", "Zero1", "beam_search", "estimate_cost", "parse_partitioner",
    "plan_fingerprint", "rank_strategies",
]
