"""UnevenPartitionedPS: shard along axis 0 into a NON-divisor shard count.

Parity: reference ``autodist/strategy/uneven_partition_ps_strategy.py:28-135``
whose ``get_num_shards`` returns the first integer > 1 that does not divide
dim 0, producing uneven shards.  On TPU, GSPMD handles non-divisible sharding
by padding, so uneven shard counts compile fine.
"""
from __future__ import annotations

from autodist_tpu.strategy.partitioned_ps_strategy import PartitionedPS
from autodist_tpu.strategy.partition_utils import first_non_divisor


class UnevenPartitionedPS(PartitionedPS):
    def _num_shards(self, dim0: int, cap: int) -> int:
        n = first_non_divisor(dim0) or 1
        return n if n <= cap else 1
