"""PartitionedAR: shard each variable along axis 0, all-reduce each shard.

Parity: reference ``autodist/strategy/partitioned_all_reduce_strategy.py:25-130``
— num_shards is the smallest divisor > 1 of dim 0; each shard gets its own
AllReduceSynchronizer (and collective group).
"""
from __future__ import annotations

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import (
    AllReduceSynchronizerConfig,
    GraphConfig,
    Strategy,
    StrategyBuilder,
    VarConfig,
)
from autodist_tpu.strategy.partition_utils import (
    partition_str,
    partitionable,
    smallest_divisor_gt_one,
)


class PartitionedAR(StrategyBuilder):
    def __init__(self, chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor", max_shards: int = 0):
        """``max_shards``: cap on shards per variable; 0 ⇒ number of replica
        devices (prevents prime-length axes exploding into per-element shards)."""
        if chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        self._chunk_size = chunk_size
        self._spec = all_reduce_spec
        self._compressor = compressor
        self._max_shards = max_shards

    def _choose_axis_and_shards(self, var, cap: int):
        if partitionable(var, 0):
            n = smallest_divisor_gt_one(var.shape[0])
            if n is not None and n <= cap:
                return 0, n
        return None, None

    def build(self, graph_item: GraphItem, resource_spec: ResourceSpec) -> Strategy:
        node_config = []
        group_counter = 0
        cap = self._max_shards or max(len(resource_spec.devices), 2)
        for var in graph_item.trainable_var_infos:
            axis, n = self._choose_axis_and_shards(var, cap)
            sync = AllReduceSynchronizerConfig(
                spec=self._spec, compressor=self._compressor,
                group=group_counter // self._chunk_size)
            group_counter += 1
            if axis is None:
                node_config.append(VarConfig(var_name=var.name, synchronizer=sync))
                continue
            parts = [
                VarConfig(
                    var_name=f"{var.name}/part_{i}",
                    synchronizer=AllReduceSynchronizerConfig(
                        spec=self._spec, compressor=self._compressor,
                        group=(group_counter + i) // self._chunk_size))
                for i in range(n)
            ]
            group_counter += n
            node_config.append(VarConfig(
                var_name=var.name,
                partitioner=partition_str(var.shape, axis, n),
                part_config=parts,
                synchronizer=sync))
        return Strategy(
            node_config=node_config,
            graph_config=GraphConfig(replicas=self.replica_devices(resource_spec)),
        )
