"""ScheduleTuner: drift-triggered re-search + schedule hot-swap.

The search (:mod:`autodist_tpu.strategy.search`) finds the best schedule
FOR THE CONSTANTS IT WAS PRICED WITH.  When the hardware or workload
changes mid-run — a throttled host, a different batch mix, a refit that
moves a leg kind's bandwidth — the winning schedule can silently stop
being the winner.  The tuner closes the loop:

1. **watch** — live :class:`~autodist_tpu.telemetry.profiler.LegSample`s
   (micro-runs on the session mesh at a configured cadence, or samples
   fed by the caller) are compared per leg kind against the ACTIVE
   calibration through the shared ``telemetry/leg-drift`` rule
   (:func:`~autodist_tpu.telemetry.calibration.drifted_leg_kinds` —
   the same string the analysis pass and the CLI print);
2. **refit + re-search** — on drift, ``fit_leg_constants`` regresses
   fresh constants from the accumulated samples/records (persisted to
   the discovered ``calibration.json`` so every other consumer sees
   them) and the beam search re-runs on the fresh constants, with the
   currently-running strategy injected as a seed so it survives when it
   still wins;
3. **hot-swap** — when the winner's schedule fingerprint differs from
   the running one, the swap is first preflighted against per-chip HBM
   (:meth:`ScheduleTuner.watermark_veto`: the liveness watermark of
   the winner's schedule, ``analysis/dataflow.py``, against the spec's
   ``hbm_gb`` — a tuner must never swap onto an OOM schedule), then
   the schedule is swapped THROUGH the elastic-resume
   machinery: a RAM-tier snapshot (``checkpoint/tiers.py``) captures
   the logical training state, the step is rebuilt with the new
   strategy's IR (same mesh — compile only, no relaunch), and the
   snapshot restores into it bit-exact: params and the step counter
   always transfer exactly; optimizer moments transfer exactly within
   a sync family and re-initialize (one WARN) when the opt layout
   itself changes (tree optimizer vs ZeRO-1 flat shards), which is
   precisely the state an oracle started fresh on the new schedule
   would hold; compressor sync-state is schedule-keyed and always
   re-initializes.  Config drift the elastic path cannot absorb (a
   snapshot that fails its digest or leaf-count check) falls back to a
   persistent-checkpoint restart with one WARN when ``checkpoint_dir``
   is configured, and aborts the swap (keeping the old schedule)
   otherwise — a tuner must never lose state.

Wire it into training with ``fit(..., tuner=ScheduleTuner(...))``
(docs/strategies.md "Search"): the tuner's :meth:`on_step` hook runs at
its own ``interval`` cadence inside the step loop and swaps the session
IN PLACE, so the loop, callbacks, and checkpointing never notice.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from autodist_tpu.utils import logging


class ScheduleTuner:
    """Self-tuning loop around the strategy search (module docstring).

    Args:
      graph_item: the captured program (the search's variable catalog).
      resource_spec: the cluster spec candidates are built against.
      space: optional :class:`~autodist_tpu.strategy.search.SearchSpace`
        (budgets + searched axes) for re-searches.
      interval: :meth:`on_step` cadence in steps (0 disables the fit
        hook; :meth:`maybe_retune` still works when called directly).
      profile: at each interval, micro-run the session's current IR
        through :class:`~autodist_tpu.telemetry.profiler.LegProfiler`
        to produce fresh samples (set False when samples arrive via
        :meth:`feed_samples` — e.g. from trace parsing).
      constants: the ACTIVE calibration the running schedule was priced
        with (default: the environment-discovered ``calibration.json``).
      calibration_path: where refit constants persist (default: the
        discovered path; None persists nowhere).
      checkpoint_dir: the persistent-restart fallback directory for a
        swap the elastic path cannot absorb.
      min_samples: drift is only judged once at least this many live
        samples accumulated (micro-run noise must not thrash schedules).
    """

    def __init__(self, graph_item, resource_spec, *, space=None,
                 interval: int = 0, profile: bool = True,
                 constants=None, calibration_path: Optional[str] = None,
                 checkpoint_dir: Optional[str] = None,
                 min_samples: int = 1):
        from autodist_tpu.telemetry.calibration import (
            default_calibration_path,
            load_default_calibration,
        )

        self._gi = graph_item
        self._resource_spec = resource_spec
        self._space = space
        self._interval = max(int(interval), 0)
        self._profile = bool(profile)
        self._constants = constants if constants is not None \
            else load_default_calibration()
        self._calibration_path = calibration_path \
            if calibration_path is not None else default_calibration_path()
        self._checkpoint_dir = checkpoint_dir
        self._min_samples = max(int(min_samples), 1)
        self._samples: List = []
        self._records: List = []
        #: the last SearchResult a retune produced.
        self.last_result = None
        #: completed hot-swaps ("elastic" path) + persistent restarts.
        self.swaps = 0
        #: did the last swap transfer optimizer moments exactly (same
        #: sync family), or re-initialize them (layout change)?
        self.last_swap_exact_opt: Optional[bool] = None
        #: per-kind drift reasons of the last check that fired.
        self.last_drift: Dict[str, str] = {}

    # -- inputs ------------------------------------------------------------
    def feed_samples(self, samples) -> None:
        """Accumulate live LegSamples (profiler micro-runs or parsed
        trace spans) for the next drift check."""
        self._samples.extend(samples)

    def feed_records(self, records) -> None:
        """Accumulate StepRecords for the refit's scale correction."""
        self._records.extend(records)

    # -- the drift trigger -------------------------------------------------
    def drift_reasons(self) -> Dict[str, str]:
        """Per-kind ``telemetry/leg-drift`` verdicts of the accumulated
        samples against the ACTIVE constants ({} = no drift)."""
        from autodist_tpu.telemetry.calibration import drifted_leg_kinds

        if len(self._samples) < self._min_samples:
            return {}
        return drifted_leg_kinds(self._samples, self._constants)

    # -- the loop ----------------------------------------------------------
    def on_step(self, session, step: int) -> bool:
        """The ``fit`` hook: at every ``interval`` steps, collect fresh
        samples (when ``profile``) and run :meth:`maybe_retune`.
        Returns True when a swap happened."""
        if not self._interval or step <= 0 or step % self._interval:
            return False
        if self._profile:
            ir = getattr(session, "schedule_ir", None)
            if ir is not None:
                from autodist_tpu.telemetry.profiler import LegProfiler

                self.feed_samples(
                    LegProfiler(mesh=session.mesh).profile_ir(ir))
        rec = getattr(session, "telemetry", None)
        if rec is not None:
            # The recorder's ring IS the window of interest — replace,
            # never append (appending would double-count overlapping
            # views of the same bounded ring across intervals).
            self._records = list(rec.records)
        return self.maybe_retune(session)

    def maybe_retune(self, session) -> bool:
        """Check drift; on drift refit constants, re-search, and swap
        when the winner's fingerprint differs.  Returns True when the
        schedule changed."""
        from autodist_tpu.telemetry import emit_event
        from autodist_tpu.telemetry.calibration import (
            fit_leg_constants,
            save_calibration,
        )

        reasons = self.drift_reasons()
        if not reasons:
            return False
        self.last_drift = dict(reasons)
        for kind in sorted(reasons):
            logging.warning("tuner: %s", reasons[kind])
        emit_event("tuner/leg-drift", kinds=sorted(reasons),
                   n_samples=len(self._samples))
        refit = fit_leg_constants(self._samples, self._records)
        if refit is None:
            return False
        if self._calibration_path:
            try:
                save_calibration(refit, self._calibration_path)
                logging.info("tuner: refit constants persisted to %s",
                             self._calibration_path)
            except OSError as e:      # advisory: the search still runs
                logging.warning("tuner: could not persist refit "
                                "calibration (%s)", e)
        swapped = self.retune(session, constants=refit)
        # Fresh constants become the active baseline either way, and the
        # window that detected the drift is consumed.
        self._constants = refit
        self._samples = []
        return swapped

    def retune(self, session, constants=None) -> bool:
        """Re-run the search on ``constants`` (default: the active ones)
        and hot-swap when the winner's fingerprint differs from the
        running schedule's.  Returns True when a swap happened."""
        from autodist_tpu.strategy.search import beam_search
        from autodist_tpu.telemetry import emit_event

        constants = constants if constants is not None else self._constants
        axes = {str(k): int(v)
                for k, v in dict(session.mesh.shape).items()}
        current = session._step.compiled_strategy.strategy
        result = beam_search(
            self._gi, self._resource_spec, axes=axes, space=self._space,
            constants=constants, extra_seeds=[("current", current)])
        self.last_result = result
        if result.best is None or result.best_strategy is None:
            logging.warning("tuner: re-search produced no legal "
                            "candidate; keeping the running schedule")
            return False
        # Compare through the SAME projection the search prices: the
        # running strategy entered as the "current" seed, so its
        # fingerprint is in the result and the comparison cannot drift
        # on builder-vs-analyzer IR differences.
        current_fp = None
        for ev in result.evaluated:
            if ev.name == "seed:current":
                current_fp = ev.fingerprint
                break
        if current_fp is None:          # current deduped into an equal plan
            from autodist_tpu.strategy.search import evaluate_candidate, \
                genes_from_strategy
            ev, _ = evaluate_candidate(
                "current", genes_from_strategy(current, self._gi),
                self._gi, self._resource_spec, axes, constants)
            current_fp = ev.fingerprint if ev is not None else None
        if result.best.fingerprint == current_fp:
            logging.info(
                "tuner: re-search confirms the running schedule "
                "(%s, %.3f ms)", result.best.fingerprint,
                result.best.cost_s * 1e3)
            emit_event("tuner/retune", swapped=False,
                       fingerprint=result.best.fingerprint)
            return False
        return self.hot_swap(session, result.best_strategy,
                             winner=result.best)

    # -- the swap ----------------------------------------------------------
    def watermark_veto(self, strategy, axes) -> Optional[str]:
        """Hot-swap preflight: why the candidate strategy's schedule
        cannot fit per-chip HBM on ``axes`` (None = fits, or no
        ``hbm_gb`` budget to check against).  The same liveness
        watermark the search prunes with (``analysis/dataflow.py``) —
        defense in depth for winners injected via ``retune``'s seeds or
        a search run without the spec's budget."""
        hbm = getattr(self._resource_spec, "hbm_bytes_per_chip", None)
        if not hbm:
            return None
        from autodist_tpu.analysis import dataflow
        from autodist_tpu.analysis.search import facts_for_candidate
        from autodist_tpu.kernel.synchronization import schedule_ir as sir

        axes = {str(k): int(v) for k, v in dict(axes).items()}
        facts, _, guard, prune = facts_for_candidate(
            strategy, self._gi, axes)
        if prune is not None:
            return f"candidate fails legality preflight ({prune})"
        accum = int(getattr(self._gi, "accum_steps", 1) or 1)
        ir = sir.ir_from_facts(facts, axes=axes, accum_steps=accum,
                               guard=guard)
        wm = dataflow.watermark_for_facts(facts, ir, axes)
        if wm is not None and wm.peak_bytes > hbm:
            return (f"schedule watermark peak ≈ "
                    f"{wm.peak_bytes / (1 << 20):.1f} MiB at leg "
                    f"{wm.peak_leg!r} exceeds the per-chip HBM budget "
                    f"{hbm / (1 << 20):.1f} MiB")
        return None

    def adopt_snapshot(self, session, snap, new_step) -> bool:
        """Load a logical RAM snapshot into ``session`` running
        ``new_step`` (possibly a DIFFERENT sync schedule than the
        snapshot's writer).  Params and the step counter always
        transfer exactly; optimizer moments transfer when the new
        step's logical opt layout matches the snapshot leaf-for-leaf
        (same sync family) and re-initialize with one WARN otherwise
        (an opt-layout change — tree optimizer vs ZeRO-1 flat shards —
        is exactly the state an oracle cold-started on the new schedule
        would hold).  Compressor sync-state is schedule-keyed and
        always re-initializes.  Returns True when the moments
        transferred exactly."""
        import jax
        import numpy as np

        from autodist_tpu.checkpoint.tiers import SnapshotError

        if not snap.verify():
            raise SnapshotError(
                f"snapshot step {snap.step} failed its digest re-check "
                "— refusing to hot-swap onto corrupted state")
        ptree = jax.tree_util.tree_structure(self._gi.params)
        leaves = snap.leaves["params"]
        if ptree.num_leaves != len(leaves):
            raise SnapshotError(
                f"snapshot param leaf count {len(leaves)} != program "
                f"{ptree.num_leaves} (program changed since capture)")
        params = jax.tree_util.tree_unflatten(ptree, leaves)
        session._params = new_step.place_params(params)
        opt_init = new_step.init_fn(session._params)
        target = jax.eval_shape(new_step.export_opt_state, opt_init)
        flat_t, tdef = jax.tree_util.tree_flatten(target)
        ls = snap.leaves.get("opt_state", [])
        exact = len(ls) == len(flat_t) and all(
            tuple(t.shape) == tuple(np.shape(l))
            and np.dtype(t.dtype) == np.dtype(np.asarray(l).dtype)
            for t, l in zip(flat_t, ls))
        if exact:
            session._opt_state = new_step.import_opt_state(
                jax.tree_util.tree_unflatten(tdef, ls))
        else:
            session._opt_state = opt_init
            logging.warning(
                "tuner: optimizer-state layout changes across this "
                "schedule swap (%d -> %d logical leaves); moments "
                "re-initialize — the same state a run started fresh on "
                "the new schedule would hold", len(ls), len(flat_t))
        session._sync_state = new_step.init_sync_state(session._params)
        session._step_count = int(snap.step)
        return exact

    def hot_swap(self, session, strategy, winner=None) -> bool:
        """Swap the session onto ``strategy`` through the RAM snapshot
        tier: snapshot logical state, rebuild the step on the same mesh
        with the new IR, restore bit-exact.  Falls back to a
        persistent-checkpoint restart (one WARN) when the elastic path
        cannot absorb the config change; keeps the old schedule (and
        returns False) when no fallback exists."""
        from autodist_tpu.checkpoint.tiers import (
            SnapshotError,
            capture_snapshot,
        )
        from autodist_tpu.kernel.graph_transformer import GraphTransformer
        from autodist_tpu.strategy.compiler import StrategyCompiler
        from autodist_tpu.telemetry import emit_event

        veto = self.watermark_veto(strategy, dict(session.mesh.shape))
        if veto is not None:
            logging.warning(
                "tuner: hot-swap aborted — %s; keeping the running "
                "schedule", veto)
            emit_event("tuner/hot-swap", step=session.step_count,
                       tier=None, aborted=True, reason=veto)
            return False
        t0 = time.perf_counter()
        old_fp = session.schedule_fingerprint
        snap = capture_snapshot(session)
        old_step = session._step
        old_state = (session._params, session._opt_state,
                     session._sync_state, session._step_count)
        compiled = StrategyCompiler(
            session.mesh, resource_spec=self._resource_spec).compile(
                strategy, self._gi)
        new_step = GraphTransformer(compiled, self._gi).transform(
            extra_metrics_fn=self._gi.metrics_fn)
        session._step = new_step
        try:
            self.last_swap_exact_opt = self.adopt_snapshot(
                session, snap, new_step)
        except SnapshotError as e:
            session._step = old_step
            (session._params, session._opt_state, session._sync_state,
             session._step_count) = old_state
            return self._persistent_restart(session, new_step, e)
        session._flops_per_step = None
        self.swaps += 1
        dt = time.perf_counter() - t0
        logging.info(
            "tuner: hot-swapped schedule %s -> %s at step %d through the "
            "RAM snapshot tier (%.1f ms%s)", old_fp,
            session.schedule_fingerprint, session.step_count, dt * 1e3,
            f"; winner {winner.name} est {winner.cost_s * 1e3:.3f} ms"
            if winner is not None else "")
        emit_event("tuner/hot-swap", step=session.step_count,
                   from_fingerprint=old_fp,
                   to_fingerprint=session.schedule_fingerprint,
                   tier="ram", duration_s=round(dt, 6),
                   winner=winner.name if winner is not None else None)
        return True

    def _persistent_restart(self, session, new_step, err) -> bool:
        """The fallback for config drift elastic resume cannot absorb:
        persist a checkpoint from the OLD schedule, rebind the new step,
        restore from disk.  One WARN; False (old schedule kept) when no
        ``checkpoint_dir`` is configured."""
        from autodist_tpu.telemetry import emit_event

        if not self._checkpoint_dir:
            logging.warning(
                "tuner: hot-swap aborted — the RAM snapshot cannot cross "
                "this config change (%s) and no checkpoint_dir fallback "
                "is configured; keeping the running schedule", err)
            emit_event("tuner/hot-swap", step=session.step_count,
                       tier=None, aborted=True, reason=str(err))
            return False
        from autodist_tpu.checkpoint import Saver

        logging.warning(
            "tuner: RAM snapshot cannot cross this config change (%s) — "
            "falling back to a persistent-checkpoint restart through %s",
            err, self._checkpoint_dir)
        saver = Saver(session)
        saver.save(self._checkpoint_dir, step=session.step_count)
        saver.wait()
        session._step = new_step
        path = Saver.latest_checkpoint(self._checkpoint_dir)
        restored = saver.restore(path)
        session._flops_per_step = None
        self.swaps += 1
        emit_event("tuner/hot-swap", step=int(restored),
                   to_fingerprint=session.schedule_fingerprint,
                   tier="persistent", reason=str(err))
        return True
