"""Analytic communication cost model for strategies.

The AutoDist system's core pitch (the AutoSync line of work) is choosing a
per-variable synchronization strategy by *predicted cost*; the OSS
reference shipped only fixed builders and byte-size load balancing
(``ps_lb_strategy.py:91-117``'s ``byte_size_load_fn`` is its entire cost
model).  This module is the TPU-era version: a closed-form estimate of a
strategy's per-step wire traffic, collective count, and synchronization
time on a chip mesh, so strategies can be ranked *before* compiling
anything.

Model (standard ring-collective algebra, cf. the scaling-book recipe):

* all-reduce of ``n`` bytes over ``d`` devices moves ``2·(d−1)/d · n``
  per device, priced as its two legs — reduce-scatter ``(d−1)/d · n``
  plus all-gather ``(d−1)/d · n`` (``reduce_scatter_bytes`` /
  ``all_gather_bytes`` / ``allreduce_bytes``) — which is also exactly
  the PS/WUS lowering this framework emits, so AR and dense-PS differ
  in *state placement*, not wire volume; ZeRO-1 (``sync=
  "reduce_scatter"``) pays the RS leg on (compressed) gradients and the
  AG leg on full-precision params, with update traffic and slots /d;
* the weight update itself is HBM-bandwidth-bound: ``(1 + slots) ·
  param bytes`` of state touched per step, divided by ``d`` under any
  weight-update sharding (PS, ZeRO-1) — the term that separates
  reduce-scatter mode from all-reduce when wire volumes tie;
* compressors scale wire bytes (bf16 ½, int8 ¼) on the gradient leg
  (all-gather of fresh params stays full-precision for PS, compressed
  all-reduce applies to both legs);
* sparse (embedding) variables under PS move only the touched rows —
  ``min(batch_rows_hint, vocab)`` — while any dense synchronizer first
  densifies the gradient to the full table (the Parallax argument,
  ``parallax_strategy.py:24-71``);
* each collective pays a launch latency ``alpha``; grouped AllReduce
  variables share one launch when the lowering fuses them — explicit
  ``fused=True`` concat-and-pmean, or the default ``assume_combiner``
  assumption that XLA's all-reduce combiner merges same-program psums
  (the verified TPU behavior).  The combiner credit is applied at GROUP
  granularity — a deliberately conservative bound: the real combiner
  may merge across groups in one step program too, so multi-group
  strategies are charged an upper-bound launch count.
  ``assume_combiner=False`` costs one launch per variable instead;
* bandwidth: ICI within one host — and across hosts on a TPU pod slice
  (``ici_connected: true`` in the yaml: one interconnect domain); the
  yaml's ``network_bandwidth`` (NIC/DCN) is the bottleneck only for
  multi-node clusters WITHOUT that flag (the reference's GPU world, or
  multi-slice TPU).

Byte counts are exact given the hints; times are order-of-magnitude
estimates for *ranking*, not predictions of wall clock.
"""
from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from autodist_tpu.graph_item import GraphItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import (
    AllReduceSynchronizerConfig,
    PSSynchronizerConfig,
    Strategy,
)
from autodist_tpu.utils import logging

# Effective per-chip bandwidths (bytes/sec) and collective launch latency.
# ICI default ≈ v5e neighbor-link effective bandwidth; override per call.
ICI_BANDWIDTH = 45e9
# Cross-slice data-center network default (≈ 200 Gbps per chip-pair
# stream = 25 GB/s): the clock for `tier: dcn` legs when neither a
# fitted calibration nor a ResourceSpec `dcn_gbps` overrides it.
DCN_BANDWIDTH = 25e9
COLLECTIVE_ALPHA = 5e-6
# Per-chip HBM bandwidth (v5e ≈ 810 GB/s): clocks the optimizer-update
# memory traffic term — the weight update is bandwidth-bound (read+write
# params and slots), and weight-update sharding divides it by the
# data-axis size (the arXiv:2004.13336 win beyond state memory).
HBM_BANDWIDTH = 8.1e11

# Wire-format scale factors per compressor (vs f32 gradients).  Every
# SHIPPED compressor must appear here (or carry a quant_ring wire
# format) — the unknown-compressor WARN below is reserved for names the
# registry has never heard of.
_COMPRESSOR_SCALE = {
    "NoneCompressor": 1.0,
    "HorovodCompressor": 0.5,
    "HorovodCompressorEF": 0.5,
    "PowerSGDCompressor": 0.25,   # rank-r factors; nominal
    "Int8Compressor": 0.25,
    "Fp8Compressor": 0.25,        # e4m3: 1 byte/elem, like int8
}


def _compressor_scale(name: str) -> Optional[float]:
    """Wire-byte factor for ``name``, or None for an unknown compressor.
    Quantized-wire compressors fall back to their registered
    ``quant_ring`` wire format (1-byte payload) so a newly shipped
    format is priced without touching this table."""
    scale = _COMPRESSOR_SCALE.get(name)
    if scale is not None:
        return scale
    from autodist_tpu.kernel.synchronization import quant_ring
    fmt = quant_ring.wire_format_of(name)
    if fmt is not None:
        return fmt.itemsize / 4.0
    return None

# Adam-family: 2 slot tensors per parameter (m, v) in f32.
_OPT_SLOTS = 2


@dataclass
class VarCost:
    """Per-variable estimate."""

    name: str
    sync: str                    # "allreduce" | "zero1" | "ps" | "ps_sparse"
    wire_bytes: float            # per chip, per step
    opt_state_bytes: float       # per chip (slot tensors)
    group: Optional[int] = None  # AllReduce fusion group, if any
    update_bytes: float = 0.0    # HBM traffic of this var's weight update
    # Wire bytes the overlap schedule hides behind compute (accumulation
    # pipelining on the reduce leg, ZeRO-1 prefetch on the gather leg) —
    # the rest is EXPOSED on the step critical path.
    hidden_bytes: float = 0.0


@dataclass
class CostReport:
    """Whole-strategy estimate (per step, per chip)."""

    per_var: List[VarCost] = field(default_factory=list)
    wire_bytes: float = 0.0
    opt_state_bytes: float = 0.0
    update_bytes: float = 0.0
    num_collectives: int = 0
    time_s: float = 0.0
    # Wire bytes left on the critical path after the overlap schedule
    # (== wire_bytes when nothing overlaps).
    exposed_wire_bytes: float = 0.0
    # Per-network-tier wire accounting (filled by estimate_ir_cost):
    # keys "ici" / "dcn"; flat single-tier programs book everything
    # under "ici".  The `--simulate` sweep and the search explain
    # surface read these to show WHERE the exposed bytes travel.
    wire_by_tier: Dict[str, float] = field(default_factory=dict)
    exposed_wire_by_tier: Dict[str, float] = field(default_factory=dict)
    # Per-leg-kind exposed seconds (filled by estimate_ir_cost only —
    # the plan-level estimate has no legs to attribute): the breakdown
    # the search explain surface prints.
    per_kind: Dict[str, float] = field(default_factory=dict)
    # MPMD pipeline bubble (filled by estimate_ir_cost from the IR's
    # carried PipelineFacts): the 1F1B warm-up/drain idle fraction that
    # stretches the compute term — 0.0 for single-program schedules.
    bubble_fraction: float = 0.0

    @property
    def overlap_fraction(self) -> float:
        """Fraction of wire traffic the schedule hides behind compute."""
        if self.wire_bytes <= 0:
            return 0.0
        return 1.0 - self.exposed_wire_bytes / self.wire_bytes

    def summary(self) -> str:
        return (f"wire {self.wire_bytes / 1e6:.2f} MB/step/chip "
                f"({self.exposed_wire_bytes / 1e6:.2f} MB exposed) over "
                f"{self.num_collectives} collectives, opt-state "
                f"{self.opt_state_bytes / 1e6:.2f} MB/chip, "
                f"est {self.time_s * 1e3:.3f} ms sync time")


def _ring_factor(d: int) -> float:
    return 2.0 * (d - 1) / d if d > 1 else 0.0


# -- per-device ring-collective byte accounting ------------------------------
# All three are exact for the standard ring/bidirectional algorithms (and
# what ICI achieves): an all-reduce IS a reduce-scatter followed by an
# all-gather, so it costs the sum of the two legs — never a flat `bytes`.

def reduce_scatter_bytes(nbytes: float, d: int) -> float:
    """(d−1)/d · nbytes per device: each device sends all but its own
    1/d chunk once around the ring."""
    return (d - 1) / d * nbytes if d > 1 else 0.0


def all_gather_bytes(nbytes: float, d: int) -> float:
    """(d−1)/d · nbytes per device (same ring, data flowing back)."""
    return (d - 1) / d * nbytes if d > 1 else 0.0


def allreduce_bytes(nbytes: float, d: int) -> float:
    """2·(d−1)/d · nbytes per device = reduce-scatter + all-gather."""
    return reduce_scatter_bytes(nbytes, d) + all_gather_bytes(nbytes, d)


def _shard_count(partitioner: str) -> int:
    if not partitioner:
        return 1
    return int(np.prod([int(x) for x in partitioner.split(",")]))


def estimate_cost(strategy: Strategy, graph_item: GraphItem,
                  resource_spec: ResourceSpec, *,
                  sparse_rows_hint: int = 4096,
                  ici_bandwidth: float = ICI_BANDWIDTH,
                  alpha: float = COLLECTIVE_ALPHA,
                  assume_combiner: bool = True,
                  compute_time_s: float = 0.0) -> CostReport:
    """Estimate one strategy's per-step sync cost on ``resource_spec``.

    Overlap-aware: per-variable ``overlap=`` schedules (the knob on
    ``AllReduceSynchronizerConfig``; shared rules in
    ``kernel.synchronization.overlap``) move wire bytes from the EXPOSED
    to the HIDDEN column — accumulation pipelining hides
    ``(accum−1)/accum`` of the gradient reduce leg behind the microbatch
    backward, ZeRO-1 prefetch hides ``PREFETCH_OVERLAP_FRACTION`` of the
    param all-gather behind the next step's prologue — and the estimate
    becomes ``max(compute, exposed_comm) + update`` instead of the plain
    additive sum, so a pipelined mode prices correctly against an
    unpipelined one.  Ring decomposition is a latency-shape change, not
    a byte change, and is priced neutrally.  ``accum_steps`` is read off
    ``graph_item``.

    Args:
      sparse_rows_hint: rows a batch touches in each sparse variable (an
        upper bound: capped at the vocab size); the model cannot know the
        batch, so callers with real input stats should pass them.
      compute_time_s: optional per-step compute time (0.0 = unknown):
        the floor the exposed communication is maxed against.
      assume_combiner: when True (default), AllReduce variables sharing a
        strategy group are costed as ONE collective launch — the TPU
        reality, where XLA's all-reduce combiner merges same-program
        psums (verified in HLO, ``graph_transformer.py`` combiner
        lowering) and ``fused=True`` groups concat explicitly.  The
        credit is deliberately applied per GROUP, not per step program:
        the real combiner can merge across groups too, so multi-group
        strategies carry a conservative (upper-bound) launch count that
        keeps the ranking sensitive to grouping quality.  Pass
        False to cost one launch per variable (a backend whose combiner
        is disabled).  An explicit ASSUMPTION, not ambient env state —
        the estimate must be reproducible.
    """
    d = max(resource_spec.num_chips, 1)
    # Bandwidth clock per the module docstring; `ici_connected` semantics
    # are defined at ResourceSpec._parse.
    multi_node = (resource_spec.num_nodes > 1
                  and not resource_spec.ici_connected)
    dcn = resource_spec.network_bandwidth_gbps * 1e9 / 8
    # A multi-slice pod bottlenecks flat collectives on the cross-slice
    # DCN tier regardless of ici_connected — the plan-level estimate has
    # no hierarchical legs, so the honest flat price uses the DCN clock.
    if getattr(resource_spec, "num_slices", 1) > 1:
        multi_node = True
        if resource_spec.dcn_gbps is not None:
            dcn = resource_spec.dcn_bytes_per_s
    bandwidth = min(ici_bandwidth, dcn) if multi_node else ici_bandwidth

    from autodist_tpu.kernel.synchronization import overlap as ov

    accum = int(getattr(graph_item, "accum_steps", 1) or 1)
    report = CostReport()
    groups_seen = set()
    infos = {v.name: v for v in graph_item.trainable_var_infos}
    for cfg in strategy.node_config:
        info = infos.get(cfg.var_name)
        if info is None:
            continue
        nbytes = info.byte_size
        sync = cfg.synchronizer
        if isinstance(sync, AllReduceSynchronizerConfig):
            scale = _compressor_scale(sync.compressor)
            if scale is None:
                logging.warning(
                    "cost model: unknown compressor %r — assuming "
                    "uncompressed wire format", sync.compressor)
                scale = 1.0
            mode = getattr(sync, "sync", "all_reduce") or "all_reduce"
            # Overlap schedule: which legs leave the critical path.  The
            # eligibility rules are the runtime's own (overlap.py), keyed
            # on the SAME knob — `bucketable` approximated by the absence
            # of a partitioner (partitioned vars ride the per-variable
            # fallback and never join the overlapped bucket schedule).
            ov_mode = getattr(sync, "overlap", "auto") or "auto"
            bucketable = not cfg.partitioner
            explicit = ov.explicit_hint(
                sync.compressor, mode,
                getattr(sync, "bucket_bytes", 0),
                fused=getattr(sync, "fused", False), overlap=ov_mode,
                hier=getattr(sync, "hier", False))
            pipelined = ov.pipeline_applies(
                ov_mode, accum_steps=accum, compressor=sync.compressor,
                bucketable=bucketable, explicit_path=explicit,
                dtype=info.dtype)
            hidden = 0.0
            if mode == "reduce_scatter" and d > 1:
                # ZeRO-1: the compressed reduce leg moves HALF the
                # all-reduce volume; fresh params come back through a
                # full-precision all-gather, and the weight update (and
                # its slots) is sharded 1/d across the data axis.
                reduce_leg = reduce_scatter_bytes(nbytes * scale, d)
                gather_leg = all_gather_bytes(nbytes, d)
                wire = reduce_leg + gather_leg
                if pipelined:
                    hidden += reduce_leg * (accum - 1) / accum
                if bucketable and ov.prefetch_applies(
                        ov_mode, sync_mode=mode, explicit_path=explicit):
                    hidden += gather_leg * ov.PREFETCH_OVERLAP_FRACTION
                vc = VarCost(cfg.var_name, "zero1", wire,
                             _OPT_SLOTS * nbytes / d, group=sync.group,
                             update_bytes=(1 + _OPT_SLOTS) * nbytes / d,
                             hidden_bytes=hidden)
            else:
                wire = allreduce_bytes(nbytes, d) * scale
                # Sparse under AR densifies first — wire covers the FULL
                # table (the reason Parallax exists); nbytes already is
                # the table.  The update is replicated: every chip touches
                # the full parameter + slot bytes.
                if pipelined:
                    hidden += wire * (accum - 1) / accum
                vc = VarCost(cfg.var_name, "allreduce", wire,
                             _OPT_SLOTS * nbytes, group=sync.group,
                             update_bytes=(1 + _OPT_SLOTS) * nbytes,
                             hidden_bytes=hidden)
            # Launch latency: a group shares ONE launch when the lowering
            # fuses it — explicit concat-and-pmean (fused=True), bucketed
            # lowering, or the assume_combiner default (XLA's combiner
            # merges same-program psums on TPU; counted per GROUP as a
            # conservative bound — see estimate_cost docstring).
            # Otherwise one per variable.  reduce_scatter mode pays two
            # launches (RS + param AG) where all-reduce pays one.
            group_fuses = getattr(sync, "fused", False) or assume_combiner \
                or getattr(sync, "bucket_bytes", 0) > 0
            launches = 2 if vc.sync == "zero1" else 1
            if d > 1:
                if not group_fuses:
                    report.num_collectives += launches
                elif sync.group not in groups_seen:
                    groups_seen.add(sync.group)
                    report.num_collectives += launches
        elif isinstance(sync, PSSynchronizerConfig):
            shards = max(_shard_count(cfg.partitioner), 1)
            if info.sparse:
                rows = min(sparse_rows_hint, info.shape[0] or 1)
                row_bytes = nbytes / max(info.shape[0], 1)
                # scatter-add of touched rows to owners + gather back.
                wire = reduce_scatter_bytes(rows * row_bytes, d) \
                    + all_gather_bytes(rows * row_bytes, d)
                kind = "ps_sparse"
                opt_bytes = _OPT_SLOTS * nbytes / d  # vocab-sharded slots
                upd_bytes = (1 + _OPT_SLOTS) * nbytes / d
            else:
                # reduce-scatter grads + all-gather fresh params = ring
                # volume.  Slot layout mirrors the compiler's weight-update
                # sharding (_wus_opt_spec): sharded over the mesh whenever
                # the partitioner or an evenly-divisible dim allows; tiny
                # odd variables replicate.
                wire = reduce_scatter_bytes(nbytes, d) \
                    + all_gather_bytes(nbytes, d)
                kind = "ps"
                can_shard = shards > 1 or any(
                    s and s % d == 0 for s in info.shape)
                sharded = d > 1 and can_shard
                opt_bytes = _OPT_SLOTS * nbytes / (d if sharded else 1)
                upd_bytes = (1 + _OPT_SLOTS) * nbytes / (d if sharded else 1)
            vc = VarCost(cfg.var_name, kind, wire, opt_bytes,
                         update_bytes=upd_bytes)
            if d > 1:
                report.num_collectives += 2  # RS + AG
        else:
            continue
        report.per_var.append(vc)
        report.wire_bytes += vc.wire_bytes
        report.exposed_wire_bytes += vc.wire_bytes - vc.hidden_bytes
        report.opt_state_bytes += vc.opt_state_bytes
        report.update_bytes += vc.update_bytes
    # The weight update is HBM-bandwidth-bound (read params + slots,
    # write them back): sharded updates (PS/WUS, ZeRO-1) touch 1/d of it
    # per chip, which is the term that separates reduce-scatter mode from
    # all-reduce when their wire volumes tie.  Counted only when there is
    # a distribution decision to make (d > 1).
    #
    # Overlap-aware aggregation: only the EXPOSED wire sits on the step
    # critical path; hidden bytes ride behind compute, so the step pays
    # max(compute, exposed comm) — with no compute hint (0.0) the max
    # degrades to the exposed-comm time, and with no overlap the whole
    # formula degrades to the PR 2 additive estimate.
    update_s = report.update_bytes / HBM_BANDWIDTH if d > 1 else 0.0
    comm_s = (report.exposed_wire_bytes / bandwidth
              + alpha * report.num_collectives)
    report.time_s = max(compute_time_s, comm_s) + update_s
    return report


def leg_participants(leg, ir) -> int:
    """Device count a leg's ring spans — the ``d`` of its byte algebra.

    Flat legs span the full mesh axis.  Hierarchical legs split the axis
    by the IR's ``num_slices``: ``tier: ici`` legs ring over the
    within-slice group (``d // num_slices``), ``tier: dcn`` legs over
    one representative per slice (``num_slices`` peers)."""
    from autodist_tpu.kernel.synchronization import schedule_ir as sir

    if leg.kind in sir.TRANSPORT_KINDS:
        # Pipeline activation transport is point-to-point: one sender
        # stage, one receiver stage, whatever the mesh axes say.
        return 2
    d = max(int(ir.axes.get(leg.axis, 1)), 1) if leg.axis else 1
    tier = getattr(leg, "tier", "")
    s = max(int(getattr(ir, "num_slices", 1) or 1), 1)
    if tier == sir.TIER_DCN:
        return max(s, 1)
    if tier == sir.TIER_ICI and s > 1 and d % s == 0:
        return max(d // s, 1)
    return d


def leg_tier(leg, ir) -> str:
    """Network tier a leg's wire actually traverses.

    Tiered (hierarchical) legs carry their tier explicitly.  An
    UNTIERED collective on the data axis of a multi-slice program is a
    flat ring spanning slice boundaries — its throughput is bound by
    the DCN crossings, so it prices (and books its wire) as DCN.  This
    is the term that makes the hierarchy win exactly when it should:
    the flat alternative pays full ring volume at DCN speed, the
    two-tier lowering pays only the 1/d_in cross-slice exchange there."""
    from autodist_tpu.const import MESH_AXIS_DATA
    from autodist_tpu.kernel.synchronization import schedule_ir as sir

    tier = getattr(leg, "tier", "")
    if tier:
        return tier
    s = max(int(getattr(ir, "num_slices", 1) or 1), 1)
    if s > 1 and leg.axis == MESH_AXIS_DATA:
        d = max(int(ir.axes.get(leg.axis, 1)), 1)
        if d % s == 0 and d > s:
            return sir.TIER_DCN
    return sir.TIER_ICI


def _leg_wire_bytes(leg, d: int) -> float:
    """One leg's per-device wire bytes under the ring algebra (hop legs
    already carry per-hop bytes; the guard psum is scalar-sized).
    ``d`` is the leg's OWN participant count (:func:`leg_participants`)
    — within-slice group size for ``tier: ici`` legs, slice count for
    ``tier: dcn`` legs — so hierarchical legs price their honest
    per-tier traffic."""
    from autodist_tpu.kernel.synchronization import schedule_ir as sir

    if leg.kind in sir.RING_HOP_KINDS:
        return float(leg.nbytes)
    if leg.kind in (sir.LEG_ALL_REDUCE, sir.LEG_PS_EXCHANGE,
                    sir.LEG_DCN_ALL_REDUCE):
        return allreduce_bytes(float(leg.nbytes), d)
    if leg.kind in (sir.LEG_REDUCE_SCATTER, sir.LEG_ALL_GATHER,
                    sir.LEG_HIER_REDUCE_SCATTER, sir.LEG_DCN_EXCHANGE,
                    sir.LEG_HIER_ALL_GATHER):
        return reduce_scatter_bytes(float(leg.nbytes), d)
    if leg.kind == sir.LEG_ALL_TO_ALL:
        # Each device keeps its own 1/d slice and ships the other
        # (d-1)/d of its per-device payload (the leg's nbytes are
        # already per-device capacity-buffer bytes).
        return float(leg.nbytes) * (d - 1) / max(d, 1)
    if leg.kind == sir.LEG_RECV_ACT:
        # The send half books the payload (one DCN transfer per
        # boundary pair); the recv is the blocking fetch — a launch,
        # not a second copy of the wire bytes.
        return 0.0
    return float(leg.nbytes)


#: Borrow source for UNFITTED leg kinds, in one place (new kinds declare
#: theirs here instead of growing another if-chain in ``leg_cost_s``):
#: when a calibration carries no constants for ``kind``, it is priced
#: with the mapped kind's fitted constants instead of the optimistic
#: defaults, resolved transitively (``fused_hop`` → ``ppermute_hop``;
#: ``dcn_exchange`` → ``dcn_all_reduce`` → ``ps_exchange`` →
#: ``all_reduce``).  Rationale per edge: a fused wire is the unfused
#: wire; PS/WUS and expert a2a move an all-reduce's ring volume over the
#: same links; the DCN kinds borrow the ps_exchange chain so an
#: ICI-only calibration prices hierarchy pessimistically (never free).
FALLBACK_KINDS = {
    "fused_hop": "ppermute_hop",
    "ps_exchange": "all_reduce",
    "all_to_all": "all_reduce",
    "dcn_all_reduce": "ps_exchange",
    "dcn_exchange": "dcn_all_reduce",
    "hier_reduce_scatter": "reduce_scatter",
    "hier_all_gather": "all_gather",
    # Pipeline activation transport rides the same cross-slice links as
    # the DCN shard exchange; an ICI-only calibration prices it
    # pessimistically through the same chain (never free).
    "send_act": "dcn_all_reduce",
    "recv_act": "send_act",
}


def resolve_priced_kind(kind: str, constants) -> str:
    """Kind whose fitted constants price ``kind``: itself when fitted,
    else the first fitted ancestor along :data:`FALLBACK_KINDS`; the
    original kind when the whole chain is unfitted (default pricing)."""
    if constants is None or kind in constants.bandwidths:
        return kind
    seen = {kind}
    cur = kind
    while cur not in constants.bandwidths:
        nxt = FALLBACK_KINDS.get(cur)
        if nxt is None or nxt in seen:
            return kind
        seen.add(nxt)
        cur = nxt
    return cur


def leg_cost_s(leg, ir, constants=None, *,
               ici_bandwidth: float = ICI_BANDWIDTH,
               dcn_bandwidth: float = DCN_BANDWIDTH,
               alpha: float = COLLECTIVE_ALPHA) -> Optional[float]:
    """Price ONE schedule-IR leg: wire bytes / bandwidth + a launch
    alpha, per-kind when ``constants`` (a
    ``telemetry.calibration.LegCalibration``) is given, the global
    defaults otherwise.  Update legs price their HBM traffic (the
    per-kind ``update`` bandwidth, or :data:`HBM_BANDWIDTH`).  An
    unfitted kind borrows its :data:`FALLBACK_KINDS` ancestor's fitted
    constants (one declaration per kind, resolved transitively by
    :func:`resolve_priced_kind`); with no fitted ancestor either, the
    leg prices at the default clock for its tier —
    ``ici_bandwidth``, or ``dcn_bandwidth`` for ``tier: dcn`` legs.
    Returns None for a leg kind the model does not price.  This is the
    prediction half of every per-leg measured-vs-predicted pair
    (``telemetry.profiler.LegSample.predicted_s``)."""
    from autodist_tpu.kernel.synchronization import schedule_ir as sir

    d = leg_participants(leg, ir)
    if leg.kind in (sir.LEG_UPDATE, sir.LEG_FUSED_UPDATE,
                    sir.LEG_FUSED_DETECT):
        # HBM-bound local passes.  Fused kinds price through their OWN
        # calibration constants when fitted (fused-vs-unfused must rank
        # as distinct alternatives); an unfitted fused_update falls back
        # to the unfused update constant, and everything degrades to the
        # raw HBM clock.
        if constants is not None:
            if leg.kind in constants.bandwidths:
                return constants.leg_time_s(leg.kind, float(leg.nbytes))
            if leg.kind == sir.LEG_FUSED_UPDATE \
                    and "update" in constants.bandwidths:
                return constants.leg_time_s("update", float(leg.nbytes))
        return float(leg.nbytes) / HBM_BANDWIDTH
    if leg.kind not in sir.COLLECTIVE_KINDS:
        return None
    wire = _leg_wire_bytes(leg, d)
    launches = 1 if (d > 1 or leg.kind == sir.LEG_PSUM_GUARD) else 0
    kind = resolve_priced_kind(leg.kind, constants)
    if constants is not None and kind in constants.bandwidths:
        bw_fit = constants.bandwidths[kind]
        if leg_tier(leg, ir) == sir.TIER_DCN:
            # The cross-slice ceiling is a TOPOLOGY parameter, not a
            # collective property: a DCN-bound leg can never beat the
            # spec's dcn bandwidth, however fast the fitted constant
            # (measured on whatever fabric calibrated it) claims.
            bw_fit = min(bw_fit, dcn_bandwidth)
        t = wire / bw_fit
        if launches:
            t += constants.alphas.get(kind, COLLECTIVE_ALPHA)
        if sir.is_quantizing(leg.compressor):
            t += constants.quant_overhead_per_byte * wire
        return t
    bw = dcn_bandwidth if leg_tier(leg, ir) == sir.TIER_DCN \
        else ici_bandwidth
    return wire / bw + alpha * launches


def act_transport_bytes(ir) -> Tuple[float, float]:
    """``(total, exposed)`` DCN activation-transport wire bytes per
    step: the ``send_act`` legs' wire (``recv_act`` books zero — same
    blob, counted once).  Exposure mirrors :func:`estimate_ir_cost`'s
    slot rule — a transfer in microbatch slot ``< accum-1`` rides
    behind the next microbatch's compute (the 1F1B steady state), only
    the final slot's boundary crossings are exposed.  The per-point
    ``--simulate`` column (docs/pipeline.md)."""
    from autodist_tpu.kernel.synchronization import schedule_ir as sir

    accum = max(int(ir.accum_steps), 1)
    total = exposed = 0.0
    for leg in ir.legs:
        if leg.kind != sir.LEG_SEND_ACT:
            continue
        wire = _leg_wire_bytes(leg, leg_participants(leg, ir))
        total += wire
        if leg.slot == sir.END_OF_STEP or leg.slot >= accum - 1:
            exposed += wire
    return total, exposed


def estimate_ir_cost(ir, *, ici_bandwidth: float = ICI_BANDWIDTH,
                     dcn_bandwidth: float = DCN_BANDWIDTH,
                     alpha: float = COLLECTIVE_ALPHA,
                     compute_time_s: float = 0.0,
                     constants=None) -> CostReport:
    """Price a sync-schedule IR (docs/schedule-ir.md) leg by leg.

    Where :func:`estimate_cost` prices the *plan projection* (it must
    guess which legs the lowering emits), this prices the PROGRAM: each
    collective leg's bytes land in the exposed or hidden column from
    its own microbatch slot — reduce legs in slots ``0..accum-2`` ride
    behind the next microbatch's backward, only the final slot is
    exposed; ZeRO-1 gather legs hide ``PREFETCH_OVERLAP_FRACTION``
    under prefetch issue order — and every leg (each ring hop
    individually) pays one ``alpha`` launch, which is exactly the
    latency-shape difference between a ring chain and a fused
    collective that the plan-level estimate prices neutrally.
    Per-device ring-collective byte algebra: a leg's recorded
    ``nbytes`` is the full vector, scaled here by ``(d-1)/d`` per leg
    direction (hop legs already carry per-hop bytes).  Quantized legs
    (int8/fp8 buckets) arrive with the HONEST wire size — 1-byte/elem
    payload plus the per-chunk scale bytes per transfer, per hop for
    ring chains — stamped by the IR builder, so the compressed wire is
    priced exactly rather than as the f32 vector.

    ``constants`` takes a measured ``telemetry.calibration.
    LegCalibration``: each leg kind is then priced with ITS OWN fitted
    launch alpha and bandwidth (ring-hop alpha vs one-shot alpha,
    RS/AG/AR bandwidths, quantize overhead, update cost), and update
    legs join the estimate through the fitted update bandwidth.  When
    ``constants`` is None the default calibration discovered from the
    environment (``AUTODIST_CALIBRATION`` /
    ``AUTODIST_TELEMETRY_DIR/calibration.json`` — see
    ``load_default_calibration``) applies automatically; without one
    the uncalibrated single-bandwidth model below is unchanged."""
    from autodist_tpu.kernel.synchronization import overlap as ov
    from autodist_tpu.kernel.synchronization import schedule_ir as sir

    if constants is None:
        from autodist_tpu.telemetry.calibration import (
            load_default_calibration,
        )
        constants = load_default_calibration()

    report = CostReport()
    accum = max(int(ir.accum_steps), 1)
    calibrated_comm_s = 0.0
    update_s = 0.0
    comm_kind_s: Dict[str, float] = {}
    for leg in ir.legs:
        if leg.kind in (sir.LEG_UPDATE, sir.LEG_FUSED_UPDATE,
                        sir.LEG_FUSED_DETECT):
            # Local HBM-bound legs join the estimate once calibration
            # knows their cost (fused kinds carry their own constants so
            # fused-vs-unfused price as distinct alternatives; an
            # unfitted fused_update borrows the unfused update constant
            # inside leg_cost_s).
            fitted = constants is not None and (
                leg.kind in constants.bandwidths
                or (leg.kind in (sir.LEG_UPDATE, sir.LEG_FUSED_UPDATE)
                    and "update" in constants.bandwidths))
            if fitted:
                t = leg_cost_s(leg, ir, constants)
                if t is not None:
                    update_s += t
                    report.per_kind[leg.kind] = \
                        report.per_kind.get(leg.kind, 0.0) + t
            continue
        if leg.kind not in sir.COLLECTIVE_KINDS:
            continue
        d = leg_participants(leg, ir)
        wire = _leg_wire_bytes(leg, d)
        hidden = 0.0
        if leg.slot != sir.END_OF_STEP and leg.slot < accum - 1:
            hidden = wire                     # rides behind backward k+1
        elif leg.kind in (sir.LEG_ALL_GATHER, sir.LEG_HIER_ALL_GATHER) \
                and ir.prefetch:
            hidden = wire * ov.PREFETCH_OVERLAP_FRACTION
        tier = leg_tier(leg, ir)
        report.wire_bytes += wire
        report.exposed_wire_bytes += wire - hidden
        report.wire_by_tier[tier] = report.wire_by_tier.get(tier, 0.0) \
            + wire
        report.exposed_wire_by_tier[tier] = \
            report.exposed_wire_by_tier.get(tier, 0.0) + wire - hidden
        launched = d > 1 or leg.kind == sir.LEG_PSUM_GUARD
        if launched:
            report.num_collectives += 1
        exposed_fraction = (wire - hidden) / wire if wire > 0 \
            else (0.0 if hidden else 1.0)
        if constants is not None:
            t = leg_cost_s(leg, ir, constants,
                           ici_bandwidth=ici_bandwidth,
                           dcn_bandwidth=dcn_bandwidth, alpha=alpha)
            if t is not None:
                calibrated_comm_s += t * exposed_fraction
                comm_kind_s[leg.kind] = comm_kind_s.get(leg.kind, 0.0) \
                    + t * exposed_fraction
        else:
            bw = dcn_bandwidth if tier == sir.TIER_DCN else ici_bandwidth
            t = ((wire - hidden) / bw
                 + (alpha if launched else 0.0))
            comm_kind_s[leg.kind] = comm_kind_s.get(leg.kind, 0.0) + t
    scale = constants.scale if constants is not None else 1.0
    for kind, t in comm_kind_s.items():
        report.per_kind[kind] = report.per_kind.get(kind, 0.0) + t * scale
    if constants is not None:
        comm_s = constants.scale * calibrated_comm_s
    else:
        exposed_dcn = report.exposed_wire_by_tier.get(sir.TIER_DCN, 0.0)
        comm_s = ((report.exposed_wire_bytes - exposed_dcn) / ici_bandwidth
                  + exposed_dcn / dcn_bandwidth
                  + alpha * report.num_collectives)
    # MPMD pipeline bubble (docs/pipeline.md): the 1F1B warm-up/drain
    # idle ticks stretch the compute term by 1/(1 - bubble) — the
    # steady-state transport legs are already priced (hidden behind
    # slots 0..M-2, exposed on the last slot) by the loop above.
    for pf in getattr(ir, "pipeline", ()) or ():
        report.bubble_fraction = max(report.bubble_fraction,
                                     pf.bubble_fraction())
    if report.bubble_fraction > 0.0 and compute_time_s > 0.0:
        compute_time_s = compute_time_s / (1.0 - report.bubble_fraction)
    report.time_s = max(compute_time_s, comm_s) + update_s
    return report


def plan_fingerprint(strategy: Strategy) -> str:
    """Short stable hash of a strategy's per-variable plan — the
    node-config projection only (ids, timestamps, and replica lists are
    excluded), so two builders that emit the SAME plan hash identically.
    The dedupe key of the deterministic-ranking contract
    (``rank_strategies(dedupe=True)`` / ``AutoStrategy(search=...)``)."""
    blob = json.dumps([n.to_dict() for n in strategy.node_config],
                      sort_keys=True, separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def rank_strategies(graph_item: GraphItem, resource_spec: ResourceSpec,
                    builders: Optional[Sequence] = None,
                    dedupe: bool = False, **cost_kwargs
                    ) -> List[Tuple[str, CostReport]]:
    """Build each candidate strategy and rank by estimated sync time.

    Default candidates: every shipped fixed builder plus AutoStrategy.
    Returns ``[(builder_class_name, CostReport), ...]`` fastest first —
    the pre-compile answer to "which strategy should I use here?".

    Deterministic run-to-run: ties break by ``(cost, builder name)``,
    and ``dedupe=True`` drops later candidates whose
    :func:`plan_fingerprint` matches an earlier one (two builders that
    degenerate to the same plan — e.g. PS and PSLoadBalancing on a
    single reduction destination — rank once).  Default False so the
    report still names every builder asked about.
    """
    if builders is None:
        from autodist_tpu.strategy import (
            AllReduce,
            AutoStrategy,
            Parallax,
            PartitionedAR,
            PartitionedPS,
            PS,
            PSLoadBalancing,
            RandomAxisPartitionAR,
            UnevenPartitionedPS,
            Zero1,
        )
        builders = [PS(), PSLoadBalancing(), PartitionedPS(),
                    UnevenPartitionedPS(), AllReduce(), PartitionedAR(),
                    RandomAxisPartitionAR(), Parallax(), Zero1(),
                    AutoStrategy()]
    ranked = []
    seen_plans = set()
    for b in builders:
        strat = b.build(graph_item, resource_spec)
        if dedupe:
            fp = plan_fingerprint(strat)
            if fp in seen_plans:
                continue
            seen_plans.add(fp)
        ranked.append((type(b).__name__,
                       estimate_cost(strat, graph_item, resource_spec,
                                     **cost_kwargs)))
    ranked.sort(key=lambda kv: (kv[1].time_s, kv[0]))
    return ranked
