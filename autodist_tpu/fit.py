"""High-level training loop: ``DistributedSession.fit``.

Parity target: the reference's Keras ``Model.fit`` path — its Keras patch
(``autodist/patch.py:119-197``) existed so ``model.fit`` ran against the
distributed session (integration case ``tests/integration/cases/c7.py``),
and its benchmarks measured throughput with a Keras ``TimeHistory``
callback (``examples/benchmark/imagenet.py:85-120``).  TPU-natively there
is no session to patch under a framework's feet; ``fit`` IS the loop:
epochs × steps with device prefetch and async dispatch, Keras-style
callbacks, periodic host-side logging, and optional checkpoint/resume.

Design constraints (why this isn't a 5-line loop):

* The hot loop must stay async — fetching every step's loss to host would
  serialize dispatch over the host↔TPU link.  Losses land on host only at
  ``log_every`` boundaries and epoch ends; in between, steps chain on
  device.
* Checkpoint/resume reuses :class:`autodist_tpu.checkpoint.saver.Saver`,
  so ``fit`` checkpoints interchange with single-device programs like any
  other checkpoint in this framework.
"""
from __future__ import annotations

import contextlib
import itertools
import os
import signal as _signal
import time
from typing import Any, Callable, Dict, Iterable, Optional, Sequence, Union

import numpy as np

from autodist_tpu.numerics.policy import (  # light import: no jax
    NonFiniteError,
    RollbackRequest as _RollbackRequest,
    emit_failure_marker as _emit_failure_marker,
)
from autodist_tpu.utils import logging


class Callback:
    """Keras-style callback protocol (all hooks optional).

    ``metrics`` passed to ``on_step_end`` are DEVICE arrays — converting
    them to host values blocks async dispatch; do so sparingly.
    """

    def on_train_begin(self, session) -> None: ...

    def on_epoch_begin(self, epoch: int) -> None: ...

    def on_step_end(self, step: int, metrics: Dict[str, Any]) -> None: ...

    def on_epoch_end(self, epoch: int, logs: Dict[str, Any]) -> None: ...

    def on_train_end(self, history: "History") -> None: ...


class TimeHistory(Callback):
    """Per-epoch wall time + items/sec — the reference benchmark's
    ``TimeHistory`` callback (examples/benchmark/imagenet.py:85-120)."""

    def __init__(self, items_per_step: Optional[int] = None):
        self.items_per_step = items_per_step
        self.epoch_times: list = []
        self.items_per_sec: list = []
        self._t0 = 0.0
        self._steps = 0

    def on_epoch_begin(self, epoch: int) -> None:
        self._t0 = time.perf_counter()
        self._steps = 0

    def on_step_end(self, step: int, metrics) -> None:
        self._steps += 1

    def on_epoch_end(self, epoch: int, logs) -> None:
        dt = time.perf_counter() - self._t0
        self.epoch_times.append(dt)
        if self.items_per_step and dt > 0:
            self.items_per_sec.append(self.items_per_step * self._steps / dt)


class History:
    """What ``fit`` returns (Keras ``History`` analog).

    ``history["loss"]`` holds the host-synced loss samples;
    ``history["loss_step"]`` the global step each sample was taken at
    (sampling is sparse — see ``log_every``)."""

    def __init__(self):
        self.history: Dict[str, list] = {"loss": [], "loss_step": [],
                                         "epoch_loss": []}
        self.epochs_run = 0
        self.steps_run = 0
        #: True when ``fit`` stopped early on a preemption signal (the
        #: partial epoch is NOT counted in ``epochs_run``).
        self.preempted = False
        #: which checkpoint tier took the emergency preemption state
        #: ("persistent" | "peer" | None) — the deadline decision's
        #: outcome (docs/resilience.md); callers exiting on preemption
        #: should use resilience.PREEMPTED_EXIT_CODE so the supervisor
        #: relaunches without consuming the restart budget.
        self.preempt_tier: Optional[str] = None
        #: which tier the resume came from ("ram" | "peer" |
        #: "persistent" | None when fit started fresh).
        self.resume_tier: Optional[str] = None
        #: this attempt's goodput summary (telemetry.goodput
        #: .attempt_goodput output), or None when fit ran no steps.
        self.goodput: Optional[dict] = None

    def _sample(self, step: int, loss: float) -> None:
        self.history["loss"].append(loss)
        self.history["loss_step"].append(step)


DataArg = Union[Iterable, Callable[[], Iterable], Dict[str, Any]]


def _validate_signals(specs: Sequence) -> list:
    """Signal names/numbers → deduped ``signal.Signals`` list (dupes
    would corrupt the previous-handler restore: the second install
    records OUR handler as 'previous')."""
    nums: list = []
    for s in specs:
        if isinstance(s, str):
            num = getattr(_signal, s, None)
            if not isinstance(num, _signal.Signals):
                raise ValueError(f"unknown signal name {s!r}")
        else:
            num = _signal.Signals(s)
        if num not in nums:
            nums.append(num)
    return nums


@contextlib.contextmanager
def _preemption_handlers(nums, preempt):
    """Install flag-setting handlers for ``nums``; ALWAYS restore the
    previous handlers on exit (reverse order), even on mid-install
    failure."""
    def _on_preempt(signum, frame):
        # Runs in the main thread between bytecodes: ONLY set the flag —
        # stream I/O (logging) from a handler can re-enter a buffered
        # writer mid-write and raise, aborting fit before the
        # checkpoint; the step boundary logs and checkpoints.
        preempt["signum"] = signum

    installed = []
    try:
        for num in nums:
            installed.append((num, _signal.signal(num, _on_preempt)))
        yield
    finally:
        for num, prev in reversed(installed):
            _signal.signal(num, prev)


def _epoch_iter(data: DataArg, steps_per_epoch: Optional[int]):
    """Normalize the data argument into a fresh per-epoch batch iterator.

    Accepted forms (reference ``Model.fit`` took arrays/datasets; here a
    functional menu):
      * callable ``() -> iterable``  — invoked per epoch (generator factory)
      * a dict (single batch pytree) — repeated ``steps_per_epoch`` times
      * any re-iterable (list/tuple) — iterated per epoch
    """
    if callable(data):
        return iter(data())
    if isinstance(data, dict):
        if not steps_per_epoch:
            raise ValueError(
                "a single-batch `data` dict requires steps_per_epoch")
        return iter(data for _ in range(steps_per_epoch))
    return iter(data)


def fit(session, data: DataArg, epochs: int = 1,
        steps_per_epoch: Optional[int] = None,
        validation_data: Optional[DataArg] = None,
        validation_steps: Optional[int] = None,
        callbacks: Sequence[Callback] = (), log_every: int = 0,
        checkpoint_dir: Optional[str] = None, checkpoint_every: int = 1,
        resume: bool = True, async_checkpoints: bool = False,
        checkpoint_keep: Optional[int] = None,
        initial_epoch: Optional[int] = None,
        prefetch_depth: int = 2,
        preemption_signals: Sequence = (),
        on_nonfinite: Optional[str] = None,
        validate: bool = False,
        snapshot_every: int = 0,
        snapshot_keep: Optional[int] = None,
        snapshot_dir: Optional[str] = None,
        tiers=None,
        tuner=None) -> History:
    """Train ``epochs`` × (``steps_per_epoch`` or len(data)) steps.

    ``epochs`` is the TOTAL target, Keras-style: resuming an interrupted
    ``fit(epochs=N)`` completes to N total epochs, not N more.  The
    starting epoch comes from ``initial_epoch`` when given; otherwise,
    after a checkpoint restore with ``steps_per_epoch`` set, it is
    derived as ``restored_step // steps_per_epoch``.  When neither is
    derivable (resumed, no ``steps_per_epoch``), the loop falls back to
    running ``epochs`` more epochs and says so in the log.

    Args:
      session: a :class:`~autodist_tpu.runner.DistributedSession`.
      data: per-epoch batches — iterable, generator factory, or one batch
        dict (see :func:`_epoch_iter`).
      validation_data: same forms; when set, ``session.evaluate`` runs at
        each epoch end (no parameter update), its mean loss lands in
        ``history["val_loss"]`` and in the ``on_epoch_end`` logs as
        ``val_loss`` (the Keras ``fit(validation_data=...)`` shape).
      validation_steps: cap on validation batches per epoch (required for
        a single-dict ``validation_data``).
      callbacks: :class:`Callback` objects.
      log_every: sync the loss to host (and log it) every N steps; 0 =
        only at epoch ends.  Small N serializes dispatch — keep ≥10 for
        benchmarking.
      checkpoint_dir: when set, save via
        :class:`~autodist_tpu.checkpoint.saver.Saver` every
        ``checkpoint_every`` epochs, and — with ``resume`` — restore the
        latest checkpoint before training (exact resume: optimizer slots
        and sync state included, step counter advanced).  When ``data``
        is a :class:`~autodist_tpu.runtime.data_loader.DataLoader` (or
        anything with ``state()``/``load_state()``), the loader position
        (epoch + within-epoch batch offset) is persisted in the
        checkpoint metadata and restored on resume, so a mid-epoch
        checkpoint continues from the EXACT next batch instead of
        re-running the partial epoch (docs/resilience.md).
      checkpoint_keep: retain only the N newest checkpoint steps —
        older ``step_M`` dirs are garbage-collected after each durable
        save (``Saver(keep=)``).
      initial_epoch: epoch to start from (epochs below it are skipped);
        overrides the step-derived default after a resume.
      async_checkpoints: persist checkpoint files in the background of
        training (the device→host snapshot stays synchronous, so saved
        values are consistent); ``fit`` waits for the last save to be
        durable before returning.
      prefetch_depth: host→device transfers kept in flight ahead of
        compute (see ``DistributedSession.prefetch``).
      preemption_signals: signal names (``"SIGTERM"``) or numbers to
        treat as preemption notices — cloud TPU VMs deliver SIGTERM
        shortly before eviction.  On receipt, ``fit`` finishes the
        in-flight step, saves a checkpoint (when ``checkpoint_dir`` is
        set — mid-epoch, so a later ``fit(..., resume=True)`` continues
        from the preempted step), sets ``history.preempted``, and
        returns.  Handlers are installed only for the duration of
        ``fit`` and the previous handlers are restored on exit.  The
        reference's closest facility is fail-fast process reaping
        (coordinator.py:98-110) — graceful preemption is beyond-parity.

      on_nonfinite: override the captured numerics policy
        (``capture(numerics=...)``, docs/numerics.md) for this fit:
        ``"skip"`` (device-side zero-update, counted in
        ``history["skipped_steps"]``), ``"raise"`` (fetch health every
        step; :class:`~autodist_tpu.numerics.NonFiniteError` on the
        first bad one), or ``"rollback"`` (after K consecutive bad steps
        or a loss-spike z-score, restore the last VERIFIED-GOOD
        checkpoint — saves taken under a clean guard are deep-verified
        and marked — re-seed the data order when the loader supports it,
        emit a supervisor failure marker, and resume; bounded by
        ``NumericsConfig.max_rollbacks``).  Requires the numerics guard;
        ``raise``/``rollback`` cost one host sync per step.

      snapshot_every: enable the RAM checkpoint tier
        (``checkpoint/tiers.py``, docs/resilience.md): every N steps a
        device→host snapshot of the training state lands in an
        in-process ring and mirrors to the peer directory — recovery in
        seconds with at most N steps lost, independent of the
        persistent ``checkpoint_every`` cadence.  0 (default) defers to
        ``AUTODIST_SNAPSHOT_EVERY``.
      snapshot_keep: RAM/peer ring depth (default
        ``AUTODIST_SNAPSHOT_KEEP``, 2).
      snapshot_dir: the peer-mirror directory (RAM-backed in
        production, e.g. under /dev/shm); defaults to
        ``AUTODIST_SNAPSHOT_DIR`` or ``<checkpoint_dir>/peer_tier``.
      tiers: a pre-built :class:`~autodist_tpu.checkpoint.tiers
        .CheckpointTiers` (e.g. with a Cluster-backed buddy transport);
        overrides the three knobs above.  With any tier configured,
        ``resume`` routes RAM-local → peer-fetch → persistent (newest
        usable step wins), so a replaced host rejoins from a
        survivor's mirror without touching persistent storage.  At a
        preemption notice, ``AUTODIST_PREEMPT_GRACE_S`` decides whether
        the persistent save can finish inside the grace window or the
        emergency snapshot goes to the peer tier instead
        (``history.preempt_tier`` records the outcome).

      tuner: a :class:`~autodist_tpu.strategy.tuner.ScheduleTuner` —
        the drift-triggered schedule hot-swap loop (docs/strategies.md
        "Search").  At the tuner's own ``interval`` cadence the step
        loop hands it the session: it profiles the running schedule's
        legs, checks the ``telemetry/leg-drift`` rule against the
        active calibration, and on drift refits the constants,
        re-searches, and hot-swaps the schedule in place through the
        RAM snapshot tier — the loop, callbacks, and checkpointing
        never notice.  No-op when None.

      validate: run the static pre-flight analyzer
        (:mod:`autodist_tpu.analysis`) on the session's compiled
        strategy before anything else — before the checkpoint restore,
        callbacks, and the first (trace-triggering) step.  ERROR
        diagnostics raise
        :class:`~autodist_tpu.analysis.StrategyValidationError`; WARNs
        log once.

    Returns a :class:`History`.
    """
    # Pre-flight FIRST: an illegal plan must fail before any restore or
    # user callback runs (and before the first step traces/compiles).
    if validate:
        from autodist_tpu.analysis import preflight_session

        preflight_session(session)
    # A bad signal name must likewise fail before any restore runs.
    handler_nums = _validate_signals(preemption_signals)

    # Numerics host policy (docs/numerics.md): the captured config wins
    # unless this fit overrides it; raise/rollback (and the loss-spike
    # detector) need a per-step host health fetch — a StepHealthMonitor.
    num_cfg = getattr(getattr(session, "_gi", None), "numerics", None)
    if on_nonfinite is not None:
        from autodist_tpu.numerics.policy import ON_NONFINITE
        if on_nonfinite not in ON_NONFINITE:
            raise ValueError(
                f"on_nonfinite must be one of {ON_NONFINITE}, "
                f"got {on_nonfinite!r}")
        if num_cfg is None or not num_cfg.guard:
            raise ValueError(
                "fit(on_nonfinite=...) needs the numerics guard: pass "
                "numerics=... to AutoDist.capture (docs/numerics.md)")
    policy = on_nonfinite or (num_cfg.on_nonfinite if num_cfg else None)
    monitor = None
    if num_cfg is not None and num_cfg.guard and (
            policy in ("raise", "rollback")
            or num_cfg.spike_zscore is not None):
        from autodist_tpu.numerics.policy import StepHealthMonitor
        monitor = StepHealthMonitor(num_cfg, policy=policy)
        if policy == "rollback" and checkpoint_dir is None:
            raise ValueError(
                "on_nonfinite='rollback' needs checkpoint_dir (the last "
                "verified-good checkpoint is the rollback anchor)")
    saver = None
    resumed_step = None
    data_resume = None
    resume_tier = None
    track_data = hasattr(data, "state") and hasattr(data, "load_state")
    if checkpoint_dir is not None:
        from autodist_tpu.checkpoint import Saver

        saver = Saver(session, async_save=async_checkpoints,
                      keep=checkpoint_keep)
    # RAM/peer checkpoint tiers (docs/resilience.md): explicit object,
    # fit knobs, or the AUTODIST_SNAPSHOT_* env config, in that order.
    if tiers is None:
        from autodist_tpu.checkpoint.tiers import CheckpointTiers
        from autodist_tpu.const import ENV

        every = snapshot_every or ENV.AUTODIST_SNAPSHOT_EVERY.val
        if every:
            peer_dir = snapshot_dir or ENV.AUTODIST_SNAPSHOT_DIR.val or (
                os.path.join(checkpoint_dir, "peer_tier")
                if checkpoint_dir else None)
            keep = snapshot_keep if snapshot_keep is not None \
                else ENV.AUTODIST_SNAPSHOT_KEEP.val
            tiers = CheckpointTiers(session, snapshot_every=every,
                                    keep=keep, peer_dir=peer_dir,
                                    buddy=ENV.AUTODIST_BUDDY.val or None)
    elif tiers._session is None:
        tiers._session = session
    if resume and (checkpoint_dir is not None or tiers is not None):
        from autodist_tpu.checkpoint.tiers import route_restore

        routed = route_restore(session, checkpoint_dir, tiers=tiers)
        if routed is not None:
            resumed_step, resume_tier, resume_meta = routed
            logging.info("fit: resumed at step %d from the %s tier",
                         resumed_step, resume_tier)
            if track_data:
                ds = resume_meta.get("data_state")
                if ds:
                    try:
                        data_resume = data.load_state(ds)
                        logging.info(
                            "fit: exact data resume — continuing at "
                            "epoch %d batch %d", data_resume["epoch"],
                            data_resume["offset"])
                    except (ValueError, KeyError) as e:
                        logging.warning(
                            "fit: checkpoint data state unusable (%s); "
                            "resuming at epoch granularity", e)

    if initial_epoch is None:
        if data_resume is not None:
            # The loader is positioned at the exact next batch; the epoch
            # containing it is where the loop picks up (its already-
            # consumed prefix is skipped by the loader, not re-run).
            initial_epoch = min(data_resume["epoch"], epochs)
        elif resumed_step and steps_per_epoch:
            # Complete to `epochs` TOTAL: skip the epochs the restored
            # step already covers (Keras initial_epoch semantics).
            initial_epoch = min(resumed_step // steps_per_epoch, epochs)
            if resumed_step % steps_per_epoch:
                # Mid-epoch checkpoints (the data-exhaustion tail save)
                # resume at epoch granularity: the partial epoch re-runs.
                logging.warning(
                    "fit: restored step %d is mid-epoch (steps_per_epoch="
                    "%d) — resuming from epoch %d re-runs its partial "
                    "progress; pass initial_epoch to override",
                    resumed_step, steps_per_epoch, initial_epoch)
        else:
            if resumed_step:
                logging.warning(
                    "fit: resumed at step %d without steps_per_epoch — "
                    "cannot derive completed epochs, so running %d MORE "
                    "epochs; pass initial_epoch (or steps_per_epoch) for "
                    "train-to-N-total semantics", resumed_step, epochs)
            initial_epoch = 0
    if initial_epoch >= epochs and resumed_step:
        logging.info("fit: restored step %d already covers %d epochs — "
                     "nothing to train", resumed_step, epochs)

    if isinstance(data, dict):
        # One repeated batch: place it once — re-placing a placed batch is
        # a no-op, so the per-step host→device transfer disappears.
        data = session.place_batch(data)
    if isinstance(validation_data, dict):
        if not validation_steps:
            # Fail BEFORE training an epoch, with the right argument name
            # (the generic _epoch_iter error would only fire at epoch end
            # and talk about steps_per_epoch).
            raise ValueError(
                "a single-batch validation_data dict requires "
                "validation_steps")
        validation_data = session.place_batch(validation_data)

    # Data-position tracking for exact mid-epoch resume: fit counts the
    # CONSUMED batches itself (the prefetcher pulls ahead of the training
    # step, so the loader's own yield count over-reports) and stamps the
    # position into every checkpoint's metadata.
    data_track = {"enabled": bool(track_data), "pos": None, "seed": None,
                  "base": (data_resume or {}).get("offset", 0),
                  "start_epoch": initial_epoch}
    if track_data:
        try:
            data_track["seed"] = data.state().get("seed")
        except Exception:
            data_track["enabled"] = False

    preempt = {"signum": None}
    hist = History()
    hist.resume_tier = resume_tier
    guard_state = {"last_finite": None, "last_skipped": None}
    # Goodput accounting (docs/observability.md): wall clock from here,
    # checkpoint stalls and rollback re-run loss accumulated as they
    # happen, the summary emitted/gauged before fit returns.
    t_fit0 = time.perf_counter()
    goodput = {"ckpt_stall_s": 0.0, "rollback_s": 0.0}
    try:
        with _preemption_handlers(handler_nums, preempt):
            # on_train_begin runs INSIDE the handler scope: a SIGTERM
            # during a slow user callback must still flag (and
            # checkpoint at the first step boundary), not kill the
            # process.
            for cb in callbacks:
                cb.on_train_begin(session)
            rollbacks = 0
            while True:
                try:
                    last_saved_step = _fit_epochs(
                        session=session, data=data, epochs=epochs,
                        steps_per_epoch=steps_per_epoch,
                        validation_data=validation_data,
                        validation_steps=validation_steps,
                        callbacks=callbacks,
                        log_every=log_every, checkpoint_dir=checkpoint_dir,
                        checkpoint_every=checkpoint_every,
                        prefetch_depth=prefetch_depth,
                        initial_epoch=initial_epoch,
                        saver=saver, hist=hist, preempt=preempt,
                        data_track=data_track, monitor=monitor,
                        guard_state=guard_state, tiers=tiers,
                        goodput=goodput, tuner=tuner)
                    break
                except _RollbackRequest as rb:
                    rollbacks += 1
                    initial_epoch = _handle_rollback(
                        session=session, saver=saver,
                        checkpoint_dir=checkpoint_dir, data=data, rb=rb,
                        rollbacks=rollbacks, num_cfg=num_cfg, epochs=epochs,
                        steps_per_epoch=steps_per_epoch,
                        data_track=data_track, hist=hist, monitor=monitor,
                        goodput=goodput)
                    guard_state["last_finite"] = None
                    guard_state["last_skipped"] = None

        if (saver is not None and hist.steps_run and not hist.preempted
                and last_saved_step != session.step_count):
            # Never lose the tail epochs to the checkpoint_every stride.
            # (A preempted fit already routed its emergency state.)
            t0 = time.perf_counter()
            saver.save(checkpoint_dir, step=session.step_count,
                       extra_meta=_data_state_meta(data_track),
                       mark_good=_guard_clean(guard_state, monitor))
            goodput["ckpt_stall_s"] += time.perf_counter() - t0
    finally:
        # ALWAYS in a finally: a SIGTERM-raised exception (or any crash)
        # racing an async save must not strand a partial step dir — the
        # in-flight save becomes durable before the process exits.
        if saver is not None:
            t0 = time.perf_counter()
            saver.wait()
            goodput["ckpt_stall_s"] += time.perf_counter() - t0

    hist.goodput = _finish_goodput(session, hist, goodput,
                                   time.perf_counter() - t_fit0)
    for cb in callbacks:
        cb.on_train_end(hist)
    return hist


def _attempt_useful_s(session, steps_run: int) -> Optional[float]:
    """Useful (forward-progress) seconds this attempt: mean measured
    step time × steps run.  None when telemetry recorded nothing — the
    goodput ratio is then reported unknown instead of flattered."""
    rec = getattr(session, "telemetry", None)
    if rec is None or not steps_run:
        return None
    times = [r.step_time_s for r in rec.records if r.step_time_s]
    if not times:
        return None
    return float(np.mean(times)) * steps_run


def _finish_goodput(session, hist, goodput: dict,
                    wall_s: float) -> Optional[dict]:
    """Per-attempt goodput summary: gauge + journal + History field."""
    if not hist.steps_run:
        return None
    from autodist_tpu.const import ENV
    from autodist_tpu.telemetry import attempt_goodput, emit_event, gauge

    gp = attempt_goodput(wall_s, _attempt_useful_s(session, hist.steps_run),
                         ckpt_stall_s=goodput["ckpt_stall_s"],
                         rollback_s=goodput["rollback_s"],
                         steps=hist.steps_run)
    if gp.get("goodput_ratio") is not None:
        gauge("autodist_goodput_ratio",
              "useful step time / wall time of the last fit attempt"
              ).set(gp["goodput_ratio"])
    emit_event("goodput/attempt", attempt=ENV.AUTODIST_ATTEMPT.val,
               preempted=hist.preempted, resume_tier=hist.resume_tier,
               **gp)
    return gp


def _preempt_save(*, session, saver, tiers, checkpoint_dir, data_track,
                  guard_state, monitor, goodput) -> Optional[str]:
    """The deadline-aware preemption decision (docs/resilience.md): can
    the persistent save finish inside ``AUTODIST_PREEMPT_GRACE_S``, or
    does the emergency state go to the peer RAM tier instead?

    The estimate is the last MEASURED persistent-save duration
    (``Saver.last_persist_s``) with a 1.25x safety margin; with a grace
    deadline set and no measurement yet, the peer tier wins (seconds,
    bounded) over gambling the whole grace window on unknown storage.
    No deadline (grace 0/unset) keeps the legacy always-persist path.
    Returns the tier that took the state, None when nothing could."""
    from autodist_tpu.const import ENV
    from autodist_tpu.resilience.heartbeat import heartbeat_phase
    from autodist_tpu.telemetry import emit_event

    grace = ENV.AUTODIST_PREEMPT_GRACE_S.val
    est = saver.last_persist_s if saver is not None else None
    can_peer = tiers is not None and tiers.enabled \
        and tiers.mirror is not None
    if saver is None:
        use_peer = can_peer
    elif grace > 0:
        use_peer = can_peer and (est is None or est * 1.25 >= grace)
    else:
        use_peer = False
    emit_event("checkpoint/preempt_decision", step=session.step_count,
               grace_s=grace or None, est_persist_s=est,
               tier="peer" if use_peer else
               ("persistent" if saver is not None else None))
    t0 = time.perf_counter()
    # The drain is phase-tagged on the heartbeat beacon: the monitor
    # reports DRAINING, not WEDGED, while the grace window runs.
    with heartbeat_phase("draining"):
        if use_peer:
            snap = tiers.snapshot(session.step_count,
                                  extra_meta=_data_state_meta(data_track),
                                  emergency=True)
            goodput["ckpt_stall_s"] += time.perf_counter() - t0
            return "peer" if snap is not None else None
        if saver is not None:
            saver.save(checkpoint_dir, step=session.step_count,
                       extra_meta=_data_state_meta(data_track),
                       mark_good=_guard_clean(guard_state, monitor))
            saver.wait()   # the process exits right after: must be durable
            goodput["ckpt_stall_s"] += time.perf_counter() - t0
            return "persistent"
    return None


def _data_state_meta(data_track) -> Optional[dict]:
    """``extra_meta`` for a checkpoint save: the current data position
    (None when tracking is off or no position is known yet)."""
    if not data_track["enabled"] or data_track["pos"] is None:
        return None
    return {"data_state": dict(data_track["pos"])}


def _guard_clean(guard_state, monitor) -> bool:
    """Is the CURRENT training state attestably healthy — i.e. should a
    checkpoint saved now be marked verified-good?  True only when the
    numerics guard is emitting health, the last observed step was finite,
    and no bad streak / spike is in flight."""
    if guard_state["last_finite"] is not True:
        return False
    return monitor is None or monitor.bad_streak == 0


def _observe_health(out, hist, guard_state, session=None) -> Optional[bool]:
    """Record the step's grad_health into host-side tracking (cheap —
    only called at points that already sync, or under an active
    monitor).  Returns all_finite, or None when the guard is off.
    When the session records telemetry, the GradHealth summary is
    annotated onto the latest StepRecord and skip-count increases are
    journaled (docs/observability.md)."""
    health = out.get("grad_health") if isinstance(out, dict) else None
    if health is None:
        return None
    finite = bool(np.asarray(health.all_finite))
    prev_skipped = guard_state["last_skipped"]
    guard_state["last_finite"] = finite
    guard_state["last_skipped"] = int(np.asarray(health.skipped_steps))
    rec = getattr(session, "telemetry", None) if session is not None \
        else None
    if rec is not None:
        rec.annotate(all_finite=finite,
                     global_norm=float(np.asarray(health.global_norm)),
                     loss_scale=float(np.asarray(health.loss_scale)),
                     skipped_steps=guard_state["last_skipped"])
    if prev_skipped is not None \
            and guard_state["last_skipped"] > prev_skipped:
        from autodist_tpu.telemetry import emit_event
        emit_event("numerics/skip",
                   step=getattr(session, "step_count", None),
                   skipped_total=guard_state["last_skipped"],
                   new_skips=guard_state["last_skipped"] - prev_skipped)
    return finite


def _host_loss(out, session) -> float:
    """Fetch the step loss to host, timing the blocking device→host
    sync as the ``blocking_fetch`` telemetry phase and annotating the
    latest StepRecord with the value."""
    rec = getattr(session, "telemetry", None)
    t0 = time.perf_counter()
    loss = float(np.asarray(out["loss"]))
    if rec is not None:
        rec.add_phase("blocking_fetch", time.perf_counter() - t0)
        rec.annotate(loss=loss)
    return loss


def _timed_batches(it, rec):
    """Wrap the epoch's batch iterator so time spent PULLING batches
    (the input pipeline's host half) lands in the ``data_load`` phase of
    the step timeline.  Identity when telemetry is off."""
    if rec is None:
        return it

    def gen():
        while True:
            t0 = time.perf_counter()
            try:
                b = next(it)
            except StopIteration:
                return
            rec.add_phase("data_load", time.perf_counter() - t0)
            yield b
    return gen()


def _handle_rollback(*, session, saver, checkpoint_dir, data, rb,
                     rollbacks, num_cfg, epochs, steps_per_epoch,
                     data_track, hist, monitor, goodput=None) -> int:
    """Anomaly rollback (docs/numerics.md): restore the last
    verified-good checkpoint, reposition (and optionally re-seed) the
    data, emit a supervisor failure marker, and return the epoch to
    resume from.  Raises :class:`NonFiniteError` when recovery is
    impossible."""
    from autodist_tpu.checkpoint import Saver

    if saver is None:
        raise NonFiniteError(
            f"{rb}; rollback needs checkpoint_dir to restore from")
    if rollbacks > num_cfg.max_rollbacks:
        raise NonFiniteError(
            f"{rb}; rollback budget exhausted "
            f"(max_rollbacks={num_cfg.max_rollbacks})")
    _emit_failure_marker(str(rb))
    saver.wait()   # pending async save must settle before we re-read
    good_path = Saver.last_good_checkpoint(checkpoint_dir)
    if good_path is None:
        raise NonFiniteError(
            f"{rb}; no verified-good checkpoint under {checkpoint_dir}")
    restored = saver.restore(good_path)
    hist.history.setdefault("rollbacks", []).append(
        {"at_step": rb.step, "restored_step": restored,
         "reason": rb.reason})
    if goodput is not None:
        # Rollback loss: the discarded steps between the anchor and the
        # failure, priced at the measured mean step time.
        lost = max(int(rb.step) - int(restored), 0)
        per_step = _attempt_useful_s(session, 1)
        if lost and per_step:
            goodput["rollback_s"] += lost * per_step
    from autodist_tpu.telemetry import emit_event
    emit_event("numerics/rollback", step=rb.step, reason=rb.reason,
               restored_step=restored, rollback_index=rollbacks,
               max_rollbacks=num_cfg.max_rollbacks)
    rec = getattr(session, "telemetry", None)
    if rec is not None:
        rec.annotate(step=rb.step, rolled_back=True)
    logging.warning(
        "numerics rollback %d/%d: %s — restored verified-good step %d "
        "from %s", rollbacks, num_cfg.max_rollbacks, rb.reason, restored,
        good_path)
    if monitor is not None:
        monitor.reset()

    # Reposition the data exactly like a resume: the good checkpoint's
    # recorded loader position when available, else epoch arithmetic.
    next_epoch = None
    if data_track["enabled"]:
        ds = Saver.read_meta(good_path).get("data_state")
        if ds:
            try:
                pos = data.load_state(ds)
                next_epoch = min(pos["epoch"], epochs)
                data_track["base"] = pos["offset"]
                data_track["start_epoch"] = next_epoch
            except (ValueError, KeyError) as e:
                logging.warning(
                    "rollback: checkpoint data state unusable (%s); "
                    "falling back to epoch arithmetic", e)
    if next_epoch is None:
        if steps_per_epoch:
            next_epoch = min(restored // steps_per_epoch, epochs)
            if restored % steps_per_epoch:
                logging.warning(
                    "rollback: restored step %d is mid-epoch — resuming "
                    "from epoch %d re-runs its partial progress",
                    restored, next_epoch)
            data_track["base"] = 0
            data_track["start_epoch"] = next_epoch
        else:
            raise NonFiniteError(
                f"{rb}; cannot derive the resume epoch — pass "
                "steps_per_epoch or use a stateful DataLoader")
    if num_cfg.reseed_on_rollback and hasattr(data, "reseed"):
        # A bad batch ordering is one plausible spike cause: shuffle the
        # replayed epochs differently (deterministically per attempt).
        old_seed = data_track.get("seed") or 0
        new_seed = old_seed + 1000003 * rollbacks
        data.reseed(new_seed)
        data_track["seed"] = new_seed
        logging.warning(
            "rollback: data order re-seeded %s -> %s", old_seed, new_seed)
    return next_epoch


def _fit_epochs(*, session, data, epochs, steps_per_epoch,
                validation_data, validation_steps, callbacks, log_every,
                checkpoint_dir, checkpoint_every, prefetch_depth,
                initial_epoch, saver, hist, preempt, data_track,
                monitor=None, guard_state=None, tiers=None, goodput=None,
                tuner=None):
    """The epoch loop (split out so ``fit`` can wrap it in the
    signal-handler scope; keyword-only — no positional-order hazard).
    Returns ``last_saved_step``."""
    if guard_state is None:
        guard_state = {"last_finite": None, "last_skipped": None}
    if goodput is None:
        goodput = {"ckpt_stall_s": 0.0, "rollback_s": 0.0}
    last_saved_step = None
    for epoch in range(initial_epoch, epochs):
        # The resumed epoch starts at the restored offset; every later
        # epoch starts at batch 0.
        epoch_base = data_track["base"] \
            if epoch == data_track["start_epoch"] else 0
        for cb in callbacks:
            cb.on_epoch_begin(epoch)
        it = _epoch_iter(data, steps_per_epoch)
        if steps_per_epoch:
            # Cap BEFORE prefetch: capping inside the loop would let the
            # prefetcher pull (and drop) batches beyond the cap — silently
            # skipping data when one shared iterator spans epochs.
            it = itertools.islice(it, steps_per_epoch)
        it = _timed_batches(it, getattr(session, "telemetry", None))
        out = None
        epoch_steps = 0
        last_sampled_step = None
        for batch in session.prefetch(it, prefetch_depth):
            out = session.run(batch, sync=False)
            epoch_steps += 1
            hist.steps_run += 1
            for cb in callbacks:
                cb.on_step_end(session.step_count, out)
            if tiers is not None:
                # RAM tier cadence: one modulo check when idle; on a
                # snapshot step the device→host copy is synchronous
                # (counted as checkpoint stall) and carries the exact
                # data position so a tier resume is mid-epoch exact.
                extra = None
                if data_track["enabled"]:
                    extra = {"data_state": {
                        "epoch": epoch,
                        "offset": epoch_base + epoch_steps,
                        "seed": data_track["seed"]}}
                if tiers.on_step(session.step_count,
                                 extra_meta=extra) is not None:
                    goodput["ckpt_stall_s"] += tiers.last_snapshot_s or 0.0
            if tuner is not None:
                # Drift-triggered schedule hot-swap (docs/strategies.md
                # "Search"): the tuner owns its cadence and swaps the
                # session in place, so nothing else in the loop changes.
                tuner.on_step(session, session.step_count)
            if monitor is not None:
                # raise/rollback/spike policies: one host sync per step
                # (documented cost of the active policies).
                finite = _observe_health(out, hist, guard_state, session)
                if finite is None:
                    raise ValueError(
                        "numerics monitoring needs grad_health in the "
                        "step metrics — this session was built without "
                        "the numerics guard (capture(numerics=...))")
                action = monitor.observe(
                    session.step_count, _host_loss(out, session), finite)
                if action == "raise":
                    raise NonFiniteError(
                        f"non-finite gradients at step "
                        f"{session.step_count} (on_nonfinite='raise')")
                if action == "rollback":
                    raise _RollbackRequest(
                        session.step_count,
                        "loss spike" if finite
                        else f"{monitor.bad_streak} consecutive "
                             f"non-finite steps")
            if log_every and hist.steps_run % log_every == 0:
                loss = _host_loss(out, session)
                hist._sample(session.step_count, loss)
                last_sampled_step = session.step_count
                tp = session.throughput()
                logging.info(
                    "fit: epoch %d step %d loss %.5f (%.1f steps/s)",
                    epoch, session.step_count, loss,
                    tp.get("steps_per_sec") or 0.0)
            if preempt["signum"] is not None:
                break
        if preempt["signum"] is not None:
            # Preemption notice (e.g. cloud SIGTERM before eviction):
            # the in-flight step finished; checkpoint NOW — mid-epoch —
            # so resume continues from this step, and stop.  The partial
            # epoch stays out of epochs_run (resume re-derives its place
            # from the step counter).
            hist.preempted = True
            loss = _host_loss(out, session) if out is not None else None
            if loss is not None and last_sampled_step != session.step_count:
                hist._sample(session.step_count, loss)
            if data_track["enabled"]:
                # Mid-epoch position: the NEXT batch is epoch_base +
                # epoch_steps of THIS epoch — resume continues exactly
                # there instead of re-running the partial epoch.
                data_track["pos"] = {"epoch": epoch,
                                     "offset": epoch_base + epoch_steps,
                                     "seed": data_track["seed"]}
            if (saver is not None or tiers is not None) and hist.steps_run:
                if out is not None:
                    _observe_health(out, hist, guard_state, session)
                hist.preempt_tier = _preempt_save(
                    session=session, saver=saver, tiers=tiers,
                    checkpoint_dir=checkpoint_dir, data_track=data_track,
                    guard_state=guard_state, monitor=monitor,
                    goodput=goodput)
                if hist.preempt_tier == "persistent":
                    last_saved_step = session.step_count
            for cb in callbacks:
                cb.on_epoch_end(epoch, {
                    "loss": loss, "epoch_steps": epoch_steps,
                    "step": session.step_count, "preempted": True})
            logging.warning(
                "fit: preempted (signal %d) at step %d%s",
                preempt["signum"], session.step_count,
                f" — emergency state took the {hist.preempt_tier} tier"
                if hist.preempt_tier else "")
            break
        if out is None:
            # on_epoch_end still fires so begin/end-paired callbacks stay
            # balanced; an iterator exhausted MID-training ends the run
            # (epochs 2+ of a one-shot generator would otherwise spin
            # through empty epochs and overcount epochs_run).
            logs = {"loss": None, "epoch_steps": 0,
                    "step": session.step_count}
            for cb in callbacks:
                cb.on_epoch_end(epoch, logs)
            if hist.steps_run:
                logging.warning(
                    "fit: data exhausted after %d epochs — pass a "
                    "re-iterable or a generator factory for multi-epoch "
                    "runs", hist.epochs_run)
                break
            logging.warning("fit: epoch %d had no batches", epoch)
            hist.epochs_run += 1
            continue
        # Epoch boundary: one host sync (already paid when the last step
        # landed on a log_every boundary — reuse that sample).
        loss = hist.history["loss"][-1] \
            if last_sampled_step == session.step_count \
            else _host_loss(out, session)
        if last_sampled_step != session.step_count:
            hist._sample(session.step_count, loss)
        hist.history["epoch_loss"].append(loss)
        hist.epochs_run += 1
        if data_track["enabled"]:
            # Epoch boundary: the next batch is the start of epoch+1 (the
            # loader's per-epoch reshuffle keys on the epoch index, so
            # this position is exact even under a steps_per_epoch cap).
            data_track["pos"] = {"epoch": epoch + 1, "offset": 0,
                                 "seed": data_track["seed"]}
        logs = {"loss": loss, "epoch_steps": epoch_steps,
                "step": session.step_count}
        # Guard bookkeeping at the epoch boundary (the host sync is
        # already paid by the loss fetch above): cumulative skipped-step
        # count into the history, health into the mark-good gate.
        _observe_health(out, hist, guard_state, session)
        if guard_state["last_skipped"] is not None:
            hist.history.setdefault("skipped_steps", []).append(
                guard_state["last_skipped"])
            logs["skipped_steps"] = guard_state["last_skipped"]
        if validation_data is not None:
            val_it = _epoch_iter(validation_data, validation_steps)
            if validation_steps:
                val_it = itertools.islice(val_it, validation_steps)
            val = session.evaluate(val_it)
            if val is None:
                logging.warning(
                    "fit: validation_data yielded no batches at epoch %d "
                    "— a one-shot generator is exhausted after the first "
                    "epoch; pass a re-iterable or a generator factory",
                    epoch)
            else:
                logs["val_loss"] = float(np.asarray(val["loss"]))
                hist.history.setdefault("val_loss", []).append(
                    logs["val_loss"])
        for cb in callbacks:
            cb.on_epoch_end(epoch, logs)
        if saver is not None and (epoch + 1) % checkpoint_every == 0:
            t0 = time.perf_counter()
            saver.save(checkpoint_dir, step=session.step_count,
                       extra_meta=_data_state_meta(data_track),
                       mark_good=_guard_clean(guard_state, monitor))
            goodput["ckpt_stall_s"] += time.perf_counter() - t0
            last_saved_step = session.step_count

    return last_saved_step
