"""Program representation: the TPU-native GraphItem.

The reference's ``GraphItem`` wraps a ``tf.Graph`` plus a gradient→variable
map and an ``Info`` collection registry (``autodist/graph_item.py:217-296``,
``111-214``).  In a functional JAX world there is no mutable graph to wrap:
the "program" is a pure train-step function over a parameter pytree.  The
TPU-native GraphItem therefore holds:

* ``params`` — the parameter pytree (the "variables"),
* ``optimizer`` — an ``optax.GradientTransformation`` (captured explicitly
  rather than via the reference's optimizer monkeypatching,
  ``autodist/graph_item.py:72-108``; see ``autodist_tpu/patch.py`` for the
  implicit-capture path),
* ``loss_fn`` — ``loss_fn(params, batch) -> scalar`` (or ``(loss, aux)``),
* an :class:`Info` catalog of variables with trainable/untrainable and
  sparse-gradient annotations (the analog of
  ``autodist/graph_item.py:111-214``'s collections replacement).

The gradient→target map of the reference is implicit here: JAX gradients are
pytrees isomorphic to ``params``, so grad↔var pairing is structural.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


_REMAT_POLICIES = {
    "full": lambda: None,  # no saveable policy: recompute everything
    "dots": lambda: jax.checkpoint_policies.checkpoint_dots,
    "dots_no_batch":
        lambda: jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
}


def _apply_remat(loss_fn: Optional[Callable], remat: Optional[str]
                 ) -> Optional[Callable]:
    """Wrap loss_fn in ``jax.checkpoint`` per the named policy (see
    GraphItem docstring); identity when off."""
    if loss_fn is None or remat in (None, "", "none"):
        return loss_fn
    if remat not in _REMAT_POLICIES:
        raise ValueError(
            f"unknown remat policy {remat!r}; choose from "
            f"{sorted(_REMAT_POLICIES)} or None")
    policy = _REMAT_POLICIES[remat]()
    if policy is None:
        return jax.checkpoint(loss_fn)
    return jax.checkpoint(loss_fn, policy=policy)


def match_var_name(name: str, patterns: Tuple[str, ...]) -> bool:
    """Public alias of the variable-pattern rule used by ``capture()``'s
    sparse/untrainable/pipeline/expert arguments (exact, path-prefix, or
    glob) — for callers building their own selections (e.g. LoRA
    targets) that must read identically."""
    return GraphItem._matches(name, patterns)


def path_name(path: Tuple) -> str:
    """Human-readable, stable name for a pytree key path: parts joined by '/'.

    Gives flax-style names like ``Dense_0/kernel`` — the analog of the
    reference's TF variable names used as strategy node keys
    (``autodist/proto/strategy.proto:44``)."""
    parts = []
    for k in path:
        if isinstance(k, jax.tree_util.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, jax.tree_util.GetAttrKey):
            parts.append(k.name)
        elif isinstance(k, jax.tree_util.SequenceKey):
            parts.append(str(k.idx))
        elif isinstance(k, jax.tree_util.FlattenedIndexKey):
            parts.append(str(k.key))
        else:  # pragma: no cover - future key types
            parts.append(str(k))
    return "/".join(parts)


@dataclass
class VarInfo:
    """Catalog entry for one variable (parity: the per-variable metadata the
    reference keeps in ``Info.variables`` protos, graph_item.py:111-160)."""

    name: str
    shape: Tuple[int, ...]
    dtype: str
    trainable: bool = True
    sparse: bool = False    # gradient has embedding/scatter structure
    pipeline: bool = False  # leading dim is a pipeline-stage axis
    expert: bool = False    # leading dim (after any stage axis) is experts

    @property
    def byte_size(self) -> int:
        return int(np.prod(self.shape or (1,))) * np.dtype(self.dtype).itemsize

    def to_dict(self) -> dict:
        return {"name": self.name, "shape": list(self.shape), "dtype": self.dtype,
                "trainable": self.trainable, "sparse": self.sparse,
                "pipeline": self.pipeline, "expert": self.expert}

    @classmethod
    def from_dict(cls, d: dict) -> "VarInfo":
        return cls(name=d["name"], shape=tuple(d["shape"]), dtype=d["dtype"],
                   trainable=d.get("trainable", True),
                   sparse=d.get("sparse", False),
                   pipeline=d.get("pipeline", False),
                   expert=d.get("expert", False))


@dataclass
class Info:
    """Variable catalog: trainable/untrainable split plus sparse annotations.

    Parity: reference ``Info`` (graph_item.py:111-214) which replaced TF
    collections with explicit variable/saver/table-initializer lists."""

    variables: List[VarInfo] = field(default_factory=list)

    @property
    def trainable_variables(self) -> List[VarInfo]:
        return [v for v in self.variables if v.trainable]

    @property
    def untrainable_variables(self) -> List[VarInfo]:
        return [v for v in self.variables if not v.trainable]

    def by_name(self, name: str) -> VarInfo:
        for v in self.variables:
            if v.name == name:
                return v
        raise KeyError(name)


class GraphItem:
    """The captured training program.

    Args:
      params: parameter pytree.
      optimizer: optax ``GradientTransformation`` (may be None for
        inspection-only GraphItems, e.g. during strategy building on a
        worker before optimizer construction).
      loss_fn: ``loss_fn(params, batch) -> loss`` or ``-> (loss, aux)``.
      sparse_vars: names (or name-prefixes) of variables whose gradients have
        embedding structure — the analog of the reference detecting
        ``IndexedSlices`` gradients (graph_item.py:275-296).  Strategy
        builders treat these differently (e.g. Parallax, parallax_strategy.py:24-71).
      untrainable_vars: names (or prefixes) FROZEN for the whole run:
        excluded from synchronization, zero updates, and no optimizer
        state (``frozen_aware_optimizer``) — batch-norm statistics, or
        the base model under parameter-efficient finetuning
        (``models/lora.py``).
      pipeline_vars: names (or prefixes) of variables whose LEADING axis is a
        pipeline-stage axis (stage-stacked parameters,
        ``autodist_tpu/parallel/pipeline.py``); the compiler shards it over
        the ``pipe`` mesh axis.  No reference analog (SURVEY §2.8: PP absent).
      expert_vars: names (or prefixes) of variables whose leading axis (or
        the axis after the stage axis, if also in pipeline_vars) enumerates
        MoE experts (``autodist_tpu/parallel/moe.py``); sharded over the
        ``expert`` mesh axis.  No reference analog (SURVEY §2.8: EP absent).
      remat: gradient rematerialization policy — trades FLOPs for HBM by
        recomputing activations in the backward pass (``jax.checkpoint``).
        One of ``None``/``"none"`` (off), ``"full"`` (recompute everything),
        ``"dots"`` (save matmul outputs only,
        ``checkpoint_dots``), ``"dots_no_batch"``
        (``checkpoint_dots_with_no_batch_dims`` — the usual transformer
        policy).  No reference analog (TF handled memory in its runtime);
        on TPU this is the standard lever when activations exceed HBM.
      has_aux: whether loss_fn returns ``(loss, aux)``.
    """

    def __init__(self,
                 params: Any,
                 optimizer: Any = None,
                 loss_fn: Optional[Callable] = None,
                 sparse_vars: Sequence[str] = (),
                 untrainable_vars: Sequence[str] = (),
                 pipeline_vars: Sequence[str] = (),
                 expert_vars: Sequence[str] = (),
                 remat: Optional[str] = None,
                 has_aux: bool = False,
                 metrics_fn: Optional[Callable] = None,
                 grad_fn: Optional[Callable] = None,
                 accum_steps: int = 1,
                 numerics=None):
        self.params = params
        self.optimizer = optimizer
        self.loss_fn = _apply_remat(loss_fn, remat)
        self.remat = remat
        self.has_aux = has_aux
        # Gradient accumulation: the step splits each batch into this many
        # microbatches (leading dim) and averages their gradients before
        # the single optimizer update — effective batch B at the live
        # memory of B/accum_steps (assumes a row-mean loss, the standard
        # contract; see GraphTransformer).
        if accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        self.accum_steps = accum_steps
        # (params, batch) -> dict of extra metrics, merged into every
        # step's / evaluate's outputs (the Keras compile(metrics=...)
        # analog; the reference fetched extra tensors via sess.run).
        self.metrics_fn = metrics_fn
        # optional manual value-and-grad replacing jax.value_and_grad in
        # the compiled step — (params, batch) -> (loss, grads).  The
        # hand-scheduled 1F1B pipeline backward plugs in here.
        if grad_fn is not None and has_aux:
            raise ValueError("grad_fn does not support has_aux")
        self.grad_fn = grad_fn
        # Numerics guard config (docs/numerics.md): fused non-finite
        # detection, loss scaling, global-norm clipping, step policy.
        # None (the default) keeps every compiled step byte-identical to
        # a guard-less build.  Coerced eagerly so a bad spec fails at
        # capture, not at transform.
        from autodist_tpu.numerics.policy import NumericsConfig
        self.numerics = NumericsConfig.coerce(numerics)
        self._sparse_patterns = tuple(sparse_vars)
        self._untrainable_patterns = tuple(untrainable_vars)
        self._pipeline_patterns = tuple(pipeline_vars)
        self._expert_patterns = tuple(expert_vars)
        self.info = self._build_info()

    # -- catalog -----------------------------------------------------------
    @staticmethod
    def _matches(name: str, patterns: Tuple[str, ...]) -> bool:
        """Exact name, path-prefix, or fnmatch glob (e.g. ``*/embedding/*``).
        Deliberately NOT substring matching — a pattern like ``emb`` must not
        capture ``embeddings_norm/scale``."""
        import fnmatch
        for p in patterns:
            if name == p or name.startswith(p.rstrip("/") + "/"):
                return True
            if any(ch in p for ch in "*?[") and fnmatch.fnmatch(name, p):
                return True
        return False

    def _build_info(self) -> Info:
        leaves = jax.tree_util.tree_flatten_with_path(self.params)[0]
        infos = []
        for path, leaf in leaves:
            name = path_name(path)
            shape = tuple(getattr(leaf, "shape", ()) or ())
            dtype = str(jnp.asarray(leaf).dtype) if not hasattr(leaf, "dtype") \
                else str(leaf.dtype)
            infos.append(VarInfo(
                name=name,
                shape=shape,
                dtype=dtype,
                trainable=not self._matches(name, self._untrainable_patterns),
                sparse=self._matches(name, self._sparse_patterns),
                pipeline=self._matches(name, self._pipeline_patterns),
                expert=self._matches(name, self._expert_patterns),
            ))
        return Info(variables=infos)

    @property
    def var_names(self) -> List[str]:
        return [v.name for v in self.info.variables]

    @property
    def trainable_var_infos(self) -> List[VarInfo]:
        return self.info.trainable_variables

    def name_to_leaf(self) -> Dict[str, Any]:
        leaves = jax.tree_util.tree_flatten_with_path(self.params)[0]
        return {path_name(p): leaf for p, leaf in leaves}

    def frozen_aware_optimizer(self, params: Any = None):
        """``self.optimizer`` wrapped so untrainable variables get ZERO
        updates and NO optimizer state (``optax.set_to_zero`` carries
        none) — the memory contract parameter-efficient finetuning
        (``models/lora.py``) relies on; XLA dead-code-eliminates the
        frozen update math.  Identity when nothing is frozen.  ``params``
        defaults to the captured tree; pass the PHYSICAL (padded) tree
        when the step state is padded (same structure, so labels match
        either way).  Reference analog: collection membership — variables
        outside TRAINABLE_VARIABLES never reach the optimizer
        (reference graph_item.py:111-214 trainable split)."""
        frozen = {v.name for v in self.info.untrainable_variables}
        if not frozen:
            return self.optimizer
        import optax

        labels = jax.tree_util.tree_map_with_path(
            lambda path, _: "frozen" if path_name(path) in frozen
            else "train", self.params if params is None else params)
        return optax.multi_transform(
            {"train": self.optimizer, "frozen": optax.set_to_zero()},
            labels)

    def prepare(self) -> "GraphItem":
        """Refresh the catalog (parity: graph_item.prepare(),
        graph_item.py:414-417, called at strategy-build time)."""
        self.info = self._build_info()
        return self

    # -- serialization -----------------------------------------------------
    # The reference serializes the full GraphDef (graph_item.py:419-473).
    # Functionally the program lives in user code (re-run identically on every
    # worker — the reference's own execution model, coordinator.py:66-90), so
    # only the abstract catalog needs to round-trip.
    def serialize(self) -> str:
        return json.dumps({
            "variables": [v.to_dict() for v in self.info.variables],
            "has_aux": self.has_aux,
        })

    @classmethod
    def deserialize_catalog(cls, data: str) -> Info:
        d = json.loads(data)
        return Info(variables=[VarInfo.from_dict(v) for v in d["variables"]])
