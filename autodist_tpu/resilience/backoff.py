"""Exponential backoff with deterministic jitter.

The one retry schedule every resilience consumer shares: the job
supervisor's relaunch loop (``resilience/supervisor.py``), the
coordinator's per-worker restart policy, and the cluster's transient
remote_copy/remote_exec retries (``cluster.py``) all delay through this
helper, so "how long until we try again" is one tested rule instead of
three ad-hoc sleeps.

Jitter is the fleet-safety half of the design: a pod-wide preemption
kills every worker at once, and N hosts relaunching on a synchronized
schedule hammer the coordinator (and any shared checkpoint store) in
lockstep.  Each delay is spread over ``±jitter/2`` of its nominal value;
passing ``seed`` makes the spread deterministic — what the chaos tests
use so every recovery timeline is reproducible.
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence, Tuple

from autodist_tpu.utils import logging


@dataclass(frozen=True)
class Backoff:
    """Bounded exponential backoff schedule.

    ``max_tries`` counts ATTEMPTS, not retries: ``max_tries=3`` means one
    initial try plus up to two retries.  ``delay(i)`` is the pause after
    failed attempt ``i`` (1-based): ``base * multiplier**(i-1)`` capped
    at ``cap``, spread over ``±jitter/2`` of itself (mean preserved).
    """

    max_tries: int = 3
    base: float = 0.5
    cap: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.5
    seed: Optional[int] = None

    def __post_init__(self):
        if self.max_tries < 1:
            raise ValueError("max_tries must be >= 1")
        if self.base < 0 or self.cap < 0:
            raise ValueError("base/cap must be >= 0")
        if not 0 <= self.jitter <= 1:
            raise ValueError("jitter must be in [0, 1]")

    def nominal(self, attempt: int) -> float:
        """Un-jittered delay after failed attempt ``attempt`` (1-based)."""
        if attempt < 1:
            raise ValueError("attempt is 1-based")
        return min(self.cap, self.base * self.multiplier ** (attempt - 1))

    def delay(self, attempt: int) -> float:
        d = self.nominal(attempt)
        if self.jitter == 0 or d == 0:
            return d
        # Deterministic per-attempt stream when seeded: delay(i) is a pure
        # function of (schedule, i), so a restarted supervisor replays the
        # same timeline.
        rng = random.Random(self.seed * 1000003 + attempt) \
            if self.seed is not None else random
        return d * (1 - self.jitter / 2 + self.jitter * rng.random())

    def delays(self) -> Sequence[float]:
        """The full retry schedule (``max_tries - 1`` pauses)."""
        return [self.delay(i) for i in range(1, self.max_tries)]

    def retry(self, fn: Callable, *, retryable: Tuple = (Exception,),
              label: str = "", sleep: Callable[[float], None] = time.sleep):
        """Call ``fn`` up to ``max_tries`` times; re-raise the last error.

        Every retry is logged with its attempt count (the transient-SSH
        audit trail the cluster layer wants); non-``retryable`` errors
        propagate immediately.
        """
        for attempt in range(1, self.max_tries + 1):
            try:
                return fn()
            except retryable as e:
                if attempt >= self.max_tries:
                    raise
                pause = self.delay(attempt)
                logging.warning(
                    "%s: attempt %d/%d failed (%s); retrying in %.2fs",
                    label or getattr(fn, "__name__", "call"), attempt,
                    self.max_tries, e, pause)
                sleep(pause)
