"""autodist_tpu.resilience — supervised recovery for multi-host jobs.

Turns worker failure from job death (the reference's ``os._exit(1)``
fail-fast) into a recoverable event:

* :mod:`~autodist_tpu.resilience.supervisor` — failure policies for the
  coordinator's watcher plus the job-level :class:`Supervisor` restart
  loop (backoff + retry budget, elastic host fall-through);
* :mod:`~autodist_tpu.resilience.elastic` — restore a ZeRO-1 checkpoint
  across a data-axis resize, exactly;
* :mod:`~autodist_tpu.resilience.heartbeat` — liveness beacons and the
  watchdog that tells "process exited" from "wedged in a collective";
* :mod:`~autodist_tpu.resilience.chaos` — deterministic fault injection
  driving the recovery tests;
* :mod:`~autodist_tpu.resilience.backoff` — the shared retry schedule.

Imports are lazy (PEP 562): ``cluster.py``/``coordinator.py`` consult
this package on the worker bootstrap path, which must not drag jax or
orbax into the process before ``jax.distributed.initialize``.
"""
from __future__ import annotations

_EXPORTS = {
    "Backoff": "autodist_tpu.resilience.backoff",
    "HeartbeatCallback": "autodist_tpu.resilience.heartbeat",
    "HeartbeatMonitor": "autodist_tpu.resilience.heartbeat",
    "HeartbeatWriter": "autodist_tpu.resilience.heartbeat",
    "heartbeat_phase": "autodist_tpu.resilience.heartbeat",
    "set_active_writer": "autodist_tpu.resilience.heartbeat",
    "PREEMPTED_EXIT_CODE": "autodist_tpu.resilience.supervisor",
    "SUPERVISED_ABORT_CODE": "autodist_tpu.resilience.supervisor",
    "ChaosCallback": "autodist_tpu.resilience.chaos",
    "ChaosMonkey": "autodist_tpu.resilience.chaos",
    "corrupt_checkpoint": "autodist_tpu.resilience.chaos",
    "grad_injections": "autodist_tpu.resilience.chaos",
    "loss_spike_events": "autodist_tpu.resilience.chaos",
    "parse_chaos": "autodist_tpu.resilience.chaos",
    "ServingChaos": "autodist_tpu.resilience.chaos",
    "Attempt": "autodist_tpu.resilience.supervisor",
    "FailFast": "autodist_tpu.resilience.supervisor",
    "FailurePolicy": "autodist_tpu.resilience.supervisor",
    "Ignore": "autodist_tpu.resilience.supervisor",
    "NotifySupervisor": "autodist_tpu.resilience.supervisor",
    "RestartWorker": "autodist_tpu.resilience.supervisor",
    "Supervisor": "autodist_tpu.resilience.supervisor",
    "SupervisorPolicy": "autodist_tpu.resilience.supervisor",
    "SupervisorReport": "autodist_tpu.resilience.supervisor",
    "policy_from_env": "autodist_tpu.resilience.supervisor",
    "ElasticResumeError": "autodist_tpu.resilience.elastic",
    "elastic_restore": "autodist_tpu.resilience.elastic",
    "preflight_elastic": "autodist_tpu.resilience.elastic",
    "remap_data_state": "autodist_tpu.resilience.elastic",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(
            f"module 'autodist_tpu.resilience' has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)


def __dir__():
    return __all__
