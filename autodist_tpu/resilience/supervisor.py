"""Chief-side job supervision: worker failure → recoverable event.

The reference's only failure story is fail-fast: a watcher thread calls
``os._exit(1)`` when any worker dies (``coordinator.py``).  That turns a
single preempted host into a dead job whose restart cost is the whole
run.  This module replaces the hard-coded exit with two layers:

1. **Failure policies** — pluggable objects the
   :class:`~autodist_tpu.coordinator.Coordinator` consults when a worker
   exits nonzero.  :class:`FailFast` keeps the reference semantics;
   :class:`RestartWorker` relaunches the dead worker in place (bounded
   retries + backoff — the pre-rendezvous SSH-flake case);
   :class:`NotifySupervisor` records WHICH host failed in a marker file
   and aborts with a distinct exit code so the layer above can act.

2. **The Supervisor** — a job-level restart loop for the post-rendezvous
   world, where a dead worker wedges every peer in a collective and the
   only sound recovery is: terminate the stragglers, re-form the
   rendezvous, and resume from the latest checkpoint.  Each attempt is
   launched through a user callable (typically re-invoking the training
   script via the existing ``Coordinator``/``Cluster`` machinery);
   failures are detected from process exits, per-host failure markers,
   and a :class:`~autodist_tpu.resilience.heartbeat.HeartbeatMonitor`
   (so a WEDGED worker — alive but stalled in a collective — is treated
   exactly like a dead one).  Relaunches back off exponentially with
   jitter under a bounded retry budget; a host that keeps failing is
   declared permanently gone and, under an elastic policy, dropped from
   the host list so the next attempt resumes on the survivors (the
   data-axis shrink is handled by
   :mod:`autodist_tpu.resilience.elastic`).
"""
from __future__ import annotations

import json
import os
import subprocess
import tempfile
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Union

from autodist_tpu.resilience.backoff import Backoff
from autodist_tpu.telemetry import emit_event
from autodist_tpu.utils import logging

#: coordinator watcher actions a failure policy may request.
ABORT = "abort"
IGNORE = "ignore"
RELAUNCH = "relaunch"

#: exit code a supervised chief uses when aborting on a worker failure,
#: distinguishable from ordinary crashes (1) and chaos kills (43).
SUPERVISED_ABORT_CODE = 73

#: exit code a job uses after a GRACEFUL preemption exit (fit saved its
#: emergency state inside the grace window and returned with
#: ``history.preempted``).  The supervisor relaunches it WITHOUT
#: consuming the restart budget: a preempted host is the platform
#: reclaiming capacity, not the job failing — burning retry budget on
#: it would let routine preemptions exhaust the budget real failures
#: need (docs/resilience.md preemption playbook).
PREEMPTED_EXIT_CODE = 75

_MARKER_PREFIX = "failure_"


class FailurePolicy:
    """What the coordinator's watcher does when a worker exits nonzero.

    ``on_worker_exit`` returns one of :data:`ABORT` (terminate the job —
    the coordinator exits with :attr:`exit_code`), :data:`IGNORE` (keep
    running without the worker), or :data:`RELAUNCH` (the coordinator
    re-ships state and re-execs the worker on its host).
    """

    exit_code = 1

    def on_worker_exit(self, address: str, code: int) -> str:
        return ABORT


class FailFast(FailurePolicy):
    """The reference behavior, as an explicit policy object."""


class Ignore(FailurePolicy):
    """Log and carry on — for fire-and-forget side launches only; a
    training job missing a worker deadlocks in its next collective."""

    def on_worker_exit(self, address: str, code: int) -> str:
        return IGNORE


class RestartWorker(FailurePolicy):
    """Relaunch a dead worker in place, with backoff and a per-host
    budget.  Sound only BEFORE the collective rendezvous forms (launch
    flakes); once training runs, use the job-level :class:`Supervisor`.
    """

    def __init__(self, backoff: Optional[Backoff] = None):
        self._backoff = backoff or Backoff(max_tries=3, base=1.0, cap=30.0)
        self._failures: Dict[str, int] = {}

    def on_worker_exit(self, address: str, code: int) -> str:
        n = self._failures.get(address, 0) + 1
        self._failures[address] = n
        if n >= self._backoff.max_tries:
            logging.error(
                "worker %s failed %d times (budget %d) — aborting",
                address, n, self._backoff.max_tries)
            return ABORT
        pause = self._backoff.delay(n)
        logging.warning(
            "worker %s exited with code %s — relaunching in %.2fs "
            "(attempt %d/%d)", address, code, pause, n + 1,
            self._backoff.max_tries)
        time.sleep(pause)   # watcher thread: never blocks training
        return RELAUNCH


class NotifySupervisor(FailurePolicy):
    """Record the failing host in a marker file, then abort with
    :data:`SUPERVISED_ABORT_CODE` — the glue between the in-process
    watcher and the job-level :class:`Supervisor`, which reads the
    marker to attribute the failure to a host."""

    exit_code = SUPERVISED_ABORT_CODE

    def __init__(self, marker_dir: str):
        self._dir = marker_dir

    def on_worker_exit(self, address: str, code: int) -> str:
        write_failure_marker(self._dir, address, code)
        return ABORT


def write_failure_marker(marker_dir: str, address: str, code: int,
                         reason: Optional[str] = None) -> str:
    """``reason`` (optional, e.g. a numerics rollback cause) rides along
    in the marker; readers that predate it ignore the extra key."""
    os.makedirs(marker_dir, exist_ok=True)
    safe = address.replace("/", "_").replace(":", "_")
    path = os.path.join(marker_dir, f"{_MARKER_PREFIX}{safe}.json")
    tmp = path + ".tmp"
    payload = {"address": address, "code": int(code), "time": time.time()}
    if reason:
        payload["reason"] = str(reason)
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def read_failure_markers(marker_dir: str) -> List[dict]:
    out = []
    try:
        names = sorted(os.listdir(marker_dir))
    except OSError:
        return out
    for name in names:
        if not (name.startswith(_MARKER_PREFIX) and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(marker_dir, name), encoding="utf-8") as f:
                out.append(json.load(f))
        except (OSError, ValueError):
            continue
    return out


def policy_from_env() -> Optional[FailurePolicy]:
    """Coordinator default: ``AUTODIST_FAILURE_POLICY`` selects the
    watcher behavior (``fail_fast`` | ``ignore`` | ``restart`` |
    ``supervised``; empty keeps the legacy fail-fast path)."""
    from autodist_tpu.const import ENV

    name = (ENV.AUTODIST_FAILURE_POLICY.val or "").strip().lower()
    if name in ("", "fail_fast", "failfast"):
        return None
    if name == "ignore":
        return Ignore()
    if name == "restart":
        return RestartWorker()
    if name == "supervised":
        marker_dir = ENV.AUTODIST_SUPERVISOR_DIR.val
        if not marker_dir:
            raise ValueError(
                "AUTODIST_FAILURE_POLICY=supervised needs "
                "AUTODIST_SUPERVISOR_DIR (the supervisor sets both)")
        return NotifySupervisor(marker_dir)
    raise ValueError(f"unknown AUTODIST_FAILURE_POLICY {name!r}")


# ---------------------------------------------------------------------------
# job-level supervision
# ---------------------------------------------------------------------------

@dataclass
class SupervisorPolicy:
    """Knobs of the restart loop (documented in docs/resilience.md)."""

    max_restarts: int = 3               # relaunches after the first attempt
    #: bound on budget-FREE preemption relaunches (exit 75) — a backstop
    #: against a pathological platform preempting every attempt forever,
    #: not a recovery budget.
    max_preemptions: int = 16
    backoff: Backoff = field(
        default_factory=lambda: Backoff(max_tries=8, base=1.0, cap=60.0))
    host_failure_budget: int = 2        # failures before a host is "gone"
    elastic: bool = False               # drop dead hosts, resume on survivors
    min_hosts: int = 1
    heartbeat_timeout: Optional[float] = None     # beacon staleness (s)
    step_timeout: Optional[float] = None          # progress stall (s)
    poll_interval: float = 0.5
    kill_grace: float = 5.0             # SIGTERM → SIGKILL escalation


@dataclass
class Attempt:
    """What a launch callable gets: everything one job attempt needs."""

    index: int
    hosts: List[str]
    marker_dir: str
    heartbeat_dir: str
    resume_step: Optional[int] = None   # latest verified checkpoint step

    def env(self) -> Dict[str, str]:
        """Env additions wiring the attempt's chief into this supervisor:
        the attempt stamp (chaos/test filters key on it) and the
        supervised failure policy (worker death → marker + abort 73)."""
        return {
            "AUTODIST_ATTEMPT": str(self.index),
            "AUTODIST_FAILURE_POLICY": "supervised",
            "AUTODIST_SUPERVISOR_DIR": self.marker_dir,
        }


@dataclass
class AttemptFailure:
    attempt: int
    kind: str                  # "exit" | "heartbeat" | "preempt"
    culprit: Optional[str]     # host/worker the failure is attributed to
    detail: str = ""
    #: crash-bundle directory the flight recorder wrote for this
    #: failure (telemetry/flightrec.py), or None when bundling was off
    #: or failed — rendered by ``--hang-report``.
    bundle: Optional[str] = None


@dataclass
class SupervisorReport:
    ok: bool
    attempts: int
    hosts: List[str]                     # surviving hosts after the run
    failures: List[AttemptFailure] = field(default_factory=list)
    gave_up: str = ""
    #: graceful preemption relaunches (exit 75) — informational; they
    #: did NOT consume the restart budget.
    preemptions: int = 0


LaunchFn = Callable[[Attempt], Union[subprocess.Popen,
                                     Mapping[str, subprocess.Popen]]]


class Supervisor:
    """Run a multi-host training job to completion through failures.

    ``launch(attempt)`` starts one job attempt — typically the chief
    process of the training script (which fans out its own workers via
    the Coordinator) — and returns its process handle(s); launch them
    with ``start_new_session=True`` so the supervisor can terminate the
    whole process group.  The supervisor waits for a clean exit,
    relaunching on failure with backoff under ``policy.max_restarts``;
    resume-from-checkpoint happens inside the job via
    ``fit(resume=True)`` (``attempt.resume_step`` reports what the
    supervisor expects to be resumed).
    """

    def __init__(self, policy: SupervisorPolicy,
                 hosts: Sequence[str] = ("localhost",),
                 checkpoint_dir: Optional[str] = None,
                 workdir: Optional[str] = None):
        self._policy = policy
        self._hosts = list(hosts)
        self._checkpoint_dir = checkpoint_dir
        self._workdir = workdir or tempfile.mkdtemp(prefix="autodist_sup_")
        self._host_failures: Dict[str, int] = {}

    @property
    def workdir(self) -> str:
        return self._workdir

    def _resume_step(self) -> Optional[int]:
        if self._checkpoint_dir is None:
            return None
        try:   # lazy: the supervisor process itself needs no jax/orbax
            from autodist_tpu.checkpoint.saver import Saver

            return Saver.latest_step(self._checkpoint_dir)
        except Exception as e:  # pragma: no cover - defensive
            logging.warning("supervisor: could not probe %s for resume "
                            "step (%s)", self._checkpoint_dir, e)
            return None

    def run(self, launch: LaunchFn) -> SupervisorReport:
        report = SupervisorReport(ok=False, attempts=0,
                                  hosts=list(self._hosts))
        index = 0          # launch counter (== report.attempts - 1)
        restarts = 0       # budget-consuming (non-preemption) relaunches
        while True:
            report.attempts = index + 1
            att = Attempt(
                index=index, hosts=list(self._hosts),
                marker_dir=os.path.join(self._workdir, f"attempt_{index}"),
                heartbeat_dir=os.path.join(self._workdir,
                                           f"attempt_{index}", "hb"),
                resume_step=self._resume_step())
            os.makedirs(att.heartbeat_dir, exist_ok=True)
            logging.info(
                "supervisor: attempt %d/%d on %d host(s)%s", index + 1,
                self._policy.max_restarts + 1, len(att.hosts),
                f", resuming from step {att.resume_step}"
                if att.resume_step is not None else "")
            emit_event("supervisor/attempt_start", attempt=index,
                       hosts=list(att.hosts), resume_step=att.resume_step)
            procs = launch(att)
            if isinstance(procs, subprocess.Popen):
                procs = {"job": procs}
            failure = self._watch(dict(procs), att)
            if failure is None:
                report.ok = True
                report.hosts = list(self._hosts)
                logging.info("supervisor: job completed after %d attempt(s)",
                             index + 1)
                emit_event("supervisor/completed", attempts=index + 1,
                           hosts=list(self._hosts))
                return report
            report.failures.append(failure)
            self._terminate(procs)
            logging.warning("supervisor: attempt %d failed (%s: %s)",
                            index + 1, failure.kind, failure.detail)
            emit_event("supervisor/attempt_failure", attempt=index,
                       failure_kind=failure.kind, culprit=failure.culprit,
                       detail=failure.detail, bundle=failure.bundle)
            if failure.culprit:
                n = self._host_failures.get(failure.culprit, 0) + 1
                self._host_failures[failure.culprit] = n
                if (n >= self._policy.host_failure_budget
                        and failure.culprit in self._hosts):
                    if (self._policy.elastic
                            and len(self._hosts) - 1
                            >= self._policy.min_hosts):
                        self._hosts.remove(failure.culprit)
                        logging.warning(
                            "supervisor: host %s failed %d times — "
                            "declaring it gone; next attempt runs "
                            "elastically on %d surviving host(s)",
                            failure.culprit, n, len(self._hosts))
                        emit_event("supervisor/host_dropped",
                                   host=failure.culprit, failures=n,
                                   surviving_hosts=list(self._hosts))
                    elif not self._policy.elastic:
                        logging.warning(
                            "supervisor: host %s exhausted its failure "
                            "budget (%d); policy is not elastic, so "
                            "relaunch keeps targeting it",
                            failure.culprit, n)
            if failure.kind == "preempt":
                # Graceful preemption (exit 75): the job checkpointed
                # inside its grace window and asked to be relaunched.
                # Relaunch promptly and WITHOUT consuming the restart
                # budget — bounded only by the max_preemptions backstop.
                report.preemptions += 1
                if report.preemptions > self._policy.max_preemptions:
                    report.gave_up = (
                        f"preemption backstop exhausted after "
                        f"{report.preemptions} preemption(s)")
                    break
                logging.info(
                    "supervisor: attempt %d exited on a preemption "
                    "notice — relaunching without consuming the restart "
                    "budget (%d/%d preemptions)", index + 1,
                    report.preemptions, self._policy.max_preemptions)
                emit_event("supervisor/preempt_relaunch", attempt=index,
                           preemptions=report.preemptions)
                index += 1
                continue
            restarts += 1
            if restarts > self._policy.max_restarts:
                report.gave_up = (f"retry budget exhausted after "
                                  f"{report.attempts} attempt(s)")
                break
            pause = self._policy.backoff.delay(restarts)
            logging.info("supervisor: backing off %.2fs before relaunch",
                         pause)
            emit_event("supervisor/backoff", attempt=index,
                       pause_s=round(pause, 3))
            time.sleep(pause)
            index += 1
        report.hosts = list(self._hosts)
        logging.error("supervisor: %s", report.gave_up)
        emit_event("supervisor/gave_up", attempts=report.attempts,
                   reason=report.gave_up)
        return report

    # -- internals ---------------------------------------------------------
    def _watch(self, procs: Dict[str, subprocess.Popen],
               att: Attempt) -> Optional[AttemptFailure]:
        monitor = None
        if self._policy.heartbeat_timeout is not None \
                or self._policy.step_timeout is not None:
            from autodist_tpu.resilience.heartbeat import HeartbeatMonitor

            monitor = HeartbeatMonitor(
                att.heartbeat_dir,
                timeout=self._policy.heartbeat_timeout or 30.0,
                step_timeout=self._policy.step_timeout)
        while True:
            running = False
            for name, proc in procs.items():
                code = proc.poll()
                if code is None:
                    running = True
                elif code == PREEMPTED_EXIT_CODE:
                    return AttemptFailure(
                        att.index, "preempt", None,
                        f"{name} exited with the preemption code "
                        f"{code} (graceful drain)")
                elif code != 0:
                    culprit = self._culprit(att) or name
                    failure = AttemptFailure(
                        att.index, "exit", culprit,
                        f"{name} exited with code {code}")
                    self._attach_bundle(failure, monitor)
                    return failure
            if not running:
                return None   # every process finished cleanly
            if monitor is not None:
                bad = monitor.failures()
                if bad:
                    worker, health = next(iter(bad.items()))
                    doing = health.doing()
                    failure = AttemptFailure(
                        att.index, "heartbeat", worker,
                        f"{worker} is {health.state} ({health.detail})"
                        + (f"; {doing}"
                           if doing and doing not in health.detail
                           else ""))
                    self._attach_bundle(failure, monitor)
                    return failure
            time.sleep(self._policy.poll_interval)

    def _attach_bundle(self, failure: AttemptFailure, monitor) -> None:
        """Flight-recorder crash bundle for a failed attempt
        (telemetry/flightrec.py): snapshot journal/StepRecord tails,
        per-host cursors, the published schedule IR, stacks, and the
        monitor verdicts under the telemetry run dir (the supervisor
        workdir when none is set).  When the beacon-carried cursors
        localize the hang, the diagnosis extends the failure detail and
        a ``flightrec/hang`` event lands in the journal.  Best-effort —
        a bundling failure never masks the attempt failure."""
        try:
            from autodist_tpu.const import ENV
            from autodist_tpu.telemetry import flightrec

            run_dir = ENV.AUTODIST_TELEMETRY_DIR.val or self._workdir
            verdicts = monitor.status() if monitor is not None else None
            bundle = flightrec.dump_bundle(
                run_dir, reason=f"{failure.kind}: {failure.detail}",
                verdicts=verdicts)
            if bundle is None:
                return
            failure.bundle = bundle
            diag = (flightrec.read_bundle(bundle) or {}).get("diagnosis")
            if diag and diag.get("detail"):
                failure.detail += f"; flightrec: {diag['detail']}"
            # A unique localization verdict refines the culprit: the
            # heartbeat path otherwise attributes to the FIRST bad
            # worker, which on a real wedge is whichever victim's stall
            # the monitor noticed first, not the straggler blocking it.
            culprits = (diag or {}).get("culprits") or ()
            if (failure.kind == "heartbeat" and len(culprits) == 1
                    and not (diag or {}).get("tie")):
                failure.culprit = culprits[0]
            logging.warning("supervisor: crash bundle written to %s "
                            "(render with `python -m autodist_tpu"
                            ".telemetry --hang-report %s`)", bundle,
                            bundle)
        except Exception as e:  # pragma: no cover - defensive
            logging.warning("supervisor: crash bundle failed (%s)", e)

    def _culprit(self, att: Attempt) -> Optional[str]:
        markers = read_failure_markers(att.marker_dir)
        return markers[-1]["address"] if markers else None

    def _terminate(self, procs: Mapping[str, subprocess.Popen]) -> None:
        """Terminate every straggler of a failed attempt (whole process
        groups, so worker subprocesses the chief launched die too)."""
        import signal

        for name, proc in procs.items():
            if proc.poll() is not None:
                continue
            logging.warning("supervisor: terminating straggler %s (pid %d)",
                            name, proc.pid)
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError, OSError):
                proc.terminate()
        deadline = time.monotonic() + self._policy.kill_grace
        for name, proc in procs.items():
            remaining = deadline - time.monotonic()
            try:
                proc.wait(timeout=max(remaining, 0.1))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError, OSError):
                    proc.kill()
                proc.wait()
