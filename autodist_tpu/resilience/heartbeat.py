"""Liveness beacons + the chief-side watchdog.

The fail-fast watcher (``coordinator.py``) only ever sees ONE failure
mode: the worker process exits.  The common TPU failure mode is the
other one — the process is alive but wedged in a collective because a
peer died or the fabric hiccuped, and nothing ever exits.  This module
closes that gap with two halves:

* each worker runs a :class:`HeartbeatWriter` — a tiny file beacon
  (atomic JSON: timestamp, pid, last completed step) refreshed by a
  daemon thread and bumped with the step number from a
  :class:`HeartbeatCallback` in the training loop;
* the chief (or the job supervisor) runs a :class:`HeartbeatMonitor`
  that classifies each worker as ALIVE / WEDGED / DEAD / UNKNOWN.

The classification rule distinguishes "process exited" from "process
wedged in a collective": a stale beacon whose pid is gone is DEAD
(relaunch it); a FRESH beacon whose *step* has not advanced within
``step_timeout`` is WEDGED — the beacon thread keeps beating while the
main thread is stuck in a collective, so wall-clock beacon age alone can
never catch a hang; only step progress can.  Beacons ride the
filesystem (worker-local for local processes, a shared/NFS checkpoint
volume for multi-host), so no extra control channel is needed.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

from autodist_tpu.utils import logging

BEAT_SUFFIX = ".hb"

#: worker health states (strings, so reports serialize trivially).
ALIVE = "alive"
WEDGED = "wedged"     # process exists but step progress stalled
DEAD = "dead"         # beacon stale and the pid is gone
UNKNOWN = "unknown"   # no beacon seen yet (within the grace window)
DRAINING = "draining"  # preemption grace window: stall is expected

#: beacon phases whose step-stall is legitimate — a multi-minute
#: Saver.save/restore holds the training loop on purpose, so a fresh
#: phase-tagged beacon must not harden into a WEDGED verdict.
CHECKPOINT_PHASES = ("checkpoint/save", "checkpoint/restore",
                     "checkpoint/wait", "checkpoint/snapshot")

# The process's training-loop beacon, registered by HeartbeatCallback
# (or set_active_writer) so long BLOCKING operations outside the loop —
# Saver.save/restore/wait — can bump it phase-tagged without plumbing a
# writer handle through every call site.
_active_writer: Optional["HeartbeatWriter"] = None
_active_lock = threading.Lock()


def set_active_writer(writer: Optional["HeartbeatWriter"]) -> None:
    """Register (or clear, with None) the process's beacon writer for
    :func:`heartbeat_phase` callers."""
    global _active_writer
    with _active_lock:
        _active_writer = writer


def active_writer() -> Optional["HeartbeatWriter"]:
    with _active_lock:
        return _active_writer


@contextlib.contextmanager
def heartbeat_phase(name: str):
    """Tag the process beacon with ``name`` for the duration of a long
    blocking operation (and beat immediately on entry/exit), so the
    monitor sees *why* step progress stalled instead of verdicting
    WEDGED.  No-op when no writer is registered — callers (the Saver)
    never need to know whether heartbeats are wired.  The phase also
    stamps flight-recorder cursors (telemetry/flightrec.py), so crash
    bundles show checkpoint/drain windows on the cursor timeline."""
    from autodist_tpu.telemetry import flightrec

    flightrec.record_cursor(name, kind="phase", event="enter")
    writer = active_writer()
    if writer is None:
        try:
            yield
        finally:
            flightrec.record_cursor(name, kind="phase", event="exit")
        return
    prev = writer.set_phase(name)
    try:
        yield
    finally:
        flightrec.record_cursor(name, kind="phase", event="exit")
        writer.set_phase(prev)


def beat_path(directory: str, worker: str) -> str:
    safe = worker.replace("/", "_").replace(":", "_")
    return os.path.join(directory, safe + BEAT_SUFFIX)


class HeartbeatWriter:
    """Worker-side beacon: atomic JSON heartbeat file.

    ``beat(step=...)`` writes immediately; ``start()`` spawns a daemon
    thread refreshing the beacon every ``interval`` seconds so liveness
    is reported even between steps (long compiles, eval epochs).  A
    :class:`~autodist_tpu.resilience.chaos.ChaosMonkey` can be attached
    to drop beacons deterministically (``drop_heartbeats`` events).
    """

    def __init__(self, directory: str, worker: str, interval: float = 5.0,
                 chaos=None):
        self._path = beat_path(directory, worker)
        os.makedirs(directory, exist_ok=True)
        self._interval = interval
        self._chaos = chaos
        self._last_step: Optional[int] = None
        self._last_snapshot: Optional[dict] = None
        self._phase: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def path(self) -> str:
        return self._path

    def beat(self, step: Optional[int] = None,
             snapshot: Optional[dict] = None) -> None:
        """``snapshot`` is the latest StepRecord summary (step, loss,
        step_time_ms — ``telemetry.StepRecorder.snapshot()``): it rides
        the beacon so the monitor can report what this worker was DOING
        when it went DEAD/WEDGED, not just how old its beacon is.  The
        daemon-thread refresh re-sends the last snapshot."""
        if self._chaos is not None and not self._chaos.heartbeats_enabled:
            return
        if step is not None:
            self._last_step = int(step)
        if snapshot is not None:
            self._last_snapshot = dict(snapshot)
        payload = {"time": time.time(), "pid": os.getpid(),
                   "step": self._last_step}
        if self._phase is not None:
            payload["phase"] = self._phase
        if self._last_snapshot is not None:
            payload["snapshot"] = self._last_snapshot
        # The latest flight-recorder cursor rides every beacon
        # (telemetry/flightrec.py): the monitor — and a crash bundle —
        # sees WHICH leg/phase each worker was in without any new
        # transport.  The daemon-thread refresh re-reads it, so the
        # cursor stays current even when the step loop is wedged.
        try:
            from autodist_tpu.telemetry import flightrec

            cursor = flightrec.beacon_cursor()
            if cursor is not None:
                payload["cursor"] = cursor
        except Exception:   # cursors are advisory; never kill the beacon
            pass
        tmp = self._path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, self._path)   # atomic: the monitor never sees
            # a half-written beacon
        except OSError as e:  # beacons are best-effort; never kill training
            logging.warning("heartbeat write failed (%s): %s", self._path, e)

    def set_phase(self, name: Optional[str]) -> Optional[str]:
        """Tag subsequent beacons with ``name`` (``None`` clears), beat
        immediately, and return the previous phase (so nested phases
        restore correctly).  The phase rides every beacon — including
        the daemon-thread refreshes — until cleared, which is what lets
        the monitor distinguish a deliberate stall (checkpoint restore,
        preemption drain) from a wedge."""
        prev, self._phase = self._phase, name
        self.beat()
        return prev

    def start(self) -> "HeartbeatWriter":
        if self._thread is None:
            self.beat()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="autodist-heartbeat")
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.beat()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._interval + 1)
            self._thread = None

    def __enter__(self) -> "HeartbeatWriter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class HeartbeatCallback:
    """``fit`` callback bumping the beacon with each completed step —
    the step-progress signal :class:`HeartbeatMonitor` needs to tell a
    wedge from a slow step.  Duck-typed to
    :class:`autodist_tpu.fit.Callback` (all hooks optional there).

    When the session records telemetry, each beat also carries the
    latest StepRecord snapshot (step, loss, step_time) — host-cheap
    (never touches device arrays), and it is what lets the monitor say
    *what* a worker was doing when it died."""

    def __init__(self, writer: HeartbeatWriter):
        self._writer = writer
        self._session = None

    def on_train_begin(self, session) -> None:
        self._session = session
        self._writer.start()
        # Long blocking saves/restores (and the preemption drain) bump
        # this beacon phase-tagged via heartbeat_phase().
        set_active_writer(self._writer)

    def on_epoch_begin(self, epoch: int) -> None: ...

    def on_step_end(self, step: int, metrics) -> None:
        rec = getattr(self._session, "telemetry", None)
        self._writer.beat(step=step,
                          snapshot=rec.snapshot() if rec else None)

    def on_epoch_end(self, epoch: int, logs) -> None: ...

    def on_train_end(self, history) -> None:
        if active_writer() is self._writer:
            set_active_writer(None)
        self._writer.stop()


@dataclass
class WorkerHealth:
    worker: str
    state: str                 # ALIVE | WEDGED | DEAD | UNKNOWN | DRAINING
    age: Optional[float] = None       # seconds since the last beacon
    step: Optional[int] = None        # last completed step, if reported
    pid: Optional[int] = None
    detail: str = ""
    #: beacon phase tag ("checkpoint/save", "draining", ...) — why a
    #: stall is expected, when the worker said so.
    phase: Optional[str] = None
    #: latest StepRecord summary the beacon carried (step, loss,
    #: step_time_ms) — what the worker was DOING at its last beat.
    snapshot: Optional[dict] = None
    #: latest flight-recorder cursor the beacon carried
    #: (telemetry/flightrec.py: leg id, slot, schedule fingerprint,
    #: age) — WHERE in the schedule the worker was at its last beat.
    cursor: Optional[dict] = None

    def doing(self) -> str:
        """Human summary of what the worker was doing: the
        flight-recorder cursor when the beacon carried one ("in
        ring_reduce_scatter leg rs:f32:0 slot 2 for 41 s" — leg cursor
        age plus the beacon's own age), falling back to the StepRecord
        snapshot ('' when neither is present)."""
        if self.cursor:
            from autodist_tpu.telemetry import flightrec

            line = flightrec.cursor_line(self.cursor, self.age or 0.0)
            if line:
                return line
        if not self.snapshot:
            return ""
        parts = [f"step {self.snapshot['step']}"] \
            if "step" in self.snapshot else []
        if "loss" in self.snapshot:
            parts.append(f"loss {self.snapshot['loss']:g}")
        if "step_time_ms" in self.snapshot:
            parts.append(f"{self.snapshot['step_time_ms']:g} ms/step")
        return "last doing: " + ", ".join(parts) if parts else ""


@dataclass
class _Progress:
    step: Optional[int] = None
    since: float = field(default_factory=time.time)


class HeartbeatMonitor:
    """Chief/supervisor-side watchdog over a beacon directory.

    Args:
      directory: where the workers' :class:`HeartbeatWriter` files live.
      timeout: beacon age (seconds) past which a worker is suspect; the
        pid is then probed (same-host) to split DEAD from WEDGED.
      step_timeout: wall-clock budget for ONE step; a worker whose
        beacons stay fresh but whose ``step`` does not advance within it
        is WEDGED — the wedged-in-a-collective case beacon age alone
        cannot see.  None disables progress tracking.
      grace: how long a worker may be beaconless after ``expect`` before
        UNKNOWN hardens into DEAD (defaults to ``timeout``).
    """

    def __init__(self, directory: str, timeout: float = 30.0,
                 step_timeout: Optional[float] = None,
                 grace: Optional[float] = None,
                 expected: Sequence[str] = ()):
        self._dir = directory
        self._timeout = timeout
        self._step_timeout = step_timeout
        self._grace = timeout if grace is None else grace
        self._expected = list(expected)
        self._started = time.time()
        self._progress: Dict[str, _Progress] = {}
        self._reported: Dict[str, str] = {}   # worker -> journaled state

    def expect(self, worker: str) -> None:
        if worker not in self._expected:
            self._expected.append(worker)

    @staticmethod
    def _pid_alive(pid: Optional[int]) -> Optional[bool]:
        """True/False when decidable on this host, None when not."""
        if not pid:
            return None
        try:
            os.kill(pid, 0)
            return True
        except ProcessLookupError:
            return False
        except PermissionError:   # exists, owned by someone else
            return True
        except OSError:
            return None

    def _read(self, worker: str) -> Optional[dict]:
        path = beat_path(self._dir, worker)
        try:
            with open(path, "r", encoding="utf-8") as f:
                payload = json.load(f)
            payload["_mtime"] = os.stat(path).st_mtime
            return payload
        except (OSError, ValueError):
            return None

    def _discovered(self) -> Sequence[str]:
        try:
            names = [n[:-len(BEAT_SUFFIX)] for n in os.listdir(self._dir)
                     if n.endswith(BEAT_SUFFIX)]
        except OSError:
            names = []
        out = list(self._expected)
        for n in names:
            if n not in out:
                out.append(n)
        return out

    def check(self, worker: str, now: Optional[float] = None) -> WorkerHealth:
        now = time.time() if now is None else now
        payload = self._read(worker)
        if payload is None:
            waited = now - self._started
            state = DEAD if waited > self._grace else UNKNOWN
            return WorkerHealth(worker, state,
                                detail=f"no beacon after {waited:.1f}s")
        # mtime is the liveness clock (monotone on one filesystem even
        # when writer/monitor wall clocks disagree); the payload time is
        # advisory.
        age = now - payload["_mtime"]
        pid = payload.get("pid")
        step = payload.get("step")
        snap = payload.get("snapshot")
        phase = payload.get("phase")
        cursor = payload.get("cursor")
        if age > self._timeout:
            # A stale beacon is stale regardless of its phase tag: the
            # beacon THREAD died too, so the drain/save story no longer
            # holds and the normal DEAD/WEDGED split applies.
            alive = self._pid_alive(pid)
            if alive:
                return WorkerHealth(worker, WEDGED, age=age, step=step,
                                    pid=pid, snapshot=snap, phase=phase,
                                    cursor=cursor,
                                    detail="beacon stale but process alive")
            return WorkerHealth(
                worker, DEAD, age=age, step=step, pid=pid, snapshot=snap,
                phase=phase, cursor=cursor,
                detail="beacon stale" + ("" if alive is False
                                         else " (pid unverifiable)"))
        if phase == "draining":
            # Preemption grace window: fit announced it is finishing a
            # durable save before exiting, so the step stall is the
            # PLAN, not a wedge — the supervisor must wait for the exit
            # code instead of terminating the draining worker.
            return WorkerHealth(
                worker, DRAINING, age=age, step=step, pid=pid,
                snapshot=snap, phase=phase, cursor=cursor,
                detail="preemption drain in progress (beacons fresh)")
        if self._step_timeout is not None and step is not None:
            prog = self._progress.get(worker)
            if prog is None or prog.step != step:
                self._progress[worker] = _Progress(step=step, since=now)
            elif now - prog.since > self._step_timeout:
                if phase in CHECKPOINT_PHASES:
                    # Phase-tagged stall: a multi-minute Saver.save/
                    # restore beats through its own phase, so the
                    # step_timeout verdict does not apply.
                    return WorkerHealth(
                        worker, ALIVE, age=age, step=step, pid=pid,
                        snapshot=snap, phase=phase, cursor=cursor,
                        detail=f"step {step} paused in {phase} for "
                               f"{now - prog.since:.1f}s (phase-tagged "
                               "— not a wedge)")
                health = WorkerHealth(
                    worker, WEDGED, age=age, step=step, pid=pid,
                    snapshot=snap, phase=phase, cursor=cursor,
                    detail=f"step {step} stalled for "
                           f"{now - prog.since:.1f}s (beacons fresh — "
                           "likely wedged in a collective)")
                doing = health.doing()
                if doing:   # the flight-recorder cursor names the leg
                    health.detail += f"; {doing}"
                return health
        return WorkerHealth(worker, ALIVE, age=age, step=step, pid=pid,
                            snapshot=snap, phase=phase, cursor=cursor)

    def status(self) -> Dict[str, WorkerHealth]:
        now = time.time()
        return {w: self.check(w, now) for w in self._discovered()}

    def failures(self) -> Dict[str, WorkerHealth]:
        """Workers the supervisor should treat as failed (DEAD or
        WEDGED — a wedged worker blocks every peer's collectives, so it
        is terminated and relaunched exactly like a dead one).  Each
        DEAD/WEDGED verdict is journaled ONCE per state transition
        (``heartbeat/verdict`` events, docs/observability.md), with the
        beacon's carried StepRecord snapshot so the event says what the
        worker was doing."""
        status = self.status()
        bad = {w: h for w, h in status.items()
               if h.state in (DEAD, WEDGED)}
        # DRAINING is journaled (the timeline should show the grace
        # window opening) but is NOT a failure: terminating a draining
        # worker would lose exactly the save the drain exists to finish.
        noted = dict(bad)
        noted.update({w: h for w, h in status.items()
                      if h.state == DRAINING})
        from autodist_tpu.telemetry import emit_event
        for w, h in noted.items():
            if self._reported.get(w) != h.state:
                self._reported[w] = h.state
                emit_event("heartbeat/verdict", worker=w, state=h.state,
                           detail=h.detail, step=h.step,
                           beacon_age_s=h.age, phase=h.phase,
                           snapshot=h.snapshot, cursor=h.cursor)
        for w in list(self._reported):
            if w not in noted:   # recovered: re-arm the transition report
                del self._reported[w]
        return bad
